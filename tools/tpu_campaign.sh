#!/bin/bash
# One-shot TPU measurement campaign for a healthy tunnel window.
#
# The axon tunnel has been wedged for most of rounds 1-4; when a window
# opens, this script banks everything the perf story needs, in priority
# order, so a re-wedge mid-campaign still leaves the most valuable
# artifacts: (1) a B5 bench pass that populates .jax_cache with the
# programs the driver's end-of-round `python bench.py` (default B5 +
# B1 smoke) will need, (2) a warm-cache B5 pass for the official-style
# TPU numbers (T1 is the B5 config), (3) the Pallas MXU aggregates
# kernel A/B + live-hardware validation, (4) the batched-SA moves sweep
# the round-3 verdict asked to re-measure on TPU, (5) B1-B4 on hardware
# for the BASELINE.md table.
#
# Usage: tools/tpu_campaign.sh [logfile]   (appends; default tpu_campaign.log)
set -u
cd "$(dirname "$0")/.."
L="${1:-tpu_campaign.log}"
# Flight recorder + stall watchdog for EVERY rung (ccx.common.tracing):
# each python below auto-arms on these env vars, appending span starts/
# ends, per-chunk heartbeats and watchdog stall dumps (all-thread stacks +
# compile counters) to one crash-safe JSONL. A wedge, driver timeout or
# SIGKILL anywhere in the campaign leaves a recording whose last line
# names the phase, chunk index and compile attribution at death — read it
# with `python -m ccx.common.tracing "$CCX_FLIGHT_RECORDER"`. 300 s
# watchdog: longer than any healthy chunk, far shorter than the >17-min
# compile the round-4 window died in.
export CCX_FLIGHT_RECORDER="${CCX_FLIGHT_RECORDER:-tpu_flight_$(date -u +%Y%m%dT%H%M%SZ).jsonl}"
export CCX_WATCHDOG_SECONDS="${CCX_WATCHDOG_SECONDS:-300}"
# XProf device trace of the bench TARGET rung's warm run (bench.py arms
# jax.profiler on that one rung only — the T1 chase — so the trace stays
# small); the trace path is echoed into the flight-recorder JSONL as
# xprof-start/xprof-stop records, so a recording cross-references the
# device timeline covering the same wall window. Cost capture
# (ccx.common.costmodel) is on by default in bench.py — every prewarmed
# program banks its XLA cost/memory record onto the BENCH line.
export CCX_PROFILE_DIR="${CCX_PROFILE_DIR:-xprof_$(date -u +%Y%m%dT%H%M%SZ)}"
{
  echo "=== TPU campaign start $(date -u +%FT%TZ) ==="
  echo "flight recorder: $CCX_FLIGHT_RECORDER (watchdog ${CCX_WATCHDOG_SECONDS}s)"
  echo "--- probe ---"
  # Require an actual TPU device: a missing/failed axon plugin makes jax
  # fall back to CPU with rc=0, which would bank hours of CPU numbers as
  # "TPU" artifacts. (timeout(1) sends SIGTERM, not SIGKILL — a stuck
  # probe client gets to release its device claim; see perf-notes wedge
  # etiology.)
  # grep STDOUT only: stderr init-failure text can itself mention "tpu"
  # (e.g. "Unable to initialize backend 'tpu'") and must not pass the gate
  probe_err="$(mktemp)"
  probe_out="$(timeout -k 60 90 python -c "import jax; print(jax.devices())" 2>"$probe_err")"
  cat "$probe_err"; rm -f "$probe_err"
  echo "$probe_out"
  if ! grep -qi tpu <<<"$probe_out"; then
    echo "device probe FAILED or non-TPU backend — aborting campaign"
    exit 1
  fi
  echo "--- sharded-anneal probe (virtual CPU mesh; before any timed rung) ---"
  # the mesh-sharded chunk programs (ccx.parallel.sharding) ride the same
  # flight recorder + watchdog as everything else; prove their compile and
  # batched-vs-sequential structure on the virtual mesh FIRST, so a
  # pathological sharded compile surfaces with a [sharded-probe]
  # breadcrumb before any timed rung (and never eats the TPU window —
  # the probe pins itself to the CPU backend)
  timeout -k 60 1800 python tools/probe_sharded.py
  echo "sharded-probe rc=$?"
  echo "--- chunked-polish compile probe at B1+B5 (before any timed rung) ---"
  # the descent-engine chunk programs are what the round-4 window died
  # compiling (>17 min greedy while_loop): prove their compile on
  # hardware FIRST, with a per-program breakdown, and fill the
  # persistent cache the bench prewarm then hits. A pathological compile
  # surfaces here with a [polish-probe] breadcrumb, never inside a rung.
  timeout -k 60 2400 python tools/probe_polish.py
  echo "polish-probe rc=$?"
  echo "--- bench pass 1 (cold compiles -> persistent cache) ---"
  # bench.py now opens with a PREWARM pass (one floored-budget optimize
  # that compiles the ladder's whole shared program set at one-chunk/
  # one-iter execution cost — the compile probe the round-4 window
  # lacked: a pathological compile surfaces in the prewarm phase
  # breadcrumb, before any timed rung is at stake), runs the MXU A/B
  # automatically on a healthy TPU (CCX_BENCH_MXU=0 skips; the explicit
  # probe steps below stay as the full-output bank), and routes the
  # target rung through the localhost gRPC sidecar (wire-inclusive T1;
  # CCX_BENCH_SIDECAR overrides). Every rung line carries a
  # compile_cache hit/miss report — a warm run with fresh compiles is a
  # cache regression, visible right in BENCH_r*.json.
  CCX_BENCH_CPU_FIRST=0 timeout -k 60 5400 python bench.py
  echo "bench pass 1 rc=$?"
  echo "--- bench pass 2 (warm cache; official-style numbers) ---"
  CCX_BENCH_CPU_FIRST=0 timeout -k 60 2400 python bench.py
  echo "bench pass 2 rc=$?"
  echo "--- sidecar-inclusive T1 at B5 (gRPC hop on the real device) ---"
  PROBE_CPU=0 timeout -k 60 2400 python tools/bench_sidecar.py B5
  echo "sidecar rc=$?"
  echo "--- swap-engine program prewarm probe at B5 ---"
  # the usage-coupled swap-polish while_loop is a NEW compiled program
  # (r6): prove its compile on hardware before any timed rung depends on
  # it (same rationale as the bench prewarm — a >17-min compile must
  # surface here with a breadcrumb, not eat a rung). The budget is traced
  # data, so this floored run compiles the exact program every real
  # budget reuses.
  PROBE_SWAP_PREWARM=1 timeout -k 60 1800 python tools/probe_swap.py
  echo "swap-prewarm rc=$?"
  echo "--- MXU aggregates A/B at B5 ---"
  CCX_MXU_AGGREGATES=0 timeout -k 60 1200 python tools/probe_mxu.py B5
  echo "xla rc=$?"
  CCX_MXU_AGGREGATES=1 timeout -k 60 1800 python tools/probe_mxu.py B5
  echo "mxu rc=$?"
  echo "--- batched-SA moves sweep (16 then 32 moves/step) ---"
  PROBE_BATCHED=1 PROBE_MOVES=16 PROBE_CHAINS=16 timeout -k 60 1800 python tools/probe_b5.py B5
  echo "moves-16 rc=$?"
  PROBE_BATCHED=1 PROBE_MOVES=32 PROBE_CHAINS=16 timeout -k 60 1800 python tools/probe_b5.py B5
  echo "moves-32 rc=$?"
  echo "--- sharded-anneal step slope on the device set ---"
  CCX_BENCH_MESH=1 CCX_BENCH_CPU_FIRST=0 timeout -k 60 1800 python bench.py
  echo "mesh rc=$?"
  echo "--- B6 scaling rung (1->2->4->8 virtual CPU mesh; MULTICHIP artifact) ---"
  # the chunk-driven mesh path at B6 scale (10k brokers / 1M partitions):
  # per-layout (chains x parts) walls, quality-verified — the JSON line
  # is the MULTICHIP_r*.json artifact the bench ledger trends and gates.
  # CPU-only virtual mesh by definition (the tunnel exposes one chip), so
  # it never competes for the TPU window; recorder + watchdog stay armed.
  CCX_BENCH_SCALING=1 timeout -k 60 3600 python bench.py
  echo "scaling rc=$?"
  echo "--- fleet serving rung (16 concurrent B3 Propose streams; FLEET artifact) ---"
  # continuous batching of concurrent Propose jobs through the multi-job
  # chunk scheduler + the sidecar gRPC path (ISSUE 8): p50/p99 latency,
  # aggregate throughput and chunk occupancy vs the serialized baseline,
  # measured in one round — the JSON line is the FLEET_r*.json artifact
  # the bench ledger trends and gates. On a real TPU the host phases of
  # one job overlap the device chunks of another, which is where the
  # serialized-vs-concurrent gap opens far past the CPU host's core count.
  CCX_BENCH_FLEET=1 timeout -k 60 2400 python bench.py
  echo "fleet rc=$?"
  echo "--- steady-state incremental rung (warm re-proposals per metrics window; STEADY artifact) ---"
  # incremental re-optimization (ISSUE 10): one cold B5 Propose, then
  # repeat warm_start Proposes under 1% metrics drift through the real
  # gRPC sidecar — the <500 ms steady-state target. The flight recorder
  # stays armed, so the convergence_report pass at campaign end prices
  # the warm-start plateau budgets alongside the cold rungs' (the warm
  # anneal phases ride the same per-chunk heartbeat/tap machinery).
  CCX_BENCH_STEADY=1 timeout -k 60 2400 python bench.py
  echo "steady rc=$?"
  echo "--- steady-state fleet rung (N warm clusters x drift windows; STEADYFLEET artifact) ---"
  # the composition of the fleet and steady rungs (ISSUE 14): 16
  # shape-bucketed warm clusters drive 1%-drift windows CONCURRENTLY
  # through the sidecar, every device resident (snapshot model + warm
  # base) byte-priced on the unified device-memory ledger
  # (ccx.common.devmem) — aggregate windows/sec and per-window p99 are
  # the gated metrics, the measured loop must pay zero fresh compiles,
  # and the ledger is sampled per window to prove the fleet never
  # exceeds the budget. On TPU this is the "millions of users" rung: a
  # window per cluster per minute at N=1000 is ~17 windows/sec. Flight
  # recorder + watchdog stay armed (exported above).
  CCX_BENCH_STEADYFLEET=1 timeout -k 60 2400 python bench.py
  echo "steady-fleet rc=$?"
  echo "--- chaos rung (fault-injected drift windows; CHAOS artifact) ---"
  # chaos-hardened warm serving (ISSUE 12): the steady drift loop under a
  # seeded fault schedule — every seam class (stream sever/corrupt,
  # mid-wave engine kill, graft kill + HBM pressure, device-diff kill,
  # warm-bank kill, cold-pipeline kill) injected once per cycle, gated on
  # 100% recovered-and-verified windows, zero stuck scheduler jobs, zero
  # leaked registry/placement entries, bounded recovery latency, and a
  # zero-fresh-compile disarmed epilogue. The flight recorder stays armed
  # (exported above), so every injected fault's recovery leaves its
  # span/heartbeat trail in the same JSONL as the clean rungs.
  CCX_BENCH_CHAOS=1 timeout -k 60 2400 python bench.py
  echo "chaos rc=$?"
  echo "--- scenario rung (adversarial structural/elasticity matrix; SCENARIO artifact) ---"
  # the scenario corpus (ISSUE 15): every adversarial family — cascading
  # broker failures, disk-full evacuation, hot-topic skew, broker
  # add/demote/remove waves, partition-count changes — as cumulative
  # delta-snapshot windows through the sidecar's WARM path, gated on
  # per-window verification, per-family pinned quality envelopes, zero
  # measured-matrix compiles, and >=1 anomaly-verb family recovering
  # warm within 2x the clean steady p50. The campaign prices recovery
  # latency for the messy cases right next to the clean rungs; the
  # flight recorder stays armed (exported above), so every structural
  # window's repair/warm-SA phases leave their span trail.
  CCX_BENCH_SCENARIO=1 timeout -k 60 2400 python bench.py
  echo "scenario rc=$?"
  echo "--- soak rung (long-horizon closed-loop SLO soak; SOAK artifact) ---"
  # the closed-loop soak (ISSUE 20): N warm clusters x continuous drift
  # on a simulated fleet clock, scenario-family anomaly injections and
  # chaos faults on one seeded schedule — every injection detected,
  # healed (detector-initiated urgent re-propose, one verb per episode)
  # and verified recovered by ccx.detector.stream, accounted by the
  # windowed SLO engine (ccx.common.slo). Banks the SOAK artifact the
  # ledger gates on zero unrecovered episodes / detector-initiated
  # census / SLO compliance / bounded time-to-heal p99 / flat devmem /
  # zero measured-loop compiles. The flight recorder stays armed
  # (exported above), so every healing episode leaves its structured
  # detected->fired->recovered timeline in the recording —
  # `python -m ccx.common.tracing <recording.jsonl>` renders it.
  CCX_BENCH_SOAK=1 timeout -k 60 2400 python bench.py
  echo "soak rc=$?"
  echo "--- movement-planning rung (wave planner vs naive batching A/B; PLAN artifact) ---"
  # executor-aware movement planning (ISSUE 17): the compiled wave
  # planner vs the legacy executor's naive greedy batching, priced under
  # the same round-barrier fluid model — planned-vs-naive makespan and
  # peak per-broker inflow on the cold B5 diff and across the
  # disk-full-evacuation scenario family, the device planner pinned
  # bit-exact to the numpy oracle, and the warm re-plan-on-delta loop
  # measured at zero fresh compiles. Banks the PLAN artifact the ledger
  # gates on planned_better / oracle_match / zero fresh compiles. The
  # flight recorder stays armed (exported above), so the plan phases
  # leave their span trail next to the scenario rung they complement.
  CCX_BENCH_PLAN=1 timeout -k 60 2400 python bench.py
  echo "plan rc=$?"
  echo "--- replica-exchange rung (temperature-ladder A/B; EXCHANGE artifact) ---"
  # the replica-exchange ladder (ISSUE 16): flat SA chain batch vs the
  # K-rung temperature ladder at the same seeded chain/step budget —
  # chunks-to-plateau and final lex quality side by side, plus the K=1
  # bit-exactness probe (the degenerate ladder must trace the legacy
  # program) and the interval-retune probe (the exchange interval is
  # traced data; retuning it must hit the compile cache). Banks the
  # EXCHANGE artifact the ledger gates on ladder_better / k1_bitexact /
  # zero fresh compiles.
  CCX_BENCH_EXCHANGE=1 timeout -k 60 2400 python bench.py
  echo "exchange rc=$?"
  echo "--- wire / result-path rung (streamed columnar warm round-trips; WIRE artifact) ---"
  # the result-path split (ISSUE 11): warm end-to-end sidecar round-trip
  # with the optimizer excluded — snapshot-up / diff / assembly /
  # frame-pack / client-decode priced per leg through the real gRPC
  # sidecar with streamed columnar results and the device diff armed.
  # On TPU this is the number that decides whether the wire keeps up
  # once warm re-proposal drops to tens of ms.
  CCX_BENCH_WIRE=1 timeout -k 60 2400 python bench.py
  echo "wire rc=$?"
  echo "--- remaining BASELINE configs on hardware (B1-B4, lean effort) ---"
  # pin all four effort knobs to the lean values: bench collapses to ONE
  # honestly-labeled "custom" rung per config instead of climbing
  # smoke+lean+full (the full-rung cold compile would eat the window
  # before B2-B4 ever ran)
  for c in B1 B2 B3 B4; do
    CCX_BENCH="$c" CCX_BENCH_CPU_FIRST=0 \
      CCX_BENCH_CHAINS=16 CCX_BENCH_STEPS=1000 CCX_BENCH_MOVES=8 \
      CCX_BENCH_POLISH_ITERS=400 CCX_BENCH_PORTFOLIO=0 \
      timeout -k 60 1800 python bench.py
    echo "$c rc=$?"
  done
  echo "--- flight-recorder summary ---"
  # one-line diagnosis of the whole campaign's recording (works the same
  # when a wedge cut the campaign short and this block never ran — the
  # JSONL itself is the artifact; this summary is a convenience)
  timeout -k 10 60 python -m ccx.common.tracing "$CCX_FLIGHT_RECORDER"
  echo "--- convergence / wasted-budget table (budget advisor) ---"
  # plateau analysis over the SAME flight record (per-span heartbeat
  # energies: which phase of which rung kept burning chunks past its
  # plateau) plus the banked-artifact advisor table — the evidence for
  # shrinking rung budgets toward the <5 s T1 without quality risk
  # (tools/convergence_report.py; full per-goal series ride the BENCH
  # lines this campaign just banked)
  timeout -k 10 60 python tools/convergence_report.py --flight "$CCX_FLIGHT_RECORDER"
  timeout -k 10 120 python tools/convergence_report.py
  echo "--- bench ledger (trend + regression gate + roofline) ---"
  # the cross-round view of what this campaign just banked next to every
  # earlier round, the >10%-wall / quality-envelope tripwires, and the
  # cost-model budget table for the freshest costModel-carrying line
  timeout -k 10 60 python tools/bench_ledger.py
  timeout -k 10 60 python tools/bench_ledger.py --check
  echo "ledger check rc=$?"
  timeout -k 10 60 python tools/bench_ledger.py --roofline
  echo "=== TPU campaign end $(date -u +%FT%TZ) ==="
} >> "$L" 2>&1
