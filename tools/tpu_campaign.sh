#!/bin/bash
# One-shot TPU measurement campaign for a healthy tunnel window.
#
# The axon tunnel has been wedged for most of rounds 1-4; when a window
# opens, this script banks everything the perf story needs, in priority
# order, so a re-wedge mid-campaign still leaves the most valuable
# artifacts: (1) a bench pass that populates .jax_cache with every
# program the driver's end-of-round bench will need, (2) a warm-cache
# bench pass for the official-style TPU numbers, (3) the Pallas MXU
# aggregates kernel A/B + live-hardware validation, (4) the batched-SA
# moves sweep the round-3 verdict asked to re-measure on TPU.
#
# Usage: tools/tpu_campaign.sh [logfile]   (appends; default tpu_campaign.log)
set -u
cd "$(dirname "$0")/.."
L="${1:-tpu_campaign.log}"
{
  echo "=== TPU campaign start $(date -u +%FT%TZ) ==="
  echo "--- probe ---"
  if ! timeout 90 python -c "import jax; print(jax.devices())"; then
    echo "device probe FAILED — tunnel wedged; aborting campaign"
    exit 1
  fi
  echo "--- bench pass 1 (cold compiles -> persistent cache) ---"
  CCX_BENCH_CPU_FIRST=0 timeout 5400 python bench.py
  echo "bench pass 1 rc=$?"
  echo "--- bench pass 2 (warm cache; official-style numbers) ---"
  CCX_BENCH_CPU_FIRST=0 timeout 2400 python bench.py
  echo "bench pass 2 rc=$?"
  echo "--- MXU aggregates A/B at B5 ---"
  CCX_MXU_AGGREGATES=0 timeout 1200 python tools/probe_mxu.py B5
  echo "xla rc=$?"
  CCX_MXU_AGGREGATES=1 timeout 1800 python tools/probe_mxu.py B5
  echo "mxu rc=$?"
  echo "--- batched-SA moves sweep (16 then 32 moves/step) ---"
  PROBE_BATCHED=1 PROBE_MOVES=16 PROBE_CHAINS=16 timeout 1800 python tools/probe_b5.py B5
  echo "moves-16 rc=$?"
  PROBE_BATCHED=1 PROBE_MOVES=32 PROBE_CHAINS=16 timeout 1800 python tools/probe_b5.py B5
  echo "moves-32 rc=$?"
  echo "=== TPU campaign end $(date -u +%FT%TZ) ==="
} >> "$L" 2>&1
