#!/usr/bin/env python
"""Chunked-polish compile probe — the TPU-window smoke test for the
descent-engine programs (tools/tpu_campaign.sh runs it BEFORE any timed
rung, the same insurance the bench prewarm and tools/probe_swap.py give
the SA/swap programs).

For each requested config shape it times, via the per-label compile
accounting in ``ccx.common.compilestats``, the COLD compile and one-chunk
WARM run of every polish-family program the pipeline executes:

* ``polish``      — the uniform greedy chunk (shared by the pre-shed
                    polish, the trd-guarded re-polish and the portfolio
                    candidate: budgets and the guard are traced),
* ``leader-pass`` — the leadership-only chunk (its own program —
                    leadership_only is shape),
* ``swap-polish`` — the usage-coupled swap chunk (shared by the pre- and
                    post-leader invocations).

The round-4 TPU window died on exactly this compile (>17 min greedy
while_loop, timed out): this probe surfaces a pathological polish compile
in minutes, with a per-program breakdown, before a timed campaign rung is
at stake. ``PROBE_POLISH_MONOLITH=1`` also times the monolithic
(``chunk_iters=0``) while_loop programs — the measurement behind the
docs/perf-notes.md "Chunked polish" compile table.

Runnable under ``JAX_PLATFORMS=cpu``;
tests/test_polish_chunked.py::test_probe_polish_b1_smoke runs the B1
shape as a fast smoke-marked tier-1 test (``pytest -m smoke``).

Env: PROBE_CONFIGS comma-list (default "B1,B5"; B5S = 1/10-scale B5),
PROBE_POLISH_MONOLITH=1 adds the monolith timings, PROBE_CHUNK_ITERS
overrides the chunk size (default: the engine default).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _cluster(name: str):
    from ccx.model.fixtures import RandomClusterSpec, bench_spec, random_cluster

    if name == "B5S":  # 1/10-scale B5: the fast iteration config
        return random_cluster(RandomClusterSpec(
            n_brokers=100, n_racks=10, n_topics=50, n_partitions=10_000,
            n_dead_brokers=2, seed=7,
        ))
    return random_cluster(bench_spec(name))


def probe_config(
    name: str,
    chunk_iters: int | None = None,
    monolith: bool = False,
    n_candidates: int = 256,
    swap_candidates: int = 128,
) -> dict:
    """Compile+run ledger for every polish-family program at one config
    shape: ``{program: {compile_s, backend_compiles, run_s, iters}}``.
    ``chunk_iters=0`` (or ``monolith=True`` for the extra ``*-monolith``
    rows) times the while_loop engine instead."""
    from ccx.common import compilestats
    from ccx.goals.base import GoalConfig
    from ccx.goals.stack import DEFAULT_GOAL_ORDER
    from ccx.search.greedy import (
        GreedyOptions,
        SwapPolishOptions,
        greedy_optimize,
        swap_polish,
    )

    m = _cluster(name)
    cfg = GoalConfig()
    goals = DEFAULT_GOAL_ORDER if name != "B1" else (
        "StructuralFeasibility", "ReplicaDistributionGoal",
    )
    ci = GreedyOptions().chunk_iters if chunk_iters is None else chunk_iters
    # one chunk's worth of real iterations: cold run pays compile + one
    # chunk, warm run times the chunk alone
    iters = max(ci, 1)

    def g_opts(lead_only: bool, chunk: int) -> GreedyOptions:
        return GreedyOptions(
            n_candidates=n_candidates, max_iters=iters, patience=iters,
            leadership_only=lead_only, chunk_iters=chunk,
        )

    def s_opts(chunk: int) -> SwapPolishOptions:
        ksw = max(swap_candidates // 2, 1)
        return SwapPolishOptions(
            n_swap_candidates=ksw, n_lead_candidates=swap_candidates - ksw,
            max_iters=iters, patience=iters, chunk_iters=chunk,
        )

    programs = [
        ("polish", lambda c: greedy_optimize(m, cfg, goals, g_opts(False, c))),
        ("leader-pass",
         lambda c: greedy_optimize(m, cfg, goals, g_opts(True, c))),
    ]
    if name != "B1":  # the bench B1 rung never runs the swap-polish stage
        programs.append(
            ("swap-polish", lambda c: swap_polish(m, cfg, goals, s_opts(c)))
        )

    out: dict = {}
    variants = [("", ci)] + ([("-monolith", 0)] if monolith and ci else [])
    for suffix, chunk in variants:
        for prog, run in programs:
            label = f"probe:{name}:{prog}{suffix}"
            with compilestats.attributed(label):
                run(chunk)
            cold = compilestats.attribution()[label]
            t0 = time.monotonic()
            run(chunk)
            out[prog + suffix] = {
                "compile_s": cold["backend_compile_secs"],
                "backend_compiles": cold["backend_compiles"],
                "cold_wall_s": cold["wall_secs"],
                "run_s": round(time.monotonic() - t0, 2),
                "iters": iters,
                "chunk_iters": chunk,
            }
    return out


def main() -> None:
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                ".jax_cache",
            ),
        ),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    log = lambda s: print(f"[polish-probe] {s}", file=sys.stderr, flush=True)  # noqa: E731
    configs = os.environ.get("PROBE_CONFIGS", "B1,B5").split(",")
    monolith = os.environ.get("PROBE_POLISH_MONOLITH") == "1"
    chunk = os.environ.get("PROBE_CHUNK_ITERS")
    results = {}
    for name in (c.strip() for c in configs if c.strip()):
        t0 = time.monotonic()
        results[name] = probe_config(
            name, chunk_iters=int(chunk) if chunk else None, monolith=monolith
        )
        log(f"{name} done in {time.monotonic() - t0:.1f}s")
        for prog, row in results[name].items():
            log(f"  {name}/{prog}: compile={row['compile_s']}s "
                f"({row['backend_compiles']} programs) run={row['run_s']}s")
    print(json.dumps({"backend": jax.default_backend(),
                      "results": results}, indent=1), flush=True)


if __name__ == "__main__":
    main()
