#!/usr/bin/env bash
# check_bridge.sh — javac-optional bridge smoke + wire-fixture cross-check.
#
# Part of the repo verify flow (tier-1 runs it via
# tests/test_bridge_conformance.py; operators run it directly):
#   1. JVM-free fixture cross-check: regenerated wire bytes must match the
#      golden fixtures byte-for-byte (tools/gen_wire_fixtures.py --check).
#   2. If javac is on PATH: compile bridge/src/main (pure JDK, no jars).
#   3. If a JRE is also present: run ccx.bridge.tools.FixtureCheck — every
#      golden fixture must decode -> re-encode byte-identically through the
#      Java msgpack codec.
#   4. If CCX_BRIDGE_GRPC_CLASSPATH is set: compile bridge/src/grpc too.
# Steps 2-4 skip cleanly (exit 0, with a note) when the toolchain is absent.
#
# Env:
#   CCX_BRIDGE_SKIP_FIXTURES=1     skip step 1 (e.g. when pytest already ran it)
#   CCX_BRIDGE_GRPC_CLASSPATH=...  grpc-java jars for the transport compile
set -euo pipefail
cd "$(dirname "$0")/.."

if [ "${CCX_BRIDGE_SKIP_FIXTURES:-0}" != "1" ]; then
  echo "check_bridge: cross-checking wire fixtures (JVM-free)"
  python tools/gen_wire_fixtures.py --check
else
  echo "check_bridge: fixture cross-check skipped (CCX_BRIDGE_SKIP_FIXTURES=1)"
fi

if ! command -v javac >/dev/null 2>&1; then
  echo "check_bridge: javac not found — Java compile smoke skipped (OK)"
  exit 0
fi

out="$(mktemp -d)"
trap 'rm -rf "$out"' EXIT

echo "check_bridge: compiling bridge/src/main with $(javac -version 2>&1)"
# shellcheck disable=SC2046 — file list is ours, no spaces
javac -d "$out" $(find bridge/src/main/java -name '*.java' | sort)
echo "check_bridge: bridge core compiles clean"

if command -v java >/dev/null 2>&1; then
  java -cp "$out" ccx.bridge.tools.FixtureCheck tests/fixtures/sidecar
else
  echo "check_bridge: java (JRE) not found — FixtureCheck skipped (OK)"
fi

if [ -n "${CCX_BRIDGE_GRPC_CLASSPATH:-}" ]; then
  echo "check_bridge: compiling bridge/src/grpc against grpc-java"
  # shellcheck disable=SC2046
  javac -cp "$out:$CCX_BRIDGE_GRPC_CLASSPATH" -d "$out" \
    $(find bridge/src/grpc/java -name '*.java' | sort)
  echo "check_bridge: grpc transport compiles clean"
else
  echo "check_bridge: CCX_BRIDGE_GRPC_CLASSPATH unset — grpc transport compile skipped (OK)"
fi

echo "check_bridge: all checks passed"
