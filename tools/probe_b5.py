"""B5-scale phase probe: separate XLA compile from steady-state run time.

Usage: python tools/probe_b5.py [B5|B2|...]
Prints per-phase cold/warm timings and an anneal per-step slope so bench
tuning is driven by data, not guesses.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("PROBE_CPU") == "1":
    jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp

from ccx.goals.base import GoalConfig
from ccx.goals.stack import DEFAULT_GOAL_ORDER
from ccx.model.fixtures import bench_spec, random_cluster
from ccx.search.annealer import AnnealOptions, anneal
from ccx.search.greedy import GreedyOptions, greedy_optimize
from ccx.search.repair import hard_repair


def t(label, fn, *a, **k):
    t0 = time.monotonic()
    r = fn(*a, **k)
    jax.block_until_ready(jax.tree.leaves(r)[0] if jax.tree.leaves(r) else r)
    dt = time.monotonic() - t0
    print(f"[probe] {label}: {dt:.2f}s", flush=True)
    return r, dt


def main():
    name = sys.argv[1] if len(sys.argv) > 1 else "B5"
    print(f"[probe] backend={jax.default_backend()} devices={jax.devices()}", flush=True)
    spec = bench_spec(name)
    m = random_cluster(spec)
    print(f"[probe] {name}: P={m.P} B={m.B} T={m.num_topics} R={m.R}", flush=True)
    cfg = GoalConfig()

    (rep, n_rep), _ = t("repair cold", hard_repair, m, cfg, DEFAULT_GOAL_ORDER)
    t("repair warm", hard_repair, m, cfg, DEFAULT_GOAL_ORDER)

    chains = int(os.environ.get("PROBE_CHAINS", "32"))
    moves = int(os.environ.get("PROBE_MOVES", "8"))
    p_swap = float(os.environ.get("PROBE_SWAP", "0.15"))
    batched = os.environ.get("PROBE_BATCHED", "1") == "1"
    warms = {}
    for steps in (10, 50):
        opts = AnnealOptions(
            n_chains=chains, n_steps=steps, moves_per_step=moves, seed=42,
            p_swap=p_swap, batched=batched,
        )
        _, cold = t(f"anneal[{steps}] cold(compile+run)", anneal, rep, cfg,
                    DEFAULT_GOAL_ORDER, opts)
        _, warm = t(f"anneal[{steps}] warm", anneal, rep, cfg,
                    DEFAULT_GOAL_ORDER, opts)
        warms[steps] = warm
        per_step = warm / steps
        print(
            f"[probe] anneal per-step (chains={chains} moves={moves} "
            f"batched={batched}): {per_step * 1e3:.1f} ms -> 3000 steps = "
            f"{per_step * 3000:.0f}s",
            flush=True,
        )
    slope = (warms[50] - warms[10]) / 40
    print(
        f"[probe] anneal step SLOPE (chains={chains} moves={moves} "
        f"batched={batched}): {slope * 1e3:.1f} ms/step, "
        f"{slope / moves * 1e3:.2f} ms/proposal",
        flush=True,
    )

    popts = GreedyOptions(n_candidates=256, max_iters=5, patience=5)
    _, cold = t("polish[5 iters] cold", greedy_optimize, rep, cfg,
                DEFAULT_GOAL_ORDER, popts)
    _, warm = t("polish[5 iters] warm", greedy_optimize, rep, cfg,
                DEFAULT_GOAL_ORDER, popts)
    print(f"[probe] polish per-iter warm: {warm / 5 * 1e3:.0f} ms", flush=True)


if __name__ == "__main__":
    main()
