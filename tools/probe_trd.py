"""B5-scale shed/re-polish interplay probe (round-5 lean-rung tuning).

Runs the FULL optimize() pipeline at lean anneal effort with the
topic-rebalance knobs taken from env, printing phase seconds and the
before/after violation counts of the tiers the stage trades between
(usage distribution vs TopicReplicaDistribution). Drives the choice of
the bench lean rung's knobs by measurement.

Env: TRD_ROUNDS, TRD_SWEEPS, TRD_LEADERS, TRD_GUARD, PROBE_CPU,
CHAINS/STEPS/MOVES/POLISH (lean defaults).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("PROBE_CPU", "1") == "1":
    jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache",
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from ccx.goals.base import GoalConfig
from ccx.model.fixtures import bench_spec, random_cluster
from ccx.optimizer import OptimizeOptions, optimize
from ccx.search.annealer import AnnealOptions
from ccx.search.greedy import GreedyOptions

WATCH = (
    "ReplicaDistributionGoal",
    "DiskUsageDistributionGoal",
    "NetworkInboundUsageDistributionGoal",
    "NetworkOutboundUsageDistributionGoal",
    "CpuUsageDistributionGoal",
    "TopicReplicaDistributionGoal",
    "LeaderReplicaDistributionGoal",
    "LeaderBytesInDistributionGoal",
    "PotentialNwOutGoal",
)


def main():
    name = sys.argv[1] if len(sys.argv) > 1 else "B5"
    m = random_cluster(bench_spec(name))
    print(
        f"[trd] {name}: P={m.P} B={m.B} T={m.num_topics} R={m.R} "
        f"backend={jax.default_backend()}",
        flush=True,
    )
    opts = OptimizeOptions(
        anneal=AnnealOptions(
            n_chains=int(os.environ.get("CHAINS", "16")),
            n_steps=int(os.environ.get("STEPS", "1000")),
            moves_per_step=int(os.environ.get("MOVES", "8")),
            seed=42,
            chunk_steps=500,
        ),
        polish=GreedyOptions(
            n_candidates=256,
            max_iters=int(os.environ.get("POLISH", "400")),
            patience=16,
            batch_moves=int(os.environ.get("BATCH", "16")),
        ),
        run_cold_greedy=False,
        run_polish=os.environ.get("POLISH", "400") != "0",
        topic_rebalance_rounds=int(os.environ.get("TRD_ROUNDS", "2")),
        topic_rebalance_max_sweeps=int(os.environ.get("TRD_SWEEPS", "128")),
        topic_rebalance_move_leaders=os.environ.get("TRD_LEADERS", "0") == "1",
        topic_rebalance_guarded=os.environ.get("TRD_GUARD", "1") == "1",
        topic_rebalance_polish_iters=(
            int(os.environ["TRD_POLISH"])
            if os.environ.get("TRD_POLISH")
            else None
        ),
        leader_pass_max_iters=(
            int(os.environ["LEADCAP"]) if os.environ.get("LEADCAP") else None
        ),
    )
    print(
        f"[trd] rounds={opts.topic_rebalance_rounds} "
        f"sweeps={opts.topic_rebalance_max_sweeps} "
        f"leaders={opts.topic_rebalance_move_leaders} "
        f"guarded={opts.topic_rebalance_guarded}",
        flush=True,
    )
    t0 = time.monotonic()
    res = optimize(
        m, GoalConfig(), opts=opts,
        progress_cb=lambda ph: print(
            f"[trd] -> {ph} @ {time.monotonic() - t0:.1f}s", flush=True
        ),
    )
    wall = time.monotonic() - t0
    print(f"[trd] wall {wall:.1f}s phases={ {k: round(v, 1) for k, v in res.phase_seconds.items()} }", flush=True)
    print(f"[trd] verified={res.verification.ok} fails={res.verification.failures}", flush=True)
    before = res.stack_before.by_name()
    after = res.stack_after.by_name()
    for g in WATCH:
        print(f"[trd] {g}: {before[g][0]:.0f} -> {after[g][0]:.0f}", flush=True)


if __name__ == "__main__":
    main()
