"""A/B probe for the Pallas MXU broker-aggregates kernel on live TPU.

Measures, in the CURRENT process env (CCX_MXU_AGGREGATES is read once at
import, so the campaign script runs this twice — env 0 and env 1):

* broker_aggregates wall (jitted, warm) at B5 scale,
* full goal-stack evaluation wall (the aggregate pass's hottest consumer),
* when the MXU kernel is active, max-abs disagreement vs the XLA twin —
  the live-hardware validation gate `mxu_aggregates_enabled` asks for
  before the kernel can become the backend-gated default.

Usage: [CCX_MXU_AGGREGATES=1] python tools/probe_mxu.py [B5|B2|...]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("PROBE_CPU") == "1":
    jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache",
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import numpy as np


def timed(label, fn, *a, reps=5):
    # drain the async warmup fully before the clock starts — on TPU the
    # warmup call returns while the device is still executing, and its
    # tail would otherwise be charged to the ms-scale timed window
    jax.block_until_ready(fn(*a))
    t0 = time.monotonic()
    for _ in range(reps):
        r = fn(*a)
    jax.block_until_ready(jax.tree.leaves(r))
    dt = (time.monotonic() - t0) / reps
    print(f"[mxu-probe] {label}: {dt * 1e3:.2f} ms (warm, avg of {reps})",
          flush=True)
    return r


def main():
    from ccx.goals.base import GoalConfig
    from ccx.goals.stack import DEFAULT_GOAL_ORDER, evaluate_stack
    from ccx.model.aggregates import _broker_aggregates_xla, broker_aggregates
    from ccx.model.fixtures import bench_spec, random_cluster
    from ccx.ops.mxu_aggregates import mxu_aggregates_enabled

    name = sys.argv[1] if len(sys.argv) > 1 else "B5"
    print(
        f"[mxu-probe] backend={jax.default_backend()} "
        f"mxu_kernel={'ON' if mxu_aggregates_enabled() else 'off'}",
        flush=True,
    )
    m = random_cluster(bench_spec(name))
    print(f"[mxu-probe] {name}: P={m.P} B={m.B} T={m.num_topics}", flush=True)

    agg = timed("broker_aggregates", jax.jit(broker_aggregates), m)
    timed(
        "evaluate_stack (full goal stack)",
        jax.jit(evaluate_stack, static_argnums=(1, 2)),
        m, GoalConfig(), DEFAULT_GOAL_ORDER,
    )

    if mxu_aggregates_enabled():
        ref = jax.jit(_broker_aggregates_xla)(m)
        # rtol+atol, matching tests/test_ops_mxu.py: B5 per-broker f32
        # aggregates are ~1e4-1e5, where reordered f32 accumulation
        # (tiled matmul vs scatter-add) legitimately differs by far more
        # than any absolute epsilon — a pure abs gate would false-fail a
        # bit-correct kernel and burn the TPU window
        rtol, atol = 1e-5, 1e-3
        worst = 0.0
        for a, b in zip(jax.tree.leaves(agg), jax.tree.leaves(ref)):
            a = np.asarray(a, np.float64)
            b = np.asarray(b, np.float64)
            excess = np.abs(a - b) - (atol + rtol * np.abs(b))
            worst = max(worst, float(np.max(excess)))
        ok = worst <= 0.0
        print(f"[mxu-probe] worst excess over (atol={atol} + rtol={rtol}"
              f"*|xla|) = {worst:.3e} ({'OK' if ok else 'MISMATCH'})",
              flush=True)
        if not ok:
            # the campaign log gates on rc — a silent rc=0 would read as a
            # passed validation for flipping the kernel default
            sys.exit(1)


if __name__ == "__main__":
    main()
