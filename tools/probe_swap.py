#!/usr/bin/env python
"""Usage-coupled swap engine Pareto probe (and TPU prewarm for its programs).

Runs the bench lean-rung pipeline at several swap-engine settings on one
snapshot and prints a quality/wall table — the measurement behind
docs/perf-notes.md "Usage-coupled swaps" and the frontier evidence the
r6 issue asks for (NwOut <= 300 / LeaderReplica <= 400 at lean budget, or
a measured table proving the budget can't reach it).

In a TPU window this doubles as the swap-program compile probe
(tools/tpu_campaign.sh): PROBE_SWAP_PREWARM=1 runs ONE floored-budget
pipeline per program shape (prewarm_options floors the swap-polish budget
too — the budget is while_loop data, so the floored run compiles the
exact program every real budget reuses) and exits — a pathological
compile surfaces here, never inside a timed campaign rung.

Env: PROBE_CONFIG (default B5; B5S = 1/10-scale B5 for fast iteration),
PROBE_SWAP_SETTINGS comma-list of pre:post swap-polish budgets (default
"0:0,150:300"), PROBE_COUPLING comma-list of SA coupling settings
(default 0.5), PROBE_SWAP_PREWARM=1 prewarm-only.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> None:
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                ".jax_cache",
            ),
        ),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    from ccx.goals.base import GoalConfig
    from ccx.goals.stack import DEFAULT_GOAL_ORDER
    from ccx.model.fixtures import RandomClusterSpec, bench_spec, random_cluster
    from ccx.optimizer import OptimizeOptions, optimize, prewarm_options

    name = os.environ.get("PROBE_CONFIG", "B5")
    if name == "B5S":  # 1/10-scale B5: the fast iteration config
        m = random_cluster(RandomClusterSpec(
            n_brokers=100, n_racks=10, n_topics=50, n_partitions=10_000,
            n_dead_brokers=2, seed=7,
        ))
    else:
        m = random_cluster(bench_spec(name))
    from bench import build_opts

    _, lean_opts, _ = build_opts("B5", "lean")
    cfg = GoalConfig()
    log = lambda s: print(f"[swap-probe] {s}", file=sys.stderr, flush=True)  # noqa: E731

    if os.environ.get("PROBE_SWAP_PREWARM") == "1":
        t0 = time.monotonic()
        optimize(m, cfg, DEFAULT_GOAL_ORDER, prewarm_options(lean_opts))
        log(f"prewarm (incl. swap-polish program) {time.monotonic() - t0:.1f}s")
        return

    import dataclasses

    budgets = []
    for tok in os.environ.get("PROBE_SWAP_SETTINGS", "0:0,150:300").split(","):
        pre, _, post = tok.partition(":")
        budgets.append((int(pre), int(post or 0)))
    couplings = [
        float(x) for x in os.environ.get("PROBE_COUPLING", "0.5").split(",")
    ]
    # warm every program once so the table rows are compile-free
    optimize(
        m, cfg, DEFAULT_GOAL_ORDER,
        dataclasses.replace(prewarm_options(lean_opts), swap_polish_iters=1),
    )
    rows = []
    for c in couplings:
        for pre, post in budgets:
            opts = dataclasses.replace(
                lean_opts,
                anneal=dataclasses.replace(lean_opts.anneal, swap_coupling=c),
                swap_polish_iters=pre,
                swap_polish_post_iters=post,
            )
            t0 = time.monotonic()
            res = optimize(m, cfg, DEFAULT_GOAL_ORDER, opts)
            wall = time.monotonic() - t0
            a = {n: float(v) for n, (v, _) in res.stack_after.by_name().items()}
            row = {
                "coupling": c,
                "swap_polish_iters": [pre, post],
                "wall_s": round(wall, 1),
                "verified": bool(res.verification.ok),
                "NwOutUsage": a["NetworkOutboundUsageDistributionGoal"],
                "LeaderReplica": a["LeaderReplicaDistributionGoal"],
                "LeaderBytesIn": a["LeaderBytesInDistributionGoal"],
                "CpuUsage": a["CpuUsageDistributionGoal"],
                "TRD": a["TopicReplicaDistributionGoal"],
                "moveCounters": res.move_counters,
                "phases": {k: round(v, 1) for k, v in res.phase_seconds.items()},
            }
            rows.append(row)
            log(json.dumps(row))
    print(json.dumps({"config": name, "rows": rows}, indent=1), flush=True)


if __name__ == "__main__":
    main()
