"""Sidecar-inclusive T1 path measurement (VERDICT r04 weak #5 / next #5).

T1 as defined by the north star is snapshot-up / proposals-down through
the gRPC hop — `bench.py` times `optimize()` in-process and leaves the
hop unmeasured. This tool runs B5 through a real localhost gRPC
`OptimizerSidecar` and itemizes where the wire time goes:

  encode   — client-side `to_msgpack` of the full snapshot
  put      — PutSnapshot RTT (transfer + server decode + cache store)
  propose  — session-referencing Propose: optimize + result encode + reply
  delta    — warm-generation path: `delta_encode` one field + Propose

Cold = first propose in the process (tracing + persistent-cache load);
warm = second propose (the resident steady state). Prints one JSON line;
the table lives in docs/perf-notes.md.

Usage: [PROBE_CPU=1] python tools/bench_sidecar.py [B5|B2|...]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("PROBE_CPU", "1") == "1":
    jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache",
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import numpy as np

from ccx.model.fixtures import bench_spec, random_cluster
from ccx.model.snapshot import (
    delta_encode,
    model_to_arrays,
    pack_arrays,
    to_msgpack,
)
from ccx.sidecar.client import SidecarClient
from ccx.sidecar.server import make_grpc_server

#: the bench lean rung's effort (bench.py RUNGS["lean"] + round-5 stage)
LEAN_OPTIONS = dict(
    chains=16, steps=1000, moves_per_step=8, seed=42,
    polish_max_iters=400, run_polish=False, run_cold_greedy=False,
    topic_rebalance_rounds=1, topic_rebalance_max_sweeps=1024,
    topic_rebalance_move_leaders=True, topic_rebalance_polish_iters=700,
    leader_pass_max_iters=300,
)


def main() -> None:
    name = sys.argv[1] if len(sys.argv) > 1 else "B5"
    m = random_cluster(bench_spec(name))
    server, port = make_grpc_server(address="127.0.0.1:0")
    server.start()
    client = SidecarClient(f"127.0.0.1:{port}")
    out: dict = {"config": name, "backend": jax.default_backend()}

    t0 = time.monotonic()
    packed = to_msgpack(m)
    out["encode_s"] = round(time.monotonic() - t0, 3)
    out["snapshot_mb"] = round(len(packed) / 1e6, 2)

    t0 = time.monotonic()
    client.put_snapshot(None, session="t1", generation=1, packed=packed)
    out["put_s"] = round(time.monotonic() - t0, 3)

    for label in ("cold", "warm"):
        t0 = time.monotonic()
        res = client.propose(session="t1", **LEAN_OPTIONS)
        out[f"propose_{label}_s"] = round(time.monotonic() - t0, 3)
        out[f"optimize_{label}_s"] = round(res["wallSeconds"], 3)
        out[f"verified_{label}"] = bool(res.get("verified", False))
        out[f"proposals_{label}"] = len(res.get("proposals", []))

    # columnar proposals-down (the warm hop's dominant wire term)
    t0 = time.monotonic()
    res = client.propose(session="t1", columnar=True, **LEAN_OPTIONS)
    out["propose_columnar_s"] = round(time.monotonic() - t0, 3)
    out["optimize_columnar_s"] = round(res["wallSeconds"], 3)
    out["hop_overhead_columnar_s"] = round(
        out["propose_columnar_s"] - out["optimize_columnar_s"], 3
    )
    out["columnar_rows"] = int(res.get("numProposals", -1))

    # warm-generation delta path: leadership of partition 0 moves
    base = model_to_arrays(m)
    new = dict(base)
    ls = np.array(base["leader_slot"], np.int32).copy()
    ls[0] = (ls[0] + 1) % 2
    new["leader_slot"] = ls
    t0 = time.monotonic()
    dpacked = pack_arrays(delta_encode(base, new))
    out["delta_encode_s"] = round(time.monotonic() - t0, 3)
    out["delta_kb"] = round(len(dpacked) / 1e3, 1)
    t0 = time.monotonic()
    client.put_snapshot(
        None, session="t1", generation=2, is_delta=True,
        base_generation=1, packed=dpacked,
    )
    out["delta_put_s"] = round(time.monotonic() - t0, 3)
    t0 = time.monotonic()
    res = client.propose(session="t1", **LEAN_OPTIONS)
    out["propose_after_delta_s"] = round(time.monotonic() - t0, 3)
    out["verified_after_delta"] = bool(res.get("verified", False))

    # the hop's contribution to warm T1 = propose RTT minus device optimize
    out["hop_overhead_warm_s"] = round(
        out["propose_warm_s"] - out["optimize_warm_s"], 3
    )
    client.close()
    server.stop(0)
    print(json.dumps(out), flush=True)



if __name__ == "__main__":
    main()
