"""Python REST client.

Parity: ``cruise-control-client`` (SURVEY.md M4/C38): endpoint methods
mirroring the servlet surface, long-polling async responses — on a 202 the
client re-requests with the returned ``User-Task-ID`` header until the
operation completes, exactly the reference client's retry loop. stdlib-only
(urllib), so the client is a standalone file operators can vendored-copy.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.parse
import urllib.request


class CruiseControlClientError(Exception):
    def __init__(self, status: int, body: dict) -> None:
        self.status = status
        self.body = body
        super().__init__(f"HTTP {status}: {body.get('errorMessage', body)}")


class CruiseControlClient:
    def __init__(self, base_url: str = "http://127.0.0.1:9090",
                 auth: tuple[str, str] | None = None,
                 poll_interval_s: float = 1.0, timeout_s: float = 600.0) -> None:
        self.base = base_url.rstrip("/") + "/kafkacruisecontrol"
        self.auth = auth
        self.poll_interval_s = poll_interval_s
        self.timeout_s = timeout_s

    # ----- plumbing ---------------------------------------------------------

    def _request(self, method: str, endpoint: str, params: dict | None = None,
                 task_id: str | None = None) -> tuple[int, dict, dict]:
        query = urllib.parse.urlencode(
            {k: _render(v) for k, v in (params or {}).items() if v is not None}
        )
        url = f"{self.base}/{endpoint}" + (f"?{query}" if query else "")
        req = urllib.request.Request(url, method=method)
        req.add_header("Accept", "application/json")
        if task_id:
            req.add_header("User-Task-ID", task_id)
        if self.auth:
            import base64

            tok = base64.b64encode(f"{self.auth[0]}:{self.auth[1]}".encode())
            req.add_header("Authorization", f"Basic {tok.decode()}")
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                return (
                    resp.status,
                    json.loads(resp.read() or b"{}"),
                    dict(resp.headers),
                )
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}"), dict(e.headers)

    def call(self, method: str, endpoint: str, params: dict | None = None) -> dict:
        """Request + long-poll to completion (ref client retry loop)."""
        deadline = time.monotonic() + self.timeout_s
        status, body, headers = self._request(method, endpoint, params)
        task_id = headers.get("User-Task-ID")
        while status == 202:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"{endpoint} still running after {self.timeout_s}s "
                    f"(task {task_id})"
                )
            time.sleep(self.poll_interval_s)
            status, body, headers = self._request(
                method, endpoint, None, task_id=task_id
            )
        if status >= 400:
            raise CruiseControlClientError(status, body)
        return body

    # ----- endpoint methods (ref C38 endpoint classes) ----------------------

    def state(self, substates: tuple[str, ...] = ()) -> dict:
        return self.call("GET", "state",
                         {"substates": substates} if substates else None)

    def load(self) -> dict:
        return self.call("GET", "load")

    def partition_load(self, max_load_entries: int = 100, resource: str = "CPU",
                       topic: str = "") -> dict:
        return self.call("GET", "partition_load", {
            "max_load_entries": max_load_entries, "resource": resource,
            "topic": topic or None,
        })

    def proposals(self, ignore_cache: bool = False) -> dict:
        return self.call("GET", "proposals",
                         {"ignore_proposal_cache": ignore_cache})

    def kafka_cluster_state(self) -> dict:
        return self.call("GET", "kafka_cluster_state")

    def user_tasks(self) -> dict:
        return self.call("GET", "user_tasks")

    def permissions(self) -> dict:
        return self.call("GET", "permissions")

    def rebalance(self, dryrun: bool = True, goals: tuple[str, ...] = (),
                  excluded_topics: str = "", rebalance_disk: bool = False,
                  destination_broker_ids: tuple[int, ...] = (),
                  reason: str = "", review_id: int | None = None) -> dict:
        return self.call("POST", "rebalance", {
            "dryrun": dryrun, "goals": goals or None,
            "excluded_topics": excluded_topics or None,
            "rebalance_disk": rebalance_disk or None,
            "destination_broker_ids": destination_broker_ids or None,
            "reason": reason or None, "review_id": review_id,
        })

    def add_broker(self, broker_ids, dryrun: bool = True, reason: str = "",
                   review_id: int | None = None) -> dict:
        return self.call("POST", "add_broker", {
            "brokerid": tuple(broker_ids), "dryrun": dryrun,
            "reason": reason or None, "review_id": review_id,
        })

    def remove_broker(self, broker_ids, dryrun: bool = True, reason: str = "",
                      destination_broker_ids: tuple[int, ...] = (),
                      review_id: int | None = None) -> dict:
        return self.call("POST", "remove_broker", {
            "brokerid": tuple(broker_ids), "dryrun": dryrun,
            "destination_broker_ids": destination_broker_ids or None,
            "reason": reason or None, "review_id": review_id,
        })

    def demote_broker(self, broker_ids, dryrun: bool = True, reason: str = "",
                      review_id: int | None = None) -> dict:
        return self.call("POST", "demote_broker", {
            "brokerid": tuple(broker_ids), "dryrun": dryrun,
            "reason": reason or None, "review_id": review_id,
        })

    def fix_offline_replicas(self, dryrun: bool = True, reason: str = "") -> dict:
        return self.call("POST", "fix_offline_replicas",
                         {"dryrun": dryrun, "reason": reason or None})

    def topic_configuration(self, topic: str, replication_factor: int,
                            dryrun: bool = True) -> dict:
        return self.call("POST", "topic_configuration", {
            "topic": topic, "replication_factor": replication_factor,
            "dryrun": dryrun,
        })

    def rightsize(self) -> dict:
        return self.call("POST", "rightsize")

    def stop_proposal_execution(self) -> dict:
        return self.call("POST", "stop_proposal_execution")

    def pause_sampling(self, reason: str = "") -> dict:
        return self.call("POST", "pause_sampling", {"reason": reason or None})

    def resume_sampling(self, reason: str = "") -> dict:
        return self.call("POST", "resume_sampling", {"reason": reason or None})

    def admin(self, **params) -> dict:
        return self.call("POST", "admin", params)

    def review(self, approve: tuple[int, ...] = (),
               discard: tuple[int, ...] = ()) -> dict:
        return self.call("POST", "review", {
            "approve": approve or None, "discard": discard or None,
        })

    def review_board(self) -> dict:
        return self.call("GET", "review_board")


def _render(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (list, tuple)):
        return ",".join(str(x) for x in v)
    return str(v)
