"""cccli — the operator command line.

Parity: the ``cccli`` entrypoint of ``cruise-control-client`` (SURVEY.md
M4/C38): one subcommand per endpoint, ``--socket-address`` for the server,
JSON output (pretty by default, ``--raw`` for machine use), long-polling
handled by the client library.

Usage::

    python -m ccx.client state
    python -m ccx.client rebalance --dryrun
    python -m ccx.client remove-broker 3 --no-dryrun --reason decommission
"""

from __future__ import annotations

import argparse
import json
import sys

from ccx.client.client import CruiseControlClient, CruiseControlClientError


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("-a", "--socket-address", default="http://127.0.0.1:9090",
                   help="Cruise Control server address")
    p.add_argument("--user", help="basic-auth user:password")
    p.add_argument("--raw", action="store_true", help="compact JSON output")


def _add_dryrun(p: argparse.ArgumentParser) -> None:
    g = p.add_mutually_exclusive_group()
    g.add_argument("--dryrun", dest="dryrun", action="store_true", default=True)
    g.add_argument("--no-dryrun", dest="dryrun", action="store_false")
    p.add_argument("--reason", default="")
    p.add_argument("--review-id", type=int, default=None)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="cccli", description=__doc__)
    sub = ap.add_subparsers(dest="command", required=True)

    def cmd(name, **kw):
        p = sub.add_parser(name, **kw)
        _add_common(p)
        return p

    cmd("state").add_argument("--substates", default="")
    cmd("load")
    p = cmd("partition-load")
    p.add_argument("--max-entries", type=int, default=100)
    p.add_argument("--resource", default="CPU")
    p.add_argument("--topic", default="")
    cmd("proposals").add_argument("--ignore-cache", action="store_true")
    cmd("kafka-cluster-state")
    cmd("user-tasks")
    cmd("permissions")
    p = cmd("rebalance")
    _add_dryrun(p)
    p.add_argument("--goals", default="")
    p.add_argument("--excluded-topics", default="")
    p.add_argument("--rebalance-disk", action="store_true")
    p.add_argument("--destination-broker-ids", default="")
    for name in ("add-broker", "remove-broker", "demote-broker"):
        p = cmd(name)
        p.add_argument("brokers", help="comma-separated broker ids")
        _add_dryrun(p)
    p = cmd("fix-offline-replicas")
    _add_dryrun(p)
    p = cmd("topic-configuration")
    p.add_argument("topic")
    p.add_argument("replication_factor", type=int)
    _add_dryrun(p)
    cmd("rightsize")
    cmd("stop-proposal-execution")
    cmd("pause-sampling").add_argument("--reason", default="")
    cmd("resume-sampling").add_argument("--reason", default="")
    p = cmd("admin")
    p.add_argument("--enable-self-healing-for", default="")
    p.add_argument("--disable-self-healing-for", default="")
    p.add_argument("--concurrency", type=int, default=None)
    p = cmd("review")
    p.add_argument("--approve", default="")
    p.add_argument("--discard", default="")
    cmd("review-board")
    return ap


def _ids(csv: str) -> tuple[int, ...]:
    return tuple(int(x) for x in csv.split(",") if x.strip())


def _strs(csv: str) -> tuple[str, ...]:
    return tuple(x.strip() for x in csv.split(",") if x.strip())


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    auth = tuple(args.user.split(":", 1)) if getattr(args, "user", None) else None
    c = CruiseControlClient(args.socket_address, auth=auth)
    try:
        cmdname = args.command
        if cmdname == "state":
            out = c.state(_strs(args.substates))
        elif cmdname == "load":
            out = c.load()
        elif cmdname == "partition-load":
            out = c.partition_load(args.max_entries, args.resource, args.topic)
        elif cmdname == "proposals":
            out = c.proposals(args.ignore_cache)
        elif cmdname == "kafka-cluster-state":
            out = c.kafka_cluster_state()
        elif cmdname == "user-tasks":
            out = c.user_tasks()
        elif cmdname == "permissions":
            out = c.permissions()
        elif cmdname == "rebalance":
            out = c.rebalance(
                dryrun=args.dryrun, goals=_strs(args.goals),
                excluded_topics=args.excluded_topics,
                rebalance_disk=args.rebalance_disk,
                destination_broker_ids=_ids(args.destination_broker_ids),
                reason=args.reason, review_id=args.review_id,
            )
        elif cmdname == "add-broker":
            out = c.add_broker(_ids(args.brokers), args.dryrun, args.reason,
                               args.review_id)
        elif cmdname == "remove-broker":
            out = c.remove_broker(_ids(args.brokers), args.dryrun, args.reason,
                                  review_id=args.review_id)
        elif cmdname == "demote-broker":
            out = c.demote_broker(_ids(args.brokers), args.dryrun, args.reason,
                                  args.review_id)
        elif cmdname == "fix-offline-replicas":
            out = c.fix_offline_replicas(args.dryrun, args.reason)
        elif cmdname == "topic-configuration":
            out = c.topic_configuration(args.topic, args.replication_factor,
                                        args.dryrun)
        elif cmdname == "rightsize":
            out = c.rightsize()
        elif cmdname == "stop-proposal-execution":
            out = c.stop_proposal_execution()
        elif cmdname == "pause-sampling":
            out = c.pause_sampling(args.reason)
        elif cmdname == "resume-sampling":
            out = c.resume_sampling(args.reason)
        elif cmdname == "admin":
            out = c.admin(
                enable_self_healing_for=_strs(args.enable_self_healing_for) or None,
                disable_self_healing_for=_strs(args.disable_self_healing_for) or None,
                concurrent_partition_movements_per_broker=args.concurrency,
            )
        elif cmdname == "review":
            out = c.review(_ids(args.approve), _ids(args.discard))
        elif cmdname == "review-board":
            out = c.review_board()
        else:  # pragma: no cover
            raise SystemExit(f"unknown command {cmdname}")
    except CruiseControlClientError as e:
        print(json.dumps(e.body, indent=None if args.raw else 2),
              file=sys.stderr)
        return 1
    print(json.dumps(out, indent=None if args.raw else 2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
