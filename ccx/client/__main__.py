from ccx.client.cli import main

raise SystemExit(main())
