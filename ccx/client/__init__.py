"""Python REST client + cccli (ref M4/C38)."""

from ccx.client.client import CruiseControlClient, CruiseControlClientError

__all__ = ["CruiseControlClient", "CruiseControlClientError"]
