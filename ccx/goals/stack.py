"""Goal-stack evaluation — lexicographic priority as tiered scalarization.

Parity: ``analyzer/GoalOptimizer.java`` (SURVEY.md C14) runs goals
sequentially in priority order, later goals forbidden from breaking earlier
ones via ``actionAcceptance``. A single device-side scalar cannot reproduce
that exactly (SURVEY.md section 7.4), so the rebuild uses:

* hard goals -> one large-weight infeasibility term (search also masks
  obviously-infeasible moves up front);
* soft goals -> geometrically-tiered weights in priority order, so a
  higher-priority improvement always dominates any lower-priority regression
  the annealer could trade for it (within float32 resolution);
* a final greedy repair/polish pass (ccx.search) re-establishes hard goals
  exactly; the verifier (ccx.verify) checks the reference's post-conditions
  rather than move-for-move parity.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from flax import struct

from ccx.common import costmodel
from ccx.goals import kernels  # noqa: F401  (populates the registry)
from ccx.goals.base import GOAL_REGISTRY, GoalConfig
from ccx.model.aggregates import BrokerAggregates, broker_aggregates
from ccx.model.tensor_model import TensorClusterModel

#: Default priority order — AnalyzerConfig `goals` default (SURVEY.md
#: section 2.3), with the structural-liveness term always first.
#: RackAwareDistributionGoal is registered but not in the default stack
#: (it is the configurable alternative to RackAwareGoal, as upstream).
DEFAULT_GOAL_ORDER: tuple[str, ...] = (
    "StructuralFeasibility",
    "RackAwareGoal",
    "MinTopicLeadersPerBrokerGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
    "NetworkInboundCapacityGoal",
    "NetworkOutboundCapacityGoal",
    "CpuCapacityGoal",
    "ReplicaDistributionGoal",
    "PotentialNwOutGoal",
    "DiskUsageDistributionGoal",
    "NetworkInboundUsageDistributionGoal",
    "NetworkOutboundUsageDistributionGoal",
    "CpuUsageDistributionGoal",
    "TopicReplicaDistributionGoal",
    "LeaderReplicaDistributionGoal",
    "LeaderBytesInDistributionGoal",
    "PreferredLeaderElectionGoal",
)

#: AnalyzerConfig `hard.goals` default set — derived from the registry's
#: per-goal hard flags so there is a single source of truth.
DEFAULT_HARD_GOALS: tuple[str, ...] = tuple(
    n for n in DEFAULT_GOAL_ORDER if GOAL_REGISTRY[n].hard
)

#: Goal stack for the rebalance_disk endpoint (SURVEY.md C18).
INTRA_BROKER_GOAL_ORDER: tuple[str, ...] = (
    "IntraBrokerDiskCapacityGoal",
    "IntraBrokerDiskUsageDistributionGoal",
)

HARD_WEIGHT = 1e6
SOFT_TIER_BASE = 4.0


@struct.dataclass
class StackResult:
    names: tuple[str, ...] = struct.field(pytree_node=False)
    hard_mask: tuple[bool, ...] = struct.field(pytree_node=False)
    violations: jnp.ndarray  # f32[n_goals]
    costs: jnp.ndarray       # f32[n_goals]

    @property
    def hard_violations(self) -> jnp.ndarray:
        mask = jnp.asarray(self.hard_mask)
        return jnp.sum(jnp.where(mask, self.violations, 0.0))

    @property
    def hard_cost(self) -> jnp.ndarray:
        mask = jnp.asarray(self.hard_mask)
        return jnp.sum(jnp.where(mask, self.costs, 0.0))

    @property
    def soft_scalar(self) -> jnp.ndarray:
        """Tier-weighted soft cost only. Search compares (hard_cost,
        soft_scalar) lexicographically — folding both into one float32
        (see ``scalar``) would erase soft deltas below the ULP of the huge
        hard term exactly while the annealer is repairing infeasibility."""
        mask = jnp.asarray(self.hard_mask)
        return jnp.sum(jnp.where(mask, 0.0, self.costs * soft_weights(self.hard_mask)))

    @property
    def scalar(self) -> jnp.ndarray:
        """Single-number summary for reporting/telemetry only; do not use
        for acceptance decisions (float32 plateau — see soft_scalar)."""
        return scalar_cost(self.costs, self.hard_mask)

    def by_name(self) -> dict[str, tuple[float, float]]:
        v = [float(x) for x in self.violations]
        c = [float(x) for x in self.costs]
        return {n: (v[i], c[i]) for i, n in enumerate(self.names)}


def soft_weights(hard_mask: tuple[bool, ...]) -> jnp.ndarray:
    """Tiered weights: hard goals get HARD_WEIGHT; soft goals decay
    geometrically in priority order, first soft goal at weight 1."""
    w = []
    soft_rank = 0
    for h in hard_mask:
        if h:
            w.append(HARD_WEIGHT)
        else:
            w.append(SOFT_TIER_BASE ** (-soft_rank))
            soft_rank += 1
    return jnp.asarray(w, jnp.float32)


def scalar_cost(costs: jnp.ndarray, hard_mask: tuple[bool, ...]) -> jnp.ndarray:
    return jnp.sum(costs * soft_weights(hard_mask))


def _evaluate(m, agg, cfg, goal_names) -> StackResult:
    violations, costs, hard_mask = [], [], []
    for name in goal_names:
        spec = GOAL_REGISTRY[name]
        r = spec.fn(m, agg, cfg)
        violations.append(r.violations)
        costs.append(r.cost)
        hard_mask.append(spec.hard)
    return StackResult(
        names=tuple(goal_names),
        hard_mask=tuple(hard_mask),
        violations=jnp.stack(violations),
        costs=jnp.stack(costs),
    )


@costmodel.instrument("stack-eval")
@functools.partial(jax.jit, static_argnames=("cfg", "goal_names"))
def _evaluate_no_agg(m, *, cfg, goal_names) -> StackResult:
    return _evaluate(m, broker_aggregates(m), cfg, goal_names)


@costmodel.instrument("stack-eval-agg")
@functools.partial(jax.jit, static_argnames=("cfg", "goal_names"))
def _evaluate_with_agg(m, agg, *, cfg, goal_names) -> StackResult:
    return _evaluate(m, agg, cfg, goal_names)


def evaluate_stack(
    m: TensorClusterModel,
    cfg: GoalConfig,
    goal_names: tuple[str, ...] = DEFAULT_GOAL_ORDER,
    agg: BrokerAggregates | None = None,
) -> StackResult:
    """Score one model state against an ordered goal stack. Runs as ONE
    compiled XLA program per (stack, cfg, shapes) — eager per-op dispatch is
    prohibitive on a remote-tunneled TPU device."""
    if agg is None:
        return _evaluate_no_agg(m, cfg=cfg, goal_names=tuple(goal_names))
    return _evaluate_with_agg(m, agg, cfg=cfg, goal_names=tuple(goal_names))
