"""Per-topic-row goal contributions for the two [T, B]-shaped goals.

``MinTopicLeadersPerBrokerGoal`` and ``TopicReplicaDistributionGoal``
(reference ``analyzer/goals/{MinTopicLeadersPerBrokerGoal,
TopicReplicaDistributionGoal}.java``, SURVEY.md C16/C17) score the
(topic, broker) count matrices. Materializing per-candidate copies of those
[T, B] aggregates was the round-1 bottleneck (candidate scoring moved ~0.5 GB
per batch at B5 scale); the fix is the same factoring as
``ccx.goals.partition_terms``: the penalty math lives in *row* functions over
one topic's [B] count row, so

* the full kernels (ccx.goals.kernels) vmap them over all T rows, and
* incremental search (ccx.search) re-scores only the single row a move
  touches — a move on partition p can only change topic(p)'s counts *and*
  that topic's alive-broker total, so every other row's contribution (and
  band) is provably unchanged,

from one implementation, so incremental sums can never drift from the full
evaluation semantics. All raw sums are integer-valued (counts and integer
band edges), hence exactly representable in float32 — incremental search can
add/subtract row deltas thousands of times with zero drift.
"""

from __future__ import annotations

import jax.numpy as jnp

from ccx.goals.base import GoalConfig
from ccx.model.tensor_model import TensorClusterModel

#: Goals whose contribution search maintains via topic-row deltas.
TOPIC_GOALS: tuple[str, ...] = (
    "MinTopicLeadersPerBrokerGoal",
    "TopicReplicaDistributionGoal",
)


def mtl_row(
    m: TensorClusterModel,
    cfg: GoalConfig,
    flagged: jnp.ndarray,   # bool[...] — topic is in the min-leaders set
    tlc_row: jnp.ndarray,   # int32[..., B] — topic_leader_count row(s)
) -> jnp.ndarray:
    """float32[...] — raw leader deficit of one (or a batch of) topic row(s):
    sum over eligible brokers of max(k - leaders, 0)."""
    alive = m.broker_valid & m.broker_alive & ~m.broker_excl_leadership
    k = cfg.min_topic_leaders_per_broker
    deficit = jnp.maximum(k - tlc_row, 0)
    deficit = jnp.where(flagged[..., None] & alive, deficit, 0)
    return jnp.sum(deficit, axis=-1).astype(jnp.float32)


def trd_row_total(m: TensorClusterModel, trc_row: jnp.ndarray) -> jnp.ndarray:
    """float32[...] — alive-broker replica total of one topic row."""
    alive = m.broker_valid & m.broker_alive
    return jnp.sum(jnp.where(alive, trc_row, 0), axis=-1).astype(jnp.float32)


def trd_row_pen(
    m: TensorClusterModel,
    cfg: GoalConfig,
    trc_row: jnp.ndarray,   # int32[..., B]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(raw_pen, n_offenders) float32[...] for one (or a batch of) topic
    row(s). Band edges are ceil/floor of avg*threshold, so the raw penalty is
    integer-valued."""
    alive = m.broker_valid & m.broker_alive
    n_alive = jnp.maximum(jnp.sum(alive), 1).astype(jnp.float32)
    total = trd_row_total(m, trc_row)
    avg = total / n_alive
    t = cfg.topic_replica_balance_threshold
    upper = jnp.ceil(avg * t)[..., None]
    lower = jnp.floor(avg * (2.0 - t))[..., None]
    counts = trc_row.astype(jnp.float32)
    pen = jnp.maximum(counts - upper, 0.0) + jnp.maximum(lower - counts, 0.0)
    pen = jnp.where(alive, pen, 0.0)
    return jnp.sum(pen, axis=-1), jnp.sum(pen > 0, axis=-1).astype(jnp.float32)


def trd_normalizer(
    m: TensorClusterModel, topic_totals: jnp.ndarray
) -> jnp.ndarray:
    """Normalizer of the TopicReplicaDistribution cost: mean over topics of
    max(avg_replicas_per_alive_broker, 1) — identical to the full kernel's
    ``_safe(mean(maximum(avg, 1.0)))``."""
    n_alive = jnp.maximum(jnp.sum(m.broker_valid & m.broker_alive), 1).astype(
        jnp.float32
    )
    avg = topic_totals / n_alive
    norm = jnp.mean(jnp.maximum(avg, 1.0))
    return jnp.where(norm > 0, norm, 1.0)
