"""The built-in goal stack as penalty kernels.

Each function mirrors one reference goal class from
``analyzer/goals/`` (SURVEY.md C16-C18; class names in register_goal).
Semantics reconstructed from upstream behavior — re-verify against the
reference source when the mount is restored (SURVEY.md section 7.4
"fidelity debt").

Conventions:
* Averages/bands are computed over *alive, valid* brokers — dead brokers
  must end up empty, which the structural liveness term enforces.
* ``violations`` counts discrete offenders (brokers, partitions or
  replicas, matching what the reference's per-goal optimization would
  still find unbalanced); ``cost`` is a smooth normalized hinge the
  annealer can descend.
* All kernels are pure, jit-safe, and vmappable over batched aggregates.
"""

from __future__ import annotations

import jax.numpy as jnp

from ccx.common.resources import Resource
from ccx.goals.base import GoalConfig, GoalResult, register_goal, result
from ccx.goals import partition_terms as pt
from ccx.goals import topic_terms as tt
from ccx.model.aggregates import BrokerAggregates
from ccx.model.tensor_model import TensorClusterModel


def _alive(m: TensorClusterModel) -> jnp.ndarray:
    return m.broker_valid & m.broker_alive


def _safe(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(x > 0, x, 1.0)


def _n_alive(m: TensorClusterModel) -> jnp.ndarray:
    return jnp.maximum(jnp.sum(_alive(m)), 1).astype(jnp.float32)


def scoring_dtype(bf16: bool) -> jnp.dtype:
    """dtype for RANK-ORDER-ONLY scoring intermediates (ISSUE 16).

    The band-pressure tables and the coupled-swap pool scorer only ever
    feed an argmax/Gumbel pick — nothing downstream reads their magnitude
    — so with ``bf16_scoring`` armed they may accumulate in bfloat16 (MXU
    native) and halve the scoring bandwidth. Every lex cost vector and
    every accept/exchange decision stays f32: goal kernels, ``lex_accept``
    and ``exchange_permutation`` must never route through this helper.
    """
    return jnp.bfloat16 if bf16 else jnp.float32


# --------------------------------------------------------------------------
# Structural feasibility (implicit in every reference goal's requirements):
# replicas must not sit on dead brokers / dead disks, leadership must not sit
# on leadership-excluded brokers, and a partition must not have two replicas
# on the same broker. The reference enforces these inside goal optimization
# (e.g. self-healing moves off dead brokers first); here they are one
# always-on top-priority hard term.
# --------------------------------------------------------------------------
@register_goal("StructuralFeasibility", hard=True, ref_class="ClusterModel invariants + self-healing requirements", placement_dependent=True)
def structural_feasibility(m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig) -> GoalResult:
    n = jnp.sum(
        pt.structural_rows(
            m, m.assignment, m.leader_slot, m.replica_disk, m.partition_valid
        )
    )
    return result(n, n)


# --------------------------------------------------------------------------
# Rack awareness
# --------------------------------------------------------------------------
@register_goal("RackAwareGoal", hard=True, placement_dependent=True)
def rack_aware(m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig) -> GoalResult:
    """Replicas of a partition live on distinct racks (ref: RackAwareGoal —
    violation when two replicas share a rack, fixable while rf <= #racks)."""
    n = jnp.sum(pt.rack_aware_rows(m, m.assignment, m.partition_valid))
    return result(n, n)


@register_goal("RackAwareDistributionGoal", hard=True, placement_dependent=True)
def rack_aware_distribution(m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig) -> GoalResult:
    """Replicas of a partition spread evenly over racks: no rack holds more
    than ceil(rf / #racks) (ref: RackAwareDistributionGoal, which relaxes
    RackAwareGoal for rf > #racks)."""
    n = jnp.sum(pt.rack_aware_distribution_rows(m, m.assignment, m.partition_valid))
    return result(n, n)


# --------------------------------------------------------------------------
# Capacity goals (hard)
# --------------------------------------------------------------------------
def _capacity_goal(res: Resource):
    def fn(m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig) -> GoalResult:
        alive = _alive(m)
        cap = m.broker_capacity[res] * cfg.capacity_threshold[int(res)]
        load = agg.broker_load[res]
        excess = jnp.where(alive, jnp.maximum(load - cap, 0.0), 0.0)
        n = jnp.sum(excess > 0).astype(jnp.float32)
        return result(n, jnp.sum(excess / _safe(cap)))

    return fn


register_goal("CpuCapacityGoal", hard=True)(_capacity_goal(Resource.CPU))
register_goal("NetworkInboundCapacityGoal", hard=True)(_capacity_goal(Resource.NW_IN))
register_goal("NetworkOutboundCapacityGoal", hard=True)(_capacity_goal(Resource.NW_OUT))
register_goal("DiskCapacityGoal", hard=True)(_capacity_goal(Resource.DISK))


@register_goal("ReplicaCapacityGoal", hard=True)
def replica_capacity(m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig) -> GoalResult:
    alive = _alive(m)
    over = jnp.where(alive, jnp.maximum(agg.replica_count - cfg.max_replicas_per_broker, 0.0), 0.0)
    n = jnp.sum(over > 0).astype(jnp.float32)
    return result(n, jnp.sum(over) / cfg.max_replicas_per_broker)


@register_goal("MinTopicLeadersPerBrokerGoal", hard=True)
def min_topic_leaders(m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig) -> GoalResult:
    """Each alive broker hosts >= k leaders of each flagged topic (ref:
    MinTopicLeadersPerBrokerGoal over `topics.with.min.leaders.per.broker`).
    Row math shared with incremental search via ccx.goals.topic_terms."""
    n = jnp.sum(tt.mtl_row(m, cfg, m.topic_min_leaders, agg.topic_leader_count))
    return result(n, n)


# --------------------------------------------------------------------------
# Distribution (soft) goals
# --------------------------------------------------------------------------
def _band_penalty(values, alive, avg, threshold):
    """Hinge penalty outside [avg*(2-t), avg*t], normalized by avg."""
    upper = avg * threshold
    lower = avg * (2.0 - threshold)
    over = jnp.maximum(values - upper, 0.0)
    under = jnp.maximum(lower - values, 0.0)
    pen = jnp.where(alive, over + under, 0.0)
    n = jnp.sum(pen > 0).astype(jnp.float32)
    return n, jnp.sum(pen) / _safe(avg)


def _usage_distribution_goal(res: Resource):
    def fn(m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig) -> GoalResult:
        """Broker utilization % within band around cluster-avg utilization %
        (ref: ResourceDistributionGoal subclasses; low-utilization gate per
        `*.low.utilization.threshold`)."""
        alive = _alive(m)
        cap = jnp.where(alive, m.broker_capacity[res], 0.0)
        load = jnp.where(alive, agg.broker_load[res], 0.0)
        avg_util = jnp.sum(load) / _safe(jnp.sum(cap))
        util = load / _safe(m.broker_capacity[res])
        t = cfg.balance_threshold[int(res)]
        n, cost = _band_penalty(util, alive, avg_util, t)
        gate = avg_util > cfg.low_utilization_threshold[int(res)]
        return result(jnp.where(gate, n, 0.0), jnp.where(gate, cost, 0.0))

    return fn


register_goal("CpuUsageDistributionGoal", hard=False)(_usage_distribution_goal(Resource.CPU))
register_goal("NetworkInboundUsageDistributionGoal", hard=False)(_usage_distribution_goal(Resource.NW_IN))
register_goal("NetworkOutboundUsageDistributionGoal", hard=False)(_usage_distribution_goal(Resource.NW_OUT))
register_goal("DiskUsageDistributionGoal", hard=False)(_usage_distribution_goal(Resource.DISK))


@register_goal("ReplicaDistributionGoal", hard=False)
def replica_distribution(m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig) -> GoalResult:
    alive = _alive(m)
    # Replica total from the aggregates (== m.n_replicas, but stays correct
    # when the partition axis is sharded and agg has been psum'd — ccx.parallel).
    avg = jnp.sum(agg.replica_count).astype(jnp.float32) / _n_alive(m)
    n, cost = _band_penalty(agg.replica_count.astype(jnp.float32), alive, avg, cfg.replica_balance_threshold)
    return result(n, cost)


@register_goal("LeaderReplicaDistributionGoal", hard=False)
def leader_replica_distribution(m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig) -> GoalResult:
    alive = _alive(m) & ~m.broker_excl_leadership
    # Leader total == valid-partition count; derived from agg for shard-safety.
    n_parts = jnp.sum(agg.leader_count).astype(jnp.float32)
    avg = n_parts / jnp.maximum(jnp.sum(alive), 1)
    n, cost = _band_penalty(agg.leader_count.astype(jnp.float32), alive, avg, cfg.leader_balance_threshold)
    return result(n, cost)


@register_goal("TopicReplicaDistributionGoal", hard=False)
def topic_replica_distribution(m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig) -> GoalResult:
    """Per-topic replica counts within a band around each topic's alive-broker
    average (ref: TopicReplicaDistributionGoal). Row math shared with
    incremental search via ccx.goals.topic_terms."""
    pen_sums, offenders = tt.trd_row_pen(m, cfg, agg.topic_replica_count)
    totals = tt.trd_row_total(m, agg.topic_replica_count)
    return result(
        jnp.sum(offenders), jnp.sum(pen_sums) / tt.trd_normalizer(m, totals)
    )


@register_goal("LeaderBytesInDistributionGoal", hard=False)
def leader_bytes_in_distribution(m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig) -> GoalResult:
    alive = _alive(m) & ~m.broker_excl_leadership
    lbi = jnp.where(alive, agg.leader_bytes_in, 0.0)
    avg = jnp.sum(lbi) / jnp.maximum(jnp.sum(alive), 1)
    n, cost = _band_penalty(lbi, alive, avg, cfg.leader_bytes_in_balance_threshold)
    return result(n, cost)


@register_goal("PotentialNwOutGoal", hard=False)
def potential_nw_out(m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig) -> GoalResult:
    """Cap the *potential* outbound a broker would serve if it led every
    hosted replica (ref: PotentialNwOutGoal)."""
    alive = _alive(m)
    cap = m.broker_capacity[Resource.NW_OUT] * cfg.capacity_threshold[int(Resource.NW_OUT)]
    excess = jnp.where(alive, jnp.maximum(agg.potential_nw_out - cap, 0.0), 0.0)
    n = jnp.sum(excess > 0).astype(jnp.float32)
    return result(n, jnp.sum(excess / _safe(cap)))


@register_goal("PreferredLeaderElectionGoal", hard=False, placement_dependent=True)
def preferred_leader_election(m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig) -> GoalResult:
    """Leadership on the preferred (slot-0) replica when it is eligible."""
    n = jnp.sum(
        pt.preferred_leader_rows(m, m.assignment, m.leader_slot, m.partition_valid)
    )
    return result(n, n / jnp.maximum(jnp.sum(agg.leader_count).astype(jnp.float32), 1.0))


# --------------------------------------------------------------------------
# Intra-broker (JBOD) goals
# --------------------------------------------------------------------------
@register_goal("IntraBrokerDiskCapacityGoal", hard=True)
def intra_disk_capacity(m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig) -> GoalResult:
    alive = (_alive(m)[:, None]) & m.disk_alive
    cap = m.disk_capacity * cfg.intra_disk_capacity_threshold
    excess = jnp.where(alive, jnp.maximum(agg.disk_load - cap, 0.0), 0.0)
    n = jnp.sum(excess > 0).astype(jnp.float32)
    return result(n, jnp.sum(excess / _safe(cap)))


@register_goal("IntraBrokerDiskUsageDistributionGoal", hard=False)
def intra_disk_usage_distribution(m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig) -> GoalResult:
    """Disk utilizations within a broker stay within `intra_disk_balance_gap`
    of the broker's mean disk utilization (ref:
    IntraBrokerDiskUsageDistributionGoal)."""
    alive = (_alive(m)[:, None]) & m.disk_alive
    util = jnp.where(alive, agg.disk_load / _safe(m.disk_capacity), 0.0)
    n_disks = jnp.maximum(jnp.sum(alive, axis=1), 1)
    broker_avg = jnp.sum(util, axis=1) / n_disks
    dev = jnp.abs(util - broker_avg[:, None]) - cfg.intra_disk_balance_gap
    pen = jnp.where(alive, jnp.maximum(dev, 0.0), 0.0)
    n = jnp.sum(pen > 0).astype(jnp.float32)
    return result(n, jnp.sum(pen))


# --------------------------------------------------------------------------
# KafkaAssigner compatibility mode (SURVEY.md C19)
# --------------------------------------------------------------------------
@register_goal("KafkaAssignerEvenRackAwareGoal", hard=True, placement_dependent=True)
def kafka_assigner_even_rack_aware(m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig) -> GoalResult:
    """KafkaAssigner mode: rack-distinct replicas AND leaders evenly spread
    over brokers (ref: KafkaAssignerEvenRackAwareGoal)."""
    ra = rack_aware(m, agg, cfg)
    alive = _alive(m)
    avg = jnp.sum(agg.leader_count).astype(jnp.float32) / _n_alive(m)
    upper = jnp.ceil(avg)
    over = jnp.where(alive, jnp.maximum(agg.leader_count - upper, 0.0), 0.0)
    n = ra.violations + jnp.sum(over > 0).astype(jnp.float32)
    return result(n, ra.cost + jnp.sum(over) / _safe(avg))


register_goal("KafkaAssignerDiskUsageDistributionGoal", hard=False)(
    _usage_distribution_goal(Resource.DISK)
)
