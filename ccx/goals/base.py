"""Goal SPI — each reference Goal class becomes a pure penalty kernel.

Parity: the reference's ``analyzer/goals/Goal.java`` SPI (SURVEY.md C15)
exposes ``optimize(clusterModel, ...)`` + ``actionAcceptance(action, model)``
and mutates the model greedily. The TPU-native re-design inverts this: a goal
is a *pure function* ``(model, aggregates, config) -> GoalResult`` scoring a
candidate state, vmappable over thousands of candidates; search (ccx.search)
owns all mutation. Priority semantics (hard goals as feasibility, soft goals
lexicographically tiered) are applied by ccx.goals.stack.

Every goal registers under the reference class name (e.g. "RackAwareGoal")
so configs, REST parameters, and parity tests use the same vocabulary.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Protocol

import jax.numpy as jnp
from flax import struct

from ccx.common.resources import (
    DEFAULT_BALANCE_THRESHOLD,
    DEFAULT_CAPACITY_THRESHOLD,
    Resource,
)

_CAPACITY_DEFAULT = tuple(DEFAULT_CAPACITY_THRESHOLD[r] for r in Resource)
_BALANCE_DEFAULT = tuple(DEFAULT_BALANCE_THRESHOLD[r] for r in Resource)


@dataclasses.dataclass(frozen=True)
class GoalConfig:
    """Static analyzer thresholds (hashable => usable as a jit static arg).

    Defaults mirror AnalyzerConfig keys (unverified against /root/reference;
    SURVEY.md provenance banner):
      cpu/disk/network capacity thresholds   `*.capacity.threshold`
      resource balance thresholds            `*.balance.threshold` (1.1)
      replica / leader count balance         `*.count.balance.threshold` (1.1)
      topic replica balance                  `topic.replica.count.balance.threshold`
      max replicas per broker                `max.replicas.per.broker`
      min topic leaders per broker           `min.topic.leaders.per.broker`
      low-utilization gate                   `*.low.utilization.threshold` (0.0)
    """

    capacity_threshold: tuple[float, float, float, float] = _CAPACITY_DEFAULT
    balance_threshold: tuple[float, float, float, float] = _BALANCE_DEFAULT
    low_utilization_threshold: tuple[float, float, float, float] = (0.0,) * 4
    replica_balance_threshold: float = 1.1
    leader_balance_threshold: float = 1.1
    topic_replica_balance_threshold: float = 1.1
    leader_bytes_in_balance_threshold: float = 1.1
    max_replicas_per_broker: float = 10_000.0
    min_topic_leaders_per_broker: int = 1
    intra_disk_capacity_threshold: float = 0.8
    intra_disk_balance_gap: float = 0.2  # |disk util - broker avg util| allowed

    @classmethod
    def from_config(cls, config) -> "GoalConfig":
        """Bridge from the service-level CruiseControlConfig key table
        (ccx.config) to the jit-static analyzer thresholds."""
        return cls(
            capacity_threshold=(
                config["cpu.capacity.threshold"],
                config["network.inbound.capacity.threshold"],
                config["network.outbound.capacity.threshold"],
                config["disk.capacity.threshold"],
            ),
            balance_threshold=(
                config["cpu.balance.threshold"],
                config["network.inbound.balance.threshold"],
                config["network.outbound.balance.threshold"],
                config["disk.balance.threshold"],
            ),
            low_utilization_threshold=(
                config["cpu.low.utilization.threshold"],
                config["network.inbound.low.utilization.threshold"],
                config["network.outbound.low.utilization.threshold"],
                config["disk.low.utilization.threshold"],
            ),
            leader_bytes_in_balance_threshold=config[
                "leader.bytes.in.balance.threshold"
            ],
            replica_balance_threshold=config["replica.count.balance.threshold"],
            leader_balance_threshold=config["leader.replica.count.balance.threshold"],
            topic_replica_balance_threshold=config[
                "topic.replica.count.balance.threshold"
            ],
            max_replicas_per_broker=float(config["max.replicas.per.broker"]),
            min_topic_leaders_per_broker=config["min.topic.leaders.per.broker"],
        )


@struct.dataclass
class GoalResult:
    """violations: discrete count (verification / reporting); cost: smooth
    normalized penalty the annealer descends. Both 0 when satisfied."""

    violations: jnp.ndarray  # f32 scalar
    cost: jnp.ndarray       # f32 scalar


class GoalFn(Protocol):
    def __call__(self, m, agg, cfg: GoalConfig) -> GoalResult: ...


@dataclasses.dataclass(frozen=True)
class GoalSpec:
    name: str
    fn: GoalFn
    hard: bool
    #: reference class this corresponds to (for parity bookkeeping)
    ref_class: str = ""
    #: True when the kernel reads per-partition placement (m.assignment /
    #: m.leader_slot) rather than only aggregates + static broker attributes.
    #: Such goals can only be searched incrementally if ccx.search maintains
    #: their contribution sums (ccx.goals.partition_terms.PARTITION_GOALS).
    placement_dependent: bool = False


GOAL_REGISTRY: dict[str, GoalSpec] = {}


def register_goal(
    name: str, *, hard: bool, ref_class: str = "", placement_dependent: bool = False
) -> Callable[[GoalFn], GoalFn]:
    def deco(fn: GoalFn) -> GoalFn:
        GOAL_REGISTRY[name] = GoalSpec(
            name=name,
            fn=fn,
            hard=hard,
            ref_class=ref_class or name,
            placement_dependent=placement_dependent,
        )
        return fn

    return deco


def result(violations, cost) -> GoalResult:
    return GoalResult(
        violations=jnp.asarray(violations, jnp.float32),
        cost=jnp.asarray(cost, jnp.float32),
    )
