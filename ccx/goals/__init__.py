from ccx.goals.base import GoalConfig, GoalResult, GOAL_REGISTRY  # noqa: F401
from ccx.goals.stack import (  # noqa: F401
    DEFAULT_GOAL_ORDER,
    DEFAULT_HARD_GOALS,
    StackResult,
    evaluate_stack,
    scalar_cost,
)
