"""Per-partition goal contributions, row-batched.

Four goals in the stack depend on a partition's own replica row rather than
on broker aggregates: StructuralFeasibility, RackAwareGoal,
RackAwareDistributionGoal and PreferredLeaderElectionGoal (reference:
``analyzer/goals/{RackAwareGoal,RackAwareDistributionGoal,
PreferredLeaderElectionGoal}.java`` + ClusterModel invariants, SURVEY.md
C16/C17). Factoring their math into row functions lets

* the full kernels (ccx.goals.kernels) evaluate them over all P rows, and
* the annealer (ccx.search) delta-update a single partition's contribution
  in O(R) per move,

from one implementation, so incremental sums can never drift from the full
evaluation semantics.

Every function takes row-batched arrays (leading axis n, n = P for full
evaluation, n = 1 inside a search step) plus the static model for broker
attributes, and returns a float32[n] violation contribution.
"""

from __future__ import annotations

import jax.numpy as jnp

from ccx.model.tensor_model import TensorClusterModel

#: Order of the per-partition goal slots maintained incrementally by search.
PARTITION_GOALS: tuple[str, ...] = (
    "StructuralFeasibility",
    "RackAwareGoal",
    "RackAwareDistributionGoal",
    "PreferredLeaderElectionGoal",
)


def _row_valid(assign: jnp.ndarray, pvalid: jnp.ndarray) -> jnp.ndarray:
    return (assign >= 0) & pvalid[:, None]


def structural_rows(
    m: TensorClusterModel,
    assign: jnp.ndarray,       # int32[n, R]
    leader_slot: jnp.ndarray,  # int32[n]
    replica_disk: jnp.ndarray,  # int32[n, R]
    pvalid: jnp.ndarray,       # bool[n]
) -> jnp.ndarray:
    """Replicas on dead brokers/disks, leaders on leadership-excluded
    brokers, duplicate brokers within a replica set."""
    R = assign.shape[1]
    valid = _row_valid(assign, pvalid)
    safe_b = jnp.clip(assign, 0, m.B - 1)

    on_dead = valid & ~(m.broker_alive & m.broker_valid)[safe_b]
    safe_d = jnp.clip(replica_disk, 0, m.D - 1)
    on_dead_disk = valid & (replica_disk >= 0) & ~m.disk_alive[safe_b, safe_d]

    lead_b = jnp.take_along_axis(
        safe_b, jnp.clip(leader_slot, 0, R - 1)[:, None], axis=1
    )[:, 0]
    lead_excl = pvalid & m.broker_excl_leadership[lead_b]

    a = jnp.where(valid, assign, -jnp.arange(1, R + 1, dtype=jnp.int32)[None, :])
    pair = (a[:, :, None] == a[:, None, :]) & (
        jnp.arange(R)[:, None] < jnp.arange(R)[None, :]
    )
    dup = jnp.sum(pair & valid[:, :, None] & valid[:, None, :], axis=(1, 2))

    return (
        jnp.sum(on_dead, axis=1)
        + jnp.sum(on_dead_disk & ~on_dead, axis=1)
        + lead_excl
        + dup
    ).astype(jnp.float32)


def _rack_rank_rows(
    m: TensorClusterModel, assign: jnp.ndarray, pvalid: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(rank int32[n, R], valid bool[n, R]) — for each replica slot, how
    many EARLIER valid slots of the same row share its rack. Pairwise over
    the R axis ([n, R, R], R <= 8) instead of a [n, R, num_racks] one-hot:
    the one-hot's width exploded to B for rack-less clusters (per-broker
    rack fallback makes num_racks == n_brokers — gigabytes of intermediate
    at anneal batch sizes) and was wider than R even for normal clusters."""
    valid = _row_valid(assign, pvalid)
    racks = m.broker_rack[jnp.clip(assign, 0, m.B - 1)]
    same = (
        (racks[:, :, None] == racks[:, None, :])
        & valid[:, :, None]
        & valid[:, None, :]
        & (jnp.arange(m.R)[None, :, None] > jnp.arange(m.R)[None, None, :])
    )
    return jnp.sum(same.astype(jnp.int32), axis=2), valid


def rack_aware_rows(
    m: TensorClusterModel, assign: jnp.ndarray, pvalid: jnp.ndarray
) -> jnp.ndarray:
    # sum_r max(count_r - 1, 0) == number of replicas that are NOT the
    # first occupant of their rack within the row
    rank, valid = _rack_rank_rows(m, assign, pvalid)
    return jnp.sum(valid & (rank >= 1), axis=1).astype(jnp.float32)


def rack_aware_distribution_rows(
    m: TensorClusterModel, assign: jnp.ndarray, pvalid: jnp.ndarray
) -> jnp.ndarray:
    # sum_r max(count_r - cap, 0) == number of replicas whose within-rack
    # rank reaches cap
    rank, valid = _rack_rank_rows(m, assign, pvalid)
    rf = jnp.sum(valid, axis=1)
    cap = jnp.ceil(rf / jnp.maximum(m.num_racks, 1)).astype(jnp.int32)
    return jnp.sum(valid & (rank >= cap[:, None]), axis=1).astype(jnp.float32)


def preferred_leader_rows(
    m: TensorClusterModel,
    assign: jnp.ndarray,
    leader_slot: jnp.ndarray,
    pvalid: jnp.ndarray,
) -> jnp.ndarray:
    safe_b0 = jnp.clip(assign[:, 0], 0, m.B - 1)
    eligible = (
        pvalid
        & (assign[:, 0] >= 0)
        & (m.broker_alive & m.broker_valid & ~m.broker_excl_leadership)[safe_b0]
    )
    return (eligible & (leader_slot != 0)).astype(jnp.float32)


def partition_sums(
    m: TensorClusterModel,
    assign: jnp.ndarray,
    leader_slot: jnp.ndarray,
    replica_disk: jnp.ndarray,
    pvalid: jnp.ndarray,
) -> jnp.ndarray:
    """float32[len(PARTITION_GOALS)] — summed contributions in
    PARTITION_GOALS order, over the given rows."""
    return jnp.stack(
        [
            jnp.sum(structural_rows(m, assign, leader_slot, replica_disk, pvalid)),
            jnp.sum(rack_aware_rows(m, assign, pvalid)),
            jnp.sum(rack_aware_distribution_rows(m, assign, pvalid)),
            jnp.sum(preferred_leader_rows(m, assign, leader_slot, pvalid)),
        ]
    )
