"""ccx-propose — one-shot proposal computation from a snapshot file.

``python -m ccx.sidecar.cli --snapshot cluster.json`` runs the optimizer
locally (in-process); ``--address host:port`` sends it to a running sidecar
instead (SURVEY.md §7.2 step 5 CLI).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ccx-propose", description=__doc__)
    ap.add_argument("--snapshot", required=True,
                    help="cluster snapshot (.json per ccx/model/snapshot.py)")
    ap.add_argument("--address", help="sidecar host:port (default: in-process)")
    ap.add_argument("--goals", default="", help="comma-separated goal names")
    ap.add_argument("--chains", type=int, default=32)
    ap.add_argument("--steps", type=int, default=3000)
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args(argv)

    from ccx.model.snapshot import from_json

    with open(args.snapshot, encoding="utf-8") as f:
        model = from_json(f.read())
    goals = tuple(g.strip() for g in args.goals.split(",") if g.strip())

    if args.address:
        from ccx.sidecar.client import SidecarClient

        client = SidecarClient(args.address)
        out = client.propose(
            model, goals=goals, chains=args.chains, steps=args.steps,
            seed=args.seed,
            on_progress=lambda s: print(f"[progress] {s}", file=sys.stderr),
        )
    else:
        from ccx.goals.base import GoalConfig
        from ccx.goals.stack import DEFAULT_GOAL_ORDER
        from ccx.optimizer import OptimizeOptions, optimize
        from ccx.search.annealer import AnnealOptions

        names = goals or DEFAULT_GOAL_ORDER
        if "StructuralFeasibility" not in names:
            names = ("StructuralFeasibility",) + tuple(names)
        res = optimize(
            model, GoalConfig(), names,
            OptimizeOptions(anneal=AnnealOptions(
                n_chains=args.chains, n_steps=args.steps, seed=args.seed)),
        )
        out = res.to_json()
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
