"""The TPU optimizer sidecar — gRPC service.

North star (BASELINE.json:5, SURVEY.md §0): the JVM keeps LoadMonitor /
Executor / REST; the analyzer hop becomes ``goal.optimizer.backend=tpu`` →
gRPC to this sidecar: snapshot up, proposals + per-goal stats down, progress
streamed so the JVM can feed its ``OperationProgress``.

Implementation notes: the wire methods are registered with
``grpc.GenericRpcHandler`` and byte-identity serializers, so no protoc
codegen is required on the Python side; every envelope is built/parsed by
the single-source schema module ``ccx/sidecar/wire.py`` (versioned,
structured error codes — see ``optimizer.proto`` for the JVM-side contract
and ``ccx/model/snapshot.py`` for the tensor schema). Delta snapshots are
cached per session keyed by generation (SURVEY.md §7.4 snapshot-transfer
mitigation).
"""

from __future__ import annotations

import logging
import os
import threading
from concurrent import futures

from ccx import __version__
from ccx.common import faults
from ccx.common.tracing import TRACER
from ccx.sidecar import GRPC_MESSAGE_OPTIONS
from ccx.goals.base import GOAL_REGISTRY, GoalConfig
from ccx.goals.stack import DEFAULT_GOAL_ORDER
from ccx.model.snapshot import (
    arrays_to_model,
    decode_msgpack,
    delta_apply,
)
from ccx.optimizer import OptimizeOptions, optimize
from ccx.search.annealer import AnnealOptions
from ccx.search.incremental import IncrementalOptions
from ccx.search.greedy import GreedyOptions
from ccx.sidecar import SERVICE, identity as _identity, wire

log = logging.getLogger(__name__)

#: streamed-result segment size (round 15): the columnar proposals blob
#: is sliced into chunks of this many bytes, each riding one
#: ``resultSegment`` frame. 1 MB keeps every frame far under the gRPC
#: message ceiling while a B5 cold result (~5 MB of columns) still ships
#: in a handful of frames. Env-overridable for tests / tuning.
RESULT_SEGMENT_BYTES = int(
    os.environ.get("CCX_RESULT_SEGMENT_BYTES", str(1 << 20))
)


class SnapshotRegistry:
    """Device-resident snapshot registry — fleet serving's N-cluster cache.

    The host arrays of every session snapshot are kept (the round-8
    ``_snapshots`` dict, unbounded and cheap), and on top of them the
    BUILT device model (``arrays_to_model`` output: padded, device-
    committed tensors) is cached per cluster so a fleet of repeat Propose
    callers stops paying the build + host→device transfer per call.
    Device residency is byte-priced on the UNIFIED device-memory ledger
    (``ccx.common.devmem`` — one costmodel-derived HBM budget shared
    with the placement store's warm bases and the compiled-program
    working set, priority-aware eviction: an urgent job's model is never
    displaced by a dryrun admission; lowest-priority / least-recently-
    used entries go first). Eviction only drops the DEVICE copy, the
    host arrays stay, so an evicted cluster's next Propose rebuilds
    instead of failing. An explicit ``hbm_budget_bytes`` detaches the
    registry onto a PRIVATE ledger with that budget (tests, embedders
    that want snapshot-only accounting); the default shares the
    process-wide ``devmem.DEVMEM``.

    Thread-safe: one lock guards the maps; the model build itself runs
    outside it (two racing builders of the same session waste one build,
    never corrupt state), and ledger admissions/evictions run outside it
    too (the ledger calls back into ``_devmem_evicted`` which re-takes
    it)."""

    #: delta fields that can be grafted onto a resident device model
    #: without a rebuild: the pure metric tensors (padded with zeros
    #: exactly like build_model pads them). Everything else (placement,
    #: topology, capacities) changes derived model structure and takes
    #: the rebuild path.
    METRIC_FIELDS = frozenset({"leader_load", "follower_load"})

    def __init__(self, hbm_budget_bytes: int | None = None) -> None:
        import weakref

        from ccx.common import devmem as _devmem

        self._lock = threading.Lock()
        #: session -> (generation, host arrays)
        self._snapshots: dict[str, tuple[int, dict]] = {}
        #: session -> (generation, device model, device bytes, lru stamp)
        self._models: dict[str, tuple[int, object, int, int]] = {}
        self._seq = 0
        self._explicit_budget = hbm_budget_bytes
        #: the device-memory ledger pricing this registry's residents —
        #: the process-wide unified one by default, a private one when an
        #: explicit budget detaches it (class docstring)
        self._devmem = (
            _devmem.DEVMEM
            if hbm_budget_bytes is None or hbm_budget_bytes <= 0
            else _devmem.DeviceMemoryManager(
                budget_bytes=int(hbm_budget_bytes)
            )
        )
        self._ns = f"reg{id(self):x}"
        self._self_ref = weakref.ref(self)
        # teardown hook: a dropped registry (tests, embedders) must not
        # leave phantom bytes on a SHARED ledger — finalize releases
        # every entry under this instance's namespace at GC
        weakref.finalize(self, self._devmem.release_namespace, self._ns)
        self.evictions = 0
        self.hits = 0
        self.misses = 0
        #: metric-only delta Puts grafted onto the resident device model
        #: (the steady-state fast path: no arrays_to_model, no full
        #: host→device transfer — two load tensors replaced in place)
        self.delta_grafts = 0
        #: grafts that failed (device surprise / injected fault) and
        #: degraded to the rebuild path — the resident model was DROPPED
        #: first, so a failed graft can never serve a torn model
        self.graft_failures = 0
        #: device-model builds that hit allocation pressure
        #: (RESOURCE_EXHAUSTED — organic or injected), evicted every
        #: resident and retried cold instead of failing the RPC
        self.pressure_evictions = 0

    def budget_bytes(self) -> int:
        return self._devmem.budget_bytes()

    # ----- unified device-memory ledger hooks -------------------------------

    def _ledger_key(self, session: str) -> str:
        return f"{self._ns}:{session}"

    def _devmem_evicted(self, key: str, stamp: int) -> None:
        """Ledger eviction callback (runs outside the ledger lock): drop
        only the DEVICE copy — the host arrays stay, the next Propose
        rebuilds. Never an error. ``stamp`` is the INSTALL stamp the
        evicting entry was admitted for: a callback that lost a race to
        a newer install (the session was rebuilt and re-admitted before
        the callback ran) must not drop the new model — its own ledger
        entry is already gone, the re-admit's entry covers the new
        install."""
        session = key.split(":", 1)[1]
        with self._lock:
            cur = self._models.get(session)
            if cur is not None and cur[3] == stamp:
                del self._models[session]
                self.evictions += 1

    def _admit(self, session: str, nbytes: int, stamp: int,
               priority: int | None = None,
               job: str | None = None) -> None:
        """Price an installed device model on the ledger (outside
        ``self._lock`` — the ledger's packing may call back into
        ``_devmem_evicted``). ``stamp`` is the install's stamp
        (``_models[session][3]``) — the evictor guard above. ``job`` is
        the serving fleet-job label (cluster id), passed through
        verbatim: None preserves an existing entry's label (the graft
        refresh must not undo a cluster-id relabel). The post-admit
        residency check closes the install/admit race: a concurrent
        packing eviction landing between the model install and this
        admit would otherwise leave a ledger entry accounting a model
        that is no longer resident."""
        ref = self._self_ref

        def _evict(key, _ref=ref, _stamp=stamp):
            reg = _ref()
            if reg is not None:
                reg._devmem_evicted(key, _stamp)

        self._devmem.admit(
            "snapshot", self._ledger_key(session), nbytes,
            priority=priority, job=job, evictor=_evict,
        )
        with self._lock:
            cur = self._models.get(session)
            resident = cur is not None and cur[3] == stamp
        if not resident:
            self._devmem.release("snapshot", self._ledger_key(session))

    # dict-compatible surface (the server's session logic + existing tests
    # reach through these like the old plain dict)
    def get(self, session: str):
        with self._lock:
            return self._snapshots.get(session)

    def put(self, session: str, generation: int, arrays: dict,
            changed: set | None = None) -> None:
        """Store a session's snapshot. ``changed`` (the delta's array
        fields, None for a full put) enables the steady-state fast path:
        a METRIC-ONLY delta grafts the new load tensors onto the already
        resident device model instead of invalidating it — repeat warm
        Proposes then never rebuild or re-transfer the model
        (``delta_grafts`` counts these; eviction/rebuild still degrade
        gracefully when the device copy is gone)."""
        with self._lock:
            self._snapshots[session] = (int(generation), arrays)
            cached = self._models.pop(session, None)
        graftable = (
            changed is not None
            and cached is not None
            and set(changed) <= self.METRIC_FIELDS
        )
        if cached is not None and not graftable:
            # device copy invalidated outright — unprice it (the graft
            # path below keeps the ledger entry alive until it decides,
            # so a successful graft preserves the entry's priority)
            self._devmem.release("snapshot", self._ledger_key(session))
        if graftable:
            # The resident model was POPPED above, so from here on every
            # failure mode is consistent by construction: a failed graft
            # (None below) simply leaves no device copy and the next
            # Propose rebuilds from the host arrays — a torn graft can
            # never be served.
            grafted = self._graft_metrics(cached[1], arrays, changed)
            if grafted is None:
                self.graft_failures += 1
                self._devmem.release("snapshot", self._ledger_key(session))
                return
            with self._lock:
                cur = self._snapshots.get(session)
                if cur is None or cur[0] != int(generation):
                    # a newer put landed while we grafted — installing
                    # this graft would pin a STALE device model under a
                    # fresh LRU stamp; drop it (the winner's own graft or
                    # the next Propose's rebuild serves the new state)
                    stamp = None
                else:
                    self._seq += 1
                    stamp = self._seq
                    self._models[session] = (
                        int(generation), grafted, cached[2], stamp
                    )
                    self.delta_grafts += 1
            if stamp is not None:
                # refresh the ledger entry (same bytes; priority AND job
                # label preserved — a metrics graft must neither demote
                # an urgent job's resident model nor undo its cluster-id
                # relabel)
                self._admit(session, cached[2], stamp, priority=None,
                            job=None)
            else:
                self._devmem.release("snapshot", self._ledger_key(session))

    @staticmethod
    def _graft_metrics(model, arrays: dict, changed: set):
        """The new load tensors padded and replaced on the device model
        (None on any surprise — the caller falls back to a rebuild).

        Zero-copy ingest (round 15): the decoded delta arrays are
        ``np.frombuffer`` views straight into the msgpack payload —
        they transfer to the device AS-IS (one host→device copy of the
        dense bytes, no intermediate host pad buffer) and the padding to
        the model's bucketed [RES, Pp] shape happens on device. At fleet
        rates this is the difference between one memcpy per delta put
        and three."""
        try:
            # chaos seam (ccx.common.faults): an injected graft failure
            # must land in THIS except — the caller counts it and
            # degrades to a rebuild, never serves a torn model
            if faults.FAULTS.armed:
                faults.FAULTS.hit("registry.graft")
            import jax.numpy as jnp
            import numpy as np

            from ccx.common.resources import NUM_RESOURCES

            reps = {}
            Pp = model.leader_load.shape[1]
            for k in changed:
                dense = np.asarray(arrays[k], np.float32).reshape(
                    NUM_RESOURCES, -1
                )
                n = dense.shape[1]
                if n > Pp:
                    return None
                dev = jnp.asarray(dense)  # the view's one host->device copy
                if n < Pp:
                    dev = jnp.pad(dev, ((0, 0), (0, Pp - n)))
                reps[k] = dev
            return model.replace(**reps)
        except Exception:  # noqa: BLE001 — fast path only, rebuild covers
            return None

    def model(self, session: str, priority: int | None = None,
              job: str | None = None):
        """The device model for a session's CURRENT snapshot — cache hit
        when resident, else built and admitted on the unified ledger.
        ``priority`` is the serving job's fleet priority: it prices the
        entry for the priority-aware packing (an urgent job's model
        cannot be displaced by a later dryrun admission; a later dryrun
        USE demotes it back — the last user wins). ``job`` is the fleet
        job label (cluster id) the entry is re-labeled with, so the
        scheduler's ``touch_job`` hook matches even when a client's
        cluster_id differs from its session.

        Crash-consistent against the two organic failure modes: an
        allocation failure (RESOURCE_EXHAUSTED — HBM pressure) evicts
        every device resident and retries the build cold instead of
        failing the RPC, and a build that raced a concurrent put is
        served but never INSTALLED over the newer generation (the install
        is generation-checked, so a stale device model cannot shadow a
        fresh snapshot)."""
        with self._lock:
            entry = self._snapshots.get(session)
            if entry is None:
                return None
            gen = entry[0]
            cached = self._models.get(session)
            if cached is not None and cached[0] == gen:
                # NOTE: the tuple's stamp is the INSTALL stamp (the
                # ledger evictor's stale-callback guard) — a cache hit
                # must not rewrite it; recency lives on the ledger
                # (touch below), not here
                self.hits += 1
                hit = cached[1]
            else:
                arrays = entry[1]
                self.misses += 1
                hit = None
        if hit is not None:
            self._devmem.touch(
                "snapshot", self._ledger_key(session), priority=priority,
                job=job,
            )
            return hit
        try:
            m = self._build(arrays)
        except Exception as e:  # noqa: BLE001 — classified below
            if not faults.is_resource_exhausted(e):
                raise
            # HBM pressure: degrade by evicting the whole device-resident
            # set and retrying the one build that must succeed (the
            # registry's admission contract: one job can always run).
            # A second failure is a real capacity problem and raises.
            self.pressure_evictions += 1
            self.evict_device(reason="pressure")
            m = self._build(arrays)
        nbytes = model_device_bytes(m)
        with self._lock:
            cur = self._snapshots.get(session)
            if cur is not None and cur[0] == gen:
                self._seq += 1
                stamp = self._seq
                self._models[session] = (gen, m, nbytes, stamp)
            else:
                stamp = None
        if stamp is not None:
            self._admit(session, nbytes, stamp, priority=priority,
                        job=job or session)
        return m

    def _build(self, arrays):
        # chaos seam (ccx.common.faults): the host→device build/transfer
        # — ``exhaust`` rules exercise the pressure-evict-retry path
        if faults.FAULTS.armed:
            faults.FAULTS.hit("snapshot.transfer")
        return arrays_to_model(arrays)

    def evict_device(self, session: str | None = None,
                     reason: str = "explicit") -> int:
        """Drop device-resident models (the host arrays always stay, so
        the next Propose rebuilds — eviction is never an error).
        ``session=None`` drops ALL residents: the HBM-pressure
        degradation path. Returns the number evicted."""
        with self._lock:
            if session is not None:
                dropped = (
                    [session]
                    if self._models.pop(session, None) is not None
                    else []
                )
            else:
                dropped = list(self._models)
                self._models.clear()
            self.evictions += len(dropped)
        for s in dropped:
            self._devmem.release(
                "snapshot", self._ledger_key(s), reason=reason
            )
        return len(dropped)

    def stats(self) -> dict:
        with self._lock:
            device_bytes = sum(v[2] for v in self._models.values())
            return {
                "sessions": len(self._snapshots),
                "deviceResident": len(self._models),
                "deviceBytes": device_bytes,
                "budgetBytes": self.budget_bytes(),
                "unifiedLedger": self._explicit_budget is None
                or self._explicit_budget <= 0,
                "evictions": self.evictions,
                "hits": self.hits,
                "misses": self.misses,
                "deltaGrafts": self.delta_grafts,
                "graftFailures": self.graft_failures,
                "pressureEvictions": self.pressure_evictions,
            }


def model_device_bytes(m) -> int:
    """Device footprint of a built model: sum of its array leaves' nbytes
    (padded shapes — what actually sits in HBM)."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(m):
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


class OptimizerSidecar:
    """Method implementations (transport-independent, tested directly)."""

    def __init__(self, goal_config: GoalConfig | None = None,
                 snapshot_hbm_budget_bytes: int | None = None) -> None:
        self.goal_config = goal_config or GoalConfig()
        self.registry = SnapshotRegistry(snapshot_hbm_budget_bytes)
        self._lock = threading.Lock()
        #: session -> (generation, ClusterModelStats) — the INPUT-side
        #: stats block of the session's current snapshot. The registry
        #: already caches the built device model per generation; its
        #: distribution stats are just as immutable, so a repeat Propose
        #: of the same generation must not re-pay the aggregate pass +
        #: host transfer (~130 ms at B5) that prices them. One entry per
        #: session (latest generation wins).
        self._input_stats: dict[str, tuple[int, object]] = {}
        #: session -> (generation, crc32 of the last PutSnapshot payload)
        #: — distinguishes a TRUE duplicate delivery (retried put whose
        #: ack was lost: same generation, same bytes → idempotent ACK)
        #: from a desynced writer reusing the current generation with
        #: NEW content (must fail loudly, never silently drop data)
        self._put_crc: dict[str, tuple[int, int]] = {}

    # ----- PutSnapshot ------------------------------------------------------

    def put_snapshot(self, request: bytes) -> bytes:
        req = wire.unpackb(request)
        wire.check_version(req)
        session = req.get("session", "")
        generation = int(req.get("generation", 0))
        if "packed" not in req:
            raise wire.WireError(
                wire.ERR_MALFORMED, "PutSnapshot request missing 'packed'"
            )
        arrays = _decode_snapshot(req["packed"], what="packed snapshot")
        import zlib

        crc = zlib.crc32(req["packed"]) & 0xFFFFFFFF
        with self._lock:
            if req.get("is_delta"):
                base = self.registry.get(session)
                if base is None:
                    raise ValueError(f"no base snapshot for session {session!r}")
                if generation == base[0]:
                    # the registry is already AT this generation. Same
                    # payload bytes ⇒ duplicate delivery (a retried client
                    # put whose ack was lost): ACK — PutSnapshot is
                    # idempotent by (session, generation), the client
                    # retry contract (docs/sidecar-wire.md Retryability).
                    # DIFFERENT bytes ⇒ a desynced writer labeling fresh
                    # data with the current generation: fail loudly (the
                    # old wrong-base error silently dropping the data
                    # would be worse — stale loads forever, no error).
                    if self._put_crc.get(session) == (generation, crc):
                        return wire.ack_response(generation)
                    raise ValueError(
                        f"delta for session {session!r} reuses current "
                        f"generation {generation} with different content "
                        "— writer desynced; re-send a full snapshot"
                    )
                base_gen = req.get("base_generation")
                if base_gen is not None and int(base_gen) != base[0]:
                    # A delta against the wrong base would build a cluster
                    # state that never existed — reject so the client
                    # re-sends a full snapshot.
                    raise ValueError(
                        f"delta base generation {base_gen} does not match "
                        f"cached generation {base[0]} for session {session!r}"
                    )
                from ccx.model.snapshot import ARRAY_FIELDS

                changed = set(arrays) & set(ARRAY_FIELDS)
                arrays = delta_apply(base[1], arrays)
                # metric-only deltas graft onto the resident device model
                # (SnapshotRegistry.put fast path) — the steady-state
                # metrics window never pays a model rebuild
                self.registry.put(session, generation, arrays,
                                  changed=changed)
            else:
                self.registry.put(session, generation, arrays)
            self._put_crc[session] = (generation, crc)
        return wire.ack_response(generation)

    # ----- Propose ----------------------------------------------------------

    def propose(self, request: bytes, cancel=None):
        """Generator: progress dicts, then the final result dict.

        ``cancel`` (an optional ``threading.Event``) is the transport's
        disconnect signal: the gRPC edge sets it from
        ``context.add_callback`` when the client goes away, and the
        optimize worker — registered on the fleet scheduler with the
        event — unwinds with ``JobCancelled`` at its next chunk-boundary
        grant, freeing the grant and residency slot instead of computing
        to completion for a dead peer. A consumer that stops iterating
        THIS generator (in-process embedders) cancels the same way via
        the ``GeneratorExit`` handler below — the event is created HERE
        when the transport passed none, so the in-process path is never
        a silent no-op."""
        if cancel is None:
            cancel = threading.Event()
        req = wire.unpackb(request)
        wire.check_version(req)
        yield wire.progress_frame("Decoding snapshot")
        model = None
        session = None
        cur_gen = None
        # incremental re-optimization (round 14): a warm_start request
        # resolves the session's last converged placement by
        # (session, base_generation) below; CCX_INCREMENTAL=0 disarms
        # the whole subsystem (from-scratch semantics, today's programs)
        from ccx.search import incremental as incr

        warm_req = bool(req.get(wire.FIELD_WARM_START)) and incr.env_enabled()
        # fleet job identity, parsed up front: the cluster id names this
        # job on the multi-job chunk scheduler; the priority ALSO prices
        # every device-resident object this RPC touches (snapshot model,
        # warm base) on the unified device-memory ledger — an urgent
        # job's residents are protected from lower-priority packing
        cluster = str(req.get("cluster_id") or req.get("session") or "anon")
        priority = int(req.get("priority") or 0)
        if req.get("snapshot") is not None:
            arrays = _decode_snapshot(req["snapshot"], what="snapshot")
        else:
            session = req.get("session", "")
            # Read, validate, apply, and store under ONE lock acquisition so
            # concurrent deltas for a session cannot silently drop updates.
            with self._lock:
                entry = self.registry.get(session)
                if entry is None:
                    # unknown session — structured invalid-argument (the
                    # warm-start edge case rides the same contract: the
                    # RPC fails, the server stays up)
                    raise ValueError(f"no snapshot for session {session!r}")
                if req.get("delta") is not None:
                    base_gen = req.get("base_generation")
                    if base_gen is not None and int(base_gen) != entry[0]:
                        raise ValueError(
                            f"delta base generation {base_gen} does not "
                            f"match cached generation {entry[0]} for "
                            f"session {session!r}"
                        )
                    from ccx.model.snapshot import ARRAY_FIELDS

                    delta_arrays = _decode_snapshot(
                        req["delta"], what="delta"
                    )
                    changed = set(delta_arrays) & set(ARRAY_FIELDS)
                    arrays = delta_apply(entry[1], delta_arrays)
                    cur_gen = int(req.get("generation", entry[0] + 1))
                    self.registry.put(
                        session, cur_gen, arrays, changed=changed
                    )
                else:
                    arrays = entry[1]
                    cur_gen = entry[0]
            # device-resident fleet path: the registry serves the BUILT
            # (padded, device-committed) model for this cluster's current
            # generation — repeat Proposes skip arrays_to_model + the
            # host->device transfer entirely, N clusters stay live under
            # the unified HBM budget (priority-aware packing; an evicted
            # cluster rebuilds)
            model = self.registry.model(session, priority=priority,
                                        job=cluster)
        if model is None:
            model = arrays_to_model(arrays)

        goals = tuple(req.get("goals") or ()) or DEFAULT_GOAL_ORDER
        unknown = [g for g in goals if g not in GOAL_REGISTRY]
        if unknown:
            raise ValueError(f"unknown goals: {unknown}")
        if "StructuralFeasibility" not in goals:
            goals = ("StructuralFeasibility",) + tuple(goals)
        o = req.get("options") or {}
        unknown_opts = set(o) - wire.PROPOSE_OPTION_KEYS
        if unknown_opts:
            # a typo'd engine knob must fail the RPC loudly (structured
            # invalid-argument), never silently run the server default —
            # the bench._wire_options footgun, now closed server-side
            raise ValueError(
                f"unknown options keys: {sorted(unknown_opts)}; this end "
                "speaks the keys in ccx.sidecar.wire.PROPOSE_OPTION_KEYS"
            )
        repair_backend = str(o.get("repair_backend", "device"))
        if repair_backend not in ("device", "host"):
            # mirror the config layer's one_of gate: a misspelled backend
            # must fail the RPC loudly, not silently select the slow
            # per-sweep-sync host loop
            raise ValueError(
                f"repair_backend must be 'device' or 'host', "
                f"got {repair_backend!r}"
            )
        opts = OptimizeOptions(
            anneal=AnnealOptions(
                n_chains=int(o.get("chains", 32)),
                n_steps=int(o.get("steps", 3000)),
                moves_per_step=int(o.get("moves_per_step", 8)),
                seed=int(o.get("seed", 42)),
                # resident sidecar: one compiled chunk program serves any
                # requested step budget (see AnnealOptions.chunk_steps).
                # 250 matches the bench ladder's shared chunk so a client
                # omitting the field reuses the SAME compiled program
                # instead of forcing a second multi-minute B5 compile
                chunk_steps=int(o.get("chunk_steps", 250)),
                p_swap=float(o.get("p_swap", 0.15)),
                p_swap_end=float(o.get("p_swap_end", -1.0)),
                swap_coupling=float(o.get("swap_coupling", 0.5)),
                # replica-exchange ladder (ISSUE 16): K and the bf16 tier
                # are program shape — a client changing them pays one new
                # chunk compile; the interval is traced data (free retune)
                n_temps=int(o.get("n_temps", 1)),
                exchange_interval=int(o.get("exchange_interval", 1)),
                bf16_scoring=bool(o.get("bf16_scoring", False)),
            ),
            polish=GreedyOptions(
                n_candidates=int(o.get("polish_candidates", 256)),
                max_iters=int(o.get("polish_max_iters", 400)),
                patience=int(o.get("polish_patience", 8)),
                batch_moves=int(o.get("polish_batch_moves", 16)),
                # 0 since r8: count-preserving moves belong to the coupled
                # swap-polish stage (matches GreedyOptions.swap_fraction)
                swap_fraction=float(o.get("polish_swap_fraction", 0.0)),
                chunk_iters=int(o.get("polish_chunk_iters", 50)),
            ),
            check_evacuation=bool(o.get("check_evacuation", True)),
            max_repair_rounds=int(o.get("max_repair_rounds", 3)),
            require_hard_zero=bool(o.get("require_hard_zero", True)),
            run_polish=bool(o.get("run_polish", True)),
            run_leader_pass=bool(o.get("run_leader_pass", True)),
            run_cold_greedy=bool(o.get("run_cold_greedy", True)),
            repair_backend=repair_backend,
            overlap_repair=bool(o.get("overlap_repair", False)),
            topic_rebalance_rounds=int(o.get("topic_rebalance_rounds", 2)),
            topic_rebalance_max_sweeps=int(
                o.get("topic_rebalance_max_sweeps", 1024)
            ),
            topic_rebalance_move_leaders=bool(
                o.get("topic_rebalance_move_leaders", True)
            ),
            topic_rebalance_guarded=bool(
                o.get("topic_rebalance_guarded", True)
            ),
            topic_rebalance_polish_iters=(
                int(o["topic_rebalance_polish_iters"])
                if o.get("topic_rebalance_polish_iters") is not None
                else None
            ),
            leader_pass_max_iters=(
                int(o["leader_pass_max_iters"])
                if o.get("leader_pass_max_iters") is not None
                else None
            ),
            swap_polish_iters=int(o.get("swap_polish_iters", 0)),
            swap_polish_post_iters=int(o.get("swap_polish_post_iters", 0)),
            swap_polish_candidates=int(o.get("swap_polish_candidates", 128)),
            swap_polish_guarded=bool(o.get("swap_polish_guarded", True)),
            swap_polish_chunk_iters=int(
                o.get("swap_polish_chunk_iters", 50)
            ),
            incremental=IncrementalOptions(
                enabled=warm_req,
                warm_swap_iters=int(o.get("warm_swap_iters", 8)),
                warm_swap_patience=int(o.get("warm_swap_patience", 3)),
                warm_swap_candidates=int(o.get("warm_swap_candidates", 32)),
                warm_steps=int(o.get("warm_steps", 100)),
                warm_chunk_steps=int(o.get("warm_chunk_steps", 25)),
                warm_chains=int(o.get("warm_chains", 2)),
                warm_moves_per_step=int(o.get("warm_moves", 8)),
                plateau_window=int(o.get("plateau_window", 1)),
                warm_t0=float(o.get("warm_t0", 1e-8)),
                warm_leader_iters=int(o.get("warm_leader_iters", 0)),
            ),
            # movement planning (round 20; plan-off default keeps the
            # pre-round-20 result byte-stable)
            plan_enabled=bool(o.get("plan_enabled", False)),
            plan_cost_tier=bool(o.get("plan_cost_tier", False)),
            plan_max_waves=int(o.get("plan_max_waves", 64)),
            plan_broker_cap=int(o.get("plan_broker_cap", 5)),
            plan_wave_bytes_mb=float(o.get("plan_wave_bytes_mb", 0.0)),
            plan_throttle_mb_per_sec=float(o.get("plan_throttle_mbps", 0.0)),
        )
        # resolve the warm base: (session, base_generation) in the
        # process-wide placement store. Graceful degradation is the
        # contract — a missing/mismatched base (e.g. the store aged the
        # session out, or the device copy of the snapshot was LRU-evicted
        # and rebuilt under a different generation) COLD-STARTS with the
        # reason on the result, never a failure.
        warm = None
        cold_reason = None
        if warm_req:
            if session is None:
                cold_reason = "warm_start requires a session"
            else:
                want_gen = req.get("base_generation")
                warm = incr.STORE.get(session, want_gen,
                                      priority=priority, job=cluster)
                if warm is None:
                    have = incr.STORE.generation(session)
                    cold_reason = (
                        f"no warm placement for session {session!r} at "
                        f"base_generation {want_gen} (store has "
                        f"{have if have is not None else 'none'})"
                    )
        yield wire.progress_frame(
            f"Optimizing {model.P}x{model.B} over {len(goals)} goals"
        )
        # per-phase progress: optimize() runs in a worker thread so its
        # synchronous progress_cb can stream through this generator — the
        # phase breadcrumbs are the wedge diagnosis for wire-routed runs
        # (a >17-min TPU polish compile must name its phase in the
        # client's partial dump, same as the in-process path)
        import queue as _queue
        import threading as _threading
        import time as _time

        q: _queue.Queue = _queue.Queue()
        box: dict = {}
        # fleet job identity (parsed up front, above): the cluster id
        # names this job on the multi-job chunk scheduler (and on every
        # span/heartbeat/histogram it emits); priority orders it in the
        # run queue — an urgent fix-offline-replicas Propose preempts a
        # queued dryrun at the next chunk boundary. Absent fields degrade
        # to the session id (pre-fleet peers) and priority 0.

        def _run():
            try:
                box["res"] = optimize(
                    model, self.goal_config, goals, opts,
                    progress_cb=lambda p: q.put(("phase", p)),
                    job=(cluster, priority),
                    warm_start=warm,
                    cancel=cancel,
                )
            except BaseException as e:  # re-raised below, at the RPC edge
                box["err"] = e
            finally:
                q.put(None)

        worker = _threading.Thread(target=_run, daemon=True)
        worker.start()
        # chunk-heartbeat relay: tap the tracer's record stream for THIS
        # worker's chunk events and forward them as structured progress
        # frames (wire.heartbeat_frame), throttled to one per second so a
        # 500-chunk anneal does not flood the stream — the JVM's
        # OperationProgress sees live per-phase chunk progress instead of
        # silence between phase boundaries
        last_beat = [0.0]

        def _tap(rec):
            if rec.get("ev") != "chunk" or rec.get("tid") != worker.ident:
                return
            now = _time.monotonic()
            if now - last_beat[0] >= 1.0:
                last_beat[0] = now
                q.put(("beat", rec))

        TRACER.add_listener(_tap)
        try:
            while True:
                item = q.get()
                if item is None:
                    break
                kind, payload = item
                if kind == "phase":
                    yield wire.progress_frame(payload)
                else:
                    yield wire.heartbeat_frame(
                        f"{payload.get('span', '?')} chunk "
                        f"{payload['chunk']}",
                        span=payload.get("span"),
                        chunk=payload["chunk"],
                        total=payload.get("total"),
                        # per-job progress frames: the interleaved fleet
                        # stream stays attributable per cluster
                        job=payload.get("job", cluster),
                        # convergence-tap energy (round 13, additive):
                        # live quality on the progress stream
                        energy=payload.get("energy"),
                    )
        except GeneratorExit:
            # the consumer stopped iterating (gRPC closed the response
            # stream / an in-process embedder bailed): cancel the worker
            # so it exits at its next chunk boundary instead of computing
            # to completion with its scheduler registration live
            if cancel is not None:
                cancel.set()
                from ccx.search.scheduler import FLEET

                FLEET.kick()
            raise
        finally:
            TRACER.remove_listener(_tap)
        worker.join()
        if "err" in box:
            raise box["err"]
        res = box["res"]
        yield wire.progress_frame("Diff + verification done")
        # bank this run's converged placement as the session's NEXT warm
        # base (device arrays by reference + the band-pressure delta
        # cache) — the steady-state loop: cold Propose banks, every later
        # warm_start Propose resolves. Gated on the env kill-switch so
        # CCX_INCREMENTAL=0 keeps today's exact behavior (and programs).
        bank_s = 0.0
        if (
            session is not None
            and cur_gen is not None
            and incr.env_enabled()
            and res.verification.ok
        ):
            t_bank = _time.monotonic()
            try:
                # a warm result carries its pressure bank precomputed (the
                # fused warm_finish program) — the bank costs nothing
                # extra; the job's priority prices the base on the
                # unified device-memory ledger
                incr.remember(session, cur_gen, res.model, self.goal_config,
                              pressure=res.warm_pressure, priority=priority,
                              job=cluster)
                # the bank's pressure-scan program is a NEW shape on a
                # session's first cold propose, dispatched AFTER optimize()'s
                # cost-capture phase already flushed — capture it HERE, still
                # inside this (cold) RPC, so the NEXT propose's cost-capture
                # phase has nothing left to compile (the ladder's warm run
                # must pay zero fresh compiles; test_bench_contract pins it)
                from ccx.common import costmodel as _cm

                if _cm.capture_enabled() and _cm.pending_count():
                    _cm.capture_pending()
            except Exception:  # noqa: BLE001 — banking is bookkeeping for
                # the NEXT window, never this response's correctness: the
                # bank-last store (incremental.remember) kept the previous
                # base intact and generation-consistent, so the next warm
                # Propose resolves the old base or cold-starts gracefully.
                # The RPC itself succeeds with the verified result.
                log.warning(
                    "warm-base banking failed for session %r gen %s — "
                    "the next warm Propose will cold-start", session,
                    cur_gen, exc_info=True,
                )
            # priced separately (wireSeconds.bank): session bookkeeping
            # for the NEXT warm window, not part of the proposals-down
            # leg this response's consumer is waiting on
            bank_s = _time.monotonic() - t_bank
        columnar = bool(req.get("columnar_proposals"))
        stream = columnar and bool(req.get(wire.FIELD_STREAM_RESULT))
        # warm-started results omit the ClusterModelStats blocks: two
        # full aggregate passes + bulk host transfers (~260 ms at B5)
        # have no place in a <500 ms steady-state window — the
        # minimal-diff contract (round 14, docs/sidecar-wire.md)
        warm_applied = bool(
            res.incremental is not None and res.incremental.get("warmStart")
        )
        if session is not None and cur_gen is not None and not warm_applied:
            # input-side stats memo: the session's snapshot at this
            # generation is immutable, so its ClusterModelStats block is
            # too — seed the result's lazy cache from the memo (repeat
            # proposes skip the aggregate pass), bank the computed block
            # after serialization otherwise
            with self._lock:
                memo = self._input_stats.get(session)
            if memo is not None and memo[0] == cur_gen:
                res._stats_before = memo[1]
        t_asm = _time.monotonic()
        result = res.to_json(
            include_proposals=not columnar, include_stats=not warm_applied,
            # streamed results ship the goal summary as flat typed arrays
            # below — never build the per-goal dicts just to discard them
            # (and never bill them to the wireSeconds.assembly leg)
            include_goal_summary=not stream,
        )
        asm_s = _time.monotonic() - t_asm
        if (
            session is not None and cur_gen is not None and not warm_applied
            and res.stats_before is not None
        ):
            with self._lock:
                self._input_stats[session] = (cur_gen, res.stats_before)
        if warm_req and cold_reason is not None and "incremental" not in result:
            # requested warm but cold-started: say so (and why) on the
            # result, in the same block a warm run reports through
            result["incremental"] = {
                "warmStart": False, "coldStart": True, "reason": cold_reason,
            }
        if not columnar:
            yield wire.result_frame(result)
            return
        # columnar result path (round 15): the optimizer's device-diff
        # columns ARE the result — no second diff pass here (the round-14
        # server paid ccx.proposals.diff inside optimize() AND
        # diff_columnar here; one columnar source now serves both views)
        from ccx.model.snapshot import pack_arrays

        result["numProposals"] = res.diff.n
        t_pack = _time.monotonic()
        blob = pack_arrays(res.diff.cols)
        pack_s = _time.monotonic() - t_pack
        # integrity (round 16, additive, BOTH columnar forms): byte flips
        # inside a bin payload decode cleanly and preserve length — only
        # a checksum catches them. crc32 runs at GB/s, sub-ms even for a
        # cold B5 blob; clients verify when the key is present (older
        # servers omit it, older clients ignore it).
        import zlib

        result["proposalsColumnarCrc32"] = zlib.crc32(blob) & 0xFFFFFFFF
        if res.plan is not None and res.plan.n_waves > 0:
            # movement plan (round 20, additive): the wave schedule rides
            # the terminal frame as one canonical blob — per-row arrays
            # are diff-sized (same N as the proposals blob) but only 4
            # columns, so it stays small enough to skip segmentation
            plan_blob = pack_arrays(res.plan.wire_cols())
            result[wire.FIELD_PLAN_COLUMNAR] = plan_blob
            result[wire.FIELD_PLAN_COLUMNAR_CRC32] = (
                zlib.crc32(plan_blob) & 0xFFFFFFFF
            )
        # wire-path self-pricing (bench.py --wire reads these): host
        # result assembly vs columnar blob packing, in seconds. Additive
        # and columnar-only — row-mode results (and the golden fixtures)
        # are untouched.
        result["wireSeconds"] = {
            "assembly": round(asm_s, 6), "pack": round(pack_s, 6),
            "bank": round(bank_s, 6),
        }
        if not stream:
            # legacy columnar client (pre-round-15): one monolithic blob
            result["proposalsColumnar"] = blob
            yield wire.result_frame(result)
            return
        # streamed columnar result (round 15): the blob rides the
        # progress stream as incremental segment frames; the terminal
        # frame carries only scalar blocks, with the goal summary as flat
        # typed arrays — packing it walks no per-goal (let alone per-row)
        # Python objects
        gs_blob = pack_arrays(res.goal_summary_columnar())
        result["goalSummaryColumnar"] = gs_blob
        result["goalSummaryColumnarCrc32"] = zlib.crc32(gs_blob) & 0xFFFFFFFF
        seg_bytes = max(int(RESULT_SEGMENT_BYTES), 1)
        total = max((len(blob) + seg_bytes - 1) // seg_bytes, 1)
        result["proposalsColumnarSegments"] = total
        result["proposalsColumnarBytes"] = len(blob)
        for i in range(total):
            yield wire.result_segment_frame(
                i, total, blob[i * seg_bytes: (i + 1) * seg_bytes]
            )
        yield wire.result_frame(result)

    def ping(self, request: bytes) -> bytes:
        import jax

        if request:  # empty bytes = pre-versioning client, accepted
            wire.check_version(wire.unpackb(request))
        return wire.pong_response(
            __version__, jax.default_backend(), jax.device_count()
        )


def _decode_snapshot(packed: bytes, what: str) -> dict:
    """Array-blob decode with the structured ``bad-snapshot`` error: a
    truncated tensor buffer (or any undecodable payload) must fail THIS
    request, not crash the server."""
    try:
        return decode_msgpack(packed)
    except Exception as e:  # noqa: BLE001 — anything here is a bad payload
        raise wire.WireError(
            wire.ERR_BAD_SNAPSHOT, f"undecodable {what}: {e}"
        ) from e


def make_grpc_server(sidecar: OptimizerSidecar | None = None,
                     address: str = "127.0.0.1:0",
                     max_workers: int | None = None):
    """Returns (grpc server, bound port). ``max_workers`` bounds concurrent
    RPC handlers — the fleet ceiling on in-flight Propose streams (each
    holds one handler thread while relaying frames). Default: env
    ``CCX_SIDECAR_WORKERS``, else 16 — sized so a 16-stream fleet bench
    never convoys in the transport before the chunk scheduler even sees
    the jobs (the scheduler, not the thread pool, is the policy layer)."""
    import os

    import grpc

    if max_workers is None:
        max_workers = int(os.environ.get("CCX_SIDECAR_WORKERS", "16"))

    from ccx.common import compilestats

    sidecar = sidecar or OptimizerSidecar()
    # live compile counters as gauges on the process registry — whoever
    # renders /metrics in this process sees compile activity mid-RPC
    compilestats.export_gauges()
    # ... and the cost observatory's gauges (captured program records,
    # projected device seconds — ccx.common.costmodel) next to them
    from ccx.common import costmodel

    costmodel.export_gauges()
    # ... and the unified device-memory ledger's (resident bytes per
    # class, evictions by reason/priority, budget — ccx.common.devmem):
    # one stats() pass seeds every labeled series so /metrics shows the
    # ledger from the first scrape
    from ccx.common.devmem import DEVMEM

    DEVMEM.stats()

    def unary(fn, rpc_name):
        def handler(request: bytes, context):
            try:
                # per-RPC span (kind="rpc"): Prometheus histogram per
                # method + flight-recorder records naming which RPC a
                # dead sidecar was serving
                with TRACER.span(rpc_name, kind="rpc",
                                 bytes=len(request or b"")):
                    return fn(request)
            except Exception as e:  # noqa: BLE001 — RPC boundary
                log.exception("rpc failed")
                # structured detail: "<code>: <message>" so a client can
                # branch on the code without parsing prose; the server
                # itself stays up (abort only fails this RPC)
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"{wire.code_of(e)}: {e}",
                )
        return handler

    def propose_stream(request: bytes, context):
        from ccx.search.scheduler import FLEET, JobCancelled

        # disconnect → cancel: gRPC fires the callback when the RPC
        # terminates for ANY reason (client disconnect, cancellation,
        # normal completion — where setting the event is a no-op). The
        # propose worker holds the event via its fleet-job registration
        # and unwinds at its next chunk-boundary grant, releasing the
        # grant and residency slot instead of computing to completion
        # for a dead peer.
        cancel = threading.Event()

        def _on_rpc_done():
            cancel.set()
            FLEET.kick()

        context.add_callback(_on_rpc_done)
        try:
            with TRACER.span("Propose", kind="rpc",
                             bytes=len(request or b"")):
                for update in sidecar.propose(request, cancel=cancel):
                    buf = wire.pack_frame(update)
                    if faults.FAULTS.armed:
                        # chaos seam: per-frame transport faults —
                        # ``corrupt`` ships flipped bytes (the client
                        # detects and restarts the stream), ``sever``
                        # raises and ends the stream abruptly below
                        buf = faults.FAULTS.hit("rpc.frame", buf)
                    yield buf
        except JobCancelled as e:
            # the peer is (almost certainly) gone; the frame is only ever
            # seen by a client racing its own disconnect — retry-safe
            log.info("propose cancelled: %s", e)
            yield wire.pack_frame(
                wire.error_frame(str(e), wire.ERR_CANCELLED)
            )
        except faults.InjectedFault as e:
            if e.kind == "sever":
                # injected transport death: end the stream with NO
                # terminal frame — the client's StreamTruncated path
                log.warning("injected stream sever: %s", e)
                return
            log.exception("propose failed (injected)")
            yield wire.pack_frame(
                wire.error_frame(str(e), wire.ERR_INTERNAL)
            )
        except Exception as e:  # noqa: BLE001
            log.exception("propose failed")
            yield wire.pack_frame(wire.error_frame(str(e), wire.code_of(e)))

    method_handlers = {
        "Propose": grpc.unary_stream_rpc_method_handler(
            propose_stream, request_deserializer=_identity,
            response_serializer=_identity,
        ),
        "PutSnapshot": grpc.unary_unary_rpc_method_handler(
            unary(sidecar.put_snapshot, "PutSnapshot"),
            request_deserializer=_identity,
            response_serializer=_identity,
        ),
        "Ping": grpc.unary_unary_rpc_method_handler(
            unary(sidecar.ping, "Ping"), request_deserializer=_identity,
            response_serializer=_identity,
        ),
    }
    handler = grpc.method_handlers_generic_handler(SERVICE, method_handlers)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        # a 100k-partition snapshot is tens of MB (B5 full snapshot:
        # 6.5 MB packed; SURVEY.md §5.8 sizes the hop at tens of MB) —
        # gRPC's 4 MB default rejects the north star's own payload
        options=GRPC_MESSAGE_OPTIONS,
    )
    server.add_generic_rpc_handlers((handler,))
    port = server.add_insecure_port(address)
    return server, port


def freeze_gc_steady_state() -> int:
    """Steady-state serving posture: collect once, then ``gc.freeze()``
    the surviving heap into the permanent generation. A long-lived
    sidecar accretes a large static object graph (modules, jax trace
    caches, compiled-program wrappers) that every gen-2 cycle collection
    re-traverses — measured as a ~250 ms pause roughly once per 15 warm
    windows at B5 on the banked host, the single p99 outlier of the
    steady rung. Frozen objects are still freed by refcounting; only the
    cycle collector skips them. Safe to call repeatedly (freezes are
    additive) — the standalone sidecar calls it once at startup and the
    steady bench after its prewarm window, when the resident program set
    is fully built. Returns the number of objects frozen."""
    import gc

    gc.collect()
    gc.freeze()
    return gc.get_freeze_count()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description="ccx TPU optimizer sidecar")
    ap.add_argument("--address", default="127.0.0.1:50051")
    ap.add_argument("--workers", type=int, default=None,
                    help="gRPC handler threads (default CCX_SIDECAR_WORKERS "
                         "or 16) — the transport ceiling on concurrent "
                         "Propose streams")
    ap.add_argument("--fleet-max-concurrent", type=int,
                    default=None,
                    help="device-residency cap of the multi-job chunk "
                         "scheduler (default CCX_FLEET_MAX_CONCURRENT or "
                         "unlimited)")
    ap.add_argument("--snapshot-hbm-mb", type=float, default=None,
                    help="HBM budget for the device-resident snapshot "
                         "registry (default CCX_FLEET_HBM_MB, else auto "
                         "from device capacity minus the cost "
                         "observatory's watermark — the standalone twin "
                         "of optimizer.fleet.snapshot.hbm.mb). Detaches "
                         "the registry from the unified ledger onto a "
                         "private snapshot-only budget; prefer "
                         "--devmem-budget-mb to size the unified pool.")
    ap.add_argument("--devmem-budget-mb", type=float, default=None,
                    help="budget of the UNIFIED device-memory ledger "
                         "(snapshots + warm bases + program working set, "
                         "ccx.common.devmem; default CCX_DEVMEM_BUDGET_MB "
                         "else the fleet snapshot derivation — the "
                         "standalone twin of optimizer.devmem.budget.mb)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    # same wedged-accelerator safeguard as the service entry point: a hung
    # jax.devices() must degrade the sidecar to CPU, not hang every RPC
    # (applies CCX_JAX_PLATFORM too — ccx.common.device)
    from ccx.common.device import ensure_responsive_backend

    ensure_responsive_backend()
    # the resident sidecar IS the compile path the T1 story measures: arm
    # cost/memory capture so every program it ever compiles banks its
    # XLA cost record (flushed by the optimizer's cost-capture phase on
    # the cold path only; CCX_COST_CAPTURE=0 opts out). In-process
    # embedders (tests, bench) arm it themselves when they want it.
    import os as _os

    from ccx.common import costmodel

    if _os.environ.get(costmodel.ENV_CAPTURE) != "0":
        costmodel.set_capture(True)
    # chaos arming (ccx.common.faults): CCX_FAULTS injects deterministic
    # faults at the named seams — never armed implicitly
    if faults.FAULTS.arm_from_env():
        log.warning("fault injection ARMED: %s", faults.FAULTS.stats())
    # fleet scheduler residency cap (0/unset = unlimited interleave)
    from ccx.search import scheduler as fleet

    mc = args.fleet_max_concurrent
    if mc is None:
        mc_env = _os.environ.get("CCX_FLEET_MAX_CONCURRENT")
        mc = int(mc_env) if mc_env else None
    if mc is not None:
        fleet.configure(max_concurrent=mc)
    # unified device-memory budget (flag > env > fleet/auto derivation)
    if args.devmem_budget_mb:
        from ccx.common import devmem

        devmem.configure(budget_mb=args.devmem_budget_mb)
    sidecar = OptimizerSidecar(
        snapshot_hbm_budget_bytes=(
            int(args.snapshot_hbm_mb * 1e6)
            if args.snapshot_hbm_mb
            else None
        )
    )
    server, port = make_grpc_server(sidecar, address=args.address,
                                    max_workers=args.workers)
    server.start()
    frozen = freeze_gc_steady_state()
    log.info("optimizer sidecar listening on port %s (gc steady-state: "
             "%d objects frozen)", port, frozen)
    server.wait_for_termination()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
