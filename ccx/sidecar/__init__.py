"""TPU optimizer sidecar: gRPC service + client + CLI (north star bridge).

Only wire-contract constants live here so the remote client
(``ccx.sidecar.client``) stays importable without the jax/optimizer stack.
"""

SERVICE = "ccx.sidecar.OptimizerService"


def identity(b: bytes) -> bytes:
    """Byte-identity (de)serializer — payloads are msgpack end to end."""
    return b
