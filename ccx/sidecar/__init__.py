"""TPU optimizer sidecar: gRPC service + client + CLI (north star bridge).

Only wire-contract constants live here so the remote client
(``ccx.sidecar.client``) stays importable without the jax/optimizer stack.
"""

SERVICE = "ccx.sidecar.OptimizerService"

#: channel/server options shared by both ends of the hop: a 100k-partition
#: snapshot is tens of MB packed (B5: 6.5 MB; SURVEY.md §5.8) and gRPC's
#: 4 MB default max rejects it
GRPC_MESSAGE_OPTIONS = (
    ("grpc.max_send_message_length", 256 * 1024 * 1024),
    ("grpc.max_receive_message_length", 256 * 1024 * 1024),
)


def identity(b: bytes) -> bytes:
    """Byte-identity (de)serializer — payloads are msgpack end to end."""
    return b
