"""Sidecar client — the JVM bridge's reference implementation.

Mirrors what the JVM-side ``goal.optimizer.backend=tpu`` strategy does
(SURVEY.md §0 north star): serialize the cluster snapshot, stream progress,
collect the ``OptimizerResult``. Used by tests, the ``ccx-propose`` CLI, and
as executable documentation of the wire contract in ``optimizer.proto``.
"""

from __future__ import annotations

import msgpack

from ccx.sidecar import GRPC_MESSAGE_OPTIONS, SERVICE, identity as _identity

# NOTE: ccx.model.snapshot (and with it jax) is imported lazily inside the
# methods that take a model object — a remote-only client (ping, session
# reuse) must work on machines without the TPU stack.


class SidecarClient:
    def __init__(self, address: str) -> None:
        import grpc

        self.channel = grpc.insecure_channel(
            address, options=list(GRPC_MESSAGE_OPTIONS)
        )
        self._propose = self.channel.unary_stream(
            f"/{SERVICE}/Propose",
            request_serializer=_identity, response_deserializer=_identity,
        )
        self._put = self.channel.unary_unary(
            f"/{SERVICE}/PutSnapshot",
            request_serializer=_identity, response_deserializer=_identity,
        )
        self._ping = self.channel.unary_unary(
            f"/{SERVICE}/Ping",
            request_serializer=_identity, response_deserializer=_identity,
        )

    def ping(self) -> dict:
        return msgpack.unpackb(self._ping(msgpack.packb({})), raw=False)

    def put_snapshot(self, model, session: str, generation: int,
                     is_delta: bool = False, base_generation: int | None = None,
                     packed: bytes | None = None) -> dict:
        payload = {
            "session": session,
            "generation": generation,
            "packed": packed if packed is not None else _pack_model(model),
            "is_delta": is_delta,
        }
        if base_generation is not None:
            payload["base_generation"] = base_generation
        return msgpack.unpackb(self._put(msgpack.packb(payload)), raw=False)

    def propose(self, model=None, session: str | None = None,
                goals: tuple[str, ...] = (), on_progress=None,
                columnar: bool = False, **options) -> dict:
        """``columnar=True`` requests the proposals as one raw-buffer
        arrays blob (``diff_columnar`` schema) instead of per-proposal
        maps — the fast path for B5-scale results; the returned dict then
        carries numpy arrays under ``proposalsColumnar``."""
        req: dict = {"goals": list(goals), "options": options}
        if columnar:
            req["columnar_proposals"] = True
        if model is not None:
            req["snapshot"] = _pack_model(model)
        if session is not None:
            req["session"] = session
        result: dict | None = None
        for raw in self._propose(msgpack.packb(req)):
            update = msgpack.unpackb(raw, raw=False)
            if "progress" in update and on_progress:
                on_progress(update["progress"])
            if "error" in update:
                raise RuntimeError(update["error"])
            if "result" in update:
                result = update["result"]
        if result is None:
            raise RuntimeError("stream ended without a result")
        if isinstance(result.get("proposalsColumnar"), (bytes, bytearray)):
            from ccx.model.snapshot import decode_msgpack

            result["proposalsColumnar"] = decode_msgpack(
                result["proposalsColumnar"]
            )
        return result

    def close(self) -> None:
        self.channel.close()


def _pack_model(model) -> bytes:
    from ccx.model.snapshot import to_msgpack

    return to_msgpack(model)
