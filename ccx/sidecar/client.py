"""Sidecar client — the JVM bridge's reference implementation.

Mirrors what the JVM-side ``goal.optimizer.backend=tpu`` strategy does
(SURVEY.md §0 north star; ``bridge/`` for the Java twin): serialize the
cluster snapshot, stream progress, collect the ``OptimizerResult``. Every
envelope comes from the single-source schema module ``ccx/sidecar/wire.py``,
so this client, the server and the golden conformance fixtures share one
encoding. Used by tests, the ``ccx-propose`` CLI, and as executable
documentation of the wire contract in ``optimizer.proto``.
"""

from __future__ import annotations

from ccx.sidecar import GRPC_MESSAGE_OPTIONS, SERVICE, identity as _identity, wire

# NOTE: ccx.model.snapshot (and with it jax) is imported lazily inside the
# methods that take a model object — a remote-only client (ping, session
# reuse) must work on machines without the TPU stack.


class SidecarClient:
    def __init__(self, address: str) -> None:
        import grpc

        self.channel = grpc.insecure_channel(
            address, options=list(GRPC_MESSAGE_OPTIONS)
        )
        self._propose = self.channel.unary_stream(
            f"/{SERVICE}/Propose",
            request_serializer=_identity, response_deserializer=_identity,
        )
        self._put = self.channel.unary_unary(
            f"/{SERVICE}/PutSnapshot",
            request_serializer=_identity, response_deserializer=_identity,
        )
        self._ping = self.channel.unary_unary(
            f"/{SERVICE}/Ping",
            request_serializer=_identity, response_deserializer=_identity,
        )

    def ping(self) -> dict:
        return wire.decode_response(self._ping(wire.ping_request()))

    def put_snapshot(self, model, session: str, generation: int,
                     is_delta: bool = False, base_generation: int | None = None,
                     packed: bytes | None = None,
                     cluster_id: str | None = None) -> dict:
        req = wire.put_snapshot_request(
            session=session, generation=generation,
            packed=packed if packed is not None else _pack_model(model),
            is_delta=is_delta, base_generation=base_generation,
            cluster_id=cluster_id,
        )
        return wire.decode_response(self._put(req))

    def propose(self, model=None, session: str | None = None,
                goals: tuple[str, ...] = (), on_progress=None,
                columnar: bool = False, cluster_id: str | None = None,
                priority: int | None = None, warm_start: bool = False,
                base_generation: int | None = None,
                stream_result: bool | None = None,
                timings: dict | None = None, **options) -> dict:
        """``columnar=True`` requests the proposals as one raw-buffer
        arrays blob (``diff_columnar`` schema) instead of per-proposal
        maps — the fast path for B5-scale results; the returned dict then
        carries numpy arrays under ``proposalsColumnar``. ``cluster_id``
        names the fleet job on the sidecar's multi-job chunk scheduler
        (default: the session id); ``priority`` orders it in the run queue
        (higher preempts at the next chunk boundary). ``warm_start``
        (round 14) asks the server to warm-start from the session's last
        converged placement at ``base_generation`` — incremental
        re-optimization with graceful cold-start fallback.

        ``stream_result`` (round 15; default: follows ``columnar``) asks
        the server to ship the columnar blob as incremental
        ``resultSegment`` frames — this client reassembles them and
        returns the same dict shape as the monolithic form (including the
        ``goalSummary`` list, reconstructed from the streamed flat-array
        form). ``timings`` (optional dict) receives client-side decode
        seconds and frame counts — the ``bench.py --wire`` split."""
        import time as _time

        if stream_result is None:
            stream_result = columnar
        req = wire.propose_request(
            goals=goals, options=options,
            snapshot=_pack_model(model) if model is not None else None,
            session=session, columnar=columnar,
            cluster_id=cluster_id, priority=priority,
            warm_start=warm_start, base_generation=base_generation,
            stream_result=bool(stream_result and columnar),
        )
        result: dict | None = None
        segments: list[bytes] = []
        n_frames = 0
        for raw in self._propose(req):
            update = wire.decode_frame(raw)  # raises SidecarError on error
            n_frames += 1
            if wire.FIELD_RESULT_SEGMENT in update:
                segments.append(update["data"])
                continue
            if "progress" in update and on_progress:
                on_progress(update["progress"])
            if "result" in update:
                result = update["result"]
        if result is None:
            raise wire.SidecarError("stream ended without a result")
        t0 = _time.monotonic()
        expected = result.get("proposalsColumnarSegments")
        if expected is not None:
            if len(segments) != int(expected):
                raise wire.SidecarError(
                    f"result stream truncated: {len(segments)} of "
                    f"{expected} segments received"
                )
            blob = b"".join(segments)
            want = result.get("proposalsColumnarBytes")
            if want is not None and len(blob) != int(want):
                raise wire.SidecarError(
                    f"result stream corrupt: {len(blob)} joined bytes, "
                    f"server sent {want}"
                )
            result["proposalsColumnar"] = blob
        if isinstance(result.get("proposalsColumnar"), (bytes, bytearray)):
            from ccx.model.snapshot import decode_msgpack

            result["proposalsColumnar"] = decode_msgpack(
                result["proposalsColumnar"]
            )
        if isinstance(result.get("goalSummaryColumnar"), (bytes, bytearray)):
            # streamed terminal frames carry the goal summary as flat
            # typed arrays — reconstruct the per-goal dict list so every
            # consumer sees one result shape regardless of transport
            from ccx.model.snapshot import decode_msgpack

            gs = decode_msgpack(result.pop("goalSummaryColumnar"))
            result["goalSummary"] = [
                {
                    "goal": g, "hard": bool(h),
                    "violationsBefore": float(vb),
                    "violationsAfter": float(va),
                    "costBefore": float(cb), "costAfter": float(ca),
                }
                for g, h, vb, va, cb, ca in zip(
                    gs["goal"], gs["hard"],
                    gs["violationsBefore"], gs["violationsAfter"],
                    gs["costBefore"], gs["costAfter"],
                )
            ]
        if timings is not None:
            timings["decode_s"] = _time.monotonic() - t0
            timings["frames"] = n_frames
            timings["segments"] = len(segments)
        return result

    def close(self) -> None:
        self.channel.close()


def _pack_model(model) -> bytes:
    from ccx.model.snapshot import to_msgpack

    return to_msgpack(model)
