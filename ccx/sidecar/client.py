"""Sidecar client — the JVM bridge's reference implementation.

Mirrors what the JVM-side ``goal.optimizer.backend=tpu`` strategy does
(SURVEY.md §0 north star; ``bridge/`` for the Java twin): serialize the
cluster snapshot, stream progress, collect the ``OptimizerResult``. Every
envelope comes from the single-source schema module ``ccx/sidecar/wire.py``,
so this client, the server and the golden conformance fixtures share one
encoding. Used by tests, the ``ccx-propose`` CLI, and as executable
documentation of the wire contract in ``optimizer.proto``.

Failure semantics (round 16 — docs/sidecar-wire.md "Retryability"):
every RPC takes a per-call deadline, and transient failures retry with
capped exponential backoff + deterministic jitter, classified per method:

* **Ping / PutSnapshot** are idempotent — PutSnapshot by
  ``(session, generation)``: a retried full put overwrites with identical
  content, and a retried delta whose first attempt actually landed is
  ACKed by the server as a duplicate delivery (generation match) instead
  of failing the base-generation guard. Retried on UNAVAILABLE /
  RESOURCE_EXHAUSTED / DEADLINE_EXCEEDED.
* **Propose** never resumes a stream — a died/truncated/corrupted stream
  (:class:`~ccx.sidecar.wire.StreamTruncated`, a locally-undecodable
  frame, a server ``internal``/``cancelled`` error frame, UNAVAILABLE)
  RESTARTS the whole request. That is safe because Propose mutates
  nothing the rerun depends on: the snapshot state is read-only to it,
  and warm-base banking is bank-last and idempotent per
  (session, generation). A retried ``warm_start`` Propose simply
  re-resolves its base — if the failed attempt lost the bank it degrades
  to the documented cold-start, never an error. Structured client-fault
  codes (``invalid-argument``, ``bad-snapshot``, ``malformed-request``
  from the SERVER, ``unsupported-wire-version``) never retry.

The client is a context manager (``with SidecarClient(addr) as c:``) so
bench/test paths stop leaking channels.
"""

from __future__ import annotations

import random
import time

from ccx.sidecar import GRPC_MESSAGE_OPTIONS, SERVICE, identity as _identity, wire

# NOTE: ccx.model.snapshot (and with it jax) is imported lazily inside the
# methods that take a model object — a remote-only client (ping, session
# reuse) must work on machines without the TPU stack.

#: server error-frame codes a Propose retry may recover from: the
#: optimizer died (injected or organic — ``internal``) or the server
#: cancelled a worker racing our own reconnect (``cancelled``). Request
#: faults (invalid-argument, bad-snapshot, server-side malformed-request,
#: unsupported-wire-version) are permanent by definition.
_RETRYABLE_FRAME_CODES = frozenset({wire.ERR_INTERNAL, wire.ERR_CANCELLED})


class SidecarClient:
    """gRPC client with per-RPC deadlines and transient-failure retry.

    ``deadline_s`` bounds each unary RPC attempt (Ping/PutSnapshot);
    ``propose_deadline_s`` bounds one whole Propose stream attempt. Both
    default to None (unbounded — a cold B5 solve is minutes on CPU, and
    a B5-scale full snapshot put over a slow link can legitimately run
    long; GRPC_MESSAGE_OPTIONS exists precisely for huge payloads):
    deadlines are opt-in per deployment, as the chaos bench does.
    ``retries`` is the number of RE-attempts after the first try (0
    disarms retry entirely — pre-round-16 behavior); backoff doubles
    from ``backoff_s`` up to ``backoff_max_s`` with deterministic jitter
    when ``retry_seed`` is set (the chaos bench pins it for
    reproducibility)."""

    def __init__(self, address: str, *, deadline_s: float | None = None,
                 propose_deadline_s: float | None = None,
                 retries: int = 3, backoff_s: float = 0.05,
                 backoff_max_s: float = 2.0,
                 retry_seed: int | None = None) -> None:
        import grpc

        self._grpc = grpc
        self.deadline_s = deadline_s
        self.propose_deadline_s = propose_deadline_s
        self.retries = max(int(retries), 0)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self._rng = random.Random(retry_seed)
        #: retry accounting (the chaos bench's client-side evidence)
        self.stats = {"attempts": 0, "retries": 0, "stream_restarts": 0}
        self.channel = grpc.insecure_channel(
            address, options=list(GRPC_MESSAGE_OPTIONS)
        )
        self._propose = self.channel.unary_stream(
            f"/{SERVICE}/Propose",
            request_serializer=_identity, response_deserializer=_identity,
        )
        self._put = self.channel.unary_unary(
            f"/{SERVICE}/PutSnapshot",
            request_serializer=_identity, response_deserializer=_identity,
        )
        self._ping = self.channel.unary_unary(
            f"/{SERVICE}/Ping",
            request_serializer=_identity, response_deserializer=_identity,
        )

    # ----- retry machinery --------------------------------------------------

    def _backoff(self, attempt: int) -> None:
        base = min(self.backoff_s * (2 ** attempt), self.backoff_max_s)
        time.sleep(base * (0.5 + 0.5 * self._rng.random()))

    def _transient_rpc(self, e: BaseException, unary: bool) -> bool:
        grpc = self._grpc
        if not isinstance(e, grpc.RpcError):
            return False
        code = e.code() if callable(getattr(e, "code", None)) else None
        transient = {
            grpc.StatusCode.UNAVAILABLE,
            grpc.StatusCode.RESOURCE_EXHAUSTED,
        }
        if unary:
            # unary methods are cheap and idempotent — an expired
            # per-attempt deadline is worth one more try
            transient.add(grpc.StatusCode.DEADLINE_EXCEEDED)
        return code in transient

    def _retrying_unary(self, call, request: bytes) -> bytes:
        last: BaseException | None = None
        for attempt in range(self.retries + 1):
            self.stats["attempts"] += 1
            try:
                return call(request, timeout=self.deadline_s)
            except Exception as e:  # noqa: BLE001 — classified below
                if attempt >= self.retries or not self._transient_rpc(
                    e, unary=True
                ):
                    raise
                last = e
                self.stats["retries"] += 1
                self._backoff(attempt)
        raise last  # pragma: no cover — loop always returns or raises

    # ----- RPCs -------------------------------------------------------------

    def ping(self) -> dict:
        return wire.decode_response(
            self._retrying_unary(self._ping, wire.ping_request())
        )

    def put_snapshot(self, model, session: str, generation: int,
                     is_delta: bool = False, base_generation: int | None = None,
                     packed: bytes | None = None,
                     cluster_id: str | None = None) -> dict:
        req = wire.put_snapshot_request(
            session=session, generation=generation,
            packed=packed if packed is not None else _pack_model(model),
            is_delta=is_delta, base_generation=base_generation,
            cluster_id=cluster_id,
        )
        return wire.decode_response(self._retrying_unary(self._put, req))

    def propose(self, model=None, session: str | None = None,
                goals: tuple[str, ...] = (), on_progress=None,
                columnar: bool = False, cluster_id: str | None = None,
                priority: int | None = None, warm_start: bool = False,
                base_generation: int | None = None,
                stream_result: bool | None = None,
                timings: dict | None = None, **options) -> dict:
        """``columnar=True`` requests the proposals as one raw-buffer
        arrays blob (``diff_columnar`` schema) instead of per-proposal
        maps — the fast path for B5-scale results; the returned dict then
        carries numpy arrays under ``proposalsColumnar``. ``cluster_id``
        names the fleet job on the sidecar's multi-job chunk scheduler
        (default: the session id); ``priority`` orders it in the run queue
        (higher preempts at the next chunk boundary). ``warm_start``
        (round 14) asks the server to warm-start from the session's last
        converged placement at ``base_generation`` — incremental
        re-optimization with graceful cold-start fallback.

        ``stream_result`` (round 15; default: follows ``columnar``) asks
        the server to ship the columnar blob as incremental
        ``resultSegment`` frames — this client reassembles them and
        returns the same dict shape as the monolithic form (including the
        ``goalSummary`` list, reconstructed from the streamed flat-array
        form). ``timings`` (optional dict) receives client-side decode
        seconds and frame counts — the ``bench.py --wire`` split.

        Transient failures (module docstring) RESTART the whole stream —
        segments from a dead attempt are discarded, never resumed."""
        if stream_result is None:
            stream_result = columnar
        req = wire.propose_request(
            goals=goals, options=options,
            snapshot=_pack_model(model) if model is not None else None,
            session=session, columnar=columnar,
            cluster_id=cluster_id, priority=priority,
            warm_start=warm_start, base_generation=base_generation,
            stream_result=bool(stream_result and columnar),
        )
        last: BaseException | None = None
        for attempt in range(self.retries + 1):
            self.stats["attempts"] += 1
            try:
                return self._propose_once(
                    req, session=session, cluster_id=cluster_id,
                    on_progress=on_progress, timings=timings,
                )
            except Exception as e:  # noqa: BLE001 — classified below
                if attempt >= self.retries or not self._retryable_propose(e):
                    raise
                last = e
                self.stats["retries"] += 1
                self.stats["stream_restarts"] += 1
                self._backoff(attempt)
        raise last  # pragma: no cover — loop always returns or raises

    def _retryable_propose(self, e: BaseException) -> bool:
        if self._transient_rpc(e, unary=False):
            return True
        if isinstance(e, wire.StreamTruncated):
            # the stream died or arrived short — restart it (the Propose
            # retry-safety contract; never resume mid-blob)
            return True
        if isinstance(e, wire.SidecarError):
            if isinstance(e.__cause__, wire.WireError):
                # the frame failed LOCAL decode/validation — undecodable
                # bytes OR an impossible wire-version value are equally
                # consistent with transit corruption (a flipped byte can
                # land anywhere, including the version int), so both
                # restart. A genuinely incompatible server fails each
                # quick attempt at its FIRST frame (and the cancel above
                # kills its worker), so the bounded retries cost little;
                # the SERVER-SENT unsupported-wire-version error frame
                # (no local cause) stays permanent below.
                return True
            return e.code in _RETRYABLE_FRAME_CODES
        return False

    def _propose_once(self, req: bytes, session, cluster_id,
                      on_progress, timings) -> dict:
        result: dict | None = None
        segments: list[bytes] = []
        n_frames = 0
        call = self._propose(req, timeout=self.propose_deadline_s)
        try:
            for raw in call:
                update = wire.decode_frame(raw)  # SidecarError on error
                n_frames += 1
                if wire.FIELD_RESULT_SEGMENT in update:
                    segments.append(update["data"])
                    continue
                if "progress" in update and on_progress:
                    on_progress(update["progress"])
                if "result" in update:
                    result = update["result"]
        except BaseException:
            # ABANDON the attempt's RPC before the caller retries: an
            # un-cancelled stream lives until GC, and its server-side
            # worker keeps computing (and holding its scheduler
            # grant/residency) concurrently with the retry — the exact
            # compute-for-a-dead-peer leak the disconnect cancellation
            # exists to stop. cancel() fires the server's context
            # callback, which cancels the worker at its next chunk
            # boundary.
            cancel = getattr(call, "cancel", None)
            if cancel is not None:
                cancel()
            raise
        if result is None:
            raise wire.StreamTruncated(
                "stream ended without a result",
                session=session, cluster_id=cluster_id,
                frames=n_frames, segments=len(segments),
            )
        t0 = time.monotonic()
        expected = result.get("proposalsColumnarSegments")
        if expected is not None:
            if len(segments) != int(expected):
                raise wire.StreamTruncated(
                    "result stream truncated",
                    session=session, cluster_id=cluster_id,
                    frames=n_frames, segments=len(segments),
                    segments_expected=int(expected),
                )
            blob = b"".join(segments)
            want = result.get("proposalsColumnarBytes")
            if want is not None and len(blob) != int(want):
                raise wire.StreamTruncated(
                    f"result stream corrupt: {len(blob)} joined bytes, "
                    f"server sent {want}",
                    session=session, cluster_id=cluster_id,
                    frames=n_frames, segments=len(segments),
                    segments_expected=int(expected),
                )
            result["proposalsColumnar"] = blob
        if isinstance(result.get("proposalsColumnar"), (bytes, bytearray)):
            from ccx.model.snapshot import decode_msgpack

            self._check_crc(
                result["proposalsColumnar"],
                result.get("proposalsColumnarCrc32"),
                "proposals blob", session, cluster_id, n_frames,
                len(segments),
            )
            try:
                result["proposalsColumnar"] = decode_msgpack(
                    result["proposalsColumnar"]
                )
            except Exception as e:  # noqa: BLE001 — corrupt in transit:
                # the server packed a valid blob (it priced it), so an
                # undecodable one was damaged on the wire — retryable
                raise wire.StreamTruncated(
                    f"result blob undecodable: {e}",
                    session=session, cluster_id=cluster_id,
                    frames=n_frames, segments=len(segments),
                ) from e
        if isinstance(result.get("planColumnar"), (bytes, bytearray)):
            # movement plan (round 20, additive): decode the wave-schedule
            # blob in place — consumers read result["planColumnar"] as a
            # dict of flat arrays next to the scalar result["plan"] block
            self._check_crc(
                result["planColumnar"],
                result.get("planColumnarCrc32"),
                "movement plan blob", session, cluster_id, n_frames,
                len(segments),
            )
            from ccx.model.snapshot import decode_msgpack

            try:
                result["planColumnar"] = decode_msgpack(
                    result["planColumnar"]
                )
            except Exception as e:  # noqa: BLE001 — damaged in transit
                raise wire.StreamTruncated(
                    f"movement plan blob undecodable: {e}",
                    session=session, cluster_id=cluster_id,
                    frames=n_frames, segments=len(segments),
                ) from e
        if isinstance(result.get("goalSummaryColumnar"), (bytes, bytearray)):
            self._check_crc(
                result["goalSummaryColumnar"],
                result.get("goalSummaryColumnarCrc32"),
                "goal summary blob", session, cluster_id, n_frames,
                len(segments),
            )
            # streamed terminal frames carry the goal summary as flat
            # typed arrays — reconstruct the per-goal dict list so every
            # consumer sees one result shape regardless of transport
            from ccx.model.snapshot import decode_msgpack

            gs = decode_msgpack(result.pop("goalSummaryColumnar"))
            result["goalSummary"] = [
                {
                    "goal": g, "hard": bool(h),
                    "violationsBefore": float(vb),
                    "violationsAfter": float(va),
                    "costBefore": float(cb), "costAfter": float(ca),
                }
                for g, h, vb, va, cb, ca in zip(
                    gs["goal"], gs["hard"],
                    gs["violationsBefore"], gs["violationsAfter"],
                    gs["costBefore"], gs["costAfter"],
                )
            ]
        if timings is not None:
            timings["decode_s"] = time.monotonic() - t0
            timings["frames"] = n_frames
            timings["segments"] = len(segments)
        return result

    @staticmethod
    def _check_crc(blob, want, what: str, session, cluster_id,
                   n_frames: int, n_segments: int) -> None:
        """Round-16 integrity check: byte flips inside a bin payload
        decode cleanly and preserve length — the server's crc32 is the
        only detector. Absent key (older server) ⇒ no check."""
        if want is None:
            return
        import zlib

        if (zlib.crc32(blob) & 0xFFFFFFFF) != int(want):
            raise wire.StreamTruncated(
                f"result stream corrupt: {what} checksum mismatch",
                session=session, cluster_id=cluster_id,
                frames=n_frames, segments=n_segments,
            )

    # ----- lifecycle --------------------------------------------------------

    def close(self) -> None:
        self.channel.close()

    def __enter__(self) -> "SidecarClient":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


def _pack_model(model) -> bytes:
    from ccx.model.snapshot import to_msgpack

    return to_msgpack(model)
