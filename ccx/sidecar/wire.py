"""Single-source wire schema for the sidecar hop (docs/sidecar-wire.md).

Every msgpack envelope that crosses the JVM ↔ TPU-sidecar boundary is built
and parsed HERE — ``ccx/sidecar/server.py``, ``ccx/sidecar/client.py`` and
the golden-fixture generator (``tools/gen_wire_fixtures.py``) all consume
this module, so the Python ends and the checked-in conformance bytes cannot
drift apart. The JVM side (``bridge/src/main/java/ccx/bridge/Wire.java``)
mirrors the constants below; ``tests/test_bridge_conformance.py`` cross-
checks both against the fixtures without a JVM.

Canonical encoding: map keys sorted lexicographically, msgpack minimal-width
integers, ``use_bin_type`` bins for raw buffers. The sidecar ACCEPTS any key
order; producers that want byte-exact conformance with the golden fixtures
must emit canonically (``packb`` here, ``MsgPack.Writer`` on the JVM).

Versioning: every request, response and stream frame carries an integer
``wire`` field. A missing field is accepted (pre-versioning peers); a value
outside ``SUPPORTED_WIRE_VERSIONS`` is a structured error
(``unsupported-wire-version``), never a crash — unary methods surface it as
gRPC INVALID_ARGUMENT, ``Propose`` as a terminal ``{"error", "code"}`` frame.

Dependency-light on purpose: msgpack only — no jax/numpy/grpc — so a remote
client (and the fixture cross-check) can import it anywhere.
"""

from __future__ import annotations

from typing import Any, Iterable

import msgpack

#: bump when an envelope field changes meaning; additions are compatible.
WIRE_VERSION = 1
SUPPORTED_WIRE_VERSIONS = (1,)
#: envelope field carrying the version (requests, responses, frames alike)
FIELD_WIRE = "wire"
#: fleet-serving envelope fields (round 12, additive — absent fields keep
#: pre-fleet semantics: session id doubles as cluster id, priority 0)
FIELD_CLUSTER_ID = "cluster_id"
FIELD_PRIORITY = "priority"
#: heartbeat-frame field naming the job a chunk belongs to
FIELD_JOB = "job"
#: incremental re-optimization (round 14, additive): a Propose with
#: ``warm_start`` true asks the sidecar to warm-start from the session's
#: last converged placement, resolved by (session, base_generation) —
#: ``base_generation`` doubles as the delta base when a delta rides the
#: same request (they are the same generation by construction: the
#: placement being warmed from was computed on that base). Absent ⇒
#: from-scratch, pre-round-14 semantics; an unresolvable warm base
#: cold-starts gracefully (the result's ``incremental`` block names the
#: reason), never fails the RPC.
FIELD_WARM_START = "warm_start"
#: streamed columnar results (round 15, additive): a Propose carrying
#: ``stream_result`` true (meaningful only with ``columnar_proposals``)
#: asks the sidecar to ship the columnar proposals blob as incremental
#: ``resultSegment`` frames riding the progress stream, with the terminal
#: ``result`` frame carrying only the scalar blocks (goal summary as flat
#: typed arrays, counters, verification) — frame packing never holds the
#: whole blob in one envelope. Absent ⇒ the monolithic result frame,
#: pre-round-15 semantics (the legacy-client compatibility pin).
FIELD_STREAM_RESULT = "stream_result"
#: segment-frame field: the 0-based sequence number of this segment
#: (``of`` carries the total, ``data`` the raw blob bytes)
FIELD_RESULT_SEGMENT = "resultSegment"

# ----- structured error codes ----------------------------------------------

#: request carried a wire version this end does not speak
ERR_UNSUPPORTED_VERSION = "unsupported-wire-version"
#: request body is not decodable msgpack / not a map / missing required keys
ERR_MALFORMED = "malformed-request"
#: the packed snapshot/delta payload is undecodable (e.g. truncated buffer)
ERR_BAD_SNAPSHOT = "bad-snapshot"
#: semantically invalid request (unknown goal, missing session, bad base gen)
ERR_INVALID = "invalid-argument"
#: the optimizer itself failed — not the caller's fault
ERR_INTERNAL = "internal"
#: the server cancelled the work (round 16, additive): the client
#: disconnected mid-Propose and the worker was cancelled at the next
#: chunk boundary. Only ever seen by a peer that raced its own
#: disconnect; retry-safe (the cancelled run banked nothing).
ERR_CANCELLED = "cancelled"


#: Every ``Propose`` ``options`` key the sidecar understands — the single
#: source the server validates requests against (``ccx/sidecar/server.py``)
#: and the bench serializer (``bench._wire_options``) must stay a subset
#: of. An unknown key is a structured ``invalid-argument`` error, never a
#: silent fallback to the server default: a typo'd engine knob (or a field
#: added to build_opts but not serialized) must fail the RPC loudly
#: instead of quietly benchmarking the wrong configuration. Additions are
#: wire-compatible (older clients simply never send them); an older
#: server REJECTS keys it cannot honor rather than misreporting results.
PROPOSE_OPTION_KEYS = frozenset({
    # SA engine
    "chains", "steps", "moves_per_step", "seed", "chunk_steps",
    "p_swap", "p_swap_end", "swap_coupling",
    "n_temps", "exchange_interval", "bf16_scoring",
    # greedy polish / leadership pass (chunked descent engine)
    "polish_candidates", "polish_max_iters", "polish_patience",
    "polish_batch_moves", "polish_swap_fraction", "polish_chunk_iters",
    # pipeline stages
    "check_evacuation", "max_repair_rounds", "require_hard_zero",
    "run_polish", "run_leader_pass", "run_cold_greedy",
    "repair_backend", "overlap_repair",
    "topic_rebalance_rounds", "topic_rebalance_max_sweeps",
    "topic_rebalance_move_leaders", "topic_rebalance_guarded",
    "topic_rebalance_polish_iters", "leader_pass_max_iters",
    # usage-coupled swap polish
    "swap_polish_iters", "swap_polish_post_iters",
    "swap_polish_candidates", "swap_polish_guarded",
    "swap_polish_chunk_iters",
    # incremental re-optimization warm-path knobs (round 14; honored on
    # warm-start Proposes, inert otherwise)
    "warm_swap_iters", "warm_swap_patience", "warm_swap_candidates",
    "warm_steps", "warm_chunk_steps", "warm_chains", "warm_moves",
    "plateau_window", "warm_t0", "warm_leader_iters",
    # movement planning (round 20, additive): device-scheduled execution
    # waves on the proposal + optional movement-cost tier on the lex
    # objective. Absent ⇒ plan-off, pre-round-20 results byte-stable.
    "plan_enabled", "plan_cost_tier", "plan_max_waves",
    "plan_broker_cap", "plan_wave_bytes_mb", "plan_throttle_mbps",
})

#: movement-plan result fields (round 20, additive): when the Propose ran
#: with ``plan_enabled``, the terminal result frame carries the wave
#: schedule as one canonical msgpack blob of flat typed arrays
#: (``wave/partition/moves/moveBytes`` per diff row +
#: ``waveBytes/waveInflowPeak/waveOutflowPeak`` per wave) next to its
#: crc32, and the ``result.plan`` scalar block (projected makespan, peak
#: inflow, wave count) rides the json result. Absent ⇒ plan-off,
#: pre-round-20 decoding unchanged (legacy fixtures byte-stable).
FIELD_PLAN_COLUMNAR = "planColumnar"
FIELD_PLAN_COLUMNAR_CRC32 = "planColumnarCrc32"


class WireError(ValueError):
    """A structured wire-contract violation: ``code`` is one of the ERR_*
    constants and rides the wire next to the message (error frame ``code``
    field / INVALID_ARGUMENT detail prefix), so a JVM client can branch on
    it without parsing prose."""

    def __init__(self, code: str, message: str) -> None:
        super().__init__(message)
        self.code = code


class SidecarError(RuntimeError):
    """Client-side image of a server error frame (or abort): ``code`` is the
    structured error code when the server sent one, else None. Subclasses
    RuntimeError so pre-versioning callers' ``except RuntimeError`` and
    message-matching keep working."""

    def __init__(self, message: str, code: str | None = None) -> None:
        super().__init__(message)
        self.code = code


class StreamTruncated(SidecarError):
    """A Propose stream died without a (complete) result — the server
    crashed mid-stream, the transport severed, or segment frames went
    missing (round 16; replaces the bare "stream ended without a result").
    Carries the context an operator (and the retry loop) needs: which
    session/cluster, how many frames arrived, how many result segments of
    how many expected. RETRY-SAFE by the Propose contract
    (docs/sidecar-wire.md "Retryability"): the client restarts the whole
    stream — never resumes mid-blob — and a rerun recomputes from the
    sidecar's own consistent state."""

    def __init__(self, message: str, session: str | None = None,
                 cluster_id: str | None = None, frames: int = 0,
                 segments: int = 0,
                 segments_expected: int | None = None) -> None:
        ctx = (
            f" (session={session!r}, cluster={cluster_id!r}, "
            f"frames={frames}, segments={segments}"
            + (f"/{segments_expected}" if segments_expected is not None
               else "")
            + ")"
        )
        super().__init__(message + ctx, code=None)
        self.session = session
        self.cluster_id = cluster_id
        self.frames = frames
        self.segments = segments
        self.segments_expected = segments_expected


# ----- canonical msgpack ----------------------------------------------------

def canonicalize(obj: Any) -> Any:
    """Recursively sort map keys (tuples become lists) — the deterministic
    form the golden fixtures are generated in."""
    if isinstance(obj, dict):
        return {k: canonicalize(obj[k]) for k in sorted(obj)}
    if isinstance(obj, (list, tuple)):
        return [canonicalize(v) for v in obj]
    return obj


def packb(obj: Any) -> bytes:
    """Canonical msgpack bytes (sorted keys, bin type for bytes)."""
    return msgpack.packb(canonicalize(obj), use_bin_type=True)


def unpackb(buf: bytes) -> dict:
    """Decode an envelope; malformed bytes raise ``WireError(ERR_MALFORMED)``
    instead of leaking msgpack internals to the RPC edge."""
    try:
        obj = msgpack.unpackb(buf, raw=False)
    except Exception as e:  # noqa: BLE001 — any unpack failure is malformed
        raise WireError(ERR_MALFORMED, f"undecodable msgpack request: {e}") from e
    if not isinstance(obj, dict):
        raise WireError(
            ERR_MALFORMED, f"request must be a msgpack map, got {type(obj).__name__}"
        )
    return obj


def check_version(msg: dict, what: str = "request") -> None:
    """Graceful unknown-version gate: absent ⇒ pre-versioning peer, accepted;
    present-but-unsupported ⇒ structured ``unsupported-wire-version``."""
    v = msg.get(FIELD_WIRE)
    if v is None:
        return
    if not isinstance(v, int) or v not in SUPPORTED_WIRE_VERSIONS:
        raise WireError(
            ERR_UNSUPPORTED_VERSION,
            f"unsupported {what} wire version {v!r}; this end speaks "
            f"{list(SUPPORTED_WIRE_VERSIONS)}",
        )


def _stamped(payload: dict) -> dict:
    out = dict(payload)
    out[FIELD_WIRE] = WIRE_VERSION
    return out


# ----- requests (client side / fixture generator) ---------------------------

def ping_request() -> bytes:
    """Canonical Ping body. The server also accepts empty bytes (legacy)."""
    return packb(_stamped({}))


def put_snapshot_request(session: str, generation: int, packed: bytes,
                         is_delta: bool = False,
                         base_generation: int | None = None,
                         cluster_id: str | None = None) -> bytes:
    req: dict = {
        "session": session,
        "generation": int(generation),
        "packed": packed,
        "is_delta": bool(is_delta),
    }
    if base_generation is not None:
        req["base_generation"] = int(base_generation)
    if cluster_id is not None:
        # fleet serving (round 12, additive): names the Kafka cluster this
        # snapshot belongs to in the sidecar's device-resident registry.
        # Absent ⇒ the session id doubles as the cluster id (pre-fleet
        # peers unchanged, fixtures byte-stable).
        req["cluster_id"] = str(cluster_id)
    return packb(_stamped(req))


def propose_request(goals: Iterable[str] = (), options: dict | None = None,
                    snapshot: bytes | None = None, session: str | None = None,
                    delta: bytes | None = None,
                    base_generation: int | None = None,
                    generation: int | None = None,
                    columnar: bool = False,
                    cluster_id: str | None = None,
                    priority: int | None = None,
                    warm_start: bool = False,
                    stream_result: bool = False) -> bytes:
    req: dict = {"goals": list(goals), "options": dict(options or {})}
    if warm_start:
        # incremental re-optimization (round 14, additive): warm-start
        # from the session's last converged placement at base_generation
        # (FIELD_WARM_START docstring); absent ⇒ from-scratch
        req["warm_start"] = True
    if snapshot is not None:
        req["snapshot"] = snapshot
    if session is not None:
        req["session"] = session
    if delta is not None:
        req["delta"] = delta
    if base_generation is not None:
        req["base_generation"] = int(base_generation)
    if generation is not None:
        req["generation"] = int(generation)
    if columnar:
        req["columnar_proposals"] = True
    if stream_result:
        # streamed columnar result (round 15, additive): segment frames +
        # a scalar terminal frame; absent ⇒ one monolithic result frame
        req["stream_result"] = True
    if cluster_id is not None:
        # fleet serving (round 12, additive): the job id this Propose runs
        # under on the multi-job chunk scheduler; absent ⇒ session id
        req["cluster_id"] = str(cluster_id)
    if priority is not None:
        # integer scheduler priority (higher = more urgent — an urgent
        # fix-offline-replicas preempts a queued dryrun at the next chunk
        # boundary); absent ⇒ 0
        req["priority"] = int(priority)
    return packb(_stamped(req))


# ----- responses / stream frames (server side) ------------------------------

def ack_response(generation: int) -> bytes:
    return packb(_stamped({"generation": int(generation)}))


def pong_response(version: str, backend: str, num_devices: int) -> bytes:
    return packb(_stamped({
        "version": version, "backend": backend, "num_devices": int(num_devices),
    }))


def progress_frame(text: str) -> dict:
    return _stamped({"progress": text})


def heartbeat_frame(text: str, span: str | None = None,
                    chunk: int | None = None,
                    total: int | None = None,
                    job: str | None = None,
                    energy: float | None = None) -> dict:
    """A progress frame carrying structured span context — the wire face
    of the flight-recorder chunk heartbeats (ccx.common.tracing), so the
    JVM's OperationProgress can show live per-phase chunk progress during
    a long TPU window. Additive and wire-compatible: pre-observability
    clients read only the ``progress`` text and ignore the extra keys.
    ``job`` (round 12, additive) is the fleet cluster id the chunk belongs
    to, so an interleaved multi-job stream stays attributable per job.
    ``energy`` (round 13, additive) is the convergence taps' tier-0 lex
    energy at this chunk (possibly one chunk stale on sync-free SA
    drives) — the JVM's progress view then shows live QUALITY, not just
    depth; absent when taps are off (legacy fixtures byte-stable)."""
    f: dict = {"progress": text}
    if span is not None:
        f["span"] = span
    if chunk is not None:
        f["chunk"] = int(chunk)
    if total is not None:
        f["total"] = int(total)
    if job is not None:
        f["job"] = str(job)
    if energy is not None:
        f["energy"] = float(energy)
    return _stamped(f)


def result_frame(result: dict) -> dict:
    return _stamped({"result": result})


def result_segment_frame(seq: int, total: int, data: bytes) -> dict:
    """One incremental columnar-result segment (round 15): ``data`` is a
    raw slice of the ``proposalsColumnar`` arrays blob; the client
    concatenates segments in ``resultSegment`` order and decodes the
    joined bytes exactly like a monolithic blob. The terminal ``result``
    frame follows the last segment and carries
    ``proposalsColumnarSegments``/``proposalsColumnarBytes`` so a
    truncated stream is detectable, never silently short."""
    return _stamped({
        FIELD_RESULT_SEGMENT: int(seq), "of": int(total), "data": data,
    })


def error_frame(message: str, code: str = ERR_INVALID) -> dict:
    return _stamped({"error": message, "code": code})


def pack_frame(frame: dict) -> bytes:
    """Stream frames are NOT canonicalized: a B5 row-mode result frame
    holds ~62k proposal maps, and the recursive key-sort would deep-copy
    all of it on the hot path the <5 s T1 budget measures. Only bytes with
    golden fixtures (requests, unary responses) need canonical form —
    frame CONTENT is compared as JSON, key-order-insensitive."""
    return msgpack.packb(frame, use_bin_type=True)


def code_of(exc: BaseException) -> str:
    """Structured code for an exception escaping a method implementation."""
    if isinstance(exc, WireError):
        return exc.code
    if isinstance(exc, (ValueError, KeyError, TypeError)):
        return ERR_INVALID
    return ERR_INTERNAL


# ----- frame decode (client side) -------------------------------------------

def decode_frame(buf: bytes) -> dict:
    """Decode one Propose stream frame; raises ``SidecarError`` (with the
    server's structured code) on an error frame or a version we don't speak."""
    try:
        frame = unpackb(buf)
        check_version(frame, what="frame")
    except WireError as e:
        raise SidecarError(str(e), code=e.code) from e
    if "error" in frame:
        raise SidecarError(str(frame["error"]), code=frame.get("code"))
    return frame


def decode_response(buf: bytes) -> dict:
    """Decode a unary response, tolerating (but checking) the version."""
    try:
        resp = unpackb(buf)
        check_version(resp, what="response")
    except WireError as e:
        raise SidecarError(str(e), code=e.code) from e
    return resp
