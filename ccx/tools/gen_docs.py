"""Documentation generator — config + REST references from the source of
truth (ref M7 docs/wiki: Configurations / REST API pages).

Run ``python -m ccx.tools.gen_docs`` to regenerate ``docs/wiki/``.
"""

from __future__ import annotations

import os

from ccx.config.configs import cruise_control_config_def
from ccx.servlet.endpoints import (
    GET_ENDPOINTS,
    PARAMETERS,
    EndPoint,
)


def gen_config_reference() -> str:
    rows = cruise_control_config_def().doc_table()
    out = [
        "# Configurations",
        "",
        "Generated from `ccx/config/configs.py` (do not edit by hand; run "
        "`python -m ccx.tools.gen_docs`). Key names follow the reference's "
        "`cruisecontrol.properties` vocabulary.",
        "",
        "| Name | Type | Default | Importance | Description |",
        "|---|---|---|---|---|",
    ]
    for r in rows:
        default = r["default"]
        if isinstance(default, tuple):
            default = ",".join(str(x) for x in default) or "(empty)"
        default = "" if default is None else str(default)
        if len(default) > 60:
            default = default[:57] + "..."
        out.append(
            f"| `{r['name']}` | {r['type']} | {default or '—'} "
            f"| {r['importance']} | {r['doc']} |"
        )
    return "\n".join(out) + "\n"


def gen_rest_reference() -> str:
    out = [
        "# REST API",
        "",
        "Generated from `ccx/servlet/endpoints.py`. All endpoints live under "
        "`/kafkacruisecontrol/<endpoint>` and return JSON. Requests that "
        "exceed `webserver.request.maxBlockTimeMs` return **202** with a "
        "`User-Task-ID` header — repeat the request with that header (or "
        "poll `user_tasks`) until **200**. With "
        "`two.step.verification.enabled`, non-dryrun mutating POSTs park in "
        "the purgatory and must be approved via `review`, then re-submitted "
        "with `review_id`.",
        "",
    ]
    for ep in EndPoint:
        method = "GET" if ep in GET_ENDPOINTS else "POST"
        out.append(f"## {method} `/kafkacruisecontrol/{ep.value}`")
        out.append("")
        out.append("| Parameter | Type | Default |")
        out.append("|---|---|---|")
        for spec in PARAMETERS[ep]:
            default = spec.default
            if isinstance(default, tuple):
                default = ",".join(map(str, default)) or "(empty)"
            out.append(
                f"| `{spec.name}` | {spec.type.value} "
                f"| {default if default is not None else '—'} |"
            )
        out.append("")
    return "\n".join(out) + "\n"


def main() -> None:
    root = os.path.join(os.path.dirname(__file__), "..", "..")
    wiki = os.path.normpath(os.path.join(root, "docs", "wiki"))
    os.makedirs(wiki, exist_ok=True)
    with open(os.path.join(wiki, "Configurations.md"), "w") as f:
        f.write(gen_config_reference())
    with open(os.path.join(wiki, "REST-API.md"), "w") as f:
        f.write(gen_rest_reference())
    print(f"wrote {wiki}/Configurations.md and {wiki}/REST-API.md")


if __name__ == "__main__":
    main()
