from ccx.main import main

raise SystemExit(main())
