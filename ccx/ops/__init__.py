"""TPU kernels (Pallas) for the framework's hot ops.

Each kernel ships with a pure-XLA twin in its caller's module; dispatch
requires the TPU backend plus a per-kernel opt-in env flag until the kernel
has run on live hardware once (see each kernel's ``*_enabled``).
Correctness is pinned by interpret-mode tests that run on CPU.
"""

from ccx.ops.mxu_aggregates import broker_aggregates_mxu, mxu_aggregates_enabled

__all__ = ["broker_aggregates_mxu", "mxu_aggregates_enabled"]
