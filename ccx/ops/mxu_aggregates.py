"""Pallas TPU kernel: broker aggregates as tiled one-hot MXU matmuls.

``ccx.model.aggregates.broker_aggregates`` is the framework's hottest O(P*R)
full pass (stack evaluation, search-state init, every repair sweep). Its XLA
form is a family of ``segment_sum`` scatter-adds — correct everywhere, but
on TPU a scatter-add serializes: the MXU sits idle while rows trickle
through the permutation unit. The TPU-native formulation is a *matmul
against a one-hot segment matrix*:

    out[F, B]  = feat[F, N]    @ onehot_b[N, B]      (per-broker features)
    out[T, B]  = onehot_t[T,N] @ (onehot_b * w)      (topic x broker counts)
    out[B, D]  = onehot_b[B,N] @ (onehot_d * w)      (broker x disk loads)

all of which run on the 128x128 systolic array. This kernel tiles the
flattened (partition x slot) axis N, materializes the one-hot blocks in
VMEM on the fly (they never touch HBM), and accumulates every output across
the sequential TPU grid in one pass over the inputs.

VMEM budget at B5 scale (B=1024, T=512, TILE=256, f32): the [T, B]
accumulators are 2 MB each, onehot_b is 1 MB, onehot_t 0.5 MB — ~6 MB
total, comfortably under the ~16 MB/core budget. Larger T*B products need a
second grid axis over topic tiles; until a fixture needs it, one axis keeps
the kernel simple.

Dispatch: ``ccx.model.aggregates.broker_aggregates`` routes here only on
the TPU backend with ``CCX_MXU_AGGREGATES=1`` set before process start
(see ``mxu_aggregates_enabled`` for why it is opt-in). Interpret-mode
tests (tests/test_ops_mxu.py) pin exact agreement with the XLA twin on
CPU via the explicit ``interpret=True`` parameter.

Reference parity: the aggregates themselves mirror
``model/ClusterModelStats.java`` inputs (SURVEY.md C4); this module only
changes how the sums are scheduled onto the hardware.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-capable installs; interpret mode needs nothing
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover - CPU-only wheels
    pltpu = None
    _VMEM = None

from ccx.common.resources import NUM_RESOURCES, Resource
from ccx.model.tensor_model import TensorClusterModel

#: flattened (partition x slot) tile — the MXU contraction dim. 256 keeps
#: the VMEM one-hots small while amortizing grid overhead; must stay a
#: multiple of 8 (f32 sublane).
TILE_N = 256


#: resolved ONCE at import: broker_aggregates is jitted at import in several
#: modules, so a mid-process flag flip would be silently ignored for
#: already-traced shapes anyway — set the env before the process starts.
_OPT_IN = os.environ.get("CCX_MXU_AGGREGATES") == "1"

#: bf16 MATMUL OPERANDS (ISSUE 16, same read-once rule): the one-hot
#: factors are exactly representable in bfloat16 and every accumulator
#: keeps ``preferred_element_type=f32``, so the integer counts stay exact;
#: only the float feature sums lose mantissa (rank-order consumers — the
#: band-pressure tables — tolerate that by design, see
#: ``ccx.goals.kernels.scoring_dtype``). Doubles MXU throughput on the
#: feature matmuls; opt-in with the same not-yet-hardware-proven caution
#: as the kernel itself.
_BF16 = os.environ.get("CCX_MXU_BF16") == "1"


def mxu_aggregates_enabled() -> bool:
    """True when broker_aggregates should take the Pallas path.

    Requires BOTH the TPU backend and the ``CCX_MXU_AGGREGATES=1`` opt-in
    (read once at import). Opt-in because the kernel has not yet executed
    on real TPU hardware: the driver compile-checks the flagship entry
    point on the live chip, and routing it through a never-hardware-run
    kernel by default would put that check at risk; the backend gate keeps
    a wedge-window CPU fallback from dragging the whole B5 bench through
    the (orders-of-magnitude slower) Pallas interpreter. The kernel is
    interpret-validated on CPU via the explicit ``interpret=True`` test
    path (tests/test_ops_mxu.py). First healthy tunnel window: run
    ``CCX_MXU_AGGREGATES=1 python bench.py`` to A/B against the XLA
    segment-sum path, then flip the default to plain backend-gating.
    """
    return _OPT_IN and jax.default_backend() == "tpu"


def _kernel(seg_ref, top_ref, dsk_ref, lead_ref, dw_ref, feat_ref,
            out_feat, out_tr, out_tl, out_disk, *, B, T, D, op_dtype):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_feat[:] = jnp.zeros_like(out_feat)
        out_tr[:] = jnp.zeros_like(out_tr)
        out_tl[:] = jnp.zeros_like(out_tl)
        out_disk[:] = jnp.zeros_like(out_disk)

    seg = seg_ref[0, :]                                    # int32[TILE]
    # one-hot over brokers: invalid slots carry seg == B and never match
    # (the drop-bucket trick of the XLA twin, without the extra column).
    # ``op_dtype`` (f32 default, bf16 with CCX_MXU_BF16=1) is the matmul
    # OPERAND dtype only — 0/1 one-hots are exact either way and every
    # accumulator stays f32 via preferred_element_type.
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (TILE_N, B), 1)
    oh_b = (seg[:, None] == iota_b).astype(op_dtype)       # [TILE, B]

    # per-broker feature rows: [F, TILE] @ [TILE, B] on the MXU
    out_feat[:] += jnp.dot(
        feat_ref[:].astype(op_dtype), oh_b,
        preferred_element_type=jnp.float32,
    )

    # (topic x broker) counts: outer products accumulated as matmuls
    iota_t = jax.lax.broadcasted_iota(jnp.int32, (TILE_N, T), 1)
    oh_t = (top_ref[0, :][:, None] == iota_t).astype(op_dtype)
    out_tr[:] += jnp.dot(
        oh_t.T, oh_b, preferred_element_type=jnp.float32
    )
    lead = lead_ref[0, :].astype(op_dtype)
    out_tl[:] += jnp.dot(
        (oh_t * lead[:, None]).T, oh_b, preferred_element_type=jnp.float32
    )

    # (broker x disk) load: [B, TILE] @ [TILE, D]
    iota_d = jax.lax.broadcasted_iota(jnp.int32, (TILE_N, D), 1)
    oh_d = (dsk_ref[0, :][:, None] == iota_d).astype(op_dtype)
    out_disk[:] += jnp.dot(
        oh_b.T, oh_d * dw_ref[0, :][:, None].astype(op_dtype),
        preferred_element_type=jnp.float32,
    )


def broker_aggregates_mxu(
    m: TensorClusterModel, interpret: bool | None = None,
    bf16: bool | None = None,
):
    """BrokerAggregates via the one-hot-matmul kernel (see module docstring).

    Bit-compatible with ``ccx.model.aggregates.broker_aggregates`` for the
    integer counts; float sums agree up to reduction order (tile-major here,
    segment-major there). ``interpret`` defaults to the Pallas interpreter
    on non-TPU backends (the CPU test path; CCX_MXU_AGGREGATES=1 without a
    TPU would otherwise fail to lower) and to compiled on TPU. ``bf16``
    (default: the ``CCX_MXU_BF16`` env, read at import) feeds the matmuls
    bfloat16 OPERANDS with f32 accumulation — integer counts stay exact
    (0/1 one-hots are bf16-representable), float feature sums become
    rank-order-grade (see ``_BF16`` note).
    """
    from ccx.model.aggregates import BrokerAggregates

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if bf16 is None:
        bf16 = _BF16
    op_dtype = jnp.bfloat16 if bf16 else jnp.float32

    B, T, D = m.B, m.num_topics, m.D
    P, R = m.P, m.R
    valid = m.replica_valid                                   # [P, R]
    is_leader = m.is_leader                                   # [P, R]

    seg = jnp.where(valid, m.assignment, B).reshape(-1)       # [N]
    top = jnp.where(valid, m.partition_topic[:, None], T).reshape(-1)
    lead = is_leader.reshape(-1)
    disk_ok = valid & (m.replica_disk >= 0)
    dsk = jnp.where(disk_ok, m.replica_disk, D).reshape(-1)
    slot_load = m.replica_load                                # [RES, P, R]
    disk_w = jnp.where(disk_ok, slot_load[Resource.DISK], 0.0).reshape(-1)

    pot = jnp.where(valid, m.leader_load[Resource.NW_OUT][:, None], 0.0)
    lbi = jnp.where(is_leader, m.leader_load[Resource.NW_IN][:, None], 0.0)
    feat = jnp.concatenate(
        [
            slot_load.reshape(NUM_RESOURCES, -1),             # broker_load
            valid.astype(jnp.float32).reshape(1, -1),         # replica_count
            is_leader.astype(jnp.float32).reshape(1, -1),     # leader_count
            pot.reshape(1, -1),                               # potential_nw_out
            lbi.reshape(1, -1),                               # leader_bytes_in
        ],
        axis=0,
    )                                                         # [F, N]
    F = feat.shape[0]
    # pad F to the f32 sublane multiple; pad N to the tile multiple with
    # drop-bucket ids so padded slots match no one-hot column
    Fp = -(-F // 8) * 8
    feat = jnp.pad(feat, ((0, Fp - F), (0, 0)))
    N = P * R
    Np = -(-N // TILE_N) * TILE_N
    pad = Np - N
    seg = jnp.pad(seg, (0, pad), constant_values=B)
    top = jnp.pad(top, (0, pad), constant_values=T)
    dsk = jnp.pad(dsk, (0, pad), constant_values=D)
    lead = jnp.pad(lead, (0, pad))
    disk_w = jnp.pad(disk_w, (0, pad))
    feat = jnp.pad(feat, ((0, 0), (0, pad)))

    grid = (Np // TILE_N,)
    row = lambda: pl.BlockSpec((1, TILE_N), lambda i: (0, i))  # noqa: E731
    full = lambda shape: pl.BlockSpec(shape, lambda i: (0, 0))  # noqa: E731
    import functools

    out_feat, out_tr, out_tl, out_disk = pl.pallas_call(
        functools.partial(_kernel, B=B, T=T, D=D, op_dtype=op_dtype),
        grid=grid,
        in_specs=[
            row(),                                            # seg
            row(),                                            # top
            row(),                                            # dsk
            row(),                                            # lead
            row(),                                            # disk_w
            pl.BlockSpec((Fp, TILE_N), lambda i: (0, i)),     # feat
        ],
        out_specs=[
            full((Fp, B)),
            full((T, B)),
            full((T, B)),
            full((B, D)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Fp, B), jnp.float32),
            jax.ShapeDtypeStruct((T, B), jnp.float32),
            jax.ShapeDtypeStruct((T, B), jnp.float32),
            jax.ShapeDtypeStruct((B, D), jnp.float32),
        ],
        interpret=interpret,
    )(
        seg.reshape(1, -1), top.reshape(1, -1), dsk.reshape(1, -1),
        lead.reshape(1, -1).astype(jnp.int32), disk_w.reshape(1, -1), feat,
    )

    return BrokerAggregates(
        broker_load=out_feat[:NUM_RESOURCES],
        replica_count=out_feat[NUM_RESOURCES].astype(jnp.int32),
        leader_count=out_feat[NUM_RESOURCES + 1].astype(jnp.int32),
        potential_nw_out=out_feat[NUM_RESOURCES + 2],
        leader_bytes_in=out_feat[NUM_RESOURCES + 3],
        topic_replica_count=out_tr.astype(jnp.int32),
        topic_leader_count=out_tl.astype(jnp.int32),
        disk_load=out_disk,
    )
