"""Typed config system (ref C35: Kafka ConfigDef-style keys + SPI loading)."""

from ccx.config.configs import (
    DEFAULT_GOALS,
    DEFAULT_HARD_GOALS,
    CruiseControlConfig,
    cruise_control_config_def,
)
from ccx.config.definition import (
    NO_DEFAULT,
    ConfigDef,
    ConfigException,
    Importance,
    Type,
    load_properties,
    resolve_class,
)

__all__ = [
    "DEFAULT_GOALS",
    "DEFAULT_HARD_GOALS",
    "CruiseControlConfig",
    "cruise_control_config_def",
    "NO_DEFAULT",
    "ConfigDef",
    "ConfigException",
    "Importance",
    "Type",
    "load_properties",
    "resolve_class",
]
