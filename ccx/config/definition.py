"""ConfigDef — typed, documented, validated configuration keys.

Parity: the reference's config system (SURVEY.md C35) is built on Kafka's
``ConfigDef``: every key is declared with a type, default, validator,
importance and doc string; ``config/KafkaCruiseControlConfig.java`` merges
per-subsystem defs (``MonitorConfig``, ``AnalyzerConfig``, ``ExecutorConfig``,
``AnomalyDetectorConfig``, ``WebServerConfig``, ``UserTaskManagerConfig``)
and class-valued keys instantiate SPI plugins reflectively. This module is
the same contract in Python: a declarative key table, coercing parser, and
reflective plugin instantiation via dotted paths.
"""

from __future__ import annotations

import dataclasses
import enum
import importlib
from typing import Any, Callable, Iterable


class ConfigException(Exception):
    """Parity: org.apache.kafka.common.config.ConfigException."""


class Type(enum.Enum):
    STRING = "string"
    INT = "int"
    LONG = "long"
    DOUBLE = "double"
    BOOLEAN = "boolean"
    LIST = "list"        # comma-separated -> tuple[str, ...]
    CLASS = "class"      # dotted path -> resolved object (class or callable)
    PASSWORD = "password"


class Importance(enum.Enum):
    HIGH = "high"
    MEDIUM = "medium"
    LOW = "low"


#: sentinel for keys with no default (required keys raise if absent)
NO_DEFAULT = object()


def _coerce(name: str, typ: Type, value: Any) -> Any:
    try:
        if typ is Type.STRING or typ is Type.PASSWORD:
            return str(value)
        if typ in (Type.INT, Type.LONG):
            if isinstance(value, bool):
                raise ValueError(value)
            return int(value)
        if typ is Type.DOUBLE:
            return float(value)
        if typ is Type.BOOLEAN:
            if isinstance(value, bool):
                return value
            s = str(value).strip().lower()
            if s in ("true", "1", "yes"):
                return True
            if s in ("false", "0", "no"):
                return False
            raise ValueError(value)
        if typ is Type.LIST:
            if isinstance(value, (list, tuple)):
                return tuple(str(v) for v in value)
            return tuple(s.strip() for s in str(value).split(",") if s.strip())
        if typ is Type.CLASS:
            # Kept as the dotted string (or the object itself); resolution is
            # lazy — CruiseControlConfig.configured_instance resolves at
            # plugin-construction time so config parsing never imports SPIs.
            return value
    except (TypeError, ValueError) as e:
        raise ConfigException(
            f"Invalid value {value!r} for configuration {name}: expected {typ.value}"
        ) from e
    raise ConfigException(f"Unknown config type {typ} for {name}")


def resolve_class(path: str) -> Any:
    """Resolve ``pkg.mod.Class`` (reflective SPI loading, ref C35)."""
    module_name, _, attr = path.rpartition(".")
    if not module_name:
        raise ConfigException(f"Not a dotted class path: {path!r}")
    try:
        module = importlib.import_module(module_name)
        return getattr(module, attr)
    except (ImportError, AttributeError) as e:
        raise ConfigException(f"Cannot resolve class {path!r}: {e}") from e


# ----- validators (parity: ConfigDef.Range / ValidString / NonEmptyList) ----

def at_least(lo: float) -> Callable[[str, Any], None]:
    def check(name: str, v: Any) -> None:
        if v < lo:
            raise ConfigException(f"{name} must be >= {lo}, got {v}")
    return check


def between(lo: float, hi: float) -> Callable[[str, Any], None]:
    def check(name: str, v: Any) -> None:
        if not (lo <= v <= hi):
            raise ConfigException(f"{name} must be in [{lo}, {hi}], got {v}")
    return check


def one_of(*allowed: str) -> Callable[[str, Any], None]:
    def check(name: str, v: Any) -> None:
        if v not in allowed:
            raise ConfigException(f"{name} must be one of {allowed}, got {v!r}")
    return check


def non_empty(name: str, v: Any) -> None:
    if v is None or (hasattr(v, "__len__") and len(v) == 0):
        raise ConfigException(f"{name} must be non-empty")


@dataclasses.dataclass(frozen=True)
class ConfigKey:
    name: str
    type: Type
    default: Any
    importance: Importance
    doc: str
    validator: Callable[[str, Any], None] | None = None


class ConfigDef:
    """A declarative table of config keys with a coercing parser."""

    def __init__(self) -> None:
        self._keys: dict[str, ConfigKey] = {}

    def define(
        self,
        name: str,
        typ: Type,
        default: Any,
        importance: Importance,
        doc: str,
        validator: Callable[[str, Any], None] | None = None,
    ) -> "ConfigDef":
        if name in self._keys:
            raise ConfigException(f"Configuration {name} defined twice")
        self._keys[name] = ConfigKey(name, typ, default, importance, doc, validator)
        return self

    def merge(self, other: "ConfigDef") -> "ConfigDef":
        for k in other._keys.values():
            if k.name not in self._keys:
                self._keys[k.name] = k
        return self

    @property
    def keys(self) -> dict[str, ConfigKey]:
        return dict(self._keys)

    def parse(self, props: dict[str, Any]) -> dict[str, Any]:
        parsed: dict[str, Any] = {}
        for name, key in self._keys.items():
            if name in props:
                value = _coerce(name, key.type, props[name])
            elif key.default is NO_DEFAULT:
                raise ConfigException(
                    f"Missing required configuration {name} which has no default"
                )
            else:
                value = key.default
            if key.validator is not None and value is not None:
                key.validator(name, value)
            parsed[name] = value
        return parsed

    def unknown_keys(self, props: Iterable[str]) -> list[str]:
        return sorted(set(props) - set(self._keys))

    def doc_table(self) -> list[dict[str, Any]]:
        """Config reference rows (used by docs generation, ref M7 wiki)."""
        return [
            {
                "name": k.name,
                "type": k.type.value,
                "default": None if k.default is NO_DEFAULT else k.default,
                "importance": k.importance.value,
                "doc": k.doc,
            }
            for k in sorted(self._keys.values(), key=lambda k: k.name)
        ]


def load_properties(path: str) -> dict[str, str]:
    """Parse a java-style ``.properties`` file (ref M6
    ``config/cruisecontrol.properties``): ``key=value`` lines, ``#``/``!``
    comments, trailing-backslash continuations."""
    props: dict[str, str] = {}
    pending = ""
    with open(path, encoding="utf-8") as f:
        for raw in f:
            line = pending + raw.strip()
            pending = ""
            if not line or line.startswith(("#", "!")):
                continue
            if line.endswith("\\"):
                pending = line[:-1]
                continue
            for sep in ("=", ":"):
                if sep in line:
                    k, _, v = line.partition(sep)
                    props[k.strip()] = v.strip()
                    break
            else:
                props[line] = ""
    return props
