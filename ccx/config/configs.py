"""CruiseControlConfig — the merged per-subsystem key table.

Parity: ``config/{KafkaCruiseControlConfig,MonitorConfig,AnalyzerConfig,
ExecutorConfig,AnomalyDetectorConfig,WebServerConfig,UserTaskManagerConfig}
.java`` (SURVEY.md C35). Key names keep the reference's dotted spelling so an
operator's ``cruisecontrol.properties`` carries over; ccx-specific keys (the
TPU optimizer backend knobs, north star ``goal.optimizer.backend=tpu``,
BASELINE.json:5) live under the ``optimizer.*`` prefix.
"""

from __future__ import annotations

from typing import Any

from ccx.config.definition import (
    NO_DEFAULT,
    ConfigDef,
    ConfigException,
    Importance,
    Type,
    at_least,
    between,
    load_properties,
    non_empty,
    one_of,
)

# Default goal list — AnalyzerConfig `goals` default order (SURVEY.md §2.3).
DEFAULT_GOALS = (
    "RackAwareGoal",
    "MinTopicLeadersPerBrokerGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
    "NetworkInboundCapacityGoal",
    "NetworkOutboundCapacityGoal",
    "CpuCapacityGoal",
    "ReplicaDistributionGoal",
    "PotentialNwOutGoal",
    "DiskUsageDistributionGoal",
    "NetworkInboundUsageDistributionGoal",
    "NetworkOutboundUsageDistributionGoal",
    "CpuUsageDistributionGoal",
    "TopicReplicaDistributionGoal",
    "LeaderReplicaDistributionGoal",
    "LeaderBytesInDistributionGoal",
    "PreferredLeaderElectionGoal",
)

DEFAULT_HARD_GOALS = (
    "RackAwareGoal",
    "MinTopicLeadersPerBrokerGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
    "NetworkInboundCapacityGoal",
    "NetworkOutboundCapacityGoal",
    "CpuCapacityGoal",
)


def monitor_config_def() -> ConfigDef:
    d = ConfigDef()
    d.define("partition.metrics.window.ms", Type.LONG, 3_600_000, Importance.HIGH,
             "Span of one partition-metrics aggregation window.", at_least(1))
    d.define("num.partition.metrics.windows", Type.INT, 5, Importance.HIGH,
             "Number of partition-metrics windows kept in memory.", at_least(1))
    d.define("broker.metrics.window.ms", Type.LONG, 300_000, Importance.HIGH,
             "Span of one broker-metrics aggregation window.", at_least(1))
    d.define("num.broker.metrics.windows", Type.INT, 20, Importance.HIGH,
             "Number of broker-metrics windows kept in memory.", at_least(1))
    d.define("min.samples.per.partition.metrics.window", Type.INT, 1, Importance.MEDIUM,
             "Minimum samples for a partition window to be valid without "
             "extrapolation.", at_least(1))
    d.define("min.samples.per.broker.metrics.window", Type.INT, 1, Importance.MEDIUM,
             "Minimum samples for a broker window to be valid.", at_least(1))
    d.define("max.allowed.extrapolations.per.partition", Type.INT, 5, Importance.LOW,
             "Extrapolated windows allowed before a partition is invalid.", at_least(0))
    d.define("max.allowed.extrapolations.per.broker", Type.INT, 5, Importance.LOW,
             "Extrapolated windows allowed before a broker is invalid.", at_least(0))
    d.define("metric.sampling.interval.ms", Type.LONG, 120_000, Importance.HIGH,
             "Period of the metric sampling loop.", at_least(1))
    d.define("num.metric.fetchers", Type.INT, 1, Importance.MEDIUM,
             "Parallel sampling fetcher threads (partitions sharded across "
             "them).", at_least(1))
    d.define("metric.sampler.class", Type.CLASS,
             "ccx.monitor.sampling.reporter_sampler.ReporterMetricSampler",
             Importance.HIGH, "MetricSampler SPI implementation (ref C10).")
    d.define("sample.store.class", Type.CLASS,
             "ccx.monitor.sampling.sample_store.FileSampleStore",
             Importance.HIGH,
             "SampleStore SPI implementation; persists samples and replays "
             "them on startup for a warm model (ref C11, checkpoint/resume).")
    d.define("sample.store.dir", Type.STRING, "/tmp/ccx-samples", Importance.MEDIUM,
             "Directory for the default file-backed sample store.")
    d.define("broker.capacity.config.resolver.class", Type.CLASS,
             "ccx.monitor.capacity.FileCapacityResolver",
             Importance.HIGH, "BrokerCapacityConfigResolver SPI (ref C5).")
    d.define("capacity.config.file", Type.STRING, "config/capacity.json",
             Importance.HIGH, "Capacity file for the default resolver.")
    d.define("monitor.state.update.interval.ms", Type.LONG, 30_000, Importance.LOW,
             "Refresh period of cached monitor state.", at_least(1))
    d.define("prometheus.server.endpoint", Type.STRING, "http://127.0.0.1:9090",
             Importance.LOW, "Prometheus base URL for the "
             "PrometheusMetricSampler (ref C10 alternative sampler).")
    d.define("leader.network.inbound.weight.for.cpu.util", Type.DOUBLE, 0.6,
             Importance.LOW, "ModelUtils leader NW_IN coefficient for CPU "
             "estimation (ref C6).", between(0, 10))
    d.define("leader.network.outbound.weight.for.cpu.util", Type.DOUBLE, 0.1,
             Importance.LOW, "ModelUtils leader NW_OUT coefficient.", between(0, 10))
    d.define("follower.network.inbound.weight.for.cpu.util", Type.DOUBLE, 0.3,
             Importance.LOW, "ModelUtils follower NW_IN coefficient.", between(0, 10))
    return d


def analyzer_config_def() -> ConfigDef:
    d = ConfigDef()
    d.define("goals", Type.LIST, DEFAULT_GOALS, Importance.HIGH,
             "Goal class names in priority order (lexicographic semantics).",
             non_empty)
    d.define("hard.goals", Type.LIST, DEFAULT_HARD_GOALS, Importance.HIGH,
             "Subset of goals that must be satisfied.", non_empty)
    d.define("default.goals", Type.LIST, (), Importance.MEDIUM,
             "Goals used when a request names none (empty = `goals`).")
    d.define("self.healing.goals", Type.LIST, (), Importance.MEDIUM,
             "Goals used by self-healing (empty = hard goals).")
    d.define("anomaly.detection.goals", Type.LIST, DEFAULT_HARD_GOALS,
             Importance.MEDIUM, "Goals scored by the goal-violation detector.")
    d.define("cpu.balance.threshold", Type.DOUBLE, 1.1, Importance.MEDIUM,
             "Max broker CPU utilization ratio vs cluster average.", at_least(1))
    d.define("disk.balance.threshold", Type.DOUBLE, 1.1, Importance.MEDIUM,
             "Max broker DISK utilization ratio vs average.", at_least(1))
    d.define("network.inbound.balance.threshold", Type.DOUBLE, 1.1, Importance.MEDIUM,
             "Max broker NW_IN utilization ratio vs average.", at_least(1))
    d.define("network.outbound.balance.threshold", Type.DOUBLE, 1.1, Importance.MEDIUM,
             "Max broker NW_OUT utilization ratio vs average.", at_least(1))
    d.define("cpu.capacity.threshold", Type.DOUBLE, 0.7, Importance.MEDIUM,
             "Usable fraction of broker CPU capacity.", between(0, 1))
    d.define("disk.capacity.threshold", Type.DOUBLE, 0.8, Importance.MEDIUM,
             "Usable fraction of broker DISK capacity.", between(0, 1))
    d.define("network.inbound.capacity.threshold", Type.DOUBLE, 0.8,
             Importance.MEDIUM, "Usable fraction of NW_IN capacity.", between(0, 1))
    d.define("network.outbound.capacity.threshold", Type.DOUBLE, 0.8,
             Importance.MEDIUM, "Usable fraction of NW_OUT capacity.", between(0, 1))
    d.define("max.replicas.per.broker", Type.LONG, 10_000, Importance.MEDIUM,
             "ReplicaCapacityGoal limit.", at_least(1))
    d.define("cpu.low.utilization.threshold", Type.DOUBLE, 0.0, Importance.LOW,
             "Below this CPU utilization a broker is ignored by the CPU "
             "distribution goal.", between(0, 1))
    d.define("disk.low.utilization.threshold", Type.DOUBLE, 0.0, Importance.LOW,
             "DISK low-utilization gate.", between(0, 1))
    d.define("network.inbound.low.utilization.threshold", Type.DOUBLE, 0.0,
             Importance.LOW, "NW_IN low-utilization gate.", between(0, 1))
    d.define("network.outbound.low.utilization.threshold", Type.DOUBLE, 0.0,
             Importance.LOW, "NW_OUT low-utilization gate.", between(0, 1))
    d.define("leader.bytes.in.balance.threshold", Type.DOUBLE, 1.1,
             Importance.LOW, "LeaderBytesInDistributionGoal band width.",
             at_least(1))
    d.define("min.topic.leaders.per.broker", Type.INT, 1, Importance.LOW,
             "MinTopicLeadersPerBrokerGoal requirement.", at_least(0))
    d.define("topics.with.min.leaders.per.broker", Type.STRING, "", Importance.LOW,
             "Regex of topics subject to MinTopicLeadersPerBrokerGoal.")
    d.define("topic.replica.count.balance.threshold", Type.DOUBLE, 3.0,
             Importance.LOW, "TopicReplicaDistributionGoal band width.", at_least(1))
    d.define("leader.replica.count.balance.threshold", Type.DOUBLE, 1.1,
             Importance.LOW, "LeaderReplicaDistributionGoal band width.", at_least(1))
    d.define("replica.count.balance.threshold", Type.DOUBLE, 1.1, Importance.MEDIUM,
             "ReplicaDistributionGoal band width.", at_least(1))
    d.define("num.proposal.precompute.threads", Type.INT, 1, Importance.MEDIUM,
             "Background proposal precompute workers (ref C14).", at_least(0))
    d.define("proposal.expiration.ms", Type.LONG, 900_000, Importance.MEDIUM,
             "Cached proposal freshness horizon.", at_least(0))
    d.define("allow.capacity.estimation.on.proposal.precompute", Type.BOOLEAN, True,
             Importance.LOW, "Permit estimated capacities during precompute.")
    # --- ccx TPU backend (north star: goal.optimizer.backend=tpu) ----------
    d.define("goal.optimizer.backend", Type.STRING, "tpu", Importance.HIGH,
             "Proposal search backend: 'tpu' = batched SA + greedy polish on "
             "device (BASELINE.json north star); 'greedy' = host-side greedy "
             "oracle only.", one_of("tpu", "greedy"))
    d.define("optimizer.num.chains", Type.INT, 32, Importance.MEDIUM,
             "SA chains vmapped on device.", at_least(1))
    d.define("optimizer.num.steps", Type.INT, 3000, Importance.MEDIUM,
             "SA steps per chain.", at_least(1))
    d.define("optimizer.moves.per.step", Type.INT, 8, Importance.MEDIUM,
             "SA proposals per chain per scan step, applied as a disjoint "
             "batch on large clusters (AnnealOptions.batched) — total churn "
             "budget is chains * steps * this.", at_least(1))
    d.define("optimizer.seed", Type.INT, 42, Importance.LOW, "SA PRNG seed.")
    d.define("optimizer.chunk.steps", Type.INT, 500, Importance.LOW,
             "Run the SA scan in fixed chunks of this many steps so one "
             "compiled program serves every optimizer.num.steps budget "
             "(TPU compiles at scale are minutes per distinct step count); "
             "0 = single scan keyed on the full step count. Results are "
             "bit-exact either way. Covers EVERY drive path — "
             "single-device, chains-mesh data parallelism and the "
             "partition-axis-sharded engine (optimizer.mesh.*) all run "
             "the same chunk contract with per-chunk heartbeats.",
             at_least(0))
    d.define("optimizer.mesh.enabled", Type.BOOLEAN, False, Importance.MEDIUM,
             "Run the SA search sharded over a jax device mesh "
             "(ccx.parallel.sharding): chains ride the mesh as data "
             "parallelism and optimizer.mesh.parts > 1 additionally shards "
             "the model's partition axis inside the search — the B6-scale "
             "(10k brokers / 1M partitions) axis. The mesh path is "
             "chunk-driven like the single-chip anneal (bounded compile, "
             "per-chunk flight-recorder heartbeats, cost capture); the "
             "winning placement is re-homed to the default device so every "
             "later pipeline phase runs the single-chip programs. Ignored "
             "with a log note when fewer than two devices are visible.")
    d.define("optimizer.mesh.devices", Type.INT, 0, Importance.LOW,
             "Devices for the optimizer mesh; 0 = all visible devices.",
             at_least(0))
    d.define("optimizer.mesh.parts", Type.INT, 1, Importance.LOW,
             "Partition-axis factor of the optimizer mesh (chains = "
             "devices / parts). 1 = chains-only data parallelism; raise "
             "for clusters whose per-device model shard (100k+ "
             "partitions) matters more than extra chains. A factor that "
             "does not divide the device count (or the padded partition "
             "axis) falls back to chains-only with a log note.",
             at_least(1))
    d.define("optimizer.polish.candidates", Type.INT, 256, Importance.LOW,
             "Greedy polish candidate moves per iteration.", at_least(1))
    d.define("optimizer.polish.max.iters", Type.INT, 400, Importance.LOW,
             "Greedy polish iteration cap.", at_least(1))
    d.define("optimizer.polish.chunk.iters", Type.INT, 50, Importance.LOW,
             "Iterations per jitted chunk program of the host-driven "
             "greedy-polish descent (the leadership pass and the "
             "topic-rebalance re-polish share the engine). The ONLY "
             "shape-bearing polish budget: max-iters/patience stay traced "
             "data, so every budget shares one compiled chunk per shape "
             "and the worst-case XLA compile is one small chunk program, "
             "not the whole iteration loop (the round-4 B5 greedy compile "
             "ran >17 min on TPU and timed out). 0 = monolithic "
             "while_loop (bit-exact with the chunked engine; the parity "
             "reference).", at_least(0))
    d.define("optimizer.swap.polish.chunk.iters", Type.INT, 50,
             Importance.LOW,
             "Iterations per jitted chunk program of the usage-coupled "
             "swap-polish descent — the optimizer.polish.chunk.iters twin "
             "(0 = monolithic while_loop; budgets stay traced either "
             "way).", at_least(0))
    d.define("optimizer.topic.rebalance.rounds", Type.INT, 2, Importance.LOW,
             "Sweep+polish rounds of the targeted TopicReplicaDistribution "
             "stage (each enumerates over-band (topic, broker) cells, "
             "re-polishes, and is adopted only on full-vector lex "
             "improvement). 0 disables.", at_least(0))
    d.define("optimizer.topic.rebalance.move.leaders", Type.BOOLEAN, True,
             Importance.LOW,
             "Let the topic-rebalance stage shed leader-held over cells by "
             "transferring leadership to a co-replica first (hard-safe; "
             "the final leadership pass rebalances afterwards). Disable "
             "for latency-bounded sweeps where follower moves are "
             "cheaper.")
    d.define("optimizer.topic.rebalance.max.sweeps", Type.INT, 1024,
             Importance.LOW,
             "Per-round sweep cap for the topic-rebalance stage. The sweep "
             "loop stops on its own when no move lands, so this is a "
             "latency bound, not a convergence knob; the default lets a "
             "round run to convergence. Latency-critical callers lower it.",
             at_least(1))
    d.define("optimizer.topic.rebalance.guarded", Type.BOOLEAN, True,
             Importance.LOW,
             "Run the topic-rebalance stage's re-polish with the "
             "TopicReplicaDistribution guard first (vetoes moves that "
             "worsen the TRD tier, so the usage re-polish cannot trade the "
             "shed's topic cells back), falling back to an unguarded "
             "polish when the guarded one fails lex adoption.")
    d.define("optimizer.topic.rebalance.polish.iters", Type.INT, -1,
             Importance.LOW,
             "Iteration budget for the topic-rebalance stage's re-polish; "
             "-1 inherits optimizer.polish.max.iters. A converged shed "
             "relocates ~55k replicas at B5 scale — the post-shed cleanup "
             "often needs more budget than the pre-shed polish.",
             at_least(-1))
    d.define("optimizer.leader.pass.max.iters", Type.INT, -1, Importance.LOW,
             "Iteration cap for the final leadership-only pass; -1 = "
             "uncapped (inherit optimizer.polish.max.iters).", at_least(-1))
    d.define("optimizer.polish.batch.moves", Type.INT, 16, Importance.LOW,
             "Non-conflicting improving moves applied per polish iteration "
             "(disjoint partitions/topics/broker sets; 1 = classic "
             "best-move hill climbing).", at_least(1))
    d.define("optimizer.portfolio.cold.greedy", Type.BOOLEAN, True,
             Importance.LOW,
             "Also run the greedy oracle from the input placement and return "
             "the lexicographic winner (the GoalOptimizer precompute-cache "
             "portfolio pattern). Costs roughly one extra polish-budget run "
             "per optimize() call; disable for latency-sensitive endpoints. "
             "Leadership-only and disk-only fast paths skip it regardless.")
    d.define("optimizer.swap.coupling", Type.DOUBLE, 0.5, Importance.LOW,
             "Share of SA swap proposals drawn usage-coupled (both "
             "endpoints Gumbel-selected from a candidate pool ranked by "
             "live broker band pressure x per-replica usage) instead of "
             "uniformly. 0 restores the uniform draw; coupling is what "
             "lets a lean budget hit the specific different-topic pairs "
             "that fix residual NetworkOutUsage/LeaderReplica cells.",
             between(0, 1))
    d.define("optimizer.swap.p.swap", Type.DOUBLE, 0.15, Importance.LOW,
             "REPLICA_SWAP share of SA proposals (AnnealOptions.p_swap; "
             "intra-broker stacks force 0).", between(0, 1))
    d.define("optimizer.swap.p.swap.end", Type.DOUBLE, -1.0, Importance.LOW,
             "End value of the linear p_swap schedule: the swap share "
             "anneals from optimizer.swap.p.swap to this value over the "
             "run (swaps matter most once count tiers settle). -1 = "
             "constant share. The schedule enters compiled programs as "
             "data — retunes never recompile the SA chunk.",
             between(-1, 1))
    d.define("optimizer.exchange.n.temps", Type.INT, 1, Importance.LOW,
             "Temperature rungs of the SA replica-exchange ladder "
             "(AnnealOptions.n_temps). >1 partitions the chain batch "
             "into K rungs on a geometric temperature ladder between t1 "
             "and t0 and swaps chain STATES between neighboring rungs at "
             "chunk boundaries (Metropolis on the soft-cost scalar, lex "
             "tie-break; the lex-best chain never leaves the coldest "
             "rung). A pure permutation of the batch axis: no new "
             "compiled-program shapes. 1 = flat chains (bit-exact legacy "
             "path). Requires optimizer.chunk.steps > 0.", at_least(1))
    d.define("optimizer.exchange.interval", Type.INT, 1, Importance.LOW,
             "Chunk boundaries between replica-exchange sweeps (1 = "
             "every chunk). Enters compiled programs as data — retunes "
             "never recompile the SA chunk.", at_least(1))
    d.define("optimizer.bf16.scoring", Type.BOOLEAN, False, Importance.LOW,
             "Opt-in bf16 scoring tier: rank-order-only intermediates "
             "(band-pressure x usage pool scores feeding the coupled-swap "
             "Gumbel picks) accumulate in bfloat16; every lex cost "
             "vector and accept/exchange decision stays f32. A "
             "throughput knob for the TPU MXU — leave False on CPU "
             "correctness paths.")
    d.define("optimizer.swap.polish.iters", Type.INT, 150, Importance.LOW,
             "Iteration budget for the usage-coupled swap-polish phase "
             "(count-preserving replica swaps + pressure-coupled "
             "leadership transfers, pure lexicographic descent, run after "
             "the topic-rebalance stage). 0 disables. The budget is "
             "while_loop data — every setting shares one compiled "
             "program. Leadership-/disk-only fast paths skip the phase.",
             at_least(0))
    d.define("optimizer.swap.polish.post.iters", Type.INT, 150,
             Importance.LOW,
             "Iteration budget for the SECOND swap-polish invocation, run "
             "after the leadership pass (the uniform leader pass stalls "
             "on LeaderReplica/LeaderBytesIn cells only the coupled draw "
             "finds). 0 disables; shares the pre-leader stage's compiled "
             "program.", at_least(0))
    d.define("optimizer.swap.polish.candidates", Type.INT, 128,
             Importance.LOW,
             "Coupled candidates scored per swap-polish iteration, split "
             "evenly between replica-swap pairs and leadership transfers "
             "(static program shape, shared by the pre- and post-leader "
             "invocations).", at_least(1))
    d.define("optimizer.swap.polish.guarded", Type.BOOLEAN, True,
             Importance.LOW,
             "Veto swap-polish candidates that significantly worsen the "
             "TopicReplicaDistribution tier (different-topic swaps move "
             "topic cells; the guard keeps a converged shed's TRD=0 from "
             "being traded back for usage cells — same rationale as "
             "optimizer.topic.rebalance.guarded).")
    d.define("optimizer.fleet.max.concurrent", Type.INT, 0, Importance.LOW,
             "Device-residency cap of the multi-job chunk scheduler "
             "(ccx.search.scheduler): at most this many concurrent "
             "optimization jobs interleave chunks on the device while the "
             "rest queue in (priority, arrival) order. 0 = unlimited. "
             "Bound it when N concurrent jobs' donated carries would "
             "pressure HBM past the snapshot registry's budget.",
             at_least(0))
    d.define("optimizer.fleet.dispatch.width", Type.INT, 0, Importance.LOW,
             "Simultaneous chunk-dispatch grants of the fleet scheduler. "
             "0 = auto (host core count, floor 2). Width 1 is strict "
             "round-robin alternation; the wider default matters on the "
             "CPU backend, where a dispatch largely IS the execution — "
             "on an accelerator the grant covers only the async enqueue. "
             "Grant ORDER stays priority-first/round-robin at any width.",
             at_least(0))
    d.define("optimizer.fleet.cluster.id", Type.STRING, "default",
             Importance.LOW,
             "This facade's cluster id on the fleet scheduler: the job "
             "label its verbs register under (spans, heartbeats and "
             "Prometheus histograms carry job=<cluster-id>), and the "
             "per-cluster mutual-exclusion key of the proposal path (two "
             "proposals for the same cluster serialize; different "
             "clusters never convoy).")
    d.define("optimizer.fleet.priority.urgent", Type.INT, 10,
             Importance.LOW,
             "Scheduler priority of urgent (self-healing) verbs — "
             "fix-offline-replicas, self-healing rebalances. Higher "
             "preempts queued lower-priority jobs at the next chunk "
             "boundary; normal dryrun verbs run at priority 0.",
             at_least(0))
    d.define("optimizer.fleet.snapshot.hbm.mb", Type.INT, 0, Importance.LOW,
             "HBM budget (MB) for the sidecar's device-resident snapshot "
             "registry (N cluster models kept live, LRU-evicted). 0 = "
             "auto: half of (device HBM capacity - the cost observatory's "
             "captured working-set watermark), floor 64 MB "
             "(ccx.common.costmodel.fleet_snapshot_budget_bytes). Also "
             "the fallback budget of the unified device-memory ledger "
             "when optimizer.devmem.budget.mb is 0.",
             at_least(0))
    d.define("optimizer.devmem.budget.mb", Type.INT, 0, Importance.LOW,
             "Budget (MB) of the UNIFIED device-memory ledger "
             "(ccx.common.devmem): one byte-priced pool for snapshot "
             "device models, warm placement bases and the compiled-"
             "program working set together, with priority-aware "
             "eviction (an urgent self-healing job's residents are "
             "never displaced by a dryrun admission; lowest-priority / "
             "least-recently-used go first; eviction degrades to a "
             "rebuild or a documented ColdStartRequired cold start, "
             "never a failed RPC). 0 = fall through to "
             "optimizer.fleet.snapshot.hbm.mb, else the auto "
             "derivation. Env twin: CCX_DEVMEM_BUDGET_MB.",
             at_least(0))
    d.define("optimizer.incremental.enabled", Type.BOOLEAN, False,
             Importance.MEDIUM,
             "Arm incremental re-optimization (ccx.search.incremental): "
             "the facade's proposal verbs and the sidecar's warm-start "
             "Propose path keep each cluster session's last converged "
             "placement device-resident, re-score only drift-touched "
             "bands on a new metrics window, warm-start the search from "
             "the previous solution with a short plateau-terminated "
             "budget, and emit the minimal diff. Off (default) restores "
             "from-scratch proposals everywhere; env CCX_INCREMENTAL=0 "
             "force-disables regardless of this key.")
    d.define("optimizer.incremental.warm.swap.iters", Type.INT, 8,
             Importance.LOW,
             "Usage-coupled swap-polish iterations of a warm re-proposal "
             "— the primary warm engine (pure lex descent over "
             "pressure-ranked swaps + leadership transfers; re-scores "
             "the band-pressure tables from carried aggregates each "
             "iteration). 8 is the <500 ms B5 operating point on the "
             "banked host (~18 ms/iteration there). 0 disables.",
             at_least(0))
    d.define("optimizer.incremental.warm.swap.patience", Type.INT, 3,
             Importance.LOW,
             "Consecutive no-improvement iterations before the warm "
             "swap polish stops (traced — its plateau rule).",
             at_least(1))
    d.define("optimizer.incremental.warm.swap.candidates", Type.INT, 32,
             Importance.LOW,
             "Candidate pool of the warm swap polish (split evenly "
             "between replica-swap pairs and leadership transfers). The "
             "applied disjoint batch saturates near 16 moves/iteration, "
             "so pools past ~32 buy wall, not quality, on a warm "
             "budget.", at_least(2))
    d.define("optimizer.incremental.warm.steps", Type.INT, 100,
             Importance.LOW,
             "SA step budget (upper bound) of the STRUCTURAL-damage warm "
             "path (repair + targeted SA before the swap polish); the "
             "plateau exit usually stops earlier.", at_least(1))
    d.define("optimizer.incremental.warm.chunk.steps", Type.INT, 25,
             Importance.LOW,
             "Steps per warm SA chunk — the plateau-decision granularity "
             "(its own small compiled chunk program, paid once).",
             at_least(1))
    d.define("optimizer.incremental.warm.chains", Type.INT, 2,
             Importance.LOW,
             "SA chains of the warm run: warm starts are exploitation, "
             "not exploration.", at_least(1))
    d.define("optimizer.incremental.warm.moves", Type.INT, 8,
             Importance.LOW,
             "Proposals per chain step of the warm run.", at_least(1))
    d.define("optimizer.incremental.plateau.window", Type.INT, 1,
             Importance.LOW,
             "Chunks without lexicographic improvement before the warm "
             "drive stops (the plateau-terminated budget, read from the "
             "convergence taps at the existing chunk boundary). Host "
             "data: retuning it never recompiles any program.",
             at_least(1))
    d.define("optimizer.incremental.warm.t0", Type.DOUBLE, 1e-8,
             Importance.LOW,
             "Warm-run initial temperature (soft-cost units): effectively "
             "pure descent — a converged placement is refined, never "
             "re-randomized, and a tiny budget must not net-accept "
             "Metropolis noise it has no budget to recover from.",
             at_least(0.0))
    d.define("optimizer.incremental.warm.leader.iters", Type.INT, 0,
             Importance.LOW,
             "Leadership-only greedy iterations after the warm SA "
             "(0 = skip): leader-bytes drift sometimes needs transfers "
             "the low-temperature SA misses.", at_least(0))
    d.define("optimizer.incremental.max.sessions", Type.INT, 32,
             Importance.LOW,
             "COUNT backstop on the process-wide warm-placement store "
             "(~12 MB of device arrays per B5-scale session). Warm "
             "bases are primarily BYTE-priced on the unified device-"
             "memory ledger (optimizer.devmem.budget.mb) next to the "
             "snapshot models, with priority-aware eviction; this cap "
             "only bounds the session count on top. An evicted session "
             "simply cold-starts on its next proposal.",
             at_least(1))
    d.define("optimizer.scenario.seed", Type.INT, 7, Importance.LOW,
             "Seed of the adversarial scenario generator "
             "(ccx.bench.scenarios): the whole family x window corpus — "
             "cascading broker failures, full-disk evacuation, hot-topic "
             "skew, broker add/demote/remove waves, partition-count "
             "changes — is a pure function of (base snapshot, seed, "
             "windows). Env twin for the bench rung: CCX_SCENARIO_SEED.",
             at_least(0))
    d.define("optimizer.scenario.windows", Type.INT, 4, Importance.LOW,
             "Windows per scenario family (cumulative damage steps). "
             "Every window of every family keeps the base snapshot's "
             "padded program-shape buckets by construction, so the "
             "whole matrix runs zero-compile after one prewarm pass. "
             "Env twin: CCX_SCENARIO_WINDOWS.", at_least(1))
    d.define("optimizer.scenario.families", Type.LIST, (), Importance.LOW,
             "Scenario families to emit (empty = all five: "
             "broker-failures, disk-evacuation, hot-skew, broker-wave, "
             "partition-change). Env twin: CCX_SCENARIO_FAMILIES "
             "(comma-separated).")
    d.define("optimizer.plan.enabled", Type.BOOLEAN, False, Importance.LOW,
             "Movement planning (ccx.search.movement, ISSUE 17): wave-"
             "schedule every proposal's columnar diff into throttle-"
             "respecting execution waves (per-broker concurrent-move caps "
             "+ per-wave byte budgets) and surface the schedule as the "
             "additive OptimizerResult.plan block the executor consumes "
             "(wave = batch). Off (default) is bit-exact with the "
             "pre-plan pipeline and compiles nothing new; warm windows "
             "re-plan the remaining waves as completions arrive as delta "
             "snapshots.")
    d.define("optimizer.plan.cost.tier", Type.BOOLEAN, False,
             Importance.LOW,
             "Append the movement-cost tier to the lexicographic "
             "portfolio adoption: a quality TIE between candidate "
             "placements resolves toward the one moving fewer bytes / "
             "pressing brokers less (bytes moved, then peak per-broker "
             "inbound bytes, computed on device from the same assignment "
             "tensors the columnar diff masks). Off (default) keeps the "
             "plain lex rule bit-exact and never compiles the cost "
             "program.")
    d.define("optimizer.plan.max.waves", Type.INT, 64, Importance.LOW,
             "Wave-axis size of the compiled scheduler state (static "
             "program shape — changing it recompiles the planner; caps "
             "and budgets below are traced data and retune for free). A "
             "diff that fits no feasible wave overflows into the last "
             "one and is reported (plan.overflowRows).", at_least(2))
    d.define("optimizer.plan.broker.cap", Type.INT, 5, Importance.LOW,
             "Per-broker concurrent-move cap per wave (source or "
             "destination), the planning image of "
             "num.concurrent.partition.movements.per.broker / the "
             "concurrency adjuster's live cap. Traced data.", at_least(1))
    d.define("optimizer.plan.wave.bytes.mb", Type.DOUBLE, 0.0,
             Importance.LOW,
             "Per-broker per-wave byte budget in model load units (MB) — "
             "the ReplicationThrottleHelper image: at throttle rate R "
             "and target wave duration T set ~R*T. <=0 = uncapped "
             "(count caps only). Traced data.")
    d.define("optimizer.plan.throttle.mbps", Type.DOUBLE, 0.0,
             Importance.LOW,
             "Per-broker replication rate (MB/s) pricing the projected "
             "wave durations (plan.waveSeconds / makespanSeconds). <=0 "
             "reports relative byte units. Traced data.")
    d.define("optimizer.plan.throttle.measured", Type.BOOLEAN, True,
             Importance.LOW,
             "Close the wave-pricing feedback loop: when the executor "
             "has MEASURED per-wave completion rates (the EWMA MB/s in "
             "its observability plan block), re-plans price the "
             "remaining waves with the measured rate instead of the "
             "static optimizer.plan.throttle.mbps. False pins the "
             "static rate (bit-exact pre-feedback pricing).")
    d.define("optimizer.repair.backend", Type.STRING, "device",
             Importance.LOW,
             "hard_repair loop driver: 'device' runs the whole sweep loop "
             "as one compiled program (traced sweep budget, no per-sweep "
             "host syncs — repair leaves the host-blocking critical path); "
             "'host' restores the python loop (one jitted sweep + one sync "
             "per iteration), the fallback and parity reference.",
             one_of("device", "host"))
    d.define("optimizer.repair.overlap", Type.BOOLEAN, False, Importance.LOW,
             "Overlap hard repair with the first SA chunk: repair runs in "
             "a background thread while the first chunk anneals the "
             "still-infeasible input, then the candidates lex-merge. Only "
             "buys wall-clock where repair executes outside the device "
             "stream the SA chunk occupies (host-backend repair on a "
             "multi-core host); the default pipelined device repair "
             "already keeps repair off the critical path.")
    d.define("optimizer.profile.dir", Type.STRING, "", Importance.LOW,
             "When non-empty, capture a jax.profiler (XProf/TensorBoard) "
             "device trace of each proposal computation into this directory "
             "(SURVEY.md 5.1: the TPU-side analogue of the reference's JMX "
             "proposal-computation-timer).")
    return d


def observability_config_def() -> ConfigDef:
    """Flight-recorder tracing keys (ccx.common.tracing; SURVEY.md §5.1
    rebuild note — the host-side OperationProgress/Dropwizard analogue,
    extended so a SIGKILLed TPU window still leaves a diagnosis)."""
    d = ConfigDef()
    d.define("observability.flight.recorder.path", Type.STRING, "",
             Importance.MEDIUM,
             "When non-empty, stream every span start/end, chunk heartbeat "
             "and watchdog dump to this JSONL file (append + atomic "
             "per-record write, so a killed or timed-out proposal run "
             "leaves a file whose last line names the active phase, chunk "
             "index and compile attribution at death — read it with "
             "`python -m ccx.common.tracing <file>`). Empty = recorder "
             "disarmed unless the CCX_FLIGHT_RECORDER env var is set.")
    d.define("observability.watchdog.seconds", Type.DOUBLE, 0.0,
             Importance.MEDIUM,
             "Stall watchdog: when > 0 and no span event or chunk "
             "heartbeat arrives for this long while spans are active, dump "
             "all-thread stacks + the active span stacks + live "
             "compilestats into the flight recorder (and stderr) — one "
             "dump per stall episode, re-armed by the next heartbeat. 0 "
             "disables (env override: CCX_WATCHDOG_SECONDS).", at_least(0))
    d.define("observability.trace.sync", Type.BOOLEAN, False,
             Importance.LOW,
             "Device-honest span timing: drain the device stream "
             "(block_until_ready on a freshly dispatched scalar) at every "
             "span close, so per-phase walls measure device completion "
             "rather than dispatch. Default off — syncing forfeits the "
             "measured repair/anneal dispatch overlap; enable for TPU "
             "timing studies only (env override: CCX_TRACE_SYNC=1).")
    d.define("observability.cost.capture", Type.BOOLEAN, False,
             Importance.MEDIUM,
             "Device cost observatory (ccx.common.costmodel): capture "
             "compiled.cost_analysis()/memory_analysis() for every NEW "
             "program shape the optimizer runs — per-program XLA FLOPs, "
             "bytes accessed and argument/output/temp HBM, rolled up as "
             "the costModel block on every proposal result, the "
             "/observability ledger, and roofline-projected per phase. "
             "The capture flush is one extra AOT compile per program "
             "shape (served by the persistent compile cache when armed), "
             "paid on the cold path only — warm runs never capture. "
             "Default off for embedded use; bench.py and the standalone "
             "sidecar arm it (env override: CCX_COST_CAPTURE=1/0).")
    d.define("observability.cost.peak.tflops", Type.DOUBLE, 0.0,
             Importance.LOW,
             "Roofline ceiling override for the CURRENT device: peak "
             "TFLOP/s used by the cost model's projections. 0 = use the "
             "built-in device-spec table (v5e/v5p/v4 published peaks, "
             "order-of-magnitude CPU host estimate).", at_least(0))
    d.define("observability.cost.hbm.gbps", Type.DOUBLE, 0.0,
             Importance.LOW,
             "Roofline ceiling override for the CURRENT device: HBM "
             "bandwidth in GB/s used by the cost model's projections. "
             "0 = use the built-in device-spec table.", at_least(0))
    d.define("observability.convergence", Type.BOOLEAN, True,
             Importance.MEDIUM,
             "Convergence telemetry taps (ccx.search.telemetry): thread a "
             "device-resident ring buffer through every chunk-driven "
             "search engine, recording per chunk the full per-goal lex "
             "cost vector, per-move-kind proposal/acceptance counters and "
             "the SA temperature — surfaced as the convergence block on "
             "every proposal result, tier-0 energy on flight-recorder "
             "heartbeats, per-job /observability timelines and the "
             "convergence-energy/plateau-step Prometheus gauges; "
             "tools/convergence_report.py turns it into per-phase plateau "
             "and budget proposals. Zero added host syncs and shape-"
             "stable (budget retunes never recompile). False restores "
             "today's compiled programs bit-exactly (env override: "
             "CCX_CONVERGENCE=0).")
    d.define("observability.faults.spec", Type.STRING, "",
             Importance.LOW,
             "Deterministic fault injection (ccx.common.faults, the chaos "
             "layer): a ;-separated schedule of "
             "seam:action@N rules armed at startup — seams "
             "snapshot.transfer / registry.graft / placement.bank / "
             "device.diff / rpc.frame / scheduler.grant / compile, "
             "actions raise / exhaust / sever / delay / corrupt, fired "
             "on the Nth hit (N+ from the Nth on, N/M every Mth, * "
             "always). Empty (the default) leaves the registry DISARMED: "
             "every seam is a single no-op attribute read and the "
             "serving path is bit-exact vs a tree without the chaos "
             "layer. Env twin for bench/standalone entry points: "
             "CCX_FAULTS.")
    d.define("observability.faults.seed", Type.INT, 0,
             Importance.LOW,
             "Seed of the fault registry's corrupt-action RNG (keyed "
             "(seed, seam, hit) — same spec + seed replays the same "
             "faults byte-identically). Env twin: CCX_FAULTS_SEED.",
             at_least(0))
    d.define("observability.slo.window.seconds", Type.DOUBLE, 10.0,
             Importance.MEDIUM,
             "Span of one SLO accounting window (ccx.common.slo): the "
             "windowed SLO engine buckets serving windows at this "
             "cadence, and the soak rung advances its simulated fleet "
             "clock by this much per tick. Time-to-detect/heal are "
             "measured on the same clock.", at_least(0.001))
    d.define("observability.slo.short.windows", Type.INT, 12,
             Importance.LOW,
             "Short (paging) burn-rate window, in serving-window counts "
             "— the fast half of the classic multi-window SLO alert.",
             at_least(1))
    d.define("observability.slo.long.windows", Type.INT, 60,
             Importance.LOW,
             "Long (ticket) burn-rate window, in serving-window counts.",
             at_least(1))
    d.define("observability.slo.warm.target", Type.DOUBLE, 0.95,
             Importance.MEDIUM,
             "Warm-served SLO target: fraction of serving windows that "
             "must be answered by the warm incremental path AND verify. "
             "The error budget is 1 - target; the "
             "ccx_slo_burn_rate{objective=\"warm_served\"} gauge reports "
             "budget burn against it.", between(0, 1))
    d.define("observability.slo.latency.budget.seconds", Type.DOUBLE, 5.0,
             Importance.MEDIUM,
             "Per-window end-to-end latency budget: windows at or under "
             "this wall count toward the latency SLO; the stream "
             "detector classifies windows over it as latency_burst.",
             at_least(0.001))
    d.define("observability.slo.latency.target", Type.DOUBLE, 0.99,
             Importance.LOW,
             "Latency SLO target fraction (the p99-style budget: 0.99 "
             "means 1% of windows may exceed the latency budget).",
             between(0, 1))
    d.define("observability.slo.dwell.target", Type.DOUBLE, 0.95,
             Importance.LOW,
             "Goal-violation dwell SLO target: fraction of windows that "
             "must carry NO classified anomaly signal — bounds how much "
             "of the timeline the fleet may spend in violation.",
             between(0, 1))
    d.define("observability.convergence.max.chunks", Type.INT, 256,
             Importance.LOW,
             "Ring-buffer depth of the convergence taps, in chunk rows. "
             "Program SHAPE like the chunk sizes (changing it mints new "
             "compiled chunk programs — a deployment choice, not a "
             "per-run retune); runs longer than this keep the opening "
             "rows plus the latest chunk and are flagged truncated. "
             "Default 256 covers every banked rung with an order of "
             "magnitude to spare at ~20 KB of HBM.", at_least(1))
    return d


def executor_config_def() -> ConfigDef:
    d = ConfigDef()
    d.define("num.concurrent.partition.movements.per.broker", Type.INT, 5,
             Importance.HIGH, "Per-broker inter-broker movement cap.", at_least(1))
    d.define("num.concurrent.intra.broker.partition.movements", Type.INT, 2,
             Importance.MEDIUM, "Per-broker intra-broker (disk) movement cap.",
             at_least(1))
    d.define("num.concurrent.leader.movements", Type.INT, 1000, Importance.HIGH,
             "Cluster-wide leadership movement batch cap.", at_least(1))
    d.define("max.num.cluster.movements", Type.INT, 1250, Importance.MEDIUM,
             "Cluster-wide cap on in-flight movements.", at_least(1))
    d.define("execution.progress.check.interval.ms", Type.LONG, 10_000,
             Importance.HIGH, "Progress polling period during execution.",
             at_least(1))
    d.define("default.replication.throttle", Type.LONG, -1, Importance.MEDIUM,
             "Replication throttle (bytes/s) applied during execution; -1 = "
             "no throttle.")
    d.define("replica.movement.strategies", Type.LIST,
             ("ccx.executor.strategy.PrioritizeMinIsrWithOfflineReplicasStrategy",
              "ccx.executor.strategy.PostponeUrpReplicaMovementStrategy",
              "ccx.executor.strategy.PrioritizeLargeReplicaMovementStrategy"),
             Importance.MEDIUM,
             "Chained ReplicaMovementStrategy classes (ref C25).")
    d.define("default.replica.movement.strategy.class", Type.CLASS,
             "ccx.executor.strategy.BaseReplicaMovementStrategy",
             Importance.LOW, "Tie-breaking tail of the strategy chain.")
    d.define("executor.concurrency.adjuster.enabled", Type.BOOLEAN, True,
             Importance.MEDIUM, "Auto-tune movement concurrency from live "
             "broker health (ref C26).")
    d.define("executor.concurrency.adjuster.interval.ms", Type.LONG, 30_000,
             Importance.LOW, "Concurrency adjuster period.", at_least(1))
    d.define("executor.concurrency.adjuster.max.partition.movements.per.broker",
             Type.INT, 12, Importance.LOW, "Adjuster upper bound.", at_least(1))
    d.define("executor.concurrency.adjuster.min.partition.movements.per.broker",
             Type.INT, 1, Importance.LOW, "Adjuster lower bound.", at_least(1))
    d.define("leader.movement.timeout.ms", Type.LONG, 180_000, Importance.LOW,
             "Leadership movement completion timeout.", at_least(1))
    d.define("task.execution.alerting.threshold.ms", Type.LONG, 90_000,
             Importance.LOW, "Warn when a task runs longer than this.", at_least(1))
    d.define("admin.client.class", Type.CLASS,
             "ccx.executor.admin.SimulatedAdminClient", Importance.HIGH,
             "AdminApi SPI implementation — the only component that writes "
             "to the managed cluster (ref C28). Set to "
             "ccx.executor.kafka_admin.KafkaAdminApi (requires kafka-python "
             "+ bootstrap.servers) to drive a real cluster.")
    d.define("admin.request.timeout.ms", Type.LONG, 30_000, Importance.LOW,
             "Request timeout for the real-cluster admin client.", at_least(1))
    return d


def anomaly_detector_config_def() -> ConfigDef:
    d = ConfigDef()
    d.define("anomaly.detection.interval.ms", Type.LONG, 300_000, Importance.HIGH,
             "Default detector period (per-type overrides below).", at_least(1))
    d.define("goal.violation.detection.interval.ms", Type.LONG, -1, Importance.LOW,
             "Goal-violation detector period; -1 = default interval.")
    d.define("metric.anomaly.detection.interval.ms", Type.LONG, -1, Importance.LOW,
             "Metric-anomaly detector period; -1 = default interval.")
    d.define("disk.failure.detection.interval.ms", Type.LONG, -1, Importance.LOW,
             "Disk-failure detector period; -1 = default interval.")
    d.define("topic.anomaly.detection.interval.ms", Type.LONG, -1, Importance.LOW,
             "Topic-anomaly detector period; -1 = default interval.")
    d.define("broker.failure.detection.backoff.ms", Type.LONG, 300_000,
             Importance.LOW, "Broker-failure re-check backoff.", at_least(1))
    d.define("failed.brokers.file.path", Type.STRING, "", Importance.LOW,
             "File persisting broker-failure first-seen times across "
             "restarts (ref failed.brokers.zk.path/file); empty = "
             "<sample.store.dir>/failed_brokers.json.")
    d.define("anomaly.notifier.class", Type.CLASS,
             "ccx.detector.notifier.SelfHealingNotifier", Importance.HIGH,
             "AnomalyNotifier SPI (ref C30).")
    d.define("self.healing.enabled", Type.BOOLEAN, False, Importance.HIGH,
             "Master switch for automatic anomaly fixing.")
    d.define("self.healing.exclude.recently.demoted.brokers", Type.BOOLEAN, True,
             Importance.LOW, "Exclude recently demoted brokers from fixes.")
    d.define("self.healing.exclude.recently.removed.brokers", Type.BOOLEAN, True,
             Importance.LOW, "Exclude recently removed brokers from fixes.")
    d.define("broker.failure.alert.threshold.ms", Type.LONG, 900_000,
             Importance.HIGH, "Grace before alerting on a dead broker.", at_least(0))
    d.define("broker.failure.self.healing.threshold.ms", Type.LONG, 1_800_000,
             Importance.HIGH, "Grace before auto-fixing a dead broker.", at_least(0))
    d.define("metric.anomaly.finder.class", Type.CLASS,
             "ccx.detector.slow_broker.SlowBrokerFinder", Importance.MEDIUM,
             "MetricAnomalyFinder SPI (ref C29).")
    d.define("slow.broker.bytes.in.rate.detection.threshold", Type.DOUBLE, 1024.0,
             Importance.LOW, "Min bytes-in rate (KB/s) for slow-broker "
             "eligibility.", at_least(0))
    d.define("slow.broker.log.flush.time.threshold.ms", Type.DOUBLE, 1000.0,
             Importance.LOW, "Log-flush-time threshold for slowness.", at_least(0))
    d.define("slow.broker.metric.history.percentile.threshold", Type.DOUBLE, 90.0,
             Importance.LOW, "History percentile a slow broker must exceed.",
             between(0, 100))
    d.define("topic.anomaly.finder.class", Type.CLASS,
             "ccx.detector.detectors.TopicReplicationFactorAnomalyFinder",
             Importance.LOW, "TopicAnomalyFinder SPI.")
    d.define("target.topic.replication.factor", Type.INT, 0, Importance.LOW,
             "Desired RF for topic-anomaly detection; 0 disables the finder "
             "(ref: the RF finder is opt-in — an uninvited RF 'fix' can make "
             "rack-awareness infeasible).", at_least(0))
    d.define("maintenance.event.reader.class", Type.CLASS,
             "ccx.detector.detectors.NoopMaintenanceEventReader",
             Importance.LOW, "MaintenanceEventReader SPI.")
    d.define("provisioner.class", Type.CLASS,
             "ccx.detector.provisioner.BasicProvisioner", Importance.LOW,
             "Provisioner SPI behind the rightsize endpoint (ref C21).")
    d.define("anomaly.detection.allow.unready.cluster", Type.BOOLEAN, False,
             Importance.LOW, "Run detectors before monitor windows are ready.")
    d.define("detector.stream.enabled", Type.BOOLEAN, True, Importance.MEDIUM,
             "Enable the live-stream anomaly detector (ccx.detector.stream): "
             "classifies every serving window's flowing signals — heartbeat "
             "energy, warm-pressure bands, goal-violation and devmem gauges "
             "— and fires the SAME facade anomaly verbs as the queue path, "
             "at urgent priority, one verb per healing episode.")
    d.define("detector.stream.seed", Type.INT, 1729, Importance.LOW,
             "Seed for the stream detector's classification tie-breaks and "
             "forecast jitter; a fixed seed makes episode timelines "
             "bit-reproducible across identical runs.", at_least(0))
    d.define("detector.stream.clean.windows", Type.INT, 3, Importance.MEDIUM,
             "Consecutive violation-free windows required to declare an "
             "episode recovered. time-to-heal is stamped at the FIRST "
             "window of the clean streak, so raising this delays the "
             "verdict without inflating the healing metric.", at_least(1))
    d.define("detector.stream.pressure.threshold", Type.DOUBLE, 0.85,
             Importance.MEDIUM,
             "warm_pressure band above which a window is classified as "
             "pressure_surge (anomalous) even if it still verified.",
             between(0, 1))
    d.define("detector.stream.forecast.windows", Type.INT, 8, Importance.LOW,
             "History length (windows) for the drift-history forecaster's "
             "least-squares pressure slope.", at_least(2))
    d.define("detector.stream.forecast.horizon.windows", Type.INT, 6,
             Importance.LOW,
             "Look-ahead horizon: if the fitted pressure slope crosses the "
             "surge threshold within this many windows, the detector "
             "pre-warms placement bases via the PlacementStore ledger "
             "(priority touch) BEFORE the surge lands.", at_least(1))
    return d


def webserver_config_def() -> ConfigDef:
    d = ConfigDef()
    d.define("webserver.http.address", Type.STRING, "127.0.0.1", Importance.HIGH,
             "REST server bind address.")
    d.define("webserver.http.port", Type.INT, 9090, Importance.HIGH,
             "REST server port.", between(0, 65535))
    d.define("webserver.openapi.port", Type.INT, 0, Importance.LOW,
             "Port for the second, OpenAPI-contract-routed asyncio API "
             "surface (ref C36, the optional Vert.x module). 0 disables it "
             "(the upstream module is optional too); both surfaces share "
             "one dispatch/auth/review path so behavior cannot drift.",
             between(0, 65535))
    d.define("webserver.openapi.address", Type.STRING, "127.0.0.1",
             Importance.LOW, "Bind address for the OpenAPI surface.")
    d.define("webserver.api.urlprefix", Type.STRING, "/kafkacruisecontrol/*",
             Importance.LOW, "Endpoint URL prefix.")
    d.define("webserver.session.maxExpiryPeriodMs", Type.LONG, 60_000,
             Importance.LOW, "Session expiry for async request tracking.",
             at_least(1))
    d.define("webserver.request.maxBlockTimeMs", Type.LONG, 10_000,
             Importance.LOW, "Max time a request blocks before going async.",
             at_least(0))
    d.define("two.step.verification.enabled", Type.BOOLEAN, False, Importance.MEDIUM,
             "Park POSTs in purgatory until reviewed (ref C33).")
    d.define("two.step.purgatory.retention.time.ms", Type.LONG, 1_209_600_000,
             Importance.LOW, "Purgatory request retention.", at_least(1))
    d.define("two.step.purgatory.max.requests", Type.INT, 25, Importance.LOW,
             "Purgatory capacity.", at_least(1))
    d.define("webserver.security.enable", Type.BOOLEAN, False, Importance.MEDIUM,
             "Enable authentication/authorization (ref C34).")
    d.define("webserver.security.provider", Type.CLASS,
             "ccx.servlet.security.BasicSecurityProvider", Importance.MEDIUM,
             "SecurityProvider SPI.")
    d.define("webserver.auth.credentials.file", Type.STRING, "", Importance.MEDIUM,
             "Credentials file for the basic provider "
             "(user: password,ROLE per line); for the JWT provider it holds "
             "the HMAC signing secret.")
    d.define("webserver.trusted.proxy.ips", Type.LIST, ("127.0.0.1",),
             Importance.LOW, "Peer addresses allowed to assert principals "
             "via the trusted-proxy provider.")
    d.define("webserver.trusted.proxy.admin.principals", Type.LIST, (),
             Importance.LOW, "Principals granted ADMIN by the trusted-proxy "
             "provider (others get USER).")
    d.define("webserver.spnego.admin.principals", Type.LIST, (),
             Importance.LOW, "Kerberos principals granted ADMIN by the "
             "SPNEGO provider (others get USER).")
    d.define("webserver.spnego.service.name", Type.STRING, "HTTP",
             Importance.LOW, "GSSAPI hostbased service name the SPNEGO "
             "provider accepts tickets for.")
    d.define("vertx.api.enabled", Type.BOOLEAN, False, Importance.LOW,
             "Alternative API server flavor flag (ref C36; same endpoints).")
    return d


def user_task_manager_config_def() -> ConfigDef:
    d = ConfigDef()
    d.define("max.active.user.tasks", Type.INT, 25, Importance.MEDIUM,
             "Concurrent async user tasks.", at_least(1))
    d.define("max.cached.completed.user.tasks", Type.INT, 100, Importance.LOW,
             "Completed tasks kept for replay via user_tasks.", at_least(1))
    d.define("completed.user.task.retention.time.ms", Type.LONG, 86_400_000,
             Importance.LOW, "Completed task retention.", at_least(1))
    return d


def reporter_config_def() -> ConfigDef:
    """Broker-side metrics reporter keys (ref C37/M3)."""
    d = ConfigDef()
    d.define("metric.reporting.interval.ms", Type.LONG, 60_000, Importance.HIGH,
             "Reporter publish period inside each broker.", at_least(1))
    d.define("cruise.control.metrics.topic", Type.STRING,
             "__CruiseControlMetrics", Importance.MEDIUM,
             "Transport channel name for raw metric records.")
    return d


def cruise_control_config_def() -> ConfigDef:
    d = ConfigDef()
    d.define("bootstrap.servers", Type.STRING, "localhost:9092", Importance.HIGH,
             "Managed cluster contact point (simulated transport address for "
             "the in-process cluster).")
    d.define("cluster.configs.file", Type.STRING, "config/clusterConfigs.json",
             Importance.LOW, "Cluster-level config overrides file.")
    for sub in (
        monitor_config_def(),
        analyzer_config_def(),
        observability_config_def(),
        executor_config_def(),
        anomaly_detector_config_def(),
        webserver_config_def(),
        user_task_manager_config_def(),
        reporter_config_def(),
    ):
        d.merge(sub)
    return d


class CruiseControlConfig:
    """Parsed, validated configuration (ref KafkaCruiseControlConfig).

    ``cfg[key]`` returns the typed value; ``configured_instance(key)``
    instantiates a class-valued key, passing this config to the constructor
    (or calling a no-arg constructor, then ``configure(cfg)`` if defined) —
    the reference's reflective SPI pattern.
    """

    def __init__(self, props: dict[str, Any] | None = None,
                 definition: ConfigDef | None = None) -> None:
        self.definition = definition or cruise_control_config_def()
        self.originals = dict(props or {})
        self._values = self.definition.parse(self.originals)

    #: file-valued keys resolved relative to the properties file's directory
    PATH_KEYS = (
        "capacity.config.file",
        "cluster.configs.file",
        "webserver.auth.credentials.file",
        "failed.brokers.file.path",
    )

    @classmethod
    def from_properties_file(cls, path: str) -> "CruiseControlConfig":
        import os

        props = load_properties(path)
        base = os.path.dirname(os.path.abspath(path))
        for key in cls.PATH_KEYS:
            v = props.get(key)
            if v and not os.path.isabs(v):
                # Relative paths in a properties file mean "relative to the
                # file", not to whatever cwd the service was launched from.
                candidate = os.path.normpath(os.path.join(base, v))
                parent = os.path.normpath(os.path.join(base, "..", v))
                props[key] = candidate if os.path.exists(candidate) else (
                    parent if os.path.exists(parent) else candidate
                )
        return cls(props)

    def __getitem__(self, key: str) -> Any:
        try:
            return self._values[key]
        except KeyError:
            raise ConfigException(f"Unknown configuration {key!r}") from None

    def get(self, key: str, default: Any = None) -> Any:
        return self._values.get(key, default)

    def __contains__(self, key: str) -> bool:
        return key in self._values

    def with_overrides(self, **overrides: Any) -> "CruiseControlConfig":
        """Per-request parameter overrides (ref C32 parameters/)."""
        props = dict(self.originals)
        props.update({k.replace("_", "."): v for k, v in overrides.items()})
        return CruiseControlConfig(props, self.definition)

    def configured_instance(self, key: str, *args: Any, **kwargs: Any) -> Any:
        from ccx.config.definition import resolve_class

        cls = self[key]
        if cls is None:
            return None
        if isinstance(cls, str):
            cls = resolve_class(cls)
        try:
            obj = cls(*args, config=self, **kwargs)
        except TypeError:
            obj = cls(*args, **kwargs)
        if hasattr(obj, "configure"):
            obj.configure(self)
        return obj

    def configured_instances(self, key: str, *args: Any) -> list[Any]:
        out = []
        for path in self[key]:
            from ccx.config.definition import resolve_class

            cls = resolve_class(path) if isinstance(path, str) else path
            try:
                obj = cls(*args, config=self)
            except TypeError:
                obj = cls(*args)
            if hasattr(obj, "configure"):
                obj.configure(self)
            out.append(obj)
        return out

    def values(self) -> dict[str, Any]:
        return dict(self._values)
