"""The optimizer façade — GoalOptimizer, TPU-native.

Parity: ``analyzer/GoalOptimizer.optimizations(clusterModel, goalsByPriority,
progress)`` (SURVEY.md C14) is the reference's entry point; it returns an
``OptimizerResult`` carrying execution proposals, per-goal stats deltas and a
violation summary. This module is that entry point for the tensor model:

    1. batched simulated annealing over the full goal stack (ccx.search),
    2. a greedy lexicographic polish pass that repairs residual hard
       violations and low-tier regressions without breaking higher goals
       (the analogue of the reference's sequential per-goal optimization),
    3. diff into ExecutionProposals + verification + result summary.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time

from ccx.common import costmodel
from ccx.common.profiling import annotate
from ccx.common.tracing import TRACER
from ccx.goals.base import GOAL_REGISTRY, GoalConfig
from ccx.goals.stack import (
    DEFAULT_GOAL_ORDER,
    INTRA_BROKER_GOAL_ORDER,
    StackResult,
)
from ccx.model.stats import ClusterModelStats, balancedness_score, cluster_model_stats
from ccx.model.tensor_model import TensorClusterModel
from ccx.proposals import ColumnarDiff, ExecutionProposal, columnar_diff
from ccx.goals.stack import evaluate_stack
from ccx.search.annealer import (
    AnnealOptions,
    allows_inter_broker,
    anneal,
    hot_partition_list_device,
)
from ccx.search.greedy import (
    GreedyOptions,
    SwapPolishOptions,
    greedy_optimize,
    swap_polish,
)
from ccx.search.incremental import (
    ColdStartRequired,
    IncrementalOptions,
    WarmStart,
)
from ccx.search.repair import (
    finalize_preferred_leaders,
    hard_repair,
    hard_repair_async,
    topic_rebalance,
)
from ccx.verify import Verification, verify_optimization


@dataclasses.dataclass
class OptimizerResult:
    """Parity: ``analyzer/OptimizerResult.java`` (SURVEY.md C20).

    Columnar-first since round 15: ``diff`` (a ``ccx.proposals.
    ColumnarDiff``) is the canonical movement representation — flat int32
    columns straight off the device diff program. The row
    ``ExecutionProposal`` list is the LAZY ``proposals`` property, built
    only when a consumer actually wants rows (executor hand-off, row-mode
    wire results); movement counters are vectorized over the columns, so
    an ``include_proposals=False`` serialization never walks ~62k Python
    objects at B5."""

    diff: ColumnarDiff
    stack_before: StackResult
    stack_after: StackResult
    verification: Verification
    model: TensorClusterModel
    wall_seconds: float
    n_sa_accepted: int
    n_polish_moves: int
    phase_seconds: dict = dataclasses.field(default_factory=dict)
    #: per-move-type proposal/acceptance counts summed over every search
    #: phase executed (SA + polishes + swap-polish + leader pass; engine
    #: activity, not output-plan attribution) — keyed by
    #: ccx.search.state.MOVE_KIND_NAMES. Rides BENCH_*.json so frontier
    #: regressions (e.g. a swap acceptance collapse) are diagnosable from
    #: artifacts alone.
    move_counters: dict = dataclasses.field(default_factory=dict)
    #: completed span tree of this optimize() call (ccx.common.tracing):
    #: per-phase wall + chunk progress + compile attribution, the
    #: flight-recorder view that rides BENCH lines and the sidecar result.
    #: Volatile (timings) — stripped from golden wire fixtures.
    span_tree: dict | None = None
    #: device cost observatory block (ccx.common.costmodel): captured XLA
    #: FLOPs/bytes/HBM per program executed by this run, roofline
    #: projections (live device + v5e/v5p), per-phase rollup. Rides BENCH
    #: lines and the sidecar result; VOLATILE in golden wire fixtures
    #: (machine-dependent by construction).
    cost_model: dict | None = None
    #: mesh block (present only on mesh-sharded runs): mesh shape, device
    #: count and the live sharded-program cache occupancy
    #: (ccx.parallel.sharding.program_cache_stats). VOLATILE in golden
    #: wire fixtures, like spanTree/costModel.
    mesh: dict | None = None
    #: incremental re-optimization block (ccx.search.incremental, ISSUE
    #: 10): present on warm-started runs ({"warmStart": true, session,
    #: baseGeneration, touchedBrokers, driftPartitions, plateau, ...})
    #: and on cold runs that were REQUESTED warm but fell back
    #: ({"coldStart": true, "reason": ...}). Rides BENCH lines and the
    #: sidecar result; VOLATILE in golden wire fixtures (run-trajectory
    #: data, like convergence).
    incremental: dict | None = None
    #: convergence-telemetry block (ccx.search.telemetry, ISSUE 9):
    #: ``{"goals": [...], "phases": {phase: [segment, ...]}}`` — per-chunk
    #: per-goal lex cost series + cumulative move counters + temperature
    #: for every chunk-driven search phase this run executed (a phase that
    #: ran several engine invocations, e.g. repair-round re-polishes,
    #: carries one segment per invocation). The budget advisor
    #: (tools/convergence_report.py) and the bench ledger's plateau
    #: columns consume it. Rides BENCH lines and the sidecar result;
    #: VOLATILE in golden wire fixtures (run-trajectory data). None with
    #: taps off (observability.convergence=false) or fully-monolithic
    #: engine configs.
    convergence: dict | None = None
    #: input placement, kept so the ClusterModelStats blocks (ref
    #: model/ClusterModelStats.java, SURVEY.md C4) can be derived lazily —
    #: computing them costs an aggregate pass + host transfer, which must not
    #: tax callers (bench hot path) that never read the stats.
    input_model: TensorClusterModel | None = None
    #: movement plan (ccx.search.movement.MovementPlan, ISSUE 17): the
    #: diff wave-scheduled under executor caps/throttle budgets. Present
    #: only when ``optimizer.plan.enabled`` — absent ⇒ legacy executor
    #: greedy batching (fixtures byte-stable). Summary rides ``to_json``
    #: as the additive ``plan`` block; the row-aligned wave arrays ride
    #: the columnar result path (``planColumnar``, wire round 20).
    plan: object | None = None
    #: warm-path only: the f32[6, B] band-pressure DEVICE stack of the
    #: shipped placement under the shipped metrics — the next window's
    #: delta cache, computed by the fused ``incremental.warm_finish``
    #: program alongside the result stack. Callers banking the result
    #: (``incremental.remember``) pass it through so the bank costs zero
    #: extra device work. Never serialized (see ``to_json``).
    warm_pressure: object | None = None

    @property
    def stats_before(self) -> ClusterModelStats | None:
        if self.input_model is None:
            return None
        if not hasattr(self, "_stats_before"):
            self._stats_before = cluster_model_stats(self.input_model)
        return self._stats_before

    @property
    def stats_after(self) -> ClusterModelStats | None:
        if not hasattr(self, "_stats_after"):
            self._stats_after = cluster_model_stats(self.model)
        return self._stats_after

    @property
    def proposals(self) -> list[ExecutionProposal]:
        """Row view of the diff — materialized on first access."""
        return self.diff.rows

    @property
    def num_replica_movements(self) -> int:
        # vectorized over the columns — include_proposals=False callers
        # (warm minimal-diff windows) never materialize the row list
        return self.diff.num_replica_movements

    @property
    def num_leadership_movements(self) -> int:
        return self.diff.num_leadership_movements

    def violation_summary(self) -> dict[str, float]:
        return {n: v for n, (v, _) in self.stack_after.by_name().items() if v > 0}

    def goal_summary_columnar(self) -> dict:
        """``goalSummary`` as flat typed arrays (wire round 15): one
        vector per column instead of G per-goal dict maps, so streamed
        frame packing builds no per-goal Python objects. Values are f32
        on the wire (like every load tensor); the goal names ride as a
        plain list."""
        import numpy as np

        before = self.stack_before.by_name()
        after = self.stack_after.by_name()
        names = list(self.stack_after.names)
        return {
            "goal": names,
            "hard": np.array(
                [bool(GOAL_REGISTRY[n].hard) for n in names], np.uint8
            ),
            "violationsBefore": np.array(
                [before[n][0] for n in names], np.float32
            ),
            "violationsAfter": np.array(
                [after[n][0] for n in names], np.float32
            ),
            "costBefore": np.array(
                [before[n][1] for n in names], np.float32
            ),
            "costAfter": np.array(
                [after[n][1] for n in names], np.float32
            ),
        }

    def to_json(
        self,
        include_proposals: bool = True,
        include_stats: bool = True,
        include_goal_summary: bool = True,
    ) -> dict:
        """``include_stats=False`` omits the ClusterModelStats blocks —
        they cost one full aggregate pass + bulk device->host transfer
        EACH for before/after (~260 ms at B5 on CPU), which would
        dominate a <500 ms steady-state warm re-proposal. The sidecar
        passes False for warm-started results (the minimal-diff
        contract: a steady-state window consumes the diff and the goal
        summary; full distribution stats ride the cold proposals and the
        load endpoint)."""
        before = self.stack_before.by_name()
        after = self.stack_after.by_name()
        return {
            # columnar consumers (sidecar columnar_proposals) skip the 60k+
            # per-proposal dict materialization entirely
            **(
                {"proposals": self.diff.rows_json()}
                if include_proposals
                else {}
            ),
            "numReplicaMovements": self.num_replica_movements,
            "numLeadershipMovements": self.num_leadership_movements,
            # streamed columnar results (wire round 15) ship the summary
            # as flat typed arrays instead — include_goal_summary=False
            # skips building the per-goal dicts only to discard them
            **(
                {
                    "goalSummary": [
                        {
                            "goal": n,
                            "hard": GOAL_REGISTRY[n].hard,
                            "violationsBefore": before[n][0],
                            "violationsAfter": after[n][0],
                            "costBefore": before[n][1],
                            "costAfter": after[n][1],
                        }
                        for n in self.stack_after.names
                    ]
                }
                if include_goal_summary
                else {}
            ),
            "verified": self.verification.ok,
            "verificationFailures": self.verification.failures,
            "optimizationFailures": self.verification.infeasible,
            "wallSeconds": self.wall_seconds,
            # per-phase wall split (bench sidecar mode budgets the T1 wire
            # path phase by phase; cheap to carry — a dozen floats)
            "phaseSeconds": {
                k: round(v, 3) for k, v in self.phase_seconds.items()
            },
            "moveCounters": self.move_counters,
            # additive (wire round 20): present only with the planner
            # armed (optimizer.plan.enabled) — legacy fixtures byte-stable
            **(
                {"plan": self.plan.summary_json()}
                if self.plan is not None
                else {}
            ),
            **({"spanTree": self.span_tree} if self.span_tree else {}),
            **({"costModel": self.cost_model} if self.cost_model else {}),
            **({"mesh": self.mesh} if self.mesh else {}),
            **({"incremental": self.incremental} if self.incremental else {}),
            **({"convergence": self.convergence} if self.convergence else {}),
            **(
                {
                    "clusterModelStats": {
                        "before": self.stats_before.to_json(),
                        "after": self.stats_after.to_json(),
                    },
                    "onDemandBalancednessScoreBefore": balancedness_score(
                        self.stats_before
                    ),
                    "onDemandBalancednessScoreAfter": balancedness_score(
                        self.stats_after
                    ),
                }
                if include_stats
                and self.stats_before is not None
                and self.stats_after is not None
                else {}
            ),
        }


@dataclasses.dataclass(frozen=True)
class OptimizeOptions:
    anneal: AnnealOptions = AnnealOptions()
    polish: GreedyOptions = GreedyOptions(n_candidates=256, max_iters=400)
    run_polish: bool = True
    #: extra polish rounds while hard violations remain — each round rebuilds
    #: the hot-partition list from the current placement so the remaining
    #: offenders are targeted (SURVEY.md section 7.4 repair passes)
    max_repair_rounds: int = 3
    require_hard_zero: bool = True
    #: disable for disk-only stacks — intra-broker moves cannot evacuate
    #: a dead broker
    check_evacuation: bool = True
    #: run a leadership-only greedy sweep as the LAST pipeline stage (ref:
    #: PreferredLeaderElectionGoal runs last in the goal order, SURVEY.md
    #: section 2.3): single leadership transfers + count-preserving
    #: leadership rotations, lex-guarded against the full stack, so the
    #: pipeline never ends with fixable preferred-leader / leader-balance
    #: debris. Skipped automatically for intra-broker (disk-only) stacks.
    run_leader_pass: bool = True
    #: sweep+polish rounds for the targeted TopicReplicaDistribution stage
    #: (repair.topic_rebalance): each round enumerates over-band
    #: (topic, broker) cells directly, re-polishes, and is adopted only on
    #: full-vector lex improvement. Iterating ratchets: the re-polish may
    #: trade some of the sweep's TRD cut back for higher-tier (usage)
    #: gains — legitimate under goal priority — but each cycle leaves the
    #: higher tiers closer to their floor, so the next sweep's cut sticks
    #: better. 0 disables. Cost per round: one topic_rebalance call
    #: (bounded by topic_rebalance_max_sweeps below — a converged round is
    #: ~14 s / 43k moves at B5) + one polish run.
    topic_rebalance_rounds: int = 2
    #: per-round sweep cap for repair.topic_rebalance. The sweep loop is
    #: self-limiting (stops at moved==0), so this is a latency bound, not a
    #: convergence knob: 1024 lets a round run to convergence (B5 from a
    #: raw snapshot: 43k moves / ~14 s, TRD 45.8k -> 10.4k WITH usage and
    #: rack side-improvements — round 4 measured; the old 16 was starving
    #: the shed at ~5k moves). Latency-critical callers lower it.
    topic_rebalance_max_sweeps: int = 1024
    #: let the TRD shed move leader-held over cells via leadership transfer
    #: (repair.topic_rebalance move_leaders). Measured at B5 full effort:
    #: TRD end state 13.7k -> 5.7k with leader tiers BETTER (the final
    #: leader pass rebalances what the transfers disturb). At shallow sweep
    #: budgets the transfers crowd out cheaper follower moves — the bench
    #: lean rung disables this and keeps the followers-only shed.
    topic_rebalance_move_leaders: bool = True
    #: run each round's re-polish with the greedy trd-guard first (veto
    #: moves that worsen the TopicReplicaDistribution tier), falling back to
    #: an unguarded polish when the guarded one fails lex adoption. The
    #: guard keeps the usage re-polish from trading the shed's topic cells
    #: back — the round-4 loss mechanism (raw converged shed TRD 24 vs 6.7k
    #: surviving the unguarded re-polish). False restores round-4 mechanics.
    topic_rebalance_guarded: bool = True
    #: iteration budget for the stage's re-polish (None = inherit
    #: polish.max_iters). A converged leader-ful shed relocates ~55k
    #: replicas at B5 — the post-shed cleanup needs MORE budget than the
    #: pre-shed polish, so latency-tuned callers shift iters here (the
    #: bench lean rung runs a small pre-shed polish + a larger guarded
    #: re-polish at equal total budget).
    topic_rebalance_polish_iters: int | None = None
    #: optional iteration cap for the final leadership-only pass (None =
    #: inherit polish.max_iters). Measured at B5 full effort: leadership-only
    #: iterations are CHEAP (~11 ms vs ~70 ms placement polish) and the pass
    #: keeps finding work deep into a 1600-iter budget (LeaderReplica
    #: violations 450 capped at 400 iters vs 108 uncapped, for <10 s of
    #: wall) — so the default is uncapped; the knob exists for
    #: latency-critical callers.
    leader_pass_max_iters: int | None = None
    #: iteration budget for the usage-coupled swap-polish phase (config
    #: `optimizer.swap.polish.iters`; 0 disables). Runs AFTER the
    #: topic-rebalance stage (so it polishes whatever the guarded re-polish
    #: left) and BEFORE the leadership pass (which cleans up the
    #: preferred-leader debris leadership-bearing swaps create). Pure lex
    #: descent over count-preserving replica swaps + pressure-coupled
    #: leadership transfers (ccx.search.greedy.swap_polish) — the move
    #: class the residual NwOut/LeaderReplica cells need (VERDICT r5 #4).
    swap_polish_iters: int = 0
    #: iteration budget for the SECOND swap-polish invocation, run AFTER
    #: the leadership pass (config `optimizer.swap.polish.post.iters`;
    #: 0 disables). Measured at B5: the leader pass leaves LeaderReplica/
    #: LeaderBytesIn cells whose fix needs the coupled draw (pressure-
    #: ranked low-usage-delta transfers + complementary swaps) — 300 post
    #: iters took LR 599 -> 239 and LBI 631 -> 271 in ~10 s where the
    #: uniform leader pass had stalled. Shares the pre-leader stage's
    #: compiled program (same candidate shape).
    swap_polish_post_iters: int = 0
    #: coupled candidates per swap-polish iteration (static program
    #: shape), split evenly between replica-swap pairs and leadership
    #: transfers so both invocations share ONE compiled program
    swap_polish_candidates: int = 128
    #: iterations per jitted swap-polish chunk program (config
    #: `optimizer.swap.polish.chunk.iters`; SwapPolishOptions.chunk_iters).
    #: 0 = monolithic while_loop. Budgets stay traced; only this is shape.
    swap_polish_chunk_iters: int = 50
    #: veto swap-polish candidates that significantly worsen the
    #: TopicReplicaDistribution tier (different-topic swaps move topic
    #: cells; the guard keeps a converged shed's TRD=0 from being traded
    #: back for usage cells — same rationale as topic_rebalance_guarded)
    swap_polish_guarded: bool = True
    #: hard_repair loop driver (config `optimizer.repair.backend`):
    #: "device" (default) runs the whole sweep loop as ONE compiled program
    #: with a traced sweep budget and feeds its lazy outputs straight into
    #: the annealer — no per-sweep host syncs, no host-blocking repair
    #: phase (repair's device time folds into the anneal dispatch queue;
    #: the phase split reports only the dispatch cost). "host" restores the
    #: round-2 python loop (one jitted sweep + one sync per iteration) —
    #: the fallback for environments where the fused program misbehaves,
    #: and the parity reference (tests/test_repair.py).
    repair_backend: str = "device"
    #: overlap hard repair with the FIRST SA chunk: repair runs in a
    #: background thread while the first `anneal.chunk_steps` steps anneal
    #: the still-infeasible input state; the two candidates then merge via
    #: the pipeline's lex-adoption rule (`_lex_better`) and the remaining
    #: steps continue from the winner (in practice the repaired state — SA
    #: cannot zero thousands of hard violations in one chunk). This buys
    #: wall-clock only where repair executes outside the device stream the
    #: SA chunk occupies (the host numpy fallback of a future
    #: non-vectorizable repair, multi-core CPU hosts); on a single-stream
    #: device the two serialize, which is why the DEFAULT path is the
    #: pipelined device repair above instead. Requires chunked SA with
    #: n_steps > chunk_steps; silently skipped otherwise.
    overlap_repair: bool = False
    #: also run the pure greedy oracle from the input placement and return
    #: the lexicographic winner — the portfolio pattern of the reference's
    #: GoalOptimizer, which precomputes candidate proposals and serves the
    #: best (SURVEY.md C14/section 2.5). Guarantees the pipeline never
    #: returns a result lexicographically worse than a plain greedy run of
    #: the same budget. Cost: one extra run at the polish budget per
    #: optimize() call (roughly doubles the polish phase) — the facade
    #: disables it for leadership-/disk-only fast paths and exposes
    #: ``optimizer.portfolio.cold.greedy`` for latency-sensitive callers.
    run_cold_greedy: bool = True
    #: run the SA phase sharded over a device mesh (config
    #: ``optimizer.mesh.enabled``): chains ride the mesh as data
    #: parallelism and, with ``mesh_parts > 1``, the model's partition
    #: axis is sharded inside the search (ccx.parallel.sharding — the B6
    #: axis). The mesh path is CHUNK-DRIVEN like the single-chip anneal
    #: (bounded compile, per-chunk heartbeats, cost capture); after the
    #: anneal the winning placement is re-homed to the default device so
    #: every downstream phase shares the single-chip compiled programs.
    #: Ignored (with a log note) when fewer than two devices are visible.
    mesh_enabled: bool = False
    #: devices for the mesh; 0 = all visible (config
    #: ``optimizer.mesh.devices``)
    mesh_devices: int = 0
    #: partition-axis factor of the mesh — chains = devices // parts
    #: (config ``optimizer.mesh.parts``). 1 = chains-only data
    #: parallelism; raise for clusters whose model shards (100k+
    #: partitions) dominate chain parallelism.
    mesh_parts: int = 1
    #: incremental re-optimization knobs (ccx.search.incremental, ISSUE
    #: 10; config ``optimizer.incremental.*``): governs the warm pipeline
    #: entered via ``optimize(warm_start=...)``. Inert on cold runs — the
    #: default IncrementalOptions() keeps every cold program bit-exact.
    incremental: IncrementalOptions = dataclasses.field(
        default_factory=IncrementalOptions
    )
    #: movement planning (ccx.search.movement; config ``optimizer.plan.*``):
    #: wave-schedule the columnar diff into throttle-respecting execution
    #: waves and surface them as the additive ``OptimizerResult.plan``
    #: block. Default OFF — the plan-off path is bit-exact with the
    #: pre-plan pipeline and compiles nothing new.
    plan_enabled: bool = False
    #: append the movement-cost tier (bytes moved, peak per-broker inflow
    #: vs the input placement) to the lexicographic portfolio adoption —
    #: a quality TIE between candidates resolves toward the cheaper
    #: schedule. Default OFF (bit-exact; the cost programs never compile).
    plan_cost_tier: bool = False
    #: wave-planner shape/limits (PlanOptions mirrors): static wave-axis
    #: size of the compiled scheduler state — raising it is a new program
    #: shape, so it is config, not per-request data
    plan_max_waves: int = 64
    #: per-broker concurrent-move cap per wave (mirrors
    #: ``num.concurrent.partition.movements.per.broker``); traced data
    plan_broker_cap: int = 5
    #: per-broker per-wave byte budget in model load units (MB), the
    #: replication-throttle image; <=0 = uncapped (count caps only);
    #: traced data
    plan_wave_bytes_mb: float = 0.0
    #: projected per-broker replication rate for wave-duration seconds;
    #: <=0 reports relative byte units; traced data (never shape)
    plan_throttle_mb_per_sec: float = 0.0


def prewarm_options(opts: OptimizeOptions) -> OptimizeOptions:
    """Floor every traced budget in ``opts`` so one ``optimize()`` call
    compiles the pipeline's full program set at minimal execution cost.

    Iteration budgets are loop-bound DATA throughout the pipeline (greedy
    max_iters/patience, the repair sweep budget, SA step counts via fixed
    chunking, the polish/swap-polish chunk engines — only chunk_iters is
    shape), so a floored run traces and compiles the SAME programs the
    real budgets execute: repair loop, device hot list, chain init, one SA
    chunk, one polish chunk + trd-guarded re-polish (guard is traced), one
    swap-polish chunk, the leadership-only pass (its own program —
    leadership_only is shape), and diff/verify. bench.py runs this once before the effort ladder — on TPU
    a cold full-budget run risks the driver timeout landing mid-compile
    (the round-4 window lost >17 min to one greedy compile); the prewarm
    pass pays compiles at one-chunk/one-iter execution cost and fills the
    persistent cache for every later rung that shares the shape.
    """
    anneal = dataclasses.replace(
        opts.anneal,
        # one full-size chunk compiles the program every later chunk
        # reuses; budgets at or below one chunk already run the minimal
        # program (the chunk is sized min(chunk_steps, n_steps))
        n_steps=(
            opts.anneal.chunk_steps
            if 0 < opts.anneal.chunk_steps < opts.anneal.n_steps
            else opts.anneal.n_steps
        ),
    )
    polish = dataclasses.replace(opts.polish, max_iters=1, patience=1)
    return dataclasses.replace(
        opts,
        anneal=anneal,
        polish=polish,
        max_repair_rounds=1,
        # the swap-polish budget is while_loop data too — one floored
        # iteration compiles the program every real budget reuses (both
        # invocations share it, so the post stage needs no extra pass)
        swap_polish_iters=min(
            max(opts.swap_polish_iters, opts.swap_polish_post_iters), 1
        ),
        swap_polish_post_iters=0,
        # one sweep round compiles nothing extra (host numpy) but exercises
        # the guarded re-polish adoption path end-to-end
        topic_rebalance_rounds=min(opts.topic_rebalance_rounds, 1),
        topic_rebalance_max_sweeps=1,
        topic_rebalance_polish_iters=None,
        leader_pass_max_iters=1 if opts.leader_pass_max_iters else None,
    )


def _make_run_mesh(opts: OptimizeOptions):
    """Build the run mesh from ``opts.mesh_*`` (None = run single-device).

    Degrades with a log note instead of aborting: fewer than two visible
    devices, or a parts factor that does not divide the device count,
    must never kill a proposal — the single-chip path is always correct.
    """
    import logging

    import jax

    from ccx.parallel.sharding import make_mesh

    log = logging.getLogger(__name__)
    devices = jax.devices()
    if opts.mesh_devices > 0:
        devices = devices[: opts.mesh_devices]
    if len(devices) < 2:
        log.warning(
            "optimizer.mesh.enabled but only %d device(s) visible; "
            "running single-device", len(devices),
        )
        return None
    parts = max(int(opts.mesh_parts), 1)
    if len(devices) % parts:
        log.warning(
            "optimizer.mesh.parts=%d does not divide %d devices; "
            "falling back to chains-only (parts=1)", parts, len(devices),
        )
        parts = 1
    return make_mesh(devices, parts=parts)


#: goals a leadership-only move can improve — stacks scoring none of these
#: skip the final leadership pass (it could only burn a compile + budget)
LEADERSHIP_GOALS = frozenset(
    {
        "PreferredLeaderElectionGoal",
        "LeaderReplicaDistributionGoal",
        "LeaderBytesInDistributionGoal",
        "MinTopicLeadersPerBrokerGoal",
        "KafkaAssignerEvenRackAwareGoal",
    }
)


def _lex_better(a: StackResult, b: StackResult) -> bool:
    """True when a's (hard-violations, cost-vector) beats b's
    lexicographically (hard feasibility always outranks soft tiers)."""
    import numpy as np

    ka = (float(a.hard_violations),) + tuple(float(x) for x in np.asarray(a.costs))
    kb = (float(b.hard_violations),) + tuple(float(x) for x in np.asarray(b.costs))
    tol = 1e-6
    for x, y in zip(ka, kb):
        if x < y - tol:
            return True
        if x > y + tol:
            return False
    return False


def _movement_lex_better(
    a_stack, a_model, b_stack, b_model, m, opts: "OptimizeOptions"
) -> bool:
    """``_lex_better`` with the movement-cost tier appended (ISSUE 17,
    ``optimizer.plan.cost.tier``): the quality tiers decide first — only
    a full lexicographic TIE falls through to (bytes moved, peak
    per-broker inflow) of each candidate vs the input placement ``m``,
    so equally-good placements resolve toward the cheaper execution.
    With the gate off this IS ``_lex_better`` (bit-exact, and the
    movement-cost program is never traced, let alone compiled)."""
    if _lex_better(a_stack, b_stack):
        return True
    if not opts.plan_cost_tier or _lex_better(b_stack, a_stack):
        return False
    from ccx.search.movement import movement_cost

    tol = 1e-6
    ca = movement_cost(m, a_model)
    cb = movement_cost(m, b_model)
    for x, y in zip(ca, cb):
        if x < y - tol:
            return True
        if x > y + tol:
            return False
    return False


def _compute_plan(m, dcols, opts: "OptimizeOptions"):
    """The plan phase (``optimizer.plan.enabled``): wave-schedule the
    shipped diff under executor caps/throttle budgets (ccx.search.
    movement). Planning is advisory bookkeeping for the executor — any
    failure logs and ships the proposal without a plan (legacy greedy
    batching), never fails the optimize."""
    import numpy as np

    from ccx.common.resources import Resource
    from ccx.search.movement import PlanOptions, plan_movement

    try:
        return plan_movement(
            dcols,
            np.asarray(m.leader_load[Resource.DISK]),
            int(m.B),
            PlanOptions(
                broker_cap=opts.plan_broker_cap,
                wave_bytes=opts.plan_wave_bytes_mb,
                max_waves=opts.plan_max_waves,
                throttle_mb_per_sec=opts.plan_throttle_mb_per_sec,
            ),
        )
    except Exception:  # noqa: BLE001 — plan must never fail a proposal
        import logging

        logging.getLogger(__name__).exception(
            "movement planning failed; shipping proposal without a plan"
        )
        return None


def optimize(
    m: TensorClusterModel,
    cfg: GoalConfig = GoalConfig(),
    goal_names: tuple[str, ...] = DEFAULT_GOAL_ORDER,
    opts: OptimizeOptions = OptimizeOptions(),
    progress_cb=None,
    job: tuple[str, int] | str | None = None,
    warm_start: WarmStart | None = None,
    cancel: threading.Event | None = None,
) -> OptimizerResult:
    """Full-stack proposal computation (reference call stack 3.2, L3a part).

    Pipeline (mirrors the reference's sequential-goal semantics, SURVEY.md
    §7.4): (1) vectorized hard-goal repair sweeps establish feasibility
    exactly — the analogue of the hard goals' own optimize() passes; (2)
    batched SA balances the soft goals without breaking hard ones; (3) a
    greedy polish + repair loop cleans up residuals.

    ``progress_cb(phase: str)`` is invoked as each phase *starts* — the
    analogue of the reference's OperationProgress steps; bench/servlet use it
    so a timed-out run still shows which phase it died in. The whole call
    runs under a tracing root span (ccx.common.tracing): every phase is a
    child span, chunk heartbeats stream to the flight recorder when armed,
    and the completed tree rides out as ``OptimizerResult.span_tree`` — so
    even a run that never returns leaves its diagnosis on disk.

    ``job`` is the fleet entry point (ccx.search.scheduler): a cluster id
    (or ``(cluster_id, priority)``) registers this call on the multi-job
    chunk scheduler for its whole duration — every chunk drive inside
    interleaves with other registered jobs at chunk boundaries, and all
    spans/heartbeats/histograms carry ``job=<cluster-id>``. None (the
    default) runs unscheduled; with no other job registered the scheduled
    path is bit-exact vs unscheduled (grants only order dispatches).

    ``warm_start`` (a ``ccx.search.incremental.WarmStart``, ISSUE 10)
    enters the incremental re-optimization pipeline when
    ``opts.incremental`` is armed: the previous converged placement is
    grafted onto this snapshot's metrics, only the drift-touched bands
    are re-scored, the search runs a short plateau-terminated warm
    budget, and the result's proposals are the minimal diff. Falls back
    to the cold pipeline (with ``OptimizerResult.incremental`` naming the
    reason) when the warm base cannot be applied. Steady-state warm jobs
    register on the fleet scheduler exactly like cold ones — same
    ``job=`` path, same priority/residency rules.
    """
    if job is not None:
        from ccx.search.scheduler import FLEET

        cluster_id, priority = (
            job if isinstance(job, tuple) else (job, 0)
        )
        # ``cancel`` (a threading.Event the transport sets on client
        # disconnect — ccx.sidecar.server wires gRPC context.add_callback
        # to it) cancels the job at the next chunk-boundary grant
        # (scheduler.JobCancelled); the job context's exit then frees the
        # grant and residency slot on the way out.
        with FLEET.job(str(cluster_id), int(priority),
                       cancel_event=cancel):
            return optimize(
                m, cfg, goal_names, opts, progress_cb,
                warm_start=warm_start,
            )
    cost0 = costmodel.exec_snapshot()
    warm = warm_start if (
        warm_start is not None and opts.incremental.armed
    ) else None
    root = TRACER.start(
        "optimize", kind="op",
        P=int(m.P), B=int(m.B), goals=len(goal_names),
        **({"warm": True} if warm is not None else {}),
    )
    cold_reason = None
    try:
        res = None
        if warm is not None:
            try:
                res = _optimize_warm(m, cfg, goal_names, opts, progress_cb,
                                     warm)
            except ColdStartRequired as e:
                cold_reason = str(e)
        if res is None:
            res = _optimize(m, cfg, goal_names, opts, progress_cb)
            if cold_reason is not None:
                res = dataclasses.replace(
                    res,
                    incremental={
                        "warmStart": False, "coldStart": True,
                        "reason": cold_reason,
                    },
                )
    finally:
        # the root MUST close on every exit path — a leaked root would nest
        # every later call on this thread under a dead tree
        TRACER.end(root)
    # span_tree is rendered AFTER the run's cost-capture phase flushed the
    # ledger, so even the cold run's phase spans price their programs; the
    # costModel block rolls the same ledger up per program and per phase
    tree = root.to_json()
    cost_model = costmodel.cost_model_json(costmodel.exec_delta(cost0), tree)
    return dataclasses.replace(res, span_tree=tree, cost_model=cost_model)


def _optimize(
    m: TensorClusterModel,
    cfg: GoalConfig,
    goal_names: tuple[str, ...],
    opts: OptimizeOptions,
    progress_cb,
) -> OptimizerResult:
    # chaos seam (ccx.common.faults): a cold pipeline entry stands in for
    # a failed/wedged XLA compile — the RPC fails structured, the client
    # retries, the sidecar's state is untouched (nothing banked yet)
    from ccx.common.faults import FAULTS as _FAULTS

    if _FAULTS.armed:
        _FAULTS.hit("compile")
    t0 = time.monotonic()
    phases: dict[str, float] = {}
    kind_prop = [0, 0, 0]
    kind_acc = [0, 0, 0]
    #: per-phase convergence-telemetry segments (ccx.search.telemetry):
    #: every chunk-driven engine result contributes its decoded per-chunk
    #: series under the pipeline phase that ran it
    conv_phases: dict[str, list] = {}

    def _tally(r, phase: str | None = None) -> None:
        """Accumulate a search result's per-move-kind counters and (when
        the convergence taps were armed) its telemetry segment."""
        for i in range(3):
            kind_prop[i] += int(r.n_prop_kind[i])
            kind_acc[i] += int(r.n_acc_kind[i])
        conv = getattr(r, "convergence", None)
        if phase is not None and conv:
            conv_phases.setdefault(phase, []).append(conv)

    @contextlib.contextmanager
    def _phase(name: str, **attrs):
        """One pipeline phase: OperationProgress callback, tracing span
        (flight-recorder record; drive_chunks heartbeats attach here),
        XProf annotation, and the phase_seconds entry. phase_seconds is
        taken from the CLOSED span so observability.trace.sync makes the
        headline per-phase numbers device-honest too, not just the tree."""
        if progress_cb is not None:
            progress_cb(name)
        s = TRACER.start(name, kind="phase", **attrs)
        try:
            with annotate(f"ccx:{name}"):
                yield
        finally:
            TRACER.end(s)
            phases[name] = s.wall_s

    stack_before = evaluate_stack(m, cfg, goal_names)
    inter = allows_inter_broker(goal_names)
    mesh = _make_run_mesh(opts) if opts.mesh_enabled else None
    overlap = (
        opts.overlap_repair
        and inter
        and opts.anneal.chunk_steps > 0
        and opts.anneal.n_steps > opts.anneal.chunk_steps
    )
    n_repair_lazy = None
    repair_box: dict = {}
    repair_thread = None
    with _phase("repair", backend=opts.repair_backend, overlap=overlap):
        if overlap:
            # repair converges in the background while the first SA chunk
            # anneals the still-infeasible input state; the anneal phase
            # joins and lex-merges. The phase split charges "repair" only
            # the dispatch and "repair-join" the residual critical-path
            # exposure — repair wall lands in "repair-concurrent".
            def _bg_repair():
                t_bg = time.monotonic()
                try:
                    repair_box["res"] = hard_repair(
                        m, cfg, goal_names, backend=opts.repair_backend
                    )
                except BaseException as e:  # re-raised on join
                    repair_box["err"] = e
                repair_box["wall"] = time.monotonic() - t_bg

            repair_thread = threading.Thread(target=_bg_repair, daemon=True)
            repair_thread.start()
            repaired, n_repair = m, 0
        elif opts.repair_backend == "device":
            # pipelined dispatch: ONE compiled repair program, outputs left
            # lazy on device — the anneal below consumes them without a
            # host sync, so the host-blocking repair phase collapses to
            # dispatch time and repair executes inside the anneal queue
            repaired, n_repair_lazy = hard_repair_async(m, cfg, goal_names)
            n_repair = 0
        else:
            repaired, n_repair = hard_repair(m, cfg, goal_names)
    with _phase(
        "anneal",
        chains=opts.anneal.n_chains,
        steps=opts.anneal.n_steps,
        chunkSteps=opts.anneal.chunk_steps,
        **(
            {"mesh": dict(zip(mesh.axis_names, mesh.devices.shape))}
            if mesh is not None
            else {}
        ),
    ):
        if overlap:
            chunk = opts.anneal.chunk_steps
            sa1 = anneal(
                m, cfg, goal_names,
                dataclasses.replace(opts.anneal, n_steps=chunk),
                mesh=mesh,
            )
            _tally(sa1, "anneal")
            t_join = time.monotonic()
            repair_thread.join()
            phases["repair-join"] = time.monotonic() - t_join
            phases["repair-concurrent"] = repair_box.get("wall", 0.0)
            if "err" in repair_box:
                # surface the background failure with its real traceback
                # instead of a KeyError that masks it
                raise repair_box["err"]
            repaired, n_repair = repair_box["res"]
            rep_stack = evaluate_stack(repaired, cfg, goal_names)
            # lex adoption (the portfolio rule): the remaining chunks
            # continue from whichever candidate is ahead — in practice the
            # repaired state (one chunk of SA cannot zero thousands of
            # hard violations), making the overlap chunk a free bet
            if _lex_better(sa1.stack_after, rep_stack):
                # repaired state discarded — its moves are not in the
                # output, so they must not count toward n_polish_moves
                start, n_sa1, n_repair = sa1.model, sa1.n_accepted, 0
            else:
                start, n_sa1 = repaired, 0
            sa = anneal(
                start, cfg, goal_names,
                dataclasses.replace(
                    opts.anneal,
                    n_steps=opts.anneal.n_steps - chunk,
                    seed=opts.anneal.seed + 1,
                ),
                mesh=mesh,
            )
            sa = dataclasses.replace(sa, n_accepted=sa.n_accepted + n_sa1)
        elif n_repair_lazy is not None and inter:
            # device hot list: derived from the (possibly still in-flight)
            # repaired arrays on device, so repair -> hot list -> chain
            # init -> SA chunks is one uninterrupted dispatch chain
            evac = hot_partition_list_device(
                repaired, goal_names=goal_names, cfg=cfg
            )
            sa = anneal(
                repaired, cfg, goal_names, opts.anneal, mesh=mesh, evac=evac
            )
        else:
            sa = anneal(repaired, cfg, goal_names, opts.anneal, mesh=mesh)
    _tally(sa, "anneal")
    if n_repair_lazy is not None:
        # the anneal consumed the repaired arrays, so this sync is free
        n_repair = int(n_repair_lazy)
    model = sa.model
    stack_after = sa.stack_after
    if mesh is not None:
        # re-home the winning placement to the default device: every
        # downstream phase (polish, shed, swap-polish, leader pass, diff,
        # verify) then shares the SINGLE-CHIP compiled programs — the mesh
        # accelerates the SA search, the pipeline's protections and
        # program caches stay exactly as on one chip
        import jax as _jax

        d0 = _jax.devices()[0]
        model = _jax.tree.map(lambda a: _jax.device_put(a, d0), model)
    n_polish = n_repair
    with _phase("polish", iters=opts.polish.max_iters, run=opts.run_polish):
        if opts.run_polish:
            polish = greedy_optimize(model, cfg, goal_names, opts.polish)
            _tally(polish, "polish")
            model = polish.model
            stack_after = polish.stack_after
            n_polish += polish.n_moves
            for _ in range(max(opts.max_repair_rounds - 1, 0)):
                if float(stack_after.hard_violations) <= 0:
                    break
                model, n_r = hard_repair(
                    model, cfg, goal_names, backend=opts.repair_backend
                )
                n_polish += n_r
                polish = greedy_optimize(model, cfg, goal_names, opts.polish)
                _tally(polish, "polish")
                if polish.n_moves == 0 and n_r == 0:
                    break
                model = polish.model
                stack_after = polish.stack_after
                n_polish += polish.n_moves
        else:
            # hard-violation recovery must not hinge on the polish flag: the
            # lean rung skips the pre-shed polish (the topic-rebalance stage
            # re-polishes instead), but residual post-SA hard violations
            # still get the repair retries the polish block would have run
            for _ in range(max(opts.max_repair_rounds - 1, 0)):
                if float(stack_after.hard_violations) <= 0:
                    break
                model, n_r = hard_repair(
                    model, cfg, goal_names, backend=opts.repair_backend
                )
                if n_r == 0:
                    break
                n_polish += n_r
                stack_after = evaluate_stack(model, cfg, goal_names)
    if opts.run_cold_greedy:
        with _phase("portfolio"):
            cold = greedy_optimize(m, cfg, goal_names, opts.polish)
            _tally(cold, "portfolio")
            # with optimizer.plan.cost.tier armed, a quality tie between
            # the portfolio candidates resolves toward the one that moves
            # fewer bytes / presses brokers less (ISSUE 17); off = the
            # plain lex rule, bit-exact
            if _movement_lex_better(
                cold.stack_after, cold.model, stack_after, model, m, opts
            ):
                model = cold.model
                stack_after = cold.stack_after
                # the returned plan is the cold-greedy one (started from the
                # input placement) — report its move count, not the
                # abandoned SA path's
                n_polish = cold.n_moves
    if (
        opts.topic_rebalance_rounds > 0
        and "TopicReplicaDistributionGoal" in goal_names
        and allows_inter_broker(goal_names)
    ):
        # targeted TopicReplicaDistribution stage: enumerate over-band
        # (topic, broker) cells directly (random proposals almost never
        # align topic and destination — repair.topic_rebalance docstring),
        # re-polish, and adopt only on full-vector lexicographic
        # improvement — a soft-goal sweep must never cost a higher tier.
        # Runs AFTER the portfolio selection so it applies to whichever
        # candidate won (a cold-greedy winner needs the stage most).
        with _phase("topic-rebalance", rounds=opts.topic_rebalance_rounds):
            repolish = (
                opts.polish
                if opts.topic_rebalance_polish_iters is None
                else dataclasses.replace(
                    opts.polish, max_iters=opts.topic_rebalance_polish_iters
                )
            )
            for _ in range(opts.topic_rebalance_rounds):
                swept, n_swept = topic_rebalance(
                    model, cfg,
                    max_sweeps=opts.topic_rebalance_max_sweeps,
                    move_leaders=opts.topic_rebalance_move_leaders,
                )
                if not n_swept:
                    break
                # trd-guarded re-polish first: recover the usage tiers the
                # shed disturbed WITHOUT trading its topic cells back (the
                # round-4 ratchet lost most of the shed this way — raw
                # converged TRD 24 vs 6.7k after unguarded re-polish). If
                # the guarded move space cannot reach lex adoption, fall
                # back to the unguarded polish, which is the proven path.
                cand = greedy_optimize(
                    swept, cfg, goal_names, repolish,
                    trd_guard=opts.topic_rebalance_guarded,
                )
                _tally(cand, "topic-rebalance")
                if opts.topic_rebalance_guarded and not _lex_better(
                    cand.stack_after, stack_after
                ):
                    cand = greedy_optimize(swept, cfg, goal_names, repolish)
                    _tally(cand, "topic-rebalance")
                if not _lex_better(cand.stack_after, stack_after):
                    break
                model = cand.model
                stack_after = cand.stack_after
                n_polish += n_swept + cand.n_moves

    def _run_swap_polish(model_in, iters, phase_name):
        # usage-coupled swap polish: the count-preserving descent for the
        # residual NwOut/LeaderReplica cells single moves cannot reach
        # (VERDICT r5 #4). Pure lex descent (hard-safe, optionally
        # TRD-guarded), so the result is adopted unconditionally. The
        # candidate budget splits evenly between replica-swap pairs and
        # leadership transfers, so the pre-leader and post-leader
        # invocations share ONE compiled program.
        with _phase(phase_name, iters=iters):
            ksw = max(opts.swap_polish_candidates // 2, 1)
            sp = swap_polish(
                model_in, cfg, goal_names,
                SwapPolishOptions(
                    n_swap_candidates=ksw,
                    n_lead_candidates=max(
                        opts.swap_polish_candidates - ksw, 0
                    ),
                    max_iters=iters,
                    trd_guard=opts.swap_polish_guarded,
                    chunk_iters=opts.swap_polish_chunk_iters,
                ),
            )
            _tally(sp, phase_name)
        return sp

    if opts.swap_polish_iters > 0 and allows_inter_broker(goal_names):
        # pre-leader invocation: clears the usage-tier (NwOut/CPU) cells
        # so the leader pass optimizes against a settled usage field; the
        # leader pass then cleans up the preferred-leader debris
        # leadership-bearing swaps leave behind
        sp = _run_swap_polish(model, opts.swap_polish_iters, "swap-polish")
        model = sp.model
        stack_after = sp.stack_after
        n_polish += sp.n_moves
    leadership_scored = LEADERSHIP_GOALS & set(goal_names)
    if (
        opts.run_leader_pass
        and leadership_scored
        and allows_inter_broker(goal_names)
    ):
        # final preferred-leadership pass over whichever candidate won:
        # greedy only applies lex-improving moves, so the result is adopted
        # unconditionally
        with _phase("leader-pass"):
            lead = greedy_optimize(
                model,
                cfg,
                goal_names,
                dataclasses.replace(
                    opts.polish,
                    leadership_only=True,
                    max_iters=(
                        opts.polish.max_iters
                        if opts.leader_pass_max_iters is None
                        else min(
                            opts.leader_pass_max_iters, opts.polish.max_iters
                        )
                    ),
                ),
            )
            _tally(lead, "leader-pass")
            model = lead.model
            stack_after = lead.stack_after
            n_polish += lead.n_moves
    if opts.swap_polish_post_iters > 0 and allows_inter_broker(goal_names):
        # post-leader invocation: the uniform leader pass stalls on the
        # LeaderReplica/LeaderBytesIn cells whose fix needs the coupled
        # draw — measured at B5 (docs/perf-notes.md "Usage-coupled
        # swaps"): 300 post iters, LR 599 -> 239, LBI 631 -> 271, ~10 s
        sp = _run_swap_polish(
            model, opts.swap_polish_post_iters, "swap-polish-post"
        )
        model = sp.model
        stack_after = sp.stack_after
        n_polish += sp.n_moves
    # exact final guarantee: fold leadership decisions into canonical
    # replica order (leader first), zeroing fixable PLE violations without
    # perturbing any other tier — see repair.finalize_preferred_leaders
    with _phase("preferred-leader"):
        model, stack_after, _ = finalize_preferred_leaders(
            model, cfg, goal_names, stack_after
        )
    with _phase("diff"):
        # compiled device diff (ccx.proposals.columnar_diff): mask +
        # bucketed compaction, only the changed rows cross device->host;
        # the columns ARE the result's canonical representation — rows
        # derive lazily if a consumer asks
        dcols = columnar_diff(m, model)
    plan = None
    if opts.plan_enabled:
        # executor-aware movement planning (ISSUE 17): wave-schedule the
        # diff where it already lives; additive — plan-off ships today's
        # exact result and compiles nothing new
        with _phase("plan"):
            plan = _compute_plan(m, dcols, opts)
    with _phase("verify"):
        verification = verify_optimization(
            m,
            model,
            cfg,
            goal_names,
            proposals=dcols,
            require_hard_zero=opts.require_hard_zero,
            check_evacuation=opts.check_evacuation,
            stack_before=stack_before,
            stack_after=stack_after,
        )
    if costmodel.capture_enabled() and costmodel.pending_count():
        # the bench prewarm-ledger seam / the sidecar's compile path: AOT
        # lower+compile every NEW program shape this run executed (verify
        # included) and bank its cost_analysis/memory_analysis record
        # (ccx.common.costmodel). Cold path only — a warm run enqueues
        # nothing and skips the phase entirely, which keeps cost capture
        # out of warm timings (and the zero-warm-fresh-compile tripwire
        # green). A pathological compile surfaces HERE with its own phase
        # breadcrumb, never inside a later timed rung.
        with _phase("cost-capture", pending=costmodel.pending_count()):
            costmodel.capture_pending()
    from ccx.common.metrics import REGISTRY
    from ccx.search.state import MOVE_KIND_NAMES

    move_counters = {}
    for i, name in enumerate(MOVE_KIND_NAMES):
        move_counters[name] = {
            "proposed": kind_prop[i], "accepted": kind_acc[i]
        }
        REGISTRY.counter(f"proposal-moves-{name}-proposed").inc(kind_prop[i])
        REGISTRY.counter(f"proposal-moves-{name}-accepted").inc(kind_acc[i])
    convergence = None
    if conv_phases:
        convergence = {"goals": list(goal_names), "phases": conv_phases}
        # live plateau gauges (ISSUE 9): per phase (and per fleet job when
        # one is registered), the chunk index after which the lex vector
        # stopped improving — the budget advisor's headline number,
        # scrapeable DURING a fleet run as each job's phases complete
        from ccx.common.convergence import plateau_chunk
        from ccx.common.tracing import TRACER as _tracer

        job = _tracer.job()
        for phase, segs in conv_phases.items():
            series = (segs[-1] or {}).get("series") or []
            if len(series) > 1:
                REGISTRY.set_gauge(
                    "convergence-plateau-step",
                    float(plateau_chunk(series)),
                    labels={
                        **({"job": job} if job else {}), "phase": phase,
                    },
                    help="chunk index of the last lex-improving chunk of "
                         "the phase's most recent engine run "
                         "(convergence taps)",
                )
    mesh_info = None
    if mesh is not None:
        from ccx.parallel.sharding import program_cache_stats

        mesh_info = {
            "meshShape": dict(zip(mesh.axis_names, mesh.devices.shape)),
            "devices": int(mesh.size),
            "shardedPrograms": program_cache_stats(),
        }
    return OptimizerResult(
        diff=dcols,
        stack_before=stack_before,
        stack_after=stack_after,
        verification=verification,
        model=model,
        wall_seconds=time.monotonic() - t0,
        n_sa_accepted=sa.n_accepted,
        n_polish_moves=n_polish,
        phase_seconds=phases,
        move_counters=move_counters,
        mesh=mesh_info,
        convergence=convergence,
        input_model=m,
        plan=plan,
    )


def _optimize_warm(
    m: TensorClusterModel,
    cfg: GoalConfig,
    goal_names: tuple[str, ...],
    opts: OptimizeOptions,
    progress_cb,
    warm: WarmStart,
) -> OptimizerResult:
    """The incremental warm pipeline (ccx.search.incremental, ISSUE 10):
    previous placement grafted onto the new metrics, drift-targeted
    plateau-terminated warm search, preferred-leader finalize, minimal
    diff, full verification. Deliberately lean — the steady-state
    target is a <500 ms re-proposal at B5 on the banked host, so the
    pipeline runs exactly one short search phase plus the exact final
    guarantees (finalize + verify) and nothing else. Raises
    ``ColdStartRequired`` when the warm base cannot be applied."""
    from ccx.search import incremental as inc

    t0 = time.monotonic()
    phases: dict[str, float] = {}
    kind_prop = [0, 0, 0]
    kind_acc = [0, 0, 0]
    conv_phases: dict[str, list] = {}

    def _tally(r, phase: str | None = None) -> None:
        for i in range(3):
            kind_prop[i] += int(r.n_prop_kind[i])
            kind_acc[i] += int(r.n_acc_kind[i])
        conv = getattr(r, "convergence", None)
        if phase is not None and conv:
            conv_phases.setdefault(phase, []).append(conv)

    @contextlib.contextmanager
    def _phase(name: str, **attrs):
        if progress_cb is not None:
            progress_cb(name)
        s = TRACER.start(name, kind="phase", **attrs)
        try:
            with annotate(f"ccx:{name}"):
                yield
        finally:
            TRACER.end(s)
            phases[name] = s.wall_s

    (model, stack_before, stack_after, search, info, base_model,
     bank_press, n_engine_moves) = inc.reoptimize(
        m, warm, cfg, goal_names, opts.incremental, opts,
        phase=_phase, tally=_tally,
    )
    # exact final guarantee, same as the cold pipeline: canonicalize
    # preferred leaders (the verifier's zero-PLE-slack contract). The
    # stack is NOT re-evaluated here — the warm pipeline defers the
    # result eval past canonicalization so the final placement is scored
    # exactly once, fused with the next window's pressure bank.
    with _phase("preferred-leader"):
        model, stack_after, _ = finalize_preferred_leaders(
            model, cfg, goal_names, stack_after, reevaluate=False
        )
    if stack_after is None:
        with _phase("warm-finish"):
            stack_after, bank_press = inc.warm_finish(model, cfg, goal_names)
    # never ship a warm result lexicographically behind its own
    # (repaired) base: the engines are descent-only, but a leadership
    # pass can in principle net-regress — when it does, the base IS the
    # better proposal, and its diff is the steady state's natural no-op.
    # SIGNIFICANCE tolerances (ccx.common.convergence — relative, the
    # asymmetric plateau rule), not the portfolio's absolute 1e-6: the
    # result stack is re-evaluated from scratch while the engines carried
    # incremental f32 sums, and ~1e-5-relative noise on a 1e3-scale high
    # tier must not read as "worse" and no-op a real improvement.
    if inc._significantly_lex_worse(stack_after, stack_before):
        model = base_model
        stack_after = stack_before
        bank_press = None  # pressure was scanned off the unshipped model
        n_engine_moves = 0  # the engines' moves are not in the output
        info["reverted"] = "lex"
    with _phase("diff"):
        dcols = columnar_diff(m, model)
    with _phase("verify"):
        verification = verify_optimization(
            m,
            model,
            cfg,
            goal_names,
            proposals=dcols,
            require_hard_zero=opts.require_hard_zero,
            check_evacuation=opts.check_evacuation,
            stack_before=stack_before,
            stack_after=stack_after,
        )
        if not verification.ok:
            # a warm search can make a lex-legitimate trade the per-goal
            # violation verifier rejects (lower-tier counts over slack).
            # The steady-state contract is "every window ships a VERIFIED
            # proposal": fall back to the (repaired) warm base — its diff
            # is the no-op/repair-only plan, trivially self-consistent —
            # and let the next metrics window try again.
            base_diff = columnar_diff(m, base_model)
            base_verification = verify_optimization(
                m,
                base_model,
                cfg,
                goal_names,
                proposals=base_diff,
                require_hard_zero=opts.require_hard_zero,
                check_evacuation=opts.check_evacuation,
                stack_before=stack_before,
                stack_after=stack_before,
            )
            if base_verification.ok:
                model = base_model
                stack_after = stack_before
                dcols = base_diff
                verification = base_verification
                bank_press = None  # scanned off the unshipped model
                n_engine_moves = 0  # moves not in the output
                info["reverted"] = "verification"
    plan = None
    if opts.plan_enabled:
        # re-plan-on-delta (ISSUE 17): every warm window plans ITS diff —
        # as each executed wave's completion arrives as a delta snapshot,
        # the next window's diff covers only the remaining movement, so
        # the remaining waves are rescheduled fresh under the live caps.
        # Computed after any verification revert: the plan always covers
        # the diff that actually ships.
        with _phase("plan"):
            plan = _compute_plan(m, dcols, opts)
    if costmodel.capture_enabled() and costmodel.pending_count():
        with _phase("cost-capture", pending=costmodel.pending_count()):
            costmodel.capture_pending()
    from ccx.common.metrics import REGISTRY
    from ccx.search.state import MOVE_KIND_NAMES

    move_counters = {}
    for i, name in enumerate(MOVE_KIND_NAMES):
        move_counters[name] = {
            "proposed": kind_prop[i], "accepted": kind_acc[i]
        }
    REGISTRY.counter("incremental-warm-proposals").inc(1)
    convergence = None
    if conv_phases:
        convergence = {"goals": list(goal_names), "phases": conv_phases}
    info["diffSize"] = dcols.n
    return OptimizerResult(
        diff=dcols,
        stack_before=stack_before,
        stack_after=stack_after,
        verification=verification,
        model=model,
        wall_seconds=time.monotonic() - t0,
        n_sa_accepted=getattr(search, "n_accepted", 0),
        n_polish_moves=n_engine_moves,
        phase_seconds=phases,
        move_counters=move_counters,
        convergence=convergence,
        incremental=info,
        input_model=m,
        warm_pressure=bank_press,
        plan=plan,
    )


def rebalance_disk(
    m: TensorClusterModel,
    cfg: GoalConfig = GoalConfig(),
    opts: OptimizeOptions | None = None,
) -> OptimizerResult:
    """Intra-broker JBOD disk rebalance (ref: rebalance?rebalance_disk,
    SURVEY.md C18). Only INTRA_BROKER_REPLICA_MOVEMENT actions are proposed."""
    if opts is None:
        opts = OptimizeOptions(
            anneal=AnnealOptions(p_disk=1.0, p_leadership=0.0, p_biased_dest=0.0),
            polish=GreedyOptions(
                p_disk=1.0, p_leadership=0.0, n_candidates=256, max_iters=400
            ),
            check_evacuation=False,
        )
    return optimize(m, cfg, INTRA_BROKER_GOAL_ORDER, opts)
