"""ccx.search — proposal search engines over the tensor cluster model.

The reference's ``analyzer/GoalOptimizer.java`` walks goals sequentially and
greedily mutates the ClusterModel (SURVEY.md C14/C15, call stack 3.2). The
TPU-native replacement is batched simulated annealing: thousands of
independent chains propose replica/leadership/disk moves, score the full goal
stack from incrementally-maintained broker aggregates, and Metropolis-accept
on a (hard, soft) lexicographic cost — all inside one jit-compiled
``lax.scan`` vmapped over chains (north star, BASELINE.json).

Modules:
  state     — per-chain search state + O(R) incremental aggregate updates
  annealer  — the batched SA engine
  greedy    — slow, faithful lexicographic hill-climbing oracle (tests/parity)
"""

from ccx.search.annealer import AnnealOptions, AnnealResult, anneal
from ccx.search.state import SearchState, init_search_state

__all__ = [
    "AnnealOptions",
    "AnnealResult",
    "anneal",
    "SearchState",
    "init_search_state",
]
