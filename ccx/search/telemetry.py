"""Convergence telemetry — device-resident per-chunk quality taps (ISSUE 9).

Every banked rung used to report only the FINAL lex vector: nothing
observed where the anneal/polish phases plateaued, which is exactly the
evidence the <5 s B5 chase needs to shrink budgets safely (ROADMAP "Bank
the number on hardware") and the convergence criterion incremental
re-optimization will key off (PAPERS.md "Integrative Dynamic
Reconfiguration...", the consumer-group autoscaler line of work — both
treat reconfiguration as an online process that must KNOW when it has
converged, not run a fixed budget).

This module is the device half: a ``(max_chunks, G + EXTRA)`` float32 ring
buffer ("tap") threaded through the chunk CARRY of every compiled search
engine — the SA chunk (``ccx.search.annealer._run_chunk``), both chunked
polish engines (``ccx.search.greedy``) and the mesh-sharded chunk program
(``ccx.parallel.sharding``). Each chunk program ends with ONE traced
``lax.dynamic_update_slice`` writing a row: the full per-goal lex cost
vector (the lex-best chain's, for multi-chain engines), cumulative
per-move-kind proposal/acceptance counters (``state.MOVE_KIND_NAMES``
order) and the temperature at the chunk's last step. Contracts:

* **Shape-stable** — ``max_chunks`` is fixed configuration (never derived
  from a budget), and the row index is traced data, so budget retunes
  reuse the compiled chunk programs exactly like the traced budgets do.
* **Zero added host syncs** — the tap rides the existing carry and comes
  back at the sync points ``drive_chunks`` already has; ``decode`` runs
  once after the run, where the engine already materializes its result.
* **Bit-exact off switch** — ``enabled()`` False passes ``tap=None``
  through every engine: the traced programs are the pre-telemetry ones
  and results are bit-identical (pinned by tests/test_convergence.py).
* **Truncation** — a run longer than ``max_chunks`` chunks clamps writes
  to the LAST row: rows ``0..max_chunks-2`` keep the opening of the run,
  the final row always holds the latest chunk, and ``decode`` flags
  ``truncated`` with the true chunk count.

The host-side analysis (plateau detection, budget proposals) lives in
``ccx.common.convergence`` — dependency-light so the ledger and the
flight-recorder tooling can use it without jax.
"""

from __future__ import annotations

import contextlib
import os

from ccx.common.convergence import plateau_chunk, wasted_fraction  # noqa: F401

#: row layout past the G goal costs: 3 proposal counters, 3 acceptance
#: counters (state.MOVE_KIND_NAMES order), temperature, and the
#: replica-exchange attempt/accept counters (zero rows for flat engines —
#: greedy/polish and K=1 SA never attempt an exchange)
EXTRA = 9

#: env off-switch for bench/tools/subprocess paths (the config key
#: ``observability.convergence`` wins when the facade set it explicitly)
ENV_CONVERGENCE = "CCX_CONVERGENCE"

_DEFAULT_MAX_CHUNKS = 256

_state: dict = {"enabled": None, "max_chunks": _DEFAULT_MAX_CHUNKS}


def enabled() -> bool:
    """Taps armed? Default ON (observability.convergence=true); tri-state
    like the tracer knobs: an explicit ``set_enabled`` wins, else the env
    (``CCX_CONVERGENCE=0`` disables), else on."""
    v = _state["enabled"]
    if v is None:
        return os.environ.get(ENV_CONVERGENCE, "1") != "0"
    return bool(v)


def set_enabled(v: bool | None) -> None:
    """Explicitly arm/disarm (None restores env/default resolution)."""
    _state["enabled"] = v


def max_chunks() -> int:
    return int(_state["max_chunks"])


def set_max_chunks(n: int) -> None:
    """Ring-buffer depth. Program SHAPE (like ``chunk_iters``): changing
    it mints new compiled chunk programs — a config choice, never a
    per-run retune."""
    _state["max_chunks"] = max(int(n), 1)


def configure(enabled: bool | None = None,
              max_chunks: int | None = None) -> None:
    """Config-driven setup (facade construction)."""
    if enabled is not None:
        set_enabled(bool(enabled))
    if max_chunks is not None and max_chunks > 0:
        set_max_chunks(max_chunks)


@contextlib.contextmanager
def taps(v: bool | None):
    """Test helper: force taps on/off within a block."""
    prev = _state["enabled"]
    _state["enabled"] = v
    try:
        yield
    finally:
        _state["enabled"] = prev


# ----- device side (traced) -------------------------------------------------


def make_tap(n_goals: int):
    """Fresh ``(buffer f32[max_chunks, G+EXTRA], count int32)`` pair —
    the carry element the chunk engines thread. ~20 KB at B5 defaults."""
    import jax.numpy as jnp

    return (
        jnp.zeros((max_chunks(), int(n_goals) + EXTRA), jnp.float32),
        jnp.zeros((), jnp.int32),
    )


def lex_best_row(cost_vecs):
    """Traced lexicographic argmin over chains: ``[K, G] -> [G]`` — the
    same column-elimination loop the greedy selection uses (G is static
    and small, so it unrolls)."""
    import jax.numpy as jnp

    K, G = cost_vecs.shape
    alive = jnp.ones((K,), bool)
    for g in range(G):
        col = jnp.where(alive, cost_vecs[:, g], jnp.inf)
        mn = jnp.min(col)
        tol = 1e-6 + 1e-6 * jnp.abs(mn)
        alive = alive & (col <= mn + tol)
    return cost_vecs[jnp.argmax(alive)]


def record(tap, cost_vec, n_prop, n_acc, temperature,
           ex_attempted=None, ex_accepted=None):
    """Traced per-chunk write: one ``dynamic_update_slice`` row (clamped
    to the last row once the buffer is full — see module docstring), count
    always advanced so ``decode`` can report the true chunk total.

    The cumulative move counters share the f32 row with the costs, so
    they are exact only below 2**24 (~16.7M) — two orders of magnitude
    above any banked rung's proposal total; past that, per-chunk deltas
    quantize (the counters are advisory trend evidence, never gated).

    ``ex_attempted``/``ex_accepted`` are THIS chunk's replica-exchange
    pair counts (not cumulative — an exchange sweep is a chunk-boundary
    event, so the per-chunk value is already the natural unit). Engines
    without a ladder omit them and write zeros."""
    import jax
    import jax.numpy as jnp

    buf, n = tap
    zero = jnp.zeros((), jnp.float32)
    row = jnp.concatenate([
        jnp.asarray(cost_vec, jnp.float32),
        jnp.asarray(n_prop, jnp.float32),
        jnp.asarray(n_acc, jnp.float32),
        jnp.asarray(temperature, jnp.float32)[None],
        jnp.asarray(
            zero if ex_attempted is None else ex_attempted, jnp.float32
        )[None],
        jnp.asarray(
            zero if ex_accepted is None else ex_accepted, jnp.float32
        )[None],
    ])
    idx = jnp.minimum(n, buf.shape[0] - 1)
    buf = jax.lax.dynamic_update_slice(
        buf, row[None, :], (idx, jnp.zeros((), n.dtype))
    )
    return buf, n + 1


# ----- host side ------------------------------------------------------------


def decode(tap, goal_names, chunk_size: int | None = None,
           budget: int | None = None, ladder: dict | None = None) -> dict | None:
    """Materialize a tap into the JSON-ready convergence segment that
    rides ``AnnealResult``/``GreedyResult`` → ``OptimizerResult.
    convergence``. One device→host transfer, at the point the engine
    already syncs on its result. Counters are CUMULATIVE (per-chunk deltas
    are a host-side diff — keeping the device write a pure copy of the
    carried counters).

    ``ladder`` (optional — the annealer passes it when n_temps > 1)
    attaches the replica-exchange ladder metadata verbatim; the per-chunk
    exchange attempt/accept series appears whenever any chunk attempted a
    pair (flat engines write zero columns and stay schema-stable)."""
    import numpy as np

    if tap is None:
        return None
    buf = np.asarray(tap[0])
    n = int(np.asarray(tap[1]))
    if n <= 0:
        return None
    G = len(goal_names)
    rows = min(n, buf.shape[0])
    out: dict = {
        "goals": list(goal_names),
        "chunks": n,
        "truncated": n > buf.shape[0],
        "series": [
            [round(float(x), 4) for x in buf[i, :G]] for i in range(rows)
        ],
        "proposed": [
            [int(x) for x in buf[i, G:G + 3]] for i in range(rows)
        ],
        "accepted": [
            [int(x) for x in buf[i, G + 3:G + 6]] for i in range(rows)
        ],
        "temperature": [
            round(float(buf[i, G + 6]), 6) for i in range(rows)
        ],
    }
    ex_att = [int(buf[i, G + 7]) for i in range(rows)]
    if any(ex_att):
        out["exchange"] = {
            "attempted": ex_att,
            "accepted": [int(buf[i, G + 8]) for i in range(rows)],
        }
    if ladder is not None:
        out["ladder"] = dict(ladder)
    if chunk_size:
        out["chunk"] = int(chunk_size)
    if budget is not None:
        out["budget"] = int(budget)
    return out
