"""Multi-job chunk scheduler — continuous batching for optimization jobs.

Fleet serving (ROADMAP "many clusters, one device"; ISSUE 8): production
Cruise Control runs one instance per Kafka cluster, so a TPU-resident
optimizer that can only serve one Propose at a time wastes the device on
every host-side phase (decode, diff, verify) of the job it is convoying
behind. The chunk boundary `annealer.drive_chunks` already yields to the
host between device chunks — exactly the preemption point continuous
batching needs. This module turns that boundary into a scheduler:

* every optimization job (one Propose call, one facade verb) registers as
  a :class:`JobHandle` with a **cluster id** and an integer **priority**;
* each chunk *dispatch* must win a grant from the run queue; grants go
  highest-priority-first, round-robin (least recently granted) within a
  priority, so N concurrent jobs interleave chunks on the device stream
  instead of convoying — and an urgent `fix-offline-replicas` submitted
  mid-run dispatches its first chunk within ONE chunk boundary of the
  currently granted dispatch;
* the grant covers only the **dispatch** (host-side enqueue of the chunk
  program). The chunk's device execution and any early-exit scalar sync
  happen outside the grant, so job B dispatches its chunk while job A's
  chunk is still executing — the device stream ends up holding
  A1, B1, A2, B2, … which is continuous batching at chunk granularity;
* up to ``dispatch_width`` grants may be outstanding at once (default:
  host core count, floor 2). Width 1 is strict alternation; the wider
  default matters on the CPU backend, where "dispatch" largely IS the
  execution (one-at-a-time grants measured 1.04x aggregate speedup vs
  1.5x at width 2 on a 2-core host), while on an accelerator the grant
  covers only the async enqueue. Order stays priority/round-robin at any
  width: a granted job leaves the wait set, so the next free grant
  always goes to the least-recently-served highest-priority waiter;
* each job carries its own donated carry, budget and flight-recorder span
  (they live on the job's thread; the scheduler never touches them), so
  one job early-exiting or failing cannot perturb another's search state.
  Since round 13 the carry also threads the job's convergence tap
  (``ccx.search.telemetry``) — the per-chunk quality series rides the
  SAME gated boundary, so every interleaved job's heartbeats (and the
  per-job ``convergence-energy`` gauge + /observability timeline) carry
  that job's own tier-0 energy, never a neighbor's;
* `max_concurrent` bounds how many jobs may be RESIDENT at once — a
  residency slot is taken at registration and held for the job's whole
  pipeline (its model, donated carries and host phases are live while
  resident), so the cap bounds both HBM pressure and host-side (GIL)
  contention; excess normal-priority jobs queue at registration and are
  admitted in (priority, arrival) order as residents finish. Jobs with
  priority > 0 BYPASS the cap: an urgent fix-offline-replicas must
  preempt at the next chunk boundary, never wait for a dryrun slot.

Single-job behavior is bit-exact vs the unscheduled path by construction:
the scheduler only *orders* chunk dispatches, it never changes what a
chunk computes, and with one registered job every grant is immediate
(pinned by tests/test_scheduler.py and the 1/10-scale B5 parity test).

Thread-safety: one Condition guards the run queue; jobs block in
``_admit`` releasing the GIL, so 16 waiting jobs cost nothing while the
granted job dispatches. Occupancy accounting (the fleet bench's
device-utilization number) integrates the time-weighted count of jobs
inside a chunk drive: ``occupancy`` is the fraction of the measurement
window during which at least one job had chunk work in flight — the
"device never idles between jobs" claim, measured host-side with no
device syncs added.
"""

from __future__ import annotations

import contextlib
import threading
import time

from ccx.common.faults import FAULTS


class JobCancelled(Exception):
    """The job's cancel event fired (client disconnected mid-Propose):
    raised at the next chunk-boundary grant acquisition so the worker
    unwinds, its ``FLEET.job`` context releases the grant and the
    residency slot, and nothing is left on the run queue. Cancellation is
    cooperative and chunk-granular — an in-flight compiled chunk always
    finishes; the NEXT dispatch raises."""

    def __init__(self, job_id: str) -> None:
        super().__init__(f"job {job_id!r} cancelled at chunk boundary")
        self.job_id = job_id


class JobHandle:
    """One registered optimization job. Mutable scheduling fields are
    guarded by the owning scheduler's lock; stats fields are written under
    the same lock and read without it (stale reads acceptable in stats)."""

    __slots__ = (
        "job_id", "priority", "seq", "resident", "waiting", "granted",
        "chunks", "wait_s", "t_registered", "t_first_chunk", "last_grant",
        "drives", "cancel_event",
    )

    def __init__(self, job_id: str, priority: int, seq: int) -> None:
        self.job_id = str(job_id)
        self.priority = int(priority)
        self.seq = seq
        #: holds a device-residency slot (first chunk granted)
        self.resident = False
        self.waiting = False
        self.granted = False
        self.chunks = 0
        self.wait_s = 0.0
        self.t_registered = time.monotonic()
        self.t_first_chunk: float | None = None
        #: grant-order stamp for round-robin within a priority
        self.last_grant = -1
        #: nesting depth of drive_chunks loops currently running this job
        self.drives = 0
        #: optional threading.Event a transport sets on client disconnect
        #: (ccx.sidecar.server wires gRPC context.add_callback to it);
        #: checked at every grant acquisition — see JobCancelled
        self.cancel_event: threading.Event | None = None

    def cancelled(self) -> bool:
        ev = self.cancel_event
        return ev is not None and ev.is_set()

    def to_json(self) -> dict:
        return {
            "job": self.job_id,
            "priority": self.priority,
            "chunks": self.chunks,
            "waitSeconds": round(self.wait_s, 4),
            "resident": self.resident,
        }


class ChunkScheduler:
    """Run queue of active optimization jobs, interleaved at chunk
    boundaries (module docstring). One instance per process (:data:`FLEET`)
    is shared by the sidecar's Propose workers and the facade's verbs."""

    def __init__(self, max_concurrent: int = 0,
                 dispatch_width: int | None = None) -> None:
        import os

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        #: 0 = unlimited device residency
        self.max_concurrent = int(max_concurrent)
        #: simultaneous chunk-dispatch grants (module docstring)
        self.dispatch_width = (
            int(dispatch_width)
            if dispatch_width
            else max(os.cpu_count() or 1, 2)
        )
        self._jobs: list[JobHandle] = []
        self._granted: set[JobHandle] = set()
        self._seq = 0
        self._grant_seq = 0
        self._tl = threading.local()
        # ---- stats (reset via reset_stats) --------------------------------
        self._t0 = time.monotonic()
        self._chunks = 0
        self._jobs_done = 0
        self._evictions = 0
        #: time-weighted occupancy integration: number of jobs currently
        #: inside a drive_chunks loop, busy seconds with >=1 such job, and
        #: the job-seconds integral (mean multiplexing depth)
        self._in_drive = 0
        self._occ_last = time.monotonic()
        self._occ_busy_s = 0.0
        self._occ_job_s = 0.0

    # ----- registration -----------------------------------------------------

    def register(self, job_id: str, priority: int = 0,
                 cancel_event: threading.Event | None = None) -> JobHandle:
        """Register a job; BLOCKS while the residency cap is reached (the
        admission queue, highest-priority / earliest-arrival first).
        Priority > 0 jobs bypass the cap — preemption must never wait for
        a dryrun slot to free. A set ``cancel_event`` raises
        :class:`JobCancelled` instead of admitting (and at every later
        grant acquisition) — the job leaves no queue entry behind."""
        with self._cond:
            self._seq += 1
            h = JobHandle(job_id, priority, self._seq)
            h.cancel_event = cancel_event
            self._jobs.append(h)
            try:
                if self.max_concurrent <= 0 or h.priority > 0:
                    h.resident = True
                else:
                    while not h.resident:
                        if h.cancelled():
                            raise JobCancelled(h.job_id)
                        free = self.max_concurrent - sum(
                            1 for j in self._jobs if j.resident
                        )
                        queued = sorted(
                            (j for j in self._jobs if not j.resident),
                            key=lambda j: (-j.priority, j.seq),
                        )
                        if free > 0 and h in queued[:free]:
                            h.resident = True
                            break
                        self._cond.wait()
            except JobCancelled:
                # a cancelled admission must free its queue entry HERE —
                # no FLEET.job finally will ever run for it
                self._jobs.remove(h)
                self._cond.notify_all()
                raise
            self._cond.notify_all()
            return h

    def unregister(self, h: JobHandle) -> None:
        with self._cond:
            if h in self._jobs:
                self._jobs.remove(h)
                self._jobs_done += 1
            self._granted.discard(h)
            h.resident = False
            self._cond.notify_all()

    @contextlib.contextmanager
    def job(self, job_id: str, priority: int = 0,
            cancel_event: threading.Event | None = None):
        """Register a job and make it THIS thread's ambient job for the
        duration: every ``drive_chunks`` loop on the thread routes its
        chunk dispatches through the run queue, and the flight recorder
        labels the thread's spans/heartbeats with ``job=<cluster-id>``
        (ccx.common.tracing). Reentrant registration (a nested pipeline
        running under an outer job) keeps the OUTER job — one Propose is
        one job, however many phases it runs. ``cancel_event`` (set by a
        transport on client disconnect, plus :meth:`kick`) cancels the
        job at the next chunk boundary (:class:`JobCancelled`); exit via
        ANY path — completion, cancellation, engine error — unregisters
        the job and frees its grant/residency."""
        outer = getattr(self._tl, "job", None)
        if outer is not None:
            yield outer
            return
        from ccx.common.tracing import TRACER

        h = self.register(job_id, priority, cancel_event=cancel_event)
        # admission hook of the unified device-memory ledger
        # (ccx.common.devmem): the registering job's priority re-prices
        # every device-resident entry carrying this job/session label
        # (its snapshot model, its warm base) — the moment an urgent
        # self-healing job is admitted, its residents are protected from
        # lower-priority packing; a later normal-priority registration
        # demotes them back (the last user wins).
        try:
            from ccx.common.devmem import DEVMEM

            DEVMEM.touch_job(h.job_id, h.priority)
        except Exception:  # noqa: BLE001 — accounting, never admission
            pass
        self._tl.job = h
        prev_label = TRACER.set_job(h.job_id)
        try:
            yield h
        finally:
            TRACER.set_job(prev_label)
            self._tl.job = None
            self.unregister(h)

    def current(self) -> JobHandle | None:
        """The ambient job of the calling thread (None = unscheduled)."""
        return getattr(self._tl, "job", None)

    # ----- chunk grants -----------------------------------------------------

    def _pick(self) -> JobHandle | None:
        """The next grant among waiting jobs: highest priority first,
        least-recently-granted within a priority (strict round-robin),
        registration order as the final tiebreak. (Residency is settled
        at registration — every waiting job here is already admitted.)"""
        best: JobHandle | None = None
        for j in self._jobs:
            if not j.waiting:
                continue
            if best is None or (
                (-j.priority, j.last_grant, j.seq)
                < (-best.priority, best.last_grant, best.seq)
            ):
                best = j
        return best

    def kick(self) -> None:
        """Wake every waiter so it re-checks its cancel event — the one
        call a canceller (another thread: the gRPC disconnect callback)
        must make after setting a job's cancel_event."""
        with self._cond:
            self._cond.notify_all()

    @contextlib.contextmanager
    def chunk(self, h: JobHandle):
        """One chunk dispatch under a grant. Blocks until ``h`` wins the
        run queue; the caller dispatches its chunk program inside the
        ``with`` and must NOT block on device results there (syncs belong
        outside, so the next job can dispatch meanwhile). Raises
        :class:`JobCancelled` when the job's cancel event is set — BEFORE
        dispatching, so "cancel mid-wave" frees the grant within one
        chunk: the in-flight chunk finishes, the next never starts."""
        t0 = time.monotonic()
        with self._cond:
            h.waiting = True
            try:
                while not (
                    len(self._granted) < self.dispatch_width
                    and self._pick() is h
                ):
                    if h.cancelled():
                        raise JobCancelled(h.job_id)
                    self._cond.wait()
                if h.cancelled():
                    raise JobCancelled(h.job_id)
            finally:
                h.waiting = False
            self._granted.add(h)
            self._grant_seq += 1
            h.last_grant = self._grant_seq
            if h.t_first_chunk is None:
                h.t_first_chunk = time.monotonic()
            h.wait_s += time.monotonic() - t0
            # re-notify after taking the grant: with dispatch_width > 1
            # another waiter may NOW be the _pick() winner for a still-free
            # slot — without this it sleeps until this chunk completes (a
            # lost wakeup that collapses multi-width dispatch to strict
            # alternation; measured 1.21s -> 1.01s on a 3-job width-2
            # micro-benchmark)
            self._cond.notify_all()
        try:
            # chaos seam (ccx.common.faults): an injected grant failure
            # exercises the "engine died mid-wave" path — the finally
            # below releases the grant, FLEET.job's exit unregisters, so
            # no fault here can strand a queue entry
            if FAULTS.armed:
                FAULTS.hit("scheduler.grant")
            yield
        finally:
            with self._cond:
                h.chunks += 1
                self._chunks += 1
                self._granted.discard(h)
                self._cond.notify_all()

    # ----- occupancy accounting --------------------------------------------

    def _occ_tick(self, delta: int) -> None:
        now = time.monotonic()
        dt = now - self._occ_last
        if self._in_drive > 0:
            self._occ_busy_s += dt
            self._occ_job_s += dt * self._in_drive
        self._occ_last = now
        self._in_drive += delta

    @contextlib.contextmanager
    def drive(self, h: JobHandle):
        """Marks ``h`` as having chunk work in flight for the duration of
        one drive_chunks loop — the occupancy integrand. Nested drives of
        the same job count once."""
        with self._cond:
            h.drives += 1
            if h.drives == 1:
                self._occ_tick(+1)
        try:
            yield
        finally:
            with self._cond:
                h.drives -= 1
                if h.drives == 0:
                    self._occ_tick(-1)

    # ----- stats ------------------------------------------------------------

    def reset_stats(self) -> None:
        with self._cond:
            now = time.monotonic()
            self._t0 = now
            self._chunks = 0
            self._jobs_done = 0
            self._occ_last = now
            self._occ_busy_s = 0.0
            self._occ_job_s = 0.0

    def stats(self) -> dict:
        """Scheduler window stats: ``occupancy`` = fraction of the window
        with >=1 job's chunks in flight (device-utilization proxy, no
        device sync); ``meanDepth`` = time-weighted mean number of such
        jobs (multiplexing depth; <=1 means serialized)."""
        with self._cond:
            now = time.monotonic()
            window = max(now - self._t0, 1e-9)
            busy = self._occ_busy_s
            job_s = self._occ_job_s
            if self._in_drive > 0:
                dt = now - self._occ_last
                busy += dt
                job_s += dt * self._in_drive
            return {
                "activeJobs": [j.to_json() for j in self._jobs],
                "maxConcurrent": self.max_concurrent,
                "dispatchWidth": self.dispatch_width,
                "windowSeconds": round(window, 3),
                "chunksGranted": self._chunks,
                "jobsCompleted": self._jobs_done,
                "occupancy": round(min(busy / window, 1.0), 4),
                "meanDepth": round(job_s / window, 3),
            }


#: the process-wide fleet scheduler — sidecar Propose workers, facade
#: verbs and the bench's concurrent streams all share one run queue (like
#: the one TRACER / one MetricRegistry)
FLEET = ChunkScheduler()


def configure(max_concurrent: int | None = None,
              dispatch_width: int | None = None) -> None:
    """Config hook (``optimizer.fleet.max.concurrent`` /
    ``optimizer.fleet.dispatch.width``): bounds device residency and
    simultaneous dispatch grants for :data:`FLEET`. None keeps the
    current value; dispatch_width 0 restores the auto default."""
    import os

    if max_concurrent is not None:
        FLEET.max_concurrent = max(int(max_concurrent), 0)
    if dispatch_width is not None:
        FLEET.dispatch_width = (
            int(dispatch_width)
            if dispatch_width > 0
            else max(os.cpu_count() or 1, 2)
        )
