"""Greedy lexicographic hill-climbing — the faithful-semantics oracle.

Parity: the reference's ``GoalOptimizer.optimizations`` walks goals in
priority order, and a move is only taken when every already-optimized goal
accepts it (``actionAcceptance``, SURVEY.md call stack 3.2 hot loop #1).
That is exactly lexicographic ordering on the per-goal cost vector: a move
is an improvement iff it strictly reduces some goal's cost without raising
any higher-priority goal's. This module implements that acceptance rule
directly and serves as

* the correctness oracle the annealer's results are score-compared against
  (SURVEY.md section 4 "score-parity vs a slow Python greedy oracle"), and
* the post-SA repair/polish pass: started from an annealed placement it
  fixes residual hard violations and low-tier regressions (e.g. preferred
  leadership) without breaking higher-priority goals, mirroring the
  reference's sequential re-optimization.

The whole loop runs ON DEVICE: each iteration vmaps ``n_candidates``
proposals, scores each in O(R) via the incremental move scorer
(ccx.search.state — no per-candidate aggregate copies), selects the
lexicographically-best DISJOINT subset on device, applies it, and
early-exits after ``patience`` consecutive iterations with no improving
candidate. Round 1's host-driven loop paid one device round-trip + a
~0.5 GB/batch aggregate materialization *per iteration* (~3.5 s/iter at B5
scale); this version's per-iteration cost is a few MB of [B]-level traffic.

Chunked descent engine (round 8): the iteration loop runs EITHER as one
monolithic ``lax.while_loop`` program (``chunk_iters=0`` — the round-4
shape whose B5 compile ran >17 min on TPU v5e and timed out) or, by
default, as a HOST-DRIVEN sequence of small jitted chunk programs: a
``fori_loop`` of ``chunk_iters`` iterations whose body goes inert (an
identity ``lax.cond`` branch) once the traced ``max_iters``/``patience``
exit fires — exactly the zeroed-budget trick the traced budgets already
use, so chunked and monolithic descents are bit-exact by construction
(the iteration counter only advances on live iterations, so the RNG
``fold_in`` stream is identical; pinned by tests/test_polish_chunked.py).
The host driver (``annealer.drive_chunks`` — shared with the SA chunk
runner) carries DONATED state between chunks and pays one scalar
device→host sync per chunk to poll the early-exit flag. Budgets stay
while_loop data; only ``chunk_iters`` is program shape.

Both entry points (uniform/leadership polish and the usage-coupled
``swap_polish``) build their per-iteration bodies from ONE shared
candidate representation — pair candidates ``(a-side edit, b-side edit)``
with an inert ``-1`` b side for single moves — so the disjoint selection
(`_select_disjoint`), exact batch composition (`_compose_pairs`) and
placement apply (`_apply_pairs`) fori_loop machinery is written once.
That unification also deleted the uniform loop's separate best-swap apply
path (an entire second ``_placement_updates`` arm under a ``lax.cond``):
swap candidates now compete inside the same disjoint batch as singles.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ccx.common import costmodel
from ccx.common.tracing import TRACER
from ccx.goals.base import GoalConfig
from ccx.goals.stack import DEFAULT_GOAL_ORDER, StackResult, evaluate_stack
from ccx.model.tensor_model import TensorClusterModel
from ccx.search.annealer import (
    CAPACITY_GOALS,
    RACK_TARGET_GOALS,
    ProposalParams,
    allows_inter_broker,
    drive_chunks,
    goal_tols,
    hot_partition_list,
    lead_swap_share,
    propose_move,
    propose_swap,
)
from ccx.goals import topic_terms as tt
from ccx.goals.base import GOAL_REGISTRY
from ccx.search.state import (
    SearchState,
    SwapDelta,
    _placement_updates,
    broker_pressure,
    bump_kind_counters,
    gather_views,
    init_search_state,
    make_cost_vector_fn,
    make_move_scorer,
    make_swap_scorer,
    make_topic_group,
    max_partitions_per_topic,
    scatter_partition,
    stack_needs_topic,
    usage_weights,
    with_placement,
)


@dataclasses.dataclass(frozen=True)
class GreedyOptions:
    #: candidate moves scored per iteration (vmapped on device)
    n_candidates: int = 512
    max_iters: int = 2000
    #: stop after this many consecutive iterations with no improving candidate
    patience: int = 8
    p_leadership: float = 0.25
    p_disk: float = 0.0
    p_biased_dest: float = 0.5
    p_evac: float = 0.3
    #: fraction of candidates proposed as two-partition REPLICA_SWAPs
    #: (ref ActionType, SURVEY.md C20). 0 (default since round 8): the
    #: count-preserving move class belongs to the DEDICATED usage-coupled
    #: ``swap_polish`` stage now — uniform swap draws almost never find
    #: the right pairs at scale (the r6 finding), and the branch measured
    #: strictly worse at 1/10-scale B5: equal-or-worse quality on every
    #: tier above TRD at 2.2x the target-rung polish wall (44 s vs 20 s),
    #: plus ~40% of the polish program's XLA compile. >0 restores the
    #: round-7 mixed-proposal loop for ablation.
    swap_fraction: float = 0.0
    #: apply up to this many NON-CONFLICTING improving moves per iteration
    #: (disjoint partitions, topics and touched-broker sets, each hard-safe
    #: and lex-improving vs the iteration's base state — the composition is
    #: then exactly additive and itself lex-improving). Swap candidates
    #: compete inside the same disjoint batch. 1 restores classic best-move
    #: hill climbing; >1 is what lets the polish clean thousands of
    #: residuals at B5 scale within max_iters.
    batch_moves: int = 16
    #: restrict EVERY proposal to leadership movements: single proposals are
    #: all LEADERSHIP_MOVEMENT (p_leadership forced to 1) and swap proposals
    #: are all count-preserving leadership rotations — no replica ever
    #: changes broker. This is the final preferred-leadership pass of the
    #: pipeline (ref: PreferredLeaderElectionGoal runs last in the goal
    #: order, SURVEY.md section 2.3) and the demote fast path.
    leadership_only: bool = False
    #: iterations per jitted chunk program of the host-driven descent
    #: (config ``optimizer.polish.chunk.iters``). The ONLY budget knob that
    #: is program shape: ``max_iters``/``patience`` stay traced data, so
    #: every iteration budget shares one compiled chunk per shape. 0 runs
    #: the monolithic ``lax.while_loop`` program instead (the parity
    #: reference — bit-exact with the chunked engine by construction).
    chunk_iters: int = 50
    seed: int = 0


@dataclasses.dataclass
class GreedyResult:
    model: TensorClusterModel
    stack_before: StackResult
    stack_after: StackResult
    n_moves: int
    n_iters: int
    #: per-move-kind (single, replica-swap, leadership-swap) proposal and
    #: acceptance counts (state.MOVE_KIND_NAMES) — observability
    n_prop_kind: tuple[int, ...] = (0, 0, 0)
    n_acc_kind: tuple[int, ...] = (0, 0, 0)
    #: decoded convergence-telemetry segment (ccx.search.telemetry):
    #: per-chunk lex cost vector / cumulative move counters recorded by
    #: the chunk carry. None on the monolithic path or with taps off.
    convergence: dict | None = None


def _lex_lt_batch(costs: jnp.ndarray, cur: jnp.ndarray) -> jnp.ndarray:
    """bool[N] — candidate vector lexicographically < current (with per-goal
    tolerance): the first significantly-changed goal improved."""
    d = costs - cur[None, :]
    tol = goal_tols(cur)[None, :]
    sig = jnp.abs(d) > tol
    any_sig = jnp.any(sig, axis=1)
    first = jnp.argmax(sig, axis=1)
    d_first = jnp.take_along_axis(d, first[:, None], axis=1)[:, 0]
    return any_sig & (d_first < 0)


def _lex_argmin(costs: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Index of the lexicographically-smallest masked row of costs[N, G]
    (on device; G is static and small, so the column loop unrolls)."""
    alive = mask
    G = costs.shape[1]
    for g in range(G):
        col = jnp.where(alive, costs[:, g], jnp.inf)
        mn = jnp.min(col)
        tol = 1e-6 + 1e-6 * jnp.abs(mn)
        alive = alive & (col <= mn + tol)
    return jnp.argmax(alive)


# ==========================================================================
# Shared disjoint-batch machinery. Every candidate is a PAIR of partition
# edits (old/new replica rows, leader slot, disk row per side); single
# moves carry an inert b side (all rows -1 — scatter_partition and
# _placement_updates drop negative rows, the same inert-write trick the
# traced budgets use). One selection loop, one composition loop and one
# apply site serve the uniform polish, the leadership pass and the
# usage-coupled swap polish — the four near-duplicate fori_loops the
# round-7 code carried are gone, and so is the uniform loop's separate
# best-swap apply path.
# ==========================================================================


def _select_disjoint(cost_vec, better, bmask, ta, tb, dual, n_batch, T):
    """Greedily take the lexicographically best remaining candidate whose
    {partitions, topics, touched brokers} are disjoint from everything
    already taken. Disjointness makes every per-broker/per-topic/
    per-partition goal term exactly additive, so the composed batch is
    itself hard-safe and lex-improving (its net change at the
    highest-priority changed tier is a sum of improvements); the exact
    recompute in `_compose_pairs` guards the non-decomposable couplings.

    ``ta``/``tb`` are the (clipped) topics of the two sides; ``dual[i]``
    marks candidates whose b side is real (pair candidates) — None when
    the caller has no pair candidates at all (the b-side bookkeeping is
    then statically absent from the program). Returns ``(sel_idx
    int32[n_batch], n_sel)`` with N as the not-taken sentinel; slot 0
    always holds the lex-best improving candidate — the single-move
    fallback checkpoint."""
    N, B = bmask.shape

    def select(k, carry):
        alive, used_b, used_t, sel, count = carry
        conf = jnp.any(bmask & used_b[None, :], axis=1) | used_t[ta]
        if dual is not None:
            conf = conf | (dual & used_t[tb])
        ok = alive & ~conf
        any_ok = jnp.any(ok)
        idx = _lex_argmin(cost_vec, ok)
        sel = sel.at[k].set(jnp.where(any_ok, idx, N))
        used_b = used_b | jnp.where(any_ok, bmask[idx], False)
        used_t = used_t.at[ta[idx]].max(any_ok)
        if dual is not None:
            used_t = used_t.at[tb[idx]].max(any_ok & dual[idx])
        alive = alive & (jnp.arange(N) != idx)
        return alive, used_b, used_t, sel, count + any_ok.astype(jnp.int32)

    sel0 = jnp.full((n_batch,), N, jnp.int32)
    _, _, _, sel_idx, n_sel = jax.lax.fori_loop(
        0, n_batch, select,
        (better, jnp.zeros(B, bool), jnp.zeros(T, bool), sel0,
         jnp.asarray(0, jnp.int32)),
    )
    return sel_idx, n_sel


def _broker_masks(touched: jnp.ndarray, N: int, B: int) -> jnp.ndarray:
    """bool[N, B] — which brokers each candidate touches (negative rows
    dropped)."""
    bmask = jnp.zeros((N, B), bool)
    return jax.vmap(lambda z, bb, v: z.at[bb].set(v, mode="drop"))(
        bmask,
        jnp.where(touched >= 0, jnp.clip(touched, 0, B - 1), B),
        touched >= 0,
    )


def _compose_pairs(
    ss, m, va, vb, olda, newa, oldb, newb, deltas, sel_idx, n_sel, n_batch,
    vector_fn, trd_norm, guard_on, guard_cols, has_pairs,
):
    """Exact composition of the selected disjoint pair-candidates.

    Disjointness makes sum-decomposable goal terms exactly additive, but
    the leader-evenness and trd-normalizer couplings are not
    sum-decomposable, and per-candidate vetoes are tolerance-filtered — a
    composed batch can net-regress a tier even though every member
    improved vs base. The composed vector is recomputed exactly here; when
    it is not lex-better than the iteration base (or trips the traced TRD
    guard), fall back to the best single candidate (slot 0), which IS
    exactly lex-improving — its vector is the scorer's exact candidate
    vector, so the fallback needs no second ``vector_fn`` instantiation.
    ``has_pairs`` (static) elides the b-side scatters when the caller has
    no pair candidates. Returns ``(accumulators, cost_vec, batch_ok,
    taken, safe)``."""
    N = deltas.cost_vec.shape[0]
    taken = sel_idx < N
    safe = jnp.clip(sel_idx, 0, N - 1)

    def acc(k, carry):
        agg, part, mtl, trd, totals = carry
        i = safe[k]
        w = taken[k].astype(jnp.float32)
        wi = taken[k].astype(jnp.int32)
        va_i = jax.tree.map(lambda x: x[i], va)
        o1 = tuple(x[i] for x in olda)
        n1 = tuple(x[i] for x in newa)
        agg = scatter_partition(agg, m, va_i, *o1, -w, -wi)
        agg = scatter_partition(agg, m, va_i, *n1, w, wi)
        totals = totals.at[va_i.topic].add(w * deltas.d_total[i])
        if has_pairs:
            vb_i = jax.tree.map(lambda x: x[i], vb)
            o2 = tuple(x[i] for x in oldb)
            n2 = tuple(x[i] for x in newb)
            agg = scatter_partition(agg, m, vb_i, *o2, -w, -wi)
            agg = scatter_partition(agg, m, vb_i, *n2, w, wi)
            totals = totals.at[vb_i.topic].add(w * deltas.d_total2[i])
        part = part + w * (deltas.part_sums[i] - ss.part_sums)
        mtl = mtl + w * deltas.d_mtl[i]
        trd = trd + w * deltas.d_trd[i]
        return agg, part, mtl, trd, totals

    # Slot 0 always holds the lex-best candidate (_select_disjoint), so the
    # state after acc(0, .) doubles as the single-move fallback checkpoint.
    first = acc(0, (ss.agg, ss.part_sums, ss.mtl_sum, ss.trd_sum,
                    ss.topic_totals))
    full = jax.lax.fori_loop(1, n_batch, acc, first)

    cost_full = vector_fn(*full[:4], trd_norm(full[4]))
    d_full = cost_full - ss.cost_vec
    # members are individually guard-safe but the trd normalizer coupling
    # is not sum-decomposable — re-check the composition
    full_guard_up = guard_on & jnp.any(
        (jnp.abs(d_full) > goal_tols(ss.cost_vec))
        & guard_cols
        & (d_full > 0)
    )
    batch_ok = (n_sel <= 1) | (
        _lex_lt_batch(cost_full[None, :], ss.cost_vec)[0] & ~full_guard_up
    )
    sel = jax.tree.map(lambda x, y: jnp.where(batch_ok, x, y), full, first)
    # fallback vector: the lex-best candidate's FULL cost vector from the
    # incremental scorer (exactly what the acceptance test compared) — the
    # same carried-incremental-vector contract the SA step runs on
    cost_first = jnp.where(taken[0], deltas.cost_vec[safe[0]], ss.cost_vec)
    cost_vec = jnp.where(batch_ok, cost_full, cost_first)
    return sel, cost_vec, batch_ok, taken, safe


def _apply_pairs(
    ss, group, pa, pb, va, vb, newa, newb, acc_sel, cost_vec, batch_ok,
    taken, safe, n_sel, dual, any_better,
):
    """Write the composed accumulators + the selected placements back into
    the search state. ``dual=None`` (no pair candidates) statically elides
    the b-side placement writes. Returns ``(state, n_applied,
    write_a)``."""
    agg, part, mtl, trd, totals = acc_sel
    n_batch = taken.shape[0]
    n_applied = jnp.where(
        any_better, jnp.where(batch_ok, n_sel, jnp.minimum(n_sel, 1)), 0
    )
    write_a = taken & (batch_ok | (jnp.arange(n_batch) == 0)) & any_better
    if dual is None:
        write = write_a
        mirror = write_a & va.pvalid[safe]
        ps = gps = pa[safe]
        ts = va.topic[safe]
        rows, leads, disks = (x[safe] for x in newa)
    else:
        write_b = write_a & dual[safe]
        write = jnp.concatenate([write_a, write_b])
        mirror = jnp.concatenate(
            [write_a & va.pvalid[safe], write_b & vb.pvalid[safe]]
        )
        ps = gps = jnp.concatenate([pa[safe], pb[safe]])
        ts = jnp.concatenate([va.topic[safe], vb.topic[safe]])
        rows = jnp.concatenate([newa[0][safe], newb[0][safe]])
        leads = jnp.concatenate([newa[1][safe], newb[1][safe]])
        disks = jnp.concatenate([newa[2][safe], newb[2][safe]])
    ss = ss.replace(
        agg=agg,
        part_sums=part,
        mtl_sum=mtl,
        trd_sum=trd,
        topic_totals=totals,
        cost_vec=cost_vec,
        n_accepted=ss.n_accepted + n_applied,
        **_placement_updates(
            ss, group, write=write, ps=ps, mirror=mirror, global_ps=gps,
            ts=ts, rows=rows, leads=leads, disks=disks,
        ),
    )
    return ss, n_applied, write_a


def _chunk_step(cond, body):
    """fori_loop body for a chunk program: run the descent iteration while
    the traced exit condition holds, identity afterwards — the inert-write
    trick that keeps chunked and monolithic descents bit-exact (inert
    iterations advance nothing, including the RNG iteration counter)."""

    def step(_, carry):
        return jax.lax.cond(cond(carry), body, lambda c: c, carry)

    return step


def _run_chunk_body(cond, body, chunk_iters, state, it, stale, moves):
    """Shared chunk-program tail: ``chunk_iters`` conditional iterations
    plus the early-exit flag the host polls. Only the STATE is donated by
    the callers — the scalar counters ride as separate (tiny, non-donated)
    operands because identical zero scalars can share one device buffer,
    and donating the same buffer twice is an XLA error."""
    state, it, stale, moves = jax.lax.fori_loop(
        0, chunk_iters, _chunk_step(cond, body), (state, it, stale, moves)
    )
    return state, it, stale, moves, ~cond((state, it, stale, moves))


def _unalias_placement(state: SearchState) -> SearchState:
    """Copy the placement buffers ``init_search_state`` shares with the
    source model. The chunk programs DONATE their carry (the buffers are
    reused in place across chunks); without this copy the first donation
    would invalidate the caller's model arrays too."""
    return state.replace(
        assignment=jnp.array(state.assignment, copy=True),
        leader_slot=jnp.array(state.leader_slot, copy=True),
        replica_disk=jnp.array(state.replica_disk, copy=True),
    )


@costmodel.instrument("descent-init")
@functools.partial(jax.jit, static_argnames=("goal_names", "cfg", "max_pt"))
def _descent_init(
    m: TensorClusterModel,
    key: jnp.ndarray,
    *,
    goal_names: tuple[str, ...],
    cfg: GoalConfig,
    max_pt: int,
) -> SearchState:
    """Starting SearchState of a descent engine as ONE compiled program
    (the greedy twin of the annealer's ``_init_chains``): topic-group
    derivation + full initial evaluation fused, instead of ~300 eager op
    dispatches — measured ~250 ms of host overhead per engine invocation
    at B5 on CPU, the dominant fixed cost of a warm-start re-proposal
    (ISSUE 10) and pure waste on every cold polish phase too."""
    group = make_topic_group(m, max_pt) if stack_needs_topic(goal_names) else None
    return init_search_state(m, cfg, goal_names, key, group=group)


# ==========================================================================
# Uniform / leadership polish
# ==========================================================================


def _make_greedy_iter(
    m, evac, n_evac, key0, max_iters, patience, guard_on,
    *, goal_names, cfg, pp, opts, max_pt,
):
    """Build the (cond, body) pair of one polish iteration over the carry
    ``(state, it, stale, moves)`` — the single source both the monolithic
    while_loop and the chunked fori_loop drivers trace, so the two engines
    cannot drift. max_iters/patience arrive as traced scalars (and are
    ZEROED in the static ``opts`` key by the caller): iteration budgets are
    loop-bound DATA, not program shape, so lean polish (400 iters) and full
    polish (1600) share ONE compiled program — a B5-scale greedy compile is
    >10 min on TPU v5e."""
    group = make_topic_group(m, max_pt) if stack_needs_topic(goal_names) else None
    scorer = make_move_scorer(m, goal_names, cfg)
    vector_fn = make_cost_vector_fn(m, goal_names, cfg)
    hard_arr = jnp.asarray(tuple(GOAL_REGISTRY[n].hard for n in goal_names))
    # trd-guard column mask: with guard_on (a traced scalar, so guarded and
    # unguarded polish share ONE compiled program) candidates that
    # significantly RAISE the TopicReplicaDistribution tier are vetoed like
    # hard regressions. TRD sits below the usage tiers in lex priority, so
    # an unguarded polish legally trades freshly-shed topic cells back for
    # usage cells — the round-4 shed/re-polish ratchet's loss mechanism.
    guard_cols = jnp.asarray(
        tuple(n == "TopicReplicaDistributionGoal" for n in goal_names)
    )
    n_swap = int(opts.n_candidates * opts.swap_fraction) if pp.p_swap > 0 else 0
    n_single = max(opts.n_candidates - n_swap, 1)
    N = n_single + n_swap
    n_batch = max(min(opts.batch_moves, n_single), 1)
    swap_scorer = make_swap_scorer(m, goal_names, cfg) if n_swap else None
    B, T = m.B, m.num_topics
    trd_norm = lambda totals: tt.trd_normalizer(m, totals)  # noqa: E731
    # [N] static: b side is real (a pair candidate); None when the program
    # carries no pair candidates at all (the b-side machinery is then
    # statically absent — the no-swap polish program is ~40% cheaper to
    # compile and to run per iteration)
    dual = (jnp.arange(N) >= n_single) if n_swap else None

    def cond(carry):
        _, it, stale, _ = carry
        return (it < max_iters) & (stale < patience)

    def body(carry):
        ss, it, stale, moves = carry
        keys = jax.random.split(
            jax.random.fold_in(key0, it), n_single + max(n_swap, 1)
        )

        def one(k):
            p, view, old, new, feasible = propose_move(k, ss, m, pp, evac, n_evac)
            delta = scorer(ss, view, old, new)
            return p, view, old, new, feasible, delta

        ps, views, olds, news, feas, sdelta = jax.vmap(one)(keys[:n_single])

        if n_swap:
            inert = tuple(jnp.full_like(x, -1) for x in olds)
            def one_swap(k):
                p1, v1, o1, n1, p2, v2, o2, n2, ok, is_lead = propose_swap(
                    k, ss, m, pp
                )
                delta = swap_scorer(ss, v1, o1, n1, v2, o2, n2)
                return p1, v1, o1, n1, p2, v2, o2, n2, ok, is_lead, delta

            (p1s, v1, o1, n1_, p2s, v2, o2, n2_, sw_ok, sw_lead, wdelta) = (
                jax.vmap(one_swap)(keys[n_single:])
            )
            cat = lambda a, b: jnp.concatenate([a, b])  # noqa: E731
            pa, pb = cat(ps, p1s), cat(ps, p2s)
            va = jax.tree.map(cat, views, v1)
            vb = jax.tree.map(cat, views, v2)
            olda = tuple(cat(a, b) for a, b in zip(olds, o1))
            newa = tuple(cat(a, b) for a, b in zip(news, n1_))
            oldb = tuple(cat(a, b) for a, b in zip(inert, o2))
            newb = tuple(cat(a, b) for a, b in zip(inert, n2_))
            feas_all = cat(feas, sw_ok)
            deltas = SwapDelta(
                cost_vec=cat(sdelta.cost_vec, wdelta.cost_vec),
                part_sums=cat(sdelta.part_sums, wdelta.part_sums),
                d_mtl=cat(sdelta.d_mtl, wdelta.d_mtl),
                d_trd=cat(sdelta.d_trd, wdelta.d_trd),
                d_total=cat(sdelta.d_total, wdelta.d_total),
                d_total2=cat(
                    jnp.zeros(n_single, sdelta.d_total.dtype), wdelta.d_total2
                ),
            )
            lead_mask = cat(jnp.zeros(n_single, bool), sw_lead)
        else:
            # singles only: MoveDelta already carries every field the
            # pair composition reads when has_pairs is statically False
            pa = pb = ps
            va = vb = views
            olda, newa = olds, news
            oldb = newb = None
            feas_all = feas
            deltas = sdelta
            lead_mask = None

        # hard-safety veto on top of lex improvement: lex_lt alone would let
        # a move improve a high tier while pushing a LOWER-priority hard
        # goal over (the reference's requirements checks forbid that), and
        # batch additivity needs every member's hard delta <= 0
        d_all = deltas.cost_vec - ss.cost_vec[None, :]
        sig_all = jnp.abs(d_all) > goal_tols(ss.cost_vec)[None, :]
        hard_up = jnp.any(sig_all & hard_arr[None, :] & (d_all > 0), axis=1)
        guard_up = guard_on & jnp.any(
            sig_all & guard_cols[None, :] & (d_all > 0), axis=1
        )
        better = (
            feas_all
            & ~hard_up
            & ~guard_up
            & _lex_lt_batch(deltas.cost_vec, ss.cost_vec)
        )
        any_better = jnp.any(better)

        a_rows = [olda[0], newa[0]]
        if n_swap:
            a_rows += [oldb[0], newb[0]]
        bmask = _broker_masks(jnp.concatenate(a_rows, axis=1), N, B)
        ta = jnp.clip(va.topic, 0, T - 1)
        tb = jnp.clip(vb.topic, 0, T - 1) if n_swap else None
        sel_idx, n_sel = _select_disjoint(
            deltas.cost_vec, better, bmask, ta, tb, dual, n_batch, T
        )
        acc_sel, cost_vec, batch_ok, taken, safe = _compose_pairs(
            ss, m, va, vb, olda, newa, oldb, newb, deltas, sel_idx, n_sel,
            n_batch, vector_fn, trd_norm, guard_on, guard_cols,
            has_pairs=bool(n_swap),
        )
        ss, n_applied, write_a = _apply_pairs(
            ss, group, pa, pb, va, vb, newa, newb, acc_sel, cost_vec,
            batch_ok, taken, safe, n_sel, dual, any_better,
        )

        # per-move-kind observability: the iteration proposed n_single
        # singles + n_swap swaps (split by variant); acceptances attribute
        # by the selected candidates' kinds
        if n_swap:
            n_lead_prop = jnp.sum(lead_mask.astype(jnp.int32))
            acc0 = jnp.sum((write_a & ~dual[safe]).astype(jnp.int32))
            acc1 = jnp.sum(
                (write_a & dual[safe] & ~lead_mask[safe]).astype(jnp.int32)
            )
            acc2 = jnp.sum(
                (write_a & dual[safe] & lead_mask[safe]).astype(jnp.int32)
            )
            ss = bump_kind_counters(
                ss,
                jnp.arange(3),
                jnp.stack(
                    [
                        jnp.asarray(n_single, jnp.int32),
                        jnp.asarray(n_swap, jnp.int32) - n_lead_prop,
                        n_lead_prop,
                    ]
                ),
                jnp.stack([acc0, acc1, acc2]),
            )
        else:
            ss = bump_kind_counters(
                ss, 0, n_single, jnp.sum(write_a.astype(jnp.int32))
            )
        it = it + 1
        stale = jnp.where(any_better, 0, stale + 1)
        return ss, it, stale, moves + n_applied

    return cond, body


@costmodel.instrument("polish-loop")
@functools.partial(
    jax.jit, static_argnames=("goal_names", "cfg", "pp", "opts", "max_pt")
)
def _greedy_loop(
    m: TensorClusterModel,
    state0: SearchState,
    evac: jnp.ndarray,
    n_evac: jnp.ndarray,
    key0: jnp.ndarray,
    max_iters: jnp.ndarray,
    patience: jnp.ndarray,
    guard_on: jnp.ndarray,
    *,
    goal_names: tuple[str, ...],
    cfg: GoalConfig,
    pp: ProposalParams,
    opts: GreedyOptions,
    max_pt: int,
):
    """Monolithic while_loop engine (``chunk_iters=0``) — the parity
    reference the chunked engine is pinned bit-exact against."""
    cond, body = _make_greedy_iter(
        m, evac, n_evac, key0, max_iters, patience, guard_on,
        goal_names=goal_names, cfg=cfg, pp=pp, opts=opts, max_pt=max_pt,
    )
    zero = jnp.asarray(0, jnp.int32)
    state, n_iters, _, n_moves = jax.lax.while_loop(
        cond, body, (state0, zero, zero, zero)
    )
    return state, n_iters, n_moves


@costmodel.instrument("polish-chunk", iters=lambda k: k["opts"].chunk_iters)
@functools.partial(
    jax.jit,
    static_argnames=("goal_names", "cfg", "pp", "opts", "max_pt"),
    donate_argnums=(0,),
)
def _greedy_chunk(
    state: SearchState,
    it: jnp.ndarray,
    stale: jnp.ndarray,
    moves: jnp.ndarray,
    m: TensorClusterModel,
    evac: jnp.ndarray,
    n_evac: jnp.ndarray,
    key0: jnp.ndarray,
    max_iters: jnp.ndarray,
    patience: jnp.ndarray,
    guard_on: jnp.ndarray,
    tap=None,
    *,
    goal_names: tuple[str, ...],
    cfg: GoalConfig,
    pp: ProposalParams,
    opts: GreedyOptions,
    max_pt: int,
):
    """One chunk of the host-driven descent: ``opts.chunk_iters`` (the only
    shape-bearing budget) conditional iterations over the DONATED state.
    Returns ``(state, it, stale, moves, tap, done)`` — ``done`` is the
    early-exit flag the host polls between chunks; ``tap`` is the
    convergence-telemetry carry (ccx.search.telemetry — one traced row per
    chunk: the carried lex cost vector + cumulative move counters; None
    keeps the pre-telemetry program, bit-exact)."""
    cond, body = _make_greedy_iter(
        m, evac, n_evac, key0, max_iters, patience, guard_on,
        goal_names=goal_names, cfg=cfg, pp=pp, opts=opts, max_pt=max_pt,
    )
    state, it, stale, moves, done = _run_chunk_body(
        cond, body, opts.chunk_iters, state, it, stale, moves
    )
    if tap is not None:
        from ccx.search import telemetry

        tap = telemetry.record(
            tap, state.cost_vec, state.n_prop_kind, state.n_acc_kind,
            jnp.zeros((), jnp.float32),
        )
    return state, it, stale, moves, tap, done


def greedy_optimize(
    m: TensorClusterModel,
    cfg: GoalConfig = GoalConfig(),
    goal_names: tuple[str, ...] = DEFAULT_GOAL_ORDER,
    opts: GreedyOptions = GreedyOptions(),
    trd_guard: bool = False,
) -> GreedyResult:
    """Hill-climb the lexicographic goal-cost vector to a local optimum.

    ``trd_guard`` additionally vetoes candidates that significantly worsen
    the TopicReplicaDistribution tier (a traced flag — no extra compiled
    program). Used by the optimizer's topic-rebalance stage so the usage
    re-polish cannot trade the shed's topic cells back (docs/perf-notes.md
    round-4 "shed/re-polish interplay"); plain polish keeps the full move
    space.
    """
    stack_before = evaluate_stack(m, cfg, goal_names)
    p_real = int(np.asarray(m.partition_valid).sum())
    bv = np.asarray(m.broker_valid)
    b_real = int(np.max(np.where(bv, np.arange(m.B), -1))) + 1
    allow_inter = allows_inter_broker(goal_names)
    lead_only = opts.leadership_only
    pp = ProposalParams(
        p_real=p_real,
        b_real=b_real,
        p_leadership=1.0 if lead_only else opts.p_leadership,
        p_disk=0.0 if lead_only else opts.p_disk,
        p_biased_dest=0.0 if lead_only else opts.p_biased_dest,
        p_evac=0.0 if lead_only else opts.p_evac,
        target_rack=(not lead_only)
        and bool(RACK_TARGET_GOALS & set(goal_names)),
        allow_inter=allow_inter and not lead_only,
        p_swap=opts.swap_fraction if allow_inter else 0.0,
        target_capacity=(not lead_only)
        and bool(CAPACITY_GOALS & set(goal_names)),
        cap_thresholds=tuple(cfg.capacity_threshold),
        # every swap proposal is a leadership rotation in leadership-only
        # mode — a replica swap would move replicas between brokers
        p_lead_swap=1.0 if lead_only else lead_swap_share(opts.p_leadership),
    )

    if lead_only:
        # leadership moves cannot heal placement offenders; skip the
        # aggregate pass that builds the hot list (p_evac is 0 anyway)
        evac_np, n_evac_i = np.zeros(1, np.int32), 0
    else:
        evac_np, n_evac_i = hot_partition_list(m, goal_names, cfg)
    max_pt = max_partitions_per_topic(m)
    state0 = _descent_init(
        m, jax.random.PRNGKey(opts.seed),
        goal_names=goal_names, cfg=cfg, max_pt=max_pt,
    )
    evac_j = jnp.asarray(evac_np)
    n_evac_j = jnp.asarray(n_evac_i, jnp.int32)
    key0 = jax.random.PRNGKey(opts.seed + 1)
    mi = jnp.asarray(opts.max_iters, jnp.int32)
    pat = jnp.asarray(opts.patience, jnp.int32)
    guard = jnp.asarray(trd_guard, bool)
    # iteration budgets are traced operands; zero them (and the RNG seed,
    # which only enters via PRNGKey data) in the compile key. chunk_iters
    # is the ONE shape-bearing budget — kept in the chunk key, zeroed in
    # the monolith key (the while_loop never reads it).
    opts_key = dataclasses.replace(opts, max_iters=0, patience=0, seed=0)
    # shape-keyed descent span (see swap_polish): names the compiled
    # program a stalled recording died inside; chunk heartbeats attach here
    with TRACER.span(
        "greedy-descent",
        candidates=opts.n_candidates,
        chunkIters=opts.chunk_iters,
        maxIters=opts.max_iters,
        leadershipOnly=lead_only,
    ):
        convergence = None
        if opts.chunk_iters > 0:
            from ccx.search import telemetry

            tap = (
                telemetry.make_tap(len(goal_names))
                if telemetry.enabled()
                else None
            )
            zero = jnp.asarray(0, jnp.int32)
            carry = (_unalias_placement(state0), zero, zero, zero, tap)

            def run_one(c, off):
                *c2, tp, done = _greedy_chunk(
                    *c[:4], m, evac_j, n_evac_j, key0, mi, pat, guard,
                    c[4],
                    goal_names=goal_names, cfg=cfg, pp=pp, opts=opts_key,
                    max_pt=max_pt,
                )
                return tuple(c2) + (tp,), done

            probe = None
            if tap is not None:
                # the descent's early-exit poll already syncs each chunk,
                # so the tier-0 heartbeat energy is a free scalar read
                def probe(c):
                    return c[0].cost_vec[0]

            state, n_iters, _, n_moves, tap = drive_chunks(
                run_one, carry, total=opts.max_iters,
                chunk=opts.chunk_iters, probe=probe,
            )
            convergence = telemetry.decode(
                tap, goal_names, chunk_size=opts.chunk_iters,
                budget=opts.max_iters,
            )
        else:
            state, n_iters, n_moves = _greedy_loop(
                m, state0, evac_j, n_evac_j, key0, mi, pat, guard,
                goal_names=goal_names, cfg=cfg, pp=pp,
                opts=dataclasses.replace(opts_key, chunk_iters=0),
                max_pt=max_pt,
            )

    result_model = with_placement(m, state)
    stack_after = evaluate_stack(result_model, cfg, goal_names)
    return GreedyResult(
        model=result_model,
        stack_before=stack_before,
        stack_after=stack_after,
        n_moves=int(np.asarray(n_moves)),
        n_iters=int(np.asarray(n_iters)),
        n_prop_kind=tuple(int(x) for x in np.asarray(state.n_prop_kind)),
        n_acc_kind=tuple(int(x) for x in np.asarray(state.n_acc_kind)),
        convergence=convergence,
    )


# ==========================================================================
# Usage-coupled swap polish — the dedicated count-preserving descent phase
# (VERDICT r5 next #4). The residual NwOut/LeaderReplica cells at lean
# effort sit in states single relocations structurally cannot reach (a
# count-band-neutral usage fix needs a SWAP; a leader-count fix needs a
# low-usage-delta transfer the uniform draws almost never find). This loop
# proposes ONLY coupled candidates: every iteration ranks all P partitions
# by live broker band pressure (ccx.search.state.broker_pressure) x
# per-replica usage, Gumbel-top-k draws (hot, cold) replica-swap pairs and
# pressure-ranked leadership transfers, scores them exactly
# (make_swap_scorer) and batch-applies the lexicographically-best disjoint
# subset (the shared pair machinery above). Pure descent: only
# lex-improving, hard-safe (optionally TRD-guarded) candidates are ever
# applied, so the phase's result is adopted unconditionally by the
# pipeline.
# ==========================================================================


@dataclasses.dataclass(frozen=True)
class SwapPolishOptions:
    #: coupled replica-swap pairs proposed per iteration (static shape).
    #: The pipeline splits `swap_polish_candidates` evenly between the two
    #: kinds so both its invocations share one compiled program.
    n_swap_candidates: int = 64
    #: coupled leadership transfers proposed per iteration (static shape)
    n_lead_candidates: int = 64
    max_iters: int = 200
    #: stop after this many consecutive iterations with no improving candidate
    patience: int = 10
    #: disjoint candidates applied per iteration (lex-best first)
    batch_moves: int = 16
    #: veto candidates that significantly worsen TopicReplicaDistribution
    #: (traced — guarded and unguarded share one program). Replica swaps
    #: between different topics move topic cells; after the shed converges
    #: the guard keeps the phase from trading TRD=0 back for usage cells.
    trd_guard: bool = True
    #: iterations per jitted chunk program (config
    #: ``optimizer.swap.polish.chunk.iters``); 0 = monolithic while_loop.
    #: Same contract as GreedyOptions.chunk_iters: the only shape-bearing
    #: budget — max_iters/patience stay traced.
    chunk_iters: int = 50
    seed: int = 0


def _make_swap_iter(
    m, key0, max_iters, patience, guard_on,
    *, goal_names, cfg, opts, max_pt,
):
    """(cond, body) of one usage-coupled swap-polish iteration — shared by
    the monolithic and chunked drivers, same budget contract as
    `_make_greedy_iter` (budgets traced, zeroed in the static key)."""
    group = make_topic_group(m, max_pt) if stack_needs_topic(goal_names) else None
    swap_scorer = make_swap_scorer(m, goal_names, cfg)
    vector_fn = make_cost_vector_fn(m, goal_names, cfg)
    hard_arr = jnp.asarray(tuple(GOAL_REGISTRY[n].hard for n in goal_names))
    guard_cols = jnp.asarray(
        tuple(n == "TopicReplicaDistributionGoal" for n in goal_names)
    )
    B, T, R, P, D = m.B, m.num_topics, m.R, m.P, m.D
    # top_k caps at the padded partition count — tiny fixtures otherwise
    # request more candidates than partitions exist
    K_sw = max(min(int(opts.n_swap_candidates), P), 1)
    K_ld = max(min(int(opts.n_lead_candidates), P), 0)
    N = K_sw + K_ld
    n_batch = max(min(opts.batch_moves, N), 1)
    trd_norm = lambda totals: tt.trd_normalizer(m, totals)  # noqa: E731
    from ccx.common.resources import Resource

    uw = usage_weights()
    u_lead_p = uw @ m.leader_load          # [P] combined usage, leader role
    u_foll_p = uw @ m.follower_load        # [P] combined usage, follower role
    lbytes_p = m.leader_load[Resource.NW_IN]
    avg_lb = jnp.sum(jnp.where(m.partition_valid, lbytes_p, 0.0)) / jnp.maximum(
        jnp.sum(m.partition_valid), 1
    )
    recv_ok = m.broker_valid & m.broker_alive & ~m.broker_excl_replicas
    lead_allowed = m.broker_valid & m.broker_alive & ~m.broker_excl_leadership
    is_swap_cand = jnp.arange(N) < K_sw    # [N] static candidate kind mask

    def cond(carry):
        _, it, stale, _ = carry
        return (it < max_iters) & (stale < patience)

    def body(carry):
        ss, it, stale, moves = carry
        key = jax.random.fold_in(key0, it)
        k_gh, k_gc, k_gl, k_d = jax.random.split(key, 4)
        press = broker_pressure(m, ss.agg, cfg)

        # ---- coupling scores over the full placement (O(P*R) elementwise;
        # the [P,R] reads are why this lives in its own loop, not the SA
        # step — the greedy-style loop tolerates extra carried-buffer uses)
        a = ss.assignment                      # [P, R]
        lead_slot = ss.leader_slot
        valid = (a >= 0) & m.partition_valid[:, None]
        movable = valid & ~m.partition_immovable[:, None]
        b = jnp.clip(a, 0, B - 1)
        is_l = jnp.arange(R)[None, :] == lead_slot[:, None]
        u = jnp.where(is_l, u_lead_p[:, None], u_foll_p[:, None])  # [P, R]

        hot_sc = press.usage_over[b] * u * movable
        hot_score = jnp.max(hot_sc, axis=1)
        hot_slot = jnp.argmax(hot_sc, axis=1).astype(jnp.int32)
        cold_sc = press.usage_under[b] * (1.0 / (1.0 + u)) * movable
        cold_score = jnp.max(cold_sc, axis=1)
        cold_slot = jnp.argmax(cold_sc, axis=1).astype(jnp.int32)

        # coupled leadership transfer: leader on a (leader-count or
        # leader-bytes) over broker -> follower slot on an under broker.
        # Two sub-couplings share the candidate budget: the LeaderReplica
        # (count) fix wants LOW-usage-delta leaders — a transfer moves the
        # (leader - follower) role load between brokers, and the usage
        # tiers ABOVE LeaderReplica veto significant regressions, so hot
        # leaders get vetoed exactly where the count fix is needed; the
        # LeaderBytesIn fix wants the opposite (move the heavy-bytes
        # leader off the over-bytes broker).
        lsafe = jnp.clip(lead_slot, 0, R - 1)
        lb = jnp.take_along_axis(b, lsafe[:, None], axis=1)[:, 0]
        has_lead = jnp.take_along_axis(valid, lsafe[:, None], axis=1)[:, 0]
        dest_ok = movable & ~is_l & lead_allowed[b]
        dest_sc = (press.lead_under[b] + 0.3 * press.lbi_under[b]) * dest_ok
        dest_best = jnp.max(dest_sc, axis=1)
        dest_slot = jnp.argmax(dest_sc, axis=1).astype(jnp.int32)
        # usage delta a transfer moves, in combined-usage units (static)
        u_delta = jnp.maximum(u_lead_p - u_foll_p, 0.0)
        avg_du = jnp.sum(jnp.where(m.partition_valid, u_delta, 0.0)) / (
            jnp.maximum(jnp.sum(m.partition_valid), 1)
        )
        damp = 1.0 / (1.0 + u_delta / jnp.maximum(avg_du, 1e-9))
        src_lr = press.lead_over[lb] * damp
        src_lbi = press.lbi_over[lb] * (lbytes_p / jnp.maximum(avg_lb, 1e-9))
        lead_score = (src_lr + src_lbi) * dest_best * has_lead * movable[
            jnp.arange(P), lsafe
        ]

        def gumbel_topk(score, k, kg):
            g = -jnp.log(
                -jnp.log(jax.random.uniform(kg, (P,), minval=1e-12, maxval=1.0))
            )
            _, idx = jax.lax.top_k(jnp.log(score + 1e-12) + g, k)
            return idx.astype(jnp.int32)

        hot_ps = gumbel_topk(hot_score, K_sw, k_gh)
        cold_ps = gumbel_topk(cold_score, K_sw, k_gc)
        if K_ld:
            lead_ps = gumbel_topk(lead_score, K_ld, k_gl)
            pa = jnp.concatenate([hot_ps, lead_ps])
            pb = jnp.concatenate([cold_ps, lead_ps])   # lead partners inert
            r1s = jnp.concatenate([hot_slot[hot_ps], dest_slot[lead_ps]])
            r2s = jnp.concatenate(
                [cold_slot[cold_ps], jnp.zeros(K_ld, jnp.int32)]
            )
        else:
            pa, pb = hot_ps, cold_ps
            r1s = hot_slot[hot_ps]
            r2s = cold_slot[cold_ps]

        views = gather_views(ss, m, jnp.concatenate([pa, pb]))
        va = jax.tree.map(lambda x: x[:N], views)
        vb = jax.tree.map(lambda x: x[N:], views)
        kds = jax.random.split(k_d, N)

        def plan(va_k, vb_k, pa_k, pb_k, r1_k, r2_k, sw_k, kd):
            x = va_k.assign[r1_k]
            y = vb_k.assign[r2_k]
            sx = jnp.clip(x, 0, B - 1)
            sy = jnp.clip(y, 0, B - 1)
            lead1 = r1_k == va_k.leader
            lead2 = r2_k == vb_k.leader
            ok_sw = (
                (pa_k != pb_k)
                & va_k.pvalid
                & vb_k.pvalid
                & ~va_k.immovable
                & ~vb_k.immovable
                & (x >= 0)
                & (y >= 0)
                & (x != y)
                & recv_ok[sx]
                & recv_ok[sy]
                & ~jnp.any(va_k.assign == y)
                & ~jnp.any(vb_k.assign == x)
                & ~(lead1 & m.broker_excl_leadership[sy])
                & ~(lead2 & m.broker_excl_leadership[sx])
            )
            gd = -jnp.log(
                -jnp.log(
                    jax.random.uniform(kd, (2, D), minval=1e-12, maxval=1.0)
                )
            )
            d1 = jnp.argmax(
                jnp.where(m.disk_alive[sy], gd[0], -jnp.inf)
            ).astype(jnp.int32)
            d2 = jnp.argmax(
                jnp.where(m.disk_alive[sx], gd[1], -jnp.inf)
            ).astype(jnp.int32)

            # leadership transfer variant (single move, partner inert):
            # mirrors _single_plan's MOVE_LEADERSHIP feasibility
            ok_ld = (
                va_k.pvalid
                & ~va_k.immovable
                & (va_k.assign[r1_k] >= 0)
                & (r1_k != va_k.leader)
                & lead_allowed[jnp.clip(va_k.assign[r1_k], 0, B - 1)]
            )

            def pick(sw_rows, ld_rows):
                return jnp.where(sw_k, sw_rows, ld_rows)

            olda = (va_k.assign, va_k.leader, va_k.disk)
            new1 = (
                pick(va_k.assign.at[r1_k].set(y), va_k.assign),
                pick(va_k.leader, r1_k).astype(jnp.int32),
                pick(
                    va_k.disk.at[r1_k].set(jnp.where(D > 1, d1, 0)),
                    va_k.disk,
                ),
            )

            def inert(rows):
                return tuple(jnp.where(sw_k, r, -1) for r in rows)

            oldb = inert((vb_k.assign, vb_k.leader, vb_k.disk))
            newb = inert(
                (
                    vb_k.assign.at[r2_k].set(x),
                    vb_k.leader,
                    vb_k.disk.at[r2_k].set(jnp.where(D > 1, d2, 0)),
                )
            )
            return olda, new1, oldb, newb, jnp.where(sw_k, ok_sw, ok_ld)

        olda, newa, oldb, newb, feas = jax.vmap(plan)(
            va, vb, pa, pb, r1s, r2s, is_swap_cand, kds
        )
        deltas = jax.vmap(
            lambda va_k, o1, n1, vb_k, o2, n2: swap_scorer(
                ss, va_k, o1, n1, vb_k, o2, n2
            )
        )(va, olda, newa, vb, oldb, newb)

        d_all = deltas.cost_vec - ss.cost_vec[None, :]
        sig_all = jnp.abs(d_all) > goal_tols(ss.cost_vec)[None, :]
        hard_up = jnp.any(sig_all & hard_arr[None, :] & (d_all > 0), axis=1)
        guard_up = guard_on & jnp.any(
            sig_all & guard_cols[None, :] & (d_all > 0), axis=1
        )
        better = (
            feas
            & ~hard_up
            & ~guard_up
            & _lex_lt_batch(deltas.cost_vec, ss.cost_vec)
        )
        any_better = jnp.any(better)

        touched = jnp.concatenate(
            [olda[0], newa[0], oldb[0], newb[0]], axis=1
        )
        bmask = _broker_masks(touched, N, B)
        ta = jnp.clip(va.topic, 0, T - 1)
        tb = jnp.clip(vb.topic, 0, T - 1)
        sel_idx, n_sel = _select_disjoint(
            deltas.cost_vec, better, bmask, ta, tb, is_swap_cand, n_batch, T
        )
        acc_sel, cost_vec, batch_ok, taken, safe = _compose_pairs(
            ss, m, va, vb, olda, newa, oldb, newb, deltas, sel_idx, n_sel,
            n_batch, vector_fn, trd_norm, guard_on, guard_cols,
            has_pairs=True,
        )
        ss, n_applied, write_a = _apply_pairs(
            ss, group, pa, pb, va, vb, newa, newb, acc_sel, cost_vec,
            batch_ok, taken, safe, n_sel, is_swap_cand, any_better,
        )
        # coupled leadership transfers are SINGLE moves (kind 0); replica
        # swaps are kind 1 — this loop proposes no leadership rotations
        acc_sw = jnp.sum((write_a & is_swap_cand[safe]).astype(jnp.int32))
        acc_ld = jnp.sum((write_a & ~is_swap_cand[safe]).astype(jnp.int32))
        ss = bump_kind_counters(
            ss,
            jnp.arange(3),
            jnp.asarray([K_ld, K_sw, 0], jnp.int32),
            jnp.stack([acc_ld, acc_sw, jnp.asarray(0, jnp.int32)]),
        )
        it = it + 1
        stale = jnp.where(any_better, 0, stale + 1)
        return ss, it, stale, moves + n_applied

    return cond, body


@costmodel.instrument("swap-polish-loop")
@functools.partial(
    jax.jit, static_argnames=("goal_names", "cfg", "opts", "max_pt")
)
def _swap_polish_loop(
    m: TensorClusterModel,
    state0: SearchState,
    key0: jnp.ndarray,
    max_iters: jnp.ndarray,
    patience: jnp.ndarray,
    guard_on: jnp.ndarray,
    *,
    goal_names: tuple[str, ...],
    cfg: GoalConfig,
    opts: SwapPolishOptions,
    max_pt: int,
):
    """Monolithic while_loop engine (``chunk_iters=0``) — the parity
    reference for the chunked swap-polish driver."""
    cond, body = _make_swap_iter(
        m, key0, max_iters, patience, guard_on,
        goal_names=goal_names, cfg=cfg, opts=opts, max_pt=max_pt,
    )
    zero = jnp.asarray(0, jnp.int32)
    state, n_iters, _, n_moves = jax.lax.while_loop(
        cond, body, (state0, zero, zero, zero)
    )
    return state, n_iters, n_moves


@costmodel.instrument(
    "swap-polish-chunk", iters=lambda k: k["opts"].chunk_iters
)
@functools.partial(
    jax.jit,
    static_argnames=("goal_names", "cfg", "opts", "max_pt"),
    donate_argnums=(0,),
)
def _swap_polish_chunk(
    state: SearchState,
    it: jnp.ndarray,
    stale: jnp.ndarray,
    moves: jnp.ndarray,
    m: TensorClusterModel,
    key0: jnp.ndarray,
    max_iters: jnp.ndarray,
    patience: jnp.ndarray,
    guard_on: jnp.ndarray,
    tap=None,
    *,
    goal_names: tuple[str, ...],
    cfg: GoalConfig,
    opts: SwapPolishOptions,
    max_pt: int,
):
    """One donated-state chunk of the swap-polish descent (see
    `_greedy_chunk` — same telemetry-tap contract)."""
    cond, body = _make_swap_iter(
        m, key0, max_iters, patience, guard_on,
        goal_names=goal_names, cfg=cfg, opts=opts, max_pt=max_pt,
    )
    state, it, stale, moves, done = _run_chunk_body(
        cond, body, opts.chunk_iters, state, it, stale, moves
    )
    if tap is not None:
        from ccx.search import telemetry

        tap = telemetry.record(
            tap, state.cost_vec, state.n_prop_kind, state.n_acc_kind,
            jnp.zeros((), jnp.float32),
        )
    return state, it, stale, moves, tap, done


def swap_polish(
    m: TensorClusterModel,
    cfg: GoalConfig = GoalConfig(),
    goal_names: tuple[str, ...] = DEFAULT_GOAL_ORDER,
    opts: SwapPolishOptions = SwapPolishOptions(),
    *,
    init: tuple | None = None,
    defer_stack_after: bool = False,
) -> GreedyResult:
    """Run the usage-coupled swap-polish descent to a local optimum.

    Only lex-improving, hard-safe candidates are applied, so the result is
    never lexicographically worse than the input; replica counts per broker
    are preserved exactly (replica swaps exchange brokers, leadership
    transfers move no replica). Intra-broker-only stacks have no
    inter-broker swap space — callers gate on ``allows_inter_broker``.

    ``init`` is an optional ``(state0, stack_before)`` pair from a caller
    that already paid the init evaluation (the warm pipeline's fused init
    program shares ONE aggregate pass between the descent state, the
    stack eval and the drift scan — two full [P]->[B/T] passes saved per
    steady-state window at B5). ``defer_stack_after=True`` skips the
    final full stack eval and returns ``stack_after=None`` — for callers
    that re-evaluate AFTER a later pipeline stage (preferred-leader
    canonicalization) anyway. Cold callers pass neither and trace the
    exact programs they always did."""
    if not allows_inter_broker(goal_names):
        raise ValueError(
            "swap_polish proposes inter-broker swaps; intra-broker-only "
            "stacks must not run it"
        )
    max_pt = max_partitions_per_topic(m)
    if init is not None:
        state0, stack_before = init
    else:
        stack_before = evaluate_stack(m, cfg, goal_names)
        state0 = _descent_init(
            m, jax.random.PRNGKey(opts.seed),
            goal_names=goal_names, cfg=cfg, max_pt=max_pt,
        )
    key0 = jax.random.PRNGKey(opts.seed + 1)
    mi = jnp.asarray(opts.max_iters, jnp.int32)
    pat = jnp.asarray(opts.patience, jnp.int32)
    guard = jnp.asarray(opts.trd_guard, bool)
    # iteration budgets and the guard are traced operands; zero them in
    # the compile key so every budget shares one program per chunk shape
    opts_key = dataclasses.replace(
        opts, max_iters=0, patience=0, seed=0, trd_guard=False
    )
    # shape-keyed descent span: attrs name the compiled-program shape
    # (candidate counts + chunk size) so a flight recording of a stalled
    # descent identifies WHICH program was being compiled/run — heartbeats
    # from drive_chunks attach the live chunk index to this span
    with TRACER.span(
        "swap-polish-descent",
        swapCandidates=opts.n_swap_candidates,
        leadCandidates=opts.n_lead_candidates,
        chunkIters=opts.chunk_iters,
        maxIters=opts.max_iters,
    ):
        convergence = None
        if opts.chunk_iters > 0:
            from ccx.search import telemetry

            tap = (
                telemetry.make_tap(len(goal_names))
                if telemetry.enabled()
                else None
            )
            zero = jnp.asarray(0, jnp.int32)
            carry = (_unalias_placement(state0), zero, zero, zero, tap)

            def run_one(c, off):
                *c2, tp, done = _swap_polish_chunk(
                    *c[:4], m, key0, mi, pat, guard, c[4],
                    goal_names=goal_names, cfg=cfg, opts=opts_key,
                    max_pt=max_pt,
                )
                return tuple(c2) + (tp,), done

            probe = None
            if tap is not None:
                def probe(c):
                    return c[0].cost_vec[0]

            state, n_iters, _, n_moves, tap = drive_chunks(
                run_one, carry, total=opts.max_iters,
                chunk=opts.chunk_iters, probe=probe,
            )
            convergence = telemetry.decode(
                tap, goal_names, chunk_size=opts.chunk_iters,
                budget=opts.max_iters,
            )
        else:
            state, n_iters, n_moves = _swap_polish_loop(
                m, state0, key0, mi, pat, guard,
                goal_names=goal_names, cfg=cfg,
                opts=dataclasses.replace(opts_key, chunk_iters=0),
                max_pt=max_pt,
            )
    result_model = with_placement(m, state)
    stack_after = (
        None if defer_stack_after
        else evaluate_stack(result_model, cfg, goal_names)
    )
    return GreedyResult(
        model=result_model,
        stack_before=stack_before,
        stack_after=stack_after,
        n_moves=int(np.asarray(n_moves)),
        n_iters=int(np.asarray(n_iters)),
        n_prop_kind=tuple(int(x) for x in np.asarray(state.n_prop_kind)),
        n_acc_kind=tuple(int(x) for x in np.asarray(state.n_acc_kind)),
        convergence=convergence,
    )
