"""Greedy lexicographic hill-climbing — the faithful-semantics oracle.

Parity: the reference's ``GoalOptimizer.optimizations`` walks goals in
priority order, and a move is only taken when every already-optimized goal
accepts it (``actionAcceptance``, SURVEY.md call stack 3.2 hot loop #1).
That is exactly lexicographic ordering on the per-goal cost vector: a move
is an improvement iff it strictly reduces some goal's cost without raising
any higher-priority goal's. This module implements that acceptance rule
directly and serves as

* the correctness oracle the annealer's results are score-compared against
  (SURVEY.md section 4 "score-parity vs a slow Python greedy oracle"), and
* the post-SA repair/polish pass: started from an annealed placement it
  fixes residual hard violations and low-tier regressions (e.g. preferred
  leadership) without breaking higher-priority goals, mirroring the
  reference's sequential re-optimization.

The whole loop runs ON DEVICE as one jitted ``lax.while_loop``: each
iteration vmaps ``n_candidates`` proposals, scores each in O(R) via the
incremental move scorer (ccx.search.state — no per-candidate aggregate
copies), selects the lexicographic argmin on device, applies it, and
early-exits after ``patience`` consecutive iterations with no improving
candidate. Round 1's host-driven loop paid one device round-trip + a
~0.5 GB/batch aggregate materialization *per iteration* (~3.5 s/iter at B5
scale); this version's per-iteration cost is a few MB of [B]-level traffic.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ccx.goals.base import GoalConfig
from ccx.goals.stack import DEFAULT_GOAL_ORDER, StackResult, evaluate_stack
from ccx.model.tensor_model import TensorClusterModel
from ccx.search.annealer import (
    CAPACITY_GOALS,
    RACK_TARGET_GOALS,
    ProposalParams,
    allows_inter_broker,
    goal_tols,
    hot_partition_list,
    propose_move,
    propose_swap,
)
from ccx.search.state import (
    SearchState,
    apply_move,
    apply_swap,
    init_search_state,
    make_move_scorer,
    make_swap_scorer,
    make_topic_group,
    max_partitions_per_topic,
    stack_needs_topic,
    with_placement,
)


@dataclasses.dataclass(frozen=True)
class GreedyOptions:
    #: candidate moves scored per iteration (vmapped on device)
    n_candidates: int = 512
    max_iters: int = 2000
    #: stop after this many consecutive iterations with no improving candidate
    patience: int = 8
    p_leadership: float = 0.25
    p_disk: float = 0.0
    p_biased_dest: float = 0.5
    p_evac: float = 0.3
    #: fraction of candidates proposed as two-partition REPLICA_SWAPs —
    #: swaps preserve replica counts, reaching load-balance states single
    #: relocations cannot (ref ActionType, SURVEY.md C20); forced to 0 for
    #: intra-broker stacks
    swap_fraction: float = 0.25
    seed: int = 0


@dataclasses.dataclass
class GreedyResult:
    model: TensorClusterModel
    stack_before: StackResult
    stack_after: StackResult
    n_moves: int
    n_iters: int


def _lex_lt_batch(costs: jnp.ndarray, cur: jnp.ndarray) -> jnp.ndarray:
    """bool[N] — candidate vector lexicographically < current (with per-goal
    tolerance): the first significantly-changed goal improved."""
    d = costs - cur[None, :]
    tol = goal_tols(cur)[None, :]
    sig = jnp.abs(d) > tol
    any_sig = jnp.any(sig, axis=1)
    first = jnp.argmax(sig, axis=1)
    d_first = jnp.take_along_axis(d, first[:, None], axis=1)[:, 0]
    return any_sig & (d_first < 0)


def _lex_argmin(costs: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Index of the lexicographically-smallest masked row of costs[N, G]
    (on device; G is static and small, so the column loop unrolls)."""
    alive = mask
    G = costs.shape[1]
    for g in range(G):
        col = jnp.where(alive, costs[:, g], jnp.inf)
        mn = jnp.min(col)
        tol = 1e-6 + 1e-6 * jnp.abs(mn)
        alive = alive & (col <= mn + tol)
    return jnp.argmax(alive)


@functools.partial(
    jax.jit, static_argnames=("goal_names", "cfg", "pp", "opts", "max_pt")
)
def _greedy_loop(
    m: TensorClusterModel,
    state0: SearchState,
    evac: jnp.ndarray,
    n_evac: jnp.ndarray,
    key0: jnp.ndarray,
    *,
    goal_names: tuple[str, ...],
    cfg: GoalConfig,
    pp: ProposalParams,
    opts: GreedyOptions,
    max_pt: int,
):
    group = make_topic_group(m, max_pt) if stack_needs_topic(goal_names) else None
    scorer = make_move_scorer(m, goal_names, cfg)
    n_swap = int(opts.n_candidates * opts.swap_fraction) if pp.p_swap > 0 else 0
    n_single = max(opts.n_candidates - n_swap, 1)
    swap_scorer = make_swap_scorer(m, goal_names, cfg) if n_swap else None

    def cond(carry):
        _, it, stale, _ = carry
        return (it < opts.max_iters) & (stale < opts.patience)

    def body(carry):
        ss, it, stale, moves = carry
        keys = jax.random.split(
            jax.random.fold_in(key0, it), n_single + max(n_swap, 1)
        )

        def one(k):
            p, view, old, new, feasible = propose_move(k, ss, m, pp, evac, n_evac)
            delta = scorer(ss, view, old, new)
            return p, view, old, new, feasible, delta

        ps, views, olds, news, feas, deltas = jax.vmap(one)(keys[:n_single])
        better = feas & _lex_lt_batch(deltas.cost_vec, ss.cost_vec)
        any_single = jnp.any(better)
        best = _lex_argmin(deltas.cost_vec, better)
        pick = lambda tree: jax.tree.map(lambda a: a[best], tree)  # noqa: E731

        def apply_best_single(s):
            return apply_move(
                s, m, ps[best], pick(views), pick(olds), pick(news),
                pick(deltas), any_single, group=group,
            )

        if n_swap:
            def one_swap(k):
                p1, v1, o1, n1, p2, v2, o2, n2, ok = propose_swap(k, ss, m, pp)
                delta = swap_scorer(ss, v1, o1, n1, v2, o2, n2)
                return p1, v1, o1, n1, p2, v2, o2, n2, ok, delta

            sw = jax.vmap(one_swap)(keys[n_single:])
            sw_ok, sw_delta = sw[8], sw[9]
            sw_better = sw_ok & _lex_lt_batch(sw_delta.cost_vec, ss.cost_vec)
            any_swap = jnp.any(sw_better)
            best_w = _lex_argmin(sw_delta.cost_vec, sw_better)
            pick_w = lambda tree: jax.tree.map(lambda a: a[best_w], tree)  # noqa: E731

            # take the swap iff it is feasible-better and the best single is
            # not lexicographically ahead of it
            single_vec = deltas.cost_vec[best]
            swap_vec = sw_delta.cost_vec[best_w]
            d = swap_vec - single_vec
            tol = goal_tols(single_vec)
            sig = jnp.abs(d) > tol
            swap_ahead = jnp.any(sig) & (d[jnp.argmax(sig)] < 0)
            take_swap = any_swap & (~any_single | swap_ahead)

            def apply_best_swap(s):
                return apply_swap(
                    s, m, sw[0][best_w], pick_w(sw[1]), pick_w(sw[2]),
                    pick_w(sw[3]), sw[4][best_w], pick_w(sw[5]), pick_w(sw[6]),
                    pick_w(sw[7]), pick_w(sw_delta), any_swap, group=group,
                )

            ss = jax.lax.cond(take_swap, apply_best_swap, apply_best_single, ss)
            any_better = any_single | any_swap
        else:
            ss = apply_best_single(ss)
            any_better = any_single

        it = it + 1
        stale = jnp.where(any_better, 0, stale + 1)
        moves = moves + any_better.astype(jnp.int32)
        return ss, it, stale, moves

    zero = jnp.asarray(0, jnp.int32)
    state, n_iters, _, n_moves = jax.lax.while_loop(
        cond, body, (state0, zero, zero, zero)
    )
    return state, n_iters, n_moves


def greedy_optimize(
    m: TensorClusterModel,
    cfg: GoalConfig = GoalConfig(),
    goal_names: tuple[str, ...] = DEFAULT_GOAL_ORDER,
    opts: GreedyOptions = GreedyOptions(),
) -> GreedyResult:
    """Hill-climb the lexicographic goal-cost vector to a local optimum."""
    stack_before = evaluate_stack(m, cfg, goal_names)
    p_real = int(np.asarray(m.partition_valid).sum())
    bv = np.asarray(m.broker_valid)
    b_real = int(np.max(np.where(bv, np.arange(m.B), -1))) + 1
    allow_inter = allows_inter_broker(goal_names)
    pp = ProposalParams(
        p_real=p_real,
        b_real=b_real,
        p_leadership=opts.p_leadership,
        p_disk=opts.p_disk,
        p_biased_dest=opts.p_biased_dest,
        p_evac=opts.p_evac,
        target_rack=bool(RACK_TARGET_GOALS & set(goal_names)),
        allow_inter=allow_inter,
        p_swap=opts.swap_fraction if allow_inter else 0.0,
        target_capacity=bool(CAPACITY_GOALS & set(goal_names)),
        cap_thresholds=tuple(cfg.capacity_threshold),
    )

    evac_np, n_evac_i = hot_partition_list(m, goal_names, cfg)
    max_pt = max_partitions_per_topic(m)
    group0 = (
        make_topic_group(m, max_pt) if stack_needs_topic(goal_names) else None
    )
    state0 = init_search_state(
        m, cfg, goal_names, jax.random.PRNGKey(opts.seed), group=group0
    )
    state, n_iters, n_moves = _greedy_loop(
        m,
        state0,
        jnp.asarray(evac_np),
        jnp.asarray(n_evac_i, jnp.int32),
        jax.random.PRNGKey(opts.seed + 1),
        goal_names=goal_names,
        cfg=cfg,
        pp=pp,
        opts=opts,
        max_pt=max_pt,
    )

    result_model = with_placement(m, state)
    stack_after = evaluate_stack(result_model, cfg, goal_names)
    return GreedyResult(
        model=result_model,
        stack_before=stack_before,
        stack_after=stack_after,
        n_moves=int(np.asarray(n_moves)),
        n_iters=int(np.asarray(n_iters)),
    )
