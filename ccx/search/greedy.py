"""Greedy lexicographic hill-climbing — the faithful-semantics oracle.

Parity: the reference's ``GoalOptimizer.optimizations`` walks goals in
priority order, and a move is only taken when every already-optimized goal
accepts it (``actionAcceptance``, SURVEY.md call stack 3.2 hot loop #1).
That is exactly lexicographic ordering on the per-goal cost vector: a move
is an improvement iff it strictly reduces some goal's cost without raising
any higher-priority goal's. This module implements that acceptance rule
directly — batched candidate scoring on device (vmapped incremental
evaluation), lexicographic selection on host — and serves as

* the correctness oracle the annealer's results are score-compared against
  (SURVEY.md section 4 "score-parity vs a slow Python greedy oracle"), and
* the post-SA repair/polish pass: started from an annealed placement it
  fixes residual hard violations and low-tier regressions (e.g. preferred
  leadership) without breaking higher-priority goals, mirroring the
  reference's sequential re-optimization.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ccx.goals.base import GoalConfig
from ccx.goals.stack import DEFAULT_GOAL_ORDER, StackResult, evaluate_stack
from ccx.model.tensor_model import TensorClusterModel
from ccx.search.annealer import (
    RACK_TARGET_GOALS,
    ProposalParams,
    allows_inter_broker,
    hot_partition_list,
    propose_move,
)
from ccx.search.state import (
    SearchState,
    init_search_state,
    make_goal_vector_fn,
    partition_row_sums,
    scatter_partition,
    with_placement,
)


@dataclasses.dataclass(frozen=True)
class GreedyOptions:
    #: candidate moves scored per iteration (vmapped on device)
    n_candidates: int = 512
    max_iters: int = 2000
    #: stop after this many consecutive iterations with no improving candidate
    patience: int = 8
    p_leadership: float = 0.25
    p_disk: float = 0.0
    p_biased_dest: float = 0.5
    p_evac: float = 0.3
    seed: int = 0
    #: accept up to this many distinct-partition improving candidates per
    #: iteration (composition is exact on state; the post-batch re-score
    #: rolls back to single-move acceptance if the combined effect is a
    #: lexicographic regression). 1 = reference-faithful one-move-at-a-time.
    batch_moves: int = 8


@dataclasses.dataclass
class GreedyResult:
    model: TensorClusterModel
    stack_before: StackResult
    stack_after: StackResult
    n_moves: int
    n_iters: int


@functools.partial(jax.jit, static_argnames=("goal_names", "cfg", "pp"))
def _score_candidates(
    state: SearchState,
    key: jnp.ndarray,
    m: TensorClusterModel,
    evac: jnp.ndarray,
    n_evac: jnp.ndarray,
    *,
    goal_names: tuple[str, ...],
    cfg: GoalConfig,
    pp: ProposalParams,
):
    """Score n_candidates random moves; return per-candidate goal-cost
    vectors plus the move payloads (rows are applied host-side)."""
    vector_fn = make_goal_vector_fn(m, goal_names, cfg)

    def one(k):
        p, old, new, feasible = propose_move(k, state, m, pp, evac, n_evac)
        agg1 = scatter_partition(state.agg, m, p, *old, jnp.float32(-1), jnp.int32(-1))
        agg2 = scatter_partition(agg1, m, p, *new, jnp.float32(1), jnp.int32(1))
        part = state.part_sums - partition_row_sums(m, p, *old) + partition_row_sums(
            m, p, *new
        )
        costs = vector_fn(agg2, part)
        return p, new, feasible, costs, part

    return jax.vmap(one)(key)


@functools.partial(jax.jit, static_argnames=("goal_names", "cfg"))
def _eval_vector(agg, part_sums, m, *, goal_names, cfg):
    """Goal-cost vector of the current state (module-level jit so repeated
    greedy_optimize calls share the compile cache)."""
    return make_goal_vector_fn(m, goal_names, cfg)(agg, part_sums)


@functools.partial(jax.jit, static_argnames=())
def _apply_move(
    state: SearchState,
    m: TensorClusterModel,
    p: jnp.ndarray,
    new_assign: jnp.ndarray,
    new_leader: jnp.ndarray,
    new_disk: jnp.ndarray,
    part_sums: jnp.ndarray,
) -> SearchState:
    old = (state.assignment[p], state.leader_slot[p], state.replica_disk[p])
    agg1 = scatter_partition(state.agg, m, p, *old, jnp.float32(-1), jnp.int32(-1))
    agg2 = scatter_partition(
        agg1, m, p, new_assign, new_leader, new_disk, jnp.float32(1), jnp.int32(1)
    )
    return state.replace(
        assignment=state.assignment.at[p].set(new_assign),
        leader_slot=state.leader_slot.at[p].set(new_leader),
        replica_disk=state.replica_disk.at[p].set(new_disk),
        agg=agg2,
        part_sums=part_sums,
        n_accepted=state.n_accepted + 1,
    )


def _lex_better(cand: np.ndarray, cur: np.ndarray, tol: float = 1e-6) -> bool:
    """cand < cur lexicographically (with tolerance)."""
    for i in range(cur.shape[0]):
        if cand[i] < cur[i] - tol:
            return True
        if cand[i] > cur[i] + tol:
            return False
    return False


def greedy_optimize(
    m: TensorClusterModel,
    cfg: GoalConfig = GoalConfig(),
    goal_names: tuple[str, ...] = DEFAULT_GOAL_ORDER,
    opts: GreedyOptions = GreedyOptions(),
) -> GreedyResult:
    """Hill-climb the lexicographic goal-cost vector to a local optimum."""
    stack_before = evaluate_stack(m, cfg, goal_names)
    p_real = int(np.asarray(m.n_partitions))
    b_real = (
        int(np.asarray(jnp.max(jnp.where(m.broker_valid, jnp.arange(m.B), -1)))) + 1
    )
    pp = ProposalParams(
        p_real=p_real,
        b_real=b_real,
        p_leadership=opts.p_leadership,
        p_disk=opts.p_disk,
        p_biased_dest=opts.p_biased_dest,
        p_evac=opts.p_evac,
        target_rack=bool(RACK_TARGET_GOALS & set(goal_names)),
        allow_inter=allows_inter_broker(goal_names),
    )

    evac_np, n_evac_i = hot_partition_list(m, goal_names)
    evac = jnp.asarray(evac_np)
    n_evac = jnp.asarray(n_evac_i, jnp.int32)

    state = init_search_state(m, cfg, goal_names, jax.random.PRNGKey(opts.seed))
    cur = np.asarray(
        _eval_vector(state.agg, state.part_sums, m, goal_names=goal_names, cfg=cfg)
    )

    key = jax.random.PRNGKey(opts.seed + 1)
    n_moves = 0
    stale = 0
    it = 0
    for it in range(opts.max_iters):
        key, sub = jax.random.split(key)
        ks = jax.random.split(sub, opts.n_candidates)
        ps, news, feas, costs, parts = _score_candidates(
            state, ks, m, evac, n_evac, goal_names=goal_names, cfg=cfg, pp=pp
        )
        costs_np = np.asarray(costs)
        feas_np = np.asarray(feas)
        ps_np = np.asarray(ps)

        # feasible strict improvements vs the current vector, best first
        improving = [
            i for i in range(opts.n_candidates)
            if feas_np[i] and _lex_better(costs_np[i], cur)
        ]
        if not improving:
            stale += 1
            if stale >= opts.patience:
                break
            continue
        stale = 0
        improving.sort(key=lambda i: tuple(costs_np[i]))

        # take up to batch_moves candidates on distinct partitions; state
        # composition is exact (agg re-derived per apply; part_sums composed
        # from per-candidate deltas), only the predicted vector is stale
        taken: list[int] = []
        seen_p: set[int] = set()
        for i in improving:
            p = int(ps_np[i])
            if p in seen_p:
                continue
            seen_p.add(p)
            taken.append(i)
            if len(taken) >= max(opts.batch_moves, 1):
                break

        prev_state, prev_cur = state, cur
        orig_part = state.part_sums
        for i in taken:
            part_corr = state.part_sums + (parts[i] - orig_part)
            state = _apply_move(
                state, m, ps[i], news[0][i], news[1][i], news[2][i], part_corr
            )
        if len(taken) == 1:
            cur = costs_np[taken[0]]
        else:
            cur = np.asarray(_eval_vector(
                state.agg, state.part_sums, m, goal_names=goal_names, cfg=cfg
            ))
            if not _lex_better(cur, prev_cur):
                # interacting moves regressed: fall back to the single best
                state, cur = prev_state, prev_cur
                i = taken[0]
                state = _apply_move(
                    state, m, ps[i], news[0][i], news[1][i], news[2][i],
                    parts[i],
                )
                cur = costs_np[i]
                taken = taken[:1]
        n_moves += len(taken)

    result_model = with_placement(m, state)
    stack_after = evaluate_stack(result_model, cfg, goal_names)
    return GreedyResult(
        model=result_model,
        stack_before=stack_before,
        stack_after=stack_after,
        n_moves=n_moves,
        n_iters=it + 1,
    )
