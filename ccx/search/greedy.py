"""Greedy lexicographic hill-climbing — the faithful-semantics oracle.

Parity: the reference's ``GoalOptimizer.optimizations`` walks goals in
priority order, and a move is only taken when every already-optimized goal
accepts it (``actionAcceptance``, SURVEY.md call stack 3.2 hot loop #1).
That is exactly lexicographic ordering on the per-goal cost vector: a move
is an improvement iff it strictly reduces some goal's cost without raising
any higher-priority goal's. This module implements that acceptance rule
directly and serves as

* the correctness oracle the annealer's results are score-compared against
  (SURVEY.md section 4 "score-parity vs a slow Python greedy oracle"), and
* the post-SA repair/polish pass: started from an annealed placement it
  fixes residual hard violations and low-tier regressions (e.g. preferred
  leadership) without breaking higher-priority goals, mirroring the
  reference's sequential re-optimization.

The whole loop runs ON DEVICE as one jitted ``lax.while_loop``: each
iteration vmaps ``n_candidates`` proposals, scores each in O(R) via the
incremental move scorer (ccx.search.state — no per-candidate aggregate
copies), selects the lexicographic argmin on device, applies it, and
early-exits after ``patience`` consecutive iterations with no improving
candidate. Round 1's host-driven loop paid one device round-trip + a
~0.5 GB/batch aggregate materialization *per iteration* (~3.5 s/iter at B5
scale); this version's per-iteration cost is a few MB of [B]-level traffic.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ccx.goals.base import GoalConfig
from ccx.goals.stack import DEFAULT_GOAL_ORDER, StackResult, evaluate_stack
from ccx.model.tensor_model import TensorClusterModel
from ccx.search.annealer import (
    CAPACITY_GOALS,
    RACK_TARGET_GOALS,
    ProposalParams,
    allows_inter_broker,
    goal_tols,
    hot_partition_list,
    lead_swap_share,
    propose_move,
    propose_swap,
)
from ccx.goals import topic_terms as tt
from ccx.goals.base import GOAL_REGISTRY
from ccx.search.state import (
    SearchState,
    _placement_updates,
    apply_swap,
    broker_pressure,
    bump_kind_counters,
    gather_views,
    init_search_state,
    make_cost_vector_fn,
    make_move_scorer,
    make_swap_scorer,
    make_topic_group,
    max_partitions_per_topic,
    scatter_partition,
    stack_needs_topic,
    usage_weights,
    with_placement,
)


@dataclasses.dataclass(frozen=True)
class GreedyOptions:
    #: candidate moves scored per iteration (vmapped on device)
    n_candidates: int = 512
    max_iters: int = 2000
    #: stop after this many consecutive iterations with no improving candidate
    patience: int = 8
    p_leadership: float = 0.25
    p_disk: float = 0.0
    p_biased_dest: float = 0.5
    p_evac: float = 0.3
    #: fraction of candidates proposed as two-partition REPLICA_SWAPs —
    #: swaps preserve replica counts, reaching load-balance states single
    #: relocations cannot (ref ActionType, SURVEY.md C20); forced to 0 for
    #: intra-broker stacks
    swap_fraction: float = 0.25
    #: apply up to this many NON-CONFLICTING improving single moves per
    #: iteration (disjoint partitions, topics and touched-broker sets, each
    #: hard-safe and lex-improving vs the iteration's base state — the
    #: composition is then exactly additive and itself lex-improving).
    #: 1 restores classic best-move hill climbing; >1 is what lets the
    #: polish clean thousands of residuals at B5 scale within max_iters.
    batch_moves: int = 16
    #: restrict EVERY proposal to leadership movements: single proposals are
    #: all LEADERSHIP_MOVEMENT (p_leadership forced to 1) and swap proposals
    #: are all count-preserving leadership rotations — no replica ever
    #: changes broker. This is the final preferred-leadership pass of the
    #: pipeline (ref: PreferredLeaderElectionGoal runs last in the goal
    #: order, SURVEY.md section 2.3) and the demote fast path.
    leadership_only: bool = False
    seed: int = 0


@dataclasses.dataclass
class GreedyResult:
    model: TensorClusterModel
    stack_before: StackResult
    stack_after: StackResult
    n_moves: int
    n_iters: int
    #: per-move-kind (single, replica-swap, leadership-swap) proposal and
    #: acceptance counts (state.MOVE_KIND_NAMES) — observability
    n_prop_kind: tuple[int, ...] = (0, 0, 0)
    n_acc_kind: tuple[int, ...] = (0, 0, 0)


def _lex_lt_batch(costs: jnp.ndarray, cur: jnp.ndarray) -> jnp.ndarray:
    """bool[N] — candidate vector lexicographically < current (with per-goal
    tolerance): the first significantly-changed goal improved."""
    d = costs - cur[None, :]
    tol = goal_tols(cur)[None, :]
    sig = jnp.abs(d) > tol
    any_sig = jnp.any(sig, axis=1)
    first = jnp.argmax(sig, axis=1)
    d_first = jnp.take_along_axis(d, first[:, None], axis=1)[:, 0]
    return any_sig & (d_first < 0)


def _lex_argmin(costs: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Index of the lexicographically-smallest masked row of costs[N, G]
    (on device; G is static and small, so the column loop unrolls)."""
    alive = mask
    G = costs.shape[1]
    for g in range(G):
        col = jnp.where(alive, costs[:, g], jnp.inf)
        mn = jnp.min(col)
        tol = 1e-6 + 1e-6 * jnp.abs(mn)
        alive = alive & (col <= mn + tol)
    return jnp.argmax(alive)


@functools.partial(
    jax.jit, static_argnames=("goal_names", "cfg", "pp", "opts", "max_pt")
)
def _greedy_loop(
    m: TensorClusterModel,
    state0: SearchState,
    evac: jnp.ndarray,
    n_evac: jnp.ndarray,
    key0: jnp.ndarray,
    max_iters: jnp.ndarray,
    patience: jnp.ndarray,
    guard_on: jnp.ndarray,
    *,
    goal_names: tuple[str, ...],
    cfg: GoalConfig,
    pp: ProposalParams,
    opts: GreedyOptions,
    max_pt: int,
):
    # max_iters/patience arrive as traced scalars (and are ZEROED in the
    # static `opts` key by the caller): iteration budgets are while_loop
    # bound data, not program shape, so lean polish (400 iters) and full
    # polish (1600) share ONE compiled program — a B5-scale greedy compile
    # is >10 min on TPU v5e.
    group = make_topic_group(m, max_pt) if stack_needs_topic(goal_names) else None
    scorer = make_move_scorer(m, goal_names, cfg)
    vector_fn = make_cost_vector_fn(m, goal_names, cfg)
    hard_arr = jnp.asarray(tuple(GOAL_REGISTRY[n].hard for n in goal_names))
    # trd-guard column mask: with guard_on (a traced scalar, so guarded and
    # unguarded polish share ONE compiled program) candidates that
    # significantly RAISE the TopicReplicaDistribution tier are vetoed like
    # hard regressions. TRD sits below the usage tiers in lex priority, so
    # an unguarded polish legally trades freshly-shed topic cells back for
    # usage cells — the round-4 shed/re-polish ratchet's loss mechanism.
    guard_cols = jnp.asarray(
        tuple(n == "TopicReplicaDistributionGoal" for n in goal_names)
    )
    n_swap = int(opts.n_candidates * opts.swap_fraction) if pp.p_swap > 0 else 0
    n_single = max(opts.n_candidates - n_swap, 1)
    n_batch = max(min(opts.batch_moves, n_single), 1)
    swap_scorer = make_swap_scorer(m, goal_names, cfg) if n_swap else None
    B, T = m.B, m.num_topics

    def cond(carry):
        _, it, stale, _ = carry
        return (it < max_iters) & (stale < patience)

    def body(carry):
        ss, it, stale, moves = carry
        keys = jax.random.split(
            jax.random.fold_in(key0, it), n_single + max(n_swap, 1)
        )

        def one(k):
            p, view, old, new, feasible = propose_move(k, ss, m, pp, evac, n_evac)
            delta = scorer(ss, view, old, new)
            return p, view, old, new, feasible, delta

        ps, views, olds, news, feas, deltas = jax.vmap(one)(keys[:n_single])
        # hard-safety veto on top of lex improvement: lex_lt alone would let
        # a move improve a high tier while pushing a LOWER-priority hard
        # goal over (the reference's requirements checks forbid that), and
        # batch additivity needs every member's hard delta <= 0
        d_all = deltas.cost_vec - ss.cost_vec[None, :]
        sig_all = jnp.abs(d_all) > goal_tols(ss.cost_vec)[None, :]
        hard_up = jnp.any(sig_all & hard_arr[None, :] & (d_all > 0), axis=1)
        guard_up = guard_on & jnp.any(
            sig_all & guard_cols[None, :] & (d_all > 0), axis=1
        )
        better = (
            feas
            & ~hard_up
            & ~guard_up
            & _lex_lt_batch(deltas.cost_vec, ss.cost_vec)
        )
        any_single = jnp.any(better)
        best = _lex_argmin(deltas.cost_vec, better)
        pick = lambda tree: jax.tree.map(lambda a: a[best], tree)  # noqa: E731

        # ---- batched selection: greedily take the lexicographically best
        # remaining candidate whose {partitions, topic, touched brokers} are
        # disjoint from everything already taken. Disjointness makes every
        # per-broker/per-topic/per-partition goal term exactly additive, so
        # the composed batch is itself hard-safe and lex-improving (its net
        # change at the highest-priority changed tier is a sum of
        # improvements).
        old_rows, new_rows = olds[0], news[0]           # [N, R]
        touched = jnp.concatenate([old_rows, new_rows], axis=1)   # [N, 2R]
        tb = jnp.clip(touched, 0, B - 1)
        bmask = jnp.zeros((n_single, B), bool)
        bmask = jax.vmap(lambda z, bb, v: z.at[bb].set(v, mode="drop"))(
            bmask, jnp.where(touched >= 0, tb, B), touched >= 0
        )
        cand_t = views.topic                             # [N]

        def select(k, carry):
            alive, used_b, used_t, sel, count = carry
            conf = (
                jnp.any(bmask & used_b[None, :], axis=1)
                | used_t[jnp.clip(cand_t, 0, T - 1)]
            )
            ok = alive & ~conf
            any_ok = jnp.any(ok)
            idx = _lex_argmin(deltas.cost_vec, ok)
            take = any_ok
            sel = sel.at[k].set(jnp.where(take, idx, n_single))
            used_b = used_b | jnp.where(take, bmask[idx], False)
            used_t = used_t.at[jnp.clip(cand_t[idx], 0, T - 1)].max(take)
            alive = alive & (jnp.arange(n_single) != idx)
            return alive, used_b, used_t, sel, count + take.astype(jnp.int32)

        sel0 = jnp.full((n_batch,), n_single, jnp.int32)
        _, _, _, sel_idx, n_sel = jax.lax.fori_loop(
            0, n_batch, select,
            (better, jnp.zeros(B, bool), jnp.zeros(T, bool), sel0,
             jnp.asarray(0, jnp.int32)),
        )

        def apply_batch(s):
            taken = sel_idx < n_single                   # [K]
            safe = jnp.clip(sel_idx, 0, n_single - 1)

            def acc(k, carry):
                agg, part, mtl, trd, totals = carry
                i = safe[k]
                w = taken[k].astype(jnp.float32)
                wi = taken[k].astype(jnp.int32)
                view_i = jax.tree.map(lambda a: a[i], views)
                old_i = tuple(x[i] for x in olds)
                new_i = tuple(x[i] for x in news)
                agg = scatter_partition(agg, m, view_i, *old_i, -w, -wi)
                agg = scatter_partition(agg, m, view_i, *new_i, w, wi)
                part = part + w * (deltas.part_sums[i] - s.part_sums)
                mtl = mtl + w * deltas.d_mtl[i]
                trd = trd + w * deltas.d_trd[i]
                totals = totals.at[view_i.topic].add(w * deltas.d_total[i])
                return agg, part, mtl, trd, totals

            # Slot 0 always holds the lex-best candidate (_lex_argmin over
            # the improving set), so the state after acc(0, .) doubles as the
            # single-move fallback checkpoint.
            first = acc(0, (s.agg, s.part_sums, s.mtl_sum, s.trd_sum,
                            s.topic_totals))
            full = jax.lax.fori_loop(1, n_batch, acc, first)

            def costs_of(c):
                agg_c, part_c, mtl_c, trd_c, totals_c = c
                return vector_fn(
                    agg_c, part_c, mtl_c, trd_c, tt.trd_normalizer(m, totals_c)
                )

            cost_full = costs_of(full)
            # Disjointness makes sum-decomposable goal terms exactly
            # additive, but the leader-evenness and trd-normalizer couplings
            # are not sum-decomposable, and per-candidate vetoes are
            # tolerance-filtered — a composed batch can net-regress a tier
            # even though every member improved vs base. The composed vector
            # is recomputed exactly here; when it is not lex-better than the
            # iteration base, fall back to the best single move, which IS
            # exactly lex-improving.
            d_full = cost_full - s.cost_vec
            full_guard_up = guard_on & jnp.any(
                (jnp.abs(d_full) > goal_tols(s.cost_vec))
                & guard_cols
                & (d_full > 0)
            )
            batch_ok = (n_sel <= 1) | (
                _lex_lt_batch(cost_full[None, :], s.cost_vec)[0]
                # members are individually guard-safe but the trd normalizer
                # coupling is not sum-decomposable — re-check the composition
                & ~full_guard_up
            )
            agg, part, mtl, trd, totals = jax.tree.map(
                lambda a, b: jnp.where(batch_ok, a, b), full, first
            )
            cost_vec = jnp.where(batch_ok, cost_full, costs_of(first))
            n_applied = jnp.where(batch_ok, n_sel, jnp.minimum(n_sel, 1))
            write = taken & (batch_ok | (jnp.arange(n_batch) == 0))
            rows_k = new_rows[safe]
            leads_k = news[1][safe]
            disks_k = news[2][safe]
            return s.replace(
                agg=agg,
                part_sums=part,
                mtl_sum=mtl,
                trd_sum=trd,
                topic_totals=totals,
                cost_vec=cost_vec,
                n_accepted=s.n_accepted + n_applied,
                **_placement_updates(
                    s,
                    group,
                    write=write,
                    ps=ps[safe],
                    mirror=write & views.pvalid[safe],
                    global_ps=ps[safe],
                    ts=cand_t[safe],
                    rows=rows_k,
                    leads=leads_k,
                    disks=disks_k,
                ),
            )



        if n_swap:
            def one_swap(k):
                p1, v1, o1, n1, p2, v2, o2, n2, ok, is_lead = propose_swap(
                    k, ss, m, pp
                )
                delta = swap_scorer(ss, v1, o1, n1, v2, o2, n2)
                return p1, v1, o1, n1, p2, v2, o2, n2, ok, is_lead, delta

            sw = jax.vmap(one_swap)(keys[n_single:])
            sw_ok, sw_lead, sw_delta = sw[8], sw[9], sw[10]
            sw_d = sw_delta.cost_vec - ss.cost_vec[None, :]
            sw_sig = jnp.abs(sw_d) > goal_tols(ss.cost_vec)[None, :]
            sw_hard_up = jnp.any(
                sw_sig & hard_arr[None, :] & (sw_d > 0), axis=1
            )
            sw_guard_up = guard_on & jnp.any(
                sw_sig & guard_cols[None, :] & (sw_d > 0), axis=1
            )
            sw_better = (
                sw_ok
                & ~sw_hard_up
                & ~sw_guard_up
                & _lex_lt_batch(sw_delta.cost_vec, ss.cost_vec)
            )
            any_swap = jnp.any(sw_better)
            best_w = _lex_argmin(sw_delta.cost_vec, sw_better)
            pick_w = lambda tree: jax.tree.map(lambda a: a[best_w], tree)  # noqa: E731

            # take the swap iff it is feasible-better and the best single is
            # not lexicographically ahead of it
            single_vec = deltas.cost_vec[best]
            swap_vec = sw_delta.cost_vec[best_w]
            d = swap_vec - single_vec
            tol = goal_tols(single_vec)
            sig = jnp.abs(d) > tol
            swap_ahead = jnp.any(sig) & (d[jnp.argmax(sig)] < 0)
            take_swap = any_swap & (~any_single | swap_ahead)

            def apply_best_swap(s):
                return apply_swap(
                    s, m, sw[0][best_w], pick_w(sw[1]), pick_w(sw[2]),
                    pick_w(sw[3]), sw[4][best_w], pick_w(sw[5]), pick_w(sw[6]),
                    pick_w(sw[7]), pick_w(sw_delta), any_swap, group=group,
                )

            prev_accepted = ss.n_accepted
            ss = jax.lax.cond(take_swap, apply_best_swap, apply_batch, ss)
            any_better = any_single | any_swap
            n_applied = ss.n_accepted - prev_accepted
            # per-move-kind observability: the iteration proposed n_single
            # singles + n_swap swaps (split by variant); acceptances land
            # on whichever branch the cond took
            n_lead_prop = jnp.sum(sw_lead.astype(jnp.int32))
            acc_kind = jnp.where(
                take_swap, jnp.where(sw_lead[best_w], 2, 1), 0
            )
            ss = bump_kind_counters(
                ss,
                jnp.arange(3),
                jnp.stack(
                    [
                        jnp.asarray(n_single, jnp.int32),
                        jnp.asarray(n_swap, jnp.int32) - n_lead_prop,
                        n_lead_prop,
                    ]
                ),
                jnp.zeros(3, jnp.int32).at[acc_kind].add(n_applied),
            )
        else:
            prev_accepted = ss.n_accepted
            ss = apply_batch(ss)
            any_better = any_single
            n_applied = ss.n_accepted - prev_accepted
            ss = bump_kind_counters(ss, 0, n_single, n_applied)

        it = it + 1
        stale = jnp.where(any_better, 0, stale + 1)
        moves = moves + n_applied
        return ss, it, stale, moves

    zero = jnp.asarray(0, jnp.int32)
    state, n_iters, _, n_moves = jax.lax.while_loop(
        cond, body, (state0, zero, zero, zero)
    )
    return state, n_iters, n_moves


def greedy_optimize(
    m: TensorClusterModel,
    cfg: GoalConfig = GoalConfig(),
    goal_names: tuple[str, ...] = DEFAULT_GOAL_ORDER,
    opts: GreedyOptions = GreedyOptions(),
    trd_guard: bool = False,
) -> GreedyResult:
    """Hill-climb the lexicographic goal-cost vector to a local optimum.

    ``trd_guard`` additionally vetoes candidates that significantly worsen
    the TopicReplicaDistribution tier (a traced flag — no extra compiled
    program). Used by the optimizer's topic-rebalance stage so the usage
    re-polish cannot trade the shed's topic cells back (docs/perf-notes.md
    round-4 "shed/re-polish interplay"); plain polish keeps the full move
    space.
    """
    stack_before = evaluate_stack(m, cfg, goal_names)
    p_real = int(np.asarray(m.partition_valid).sum())
    bv = np.asarray(m.broker_valid)
    b_real = int(np.max(np.where(bv, np.arange(m.B), -1))) + 1
    allow_inter = allows_inter_broker(goal_names)
    lead_only = opts.leadership_only
    pp = ProposalParams(
        p_real=p_real,
        b_real=b_real,
        p_leadership=1.0 if lead_only else opts.p_leadership,
        p_disk=0.0 if lead_only else opts.p_disk,
        p_biased_dest=0.0 if lead_only else opts.p_biased_dest,
        p_evac=0.0 if lead_only else opts.p_evac,
        target_rack=(not lead_only)
        and bool(RACK_TARGET_GOALS & set(goal_names)),
        allow_inter=allow_inter and not lead_only,
        p_swap=opts.swap_fraction if allow_inter else 0.0,
        target_capacity=(not lead_only)
        and bool(CAPACITY_GOALS & set(goal_names)),
        cap_thresholds=tuple(cfg.capacity_threshold),
        # every swap proposal is a leadership rotation in leadership-only
        # mode — a replica swap would move replicas between brokers
        p_lead_swap=1.0 if lead_only else lead_swap_share(opts.p_leadership),
    )

    if lead_only:
        # leadership moves cannot heal placement offenders; skip the
        # aggregate pass that builds the hot list (p_evac is 0 anyway)
        evac_np, n_evac_i = np.zeros(1, np.int32), 0
    else:
        evac_np, n_evac_i = hot_partition_list(m, goal_names, cfg)
    max_pt = max_partitions_per_topic(m)
    group0 = (
        make_topic_group(m, max_pt) if stack_needs_topic(goal_names) else None
    )
    state0 = init_search_state(
        m, cfg, goal_names, jax.random.PRNGKey(opts.seed), group=group0
    )
    state, n_iters, n_moves = _greedy_loop(
        m,
        state0,
        jnp.asarray(evac_np),
        jnp.asarray(n_evac_i, jnp.int32),
        jax.random.PRNGKey(opts.seed + 1),
        jnp.asarray(opts.max_iters, jnp.int32),
        jnp.asarray(opts.patience, jnp.int32),
        jnp.asarray(trd_guard, bool),
        goal_names=goal_names,
        cfg=cfg,
        pp=pp,
        # iteration budgets are traced operands; zero them (and the RNG
        # seed, which only enters via PRNGKey data) in the compile key
        opts=dataclasses.replace(opts, max_iters=0, patience=0, seed=0),
        max_pt=max_pt,
    )

    result_model = with_placement(m, state)
    stack_after = evaluate_stack(result_model, cfg, goal_names)
    return GreedyResult(
        model=result_model,
        stack_before=stack_before,
        stack_after=stack_after,
        n_moves=int(np.asarray(n_moves)),
        n_iters=int(np.asarray(n_iters)),
        n_prop_kind=tuple(int(x) for x in np.asarray(state.n_prop_kind)),
        n_acc_kind=tuple(int(x) for x in np.asarray(state.n_acc_kind)),
    )


# ==========================================================================
# Usage-coupled swap polish — the dedicated count-preserving descent phase
# (VERDICT r5 next #4). The residual NwOut/LeaderReplica cells at lean
# effort sit in states single relocations structurally cannot reach (a
# count-band-neutral usage fix needs a SWAP; a leader-count fix needs a
# low-usage-delta transfer the uniform draws almost never find). This loop
# proposes ONLY coupled candidates: every iteration ranks all P partitions
# by live broker band pressure (ccx.search.state.broker_pressure) x
# per-replica usage, Gumbel-top-k draws (hot, cold) replica-swap pairs and
# pressure-ranked leadership transfers, scores them exactly
# (make_swap_scorer) and batch-applies the lexicographically-best disjoint
# subset. Pure descent: only lex-improving, hard-safe (optionally
# TRD-guarded) candidates are ever applied, so the phase's result is
# adopted unconditionally by the pipeline.
# ==========================================================================


@dataclasses.dataclass(frozen=True)
class SwapPolishOptions:
    #: coupled replica-swap pairs proposed per iteration (static shape).
    #: The pipeline splits `swap_polish_candidates` evenly between the two
    #: kinds so both its invocations share one compiled program.
    n_swap_candidates: int = 64
    #: coupled leadership transfers proposed per iteration (static shape)
    n_lead_candidates: int = 64
    max_iters: int = 200
    #: stop after this many consecutive iterations with no improving candidate
    patience: int = 10
    #: disjoint candidates applied per iteration (lex-best first)
    batch_moves: int = 16
    #: veto candidates that significantly worsen TopicReplicaDistribution
    #: (traced — guarded and unguarded share one program). Replica swaps
    #: between different topics move topic cells; after the shed converges
    #: the guard keeps the phase from trading TRD=0 back for usage cells.
    trd_guard: bool = True
    seed: int = 0


@functools.partial(
    jax.jit, static_argnames=("goal_names", "cfg", "opts", "max_pt")
)
def _swap_polish_loop(
    m: TensorClusterModel,
    state0: SearchState,
    key0: jnp.ndarray,
    max_iters: jnp.ndarray,
    patience: jnp.ndarray,
    guard_on: jnp.ndarray,
    *,
    goal_names: tuple[str, ...],
    cfg: GoalConfig,
    opts: SwapPolishOptions,
    max_pt: int,
):
    # iteration budgets arrive as traced scalars (zeroed in the static opts
    # key by the caller) — lean and full swap budgets share ONE program
    group = make_topic_group(m, max_pt) if stack_needs_topic(goal_names) else None
    swap_scorer = make_swap_scorer(m, goal_names, cfg)
    vector_fn = make_cost_vector_fn(m, goal_names, cfg)
    hard_arr = jnp.asarray(tuple(GOAL_REGISTRY[n].hard for n in goal_names))
    guard_cols = jnp.asarray(
        tuple(n == "TopicReplicaDistributionGoal" for n in goal_names)
    )
    B, T, R, P, D = m.B, m.num_topics, m.R, m.P, m.D
    # top_k caps at the padded partition count — tiny fixtures otherwise
    # request more candidates than partitions exist
    K_sw = max(min(int(opts.n_swap_candidates), P), 1)
    K_ld = max(min(int(opts.n_lead_candidates), P), 0)
    N = K_sw + K_ld
    n_batch = max(min(opts.batch_moves, N), 1)
    from ccx.common.resources import Resource
    from ccx.goals import topic_terms as tt_

    uw = usage_weights()
    u_lead_p = uw @ m.leader_load          # [P] combined usage, leader role
    u_foll_p = uw @ m.follower_load        # [P] combined usage, follower role
    lbytes_p = m.leader_load[Resource.NW_IN]
    avg_lb = jnp.sum(jnp.where(m.partition_valid, lbytes_p, 0.0)) / jnp.maximum(
        jnp.sum(m.partition_valid), 1
    )
    recv_ok = m.broker_valid & m.broker_alive & ~m.broker_excl_replicas
    lead_allowed = m.broker_valid & m.broker_alive & ~m.broker_excl_leadership
    is_swap_cand = jnp.arange(N) < K_sw    # [N] static candidate kind mask

    def cond(carry):
        _, it, stale, _ = carry
        return (it < max_iters) & (stale < patience)

    def body(carry):
        ss, it, stale, moves = carry
        key = jax.random.fold_in(key0, it)
        k_gh, k_gc, k_gl, k_d = jax.random.split(key, 4)
        press = broker_pressure(m, ss.agg, cfg)

        # ---- coupling scores over the full placement (O(P*R) elementwise;
        # the [P,R] reads are why this lives in its own loop, not the SA
        # step — the greedy-style loop tolerates extra carried-buffer uses)
        a = ss.assignment                      # [P, R]
        lead_slot = ss.leader_slot
        valid = (a >= 0) & m.partition_valid[:, None]
        movable = valid & ~m.partition_immovable[:, None]
        b = jnp.clip(a, 0, B - 1)
        is_l = jnp.arange(R)[None, :] == lead_slot[:, None]
        u = jnp.where(is_l, u_lead_p[:, None], u_foll_p[:, None])  # [P, R]

        hot_sc = press.usage_over[b] * u * movable
        hot_score = jnp.max(hot_sc, axis=1)
        hot_slot = jnp.argmax(hot_sc, axis=1).astype(jnp.int32)
        cold_sc = press.usage_under[b] * (1.0 / (1.0 + u)) * movable
        cold_score = jnp.max(cold_sc, axis=1)
        cold_slot = jnp.argmax(cold_sc, axis=1).astype(jnp.int32)

        # coupled leadership transfer: leader on a (leader-count or
        # leader-bytes) over broker -> follower slot on an under broker.
        # Two sub-couplings share the candidate budget: the LeaderReplica
        # (count) fix wants LOW-usage-delta leaders — a transfer moves the
        # (leader - follower) role load between brokers, and the usage
        # tiers ABOVE LeaderReplica veto significant regressions, so hot
        # leaders get vetoed exactly where the count fix is needed; the
        # LeaderBytesIn fix wants the opposite (move the heavy-bytes
        # leader off the over-bytes broker).
        lsafe = jnp.clip(lead_slot, 0, R - 1)
        lb = jnp.take_along_axis(b, lsafe[:, None], axis=1)[:, 0]
        has_lead = jnp.take_along_axis(valid, lsafe[:, None], axis=1)[:, 0]
        dest_ok = movable & ~is_l & lead_allowed[b]
        dest_sc = (press.lead_under[b] + 0.3 * press.lbi_under[b]) * dest_ok
        dest_best = jnp.max(dest_sc, axis=1)
        dest_slot = jnp.argmax(dest_sc, axis=1).astype(jnp.int32)
        # usage delta a transfer moves, in combined-usage units (static)
        u_delta = jnp.maximum(u_lead_p - u_foll_p, 0.0)
        avg_du = jnp.sum(jnp.where(m.partition_valid, u_delta, 0.0)) / (
            jnp.maximum(jnp.sum(m.partition_valid), 1)
        )
        damp = 1.0 / (1.0 + u_delta / jnp.maximum(avg_du, 1e-9))
        src_lr = press.lead_over[lb] * damp
        src_lbi = press.lbi_over[lb] * (lbytes_p / jnp.maximum(avg_lb, 1e-9))
        lead_score = (src_lr + src_lbi) * dest_best * has_lead * movable[
            jnp.arange(P), lsafe
        ]

        def gumbel_topk(score, k, kg):
            g = -jnp.log(
                -jnp.log(jax.random.uniform(kg, (P,), minval=1e-12, maxval=1.0))
            )
            _, idx = jax.lax.top_k(jnp.log(score + 1e-12) + g, k)
            return idx.astype(jnp.int32)

        hot_ps = gumbel_topk(hot_score, K_sw, k_gh)
        cold_ps = gumbel_topk(cold_score, K_sw, k_gc)
        if K_ld:
            lead_ps = gumbel_topk(lead_score, K_ld, k_gl)
            pa = jnp.concatenate([hot_ps, lead_ps])
            pb = jnp.concatenate([cold_ps, lead_ps])   # lead partners inert
            r1s = jnp.concatenate([hot_slot[hot_ps], dest_slot[lead_ps]])
            r2s = jnp.concatenate(
                [cold_slot[cold_ps], jnp.zeros(K_ld, jnp.int32)]
            )
        else:
            pa, pb = hot_ps, cold_ps
            r1s = hot_slot[hot_ps]
            r2s = cold_slot[cold_ps]

        views = gather_views(ss, m, jnp.concatenate([pa, pb]))
        va = jax.tree.map(lambda x: x[:N], views)
        vb = jax.tree.map(lambda x: x[N:], views)
        kds = jax.random.split(k_d, N)

        def plan(va_k, vb_k, pa_k, pb_k, r1_k, r2_k, sw_k, kd):
            x = va_k.assign[r1_k]
            y = vb_k.assign[r2_k]
            sx = jnp.clip(x, 0, B - 1)
            sy = jnp.clip(y, 0, B - 1)
            lead1 = r1_k == va_k.leader
            lead2 = r2_k == vb_k.leader
            ok_sw = (
                (pa_k != pb_k)
                & va_k.pvalid
                & vb_k.pvalid
                & ~va_k.immovable
                & ~vb_k.immovable
                & (x >= 0)
                & (y >= 0)
                & (x != y)
                & recv_ok[sx]
                & recv_ok[sy]
                & ~jnp.any(va_k.assign == y)
                & ~jnp.any(vb_k.assign == x)
                & ~(lead1 & m.broker_excl_leadership[sy])
                & ~(lead2 & m.broker_excl_leadership[sx])
            )
            gd = -jnp.log(
                -jnp.log(
                    jax.random.uniform(kd, (2, D), minval=1e-12, maxval=1.0)
                )
            )
            d1 = jnp.argmax(
                jnp.where(m.disk_alive[sy], gd[0], -jnp.inf)
            ).astype(jnp.int32)
            d2 = jnp.argmax(
                jnp.where(m.disk_alive[sx], gd[1], -jnp.inf)
            ).astype(jnp.int32)

            # leadership transfer variant (single move, partner inert):
            # mirrors _single_plan's MOVE_LEADERSHIP feasibility
            ok_ld = (
                va_k.pvalid
                & ~va_k.immovable
                & (va_k.assign[r1_k] >= 0)
                & (r1_k != va_k.leader)
                & lead_allowed[jnp.clip(va_k.assign[r1_k], 0, B - 1)]
            )

            def pick(sw_rows, ld_rows):
                return jnp.where(sw_k, sw_rows, ld_rows)

            olda = (va_k.assign, va_k.leader, va_k.disk)
            new1 = (
                pick(va_k.assign.at[r1_k].set(y), va_k.assign),
                pick(va_k.leader, r1_k).astype(jnp.int32),
                pick(
                    va_k.disk.at[r1_k].set(jnp.where(D > 1, d1, 0)),
                    va_k.disk,
                ),
            )

            def inert(rows):
                return tuple(jnp.where(sw_k, r, -1) for r in rows)

            oldb = inert((vb_k.assign, vb_k.leader, vb_k.disk))
            newb = inert(
                (
                    vb_k.assign.at[r2_k].set(x),
                    vb_k.leader,
                    vb_k.disk.at[r2_k].set(jnp.where(D > 1, d2, 0)),
                )
            )
            return olda, new1, oldb, newb, jnp.where(sw_k, ok_sw, ok_ld)

        olda, newa, oldb, newb, feas = jax.vmap(plan)(
            va, vb, pa, pb, r1s, r2s, is_swap_cand, kds
        )
        deltas = jax.vmap(
            lambda va_k, o1, n1, vb_k, o2, n2: swap_scorer(
                ss, va_k, o1, n1, vb_k, o2, n2
            )
        )(va, olda, newa, vb, oldb, newb)

        d_all = deltas.cost_vec - ss.cost_vec[None, :]
        sig_all = jnp.abs(d_all) > goal_tols(ss.cost_vec)[None, :]
        hard_up = jnp.any(sig_all & hard_arr[None, :] & (d_all > 0), axis=1)
        guard_up = guard_on & jnp.any(
            sig_all & guard_cols[None, :] & (d_all > 0), axis=1
        )
        better = (
            feas
            & ~hard_up
            & ~guard_up
            & _lex_lt_batch(deltas.cost_vec, ss.cost_vec)
        )
        any_better = jnp.any(better)

        # ---- lex-best-first disjoint selection (greedy apply_batch rule:
        # disjoint {touched brokers} u {topics} makes sum-decomposable terms
        # exactly additive; the exact recompute below guards the rest) -----
        touched = jnp.concatenate(
            [olda[0], newa[0], oldb[0], newb[0]], axis=1
        )  # [N, 8R]? (4 row groups x R)
        bmask = jnp.zeros((N, B), bool)
        bmask = jax.vmap(lambda z, bb, v: z.at[bb].set(v, mode="drop"))(
            bmask,
            jnp.where(touched >= 0, jnp.clip(touched, 0, B - 1), B),
            touched >= 0,
        )
        ta = jnp.clip(va.topic, 0, T - 1)
        tb = jnp.clip(vb.topic, 0, T - 1)

        def select(k, carry):
            alive, used_b, used_t, sel, count = carry
            conf = (
                jnp.any(bmask & used_b[None, :], axis=1)
                | used_t[ta]
                | (is_swap_cand & used_t[tb])
            )
            ok = alive & ~conf
            any_ok = jnp.any(ok)
            idx = _lex_argmin(deltas.cost_vec, ok)
            sel = sel.at[k].set(jnp.where(any_ok, idx, N))
            used_b = used_b | jnp.where(any_ok, bmask[idx], False)
            used_t = used_t.at[ta[idx]].max(any_ok)
            used_t = used_t.at[tb[idx]].max(any_ok & is_swap_cand[idx])
            alive = alive & (jnp.arange(N) != idx)
            return alive, used_b, used_t, sel, count + any_ok.astype(jnp.int32)

        sel0 = jnp.full((n_batch,), N, jnp.int32)
        _, _, _, sel_idx, n_sel = jax.lax.fori_loop(
            0, n_batch, select,
            (better, jnp.zeros(B, bool), jnp.zeros(T, bool), sel0,
             jnp.asarray(0, jnp.int32)),
        )
        taken = sel_idx < N
        safe = jnp.clip(sel_idx, 0, N - 1)

        # ---- exact composition over the selected disjoint subset ---------
        def acc(k, carry):
            agg, part, mtl, trd, totals = carry
            i = safe[k]
            w = taken[k].astype(jnp.float32)
            wi = taken[k].astype(jnp.int32)
            va_i = jax.tree.map(lambda x: x[i], va)
            vb_i = jax.tree.map(lambda x: x[i], vb)
            o1 = tuple(x[i] for x in olda)
            n1 = tuple(x[i] for x in newa)
            o2 = tuple(x[i] for x in oldb)
            n2 = tuple(x[i] for x in newb)
            agg = scatter_partition(agg, m, va_i, *o1, -w, -wi)
            agg = scatter_partition(agg, m, va_i, *n1, w, wi)
            agg = scatter_partition(agg, m, vb_i, *o2, -w, -wi)
            agg = scatter_partition(agg, m, vb_i, *n2, w, wi)
            part = part + w * (deltas.part_sums[i] - ss.part_sums)
            mtl = mtl + w * deltas.d_mtl[i]
            trd = trd + w * deltas.d_trd[i]
            totals = totals.at[va_i.topic].add(w * deltas.d_total[i])
            totals = totals.at[vb_i.topic].add(w * deltas.d_total2[i])
            return agg, part, mtl, trd, totals

        first = acc(0, (ss.agg, ss.part_sums, ss.mtl_sum, ss.trd_sum,
                        ss.topic_totals))
        full = jax.lax.fori_loop(1, n_batch, acc, first)

        def costs_of(c):
            agg_c, part_c, mtl_c, trd_c, totals_c = c
            return vector_fn(
                agg_c, part_c, mtl_c, trd_c, tt_.trd_normalizer(m, totals_c)
            )

        cost_full = costs_of(full)
        d_full = cost_full - ss.cost_vec
        full_guard_up = guard_on & jnp.any(
            (jnp.abs(d_full) > goal_tols(ss.cost_vec))
            & guard_cols
            & (d_full > 0)
        )
        batch_ok = (n_sel <= 1) | (
            _lex_lt_batch(cost_full[None, :], ss.cost_vec)[0] & ~full_guard_up
        )
        agg, part, mtl, trd, totals = jax.tree.map(
            lambda x, y: jnp.where(batch_ok, x, y), full, first
        )
        cost_vec = jnp.where(batch_ok, cost_full, costs_of(first))
        n_applied = jnp.where(
            any_better, jnp.where(batch_ok, n_sel, jnp.minimum(n_sel, 1)), 0
        )
        write_a = taken & (batch_ok | (jnp.arange(n_batch) == 0)) & any_better
        write_b = write_a & is_swap_cand[safe]
        acc_sw = jnp.sum((write_a & is_swap_cand[safe]).astype(jnp.int32))
        acc_ld = jnp.sum((write_a & ~is_swap_cand[safe]).astype(jnp.int32))
        ss = ss.replace(
            agg=agg,
            part_sums=part,
            mtl_sum=mtl,
            trd_sum=trd,
            topic_totals=totals,
            cost_vec=cost_vec,
            n_accepted=ss.n_accepted + n_applied,
            **_placement_updates(
                ss,
                group,
                write=jnp.concatenate([write_a, write_b]),
                ps=jnp.concatenate([pa[safe], pb[safe]]),
                mirror=jnp.concatenate(
                    [
                        write_a & va.pvalid[safe],
                        write_b & vb.pvalid[safe],
                    ]
                ),
                global_ps=jnp.concatenate([pa[safe], pb[safe]]),
                ts=jnp.concatenate([va.topic[safe], vb.topic[safe]]),
                rows=jnp.concatenate([newa[0][safe], newb[0][safe]]),
                leads=jnp.concatenate([newa[1][safe], newb[1][safe]]),
                disks=jnp.concatenate([newa[2][safe], newb[2][safe]]),
            ),
        )
        ss = bump_kind_counters(
            ss,
            jnp.arange(3),
            jnp.asarray([K_ld, K_sw, 0], jnp.int32),
            jnp.stack([acc_ld, acc_sw, jnp.asarray(0, jnp.int32)]),
        )
        it = it + 1
        stale = jnp.where(any_better, 0, stale + 1)
        return ss, it, stale, moves + n_applied

    zero = jnp.asarray(0, jnp.int32)
    state, n_iters, _, n_moves = jax.lax.while_loop(
        cond, body, (state0, zero, zero, zero)
    )
    return state, n_iters, n_moves


def swap_polish(
    m: TensorClusterModel,
    cfg: GoalConfig = GoalConfig(),
    goal_names: tuple[str, ...] = DEFAULT_GOAL_ORDER,
    opts: SwapPolishOptions = SwapPolishOptions(),
) -> GreedyResult:
    """Run the usage-coupled swap-polish descent to a local optimum.

    Only lex-improving, hard-safe candidates are applied, so the result is
    never lexicographically worse than the input; replica counts per broker
    are preserved exactly (replica swaps exchange brokers, leadership
    transfers move no replica). Intra-broker-only stacks have no
    inter-broker swap space — callers gate on ``allows_inter_broker``."""
    if not allows_inter_broker(goal_names):
        raise ValueError(
            "swap_polish proposes inter-broker swaps; intra-broker-only "
            "stacks must not run it"
        )
    stack_before = evaluate_stack(m, cfg, goal_names)
    max_pt = max_partitions_per_topic(m)
    group0 = (
        make_topic_group(m, max_pt) if stack_needs_topic(goal_names) else None
    )
    state0 = init_search_state(
        m, cfg, goal_names, jax.random.PRNGKey(opts.seed), group=group0
    )
    state, n_iters, n_moves = _swap_polish_loop(
        m,
        state0,
        jax.random.PRNGKey(opts.seed + 1),
        jnp.asarray(opts.max_iters, jnp.int32),
        jnp.asarray(opts.patience, jnp.int32),
        jnp.asarray(opts.trd_guard, bool),
        goal_names=goal_names,
        cfg=cfg,
        # iteration budgets and the guard are traced operands; zero them in
        # the compile key so every budget shares one program
        opts=dataclasses.replace(
            opts, max_iters=0, patience=0, seed=0, trd_guard=False
        ),
        max_pt=max_pt,
    )
    result_model = with_placement(m, state)
    stack_after = evaluate_stack(result_model, cfg, goal_names)
    return GreedyResult(
        model=result_model,
        stack_before=stack_before,
        stack_after=stack_after,
        n_moves=int(np.asarray(n_moves)),
        n_iters=int(np.asarray(n_iters)),
        n_prop_kind=tuple(int(x) for x in np.asarray(state.n_prop_kind)),
        n_acc_kind=tuple(int(x) for x in np.asarray(state.n_acc_kind)),
    )
