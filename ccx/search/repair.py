"""Vectorized hard-goal repair sweeps.

Parity/motivation: the reference optimizes goals *sequentially* —
``RackAwareGoal.optimize`` walks every violating replica and relocates it
before any balancing goal runs (SURVEY.md C16, call stack 3.2). Stochastic
search discovers those same repairs one accepted move at a time, which is
hopeless when a snapshot starts with thousands of violations (B5: ~10k
rack offenders). This module is the TPU-native form of the reference's
per-goal repair pass: ONE jitted sweep selects, for **every** violating
partition at once,

* the offending slot — a replica on a dead broker/disk, a duplicate broker,
  or (when the stack contains a rack goal) a rack-duplicate replica — and
* a destination broker on an unused rack with the most capacity headroom
  (noise-perturbed so simultaneous choosers spread out),

then applies all moves with one scatter. A handful of sweeps reaches
hard-feasibility; the annealer then only has to *balance* (soft goals),
which is what Metropolis search is actually good at.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ccx.common import costmodel
from ccx.goals.base import GoalConfig
from ccx.model.tensor_model import TensorClusterModel, build_model
from ccx.search.annealer import (
    CAPACITY_GOALS,
    RACK_TARGET_GOALS,
    _evac_bucket,
    allows_inter_broker,
)


def _sweep_impl(
    m: TensorClusterModel,
    assignment: jnp.ndarray,   # int32[P, R]
    leader_slot: jnp.ndarray,  # int32[P]
    replica_disk: jnp.ndarray,  # int32[P, R]
    key: jnp.ndarray,
    *,
    target_rack: bool,
    target_capacity: bool,
    cfg: GoalConfig,
    nk: int,
):
    P, R, B, K = m.P, m.R, m.B, m.num_racks
    pvalid = m.partition_valid
    valid = (assignment >= 0) & pvalid[:, None]
    safe_b = jnp.clip(assignment, 0, B - 1)
    alive_b = m.broker_alive & m.broker_valid
    recv_ok = alive_b & ~m.broker_excl_replicas

    from ccx.model.aggregates import broker_aggregates

    agg = broker_aggregates(
        m.replace(
            assignment=assignment, leader_slot=leader_slot,
            replica_disk=replica_disk,
        )
    )

    # --- offender selection -------------------------------------------------
    on_dead = valid & ~alive_b[safe_b]
    safe_d = jnp.clip(replica_disk, 0, m.D - 1)
    on_dead_disk = valid & (replica_disk >= 0) & ~m.disk_alive[safe_b, safe_d]

    # duplicate broker within the replica set (slot j duplicates some k<j)
    a_keyed = jnp.where(valid, assignment, -1 - jnp.arange(R, dtype=jnp.int32)[None, :])
    dup_broker = jnp.any(
        (a_keyed[:, :, None] == a_keyed[:, None, :])
        & (jnp.arange(R)[None, :, None] > jnp.arange(R)[None, None, :]),
        axis=2,
    )

    racks = jnp.where(valid, m.broker_rack[safe_b], -1 - jnp.arange(R)[None, :])
    dup_rack = jnp.any(
        (racks[:, :, None] == racks[:, None, :])
        & (jnp.arange(R)[None, :, None] > jnp.arange(R)[None, None, :]),
        axis=2,
    )

    # capacity offenders: replicas on brokers above EFFECTIVE capacity
    # (capacity * per-resource threshold — where the hard CapacityGoal hinge
    # starts, kernels._capacity_goal), selected with probability ~ the
    # broker's excess fraction so a sweep sheds roughly the overflow instead
    # of evacuating the whole broker. Only for stacks that score capacity.
    thr = jnp.asarray(cfg.capacity_threshold, jnp.float32)
    cap_eff = m.broker_capacity * thr[:, None]
    # capacity 0 = unconstrained resource (capacity unset), utilization 0
    util = jnp.max(
        jnp.where(
            cap_eff > 0,
            agg.broker_load / jnp.where(cap_eff > 0, cap_eff, 1.0),
            0.0,
        ),
        axis=0,
    )                                                       # [B]
    if target_capacity:
        over_b = alive_b & (util > 1.0)
        exc_frac = jnp.where(
            over_b,
            jnp.clip(1.0 - 1.0 / jnp.maximum(util, 1e-9), 0.0, 1.0),
            0.0,
        )
        # scale selection so a sweep sheds at most roughly what the
        # under-capacity brokers can absorb — otherwise every offender
        # piles onto the few cool brokers and the sweeps oscillate
        excess_rel = jnp.sum(jnp.where(over_b, util - 1.0, 0.0))
        head_rel = jnp.sum(
            jnp.where(alive_b & ~over_b, jnp.maximum(1.0 - util, 0.0), 0.0)
        )
        absorb = jnp.clip(head_rel / jnp.maximum(excess_rel, 1e-9), 0.0, 1.0)
        key, k_cap = jax.random.split(key)
        u_cap = jax.random.uniform(k_cap, (P, R))
        on_over = (
            valid
            & over_b[safe_b]
            & (u_cap < 1.5 * absorb * exc_frac[safe_b])
        )
        # Deterministic floor: the lowest-draw replica on EVERY over-
        # capacity broker is always selected. The probabilistic thinning
        # above sheds roughly the overflow, but at a small excess fraction
        # (or tight absorb) it can select NOTHING — the sweep then reports
        # n_moved == 0 and the repair loop declares a fixpoint while
        # over-capacity brokers remain (the round-10..15 seed failure:
        # hard_repair "converged" with NetworkOutbound violations left).
        # Forcing one replica per over broker keeps every sweep making
        # progress until either the overload clears or the oscillation
        # break fires.
        u_rank = jnp.where(valid & over_b[safe_b], u_cap, jnp.inf)
        min_u = (
            jnp.full((B,), jnp.inf, u_cap.dtype)
            .at[safe_b]
            .min(u_rank, mode="drop")
        )
        on_over = on_over | (
            valid & over_b[safe_b] & (u_rank <= min_u[safe_b])
        )
    else:
        over_b = jnp.zeros_like(alive_b)
        on_over = jnp.zeros_like(valid)

    score = (
        3.0 * on_dead
        + 2.5 * on_dead_disk
        + 2.0 * dup_broker
        + (1.0 * dup_rack if target_rack else 0.0)
        + 0.75 * on_over
    )
    slot = jnp.argmax(score, axis=1)                       # int[P]
    has_offender = jnp.max(score, axis=1) > 0.0
    off_is_disk_only = (
        jnp.take_along_axis(on_dead_disk, slot[:, None], 1)[:, 0]
        & ~jnp.take_along_axis(on_dead, slot[:, None], 1)[:, 0]
        & ~jnp.take_along_axis(dup_broker, slot[:, None], 1)[:, 0]
        & ~jnp.take_along_axis(on_over, slot[:, None], 1)[:, 0]
        & (
            ~jnp.take_along_axis(dup_rack, slot[:, None], 1)[:, 0]
            if target_rack
            else jnp.ones_like(slot, bool)
        )
    )

    # --- bounded offender set ----------------------------------------------
    # Destination scoring needs [offenders, B] matrices; doing it for every
    # partition materialized ~0.5 GB of [P, B] temporaries at B5 scale.
    # Offenders are a small fraction of P, so score only the first ``nk``
    # of them (static bound) — when more exist, the next sweep of the
    # hard_repair loop picks up the remainder.
    # Severity-ordered selection (argsort on the per-partition max offender
    # score): structural offenders (dead broker/disk, duplicate, rack)
    # outrank capacity shedding, so plentiful hot-broker picks can never
    # starve the offenders the sweep MUST fix before hard_repair's
    # capacity-oscillation break may fire.
    score_max = jnp.max(score, axis=1)
    eligible = pvalid & has_offender
    order = jnp.argsort(jnp.where(eligible, -score_max, jnp.inf))[:nk]
    sel_ok = eligible[order]                                  # bool[nk]
    sel = jnp.where(sel_ok, order, P)
    ssel = jnp.clip(sel, 0, P - 1)
    slot_s = slot[ssel]                                       # int[nk]
    valid_s = valid[ssel]                                     # [nk, R]
    safe_b_s = safe_b[ssel]                                   # [nk, R]
    racks_s = racks[ssel]                                     # [nk, R]

    # brokers already hosting the partition (excluding the offender slot)
    keep = valid_s & (jnp.arange(R)[None, :] != slot_s[:, None])
    rows = jnp.repeat(jnp.arange(nk)[:, None], R, 1)
    in_part = jnp.zeros((nk, B), bool).at[rows, safe_b_s].max(keep)

    rack_idx = jnp.clip(racks_s, 0, K - 1)
    used_rack = jnp.zeros((nk, K), bool).at[rows, rack_idx].max(
        keep & (racks_s >= 0)
    )

    # prefer destinations under effective capacity, but never strand an
    # offender: when no under-capacity destination exists (e.g. every alive
    # broker runs hot after failures), fall back to any alive receiver
    allowed_any = recv_ok[None, :] & ~in_part
    allowed_cap = allowed_any & ~over_b[None, :]
    has_cap_dest = jnp.any(allowed_cap, axis=1, keepdims=True)
    allowed_base = jnp.where(has_cap_dest, allowed_cap, allowed_any)
    rack_free = ~used_rack[:, jnp.clip(m.broker_rack, 0, K - 1)]  # [nk, B]
    allowed_rack = allowed_base & rack_free
    use_rack_constraint = jnp.any(allowed_rack, axis=1, keepdims=True)
    allowed = jnp.where(use_rack_constraint, allowed_rack, allowed_base)

    # headroom score: spare capacity across EVERY resource (a destination
    # with free disk but saturated CPU would just trade one capacity
    # violation for another), plus replica-count headroom; noise-spread
    headroom = 1.0 - util
    count_head = 1.0 - agg.replica_count / jnp.maximum(
        jnp.max(agg.replica_count), 1.0
    )
    base_score = headroom + 0.5 * count_head
    noise = jax.random.uniform(key, (nk, B)) * 0.35
    dest_score = jnp.where(allowed, base_score[None, :] + noise, -jnp.inf)
    dest = jnp.argmax(dest_score, axis=1).astype(jnp.int32)   # int[nk]
    dest_found = jnp.isfinite(jnp.max(dest_score, axis=1))

    # --- disk-only offenders move disks, not brokers ------------------------
    # choose the least-loaded alive disk on the *current* broker
    cur_b = jnp.take_along_axis(safe_b_s, slot_s[:, None], 1)[:, 0]
    disk_ok = m.disk_alive[cur_b]                             # [nk, D]
    disk_load = agg.disk_load[cur_b] / jnp.maximum(m.disk_capacity[cur_b], 1e-9)
    disk_score = jnp.where(disk_ok, -disk_load, -jnp.inf)
    best_disk = jnp.argmax(disk_score, axis=1).astype(jnp.int32)
    disk_found = jnp.isfinite(jnp.max(disk_score, axis=1))

    # --- apply (suppressed writes routed out of bounds and dropped) ---------
    disk_only_s = off_is_disk_only[ssel]
    do_move = sel_ok & dest_found & ~disk_only_s
    do_disk = sel_ok & disk_only_s & disk_found
    new_assignment = assignment.at[
        jnp.where(do_move, ssel, P), slot_s
    ].set(dest, mode="drop")
    new_disk_val = jnp.where(do_move, 0, best_disk)
    new_replica_disk = replica_disk.at[
        jnp.where(do_move | do_disk, ssel, P), slot_s
    ].set(new_disk_val, mode="drop")
    n_moved = jnp.sum(do_move) + jnp.sum(do_disk)
    n_over_b = jnp.sum(over_b)
    # FIXABLE structural offenders present BEFORE this sweep's moves (dead
    # broker/disk, duplicate broker, rack duplicate) — capacity shedding is
    # the only offender class the oscillation break in hard_repair may
    # abandon, so the caller needs to know whether any structural work
    # remained when the sweep ran. Rack duplicates only count while the row
    # is rack-FEASIBLE (rf <= racks with an alive receiver): infeasible rows
    # (OptimizationFailure territory, ccx.feasibility) would otherwise pin
    # n_struct > 0 forever and disable the break entirely.
    rack_has_recv = (
        jnp.zeros(K, bool)
        .at[jnp.clip(m.broker_rack, 0, K - 1)]
        .max(recv_ok & m.broker_valid)
    )
    n_recv_racks = jnp.sum(rack_has_recv)
    rf_row = jnp.sum(valid, axis=1)
    rack_fixable = rf_row <= n_recv_racks
    structural = (
        on_dead
        | on_dead_disk
        | dup_broker
        | (
            dup_rack & rack_fixable[:, None]
            if target_rack
            else jnp.zeros_like(dup_broker)
        )
    )
    n_struct = jnp.sum(pvalid & jnp.any(structural, axis=1))
    return new_assignment, new_replica_disk, n_moved, n_over_b, n_struct


#: host-path entry: one jitted sweep per call (the round-2 design; the
#: hard_repair loop around it syncs n_moved per sweep). The device path
#: compiles the same body inside `_repair_loop`'s while_loop instead.
_sweep = costmodel.instrument("repair-sweep")(jax.jit(
    _sweep_impl,
    static_argnames=("target_rack", "target_capacity", "cfg", "nk"),
))


@costmodel.instrument("repair-loop")
@functools.partial(
    jax.jit,
    static_argnames=("target_rack", "target_capacity", "cfg", "nk"),
)
def _repair_loop(
    m: TensorClusterModel,
    assignment: jnp.ndarray,
    leader_slot: jnp.ndarray,
    replica_disk: jnp.ndarray,
    key: jnp.ndarray,
    max_sweeps: jnp.ndarray,   # int32 scalar — TRACED budget (one program
    #                            per model shape serves every sweep budget)
    *,
    target_rack: bool,
    target_capacity: bool,
    cfg: GoalConfig,
    nk: int,
):
    """Device-resident hard repair: the whole sweep loop as ONE compiled
    program (`optimizer.repair.backend=device`).

    The host path dispatches one jitted `_sweep` per iteration and syncs
    `n_moved` back after each — at B5 on the tunneled TPU that is eight
    dispatch+transfer round trips on the critical path, and the repair
    phase cannot overlap with anything downstream. Here the loop runs as a
    `lax.while_loop` with the SAME body (`_sweep_impl`), the SAME per-sweep
    key-split sequence, and the SAME stop conditions (no moves, or
    capacity-shed oscillation with zero structural offenders), so the
    result is bit-comparable to the host loop (pinned by
    tests/test_repair.py parity); the single dispatch returns lazy arrays
    the caller can feed straight into the annealer without a host sync.

    Returns (assignment, replica_disk, total_moved[int32 scalar]).
    """

    def cond(carry):
        _, _, _, i, _, _, done = carry
        return (~done) & (i < max_sweeps)

    def body(carry):
        a, d, key, i, total, prev_over, done = carry
        key, sub = jax.random.split(key)
        a, d, n, n_over, n_struct = _sweep_impl(
            m, a, leader_slot, d, sub,
            target_rack=target_rack, target_capacity=target_capacity,
            cfg=cfg, nk=nk,
        )
        total = total + n
        # same break rules as the host loop: stop on a no-move sweep, or on
        # capacity-shed oscillation (over-broker count not decreasing) once
        # no structural offender remained when the sweep ran. prev_over
        # starts at -1 (the host loop's `prev_over is None`).
        osc = (n_struct == 0) & (prev_over > 0) & (prev_over <= n_over)
        done = (n == 0) | osc
        return a, d, key, i + 1, total, n_over, done

    zero = jnp.asarray(0, jnp.int32)
    a, d, _, _, total, _, _ = jax.lax.while_loop(
        cond,
        body,
        (assignment, replica_disk, key, zero, zero,
         jnp.asarray(-1, jnp.int32), jnp.asarray(False)),
    )
    return a, d, total


def canonicalize_preferred_leaders(
    m: TensorClusterModel,
) -> tuple[TensorClusterModel, int]:
    """Reorder replica lists so every chosen leader sits in the preferred
    (slot-0) position — the pipeline's PreferredLeaderElectionGoal
    guarantee.

    Parity: the reference encodes leadership decisions in its proposals as
    *replica-list order* — an ExecutionProposal's new leader is the first
    replica of ``newReplicas`` and the executor runs a preferred-leader
    election after reordering (SURVEY.md C20/C24; PreferredLeaderElectionGoal
    "leadership on the first replica", section 2.3). The search engine moves
    leadership freely to balance the leader tiers; this final pass folds
    those decisions into the canonical order the reference's proposals carry.
    Swapping two slots of a row relabels positions only: every goal except
    PreferredLeaderElection scores roles (who leads, who follows) and broker
    sets, which are unchanged — so the pass is exact, coupling-free, and
    always ends with zero fixable PLE violations.

    Immovable/excluded partitions are never touched (the search engine does
    not move them either, so they can only carry PLE violations present in
    the input). Returns (model, partitions reordered).
    """
    a = np.asarray(m.assignment).copy()
    lead = np.asarray(m.leader_slot).copy()
    dsk = np.asarray(m.replica_disk).copy()
    pvalid = np.asarray(m.partition_valid)
    imm = np.asarray(m.partition_immovable)
    alive = np.asarray(m.broker_alive) & np.asarray(m.broker_valid)
    excl = np.asarray(m.broker_excl_leadership)
    b0 = np.clip(a[:, 0], 0, m.B - 1)
    # mirror of partition_terms.preferred_leader_rows eligibility: only rows
    # whose slot-0 broker could actually lead count as violations
    eligible = pvalid & (a[:, 0] >= 0) & alive[b0] & ~excl[b0]
    viol = eligible & (lead != 0) & ~imm
    idx = np.nonzero(viol)[0]
    if idx.size == 0:
        return m, 0
    j = lead[idx]
    a[idx, 0], a[idx, j] = a[idx, j], a[idx, 0]
    dsk[idx, 0], dsk[idx, j] = dsk[idx, j], dsk[idx, 0]
    lead[idx] = 0
    out = m.replace(
        assignment=jnp.asarray(a, dtype=m.assignment.dtype),
        leader_slot=jnp.asarray(lead, dtype=m.leader_slot.dtype),
        replica_disk=jnp.asarray(dsk, dtype=m.replica_disk.dtype),
    )
    return out, int(idx.size)


def _group_ranks(keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(rank, group_start) per element of a SORTED key array: rank counts
    earlier elements with the same key; group_start indexes each element's
    first group member. One implementation for the shed's three segment-
    rank uses (topic fan-out occ, per-dest intake, per-(dest, topic) room)."""
    idx = np.arange(keys.size)
    seg = np.r_[True, keys[1:] != keys[:-1]] if keys.size else np.zeros(0, bool)
    start = np.maximum.accumulate(np.where(seg, idx, 0))
    return idx - start, start


def topic_rebalance(
    m: TensorClusterModel,
    cfg: GoalConfig,
    # latency bound only — the loop stops at moved==0; 1024 lets a call run
    # to convergence (43k moves / ~14 s at B5; 16 was starving the shed at
    # ~5.3k moves, the round-4 sweep-budget finding in docs/perf-notes.md)
    max_sweeps: int = 1024,
    rounds_per_sweep: int = 16,
    seed: int = 23,
    #: allow shedding leader-held over cells by transferring leadership to a
    #: co-replica first (round-4 diagnosis: after the followers-only shed
    #: converges, EVERY residual over-cell replica is a leader). False
    #: restores the leadership-untouched contract.
    move_leaders: bool = True,
) -> tuple[TensorClusterModel, int]:
    """Targeted TopicReplicaDistribution sweep: shed (topic, broker) cells
    above their per-topic band by relocating follower replicas to brokers
    with topic room, never violating any hard constraint.

    Motivation (ref TopicReplicaDistributionGoal, SURVEY.md C17): TRD
    violations are (topic, broker) cells outside the per-topic band — at B5
    scale ~45k cells, by far the largest count in the stack. Random search
    proposals almost never align a drawn partition's topic with a
    topic-underloaded destination, so SA + polish barely move the count
    (round-4 parity: 45.8k -> 44.8k at full effort). This pass enumerates
    the offending cells directly — the same design as ``hard_repair``'s
    sweeps, but for a soft goal, so it must be adopted lex-guarded (the
    optimizer polishes the swept placement and keeps it only if the full
    cost vector improves; see optimize()).

    Aggregates ((topic, broker) counts, role-resolved broker loads, replica
    counts, per-disk loads) are built ONCE per call and maintained per
    accepted move; per-topic totals — and so the band uppers and the
    replica-count cap — are move-invariant and hoisted. Per sweep: pick one
    follower replica per over cell (one per partition); route each to its
    topic's best destination — live band room, rack-distinct, not already
    hosting, alive+receiving, strictly under effective capacity on EVERY
    resource, under the replica-count band and ReplicaCapacity cap,
    utilization < 0.9 (keeps the usage tiers from absorbing the shed load).
    Destinations take BATCHED intake per round under cumulative band-room /
    replica-count / capacity checks that are exactly as safe as the old
    one-move-per-dest rule (see the intake comment in the accept block).

    Followers are always preferred; with ``move_leaders`` (default) a
    leader-held over cell is shed by first transferring leadership to a
    co-replica (hard-safe: the new-leader broker must accept leadership,
    absorb the leader-load delta within strict capacity, and MTL-flagged
    topics keep their per-broker leader minimum at the source). Leader
    tiers may shift — they sit BELOW TopicReplicaDistribution in the goal
    order, the optimizer adopts rounds lex-guarded, and the pipeline's
    final leadership pass rebalances them. With ``move_leaders=False``
    leadership and leader loads are bit-unchanged. Host-side numpy like
    ``canonicalize_preferred_leaders`` (one [P, R] transfer).
    Returns (model, moves applied).
    """
    a = np.asarray(m.assignment).copy()
    dsk = np.asarray(m.replica_disk).copy()
    pvalid = np.asarray(m.partition_valid)
    topic = np.asarray(m.partition_topic)
    alive = np.asarray(m.broker_alive & m.broker_valid)
    recv_ok = alive & ~np.asarray(m.broker_excl_replicas)
    imm = np.asarray(m.partition_immovable)
    rack = np.asarray(m.broker_rack)
    lslot = np.asarray(m.leader_slot).copy()
    T, B, P, R = m.num_topics, m.B, m.P, m.R
    from ccx.common.resources import NUM_RESOURCES, Resource

    thr = cfg.topic_replica_balance_threshold
    capthr = np.asarray(cfg.capacity_threshold)
    cap_eff = np.asarray(m.broker_capacity) * capthr[:, None]    # [RES, B]
    cap_eff = np.where(cap_eff > 0, cap_eff, np.inf)
    lead_load = np.asarray(m.leader_load)                        # [RES, P]
    foll_load = np.asarray(m.follower_load)
    rng = np.random.default_rng(seed)
    total_moved = 0

    is_l = np.zeros((P, R), bool)
    is_l[np.arange(P), np.clip(lslot, 0, R - 1)] = True
    # role-resolved slot loads and the topic matrix ([RES, P, R] is tens of
    # MB at B5 — build once). NOT invariant: with move_leaders, a
    # leadership transfer updates is_l/slot_load in place for the two
    # affected slots; never cache anything derived from them across moves.
    tmat = np.repeat(topic, R).reshape(P, R)
    slot_load = np.where(
        is_l[None], lead_load[:, :, None], foll_load[:, :, None]
    )                                                            # [RES, P, R]
    D = m.D
    disk_alive = np.asarray(m.disk_alive)                        # [B, D]

    # Aggregates are built ONCE and maintained incrementally by the move
    # loop (counts/bload/rc/dload all update per accepted move) — the
    # round-4 profile showed the per-sweep O(P*R) scatter rebuilds were
    # ~2.9 s of the 3.75 s call at B5 while every sweep after the first
    # moves only hundreds of replicas. Per-topic totals (and so the band
    # uppers and the replica-count cap) are invariant under moves.
    valid = (a >= 0) & pvalid[:, None]   # moves never invalidate a slot
    counts = np.zeros((T, B), np.int64)
    np.add.at(counts, (tmat[valid], a[valid]), 1)
    counts[:, ~alive] = 0
    tot = counts.sum(1).astype(np.float64)
    avg = tot / max(int(alive.sum()), 1)
    upper = np.ceil(avg * thr)

    bload = np.zeros((NUM_RESOURCES, B))
    for res in range(NUM_RESOURCES):
        np.add.at(bload[res], a[valid], slot_load[res][valid])
    # per-disk DISK load for JBOD-safe placement of moved replicas
    dload = np.zeros((B, D))
    dvalid = valid & (dsk >= 0)
    np.add.at(
        dload,
        (a[dvalid], np.clip(dsk, 0, D - 1)[dvalid]),
        slot_load[int(Resource.DISK)][dvalid],
    )
    rc = np.bincount(a[valid], minlength=B).astype(np.int64)
    rc_avg = rc[alive].sum() / max(int(alive.sum()), 1)
    rc_cap = min(
        int(np.floor(rc_avg * cfg.replica_balance_threshold)),
        int(cfg.max_replicas_per_broker),
    )

    # leadership-transfer support (move_leaders): the followers-only shed
    # converges with EVERY residual over-cell replica being its partition's
    # leader (round-4 diagnosis: 21,860 of 21,860 at B5 — the binding
    # constraint was role, not room/rack/capacity). A leader candidate is
    # moved by first transferring leadership to a co-replica (the reference
    # expresses this as a LEADERSHIP_MOVEMENT + replica move; leader tiers
    # sit BELOW TopicReplicaDistribution in the goal order, so the trade is
    # lex-legitimate and the pipeline's final leader pass rebalances
    # leadership afterwards). Hard-goal safety: the new-leader broker must
    # accept leadership (not excluded), absorb the leader-load delta within
    # strict capacity, and — for topics under MinTopicLeadersPerBroker —
    # the source broker must keep >= k leaders of the topic.
    excl_lead = np.asarray(m.broker_excl_leadership)
    tmin = np.asarray(m.topic_min_leaders)
    need_tlc = move_leaders and bool(tmin.any())
    if need_tlc:
        tlc = np.zeros((T, B), np.int64)
        lv = valid & is_l
        np.add.at(tlc, (tmat[lv], a[lv]), 1)
        k_min = int(cfg.min_topic_leaders_per_broker)

    for _ in range(max_sweeps):
        util = np.max(bload / cap_eff, axis=0)
        over = counts > upper[:, None]
        on_over = (
            valid & over[tmat, np.clip(a, 0, B - 1)] & ~imm[:, None]
        )
        cand_f = on_over & ~is_l
        pf, rf = np.nonzero(cand_f)
        if move_leaders:
            # leaders need a co-replica to hand leadership to
            cand_l = on_over & is_l & (valid.sum(1) >= 2)[:, None]
            pl, rl = np.nonzero(cand_l)
        else:
            pl = rl = np.zeros(0, np.int64)
        if pf.size + pl.size == 0:
            break
        # one candidate per partition AND per (topic, src broker) cell —
        # followers FIRST so a cell with both sheds the cheaper follower
        # (no leader-tier disturbance); permutation keeps cell picks fair
        of = rng.permutation(pf.size)
        ol = rng.permutation(pl.size)
        ps = np.concatenate([pf[of], pl[ol]])
        rs = np.concatenate([rf[of], rl[ol]])
        # np.unique picks each value's FIRST occurrence but returns indices
        # in value order — np.sort restores array order so the
        # followers-before-leaders priority actually survives both dedups
        fp = np.sort(np.unique(ps, return_index=True)[1])
        ps, rs = ps[fp], rs[fp]
        cell = topic[ps].astype(np.int64) * B + a[ps, rs]
        fc = np.sort(np.unique(cell, return_index=True)[1])
        ps, rs = ps[fc], rs[fc]
        ts = topic[ps]
        # occurrence rank of each candidate within its topic (sweep-stable):
        # candidates of ONE topic fan out over DIFFERENT destinations in the
        # same round (dest rank = round + topic rotation + occ), instead of
        # all chasing the topic's single rank-k dest — the per-(topic, dest)
        # band room (~1-2) otherwise caps a topic at ~1 accept per round and
        # the loop at ~60 moves/round x ~900 rounds (profiled round 5).
        t_order = np.argsort(ts, kind="stable")
        t_inv = np.empty_like(t_order)
        t_inv[t_order] = np.arange(ts.size)
        occ = _group_ranks(ts[t_order])[0][t_inv]
        lead_row = is_l[ps, rs]
        # new-leader slot: the first OTHER valid replica slot whose broker
        # can actually accept leadership (alive, not leadership-excluded) —
        # pinning the first valid slot regardless left R>=3 cells unshed for
        # the whole sweep when that one co-replica happened to be dead or
        # excluded. Capacity eligibility is still checked per-round (b2_ok);
        # the leader pass re-optimizes leadership placement later.
        ov = valid[ps].copy()
        ov[np.arange(ps.size), rs] = False
        ab = np.clip(a[ps], 0, B - 1)
        elig = ov & alive[ab] & ~excl_lead[ab]
        nl = np.where(elig.any(axis=1), np.argmax(elig, axis=1),
                      np.argmax(ov, axis=1))
        b2 = np.where(lead_row, a[ps, nl], -1)

        room = np.where(
            recv_ok[None, :], np.maximum(upper[:, None] - counts, 0), 0
        )
        dest_ok_b = (
            (rc[None, :] < rc_cap)
            & (util[None, :] < 0.9)
            & (room > 0)
            & disk_alive.any(axis=1)[None, :]   # needs a live disk to land on
        )
        dest_score = np.where(
            dest_ok_b, room + (0.9 - util[None, :]), -np.inf
        )
        # top destinations per topic, W wide. dest_score is nearly
        # topic-independent (room is mostly 0/1 mid-shed, so coolness
        # dominates), which made every topic's rank-k pick the SAME few
        # coolest brokers — the per-dest rc/capacity serialization that
        # capped rounds at ~30 accepted moves. Each topic therefore starts
        # at its own rotation offset into its top-W list (deterministic,
        # all entries still room>0 & cool), spreading the ~500 topics
        # across ~W distinct destinations per round.
        width = min(B, max(rounds_per_sweep, 64))
        top_dest = np.argsort(-dest_score, axis=1)[:, :width]
        moved = 0
        kf = kl = 0
        for k in range(min(rounds_per_sweep, top_dest.shape[1])):
            if ps.size == 0:
                break
            have_f = bool((~lead_row).any())
            have_l = move_leaders and bool(lead_row.any())
            if not (have_f or have_l):
                break
            # alternate follower and leader rounds (when both classes have
            # candidates): follower rounds run plain batched intake; leader
            # rounds draw a random broker bipartition so the dest set
            # (heads) and the new-leader set (tails) are disjoint BY
            # CONSTRUCTION — the b2 capacity check then stays exact under
            # batched intake because no new-leader broker can also receive
            # dest load this round. (A pairwise dest/b2 cross-filter
            # collapses once intake is batched: tens of thousands of
            # leader rows' b2 values blanket every broker.) Each class
            # keeps its own destination-rank cursor.
            lead_round = have_l and (not have_f or k % 2 == 1)
            if lead_round:
                rank_k, kl = kl, kl + 1
            else:
                rank_k, kf = kf, kf + 1
            dest = top_dest[ts, (rank_k + ts + occ) % top_dest.shape[1]]
            ok = np.isfinite(dest_score[ts, dest])
            ok &= lead_row if lead_round else ~lead_row
            # counts is maintained per move, so the band-room check is
            # live (the old intake side-array measured vs sweep-start room)
            ok &= (upper[ts] - counts[ts, dest]) > 0
            ok &= rc[dest] < rc_cap
            ok &= ~(a[ps] == dest[:, None]).any(axis=1)
            rrows = np.where(a[ps] >= 0, rack[np.clip(a[ps], 0, B - 1)], -1)
            rrows[np.arange(ps.size), rs] = -1
            ok &= ~(rrows == rack[dest][:, None]).any(axis=1)
            ok &= np.all(
                bload[:, dest] + foll_load[:, ps] <= cap_eff[:, dest], axis=0
            )
            if lead_round:
                # leader rows additionally need the new-leader broker to be
                # eligible and to absorb the (leader - follower) load delta
                # strictly within capacity, and MTL-flagged topics must
                # keep >= k leaders of the topic on the source broker
                b2c = np.clip(b2, 0, B - 1)
                delta = lead_load[:, ps] - foll_load[:, ps]
                b2_ok = (
                    alive[b2c]
                    & ~excl_lead[b2c]
                    & np.all(
                        bload[:, b2c] + delta <= cap_eff[:, b2c], axis=0
                    )
                )
                if need_tlc:
                    srcb = np.clip(a[ps, rs], 0, B - 1)
                    b2_ok &= ~tmin[ts] | (tlc[ts, srcb] - 1 >= k_min)
                coin = rng.integers(0, 2, B).astype(bool)
                ok &= b2_ok & ~coin[dest] & coin[b2c]
            if ok.any():
                oi = np.nonzero(ok)[0]
                if lead_round:
                    # one leadership transfer per NEW-LEADER broker per
                    # round: exactly one delta lands on each b2 broker
                    _, fb2 = np.unique(b2[oi], return_index=True)
                    oi = oi[np.sort(fb2)]
                if oi.size == 0:
                    continue
                # batched intake: MULTIPLE accepted moves per destination
                # per round, with cumulative checks that keep the old
                # one-per-dest rule's exactness: within each dest group
                # ((dest, topic)-sorted) a row is taken only while the live
                # (topic, dest) band room, the replica-count cap, and EVERY
                # resource capacity still hold with all earlier group rows'
                # loads included. Cumulative sums also count group rows that
                # end up rejected, which can only UNDER-accept — never
                # overshoot; rejected rows retry the next-ranked destination
                # next round. (The one-per-dest rule serialized the B5
                # leader-ful converged shed to ~18 moves/round x 3k rounds.)
                order = np.lexsort((ts[oi], dest[oi]))
                ois = oi[order]
                d_s, t_s = dest[ois], ts[ois]
                rank_d, start_d = _group_ranks(d_s)
                # (dest, topic) pairs are sorted by the lexsort, so the
                # combined key is sorted too
                rank_td, _ = _group_ranks(d_s.astype(np.int64) * T + t_s)
                load_s = foll_load[:, ps[ois]]               # [RES, n]
                cum = np.cumsum(load_s, axis=1)
                grp_base = (cum - load_s)[:, start_d]
                cum_within = cum - grp_base                  # incl. self
                take = rank_td < (upper[t_s] - counts[t_s, d_s])
                take &= rank_d < (rc_cap - rc[d_s])
                take &= np.all(
                    bload[:, d_s] + cum_within <= cap_eff[:, d_s], axis=0
                )
                oi, rank_acc = ois[take], rank_d[take]
                if oi.size == 0:
                    continue
                ai, ri, di = ps[oi], rs[oi], dest[oi]
                lr = lead_row[oi]
                src = a[ai, ri]
                old_d = dsk[ai, ri]
                # source sheds its CURRENT role-resolved load (leader rows
                # were carrying leader load); dest always gains follower
                # load; a leader row's new-leader broker gains the
                # (leader - follower) delta
                cur = slot_load[:, ai, ri]          # [RES, n] role-resolved
                for res in range(NUM_RESOURCES):
                    np.subtract.at(bload[res], src, cur[res])
                    np.add.at(bload[res], di, foll_load[res, ai])
                if lr.any():
                    ail, nll = ai[lr], nl[oi][lr]
                    b2l = a[ail, nll]
                    for res in range(NUM_RESOURCES):
                        np.add.at(
                            bload[res], b2l,
                            lead_load[res, ail] - foll_load[res, ail],
                        )
                    # new leader's existing disk now carries leader disk
                    # load instead of follower disk load
                    d2 = dsk[ail, nll]
                    np.add.at(
                        dload,
                        (b2l, np.clip(d2, 0, D - 1)),
                        np.where(
                            d2 >= 0,
                            lead_load[int(Resource.DISK), ail]
                            - foll_load[int(Resource.DISK), ail],
                            0.0,
                        ),
                    )
                    if need_tlc:
                        np.subtract.at(tlc, (topic[ail], a[ail, ri[lr]]), 1)
                        np.add.at(tlc, (topic[ail], b2l), 1)
                    # role bookkeeping: leadership transfers to slot nl
                    lslot[ail] = nll
                    is_l[ail, ri[lr]] = False
                    is_l[ail, nll] = True
                    for res in range(NUM_RESOURCES):
                        slot_load[res, ail, ri[lr]] = foll_load[res, ail]
                        slot_load[res, ail, nll] = lead_load[res, ail]
                a[ai, ri] = di
                # JBOD-safe disk choice: the destination's least-loaded
                # ALIVE disk (same policy as _sweep); one move per dest per
                # round keeps dload per-move exact
                np.subtract.at(
                    dload,
                    (src, np.clip(old_d, 0, D - 1)),
                    # source sheds the CURRENT role-resolved disk load —
                    # leader rows were carrying leader disk load
                    np.where(old_d >= 0, cur[int(Resource.DISK)], 0.0),
                )
                # k-th least-loaded alive disk for the k-th intake of the
                # dest this round: one argmin per row would stack every
                # batched intake onto the same disk (quality-only — the
                # default stack's DiskCapacityGoal is broker-level)
                dchoice = np.where(disk_alive[di], dload[di], np.inf)
                ranked = np.argsort(dchoice, axis=1)
                n_alive_d = np.maximum(disk_alive[di].sum(axis=1), 1)
                best_d = ranked[
                    np.arange(di.size), rank_acc % n_alive_d
                ].astype(dsk.dtype)
                dsk[ai, ri] = best_d
                np.add.at(
                    dload, (di, best_d), foll_load[int(Resource.DISK), ai]
                )
                # sources are always alive (dead-broker columns are zeroed
                # in counts, so they are never over-band), so the live
                # count update stays consistent with the init-time zeroing
                np.subtract.at(counts, (ts[oi], src), 1)
                np.add.at(counts, (ts[oi], di), 1)
                np.subtract.at(rc, src, 1)
                np.add.at(rc, di, 1)
                moved += oi.size
                keep = np.ones(ps.size, bool)
                keep[oi] = False
                ps, rs, ts = ps[keep], rs[keep], ts[keep]
                lead_row, b2, nl = lead_row[keep], b2[keep], nl[keep]
                occ = occ[keep]
            # candidates that found no destination this round retry the
            # next-ranked destination in the following round
        total_moved += moved
        if moved == 0:
            break

    if total_moved == 0:
        return m, 0
    out = m.replace(
        assignment=jnp.asarray(a, dtype=m.assignment.dtype),
        replica_disk=jnp.asarray(dsk, dtype=m.replica_disk.dtype),
        leader_slot=jnp.asarray(lslot, dtype=m.leader_slot.dtype),
    )
    return out, total_moved


def finalize_preferred_leaders(
    model: TensorClusterModel,
    cfg: GoalConfig,
    goal_names: tuple[str, ...],
    stack_after,
    reevaluate: bool = True,
):
    """The pipeline's LAST stage, shared by every verified path (optimize()
    and the facade's greedy backend): canonicalize preferred leaders and
    re-evaluate the stack when anything changed. The verifier's zero
    PLE slack (ccx.verify.soft_goal_slack) is a contract that every
    verified pipeline ends here — change this helper, not the call sites.

    Returns (model, stack_after, n_canonicalized). No-op for stacks that
    don't score PreferredLeaderElectionGoal (e.g. intra-broker disk-only).

    ``reevaluate=False`` (the warm pipeline) returns ``stack_after=None``
    instead of paying the re-evaluation when canonicalization changed the
    placement — the caller evaluates the final model exactly once anyway
    (``incremental.warm_finish`` fuses that eval with the pressure bank).
    """
    if "PreferredLeaderElectionGoal" not in goal_names:
        return model, stack_after, 0
    model, n = canonicalize_preferred_leaders(model)
    if n:
        if not reevaluate:
            return model, None, n
        from ccx.goals.stack import evaluate_stack

        stack_after = evaluate_stack(model, cfg, goal_names)
    return model, stack_after, n


@costmodel.instrument("leader-fix")
@jax.jit
def _leader_fix(m: TensorClusterModel, assignment, leader_slot):
    """Point leaders at an alive, non-excluded replica where possible."""
    valid = (assignment >= 0) & m.partition_valid[:, None]
    safe_b = jnp.clip(assignment, 0, m.B - 1)
    lead_ok = (
        m.broker_alive & m.broker_valid & ~m.broker_excl_leadership
    )[safe_b] & valid
    cur_ok = jnp.take_along_axis(lead_ok, leader_slot[:, None], 1)[:, 0]
    first_ok = jnp.argmax(lead_ok, axis=1).astype(jnp.int32)
    any_ok = jnp.any(lead_ok, axis=1)
    return jnp.where(cur_ok | ~any_ok, leader_slot, first_ok)


def _repair_nk(m: TensorClusterModel, nk: int | None) -> int:
    # static per-sweep offender bound: [nk, B] scoring matrices instead of
    # [P, B] (0.5 GB of temporaries at B5). The P//16 bucket (shared with
    # the SA hot-list operand — ONE sizing rule, see _evac_bucket) covers
    # typical offender densities in one or two sweeps; the sweep loop
    # retries while offenders remain, so a larger spill only costs extra
    # sweeps, never correctness.
    if nk is None:
        return _evac_bucket(m.P)
    return nk


def hard_repair_async(
    m: TensorClusterModel,
    cfg: GoalConfig,
    goal_names: tuple[str, ...],
    max_sweeps: int = 8,
    seed: int = 17,
    nk: int | None = None,
) -> tuple[TensorClusterModel, jnp.ndarray]:
    """Device-backend repair WITHOUT a host sync: dispatches the single
    `_repair_loop` program and returns (model of lazy arrays, total-moves
    device scalar). The optimizer's pipelined path feeds the arrays
    straight into the annealer — repair leaves the host-blocking critical
    path entirely (its device time folds into the anneal phase's queue,
    and on the tunneled TPU the eight per-sweep round trips disappear)."""
    target_rack = bool(RACK_TARGET_GOALS & set(goal_names))
    target_capacity = bool(CAPACITY_GOALS & set(goal_names))
    assignment, replica_disk = m.assignment, m.replica_disk
    total = jnp.asarray(0, jnp.int32)
    if allows_inter_broker(goal_names):
        assignment, replica_disk, total = _repair_loop(
            m, assignment, m.leader_slot, replica_disk,
            jax.random.PRNGKey(seed), jnp.asarray(max_sweeps, jnp.int32),
            target_rack=target_rack, target_capacity=target_capacity,
            cfg=cfg, nk=_repair_nk(m, nk),
        )
    leader_slot = _leader_fix(m, assignment, m.leader_slot)
    out = m.replace(
        assignment=assignment, leader_slot=leader_slot,
        replica_disk=replica_disk,
    )
    return out, total


def hard_repair(
    m: TensorClusterModel,
    cfg: GoalConfig,
    goal_names: tuple[str, ...],
    max_sweeps: int = 8,
    seed: int = 17,
    nk: int | None = None,
    backend: str = "host",
) -> tuple[TensorClusterModel, int]:
    """Sweep until no targetable hard offenders remain (or max_sweeps).

    Returns (repaired model, total moves). Only runs the placement sweep for
    stacks that allow inter-broker movement; leader placement is fixed in
    all cases. ``nk`` overrides the per-sweep offender bound (tests).

    ``backend`` selects the loop driver (config `optimizer.repair.backend`):
    "device" runs the whole sweep loop as one compiled program
    (`_repair_loop` — traced sweep budget, no per-sweep host syncs);
    "host" is the round-2 python loop, kept as the fallback and the
    parity reference. Both share `_sweep_impl`, the per-sweep key-split
    sequence and the stop rules, so their repaired states agree (pinned by
    tests/test_repair.py::test_device_repair_parity_with_host).
    """
    if backend == "device":
        out, total = hard_repair_async(
            m, cfg, goal_names, max_sweeps=max_sweeps, seed=seed, nk=nk
        )
        return out, int(total)
    target_rack = bool(RACK_TARGET_GOALS & set(goal_names))
    target_capacity = bool(CAPACITY_GOALS & set(goal_names))
    assignment = m.assignment
    leader_slot = m.leader_slot
    replica_disk = m.replica_disk
    total = 0
    nk = _repair_nk(m, nk)
    if allows_inter_broker(goal_names):
        key = jax.random.PRNGKey(seed)
        prev_over = None
        for i in range(max_sweeps):
            key, sub = jax.random.split(key)
            assignment, replica_disk, n, n_over, n_struct = _sweep(
                m, assignment, leader_slot, replica_disk, sub,
                target_rack=target_rack, target_capacity=target_capacity,
                cfg=cfg, nk=nk,
            )
            n = int(n)
            n_over = int(n_over)
            total += n
            if n == 0:
                break
            # capacity shedding that stops reducing the over-capacity broker
            # count is oscillating (destinations saturated) — stop and let
            # the annealer's targeted draws finish the job. Only honored once
            # NO structural offenders (dead broker/disk, duplicate, rack)
            # remained when the sweep ran: with > nk offenders a sweep is
            # bounded, and breaking early could strand dead-broker
            # evacuation on the annealer's random draws.
            if (
                int(n_struct) == 0
                and prev_over is not None
                and 0 < prev_over <= n_over
            ):
                break
            prev_over = n_over
    leader_slot = _leader_fix(m, assignment, leader_slot)
    out = m.replace(
        assignment=assignment, leader_slot=leader_slot,
        replica_disk=replica_disk,
    )
    return out, total
