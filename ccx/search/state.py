"""Per-chain search state with O(R)-per-move incremental cost maintenance.

The expensive part of scoring a candidate move is the broker aggregates
(``ccx.model.aggregates``: one full pass is O(P*R)). A move only changes one
partition's contribution, so search maintains everything incrementally — the
TPU-native analogue of the reference's ``ClusterModel.relocateReplica`` /
``transferLeadership`` in-place load bookkeeping (SURVEY.md C1):

* **[B]-level aggregates** (broker_load, replica/leader counts, potential
  nw-out, leader bytes-in, disk_load) — O(R) scatter-adds per move; goal
  kernels re-score them in O(B) per candidate (small).
* **[T, B] topic count matrices** — NOT carried in the search state at all.
  Round 2 finding: reading a topic row and scatter-writing cells of the same
  loop-carried [T, B] matrix defeats XLA's in-place buffer reuse, copying
  both matrices every move (~128 MB/move at B5 scale across 32 chains —
  measured 40 ms/move on CPU vs <1 ms for everything else combined). The
  two topic goals' contributions are instead carried as exact scalar
  accumulators, and the ONE topic row a move touches is **derived on demand
  from the live assignment** via a static topic→member-partitions index
  (``topic_member_index``; O(max-partitions-per-topic × R) gather +
  [B]-scatter, a few KB) and re-scored with the shared
  ``ccx.goals.topic_terms`` row functions.
* **per-partition goal sums** (``ccx.goals.partition_terms``) — row deltas.
* **the full per-goal cost vector** — assembled exactly per candidate, so
  acceptance can compare lexicographically (no tier-weight float32 blindness
  for low tiers).

Exactness: every accumulator (partition sums, topic deficit/penalty sums,
topic totals) is integer-valued and therefore exact in float32 under
incremental +/- updates; float drift is confined to broker_load-style sums,
whose goal costs are recomputed (not accumulated) each move. Rejected moves
apply all updates with weight 0 — a bit-exact no-op, so state never drifts on
rejection.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from ccx.common.resources import Resource
from ccx.goals import partition_terms as pt
from ccx.goals import topic_terms as tt
from ccx.goals.base import GOAL_REGISTRY, GoalConfig
from ccx.goals.stack import soft_weights
from ccx.model.aggregates import BrokerAggregates, broker_aggregates
from ccx.model.tensor_model import TensorClusterModel


#: move-kind indexes for the per-move-type proposal/acceptance counters
#: (single covers replica/leadership/disk relocations; the two swap kinds
#: are the count-preserving pair actions)
KIND_SINGLE = 0
KIND_REPLICA_SWAP = 1
KIND_LEADERSHIP_SWAP = 2
NUM_MOVE_KINDS = 3
MOVE_KIND_NAMES = ("single", "replicaSwap", "leadershipSwap")


@struct.dataclass
class SearchState:
    """Dynamic per-chain state. The static cluster attributes (loads,
    capacities, racks, masks) live in the TensorClusterModel the search was
    started from; only placement (and derived bookkeeping) changes."""

    assignment: jnp.ndarray    # int32[P, R]
    leader_slot: jnp.ndarray   # int32[P]
    replica_disk: jnp.ndarray  # int32[P, R]
    agg: BrokerAggregates
    part_sums: jnp.ndarray     # float32[len(pt.PARTITION_GOALS)] (exact ints)
    topic_totals: jnp.ndarray  # float32[T] alive-broker replica totals (exact)
    mtl_sum: jnp.ndarray       # f32 scalar — raw MinTopicLeaders deficit
    trd_sum: jnp.ndarray       # f32 scalar — raw TopicReplicaDistribution pen
    cost_vec: jnp.ndarray      # f32[G] — per-goal costs, priority order
    key: jnp.ndarray           # PRNG key
    n_accepted: jnp.ndarray    # int32 scalar
    hard_mask: tuple[bool, ...] = struct.field(pytree_node=False)
    #: topic-grouped mirror of the placement (``grouped_placement``): present
    #: iff the stack scores topic goals; None otherwise
    grouped_assign: jnp.ndarray | None = None   # int32[T, max_pt, R]
    grouped_leader: jnp.ndarray | None = None   # int32[T, max_pt]
    #: per-move-kind proposal/acceptance counters int32[NUM_MOVE_KINDS]
    #: (single / replica-swap / leadership-swap — ref ActionType vocabulary).
    #: Observability only: weight-0 updates keep rejected moves bit-exact
    #: no-ops on every OTHER field; these two count regardless so frontier
    #: regressions are diagnosable from artifacts alone.
    n_prop_kind: jnp.ndarray | None = None
    n_acc_kind: jnp.ndarray | None = None

    @property
    def hard_cost(self) -> jnp.ndarray:
        mask = jnp.asarray(self.hard_mask)
        return jnp.sum(jnp.where(mask, self.cost_vec, 0.0))

    @property
    def soft_cost(self) -> jnp.ndarray:
        mask = jnp.asarray(self.hard_mask)
        return jnp.sum(
            jnp.where(mask, 0.0, self.cost_vec * soft_weights(self.hard_mask))
        )


@struct.dataclass
class MoveDelta:
    """Everything needed to accept a scored candidate move exactly."""

    cost_vec: jnp.ndarray   # f32[G] — candidate state's full cost vector
    part_sums: jnp.ndarray  # f32[4] — candidate partition-goal sums
    d_mtl: jnp.ndarray      # f32 — raw MinTopicLeaders deficit delta
    d_trd: jnp.ndarray      # f32 — raw TopicReplicaDistribution pen delta
    d_total: jnp.ndarray    # f32 — topic(p) alive-replica-total delta


@struct.dataclass
class SwapDelta:
    """MoveDelta for a two-partition REPLICA_SWAP: combined accumulator
    deltas plus the second topic's total delta (zero when both partitions
    share a topic)."""

    cost_vec: jnp.ndarray
    part_sums: jnp.ndarray
    d_mtl: jnp.ndarray
    d_trd: jnp.ndarray
    d_total: jnp.ndarray    # topic(p1) alive-total delta (combined if same)
    d_total2: jnp.ndarray   # topic(p2) alive-total delta (0 if same topic)


@struct.dataclass
class PartitionView:
    """Every per-partition datum one move needs, gathered into O(R) scalars.

    This is the sharding seam (SURVEY.md section 5.7): search logic consumes
    a PartitionView instead of indexing the [P]-axis arrays directly, so the
    partition axis can live sharded across a device mesh — the shard owning
    partition p gathers its view locally and a psum broadcasts it
    (ccx.parallel), while the unsharded path is a plain local gather.
    """

    pvalid: jnp.ndarray     # bool scalar
    immovable: jnp.ndarray  # bool scalar
    topic: jnp.ndarray      # int32 scalar
    lead_load: jnp.ndarray  # f32[RES] — leader-role load of partition p
    foll_load: jnp.ndarray  # f32[RES]
    assign: jnp.ndarray     # int32[R] — current row in the search state
    leader: jnp.ndarray     # int32 scalar
    disk: jnp.ndarray       # int32[R]


def gather_view(state: SearchState, m: TensorClusterModel, p: jnp.ndarray) -> PartitionView:
    """Local (unsharded) gather of partition p's view."""
    return PartitionView(
        pvalid=m.partition_valid[p],
        immovable=m.partition_immovable[p],
        topic=m.partition_topic[p],
        lead_load=jax.lax.dynamic_slice_in_dim(m.leader_load, p, 1, axis=1)[:, 0],
        foll_load=jax.lax.dynamic_slice_in_dim(m.follower_load, p, 1, axis=1)[:, 0],
        assign=state.assignment[p],
        leader=state.leader_slot[p],
        disk=state.replica_disk[p],
    )


def gather_views(
    state: SearchState, m: TensorClusterModel, ps: jnp.ndarray
) -> PartitionView:
    """Stacked local gather: one PartitionView with leading axis len(ps).

    The annealer's unified two-partition step gathers BOTH partitions of a
    (possibly degenerate) swap in a single stacked read per carried buffer —
    two separate gathers would be a second use and defeat XLA's in-place
    scatter on the buffer (module docstring)."""
    return PartitionView(
        pvalid=m.partition_valid[ps],
        immovable=m.partition_immovable[ps],
        topic=m.partition_topic[ps],
        lead_load=m.leader_load[:, ps].T,     # [k, RES]
        foll_load=m.follower_load[:, ps].T,
        assign=state.assignment[ps],          # [k, R]
        leader=state.leader_slot[ps],
        disk=state.replica_disk[ps],
    )


def view_at(views: PartitionView, i: int) -> PartitionView:
    """The i-th PartitionView of a stacked gather."""
    return jax.tree.map(lambda x: x[i], views)


# --------------------------------------------------------------------------
# Usage-coupled swap proposal support (VERDICT r5 next #4): per-broker
# overload scores for the tiers only count-preserving swaps can fix, plus
# the static per-replica usage weighting both samplers share.
# --------------------------------------------------------------------------

#: static resource weights for the combined per-replica usage scalar the
#: coupled samplers rank candidates by. NW_OUT dominates (the lean rung's
#: residual frontier tier, NetworkOutboundUsageDistribution); CPU rides at
#: 0.3 because CPU cells sit one tier below and correlate with the same
#: hot replicas. NW_IN/DISK excluded: their tiers are already near-solved
#: at lean and their loads would dilute the NW_OUT ranking.
USAGE_WEIGHTS = (0.3, 0.0, 1.0, 0.0)  # CPU, NW_IN, NW_OUT, DISK


def usage_weights() -> jnp.ndarray:
    return jnp.asarray(USAGE_WEIGHTS, jnp.float32)


def bump_kind_counters(
    state: "SearchState",
    kind: jnp.ndarray,
    proposed: jnp.ndarray,
    accepted: jnp.ndarray,
) -> "SearchState":
    """Scatter-add the per-move-kind proposal/acceptance counters (KIND_*
    indexes; ``kind`` scalar or [k] with matching int weights). Counting is
    explicit at the proposal sites — not inside apply_move/apply_swap — so
    mixed-branch loops (greedy's single-batch vs best-swap cond) attribute
    each iteration's full proposal mix exactly once. No-op when the state
    carries no counters."""
    if state.n_prop_kind is None:
        return state
    return state.replace(
        n_prop_kind=state.n_prop_kind.at[kind].add(proposed),
        n_acc_kind=state.n_acc_kind.at[kind].add(accepted),
    )


@struct.dataclass
class BrokerPressure:
    """Per-broker over/under band-deviation scores, derived from the live
    [B]-level aggregates each step/iteration (O(B) math — never a [P] pass).

    The *_over arrays are the hot-endpoint sampling weights (replicas ON
    these brokers want to shed usage/leadership), the *_under arrays the
    cold-endpoint weights. Band math mirrors ``ccx.goals.kernels``
    ``_band_penalty`` exactly (hinge outside [avg*(2-t), avg*t] over alive
    brokers) plus a mild toward-average term so the sampler still pairs
    endpoints when strict violators have no strict-violator partner —
    acceptance (lex + hard veto) remains the only correctness gate."""

    usage_over: jnp.ndarray   # f32[B] combined NW_OUT/CPU utilization over
    usage_under: jnp.ndarray  # f32[B] combined utilization headroom
    lead_over: jnp.ndarray    # f32[B] leader-count band excess
    lead_under: jnp.ndarray   # f32[B] leader-count band deficit
    lbi_over: jnp.ndarray     # f32[B] leader-bytes-in band excess
    lbi_under: jnp.ndarray    # f32[B] leader-bytes-in band deficit


def _band_pressure(values, alive, avg, threshold):
    """(over, under) hinge distances outside the kernel band, plus a 0.1x
    toward-average term inside it (sampling weight only), normalized by
    avg so resources combine."""
    safe_avg = jnp.maximum(avg, 1e-9)
    upper = avg * threshold
    lower = avg * (2.0 - threshold)
    over = jnp.maximum(values - upper, 0.0) + 0.1 * jnp.maximum(
        values - avg, 0.0
    )
    under = jnp.maximum(lower - values, 0.0) + 0.1 * jnp.maximum(
        avg - values, 0.0
    )
    return (
        jnp.where(alive, over / safe_avg, 0.0),
        jnp.where(alive, under / safe_avg, 0.0),
    )


def broker_pressure(
    m: TensorClusterModel, agg: BrokerAggregates, cfg: GoalConfig
) -> BrokerPressure:
    """Live per-broker pressure for the swap-coupled tiers from the
    incrementally-maintained aggregates (no placement reads)."""
    alive = m.broker_valid & m.broker_alive
    usage_over = jnp.zeros(m.B, jnp.float32)
    usage_under = jnp.zeros(m.B, jnp.float32)
    for res in (Resource.NW_OUT, Resource.CPU):
        wr = float(USAGE_WEIGHTS[int(res)])
        if wr == 0.0:
            continue
        cap = m.broker_capacity[res]
        load = jnp.where(alive, agg.broker_load[res], 0.0)
        avg_util = jnp.sum(load) / jnp.maximum(
            jnp.sum(jnp.where(alive, cap, 0.0)), 1e-9
        )
        util = load / jnp.where(cap > 0, cap, 1.0)
        over, under = _band_pressure(
            util, alive & (cap > 0), avg_util, cfg.balance_threshold[int(res)]
        )
        usage_over = usage_over + wr * over
        usage_under = usage_under + wr * under

    lead_ok = alive & ~m.broker_excl_leadership
    n_lead = jnp.maximum(jnp.sum(lead_ok), 1).astype(jnp.float32)
    counts = agg.leader_count.astype(jnp.float32)
    lead_avg = jnp.sum(jnp.where(lead_ok, counts, 0.0)) / n_lead
    lead_over, lead_under = _band_pressure(
        counts, lead_ok, lead_avg, cfg.leader_balance_threshold
    )

    lbi = jnp.where(lead_ok, agg.leader_bytes_in, 0.0)
    lbi_avg = jnp.sum(lbi) / n_lead
    lbi_over, lbi_under = _band_pressure(
        lbi, lead_ok, lbi_avg, cfg.leader_bytes_in_balance_threshold
    )
    return BrokerPressure(
        usage_over=usage_over,
        usage_under=usage_under,
        lead_over=lead_over,
        lead_under=lead_under,
        lbi_over=lbi_over,
        lbi_under=lbi_under,
    )


def max_partitions_per_topic(m: TensorClusterModel) -> int:
    """Host-side static bound for ``topic_member_index`` (jit static arg).

    Bucketed UP to the next power of two (floor 8): the bound is a
    capacity — topics with fewer members are -1-padded, so a larger cap is
    bit-inert — but it keys every compiled search program. Exact counts
    made same-shape clusters compile per SNAPSHOT (fleet serving's 16
    concurrent B3-sized jobs each paid a fresh SA/polish program set
    because their random topic skews differed by a few partitions);
    bucketing pins the program to the shape family, so a fleet of
    same-bucket clusters shares ONE compiled set and a drifting snapshot
    only recompiles when its densest topic crosses a power of two."""
    import numpy as np

    topic = np.asarray(m.partition_topic)
    valid = np.asarray(m.partition_valid)
    if not valid.any():
        return 1
    exact = max(int(np.bincount(topic[valid], minlength=m.num_topics).max()), 1)
    return max(1 << (exact - 1).bit_length(), 8)


def topic_member_index(m: TensorClusterModel, max_pt: int) -> jnp.ndarray:
    """int32[T, max_pt] — partition ids of each topic's valid partitions,
    -1 padded. Static during a search (topic membership never changes);
    device-computable so it can be built inside a jitted runner."""
    T = m.num_topics
    topic = jnp.where(
        m.partition_valid, m.partition_topic, jnp.int32(T)
    )  # invalid partitions sort to a sentinel bucket past every topic
    order = jnp.argsort(topic).astype(jnp.int32)
    counts = jnp.zeros(T + 1, jnp.int32).at[topic].add(1)[:T]
    starts = jnp.cumsum(counts) - counts
    idx = starts[:, None] + jnp.arange(max_pt, dtype=jnp.int32)[None, :]
    in_range = jnp.arange(max_pt)[None, :] < counts[:, None]
    return jnp.where(in_range, order[jnp.clip(idx, 0, m.P - 1)], -1)


#: goals whose incremental scoring needs per-topic broker-count rows
TOPIC_GOALS = frozenset(
    {"MinTopicLeadersPerBrokerGoal", "TopicReplicaDistributionGoal"}
)


def stack_needs_topic(goal_names: tuple[str, ...]) -> bool:
    """True when the stack scores topic goals — the searches then carry the
    grouped placement mirror (``make_topic_group`` + ``grouped_placement``)."""
    return bool(TOPIC_GOALS & set(goal_names))


@struct.dataclass
class TopicGroup:
    """Static topic-membership structure (never mutated during search).

    ``members[t, j]`` — global partition id of topic t's j-th valid
    partition (-1 pad); ``member_slot[p]`` — j such that
    ``members[topic(p), j] == p`` (0 for invalid partitions — writes for
    those are routed out of bounds and dropped)."""

    members: jnp.ndarray      # int32[T, max_pt]
    member_slot: jnp.ndarray  # int32[P]


def make_topic_group(m: TensorClusterModel, max_pt: int) -> TopicGroup:
    members = topic_member_index(m, max_pt)
    flat = members.reshape(-1)
    slots = jnp.tile(
        jnp.arange(members.shape[1], dtype=jnp.int32), members.shape[0]
    )
    ok = flat >= 0
    # every valid partition appears exactly once; pad entries add 0 at p=0
    member_slot = (
        jnp.zeros(m.P, jnp.int32)
        .at[jnp.clip(flat, 0, m.P - 1)]
        .add(jnp.where(ok, slots, 0))
    )
    return TopicGroup(members=members, member_slot=member_slot)


def grouped_placement(
    m: TensorClusterModel, group: TopicGroup
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Initial topic-grouped mirror of (assignment rows, leader slot):
    ``grouped_assign[t, j] = assignment[members[t, j]]`` (-1 pad rows).

    Why a mirror exists at all: the topic goals need topic t's per-broker
    counts each move. Deriving them from ``assignment`` adds a second gather
    on the loop-carried placement arrays, and XLA abandons in-place scatter
    on a carried buffer with more than one read — copying ~3.5 MB x chains
    per move (measured 17 ms/move at B5 scale). The mirror gives every
    carried buffer exactly one read + one write per move: ``assignment``
    keeps its view-gather + row-write, the mirror gets one block read +
    one cell write, and both stay in-place."""
    ok = group.members >= 0
    mpc = jnp.clip(group.members, 0, m.P - 1)
    ga = jnp.where(ok[..., None], m.assignment[mpc], -1)
    gl = jnp.where(ok, m.leader_slot[mpc], -1)
    return ga, gl


def derived_topic_rows(state: "SearchState", ts: jnp.ndarray, B: int):
    """Per-broker (replica_count, leader_count) int32[..., B] rows for the
    topic(s) ``ts`` (scalar or [k]), derived from the grouped mirror with a
    single stacked gather."""
    if state.grouped_assign is None:
        raise ValueError(
            "goal stack scores topic goals but the search state carries no "
            "grouped placement mirror — init_search_state(group=...) required"
        )
    blocks = state.grouped_assign[ts]        # [..., max_pt, R]
    leads = state.grouped_leader[ts]         # [..., max_pt]
    valid = blocks >= 0
    b = jnp.clip(blocks, 0, B - 1)
    R = blocks.shape[-1]
    is_lead = (jnp.arange(R) == leads[..., None]) & valid

    def count(vals):
        flat_b = b.reshape(*b.shape[:-2], -1)
        flat_v = vals.reshape(*vals.shape[:-2], -1).astype(jnp.int32)
        zero = jnp.zeros((*b.shape[:-2], B), jnp.int32)
        if flat_b.ndim == 1:
            return zero.at[flat_b].add(flat_v)
        return jax.vmap(lambda z, bb, vv: z.at[bb].add(vv))(
            zero, flat_b, flat_v
        )

    return count(valid), count(is_lead)


def _scatter_broker_fields(
    agg: BrokerAggregates,
    m: TensorClusterModel,
    view: PartitionView,
    assign_row: jnp.ndarray,
    leader_slot_p: jnp.ndarray,
    disk_row: jnp.ndarray,
    w_f: jnp.ndarray,
    w_i: jnp.ndarray,
) -> BrokerAggregates:
    """Scatter-add one partition's contribution (times weight) into the
    [B]-level aggregate fields, leaving the [T, B] matrices untouched —
    candidate scoring updates only the cheap-to-copy [B]-level fields and
    scores the topic goals from row deltas instead. Weight 0 is a bit-exact
    no-op, which is how rejected moves avoid drift."""
    R = assign_row.shape[0]
    valid = (assign_row >= 0) & view.pvalid
    b = jnp.clip(assign_row, 0, m.B - 1)
    is_lead = (jnp.arange(R) == leader_slot_p) & valid

    lead_load = view.lead_load
    foll_load = view.foll_load
    # [RES, R] role-resolved slot loads, zeroed for invalid slots
    slot_load = jnp.where(is_lead[None, :], lead_load[:, None], foll_load[:, None])
    slot_load = jnp.where(valid[None, :], slot_load, 0.0)

    vf = valid.astype(jnp.float32)
    vi = valid.astype(jnp.int32)
    li = is_lead.astype(jnp.int32)
    lf = is_lead.astype(jnp.float32)
    d = jnp.clip(disk_row, 0, m.D - 1)
    disk_ok = valid & (disk_row >= 0)

    return agg.replace(
        broker_load=agg.broker_load.at[:, b].add(w_f * slot_load),
        replica_count=agg.replica_count.at[b].add(w_i * vi),
        leader_count=agg.leader_count.at[b].add(w_i * li),
        potential_nw_out=agg.potential_nw_out.at[b].add(
            w_f * lead_load[Resource.NW_OUT] * vf
        ),
        leader_bytes_in=agg.leader_bytes_in.at[b].add(
            w_f * lead_load[Resource.NW_IN] * lf
        ),
        disk_load=agg.disk_load.at[b, d].add(
            w_f * slot_load[Resource.DISK] * disk_ok.astype(jnp.float32)
        ),
    )


def scatter_partition(
    agg: BrokerAggregates,
    m: TensorClusterModel,
    view: PartitionView,
    assign_row: jnp.ndarray,   # int32[R]
    leader_slot_p: jnp.ndarray,  # int32 scalar
    disk_row: jnp.ndarray,     # int32[R]
    w_f: jnp.ndarray,          # f32 scalar weight (+1 add, -1 remove, 0 no-op)
    w_i: jnp.ndarray,          # int32 scalar weight
) -> BrokerAggregates:
    """Weighted scatter of one partition's contribution into the [B]-level
    aggregate fields (<= 2R cells per array). The [T, B] topic matrices are
    deliberately NOT maintained during search — topic rows are derived on
    demand from the grouped placement mirror (``derived_topic_rows``; see
    module docstring for the copy-per-move pathology this avoids)."""
    return _scatter_broker_fields(
        agg, m, view, assign_row, leader_slot_p, disk_row, w_f, w_i
    )


def topic_row_delta(
    m: TensorClusterModel,
    view: PartitionView,
    old: tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray],
    new: tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray],
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(d_replica_count, d_leader_count) int32[B] — the move's delta to
    topic(p)'s count rows."""
    R = old[0].shape[0]

    def contrib(assign_row, leader_slot_p, w):
        valid = (assign_row >= 0) & view.pvalid
        b = jnp.clip(assign_row, 0, m.B - 1)
        is_lead = (jnp.arange(R) == leader_slot_p) & valid
        drc = jnp.zeros(m.B, jnp.int32).at[b].add(w * valid.astype(jnp.int32))
        dlc = jnp.zeros(m.B, jnp.int32).at[b].add(w * is_lead.astype(jnp.int32))
        return drc, dlc

    drc_o, dlc_o = contrib(old[0], old[1], -1)
    drc_n, dlc_n = contrib(new[0], new[1], 1)
    return drc_o + drc_n, dlc_o + dlc_n


def partition_row_sums(
    m: TensorClusterModel,
    view: PartitionView,
    assign_row: jnp.ndarray,
    leader_slot_p: jnp.ndarray,
    disk_row: jnp.ndarray,
) -> jnp.ndarray:
    """float32[4] — one partition's contribution to PARTITION_GOALS sums."""
    return pt.partition_sums(
        m,
        assign_row[None, :],
        leader_slot_p[None],
        disk_row[None, :],
        view.pvalid[None],
    )


#: KafkaAssignerEvenRackAwareGoal (SURVEY.md C19) decomposes into the
#: incrementally-maintained RackAwareGoal sum + an aggregate-side
#: leader-evenness term, so it is searchable without its own slot.
DECOMPOSED = {"KafkaAssignerEvenRackAwareGoal"}


def check_searchable(goal_names: tuple[str, ...]) -> None:
    part_idx = {n: i for i, n in enumerate(pt.PARTITION_GOALS)}
    for name in goal_names:
        if (
            GOAL_REGISTRY[name].placement_dependent
            and name not in part_idx
            and name not in DECOMPOSED
        ):
            raise ValueError(
                f"goal {name} reads per-partition placement but has no "
                "incrementally-maintained sum; it cannot be searched "
                "(add it to partition_terms.PARTITION_GOALS or evaluate "
                "it via evaluate_stack only)"
            )


def _kaera_evenness(m: TensorClusterModel, leader_count: jnp.ndarray) -> jnp.ndarray:
    """Leader-evenness half of KafkaAssignerEvenRackAwareGoal's cost (same
    math as the full kernel in ccx.goals.kernels)."""
    alive = m.broker_valid & m.broker_alive
    n_alive = jnp.maximum(jnp.sum(alive).astype(jnp.float32), 1.0)
    avg = jnp.sum(leader_count).astype(jnp.float32) / n_alive
    upper = jnp.ceil(avg)
    over = jnp.where(alive, jnp.maximum(leader_count - upper, 0.0), 0.0)
    return jnp.sum(over) / jnp.maximum(avg, 1e-9)


def make_cost_vector_fn(
    m: TensorClusterModel, goal_names: tuple[str, ...], cfg: GoalConfig
):
    """Build ``(agg, part_sums, mtl_sum, trd_sum, trd_norm) -> costs f32[G]``.

    Topic-goal entries come from the exact scalar accumulators; every other
    aggregate goal re-scores its kernel against the (cheap) [B]-level fields.
    The [T, B] matrices inside ``agg`` are never read here.
    """
    check_searchable(goal_names)
    part_idx = {n: i for i, n in enumerate(pt.PARTITION_GOALS)}

    def vector_fn(
        agg: BrokerAggregates,
        part_sums: jnp.ndarray,
        mtl_sum: jnp.ndarray,
        trd_sum: jnp.ndarray,
        trd_norm: jnp.ndarray,
    ) -> jnp.ndarray:
        # PreferredLeaderElectionGoal's kernel cost is violations/n_leaders;
        # the leader total from agg equals the valid-partition count and stays
        # correct under partition-axis sharding (psum'd agg, ccx.parallel).
        inv_np = 1.0 / jnp.maximum(
            jnp.sum(agg.leader_count).astype(jnp.float32), 1.0
        )
        costs = []
        for name in goal_names:
            if name in part_idx:
                c = part_sums[part_idx[name]]
                if name == "PreferredLeaderElectionGoal":
                    c = c * inv_np
            elif name == "MinTopicLeadersPerBrokerGoal":
                c = mtl_sum
            elif name == "TopicReplicaDistributionGoal":
                c = trd_sum / trd_norm
            elif name == "KafkaAssignerEvenRackAwareGoal":
                c = part_sums[part_idx["RackAwareGoal"]] + _kaera_evenness(
                    m, agg.leader_count
                )
            else:
                c = GOAL_REGISTRY[name].fn(m, agg, cfg).cost
            costs.append(c)
        return jnp.stack(costs)

    return vector_fn


def make_move_scorer(
    m: TensorClusterModel,
    goal_names: tuple[str, ...],
    cfg: GoalConfig,
):
    """Build ``score(state, view, old_rows, new_rows) -> MoveDelta``.

    Per move this touches: O(R) scatter cells on the [B]-level aggregates,
    ONE topic-row pair derived from the grouped mirror
    (``derived_topic_rows``), and O(B) kernel re-scores — independent of
    P and T.
    """
    vector_fn = make_cost_vector_fn(m, goal_names, cfg)
    needs_topic = stack_needs_topic(goal_names)
    T = m.num_topics

    def score(
        state: SearchState,
        view: PartitionView,
        old: tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray],
        new: tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray],
    ) -> MoveDelta:
        agg1 = _scatter_broker_fields(
            state.agg, m, view, *old, jnp.float32(-1), jnp.int32(-1)
        )
        agg2 = _scatter_broker_fields(agg1, m, view, *new, jnp.float32(1), jnp.int32(1))
        part_new = (
            state.part_sums
            - partition_row_sums(m, view, *old)
            + partition_row_sums(m, view, *new)
        )

        zero = jnp.float32(0.0)
        if needs_topic:
            t = view.topic
            drc, dlc = topic_row_delta(m, view, old, new)
            trc_row, tlc_row = derived_topic_rows(state, t, m.B)
            new_trc = trc_row + drc
            new_tlc = tlc_row + dlc
            flagged = m.topic_min_leaders[t]
            d_mtl = tt.mtl_row(m, cfg, flagged, new_tlc) - tt.mtl_row(
                m, cfg, flagged, tlc_row
            )
            pen_new, _ = tt.trd_row_pen(m, cfg, new_trc)
            pen_old, _ = tt.trd_row_pen(m, cfg, trc_row)
            d_trd = pen_new - pen_old
            total_old = tt.trd_row_total(m, trc_row)
            total_new = tt.trd_row_total(m, new_trc)
            d_total = total_new - total_old
            # normalizer shift: only topic t's avg term changes
            n_alive = jnp.maximum(
                jnp.sum(m.broker_valid & m.broker_alive), 1
            ).astype(jnp.float32)
            norm_old = tt.trd_normalizer(m, state.topic_totals)
            norm_new = norm_old + (
                jnp.maximum(total_new / n_alive, 1.0)
                - jnp.maximum(total_old / n_alive, 1.0)
            ) / jnp.float32(T)
            norm_new = jnp.where(norm_new > 0, norm_new, 1.0)
        else:
            d_mtl = d_trd = d_total = zero
            norm_new = jnp.float32(1.0)

        cost_vec = vector_fn(
            agg2, part_new, state.mtl_sum + d_mtl, state.trd_sum + d_trd, norm_new
        )
        return MoveDelta(
            cost_vec=cost_vec,
            part_sums=part_new,
            d_mtl=d_mtl,
            d_trd=d_trd,
            d_total=d_total,
        )

    return score


def _placement_updates(
    state: SearchState,
    group: "TopicGroup | None",
    write: jnp.ndarray,      # bool[k] — row writes to perform (accept&owned)
    ps: jnp.ndarray,         # int32[k] LOCAL partition indexes
    mirror: jnp.ndarray,     # bool[k] — mirror writes (accept, every shard)
    global_ps: jnp.ndarray,  # int32[k] GLOBAL partition ids
    ts: jnp.ndarray,         # int32[k] topics
    rows: jnp.ndarray,       # int32[k, R] new assignment rows
    leads: jnp.ndarray,      # int32[k] new leader slots
    disks: jnp.ndarray,      # int32[k, R] new disk rows
) -> dict:
    """Placement (+ grouped-mirror) writes as stacked mode='drop' scatters.

    Every carried buffer gets exactly ONE scatter per move batch and no
    extra read: suppressed writes (reject / non-owner shard / invalid
    partition) are routed to an out-of-bounds index and dropped, instead of
    writing the current value back — a re-read of the current row would be a
    second use of the loop-carried buffer, which defeats XLA's in-place
    scatter and copies the whole array every move (see module docstring)."""
    Pn = state.assignment.shape[0]
    pidx = jnp.where(write, ps, Pn)
    out = dict(
        assignment=state.assignment.at[pidx].set(rows, mode="drop"),
        leader_slot=state.leader_slot.at[pidx].set(leads, mode="drop"),
        replica_disk=state.replica_disk.at[pidx].set(disks, mode="drop"),
    )
    if state.grouped_assign is None:
        return out
    if group is None:
        raise ValueError("state carries a grouped mirror; pass group=")
    max_pt = group.members.shape[1]
    slots = jnp.where(
        mirror,
        group.member_slot[jnp.clip(global_ps, 0, group.member_slot.shape[0] - 1)],
        max_pt,
    )
    out["grouped_assign"] = state.grouped_assign.at[ts, slots].set(
        rows, mode="drop"
    )
    out["grouped_leader"] = state.grouped_leader.at[ts, slots].set(
        leads, mode="drop"
    )
    return out


def apply_move(
    state: SearchState,
    m: TensorClusterModel,
    p: jnp.ndarray,
    view: PartitionView,
    old: tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray],
    new: tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray],
    delta: MoveDelta,
    accept: jnp.ndarray,        # bool scalar
    owned: jnp.ndarray | bool = True,
    group: "TopicGroup | None" = None,
    global_p: jnp.ndarray | None = None,
) -> SearchState:
    """Apply a scored move iff ``accept`` — reject is a bit-exact no-op
    (suppressed writes are dropped; weighted scatters run with weight 0).

    ``p`` indexes this state's [P]-axis arrays (a *local* index when the
    partition axis is sharded; ``global_p`` is the mesh-global id for the
    grouped-mirror write, defaulting to ``p``); ``owned`` gates the row
    writes so only the shard owning the partition mutates placement, while
    the replicated aggregates/accumulators/mirror are updated identically
    on every shard."""
    af = accept.astype(jnp.float32)
    ai = accept.astype(jnp.int32)
    agg = scatter_partition(state.agg, m, view, *old, -af, -ai)
    agg = scatter_partition(agg, m, view, *new, af, ai)
    t = view.topic
    owned = jnp.asarray(owned)

    def sel(n, o):
        return jnp.where(accept, n, o)

    gp = p if global_p is None else global_p
    return state.replace(
        agg=agg,
        part_sums=sel(delta.part_sums, state.part_sums),
        topic_totals=state.topic_totals.at[t].add(af * delta.d_total),
        mtl_sum=state.mtl_sum + af * delta.d_mtl,
        trd_sum=state.trd_sum + af * delta.d_trd,
        cost_vec=sel(delta.cost_vec, state.cost_vec),
        n_accepted=state.n_accepted + ai,
        **_placement_updates(
            state,
            group,
            write=jnp.stack([accept & owned]),
            ps=jnp.stack([p]),
            mirror=jnp.stack([accept & view.pvalid]),
            global_ps=jnp.stack([gp]),
            ts=jnp.stack([t]),
            rows=jnp.stack([new[0]]),
            leads=jnp.stack([new[1]]),
            disks=jnp.stack([new[2]]),
        ),
    )


def init_search_state(
    m: TensorClusterModel,
    cfg: GoalConfig,
    goal_names: tuple[str, ...],
    key: jnp.ndarray,
    group: "TopicGroup | None" = None,
    agg: BrokerAggregates | None = None,
) -> SearchState:
    """Full (non-incremental) evaluation of the starting state. The cost
    vector is assembled through the same row functions the incremental path
    uses, so deltas can never drift from the initial evaluation semantics.

    ``agg`` lets a caller that ALREADY paid the aggregate pass (the warm
    pipeline's fused init program, which shares one pass between this
    state, the stack eval and the pressure scan) hand it in; None (every
    cold caller) computes it here, tracing the identical program as
    before the parameter existed."""
    if agg is None:
        agg = broker_aggregates(m)
    part_sums = pt.partition_sums(
        m, m.assignment, m.leader_slot, m.replica_disk, m.partition_valid
    )
    mtl_sum = jnp.sum(tt.mtl_row(m, cfg, m.topic_min_leaders, agg.topic_leader_count))
    pen, _ = tt.trd_row_pen(m, cfg, agg.topic_replica_count)
    trd_sum = jnp.sum(pen)
    topic_totals = tt.trd_row_total(m, agg.topic_replica_count)
    trd_norm = tt.trd_normalizer(m, topic_totals)
    cost_vec = make_cost_vector_fn(m, goal_names, cfg)(
        agg, part_sums, mtl_sum, trd_sum, trd_norm
    )
    # The [T, B] matrices are NOT maintained during search (module
    # docstring); carry loud [1, 1] dummies so any stale read fails on shape
    # instead of silently returning the initial counts.
    agg = agg.replace(
        topic_replica_count=jnp.zeros((1, 1), jnp.int32),
        topic_leader_count=jnp.zeros((1, 1), jnp.int32),
    )
    ga = gl = None
    if group is not None:
        ga, gl = grouped_placement(m, group)
    return SearchState(
        assignment=m.assignment,
        leader_slot=m.leader_slot,
        replica_disk=m.replica_disk,
        agg=agg,
        part_sums=part_sums,
        topic_totals=topic_totals,
        mtl_sum=mtl_sum,
        trd_sum=trd_sum,
        cost_vec=cost_vec,
        key=key,
        n_accepted=jnp.asarray(0, jnp.int32),
        hard_mask=tuple(GOAL_REGISTRY[n].hard for n in goal_names),
        grouped_assign=ga,
        grouped_leader=gl,
        n_prop_kind=jnp.zeros(NUM_MOVE_KINDS, jnp.int32),
        n_acc_kind=jnp.zeros(NUM_MOVE_KINDS, jnp.int32),
    )


def with_placement(m: TensorClusterModel, s: SearchState) -> TensorClusterModel:
    """Rebuild a TensorClusterModel carrying a search state's placement."""
    return m.replace(
        assignment=s.assignment,
        leader_slot=s.leader_slot,
        replica_disk=s.replica_disk,
    )


def make_swap_scorer(
    m: TensorClusterModel,
    goal_names: tuple[str, ...],
    cfg: GoalConfig,
):
    """Build ``score_swap(state, view1, old1, new1, view2, old2, new2) ->
    MoveDelta`` for two-partition REPLICA_SWAP actions (ref ActionType,
    SURVEY.md C20).

    A swap exchanges two replicas between brokers. Crucially it crosses
    states a single move cannot reach: fixing a usage-band violation on a
    replica-count-balanced broker means any single relocation transiently
    breaks the count band and is vetoed lexicographically — the reference
    uses REPLICA_SWAP for exactly this. Scoring composes both partitions'
    deltas exactly, including the same-topic case where both touch one
    [T, B] count row.

    The returned MoveDelta carries the *combined* accumulator deltas; apply
    with two ``apply_move`` calls (old1->new1 then old2->new2) which compose
    bit-exactly on the incremental state.
    """
    vector_fn = make_cost_vector_fn(m, goal_names, cfg)
    needs_topic = stack_needs_topic(goal_names)
    T = m.num_topics

    def score_swap(
        state: SearchState,
        view1: PartitionView,
        old1,
        new1,
        view2: PartitionView,
        old2,
        new2,
    ) -> MoveDelta:
        agg = _scatter_broker_fields(
            state.agg, m, view1, *old1, jnp.float32(-1), jnp.int32(-1)
        )
        agg = _scatter_broker_fields(agg, m, view1, *new1, jnp.float32(1), jnp.int32(1))
        agg = _scatter_broker_fields(agg, m, view2, *old2, jnp.float32(-1), jnp.int32(-1))
        agg = _scatter_broker_fields(agg, m, view2, *new2, jnp.float32(1), jnp.int32(1))
        part_new = (
            state.part_sums
            - partition_row_sums(m, view1, *old1)
            + partition_row_sums(m, view1, *new1)
            - partition_row_sums(m, view2, *old2)
            + partition_row_sums(m, view2, *new2)
        )

        zero = jnp.float32(0.0)
        if needs_topic:
            t1, t2 = view1.topic, view2.topic
            same = t1 == t2
            drc1, dlc1 = topic_row_delta(m, view1, old1, new1)
            drc2, dlc2 = topic_row_delta(m, view2, old2, new2)
            # ONE stacked gather on the grouped mirror for both topics —
            # two separate reads would be a second use of the carried buffer
            # (copy-per-move pathology, module docstring)
            trc12, tlc12 = derived_topic_rows(state, jnp.stack([t1, t2]), m.B)
            trc1, tlc1 = trc12[0], tlc12[0]
            trc2, tlc2 = trc12[1], tlc12[1]
            f1 = m.topic_min_leaders[t1]
            f2 = m.topic_min_leaders[t2]
            n_alive = jnp.maximum(
                jnp.sum(m.broker_valid & m.broker_alive), 1
            ).astype(jnp.float32)

            def row_deltas(trc_a, tlc_a, drc_a, dlc_a, flag):
                new_trc = trc_a + drc_a
                new_tlc = tlc_a + dlc_a
                d_mtl_ = tt.mtl_row(m, cfg, flag, new_tlc) - tt.mtl_row(
                    m, cfg, flag, tlc_a
                )
                pen_n, _ = tt.trd_row_pen(m, cfg, new_trc)
                pen_o, _ = tt.trd_row_pen(m, cfg, trc_a)
                tot_o = tt.trd_row_total(m, trc_a)
                tot_n = tt.trd_row_total(m, new_trc)
                d_norm_ = (
                    jnp.maximum(tot_n / n_alive, 1.0)
                    - jnp.maximum(tot_o / n_alive, 1.0)
                ) / jnp.float32(T)
                return d_mtl_, pen_n - pen_o, tot_n - tot_o, d_norm_

            # same topic: one row takes both deltas; else two independent rows
            sm = row_deltas(trc1, tlc1, drc1 + drc2, dlc1 + dlc2, f1)
            a1 = row_deltas(trc1, tlc1, drc1, dlc1, f1)
            a2 = row_deltas(trc2, tlc2, drc2, dlc2, f2)
            d_mtl = jnp.where(same, sm[0], a1[0] + a2[0])
            d_trd = jnp.where(same, sm[1], a1[1] + a2[1])
            # per-topic total deltas so apply_swap can update both cells
            d_total = jnp.where(same, sm[2], a1[2])
            d_total2 = jnp.where(same, zero, a2[2])
            d_norm = jnp.where(same, sm[3], a1[3] + a2[3])
            norm_old = tt.trd_normalizer(m, state.topic_totals)
            norm_new = norm_old + d_norm
            norm_new = jnp.where(norm_new > 0, norm_new, 1.0)
        else:
            d_mtl = d_trd = d_total = d_total2 = zero
            norm_new = jnp.float32(1.0)

        cost_vec = vector_fn(
            agg, part_new, state.mtl_sum + d_mtl, state.trd_sum + d_trd, norm_new
        )
        return SwapDelta(
            cost_vec=cost_vec,
            part_sums=part_new,
            d_mtl=d_mtl,
            d_trd=d_trd,
            d_total=d_total,
            d_total2=d_total2,
        )

    return score_swap


def apply_swap(
    state: SearchState,
    m: TensorClusterModel,
    p1: jnp.ndarray,
    view1: PartitionView,
    old1,
    new1,
    p2: jnp.ndarray,
    view2: PartitionView,
    old2,
    new2,
    delta: "SwapDelta",
    accept: jnp.ndarray,
    owned1: jnp.ndarray | bool = True,
    owned2: jnp.ndarray | bool = True,
    group: "TopicGroup | None" = None,
    global_p1: jnp.ndarray | None = None,
    global_p2: jnp.ndarray | None = None,
    active2: jnp.ndarray | bool = True,
) -> SearchState:
    """Apply a scored two-partition swap iff ``accept`` (bit-exact no-op on
    reject, same contract as apply_move; both rows land in one stacked
    mode='drop' scatter per carried buffer).

    ``active2=False`` makes partition 2 inert (the unified single-move path:
    a single move is a degenerate swap) — its row/mirror writes are dropped,
    which also guards the duplicate-index case p1 == p2 where an undefined
    scatter order could clobber the accepted row."""
    af = accept.astype(jnp.float32)
    ai = accept.astype(jnp.int32)
    agg = scatter_partition(state.agg, m, view1, *old1, -af, -ai)
    agg = scatter_partition(agg, m, view1, *new1, af, ai)
    agg = scatter_partition(agg, m, view2, *old2, -af, -ai)
    agg = scatter_partition(agg, m, view2, *new2, af, ai)

    def sel(n, o):
        return jnp.where(accept, n, o)

    totals = state.topic_totals.at[view1.topic].add(af * delta.d_total)
    totals = totals.at[view2.topic].add(af * delta.d_total2)
    gp1 = p1 if global_p1 is None else global_p1
    gp2 = p2 if global_p2 is None else global_p2

    return state.replace(
        agg=agg,
        part_sums=sel(delta.part_sums, state.part_sums),
        topic_totals=totals,
        mtl_sum=state.mtl_sum + af * delta.d_mtl,
        trd_sum=state.trd_sum + af * delta.d_trd,
        cost_vec=sel(delta.cost_vec, state.cost_vec),
        n_accepted=state.n_accepted + ai,
        **_placement_updates(
            state,
            group,
            write=jnp.stack(
                [
                    accept & jnp.asarray(owned1),
                    accept & jnp.asarray(owned2) & jnp.asarray(active2),
                ]
            ),
            ps=jnp.stack([p1, p2]),
            mirror=jnp.stack(
                [
                    accept & view1.pvalid,
                    accept & view2.pvalid & jnp.asarray(active2),
                ]
            ),
            global_ps=jnp.stack([gp1, gp2]),
            ts=jnp.stack([view1.topic, view2.topic]),
            rows=jnp.stack([new1[0], new2[0]]),
            leads=jnp.stack([new1[1], new2[1]]),
            disks=jnp.stack([new1[2], new2[2]]),
        ),
    )
