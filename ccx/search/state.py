"""Per-chain search state with O(R) incremental aggregate maintenance.

The expensive part of scoring a candidate move is the broker aggregates
(``ccx.model.aggregates``: one full pass is O(P*R)). A move only changes one
partition's contribution, so search maintains the aggregates incrementally:
*un-scatter* the partition's old contribution, *scatter* its new one — O(R)
scatter-adds — then score the goal stack from the updated aggregates
(O(B*RES + T*B)). This is the TPU-native analogue of the reference's
``ClusterModel.relocateReplica``/``transferLeadership`` in-place load
bookkeeping (SURVEY.md C1).

The four per-partition goals (ccx.goals.partition_terms.PARTITION_GOALS) are
maintained as running sums the same way: subtract the old row's contribution,
add the new row's.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from ccx.common.resources import Resource
from ccx.goals import partition_terms as pt
from ccx.goals.base import GOAL_REGISTRY, GoalConfig
from ccx.goals.stack import soft_weights
from ccx.model.aggregates import BrokerAggregates, broker_aggregates
from ccx.model.tensor_model import TensorClusterModel


@struct.dataclass
class SearchState:
    """Dynamic per-chain state. The static cluster attributes (loads,
    capacities, racks, masks) live in the TensorClusterModel the search was
    started from; only placement changes during search."""

    assignment: jnp.ndarray    # int32[P, R]
    leader_slot: jnp.ndarray   # int32[P]
    replica_disk: jnp.ndarray  # int32[P, R]
    agg: BrokerAggregates
    part_sums: jnp.ndarray     # float32[len(PARTITION_GOALS)]
    hard_cost: jnp.ndarray     # f32 scalar
    soft_cost: jnp.ndarray     # f32 scalar
    key: jnp.ndarray           # PRNG key
    n_accepted: jnp.ndarray    # int32 scalar


def scatter_partition(
    agg: BrokerAggregates,
    m: TensorClusterModel,
    p: jnp.ndarray,            # int32 scalar — partition index
    assign_row: jnp.ndarray,   # int32[R]
    leader_slot_p: jnp.ndarray,  # int32 scalar
    disk_row: jnp.ndarray,     # int32[R]
    w_f: jnp.ndarray,          # f32 scalar weight (+1 add, -1 remove, 0 no-op)
    w_i: jnp.ndarray,          # int32 scalar weight
) -> BrokerAggregates:
    """Scatter-add one partition's contribution (times weight) into agg."""
    R = assign_row.shape[0]
    valid = (assign_row >= 0) & m.partition_valid[p]
    b = jnp.clip(assign_row, 0, m.B - 1)
    is_lead = (jnp.arange(R) == leader_slot_p) & valid

    lead_load = jax.lax.dynamic_slice_in_dim(m.leader_load, p, 1, axis=1)[:, 0]
    foll_load = jax.lax.dynamic_slice_in_dim(m.follower_load, p, 1, axis=1)[:, 0]
    # [RES, R] role-resolved slot loads, zeroed for invalid slots
    slot_load = jnp.where(is_lead[None, :], lead_load[:, None], foll_load[:, None])
    slot_load = jnp.where(valid[None, :], slot_load, 0.0)

    vf = valid.astype(jnp.float32)
    vi = valid.astype(jnp.int32)
    li = is_lead.astype(jnp.int32)
    lf = is_lead.astype(jnp.float32)

    t = m.partition_topic[p]
    d = jnp.clip(disk_row, 0, m.D - 1)
    disk_ok = valid & (disk_row >= 0)

    return BrokerAggregates(
        broker_load=agg.broker_load.at[:, b].add(w_f * slot_load),
        replica_count=agg.replica_count.at[b].add(w_i * vi),
        leader_count=agg.leader_count.at[b].add(w_i * li),
        potential_nw_out=agg.potential_nw_out.at[b].add(
            w_f * lead_load[Resource.NW_OUT] * vf
        ),
        leader_bytes_in=agg.leader_bytes_in.at[b].add(
            w_f * lead_load[Resource.NW_IN] * lf
        ),
        topic_replica_count=agg.topic_replica_count.at[t, b].add(w_i * vi),
        topic_leader_count=agg.topic_leader_count.at[t, b].add(w_i * li),
        disk_load=agg.disk_load.at[b, d].add(
            w_f * slot_load[Resource.DISK] * disk_ok.astype(jnp.float32)
        ),
    )


def partition_row_sums(
    m: TensorClusterModel,
    p: jnp.ndarray,
    assign_row: jnp.ndarray,
    leader_slot_p: jnp.ndarray,
    disk_row: jnp.ndarray,
) -> jnp.ndarray:
    """float32[4] — one partition's contribution to PARTITION_GOALS sums."""
    return pt.partition_sums(
        m,
        assign_row[None, :],
        leader_slot_p[None],
        disk_row[None, :],
        m.partition_valid[p][None],
    )


def make_goal_vector_fn(
    m: TensorClusterModel, goal_names: tuple[str, ...], cfg: GoalConfig
):
    """Build ``(agg, part_sums) -> costs f32[G]`` in goal-priority order.

    Aggregate-based goals are the registered kernels evaluated against the
    *static* model attributes + the live aggregates; per-partition goals read
    the incrementally-maintained sums.
    """
    part_idx = {n: i for i, n in enumerate(pt.PARTITION_GOALS)}
    # KafkaAssignerEvenRackAwareGoal (SURVEY.md C19) decomposes into the
    # incrementally-maintained RackAwareGoal sum + an aggregate-side
    # leader-evenness term, so it is searchable without its own slot.
    DECOMPOSED = {"KafkaAssignerEvenRackAwareGoal"}
    for name in goal_names:
        if (
            GOAL_REGISTRY[name].placement_dependent
            and name not in part_idx
            and name not in DECOMPOSED
        ):
            raise ValueError(
                f"goal {name} reads per-partition placement but has no "
                "incrementally-maintained sum; it cannot be searched "
                "(add it to partition_terms.PARTITION_GOALS or evaluate "
                "it via evaluate_stack only)"
            )
    def vector_fn(agg: BrokerAggregates, part_sums: jnp.ndarray) -> jnp.ndarray:
        # PreferredLeaderElectionGoal's kernel cost is violations/n_partitions;
        # the leader total from agg equals the valid-partition count and stays
        # correct under partition-axis sharding (psum'd agg, ccx.parallel).
        inv_np = 1.0 / jnp.maximum(
            jnp.sum(agg.leader_count).astype(jnp.float32), 1.0
        )
        costs = []
        for name in goal_names:
            if name in part_idx:
                c = part_sums[part_idx[name]]
                if name == "PreferredLeaderElectionGoal":
                    c = c * inv_np
            elif name == "KafkaAssignerEvenRackAwareGoal":
                # rack part from the incremental sum; leader-evenness from
                # the live aggregates (same math as the full kernel)
                alive = m.broker_valid & m.broker_alive
                n_alive = jnp.maximum(jnp.sum(alive).astype(jnp.float32), 1.0)
                avg = jnp.sum(agg.leader_count).astype(jnp.float32) / n_alive
                upper = jnp.ceil(avg)
                over = jnp.where(
                    alive, jnp.maximum(agg.leader_count - upper, 0.0), 0.0
                )
                c = part_sums[part_idx["RackAwareGoal"]] + jnp.sum(over) / (
                    jnp.maximum(avg, 1e-9)
                )
            else:
                c = GOAL_REGISTRY[name].fn(m, agg, cfg).cost
            costs.append(c)
        return jnp.stack(costs)

    return vector_fn


def make_cost_fn(m: TensorClusterModel, goal_names: tuple[str, ...], cfg: GoalConfig):
    """Build ``(agg, part_sums) -> (hard_cost, soft_cost)`` for a goal stack.

    Priority semantics follow ccx.goals.stack: hard goals sum into hard_cost,
    soft goals are tier-weighted into soft_cost (SURVEY.md section 7.4).
    """
    hard_mask = tuple(GOAL_REGISTRY[n].hard for n in goal_names)
    weights = soft_weights(hard_mask)
    vector_fn = make_goal_vector_fn(m, goal_names, cfg)

    def cost_fn(agg: BrokerAggregates, part_sums: jnp.ndarray):
        cv = vector_fn(agg, part_sums)
        hmask = jnp.asarray(hard_mask)
        hard = jnp.sum(jnp.where(hmask, cv, 0.0))
        soft = jnp.sum(jnp.where(hmask, 0.0, cv * weights))
        return hard, soft

    return cost_fn


def init_search_state(
    m: TensorClusterModel,
    cfg: GoalConfig,
    goal_names: tuple[str, ...],
    key: jnp.ndarray,
) -> SearchState:
    """Full (non-incremental) evaluation of the starting state."""
    agg = broker_aggregates(m)
    part_sums = pt.partition_sums(
        m, m.assignment, m.leader_slot, m.replica_disk, m.partition_valid
    )
    hard, soft = make_cost_fn(m, goal_names, cfg)(agg, part_sums)
    return SearchState(
        assignment=m.assignment,
        leader_slot=m.leader_slot,
        replica_disk=m.replica_disk,
        agg=agg,
        part_sums=part_sums,
        hard_cost=hard,
        soft_cost=soft,
        key=key,
        n_accepted=jnp.asarray(0, jnp.int32),
    )


def with_placement(m: TensorClusterModel, s: SearchState) -> TensorClusterModel:
    """Rebuild a TensorClusterModel carrying a search state's placement."""
    return m.replace(
        assignment=s.assignment,
        leader_slot=s.leader_slot,
        replica_disk=s.replica_disk,
    )
