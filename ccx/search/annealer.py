"""Batched simulated annealing over candidate placements.

This is the TPU-native replacement for ``GoalOptimizer``'s greedy walk
(SURVEY.md C14, call stack 3.2 hot loop #1): instead of one thread mutating
one ClusterModel via per-goal ``rebalanceForBroker`` loops, K independent
chains each propose ``moves_per_step`` moves per scan step — the reference's
``ActionType`` vocabulary (SURVEY.md C20): INTER_BROKER_REPLICA_MOVEMENT,
LEADERSHIP_MOVEMENT, INTRA_BROKER_REPLICA_MOVEMENT — score the full goal
stack from incrementally-updated aggregates (O(R) per move, ccx.search.state)
and accept on the **full per-goal cost vector**. The whole search is one
``lax.scan`` of a vmapped step: chains are the embarrassingly-parallel batch
axis (the descendant of ``num.proposal.precompute.threads``, SURVEY.md §2.5).

Acceptance semantics mirror the reference's sequential-goal priority exactly
where it matters (``actionAcceptance`` veto, SURVEY.md §7.4):

* a move that raises any *hard* goal's cost is never accepted;
* a strict lexicographic improvement of the cost vector is always accepted —
  including one whose only effect is on the lowest-priority tier, which a
  tier-weighted float32 scalar would be blind to;
* otherwise Metropolis on the tier-weighted soft delta with a geometric
  temperature schedule provides uphill exploration.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ccx.common import costmodel
from ccx.common.resources import Resource
from ccx.goals.base import GOAL_REGISTRY, GoalConfig
from ccx.goals.kernels import scoring_dtype
from ccx.goals.stack import DEFAULT_GOAL_ORDER, StackResult, evaluate_stack, soft_weights
from ccx.model.tensor_model import TensorClusterModel
from ccx.search.state import (
    SearchState,
    apply_move,
    apply_swap,
    broker_pressure,
    bump_kind_counters,
    gather_view,
    init_search_state,
    make_move_scorer,
    make_swap_scorer,
    make_topic_group,
    max_partitions_per_topic,
    stack_needs_topic,
    usage_weights,
    with_placement,
)

# Move kinds (ref ActionType, SURVEY.md C20).
MOVE_REPLICA = 0      # INTER_BROKER_REPLICA_MOVEMENT
MOVE_LEADERSHIP = 1   # LEADERSHIP_MOVEMENT
MOVE_DISK = 2         # INTRA_BROKER_REPLICA_MOVEMENT (JBOD)
MOVE_SWAP = 3         # REPLICA_SWAP (two-partition exchange)


@dataclasses.dataclass(frozen=True)
class AnnealOptions:
    n_chains: int = 64
    n_steps: int = 3000
    #: proposals per chain per scan step — raise so churn scales with
    #: partition count without growing the scan length
    moves_per_step: int = 1
    #: True (default): the ``moves_per_step`` proposals of a step are drawn,
    #: scored against the step's base state and applied as a
    #: pairwise-DISJOINT batch (one stacked gather/scatter per carried
    #: buffer per step — the polish batching lifted into SA, ~K× churn per
    #: unit wall-clock; under partition-axis sharding this also amortizes
    #: the per-move psum into one per step). False: proposals compose
    #: sequentially inside the step (each scores the state left by the
    #: previous one) — the round-2 engine, kept for ablation and as the
    #: reference semantics for equivalence tests. Both modes are
    #: deterministic given the seed, but their chains differ.
    batched: bool = True
    t0: float = 0.3          # initial temperature (soft-cost units)
    t1: float = 1e-4         # final temperature
    p_leadership: float = 0.15
    p_disk: float = 0.0      # raise for JBOD / rebalance_disk stacks
    #: probability the destination broker is drawn headroom-weighted rather
    #: than uniformly (mirrors the greedy's overloaded->underloaded bias,
    #: SURVEY.md section 7.4 "proposal distributions").
    p_biased_dest: float = 0.5
    #: probability of targeting the self-healing evacuation set (replicas on
    #: dead brokers/disks) when it is non-empty.
    p_evac: float = 0.3
    #: probability a proposal is a two-partition REPLICA_SWAP — swaps cross
    #: count-preserving barriers single moves cannot (ref ActionType,
    #: SURVEY.md C20); 0 disables (intra-broker stacks set 0).
    p_swap: float = 0.15
    #: >= 0: the swap share anneals linearly from ``p_swap`` to this value
    #: over the run — swaps matter most once the count tiers have settled
    #: (late in the schedule), so a lean budget can start single-heavy and
    #: finish swap-heavy. < 0 (default): constant ``p_swap``. The ramp
    #: enters the step as traced data (the chunk runner keeps ONE compiled
    #: program across schedules). Config: ``optimizer.swap.p.swap.end``.
    p_swap_end: float = -1.0
    #: share of swap proposals drawn USAGE-COUPLED instead of uniform
    #: (batched step only): both endpoints are Gumbel-selected from a
    #: ``couple_pool``-candidate pool scored by live broker band pressure
    #: (ccx.search.state.broker_pressure) x per-replica usage, so the
    #: (overloaded-broker hot replica, underloaded-broker cool replica)
    #: pairs that fix residual NwOut/leader cells stop being needles in a
    #: uniform haystack. Config: ``optimizer.swap.coupling``.
    swap_coupling: float = 0.5
    #: candidates per coupled endpoint draw (static — program shape)
    couple_pool: int = 4
    #: >0: run the scan in fixed chunks of this many steps with the global
    #: step index passed as data, so ONE compiled program (per chains/moves
    #: shape) serves every n_steps — TPU B5 compiles are minutes apiece and
    #: the effort ladder/retunes stop paying them per rung. 0 (default):
    #: single scan of n_steps (compile keyed on it). Results are bit-exact
    #: either way (same step body, same f32 temperature schedule).
    #: Chunking covers EVERY drive path: single-device, chains-mesh data
    #: parallelism, and the partition-axis-sharded engine in ccx.parallel
    #: (whose chunk program cache is keyed on static config, budgets
    #: traced) — a mesh run keeps bounded compile + per-chunk heartbeats.
    chunk_steps: int = 0
    #: >0 arms the plateau-early-exit mode of the chunked drive (ISSUE
    #: 10, incremental re-optimization): after each chunk the driver
    #: reads the convergence tap's CURRENT row at the chunk boundary and
    #: stops once this many consecutive chunks fail to lex-improve
    #: (ccx.common.convergence tolerances) — a detected-plateau budget
    #: instead of a fixed one. Host-side data only: the window never
    #: enters any traced program, so retunes NEVER recompile (the chunk
    #: runner's static key zeroes it — pinned). Requires chunk_steps > 0
    #: and the telemetry taps armed; 0 (default) is today's fixed-budget
    #: drive, bit-exact.
    plateau_window: int = 0
    #: >1 arms the replica-exchange ladder (ISSUE 16): the chain batch is
    #: partitioned into this many temperature rungs. Rung 0 runs the exact
    #: legacy ``t0→t1`` cooling schedule; rung K-1 holds at ``t0``; the
    #: rungs between cool toward a geometric ladder of end temperatures
    #: between ``t1`` and ``t0`` (each rung scales the decay EXPONENT, so
    #: every rung shares the one compiled chunk program — temperatures are
    #: data, never shape). At chunk boundaries neighboring rungs exchange
    #: chain STATES via the Metropolis criterion on the soft-cost scalar
    #: (``exchange_permutation``) — a pure permutation of the batch axis:
    #: no new shapes, no recompile classes, and the lex-best chain is
    #: pinned to the coldest rung (never exchanged hotter). 1 (default)
    #: traces the literal legacy program — bit-exact. Requires
    #: chunk_steps > 0 (exchange needs chunk boundaries; monolithic runs
    #: log a note and stay flat). Config: ``optimizer.exchange.n.temps``.
    n_temps: int = 1
    #: chunk boundaries between exchange events when the ladder is armed
    #: (1 = every chunk). Traced data — the chunk runner's static key
    #: zeroes it, so interval retunes reuse the compiled program. Config:
    #: ``optimizer.exchange.interval``.
    exchange_interval: int = 1
    #: opt-in bf16 scoring tier (ISSUE 16): the usage-coupled endpoint
    #: scorer (broker band-pressure tables x per-replica usage inside the
    #: batched step) ranks its Gumbel pools in bfloat16 — rank-order-only
    #: intermediates; the lex cost vector and every accept/exchange
    #: decision stay f32. Pure-throughput knob for the MXU; False
    #: (default) keeps CPU correctness paths bit-exact. Config:
    #: ``optimizer.bf16.scoring``.
    bf16_scoring: bool = False
    seed: int = 0


@dataclasses.dataclass
class AnnealResult:
    model: TensorClusterModel
    stack_before: StackResult
    stack_after: StackResult
    n_accepted: int
    n_chains: int
    n_steps: int
    best_chain: int
    #: best chain's per-move-kind (single, replica-swap, leadership-swap)
    #: proposal/acceptance counts — observability (state.MOVE_KIND_NAMES)
    n_prop_kind: tuple[int, ...] = (0, 0, 0)
    n_acc_kind: tuple[int, ...] = (0, 0, 0)
    #: decoded convergence-telemetry segment (ccx.search.telemetry): the
    #: per-chunk lex-best cost vector / move counters / temperature series
    #: the chunk carry recorded. None on the monolithic (unchunked) path
    #: or with taps off (observability.convergence=false).
    convergence: dict | None = None
    #: plateau-exit report (ISSUE 10): ``{"exited", "chunksRun",
    #: "chunksBudget", "window"}`` when the plateau-early-exit mode was
    #: armed (AnnealOptions.plateau_window > 0), else None.
    plateau: dict | None = None

    @property
    def improved(self) -> bool:
        before = float(self.stack_before.hard_cost), float(self.stack_before.soft_scalar)
        after = float(self.stack_after.hard_cost), float(self.stack_after.soft_scalar)
        return after <= before


@dataclasses.dataclass(frozen=True)
class ProposalParams:
    """Static knobs for move proposal (shared by annealer + greedy)."""

    p_real: int
    b_real: int
    p_leadership: float = 0.15
    p_disk: float = 0.0
    p_biased_dest: float = 0.5
    #: probability of drawing the partition from the hot list (replicas on
    #: dead brokers/disks — the self-healing set, SURVEY.md section 5.3 —
    #: plus rack-uniqueness offenders when the stack has a rack goal).
    #: Only applied when the list is non-empty.
    p_evac: float = 0.3
    #: also target duplicate-rack replica slots on hot draws (set when the
    #: goal stack contains a rack goal; must stay False for intra-broker
    #: disk-only stacks, whose moves may not change brokers).
    target_rack: bool = False
    #: False for intra-broker-only stacks: hot draws never force an
    #: inter-broker evacuation move.
    allow_inter: bool = True
    #: REPLICA_SWAP share of proposals (0 disables the swap branch).
    p_swap: float = 0.15
    #: True when the stack scores capacity goals: hot draws then target
    #: replicas on over-effective-capacity brokers and biased destinations
    #: avoid them. Effective capacity = broker_capacity * per-resource
    #: threshold (ref *.capacity.threshold; kernels._capacity_goal).
    target_capacity: bool = True
    #: per-resource capacity thresholds from GoalConfig (static)
    cap_thresholds: tuple[float, float, float, float] = (1.0, 1.0, 1.0, 1.0)
    #: share of swap proposals taken by the LEADERSHIP-swap variant (vs the
    #: replica swap). Scaled from the configured leadership share by
    #: ``lead_swap_share`` so a stack with a tiny p_leadership doesn't spend
    #: half its swap budget on leadership rotations.
    p_lead_swap: float = 0.5
    #: share of swap proposals drawn usage-coupled (AnnealOptions
    #: .swap_coupling; batched step only — the sequential step keeps the
    #: uniform draw as the ablation reference). 0 disables the pool pass.
    p_couple: float = 0.0
    #: static pool size per coupled endpoint draw
    couple_pool: int = 4
    #: bf16 scoring tier (AnnealOptions.bf16_scoring): coupled-endpoint
    #: pool scores rank in bfloat16; acceptance math stays f32.
    bf16: bool = False


def lead_swap_share(p_leadership: float) -> float:
    """Leadership-swap share of swap proposals, following the configured
    leadership share: 0.5 at the default p_leadership=0.15 (measured-good
    mix for the PLE/leader-distribution tiers), proportionally less below
    it, 0 when leadership moves are disabled."""
    if p_leadership <= 0:
        return 0.0
    return 0.5 * min(p_leadership / 0.15, 1.0)


RACK_TARGET_GOALS = frozenset(
    {"RackAwareGoal", "RackAwareDistributionGoal", "KafkaAssignerEvenRackAwareGoal"}
)

CAPACITY_GOALS = frozenset(
    {"CpuCapacityGoal", "NetworkInboundCapacityGoal",
     "NetworkOutboundCapacityGoal", "DiskCapacityGoal"}
)

#: Goals whose stacks move replicas only *within* a broker (rebalance_disk);
#: such searches must never propose inter-broker moves, including dead-broker
#: evacuation (SURVEY.md C18).
INTRA_ONLY_GOALS = frozenset(
    {"IntraBrokerDiskCapacityGoal", "IntraBrokerDiskUsageDistributionGoal"}
)


def allows_inter_broker(goal_names: tuple[str, ...]) -> bool:
    return not set(goal_names) <= INTRA_ONLY_GOALS


def _evac_bucket(P: int) -> int:
    """Static offender-count bucket for a model with padded partition
    count P — ONE sizing rule shared by the SA hot-list operand (here) and
    the repair sweeps' per-sweep offender bound (repair._repair_nk), so a
    retune moves both together.

    Hot-list lengths vary snapshot to snapshot, and every program taking
    the list as an operand (the chunk runner, the greedy loop) is compiled
    per operand SHAPE — the old next-pow2 bucketing silently recompiled
    the multi-minute B5 programs whenever the offender count crossed a
    bucket. One fixed size pins the program; it must also stay SMALL: the
    operand rides through every while_loop iteration, and a full-P pad
    measured +2 s/500-step B5 SA chunk and +7 ms/greedy-polish iteration
    on CPU vs a 4k pad (+5 s on the lean rung's 700-iter re-polish).
    P//16 (>=1024) covers post-repair offender counts with an order of
    magnitude to spare (B5: ~2k structural offenders vs 8192); the host
    path escapes to a second P-sized program for pathological snapshots,
    so there are at most TWO programs per model shape, both stable."""
    return min(P, max(1024, P // 16))


def _pad_fixed(idx: np.ndarray, size: int) -> tuple[np.ndarray, int]:
    """Pad an offender-index list to a fixed size (see _evac_bucket). The
    pad region is never read (draws index strictly below n_evac) and the
    array is shared, not per-chain."""
    n = len(idx)
    out = np.zeros(max(size, 1), np.int32)
    out[:n] = idx
    return out, n


def hot_partition_list(
    m: TensorClusterModel,
    goal_names: tuple[str, ...] = (),
    cfg: GoalConfig | None = None,
) -> tuple[np.ndarray, int]:
    """Partitions violating *targetable* hard constraints: structural
    (dead broker/disk, the self-healing set) plus — when the stack contains a
    rack goal — rack-uniqueness offenders. Search draws from this list with
    probability ``p_evac`` so the few offenders in a huge cluster are hit
    often enough to be repaired (SURVEY.md section 7.4 "proposal
    distributions"). Intra-broker-only stacks exclude dead-*broker*
    partitions (unfixable without inter-broker moves)."""
    hot: set[int] = set()
    a = np.asarray(m.assignment)
    pvalid = np.asarray(m.partition_valid)
    valid = (a >= 0) & pvalid[:, None]
    if allows_inter_broker(goal_names):
        on_dead = (
            valid
            & ~np.asarray(m.broker_alive & m.broker_valid)[np.clip(a, 0, m.B - 1)]
        )
        hot.update(np.unique(np.nonzero(on_dead)[0]).tolist())
    rd = np.asarray(m.replica_disk)
    dead_disk = (
        valid
        & (rd >= 0)
        & ~np.asarray(m.disk_alive)[np.clip(a, 0, m.B - 1), np.clip(rd, 0, m.D - 1)]
    )
    hot.update(np.unique(np.nonzero(dead_disk)[0]).tolist())

    if RACK_TARGET_GOALS & set(goal_names):
        racks = np.asarray(m.broker_rack)[np.clip(a, 0, m.B - 1)]
        racks = np.where(valid, racks, -1 - np.arange(m.R)[None, :])
        dup = (racks[:, :, None] == racks[:, None, :]) & (
            np.arange(m.R)[:, None] < np.arange(m.R)[None, :]
        )
        hot.update(np.unique(np.nonzero(dup.any(axis=(1, 2)) & pvalid)[0]).tolist())

    if (
        not hot
        and allows_inter_broker(goal_names)
        and CAPACITY_GOALS & set(goal_names)
    ):
        # capacity offenders: partitions with a replica on a broker above
        # EFFECTIVE capacity (capacity * threshold, where the hard
        # CapacityGoal hinge starts). Only added when NO structural offender
        # (dead broker/disk, rack duplicate) exists — the targeted draws for
        # those must not be diluted by (far more numerous) hot-broker
        # partitions.
        from ccx.model.aggregates import broker_aggregates_jit

        thr = np.asarray((cfg or GoalConfig()).capacity_threshold)
        agg = broker_aggregates_jit(m)
        cap = np.asarray(m.broker_capacity) * thr[:, None]
        load = np.asarray(agg.broker_load)
        util = np.max(
            np.where(cap > 0, load / np.where(cap > 0, cap, 1.0), 0.0), axis=0
        )
        over_b = np.asarray(m.broker_alive & m.broker_valid) & (util > 1.0)
        if over_b.any():
            on_over = valid & over_b[np.clip(a, 0, m.B - 1)]
            hot.update(np.unique(np.nonzero(on_over)[0]).tolist())
    idx = np.asarray(sorted(hot), np.int32)
    bucket = _evac_bucket(m.P)
    return _pad_fixed(idx, bucket if len(idx) <= bucket else m.P)


@costmodel.instrument("hot-list")
@functools.partial(jax.jit, static_argnames=("goal_names", "cfg"))
def hot_partition_list_device(
    m: TensorClusterModel,
    *,
    goal_names: tuple[str, ...],
    cfg: GoalConfig,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """`hot_partition_list` as one jitted program over the model's DEVICE
    arrays: (evac int32[_evac_bucket(P)] — sorted offender ids, 0-padded;
    n_evac scalar).

    The host version materializes the placement to numpy, which blocks the
    caller on everything queued ahead of it. The optimizer's pipelined
    device-repair path (`OptimizeOptions.repair_backend="device"`) instead
    derives the list from the repaired arrays on device, so the chain
    repair -> hot list -> chain init -> SA chunks is dispatched without a
    single host sync. Same selection rules as the host version: structural
    offenders (dead broker/disk, rack duplicates when the stack has a rack
    goal), else capacity offenders for capacity-scoring stacks."""
    P, B, D, R = m.P, m.B, m.D, m.R
    a = m.assignment
    pvalid = m.partition_valid
    valid = (a >= 0) & pvalid[:, None]
    safe_b = jnp.clip(a, 0, B - 1)
    hot = jnp.zeros(P, bool)
    allow_inter = allows_inter_broker(goal_names)
    if allow_inter:
        on_dead = valid & ~(m.broker_alive & m.broker_valid)[safe_b]
        hot = hot | jnp.any(on_dead, axis=1)
    rd = m.replica_disk
    dead_disk = (
        valid & (rd >= 0) & ~m.disk_alive[safe_b, jnp.clip(rd, 0, D - 1)]
    )
    hot = hot | jnp.any(dead_disk, axis=1)
    if RACK_TARGET_GOALS & set(goal_names):
        racks = jnp.where(
            valid, m.broker_rack[safe_b], -1 - jnp.arange(R, dtype=jnp.int32)
        )
        dup = (racks[:, :, None] == racks[:, None, :]) & (
            jnp.arange(R)[:, None] < jnp.arange(R)[None, :]
        )
        hot = hot | (jnp.any(dup, axis=(1, 2)) & pvalid)
    if allow_inter and CAPACITY_GOALS & set(goal_names):
        # capacity offenders only when NO structural offender exists —
        # same dilution rule as the host version
        from ccx.model.aggregates import broker_aggregates

        thr = jnp.asarray(cfg.capacity_threshold, jnp.float32)
        agg = broker_aggregates(m)
        cap = m.broker_capacity * thr[:, None]
        util = jnp.max(
            jnp.where(cap > 0, agg.broker_load / jnp.where(cap > 0, cap, 1.0), 0.0),
            axis=0,
        )
        over_b = (m.broker_alive & m.broker_valid) & (util > 1.0)
        hot_cap = jnp.any(valid & over_b[safe_b], axis=1)
        hot = jnp.where(jnp.any(hot), hot, hot_cap)
    # static bucket size (see _evac_bucket): the device program cannot
    # data-dependently escape to a P-sized pad like the host path, so a
    # pathological overflow truncates to the lowest `bucket` offender ids —
    # that only biases which hot partitions SA prioritizes for a few
    # sweeps, never feasibility (acceptance + repair still guard them)
    bucket = _evac_bucket(P)
    idx = jnp.nonzero(hot, size=bucket, fill_value=0)[0].astype(jnp.int32)
    n = jnp.minimum(jnp.sum(hot), bucket).astype(jnp.int32)
    return idx, n


def _draw_partition(
    k_p: jnp.ndarray,
    k_ev: jnp.ndarray,
    k_evi: jnp.ndarray,
    pp: ProposalParams,
    evac: jnp.ndarray | None,
    n_evac: jnp.ndarray | None,
):
    """Index-only partition draw (uniform, or from the hot list with
    probability p_evac) — no view needed yet."""
    p = jax.random.randint(k_p, (), 0, pp.p_real)
    use_evac = jnp.asarray(False)
    if evac is not None and n_evac is not None:
        use_evac = (jax.random.uniform(k_ev) < pp.p_evac) & (n_evac > 0)
        ei = jax.random.randint(k_evi, (), 0, jnp.maximum(n_evac, 1))
        p = jnp.where(use_evac, evac[ei], p)
    return p, use_evac


def propose_move(
    key: jnp.ndarray,
    state: SearchState,
    m: TensorClusterModel,
    pp: ProposalParams,
    evac: jnp.ndarray | None = None,
    n_evac: jnp.ndarray | None = None,
    gather=None,
):
    """Draw one candidate move: returns (p, view, old rows, new rows,
    feasible). Index draw + local view gather + ``_single_plan``."""
    k_plan, k_p, k_ev, k_evi = jax.random.split(key, 4)
    p, use_evac = _draw_partition(k_p, k_ev, k_evi, pp, evac, n_evac)
    view = (gather or gather_view)(state, m, p)
    old, new, feasible = _single_plan(k_plan, state, m, pp, view, use_evac)
    return p, view, old, new, feasible


def _single_plan(
    key: jnp.ndarray,
    state: SearchState,
    m: TensorClusterModel,
    pp: ProposalParams,
    view,
    use_evac: jnp.ndarray,
):
    """Build one candidate move from a gathered view: returns
    (old rows, new rows, feasible).

    Feasibility masking mirrors the reference's per-goal requirements checks
    (never *create* structural violations): destination must be alive, valid,
    not replica-excluded, not already hosting the partition; leadership may
    only land on alive, non-leadership-excluded brokers; excluded
    (immovable) partitions are untouchable (OptimizationOptions,
    SURVEY.md C20)."""
    R, B, D = m.R, m.B, m.D
    k_kind, k_r, k_dst, k_dstu, k_disk, k_bias, k_pref = jax.random.split(key, 7)

    kind = jax.random.choice(
        k_kind,
        jnp.asarray([MOVE_REPLICA, MOVE_LEADERSHIP, MOVE_DISK]),
        p=jnp.asarray(
            [1.0 - pp.p_leadership - pp.p_disk, pp.p_leadership, pp.p_disk]
        ),
    )
    r = jax.random.randint(k_r, (), 0, R)
    # Half of leadership transfers target the PREFERRED slot (slot 0) — the
    # move PreferredLeaderElectionGoal wants (ref
    # goals/PreferredLeaderElectionGoal.java semantics) is rare under a
    # uniform slot draw.
    prefer = jax.random.uniform(k_pref) < 0.5
    r = jnp.where((kind == MOVE_LEADERSHIP) & prefer, 0, r).astype(jnp.int32)
    old_assign = view.assign                  # [R]
    old_leader = view.leader
    old_disk = view.disk                      # [R]

    # On a hot-list draw, target the offending slot. A replica on a dead
    # *broker* can only be healed by relocation; a replica on a dead *disk*
    # of a live broker is healed by an intra-broker disk move (keeps the
    # rebalance_disk contract intra-broker-only when p_disk=1); a replica
    # sharing its rack with an earlier slot is healed by relocation to an
    # unused rack (pp.target_rack).
    ok_b = m.broker_alive & m.broker_valid
    safe_row = jnp.clip(old_assign, 0, B - 1)
    safe_dk = jnp.clip(old_disk, 0, D - 1)
    slot_ok = old_assign >= 0
    thr = jnp.asarray(pp.cap_thresholds, jnp.float32)
    cap_eff = m.broker_capacity * thr[:, None]
    # a resource with capacity 0 is UNCONSTRAINED (capacity unset), not
    # infinitely over — contribute 0 utilization for it
    util_b = jnp.max(
        jnp.where(
            cap_eff > 0,
            state.agg.broker_load / jnp.where(cap_eff > 0, cap_eff, 1.0),
            0.0,
        ),
        axis=0,
    )
    if pp.allow_inter:
        dead_broker_slot = slot_ok & ~ok_b[safe_row]
        # hot draws also target replicas on brokers above EFFECTIVE capacity
        # (capacity * threshold — where the hard CapacityGoal hinge starts,
        # kernels._capacity_goal) — healed by relocation
        over_slot = (
            slot_ok & ok_b[safe_row] & (util_b[safe_row] > 1.0)
            if pp.target_capacity
            else jnp.zeros_like(slot_ok)
        )
    else:
        dead_broker_slot = jnp.zeros_like(slot_ok)
        over_slot = jnp.zeros_like(slot_ok)
    dead_disk_slot = (
        slot_ok
        & ok_b[safe_row]
        & (old_disk >= 0)
        & ~m.disk_alive[safe_row, safe_dk]
    )
    row_racks = jnp.where(
        slot_ok, m.broker_rack[safe_row], -1 - jnp.arange(R, dtype=jnp.int32)
    )
    if pp.target_rack:
        rack_dup_slot = slot_ok & jnp.any(
            (row_racks[None, :] == row_racks[:, None])
            & (jnp.arange(R)[None, :] < jnp.arange(R)[:, None]),
            axis=1,
        )
    else:
        rack_dup_slot = jnp.zeros_like(slot_ok)
    # prioritized like the repair sweep: a dead-broker replica outranks a
    # dead disk outranks a rack duplicate outranks a capacity overload —
    # otherwise a cluster where most brokers run hot would drown out the
    # rare structural offenders hot draws exist for
    bad_score = (
        3.0 * dead_broker_slot
        + 2.5 * dead_disk_slot
        + 1.0 * rack_dup_slot
        + 0.5 * over_slot
    )
    has_bad = jnp.max(bad_score) > 0.0
    bad_r = jnp.argmax(bad_score)
    r = jnp.where(use_evac & has_bad, bad_r, r).astype(jnp.int32)
    evac_kind = jnp.where(dead_disk_slot[bad_r], MOVE_DISK, MOVE_REPLICA)
    kind = jnp.where(use_evac & has_bad, evac_kind, kind)
    repair_rack = use_evac & has_bad & rack_dup_slot[bad_r] & ~dead_disk_slot[bad_r]

    src = old_assign[r]
    slot_valid = src >= 0
    movable = view.pvalid & ~view.immovable

    # --- destination broker: headroom-weighted or uniform ------------------
    alive_ok = m.broker_valid & m.broker_alive & ~m.broker_excl_replicas
    headroom = 1.0 - util_b                                     # [B]
    if pp.target_capacity:
        w = jnp.where(
            alive_ok & (util_b <= 1.0), jnp.maximum(headroom, 0.0) + 0.05, 0.0
        )
        # every alive broker over capacity (e.g. after broker failures):
        # fall back to least-loaded so evacuations still have a destination
        w_fb = jnp.where(alive_ok, 1.0 / jnp.maximum(util_b, 1e-9), 0.0)
        w = jnp.where(jnp.any(w > 0), w, w_fb)
    else:
        w = jnp.where(alive_ok, jnp.maximum(headroom, 0.0) + 0.05, 0.0)
    g = -jnp.log(-jnp.log(jax.random.uniform(k_dst, (B,), minval=1e-12, maxval=1.0)))
    dst_biased = jnp.argmax(jnp.where(w > 0, jnp.log(w) + g, -jnp.inf))
    dst_uniform = jax.random.randint(k_dstu, (), 0, pp.b_real)
    use_bias = jax.random.uniform(k_bias) < pp.p_biased_dest
    dst = jnp.where(use_bias, dst_biased, dst_uniform).astype(jnp.int32)
    if pp.target_rack:
        # Rack-repair draws relocate onto a rack the partition doesn't use
        # (when one with headroom exists — otherwise fall through).
        rack_used = jnp.any(
            m.broker_rack[None, :] == jnp.where(slot_ok, row_racks, -1)[:, None],
            axis=0,
        )  # [B]
        w_rack = jnp.where(rack_used, 0.0, w)
        any_free = jnp.any(w_rack > 0)
        dst_rack = jnp.argmax(jnp.where(w_rack > 0, jnp.log(w_rack) + g, -jnp.inf))
        dst = jnp.where(repair_rack & any_free, dst_rack, dst).astype(jnp.int32)

    # --- feasibility masks (never *create* hard structural violations) -----
    dst_ok = alive_ok[dst] & (dst != src)
    no_dup = ~jnp.any(old_assign == dst)
    is_leader_slot = r == old_leader
    dst_lead_ok = ~(is_leader_slot & m.broker_excl_leadership[dst])
    move_ok = (
        (kind == MOVE_REPLICA) & slot_valid & movable & dst_ok & no_dup & dst_lead_ok
    )

    # destination disk on dst: random among its alive disks
    gd = -jnp.log(
        -jnp.log(jax.random.uniform(k_disk, (D,), minval=1e-12, maxval=1.0))
    )
    dst_disk = jnp.argmax(jnp.where(m.disk_alive[dst], gd, -jnp.inf)).astype(jnp.int32)

    # --- leadership transfer ----------------------------------------------
    tgt_b = jnp.clip(old_assign[r], 0, B - 1)
    lead_ok = (
        (kind == MOVE_LEADERSHIP)
        & slot_valid
        & movable
        & (r != old_leader)
        & (m.broker_valid & m.broker_alive & ~m.broker_excl_leadership)[tgt_b]
    )

    # --- intra-broker disk move -------------------------------------------
    src_b = jnp.clip(src, 0, B - 1)
    disk_new = jnp.argmax(jnp.where(m.disk_alive[src_b], gd, -jnp.inf)).astype(
        jnp.int32
    )
    disk_ok = (
        (kind == MOVE_DISK)
        & slot_valid
        & movable
        & (disk_new != old_disk[r])
        & (D > 1)
    )

    feasible = move_ok | lead_ok | disk_ok

    # --- build candidate rows ---------------------------------------------
    new_assign = jnp.where(move_ok, old_assign.at[r].set(dst), old_assign)
    new_leader = jnp.where(lead_ok, r, old_leader).astype(jnp.int32)
    new_disk = jnp.where(
        move_ok,
        old_disk.at[r].set(jnp.where(D > 1, dst_disk, 0)),
        jnp.where(disk_ok, old_disk.at[r].set(disk_new), old_disk),
    )
    return (
        (old_assign, old_leader, old_disk),
        (new_assign, new_leader, new_disk),
        feasible,
    )


def propose_swap(
    key: jnp.ndarray,
    state: SearchState,
    m: TensorClusterModel,
    pp: ProposalParams,
    gather=None,
):
    """Draw one candidate REPLICA_SWAP (ref ActionType, SURVEY.md C20): two
    random replicas exchange brokers. Swaps preserve every broker's replica
    count, so they reach load-balance states that single relocations cannot
    without transiently violating the count-distribution band.

    Returns (p1, view1, old1, new1, p2, view2, old2, new2, feasible,
    is_lead)."""
    k_p1, k_p2, k_plan = jax.random.split(key, 3)
    p1 = jax.random.randint(k_p1, (), 0, pp.p_real)
    p2 = jax.random.randint(k_p2, (), 0, pp.p_real)
    g = gather or gather_view
    view1 = g(state, m, p1)
    view2 = g(state, m, p2)
    old1, new1, old2, new2, ok, is_lead = _swap_plan(
        k_plan, m, pp, p1, view1, p2, view2
    )
    return p1, view1, old1, new1, p2, view2, old2, new2, ok, is_lead


def _swap_plan(
    key: jnp.ndarray,
    m: TensorClusterModel,
    pp: ProposalParams,
    p1: jnp.ndarray,
    view1,
    p2: jnp.ndarray,
    view2,
    use_lead: jnp.ndarray | None = None,
    couple=None,
):
    """Build a swap candidate from two gathered views: returns
    (old1, new1, old2, new2, feasible, is_lead).

    Two variants share the draw: a REPLICA swap (exchange brokers between
    two replicas — preserves every broker's replica count) and a LEADERSHIP
    swap (rotate leadership p1->broker(leader2), p2->broker(leader1) —
    preserves every broker's LEADER count). The leadership swap is how
    preferred-leader / leader-bytes improvements cross the
    LeaderReplicaDistribution tier, which vetoes any single transfer that
    unbalances leader counts (the reference reaches these states through
    PreferredLeaderElectionGoal's count-neutral passes).

    ``use_lead`` (traced bool) pre-decides the variant when the caller drew
    it earlier (the coupled batched step scores its candidate pools
    per-variant); None keeps the internal ``p_lead_swap`` draw.
    ``couple = (use_couple, r1_c, r2_c)`` overrides the uniform slot draw
    with the coupling pass's hot/cool slots for coupled replica swaps."""
    R, B, D = m.R, m.B, m.D
    k_r1, k_r2, k_d1, k_d2, k_kind = jax.random.split(key, 5)
    r1 = jax.random.randint(k_r1, (), 0, R)
    r2 = jax.random.randint(k_r2, (), 0, R)
    if couple is not None:
        use_couple, r1_c, r2_c = couple
        r1 = jnp.where(use_couple, r1_c, r1).astype(jnp.int32)
        r2 = jnp.where(use_couple, r2_c, r2).astype(jnp.int32)
    x = view1.assign[r1]
    y = view2.assign[r2]
    sx = jnp.clip(x, 0, B - 1)
    sy = jnp.clip(y, 0, B - 1)
    recv_ok = m.broker_valid & m.broker_alive & ~m.broker_excl_replicas
    lead1 = r1 == view1.leader
    lead2 = r2 == view2.leader

    ok = (
        (p1 != p2)
        & view1.pvalid
        & view2.pvalid
        & ~view1.immovable
        & ~view2.immovable
        & (x >= 0)
        & (y >= 0)
        & (x != y)
        & recv_ok[sx]
        & recv_ok[sy]
        & ~jnp.any(view1.assign == y)
        & ~jnp.any(view2.assign == x)
        & ~(lead1 & m.broker_excl_leadership[sy])
        & ~(lead2 & m.broker_excl_leadership[sx])
    )

    gd1 = -jnp.log(-jnp.log(jax.random.uniform(k_d1, (D,), minval=1e-12, maxval=1.0)))
    gd2 = -jnp.log(-jnp.log(jax.random.uniform(k_d2, (D,), minval=1e-12, maxval=1.0)))
    d1 = jnp.argmax(jnp.where(m.disk_alive[sy], gd1, -jnp.inf)).astype(jnp.int32)
    d2 = jnp.argmax(jnp.where(m.disk_alive[sx], gd2, -jnp.inf)).astype(jnp.int32)

    old1 = (view1.assign, view1.leader, view1.disk)
    old2 = (view2.assign, view2.leader, view2.disk)
    new1 = (
        view1.assign.at[r1].set(y),
        view1.leader,
        view1.disk.at[r1].set(jnp.where(D > 1, d1, 0)),
    )
    new2 = (
        view2.assign.at[r2].set(x),
        view2.leader,
        view2.disk.at[r2].set(jnp.where(D > 1, d2, 0)),
    )

    # --- leadership-swap variant ------------------------------------------
    lb1 = jnp.clip(view1.assign[jnp.clip(view1.leader, 0, R - 1)], 0, B - 1)
    lb2 = jnp.clip(view2.assign[jnp.clip(view2.leader, 0, R - 1)], 0, B - 1)
    # p1's leadership lands on lb2 (needs a replica there), p2's on lb1
    on_lb2 = view1.assign == lb2
    on_lb1 = view2.assign == lb1
    r1l = jnp.argmax(on_lb2).astype(jnp.int32)
    r2l = jnp.argmax(on_lb1).astype(jnp.int32)
    lead_allowed = (
        m.broker_valid & m.broker_alive & ~m.broker_excl_leadership
    )
    ok_lead = (
        (p1 != p2)
        & view1.pvalid
        & view2.pvalid
        & ~view1.immovable
        & ~view2.immovable
        & (lb1 != lb2)
        & jnp.any(on_lb2)
        & jnp.any(on_lb1)
        & lead_allowed[lb1]
        & lead_allowed[lb2]
    )
    if use_lead is None:
        lead_possible = pp.p_lead_swap > 0
        use_lead = (
            (jax.random.uniform(k_kind) < pp.p_lead_swap)
            if lead_possible
            else jnp.asarray(False)
        )
    else:
        lead_possible = True
        use_lead = jnp.asarray(use_lead)
    if lead_possible:
        def sel_rows(a, b):
            return jnp.where(use_lead, a, b)

        new1 = (
            sel_rows(view1.assign, new1[0]),
            jnp.where(use_lead, r1l, new1[1]).astype(jnp.int32),
            sel_rows(view1.disk, new1[2]),
        )
        new2 = (
            sel_rows(view2.assign, new2[0]),
            jnp.where(use_lead, r2l, new2[1]).astype(jnp.int32),
            sel_rows(view2.disk, new2[2]),
        )
        ok = jnp.where(use_lead, ok_lead, ok)
    return old1, new1, old2, new2, ok, jnp.asarray(use_lead)


def goal_tols(cost_vec: jnp.ndarray) -> jnp.ndarray:
    """Per-goal significance tolerance for vector comparisons. Partition and
    topic sums are exact integers (tolerance only guards true float goals
    like capacity hinges); relative term keeps incremental drift on large
    costs from reading as a change."""
    return 1e-6 + 1e-6 * jnp.abs(cost_vec)


def lex_accept(
    cur_vec: jnp.ndarray,
    new_vec: jnp.ndarray,
    hard_arr: jnp.ndarray,    # bool[G]
    weights: jnp.ndarray,     # f32[G] tier weights (soft goals)
    temperature: jnp.ndarray,
    key: jnp.ndarray,
) -> jnp.ndarray:
    """Vector-lexicographic SA acceptance (see module docstring)."""
    d = new_vec - cur_vec
    tol = goal_tols(cur_vec)
    sig = jnp.abs(d) > tol
    any_sig = jnp.any(sig)
    first = jnp.argmax(sig)
    lex_lt = any_sig & (d[first] < 0)
    hard_up = jnp.any(sig & hard_arr & (d > 0))
    soft_d = jnp.sum(jnp.where(hard_arr, 0.0, d * weights))
    u = jax.random.uniform(key, minval=1e-12, maxval=1.0)
    metropolis = jnp.log(u) < (-soft_d / jnp.maximum(temperature, 1e-30))
    return ~hard_up & (lex_lt | ~any_sig | metropolis)


def _anneal_step(
    state: SearchState,
    temperature: jnp.ndarray,
    step_idx: jnp.ndarray,
    evac: jnp.ndarray,
    n_evac: jnp.ndarray,
    *,
    m: TensorClusterModel,
    pp: ProposalParams,
    hard_arr: jnp.ndarray,
    weights: jnp.ndarray,
    moves_per_step: int,
    scorer,
    swap_scorer,
    gather=None,
    locate=None,
    group=None,
    swap_ramp=0.0,
    swap_schedule_on: bool = False,
    cfg=None,
) -> SearchState:
    """``moves_per_step`` sequential proposals on one chain (vmapped over
    chains by the caller). Sequential composition inside the step is exact:
    each proposal scores against the state left by the previous one.

    ``swap_ramp`` (traced scalar, per-step delta of the swap share) makes
    the swap probability ``pp.p_swap + swap_ramp * step`` — the p_swap
    schedule enters as DATA so the chunk runner's one-program contract
    survives schedule retunes. ``cfg`` is accepted for signature parity
    with the batched step (the sequential path keeps uniform draws).

    Every proposal — single move or REPLICA_SWAP — flows through ONE
    two-partition code path (a single move is a degenerate swap whose second
    partition is inert). A ``lax.cond`` between a single-move branch and a
    swap branch doubles the number of uses of every loop-carried buffer,
    which defeats XLA's in-place scatter analysis and copies the whole
    search state per move (measured 95 ms/move at B5 scale on CPU vs
    ~2 ms condless). The unified path keeps exactly one stacked gather and
    one stacked scatter per carried buffer per proposal.

    ``gather``/``locate`` are the partition-axis-sharding hooks
    (ccx.parallel): ``gather(state, ps)`` produces the stacked PartitionView
    (owner gather + psum), ``locate(p) -> (local_index, owned)`` maps a
    global partition id onto this shard's slice."""
    from ccx.search.state import gather_views, view_at

    def inner_single_only(i, ss: SearchState) -> SearchState:
        # Static fast path for p_swap == 0 stacks (leadership-only demote,
        # disk-only rebalance): no second-partition gather/scatter at all,
        # and rejected moves stay bit-exact no-ops.
        key = jax.random.fold_in(ss.key, step_idx * moves_per_step + i)
        k_p, k_ev, k_evi, k_single, k_acc = jax.random.split(key, 5)
        p, use_evac = _draw_partition(k_p, k_ev, k_evi, pp, evac, n_evac)
        views = (gather or gather_views)(ss, m, jnp.stack([p]))
        view = view_at(views, 0)
        old, new, feasible = _single_plan(k_single, ss, m, pp, view, use_evac)
        delta = scorer(ss, view, old, new)
        accept = feasible & lex_accept(
            ss.cost_vec, delta.cost_vec, hard_arr, weights, temperature, k_acc
        )
        p_idx, owned = locate(p) if locate is not None else (p, True)
        ss = apply_move(
            ss, m, p_idx, view, old, new, delta, accept, owned,
            group=group, global_p=p,
        )
        return bump_kind_counters(ss, 0, 1, accept.astype(jnp.int32))

    def inner(i, ss: SearchState) -> SearchState:
        key = jax.random.fold_in(ss.key, step_idx * moves_per_step + i)
        k_sel, k_p, k_ev, k_evi, k_p1, k_p2, k_single, k_swap, k_acc = (
            jax.random.split(key, 9)
        )
        use_swap = jax.random.uniform(k_sel) < (
            pp.p_swap + swap_ramp * step_idx
        )

        p_single, use_evac = _draw_partition(k_p, k_ev, k_evi, pp, evac, n_evac)
        p1_sw = jax.random.randint(k_p1, (), 0, pp.p_real)
        p2_sw = jax.random.randint(k_p2, (), 0, pp.p_real)
        pa = jnp.where(use_swap, p1_sw, p_single)
        pb = p2_sw

        views = (gather or gather_views)(ss, m, jnp.stack([pa, pb]))
        va, vb = view_at(views, 0), view_at(views, 1)

        old_s, new_s, feas_s = _single_plan(
            k_single, ss, m, pp, va, use_evac & ~use_swap
        )
        o1w, n1w, o2w, n2w, ok_w, is_lead = _swap_plan(
            k_swap, m, pp, pa, va, pb, vb
        )

        def pick(a, b):
            return jnp.where(use_swap, a, b)

        def inert(rows):
            # single moves blank partition b's rows to -1: its scatter
            # contributions then carry weight 0 exactly (valid mask False),
            # keeping the inert partition a bit-exact no-op instead of a
            # float (a - x) + x round trip
            return tuple(jnp.where(use_swap, r, -1) for r in rows)

        olda = (va.assign, va.leader, va.disk)
        newa = (pick(n1w[0], new_s[0]), pick(n1w[1], new_s[1]),
                pick(n1w[2], new_s[2]))
        oldb = inert((vb.assign, vb.leader, vb.disk))
        newb = inert((n2w[0], n2w[1], n2w[2]))
        feasible = jnp.where(use_swap, ok_w, feas_s)

        delta = swap_scorer(ss, va, olda, newa, vb, oldb, newb)
        accept = feasible & lex_accept(
            ss.cost_vec, delta.cost_vec, hard_arr, weights, temperature, k_acc
        )
        if locate is not None:
            ia, owna = locate(pa)
            ib, ownb = locate(pb)
        else:
            ia, owna, ib, ownb = pa, True, pb, True
        ss = apply_swap(
            ss, m, ia, va, olda, newa, ib, vb, oldb, newb, delta, accept,
            owna, ownb, group=group, global_p1=pa, global_p2=pb,
            active2=use_swap,
        )
        kind = jnp.where(
            use_swap, jnp.where(is_lead, 2, 1), 0
        ).astype(jnp.int32)
        return bump_kind_counters(ss, kind, 1, accept.astype(jnp.int32))

    # the branch is program SHAPE: a traced ramp cannot flip it, so the
    # builder passes the static schedule flag alongside the traced ramp
    body = inner if (pp.p_swap > 0.0 or swap_schedule_on) else inner_single_only
    return jax.lax.fori_loop(0, moves_per_step, body, state)


def _anneal_step_batched(
    state: SearchState,
    temperature: jnp.ndarray,
    step_idx: jnp.ndarray,
    evac: jnp.ndarray,
    n_evac: jnp.ndarray,
    *,
    m: TensorClusterModel,
    pp: ProposalParams,
    hard_arr: jnp.ndarray,
    weights: jnp.ndarray,
    moves_per_step: int,
    scorer,
    swap_scorer,
    vector_fn,
    gather=None,
    locate=None,
    group=None,
    swap_ramp=0.0,
    swap_schedule_on: bool = False,
    cfg=None,
) -> SearchState:
    """``moves_per_step`` proposals drawn, scored and accepted against the
    step's BASE state, then applied as a pairwise-disjoint batch — the
    polish-pass batching (ccx.search.greedy apply_batch) lifted into the SA
    step.

    Swap endpoints are drawn USAGE-COUPLED with probability ``pp.p_couple``
    (AnnealOptions.swap_coupling): each endpoint Gumbel-picked from a
    ``pp.couple_pool``-candidate pool ranked by live broker band pressure
    (ccx.search.state.broker_pressure, O(B) from the carried aggregates —
    never a [P] pass) x per-replica usage, hot x complementary. Pool slot 0
    is the plain uniform draw, so uncoupled candidates force selection 0
    and the program stays shape-stable across coupling settings; at
    ``p_couple == 0`` the pool collapses to C=1 and the step is the
    round-6 uniform engine. ``swap_ramp``/``swap_schedule_on``: see
    ``_anneal_step`` — the p_swap schedule enters as traced data. Wall-clock rationale: the sequential step pays one stacked
    gather + one stacked scatter per carried buffer *per proposal*; this
    step pays the same *per step*, so K proposals cost ~one proposal's
    kernel sequencing. Under partition-axis sharding the per-proposal psum
    (ccx.parallel.sharding) collapses the same way: ONE collective per step
    for all 2K views.

    Acceptance semantics: each candidate independently passes the
    vector-lexicographic/Metropolis rule vs the base state; candidates whose
    {touched brokers} ∪ {touched topics} overlap an earlier-selected
    candidate are dropped (disjointness makes every sum-decomposable goal
    term exactly additive). The non-sum-decomposable couplings
    (leader-evenness, trd normalizer) cannot violate hard tiers by
    composition, but the composed vector is still recomputed exactly and the
    whole batch is rejected in the (float-drift-only) event a hard tier
    regressed. Chains in batched mode are deterministic given the seed but
    differ from sequential-mode chains (AnnealOptions.batched docstring).
    """
    from ccx.goals import topic_terms as tt
    from ccx.search.state import (
        _placement_updates,
        gather_views,
        scatter_partition,
        view_at,
    )

    K = moves_per_step
    B, T, R = m.B, m.num_topics, m.R
    ss = state
    keys = jax.random.split(jax.random.fold_in(ss.key, step_idx), K)
    couple_on = pp.p_couple > 0.0 and cfg is not None
    C = max(int(pp.couple_pool), 1) if couple_on else 1

    # --- draw K candidate endpoint POOLS (index-only, no state reads) -----
    def draw(k):
        (k_sel, k_p, k_ev, k_evi, k_pa, k_pb, k_s, k_w, k_acc, k_lead,
         k_cpl, k_ga, k_gb) = jax.random.split(k, 13)
        use_swap = (
            (jax.random.uniform(k_sel) < (pp.p_swap + swap_ramp * step_idx))
            if (pp.p_swap > 0.0 or swap_schedule_on)
            else jnp.asarray(False)
        )
        p_single, use_evac = _draw_partition(k_p, k_ev, k_evi, pp, evac, n_evac)
        pool_a = jax.random.randint(k_pa, (C,), 0, pp.p_real)
        pool_b = jax.random.randint(k_pb, (C,), 0, pp.p_real)
        # pool slot 0 doubles as the single-move partition on non-swap draws
        pool_a = pool_a.at[0].set(jnp.where(use_swap, pool_a[0], p_single))
        use_lead = (
            (jax.random.uniform(k_lead) < pp.p_lead_swap)
            if pp.p_lead_swap > 0
            else jnp.asarray(False)
        )
        use_couple = (
            ((jax.random.uniform(k_cpl) < pp.p_couple) & use_swap)
            if couple_on
            else jnp.asarray(False)
        )
        return (pool_a, pool_b, use_swap, use_evac & ~use_swap, use_lead,
                use_couple, k_s, k_w, k_acc, k_ga, k_gb)

    (pools_a, pools_b, use_swap, use_evac, use_lead, use_couple,
     ks_single, ks_swap, ks_acc, ks_ga, ks_gb) = jax.vmap(draw)(keys)

    # ONE stacked gather for all 2*K*C pool views per carried placement
    # buffer (the sharding hook turns this into one owner-gather + one psum)
    views = (gather or gather_views)(
        ss, m, jnp.concatenate([pools_a.reshape(-1), pools_b.reshape(-1)])
    )
    va_pool = jax.tree.map(
        lambda x: x[: K * C].reshape((K, C) + x.shape[1:]), views
    )
    vb_pool = jax.tree.map(
        lambda x: x[K * C:].reshape((K, C) + x.shape[1:]), views
    )

    if couple_on:
        # ---- usage-coupled endpoint selection: Gumbel-pick each endpoint
        # from its pool, ranked by live broker band pressure (over for
        # endpoint a, under for b) x per-replica usage — elementwise math
        # on already-gathered views, no extra carried-buffer reads --------
        press = broker_pressure(m, ss.agg, cfg)
        uw = usage_weights()
        # bf16 scoring tier (ISSUE 16): the pool scores only feed an
        # argmax/Gumbel rank — cast the pressure-table x usage products to
        # the scoring dtype and return to f32 only at the logits, so the
        # Gumbel noise, acceptance and cost vectors never leave f32.
        sdt = scoring_dtype(pp.bf16)

        def pool_scores(vp, over: bool):
            b = jnp.clip(vp.assign, 0, B - 1)                    # [C, R]
            ok = (
                (vp.assign >= 0)
                & vp.pvalid[:, None]
                & ~vp.immovable[:, None]
            )
            is_l = jnp.arange(R)[None, :] == vp.leader[:, None]
            u_lead = vp.lead_load @ uw                           # [C]
            u_foll = vp.foll_load @ uw
            u = jnp.where(is_l, u_lead[:, None], u_foll[:, None])  # [C, R]
            u = u.astype(sdt)
            if over:
                sc = press.usage_over[b].astype(sdt) * u * ok
            else:
                sc = press.usage_under[b].astype(sdt) * (1.0 / (1.0 + u)) * ok
            slot = jnp.argmax(sc, axis=1).astype(jnp.int32)
            rs_logit = jnp.log(jnp.max(sc, axis=1).astype(jnp.float32) + 1e-12)
            # leadership-swap variant: endpoint quality is the LEADER
            # broker's leader-bytes band pressure x the leader's bytes-in
            lsafe = jnp.clip(vp.leader, 0, R - 1)[:, None]
            lb = jnp.take_along_axis(b, lsafe, axis=1)[:, 0]
            has_lead = vp.pvalid & (
                jnp.take_along_axis(vp.assign, lsafe, axis=1)[:, 0] >= 0
            )
            lbytes = vp.lead_load[:, Resource.NW_IN].astype(sdt)
            if over:
                lsc = press.lbi_over[lb].astype(sdt) * lbytes
            else:
                lsc = press.lbi_under[lb].astype(sdt) * (1.0 / (1.0 + lbytes))
            lsc = jnp.where(has_lead, lsc.astype(jnp.float32), 0.0)
            ls_logit = jnp.log(lsc + 1e-12)
            return rs_logit, ls_logit, slot

        rs_a, ls_a, slot_a = jax.vmap(lambda vp: pool_scores(vp, True))(
            va_pool
        )
        rs_b, ls_b, slot_b = jax.vmap(lambda vp: pool_scores(vp, False))(
            vb_pool
        )

        def gumbel_pick(logit_rs, logit_ls, ul, uc, kg):
            logit = jnp.where(ul, logit_ls, logit_rs)
            g = -jnp.log(
                -jnp.log(
                    jax.random.uniform(kg, (C,), minval=1e-12, maxval=1.0)
                )
            )
            s = jnp.argmax(logit + g).astype(jnp.int32)
            return jnp.where(uc, s, 0)

        sel_a = jax.vmap(gumbel_pick)(rs_a, ls_a, use_lead, use_couple, ks_ga)
        sel_b = jax.vmap(gumbel_pick)(rs_b, ls_b, use_lead, use_couple, ks_gb)
        ar = jnp.arange(K)
        va = jax.tree.map(lambda x: x[ar, sel_a], va_pool)
        vb = jax.tree.map(lambda x: x[ar, sel_b], vb_pool)
        pa = pools_a[ar, sel_a]
        pb = pools_b[ar, sel_b]
        r1_c = slot_a[ar, sel_a]
        r2_c = slot_b[ar, sel_b]
    else:
        va = jax.tree.map(lambda x: x[:, 0], va_pool)
        vb = jax.tree.map(lambda x: x[:, 0], vb_pool)
        pa = pools_a[:, 0]
        pb = pools_b[:, 0]
        r1_c = jnp.zeros((K,), jnp.int32)
        r2_c = jnp.zeros((K,), jnp.int32)

    def plan(k_s, k_w, va_k, vb_k, pa_k, pb_k, use_swap_k, use_evac_k,
             use_lead_k, use_couple_k, r1_k, r2_k):
        old_s, new_s, feas_s = _single_plan(k_s, ss, m, pp, va_k, use_evac_k)
        o1w, n1w, o2w, n2w, ok_w, _ = _swap_plan(
            k_w, m, pp, pa_k, va_k, pb_k, vb_k,
            use_lead=use_lead_k if pp.p_lead_swap > 0 else None,
            couple=(use_couple_k & ~use_lead_k, r1_k, r2_k),
        )

        def pick(a, b):
            return jnp.where(use_swap_k, a, b)

        def inert(rows):
            # single moves blank partition b's rows to -1 (bit-exact no-op
            # contribution, same trick as the sequential unified path)
            return tuple(jnp.where(use_swap_k, r, -1) for r in rows)

        olda = (va_k.assign, va_k.leader, va_k.disk)
        newa = (
            pick(n1w[0], new_s[0]),
            pick(n1w[1], new_s[1]),
            pick(n1w[2], new_s[2]),
        )
        oldb = inert((vb_k.assign, vb_k.leader, vb_k.disk))
        newb = inert(n2w)
        return olda, newa, oldb, newb, jnp.where(use_swap_k, ok_w, feas_s)

    olda, newa, oldb, newb, feas = jax.vmap(plan)(
        ks_single, ks_swap, va, vb, pa, pb, use_swap, use_evac,
        use_lead, use_couple, r1_c, r2_c
    )

    deltas = jax.vmap(
        lambda va_k, o1, n1, vb_k, o2, n2: swap_scorer(
            ss, va_k, o1, n1, vb_k, o2, n2
        )
    )(va, olda, newa, vb, oldb, newb)

    accept = feas & jax.vmap(
        lambda vec, k: lex_accept(
            ss.cost_vec, vec, hard_arr, weights, temperature, k
        )
    )(deltas.cost_vec, ks_acc)

    # --- disjoint selection in draw order (keeps the SA proposal mix
    # unbiased; the polish pass, whose job is descent, selects lex-best
    # first instead) --------------------------------------------------------
    touched = jnp.concatenate([olda[0], newa[0], oldb[0], newb[0]], axis=1)
    bmask = jnp.zeros((K, B), bool)
    bmask = jax.vmap(lambda z, bb, v: z.at[bb].set(v, mode="drop"))(
        bmask,
        jnp.where(touched >= 0, jnp.clip(touched, 0, B - 1), B),
        touched >= 0,
    )
    ta = jnp.clip(va.topic, 0, T - 1)
    tb = jnp.clip(vb.topic, 0, T - 1)

    def select(k, carry):
        used_b, used_t, sel = carry
        conf = (
            jnp.any(bmask[k] & used_b)
            | used_t[ta[k]]
            | (use_swap[k] & used_t[tb[k]])
        )
        take_k = accept[k] & ~conf
        sel = sel.at[k].set(take_k)
        used_b = used_b | (bmask[k] & take_k)
        used_t = used_t.at[ta[k]].max(take_k)
        used_t = used_t.at[tb[k]].max(take_k & use_swap[k])
        return used_b, used_t, sel

    _, _, take = jax.lax.fori_loop(
        0,
        K,
        select,
        (jnp.zeros(B, bool), jnp.zeros(T, bool), jnp.zeros(K, bool)),
    )

    # --- exact composition over the selected disjoint subset ---------------
    def acc(k, carry):
        agg, part, mtl, trd, totals = carry
        w = take[k].astype(jnp.float32)
        wi = take[k].astype(jnp.int32)
        va_k = view_at(va, k)
        vb_k = view_at(vb, k)
        o1 = tuple(x[k] for x in olda)
        n1 = tuple(x[k] for x in newa)
        o2 = tuple(x[k] for x in oldb)
        n2 = tuple(x[k] for x in newb)
        agg = scatter_partition(agg, m, va_k, *o1, -w, -wi)
        agg = scatter_partition(agg, m, va_k, *n1, w, wi)
        agg = scatter_partition(agg, m, vb_k, *o2, -w, -wi)
        agg = scatter_partition(agg, m, vb_k, *n2, w, wi)
        part = part + w * (deltas.part_sums[k] - ss.part_sums)
        mtl = mtl + w * deltas.d_mtl[k]
        trd = trd + w * deltas.d_trd[k]
        totals = totals.at[va_k.topic].add(w * deltas.d_total[k])
        totals = totals.at[vb_k.topic].add(w * deltas.d_total2[k])
        return agg, part, mtl, trd, totals

    agg, part, mtl, trd, totals = jax.lax.fori_loop(
        0, K, acc, (ss.agg, ss.part_sums, ss.mtl_sum, ss.trd_sum, ss.topic_totals)
    )
    cost_vec = vector_fn(agg, part, mtl, trd, tt.trd_normalizer(m, totals))

    # Composed-batch acceptance on the EXACT recomputed vector. Per-candidate
    # deltas are scored against the base state, so non-sum-decomposable
    # couplings (leader-evenness averages, trd normalizer) can make the
    # composition worse than the members jointly sanctioned. The guard is
    # DETERMINISTIC — no second Metropolis roll (that would square the
    # members' joint acceptance probability, annealing uphill batches at
    # effectively half temperature): keep the batch iff its exact vector is
    # lex-no-worse than the step BASE (a descent batch) OR lex-no-worse than
    # the PREDICTED composition (base + sum of accepted member deltas — the
    # outcome each member's own lex/Metropolis pass already sanctioned).
    # Only coupling-caused excess regression is rejected; member-sanctioned
    # uphill exploration passes exactly once, like sequential composition.
    d = cost_vec - ss.cost_vec
    hard_regressed = jnp.any(
        (jnp.abs(d) > goal_tols(ss.cost_vec)) & hard_arr & (d > 0)
    )
    n_take = jnp.sum(take.astype(jnp.int32))
    predicted = ss.cost_vec + jnp.sum(
        jnp.where(take[:, None], deltas.cost_vec - ss.cost_vec[None, :], 0.0),
        axis=0,
    )

    def _lex_not_worse(vec, ref):
        dd = vec - ref
        sig = jnp.abs(dd) > goal_tols(ref)
        return ~(jnp.any(sig) & (dd[jnp.argmax(sig)] > 0))

    batch_ok = ~hard_regressed & (
        _lex_not_worse(cost_vec, ss.cost_vec)
        | _lex_not_worse(cost_vec, predicted)
    )

    def sel_tree(new, old):
        return jax.tree.map(lambda a, b: jnp.where(batch_ok, a, b), new, old)

    if locate is not None:
        ia, owna = locate(pa)
        ib, ownb = locate(pb)
    else:
        ia, owna = pa, jnp.ones((K,), bool)
        ib, ownb = pb, jnp.ones((K,), bool)

    write_a = take & batch_ok & owna
    write_b = take & batch_ok & use_swap & ownb
    mirror_a = take & batch_ok & va.pvalid
    mirror_b = take & batch_ok & use_swap & vb.pvalid
    kind = jnp.where(use_swap, jnp.where(use_lead, 2, 1), 0).astype(jnp.int32)
    ss = bump_kind_counters(
        ss, kind, 1, (take & batch_ok).astype(jnp.int32)
    )
    return ss.replace(
        agg=sel_tree(agg, ss.agg),
        part_sums=sel_tree(part, ss.part_sums),
        mtl_sum=sel_tree(mtl, ss.mtl_sum),
        trd_sum=sel_tree(trd, ss.trd_sum),
        topic_totals=sel_tree(totals, ss.topic_totals),
        cost_vec=sel_tree(cost_vec, ss.cost_vec),
        n_accepted=ss.n_accepted + jnp.where(batch_ok, n_take, 0),
        **_placement_updates(
            ss,
            group,
            write=jnp.concatenate([write_a, write_b]),
            ps=jnp.concatenate([ia, ib]),
            mirror=jnp.concatenate([mirror_a, mirror_b]),
            global_ps=jnp.concatenate([pa, pb]),
            ts=jnp.concatenate([va.topic, vb.topic]),
            rows=jnp.concatenate([newa[0], newb[0]]),
            leads=jnp.concatenate([newa[1], newb[1]]),
            disks=jnp.concatenate([newa[2], newb[2]]),
        ),
    )


def _swap_ramp_of(opts: AnnealOptions, n: int) -> float:
    """Per-step swap-share delta of the linear p_swap schedule (0.0 when
    the schedule is off, ``p_swap_end < 0``)."""
    if opts.p_swap_end < 0:
        return 0.0
    return (opts.p_swap_end - opts.p_swap) / max(n - 1, 1)


def _build_step(
    m: TensorClusterModel,
    goal_names: tuple[str, ...],
    cfg: GoalConfig,
    opts: AnnealOptions,
    p_real: int,
    b_real: int,
    max_pt: int,
    swap_ramp=0.0,
):
    """Construct the per-step transition (called inside a trace).

    Shared by the one-shot scan (`_run_chains`) and the fixed-chunk runner
    (`_run_chunk`) so both compile the identical step body. Returns
    ``(step, group)``; ``opts.n_steps`` is never read here — the cooling
    schedule is the caller's business — so a chunk-runner static key with
    ``n_steps`` zeroed still builds the exact same transition. The p_swap
    schedule follows the same rule: ``swap_ramp`` (per-step swap-share
    delta) may be a traced scalar; only the SIGN of ``opts.p_swap_end``
    (schedule on/off) is program shape.
    """
    group = make_topic_group(m, max_pt) if stack_needs_topic(goal_names) else None
    hard_mask = tuple(GOAL_REGISTRY[n].hard for n in goal_names)
    hard_arr = jnp.asarray(hard_mask)
    weights = soft_weights(hard_mask)

    allow_inter = allows_inter_broker(goal_names)
    schedule_on = allow_inter and opts.p_swap_end >= 0
    pp = ProposalParams(
        p_real=p_real,
        b_real=b_real,
        p_leadership=opts.p_leadership,
        p_disk=opts.p_disk,
        p_biased_dest=opts.p_biased_dest,
        p_evac=opts.p_evac,
        target_rack=bool(RACK_TARGET_GOALS & set(goal_names)),
        allow_inter=allow_inter,
        p_swap=opts.p_swap if allow_inter else 0.0,
        target_capacity=bool(CAPACITY_GOALS & set(goal_names)),
        cap_thresholds=tuple(cfg.capacity_threshold),
        p_lead_swap=lead_swap_share(opts.p_leadership),
        p_couple=opts.swap_coupling if allow_inter else 0.0,
        couple_pool=opts.couple_pool,
        bf16=opts.bf16_scoring,
    )
    from ccx.search.state import make_cost_vector_fn

    # Batched disjoint proposals need room to BE disjoint: each move touches
    # ~2R brokers, so on small clusters (B1-scale) most of a batch conflicts
    # and churn collapses — measured 2.5x fewer accepted moves at B=10.
    # Sequential composition wins there; batching wins from ~hundreds of
    # brokers up (B5: 1024 >> 4*R*K). p_swap == 0 stacks (leadership-only
    # demote, disk-only rebalance) also stay sequential: the batched step
    # always runs the unified two-partition gather/scatter, losing the
    # ``inner_single_only`` fast path that keeps exactly one use per carried
    # buffer (the XLA in-place scatter constraint, _anneal_step docstring).
    batched = (
        opts.batched
        and opts.moves_per_step > 1
        and (pp.p_swap > 0.0 or schedule_on)
        and b_real >= 4 * m.R * opts.moves_per_step
    )
    step = functools.partial(
        _anneal_step_batched if batched else _anneal_step,
        m=m,
        pp=pp,
        hard_arr=hard_arr,
        weights=weights,
        moves_per_step=max(opts.moves_per_step, 1),
        scorer=make_move_scorer(m, goal_names, cfg),
        swap_scorer=make_swap_scorer(m, goal_names, cfg),
        group=group,
        swap_ramp=swap_ramp,
        swap_schedule_on=schedule_on,
        cfg=cfg,
        **(
            {"vector_fn": make_cost_vector_fn(m, goal_names, cfg)}
            if batched
            else {}
        ),
    )
    return step, group


@costmodel.instrument("chain-init")
@functools.partial(jax.jit, static_argnames=("goal_names", "cfg", "max_pt"))
def _init_chains(
    m: TensorClusterModel,
    keys: jnp.ndarray,
    *,
    goal_names: tuple[str, ...],
    cfg: GoalConfig,
    max_pt: int,
) -> SearchState:
    group = make_topic_group(m, max_pt) if stack_needs_topic(goal_names) else None
    state0 = init_search_state(m, cfg, goal_names, keys[0], group=group)
    return jax.vmap(lambda k: state0.replace(key=k))(keys)


def _probe_ready(x) -> bool:
    """Non-blocking readiness poll for a dispatched probe scalar. False
    when the runtime offers no ``is_ready`` (never block — the probe is a
    best-effort heartbeat enrichment, not a sync point)."""
    fn = getattr(x, "is_ready", None)
    try:
        return bool(fn()) if callable(fn) else False
    except Exception:  # noqa: BLE001 — a deleted/donated buffer reads False
        return False


@dataclasses.dataclass
class PlateauExit:
    """Plateau-terminated budget for one ``drive_chunks`` call (ISSUE 10).

    ``row(carry)`` returns the convergence tap's CURRENT chunk row (the
    lex-best per-goal cost vector) as a device array; the driver reads it
    at the chunk boundary — for engines with an early-exit sync that read
    is free, for sync-free SA drives it IS the early-exit sync (one small
    transfer per chunk, the price of a data-dependent budget). The
    decision deliberately does NOT reuse the non-blocking heartbeat
    probe: that one is a chunk stale by construction, and an exit rule
    one chunk behind both overshoots the budget and — worse — reads the
    *previous* chunk's improvement as the current one's, so a drive that
    drifts exactly at the plateau boundary would exit a chunk early
    (pinned by tests/test_incremental.py).

    ``window``/``min_chunks`` are host data — retuning them reuses every
    compiled program. Result fields are filled in by the driver."""

    row: object
    window: int = 1
    min_chunks: int = 1
    # ----- filled by drive_chunks ------------------------------------------
    # chunks_run and last_improved_chunk share a 1-based basis (ordinal
    # of the chunk), so ``chunks_run - last_improved_chunk`` is exactly
    # the number of chunks run past the plateau (0 = improved-to-the-end)
    exited: bool = False
    chunks_run: int = 0
    last_improved_chunk: int = 0

    def to_json(self, budget_chunks: int | None = None) -> dict:
        out = {
            "exited": bool(self.exited),
            "chunksRun": int(self.chunks_run),
            "window": int(self.window),
            "lastImprovedChunk": int(self.last_improved_chunk),
        }
        if budget_chunks is not None:
            out["chunksBudget"] = int(budget_chunks)
        return out


def drive_chunks(run_one, carry, *, total: int, chunk: int, probe=None,
                 plateau: PlateauExit | None = None):
    """Host-side chunk driver shared by the SA chunk runner and both
    chunked polish engines (ccx.search.greedy): invoke
    ``run_one(carry, off)`` once per chunk offset, threading the (usually
    donated) carry through. ``run_one`` returns ``(carry, done)``; a
    non-None truthy ``done`` ends the loop early — ONE scalar device→host
    sync per chunk, the early-exit check the monolithic while_loop used to
    do on device. SA chunks have no early exit and return ``done=None``
    (no sync at all: the chunks stay queued on the device stream).

    Every chunk boundary emits a flight-recorder heartbeat (tracing): the
    chunk index lands on the enclosing phase span and — when the recorder
    is armed — in the JSONL, so a SIGKILLed run's last record names
    exactly how deep into which phase it died, and the stall watchdog
    re-arms on live progress. Host-side only (no device sync is added):
    unarmed, the heartbeat is two attribute writes.

    When the calling thread runs under a fleet job
    (``ccx.search.scheduler.FLEET.job(...)`` — the optimizer's job-handle
    entry point), every chunk DISPATCH must win a grant from the multi-job
    run queue: N concurrent jobs interleave their chunks round-robin
    (priority-ordered) on the device stream instead of convoying, and the
    chunk boundary becomes the preemption point an urgent job jumps in at.
    Only the dispatch is gated — the early-exit sync runs outside the
    grant so another job dispatches while this chunk executes. With no
    ambient job (tests, tools, single-tenant paths) the loop is exactly
    the ungated round-11 driver.

    ``probe(carry) -> device scalar`` (optional — the convergence taps,
    ccx.search.telemetry) supplies the tier-0 lex energy joined onto each
    heartbeat, WITHOUT adding a host sync: engines with an early-exit
    sync (``done`` non-None) read the probe at that existing sync; SA
    chunks (``done=None``, fully pipelined) dispatch the probe async and
    each heartbeat reports the latest probe that ``is_ready`` — typically
    the previous chunk's energy, one chunk stale by construction.

    ``plateau`` (a :class:`PlateauExit`) arms the plateau-terminated
    budget (ISSUE 10): after each chunk the driver reads the convergence
    tap's CURRENT row via ``plateau.row(carry)`` and ends the drive once
    ``plateau.window`` consecutive chunks stop lex-improving
    (``ccx.common.convergence`` tolerances, the same asymmetric rule the
    budget advisor uses). The read doubles as this chunk's heartbeat
    energy, so a plateau-armed drive's heartbeats are CURRENT, never the
    non-blocking probe's one-chunk-stale value — the exit decision and
    the recorded quality both describe the chunk that just ran."""
    from ccx.common.convergence import lex_improved
    from ccx.common.tracing import TRACER
    from ccx.search.scheduler import FLEET

    step = max(int(chunk), 1)
    n = max(int(total), 0)
    job = FLEET.current()
    energy = None
    pending = None
    best_vec = None
    since_improve = 0
    with (FLEET.drive(job) if job is not None else contextlib.nullcontext()):
        for i, off in enumerate(range(0, n, step)):
            if job is not None:
                with FLEET.chunk(job):
                    carry, done = run_one(carry, off)
            else:
                carry, done = run_one(carry, off)
            done_plateau = False
            if plateau is not None:
                try:
                    import numpy as _np

                    vec = [float(x) for x in _np.asarray(plateau.row(carry))]
                except Exception:  # noqa: BLE001 — a broken tap read must
                    plateau = None  # degrade to the fixed budget, not crash
                else:
                    # this read IS the chunk-boundary sync: energy below is
                    # the CURRENT chunk's tier-0 cost, and the exit rule
                    # compares the current chunk, not the stale probe
                    energy = vec[0] if vec else None
                    plateau.chunks_run = i + 1
                    if best_vec is None or lex_improved(vec, best_vec):
                        best_vec = list(vec)
                        since_improve = 0
                        plateau.last_improved_chunk = i + 1
                    else:
                        since_improve += 1
                    done_plateau = (
                        i + 1 >= max(plateau.min_chunks, 1)
                        and since_improve >= max(plateau.window, 1)
                    )
                    plateau.exited = done_plateau and off + step < n
            if probe is not None and plateau is None:
                try:
                    val = probe(carry)
                    if done is not None:
                        # the early-exit poll below blocks on this chunk
                        # anyway — reading the probe here adds a scalar
                        # transfer, not a sync
                        energy, pending = float(val), None
                    else:
                        if pending is not None and _probe_ready(pending):
                            energy = float(pending)
                        pending = val
                except Exception:  # noqa: BLE001 — enrichment only: a
                    # broken probe must never break the drive loop
                    probe = None
            TRACER.heartbeat(i, offset=off, total=n, energy=energy)
            if done is not None and bool(done):
                break
            if done_plateau:
                break
    return carry


def ladder_rungs(n_temps: int, n_chains: int) -> np.ndarray:
    """int32[n_chains] rung index per chain under the replica-exchange
    ladder: equal-sized contiguous blocks of ``n_chains // K`` chains, rung
    0 coldest. When K does not divide the batch (``round_up_chains`` makes
    this rare) the remainder chains fold into the hottest rung — they run
    the rung-(K-1) schedule but sit OUTSIDE the exchange pairing, so the
    pairing stays a clean bijection."""
    K = max(int(n_temps), 1)
    size = max(int(n_chains) // K, 1)
    return np.minimum(np.arange(int(n_chains)) // size, K - 1).astype(np.int32)


def ladder_fracs(n_temps: int, n_chains: int) -> np.ndarray:
    """f32[n_chains] decay-exponent fraction per chain: rung k cools as
    ``T_k(t) = t0 * decay**(t * (1 - k/(K-1)))``, i.e. rung 0 is the exact
    legacy schedule, rung K-1 holds at ``t0``, and the rung END
    temperatures form the geometric ladder ``t1^(1-k/(K-1)) * t0^(k/(K-1))``
    between ``t1`` and ``t0``. A static per-chain constant — temperatures
    stay traced data and every rung shares the one compiled chunk."""
    K = max(int(n_temps), 1)
    if K == 1:
        return np.ones(int(n_chains), np.float32)
    rung = ladder_rungs(K, n_chains).astype(np.float64)
    return (1.0 - rung / (K - 1)).astype(np.float32)


def ladder_end_temps(opts: AnnealOptions) -> list[float]:
    """Host-side end-of-schedule temperature per rung (telemetry/report)."""
    K = max(int(opts.n_temps), 1)
    if K == 1:
        return [float(opts.t1)]
    return [
        float(opts.t1 ** (1.0 - k / (K - 1)) * opts.t0 ** (k / (K - 1)))
        for k in range(K)
    ]


def _lex_lt_rows(a: jnp.ndarray, b: jnp.ndarray, mask=None) -> jnp.ndarray:
    """bool[n]: row ``a[i]`` lexicographically beats ``b[i]`` under the
    ``goal_tols`` significance rule (optionally restricted to a goal
    ``mask``). Rowwise twin of the scalar test inside ``lex_accept``."""
    d = a - b
    sig = jnp.abs(d) > goal_tols(b)
    if mask is not None:
        sig = sig & mask[None, :]
    first = jnp.argmax(sig, axis=1)
    any_sig = jnp.any(sig, axis=1)
    return any_sig & (jnp.take_along_axis(d, first[:, None], axis=1)[:, 0] < 0)


def exchange_permutation(
    cost_vec: jnp.ndarray,      # f32[n, G] per-chain lex cost vectors
    temps: jnp.ndarray,         # f32[n] per-chain current temperature
    key: jnp.ndarray,           # PRNG key for the Metropolis draws
    *,
    n_temps: int,
    hard_arr: jnp.ndarray,      # bool[G]
    weights: jnp.ndarray,       # f32[G] soft tier weights
    parity,                     # 0: pair rungs (0,1),(2,3)…; 1: (1,2),(3,4)…
):
    """One replica-exchange sweep as a PERMUTATION of the chain axis.

    Neighboring rungs pair elementwise (rung r chain j ↔ rung r+1 chain j,
    alternating even/odd rung pairings by ``parity`` so the whole ladder
    mixes over successive sweeps). Each pair swaps WHOLE chain states —
    every SearchState leaf, RNG keys included — so the move is invisible
    to everything but the temperature a chain will see next: replica
    counts, leader invariants and devmem accounting are untouched by
    construction, and no shapes change (zero new compile classes).

    Decision per pair, evaluated at the cold member:
    1. hard tiers significantly differ (``goal_tols``) → deterministic:
       swap iff the hot member is hard-lex-better (hard goals behave as
       the lex gate in ``lex_accept`` — never Metropolis'd);
    2. soft scalars significantly differ → standard Metropolis exchange
       ``log u < (1/T_cold - 1/T_hot) * (E_cold - E_hot)`` on the
       tier-weighted soft-cost scalar;
    3. tie → full-vector lex: swap iff the hot member is lex-better.
    The lex-best chain overrides all three: it is never exchanged away
    from its rung toward hotter, and always exchanged colder — the coldest
    rung can only gain it, never lose it.

    Returns ``(perm int32[n], attempted, accepted)``; apply with
    ``jax.tree.map(lambda x: x[perm], states)``. ``perm`` is an involution
    (pairs swap or stay), hence always a valid permutation.
    """
    n, G = cost_vec.shape
    K = max(int(n_temps), 1)
    size = max(n // K, 1)
    idx = jnp.arange(n, dtype=jnp.int32)
    rung = jnp.minimum(idx // size, K - 1)
    in_ladder = idx < K * size  # remainder chains sit outside the pairing
    low = ((rung - parity) % 2) == 0
    partner_rung = jnp.where(low, rung + 1, rung - 1)
    valid = in_ladder & (partner_rung >= 0) & (partner_rung < K)
    partner = jnp.clip(partner_rung, 0, K - 1) * size + (idx % size)
    partner = jnp.where(valid, partner, idx)

    soft_w = jnp.where(hard_arr, 0.0, weights)
    E = cost_vec @ soft_w                       # f32[n] soft-cost scalar
    cv_p = cost_vec[partner]

    # the lex-best chain (same elimination as telemetry.lex_best_row)
    alive = jnp.ones((n,), bool)
    for g in range(G):
        col = jnp.where(alive, cost_vec[:, g], jnp.inf)
        mn = jnp.min(col)
        alive = alive & (col <= mn + 1e-6 + 1e-6 * jnp.abs(mn))
    is_best = idx == jnp.argmax(alive)

    hard_sig = jnp.any(
        (jnp.abs(cost_vec - cv_p) > goal_tols(cost_vec)) & hard_arr[None, :],
        axis=1,
    )
    hot_hard_better = _lex_lt_rows(cv_p, cost_vec, mask=hard_arr)

    E_p = E[partner]
    inv_t = 1.0 / jnp.maximum(temps, 1e-30)
    dlog = (inv_t - inv_t[partner]) * (E - E_p)
    u = jax.random.uniform(key, (n,), minval=1e-12, maxval=1.0)
    metro = jnp.log(u) < dlog
    soft_tie = jnp.abs(E - E_p) <= 1e-6 + 1e-6 * jnp.abs(E)
    hot_lex_better = _lex_lt_rows(cv_p, cost_vec)

    d = jnp.where(soft_tie, hot_lex_better, metro)
    d = jnp.where(hard_sig, hot_hard_better, d)
    d = jnp.where(is_best, False, d)            # never demote the best
    d = jnp.where(is_best[partner], True, d)    # always promote the best
    d = d & valid & low                         # decided at the cold member
    swap = d | d[partner]
    perm = jnp.where(swap, partner, idx)
    attempted = jnp.sum((valid & low).astype(jnp.int32))
    accepted = jnp.sum(d.astype(jnp.int32))
    return perm, attempted, accepted


@costmodel.instrument("sa-chunk", iters=lambda k: k["chunk"])
@functools.partial(
    jax.jit,
    static_argnames=(
        "goal_names", "cfg", "opts", "p_real", "b_real", "max_pt", "chunk",
    ),
    donate_argnums=(0,),
)
def _run_chunk(
    states: SearchState,
    m: TensorClusterModel,
    evac: jnp.ndarray,
    n_evac: jnp.ndarray,
    t_offset: jnp.ndarray,
    decay: jnp.ndarray,
    swap_ramp: jnp.ndarray,
    n_total: jnp.ndarray,
    ex_interval=None,
    tap=None,
    *,
    goal_names: tuple[str, ...],
    cfg: GoalConfig,
    opts: AnnealOptions,
    p_real: int,
    b_real: int,
    max_pt: int,
    chunk: int,
) -> SearchState:
    """Fixed-length scan segment with the global step index passed as data.

    The caller zeroes ``opts.n_steps`` in the static key and feeds the
    cooling schedule in as traced scalars (``t_offset``, ``decay``), so
    EVERY step budget reuses one compiled program per chunk shape. On TPU a
    B5-scale anneal compile is minutes (measured 155-379 s per distinct
    n_steps on v5e); chunking pays it once per (chains, moves) shape instead
    of once per rung/retune. Bit-exact vs `_run_chains`: the step body is
    identical (`_build_step`) and ``temp = t0 * decay**t`` sees the same
    f32 values — XLA folds the unchunked path's python-float decay to f32
    exactly as `jnp.float32(decay)` does here. ``swap_ramp`` rides along
    the same way (the p_swap schedule is data, not shape).

    ``n_total`` (traced) is the run's REAL step budget: steps with
    ``t >= n_total`` are inert (identity ``lax.cond`` branch), so a budget
    that does not divide ``chunk`` runs its remainder as a zeroed-budget
    tail inside the SAME compiled program — the round-7 restriction
    ("pick n_steps % chunk_steps == 0 or pay a second compile") is gone.

    ``tap`` (optional — the convergence telemetry carry,
    ccx.search.telemetry) rides through untouched-by-the-scan and gets ONE
    traced ``dynamic_update_slice`` row at chunk end: the lex-best chain's
    full cost vector, chain-summed cumulative move counters, and the
    temperature at the chunk's last live step. None (taps off) traces the
    identical pre-telemetry program, so taps-off results are bit-exact.

    ``opts.n_temps > 1`` arms the replica-exchange ladder (ISSUE 16): each
    chain's temperature follows its rung's schedule (``ladder_fracs`` — a
    static per-chain exponent fraction, so temperatures remain traced
    data) and the chunk ends with one ``exchange_permutation`` sweep of
    the batch axis, gated on the traced ``ex_interval`` (every
    ``ex_interval``-th chunk; the static key zeroes it, so interval
    retunes reuse the program). K == 1 traces the literal legacy program
    — the ladder code is absent, not disabled — so flat runs stay
    bit-exact by construction.
    """
    step, _ = _build_step(
        m, goal_names, cfg, opts, p_real, b_real, max_pt, swap_ramp=swap_ramp
    )
    K_t = max(int(opts.n_temps), 1)
    n_batch = states.cost_vec.shape[0]
    frac = (
        jnp.asarray(ladder_fracs(K_t, n_batch)) if K_t > 1 else None
    )

    def body(ss: SearchState, t: jnp.ndarray) -> tuple[SearchState, None]:
        def active(s):
            if K_t > 1:
                temp = opts.t0 * decay ** (t.astype(jnp.float32) * frac)
                return jax.vmap(step, in_axes=(0, 0, None, None, None))(
                    s, temp, t, evac, n_evac
                )
            temp = opts.t0 * decay**t
            return jax.vmap(step, in_axes=(0, None, None, None, None))(
                s, temp, t, evac, n_evac
            )

        ss = jax.lax.cond(t < n_total, active, lambda s: s, ss)
        return ss, None

    states, _ = jax.lax.scan(body, states, t_offset + jnp.arange(chunk))
    t_last = jnp.maximum(jnp.minimum(t_offset + chunk, n_total) - 1, 0)
    n_ex_att = n_ex_acc = jnp.zeros((), jnp.int32)
    if K_t > 1:
        hard_mask = tuple(GOAL_REGISTRY[g].hard for g in goal_names)
        interval = jnp.maximum(
            jnp.asarray(
                1 if ex_interval is None else ex_interval, jnp.int32
            ),
            1,
        )
        chunk_ord = t_offset // chunk
        do_ex = (((chunk_ord + 1) % interval) == 0) & (t_offset < n_total)
        parity = (chunk_ord // interval) % 2
        perm, att, acc = exchange_permutation(
            states.cost_vec,
            opts.t0 * decay ** (t_last.astype(jnp.float32) * frac),
            jax.random.fold_in(states.key[0], t_offset),
            n_temps=K_t,
            hard_arr=jnp.asarray(hard_mask),
            weights=soft_weights(hard_mask),
            parity=parity,
        )
        perm = jnp.where(do_ex, perm, jnp.arange(n_batch, dtype=jnp.int32))
        n_ex_att = jnp.where(do_ex, att, 0)
        n_ex_acc = jnp.where(do_ex, acc, 0)
        states = jax.tree.map(lambda x: x[perm], states)
    if tap is not None:
        from ccx.search import telemetry

        tap = telemetry.record(
            tap,
            telemetry.lex_best_row(states.cost_vec),
            jnp.sum(states.n_prop_kind, axis=0),
            jnp.sum(states.n_acc_kind, axis=0),
            opts.t0 * decay**t_last,
            n_ex_att,
            n_ex_acc,
        )
    return states, tap


@costmodel.instrument("sa-monolith", iters=lambda k: k["opts"].n_steps)
@functools.partial(
    jax.jit,
    static_argnames=("goal_names", "cfg", "opts", "p_real", "b_real", "max_pt"),
)
def _run_chains(
    m: TensorClusterModel,
    keys: jnp.ndarray,
    evac: jnp.ndarray,
    n_evac: jnp.ndarray,
    *,
    goal_names: tuple[str, ...],
    cfg: GoalConfig,
    opts: AnnealOptions,
    p_real: int,
    b_real: int,
    max_pt: int,
) -> SearchState:
    n = max(opts.n_steps, 1)
    step, group = _build_step(
        m, goal_names, cfg, opts, p_real, b_real, max_pt,
        swap_ramp=_swap_ramp_of(opts, n),
    )
    state0 = init_search_state(m, cfg, goal_names, keys[0], group=group)
    states = jax.vmap(lambda k: state0.replace(key=k))(keys)

    decay = (opts.t1 / opts.t0) ** (1.0 / max(n - 1, 1))

    def body(ss: SearchState, t: jnp.ndarray) -> tuple[SearchState, None]:
        temp = opts.t0 * decay**t
        ss = jax.vmap(step, in_axes=(0, None, None, None, None))(
            ss, temp, t, evac, n_evac
        )
        return ss, None

    states, _ = jax.lax.scan(body, states, jnp.arange(n))
    return states


def best_chain_index(cost_vecs: np.ndarray) -> int:
    """Lexicographic argmin across chains (host-side, tiny array)."""
    order = sorted(range(cost_vecs.shape[0]), key=lambda i: tuple(cost_vecs[i]))
    return int(order[0])


#: (n_chains, ranks, n_temps) shapes whose padding note already logged —
#: the warm drive calls round_up_chains every window, and one note per
#: SHAPE is signal where one per call was log spam.
_ROUNDED_SHAPES: set = set()


def round_up_chains(
    n_chains: int, ranks: int, where: str, n_temps: int = 1
) -> int:
    """Next multiple of ``ranks * n_temps`` >= ``n_chains``, noted once.

    A campaign retune (or an odd device count) used to abort with a hard
    ``ValueError`` when the chain count did not divide the mesh; rounding
    up instead costs a few extra chains (more search, same wall — chains
    are the embarrassingly-parallel axis) and never kills a window. Under
    the replica-exchange ladder the multiple is K x ranks so every rung
    stays equal-sized across the sharded mesh path (a ragged hottest rung
    would silently sit out the exchange pairing). The padding note logs
    once per (n_chains, ranks, n_temps) shape, not per call."""
    mult = max(int(ranks), 1) * max(int(n_temps), 1)
    if mult <= 1 or n_chains % mult == 0:
        return max(n_chains, mult)
    rounded = ((n_chains + mult - 1) // mult) * mult
    shape = (int(n_chains), int(ranks), int(n_temps))
    if shape not in _ROUNDED_SHAPES:
        _ROUNDED_SHAPES.add(shape)
        import logging

        logging.getLogger(__name__).warning(
            "%s: n_chains=%d not divisible by %d (mesh chain ranks %d x "
            "temperature rungs %d); rounding up to %d",
            where, n_chains, mult, ranks, n_temps, rounded,
        )
    return rounded


def anneal(
    m: TensorClusterModel,
    cfg: GoalConfig = GoalConfig(),
    goal_names: tuple[str, ...] = DEFAULT_GOAL_ORDER,
    opts: AnnealOptions = AnnealOptions(),
    mesh=None,
    evac=None,
) -> AnnealResult:
    """Run batched SA and return the best chain's placement as a new model.

    Chains never accept hard-cost-increasing moves, and the temperature
    schedule ends near zero, so each chain's final state is its best
    reachable local optimum; the winner is the lexicographic argmin of the
    full cost vector across chains. The returned model's stack scores are
    re-evaluated from scratch (incremental float drift cannot leak into
    reported results).

    With ``mesh`` (a jax.sharding.Mesh), the run is sharded across every
    mesh device. A mesh whose ``parts`` axis is >1 (and divides the padded
    P) dispatches to the partition-axis-sharded engine
    (``ccx.parallel.sharding.sharded_anneal`` — model tensors stay sharded
    for the whole run); otherwise chains ride the mesh as pure data
    parallelism with the model and evacuation list replicated. Either way
    the CHUNKED driver applies when ``opts.chunk_steps > 0`` — a mesh run
    gets the same bounded compile, per-chunk heartbeats and flight-recorder
    evidence as a single-chip run (pre-round-11 mesh runs silently fell
    back to the one-shot scan). ``opts.n_chains`` is rounded UP to the next
    mesh multiple when it does not divide (logged, never an abort).

    ``evac`` optionally supplies a precomputed hot-partition list as
    ``(indices int32[P], count)`` — device arrays are fine. The optimizer's
    pipelined device-repair path passes `hot_partition_list_device` output
    so this function never has to materialize the (possibly still
    in-flight) placement to host; None computes the host list as before.
    """
    if mesh is not None:
        # partition-axis mesh: hand the whole run to the sharded engine
        # (ccx.parallel) — it shares this function's RNG stream/acceptance
        # rule and, with chunk_steps > 0, the chunked drive contract. A
        # parts axis that does not divide the padded P falls through to
        # chains-only data parallelism with a note (never an abort).
        parts = dict(zip(mesh.axis_names, mesh.devices.shape)).get("parts", 1)
        if parts > 1:
            if int(m.P) % parts == 0:
                from ccx.parallel.sharding import sharded_anneal

                return sharded_anneal(
                    m, cfg, goal_names, opts, mesh, evac=evac
                )
            import logging

            logging.getLogger(__name__).warning(
                "anneal: padded P=%d not divisible by mesh parts=%d; "
                "running chains-only data parallelism over the %d devices",
                int(m.P), parts, mesh.size,
            )

    stack_before = evaluate_stack(m, cfg, goal_names)
    p_real = int(np.asarray(m.partition_valid).sum())
    bv = np.asarray(m.broker_valid)
    b_real = int(np.max(np.where(bv, np.arange(m.B), -1))) + 1
    evac, n_evac = (
        evac if evac is not None else hot_partition_list(m, goal_names, cfg)
    )

    n_chains = opts.n_chains
    n_temps = max(int(opts.n_temps), 1) if opts.chunk_steps > 0 else 1
    if mesh is not None or n_temps > 1:
        n_chains = round_up_chains(
            n_chains, mesh.size if mesh is not None else 1, "anneal",
            n_temps=n_temps,
        )
    keys = jax.random.split(jax.random.PRNGKey(opts.seed), n_chains)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        keys = jax.device_put(
            keys, NamedSharding(mesh, PartitionSpec(mesh.axis_names))
        )
        m = jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(mesh, PartitionSpec())), m
        )
        # the evac list may arrive committed to a single device (the
        # pipelined hot_partition_list_device path) — replicate it on the
        # mesh or the mixed-committment jit call errors out
        rep = NamedSharding(mesh, PartitionSpec())
        evac = jax.device_put(jnp.asarray(evac), rep)
        n_evac = jax.device_put(jnp.asarray(n_evac, jnp.int32), rep)
    max_pt = max_partitions_per_topic(m)
    if opts.chunk_steps > 0:
        # Chunked path: one compiled chunk program serves every step budget
        # (see _run_chunk). The chunk length is ALWAYS chunk_steps — a
        # budget that does not divide it runs its remainder as a
        # zeroed-budget tail (t >= n inert) inside the same program, so
        # arbitrary retunes never pay a second compile. A chains-mesh run
        # takes the SAME gate (jit caches per sharding): bounded compile,
        # drive_chunks heartbeats and cost capture all survive the mesh.
        n = max(opts.n_steps, 1)
        decay = (opts.t1 / opts.t0) ** (1.0 / max(n - 1, 1))
        # the schedule's MAGNITUDE is traced data (swap_ramp below); only
        # its on/off sign may shape the program, so the static key pins
        # p_swap_end to a sign sentinel and schedule retunes reuse the
        # compiled chunk
        # plateau_window is a host-side drive knob (PlateauExit), never
        # program shape — zero it in the static key so arming/retuning
        # the plateau exit reuses the compiled chunk (pinned)
        # exchange_interval is traced data (like the budget/schedule) —
        # zero it in the static key so interval retunes reuse the chunk;
        # n_temps/bf16_scoring stay: they ARE program shape (ladder
        # in_axes / scoring dtype).
        opts_key = dataclasses.replace(
            opts, n_steps=0, seed=0,
            p_swap_end=1.0 if opts.p_swap_end >= 0 else -1.0,
            plateau_window=0, exchange_interval=0,
        )
        states = _init_chains(
            m, keys, goal_names=goal_names, cfg=cfg, max_pt=max_pt
        )
        evac_j = jnp.asarray(evac)
        n_evac_j = jnp.asarray(n_evac, jnp.int32)
        ramp = jnp.asarray(_swap_ramp_of(opts, n), jnp.float32)
        decay_j = jnp.asarray(decay, jnp.float32)
        n_j = jnp.asarray(n, jnp.int32)
        # convergence taps (ccx.search.telemetry): the ring buffer rides
        # the chunk carry; None (taps off) keeps the program bit-exact
        from ccx.search import telemetry

        tap = telemetry.make_tap(len(goal_names)) if telemetry.enabled() else None
        if mesh is not None and tap is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            # like evac: replicate the tap or the mixed-committment jit
            # call on a chains-mesh errors out
            tap = jax.device_put(
                tap, NamedSharding(mesh, PartitionSpec())
            )

        ex_interval_j = jnp.asarray(
            max(int(opts.exchange_interval), 1), jnp.int32
        )

        def run_one(carry, off):
            states, tp = carry
            return _run_chunk(
                states, m, evac_j, n_evac_j,
                jnp.asarray(off, jnp.int32), decay_j, ramp, n_j,
                ex_interval_j, tp,
                goal_names=goal_names, cfg=cfg, opts=opts_key,
                p_real=p_real, b_real=b_real, max_pt=max_pt,
                chunk=int(opts.chunk_steps),
            ), None

        probe = None
        if tap is not None:
            # tier-0 heartbeat energy: best chain's top-tier cost — read
            # non-blocking by drive_chunks (SA chunks have no sync point)
            def probe(carry):
                return jnp.min(carry[0].cost_vec[:, 0])

        plateau = None
        if opts.plateau_window > 0 and tap is not None:
            # plateau-early-exit (ISSUE 10): the exit rule reads the
            # tap's CURRENT row — the lex-best full cost vector the chunk
            # program just wrote — at the chunk boundary. The read is the
            # warm drive's one sync per chunk; the window is host data
            # (no program sees it, retunes never recompile).
            G = len(goal_names)

            def tap_row(carry):
                buf, cnt = carry[1]
                idx = jnp.clip(cnt - 1, 0, buf.shape[0] - 1)
                return buf[idx, :G]

            plateau = PlateauExit(
                row=tap_row, window=int(opts.plateau_window)
            )

        states, tap = drive_chunks(
            run_one, (states, tap), total=n, chunk=opts.chunk_steps,
            probe=probe, plateau=plateau,
        )
        ladder_meta = None
        if n_temps > 1:
            ladder_meta = {
                "nTemps": n_temps,
                "interval": max(int(opts.exchange_interval), 1),
                "rungSize": n_chains // n_temps,
                "t0": float(opts.t0),
                "endTemps": ladder_end_temps(opts),
            }
        convergence = telemetry.decode(
            tap, goal_names, chunk_size=opts.chunk_steps, budget=n,
            ladder=ladder_meta,
        )
        plateau_info = (
            plateau.to_json(
                budget_chunks=(n + opts.chunk_steps - 1) // opts.chunk_steps
            )
            if plateau is not None
            else None
        )
    else:
        if opts.n_temps > 1:
            import logging

            logging.getLogger(__name__).warning(
                "anneal: n_temps=%d needs chunk_steps > 0 (exchange runs "
                "at chunk boundaries); monolithic run stays flat",
                opts.n_temps,
            )
        states = _run_chains(
            m, keys, jnp.asarray(evac), jnp.asarray(n_evac, jnp.int32),
            goal_names=goal_names, cfg=cfg, opts=opts,
            p_real=p_real, b_real=b_real,
            max_pt=max_pt,
        )
        convergence = None
        plateau_info = None

    best = best_chain_index(np.asarray(states.cost_vec))
    pick = jax.tree.map(lambda a: a[best], states)
    result_model = with_placement(m, pick)
    stack_after = evaluate_stack(result_model, cfg, goal_names)

    return AnnealResult(
        model=result_model,
        stack_before=stack_before,
        stack_after=stack_after,
        n_accepted=int(np.asarray(pick.n_accepted)),
        n_chains=n_chains,
        n_steps=opts.n_steps,
        best_chain=best,
        n_prop_kind=tuple(int(x) for x in np.asarray(pick.n_prop_kind)),
        n_acc_kind=tuple(int(x) for x in np.asarray(pick.n_acc_kind)),
        convergence=convergence,
        plateau=plateau_info,
    )
