"""Incremental re-optimization — the warm-start drift loop (ISSUE 10).

Every proposal used to be computed from scratch, but real clusters drift
continuously: "Integrative Dynamic Reconfiguration in a Parallel Stream
Processing Engine" (PAPERS.md) treats reconfiguration as an online
process, and the consumer-group autoscaler line of work makes elasticity
events the common case. This module turns the optimizer from a verb into
a control loop: keep the last converged placement device-resident per
cluster session, and on a metrics window

1. **re-score only the touched bands** — the band-pressure tables
   (``ccx.search.state.broker_pressure``) double as the delta cache: the
   previous run banked its per-broker pressure vector, the new metrics
   produce a new one, and only brokers whose pressure moved beyond a
   tolerance are "touched". Partitions with a replica on a touched broker
   (plus any structural offenders) become the warm run's targeted hot
   list, so a tiny budget concentrates where the drift is;
2. **warm-start the search from the previous solution** — the previous
   placement is grafted onto the new metric tensors (a few device array
   replacements, never a model rebuild) and the SA/polish machinery runs
   from it with a short traced budget at low temperature (descent with a
   whisper of Metropolis, not an anneal from random);
3. **terminate on detected plateau** instead of a fixed budget — the
   convergence taps (``ccx.search.telemetry``) already write the lex-best
   cost vector at every chunk boundary; the plateau-early-exit mode in
   ``annealer.drive_chunks`` reads that row at the existing chunk
   boundary and stops the drive once ``plateau_window`` chunks stop
   improving (``ccx.common.convergence`` tolerances). The window is host
   data: retuning it never recompiles anything;
4. **emit a minimal diff** — the proposal is the placement delta against
   the warm base (``ccx.proposals.columnar_diff``, the compiled device
   diff since round 15 — only the changed rows cross device→host),
   which at a 1 % metrics drift is a few hundred rows, not a 60k full
   plan.

Gating: the whole subsystem is OFF unless armed — config
``optimizer.incremental.enabled`` (REST-overridable) or an explicit
warm-start request, and env ``CCX_INCREMENTAL=0`` force-disables
everything. Disarmed, every program traced/compiled today is traced
bit-identically (the plateau loop is host-side and the warm pipeline is
never entered) — pinned by tests/test_incremental.py.

The store below is process-wide (like ``scheduler.FLEET`` and the
tracer): the sidecar's Propose path, the facade's verbs and the bench all
share one map of device-resident converged placements.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time

from ccx.common.devmem import DEVMEM

#: env off-switch (the config key ``optimizer.incremental.enabled`` wins
#: when the facade set it explicitly; the env kills the subsystem outright
#: for bench/tools/subprocess paths)
ENV_INCREMENTAL = "CCX_INCREMENTAL"

#: relative band-pressure change that marks a broker "touched" by drift
#: (either direction, on any of the six pressure tables). 2 % of the
#: pressure scale: smaller than any drift worth re-optimizing for, large
#: enough that f32 noise never marks the whole cluster touched.
PRESSURE_RTOL = 0.02
PRESSURE_ATOL = 1e-3


def env_enabled() -> bool:
    """False when ``CCX_INCREMENTAL=0`` — the hard off-switch that
    restores today's cold-only behavior everywhere."""
    return os.environ.get(ENV_INCREMENTAL, "1") != "0"


@dataclasses.dataclass(frozen=True)
class IncrementalOptions:
    """Warm-path knobs (config ``optimizer.incremental.*``)."""

    #: master gate (``optimizer.incremental.enabled``); the env
    #: ``CCX_INCREMENTAL=0`` overrides True.
    enabled: bool = False
    #: usage-coupled swap-polish iterations of the warm run — the PRIMARY
    #: warm engine (``optimizer.incremental.warm.swap.iters``). Pure lex
    #: descent over pressure-ranked replica swaps + leadership transfers:
    #: it re-scores the band-pressure tables from the carried aggregates
    #: every iteration (O(B) — the delta-cache re-scoring), targets
    #: exactly the drift-touched cells, and can never regress the lex
    #: vector. 8 is the <500 ms budget at B5 on the banked host (~18
    #: ms/live-iteration there; the descent applies a disjoint batch per
    #: iteration, so 8 iterations land up to ~128 moves — a 1 % drift's
    #: usage-band damage — while 12 buys ~35 % more moves for ~70 ms;
    #: the warm-vs-cold quality tripwire in tests/test_incremental.py
    #: pins that this budget stays within tolerance of from-scratch).
    warm_swap_iters: int = 8
    #: consecutive no-improvement iterations before the warm swap polish
    #: stops (traced — the descent's own plateau rule)
    warm_swap_patience: int = 3
    #: total candidate pool of the warm swap polish, split evenly between
    #: replica-swap pairs and leadership transfers
    #: (``optimizer.incremental.warm.swap.candidates``). Smaller than the
    #: cold rung's 128: the applied disjoint batch saturates near 16
    #: moves/iteration well below that, and the warm wall scales with the
    #: pool (measured at B5 CPU: 64+64 ≈ +40 ms/iter vs 16+16 ≈ +13
    #: ms/iter at an identical applied-move count)
    warm_swap_candidates: int = 32
    #: SA step budget of the STRUCTURAL-damage warm path (dead brokers /
    #: disks in the drift window — repair + targeted SA before the swap
    #: polish); an upper bound, the plateau exit usually stops earlier
    #: (``optimizer.incremental.warm.steps``)
    warm_steps: int = 100
    #: steps per warm SA chunk — the plateau-decision granularity
    #: (``optimizer.incremental.warm.chunk.steps``). Its own (small)
    #: compiled chunk program, paid once and shared by every warm call.
    warm_chunk_steps: int = 25
    #: chains of the warm run (``optimizer.incremental.warm.chains``):
    #: warm starts are exploitation, not exploration — 2 keeps a spare
    #: diversity chain at ~1/8 the cost of the cold rung's 16
    warm_chains: int = 2
    #: proposals per chain step (``optimizer.incremental.warm.moves``)
    warm_moves_per_step: int = 8
    #: chunks without lex improvement before the warm SA drive stops
    #: (``optimizer.incremental.plateau.window``). Host data — retunes
    #: never recompile (pinned).
    plateau_window: int = 1
    #: warm-run initial temperature (soft-cost units): effectively pure
    #: descent — a converged placement is refined, never re-randomized,
    #: and a tiny budget must not net-accept Metropolis noise it has no
    #: budget to recover from (``optimizer.incremental.warm.t0``)
    warm_t0: float = 1e-8
    #: leadership-only greedy iterations after the warm engines (0 =
    #: skip; ``optimizer.incremental.warm.leader.iters``) — leader-bytes
    #: drift sometimes needs transfers the coupled draw misses
    warm_leader_iters: int = 0
    #: COUNT backstop on the process-wide placement store
    #: (``optimizer.incremental.max.sessions``). Warm bases are
    #: primarily BYTE-priced on the unified device-memory ledger
    #: (``ccx.common.devmem``, one budget with the snapshot registry,
    #: priority-aware eviction); this cap only bounds the session count
    #: on top.
    max_sessions: int = 32
    #: leadership-only warm profile (round 18): the facade's demote verb
    #: — its result may move LEADERSHIP ONLY, so (a) the warm base is
    #: usable only when its replica placement matches the live
    #: snapshot's (a base carrying unapplied replica moves would leak
    #: them into the verb's diff — documented ColdStartRequired
    #: otherwise) and (b) callers zero the swap engine and arm the
    #: leadership pass instead.
    leadership_only: bool = False

    @property
    def armed(self) -> bool:
        return self.enabled and env_enabled()


@dataclasses.dataclass
class WarmStart:
    """One session's last converged placement — the warm base.

    The placement arrays are DEVICE arrays taken by reference from the
    previous ``OptimizerResult.model`` (assignment ``int32[P, R]``,
    leader_slot ``int32[P]``, replica_disk ``int32[P, R]`` — ~12 MB at
    B5, two orders of magnitude below the snapshot model itself), plus
    the band-pressure vector banked as the drift delta-cache and the lex
    cost vector for quality accounting."""

    session: str
    generation: int
    assignment: object
    leader_slot: object
    replica_disk: object
    #: f32[6, B] DEVICE array — the six broker_pressure tables stacked,
    #: under the metrics the placement was optimized for (the delta
    #: cache). Banked async: ``remember`` dispatches the fused pressure
    #: program and never syncs; the first read is the next window's
    #: drift scan.
    pressure: object | None = None
    #: host tuple of the converged lex cost vector (reporting only)
    cost_vec: tuple = ()
    #: monotonic stamp for LRU eviction
    stamp: float = 0.0
    #: per-put install token (the ledger evictor's stale-callback guard
    #: — a callback that lost a race to a newer bank must not drop it)
    token: int = 0

    def shape_key(self) -> tuple:
        a = self.assignment
        return (tuple(a.shape), tuple(self.leader_slot.shape))


def warm_device_bytes(warm: WarmStart) -> int:
    """Device footprint of one warm base: the placement arrays plus the
    banked pressure stack (what actually sits in HBM per session)."""
    total = 0
    for a in (warm.assignment, warm.leader_slot, warm.replica_disk,
              warm.pressure):
        nb = getattr(a, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


class PlacementStore:
    """Process-wide device-resident placement registry, keyed by session.

    ``put`` keeps placements by reference (no copy, no transfer);
    ``get(session, base_generation)`` returns the stored placement only
    when the generation matches (None asks for the latest). Residency is
    BYTE-priced on the unified device-memory ledger
    (``ccx.common.devmem`` — one budget with the snapshot registry's
    device models, priority-aware eviction: an urgent job's base is
    never displaced by a dryrun admission), with ``max_sessions`` kept
    as a count backstop. An evicted session simply cold-starts on its
    next Propose (``ColdStartRequired`` with the reason on the result —
    the graceful-degradation contract; eviction is never an error)."""

    def __init__(self, max_sessions: int = 32, ledger=None) -> None:
        import weakref

        self._lock = threading.Lock()
        self._by_session: dict[str, WarmStart] = {}
        self.max_sessions = int(max_sessions)
        self._seq = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        #: the unified device-memory ledger (None = count-LRU only, the
        #: standalone/test construction path; the module :data:`STORE`
        #: shares the process-wide ``devmem.DEVMEM``)
        self._ledger = ledger
        self._ns = f"store{id(self):x}"
        self._self_ref = weakref.ref(self)
        if ledger is not None:
            # teardown hook: a dropped store must not leave phantom
            # bytes on a shared ledger — finalize releases this
            # instance's namespace at GC
            weakref.finalize(self, ledger.release_namespace, self._ns)

    def _ledger_key(self, session: str) -> str:
        return f"{self._ns}:{session}"

    def _ledger_evicted(self, key: str, token: int) -> None:
        """Ledger eviction callback: drop only this store's entry (the
        next warm Propose for the session cold-starts with the reason on
        the result — never a failed RPC). ``token`` is the install token
        the evicting entry was admitted for — a callback that lost a
        race to a NEWER bank of the same session must not drop it (its
        own ledger entry is already gone; the re-admit covers the new
        base)."""
        session = key.split(":", 1)[1]
        with self._lock:
            cur = self._by_session.get(session)
            if cur is not None and cur.token == token:
                del self._by_session[session]
                self.evictions += 1

    def put(self, warm: WarmStart, priority: int | None = None,
            job: str | None = None) -> None:
        count_victims: list[str] = []
        with self._lock:
            warm.stamp = time.monotonic()
            self._seq += 1
            warm.token = self._seq
            self._by_session[warm.session] = warm
            while len(self._by_session) > max(self.max_sessions, 1):
                victim = min(
                    self._by_session, key=lambda s: self._by_session[s].stamp
                )
                del self._by_session[victim]
                self.evictions += 1
                count_victims.append(victim)
        if self._ledger is None:
            return
        for victim in count_victims:
            self._ledger.release("warmBase", self._ledger_key(victim))
        ref = self._self_ref
        token = warm.token

        def _evict(key, _ref=ref, _token=token):
            s = _ref()
            if s is not None:
                s._ledger_evicted(key, _token)

        self._ledger.admit(
            "warmBase", self._ledger_key(warm.session),
            warm_device_bytes(warm), priority=priority,
            job=job or warm.session, evictor=_evict,
        )
        # close the install/admit race: a concurrent packing eviction
        # between the store write above and this admit popped the base —
        # the re-added ledger entry would account bytes that are no
        # longer resident
        with self._lock:
            cur = self._by_session.get(warm.session)
            resident = cur is not None and cur.token == token
        if not resident:
            self._ledger.release(
                "warmBase", self._ledger_key(warm.session)
            )

    def get(self, session: str, base_generation: int | None = None,
            priority: int | None = None,
            job: str | None = None) -> WarmStart | None:
        with self._lock:
            warm = self._by_session.get(session)
            if warm is None or (
                base_generation is not None
                and int(base_generation) != warm.generation
            ):
                self.misses += 1
                return None
            warm.stamp = time.monotonic()
            self.hits += 1
        if self._ledger is not None:
            # LRU-refresh on the ledger; the reader's job priority becomes
            # the entry's (the last user wins, in both directions) and the
            # reader's fleet-job label re-labels it for touch_job
            self._ledger.touch(
                "warmBase", self._ledger_key(session), priority=priority,
                job=job,
            )
        return warm

    def generation(self, session: str) -> int | None:
        with self._lock:
            warm = self._by_session.get(session)
            return None if warm is None else warm.generation

    def drop(self, session: str) -> None:
        with self._lock:
            had = self._by_session.pop(session, None) is not None
        if had and self._ledger is not None:
            self._ledger.release("warmBase", self._ledger_key(session))

    def clear(self) -> None:
        with self._lock:
            sessions = list(self._by_session)
            self._by_session.clear()
        if self._ledger is not None:
            for s in sessions:
                self._ledger.release("warmBase", self._ledger_key(s))

    def device_bytes(self) -> int:
        with self._lock:
            return sum(
                warm_device_bytes(w) for w in self._by_session.values()
            )

    def stats(self) -> dict:
        with self._lock:
            device_bytes = sum(
                warm_device_bytes(w) for w in self._by_session.values()
            )
            return {
                "sessions": len(self._by_session),
                "maxSessions": self.max_sessions,
                "deviceBytes": device_bytes,
                "ledger": self._ledger is not None,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }


#: the process-wide store (sidecar Propose path, facade verbs, bench) —
#: byte-priced on the unified device-memory ledger next to the snapshot
#: registry's device models (one budget, priority-aware eviction)
STORE = PlacementStore(ledger=DEVMEM)


def configure(max_sessions: int | None = None) -> None:
    """Config hook (``optimizer.incremental.max.sessions``)."""
    if max_sessions is not None and max_sessions > 0:
        STORE.max_sessions = int(max_sessions)


# ----- warm-base construction ------------------------------------------------


def remember(
    session: str, generation: int, model, cfg=None, pressure=None,
    priority: int | None = None, job: str | None = None,
) -> WarmStart:
    """Bank a converged result as the session's warm base: placement
    arrays by reference, plus the band-pressure delta cache (one jitted
    aggregate pass over the model). The pressure program is DISPATCHED
    here but never synced — the bank stays a device array and the first
    read happens at the next window's drift scan, long after the device
    finished. A blocking bank was ~116 ms of the measured warm wall at
    B5 on CPU; the async one is ~5 ms of dispatch — and a warm result
    carries the bank precomputed (``OptimizerResult.warm_pressure``, the
    fused ``warm_finish`` program's second output): pass it as
    ``pressure`` and this banks with ZERO extra device work. Called by
    the sidecar / facade / bench after every successful proposal for the
    session."""
    cost = ()
    if pressure is None:
        try:
            pressure = _pressure_stack(model, cfg)
        except Exception:  # noqa: BLE001 — the delta cache is an
            pressure = None  # optimization, never a correctness dependency
    warm = WarmStart(
        session=str(session),
        generation=int(generation),
        assignment=model.assignment,
        leader_slot=model.leader_slot,
        replica_disk=model.replica_disk,
        pressure=pressure,
        cost_vec=cost,
    )
    # BANK-LAST (ISSUE 12): the store write is the final, atomic step —
    # any failure up to here (including the injected one) leaves the
    # session's PREVIOUS base intact and generation-consistent, so the
    # next warm Propose either resolves the old base or cold-starts; a
    # partially-built warm base is never visible. The chaos seam sits
    # exactly at the commit point.
    from ccx.common.faults import FAULTS

    if FAULTS.armed:
        FAULTS.hit("placement.bank")
    # ``priority`` (the banking job's fleet priority — explicit from the
    # sidecar, ambient from a facade verb's FLEET.job context) prices the
    # base on the unified device-memory ledger: an urgent job's base is
    # protected from lower-priority admissions until a later normal-
    # priority use demotes it. ``job`` (the fleet cluster id, when it
    # differs from the session) labels the entry so the scheduler's
    # touch_job admission hook matches.
    STORE.put(warm, priority=priority, job=job)
    return warm


#: module-level jitted pressure programs (ONE compile per model shape —
#: a per-call jax.jit wrapper would recompile every time)
_PRESSURE_JIT = None
_TOUCHED_JIT = None


def _pressure_stack(model, cfg):
    """f32[6, B] DEVICE array: the six ``broker_pressure`` tables of a
    model under its own metrics, as one fused jitted program (aggregate
    pass + band math + stack). Async by design — callers that only bank
    it never sync."""
    global _PRESSURE_JIT

    from ccx.goals.base import GoalConfig

    if _PRESSURE_JIT is None:
        import functools

        import jax
        import jax.numpy as jnp

        from ccx.common import costmodel
        from ccx.model.aggregates import broker_aggregates
        from ccx.search.state import broker_pressure

        @costmodel.instrument("pressure-scan")
        @functools.partial(jax.jit, static_argnames=("cfg",))
        def _stack(m, *, cfg):
            p = broker_pressure(m, broker_aggregates(m), cfg=cfg)
            return jnp.stack(
                (p.usage_over, p.usage_under, p.lead_over, p.lead_under,
                 p.lbi_over, p.lbi_under)
            )

        _PRESSURE_JIT = _stack
    return _PRESSURE_JIT(model, cfg=cfg or GoalConfig())


#: module-level jitted warm programs (ONE compile per model shape each).
#: ``_warm_init``: the fused first half of a metrics-only warm window —
#: full broker aggregates computed ONCE and shared by (a) the descent
#: engine's starting SearchState, (b) the exact stack evaluation of the
#: warm base under the new metrics (its hard-violation count is the
#: structural-path gate, replacing the separate hot-list sync), (c) the
#: band-pressure stack of the drift scan and (d) the touched-band mask
#: against the banked delta cache. Before the fusion every one of those
#: consumers paid its own aggregate pass — ~290 ms of the measured warm
#: wall at B5 on CPU collapsed to ~105 ms.
#: ``_warm_finish``: the fused second half — ONE aggregate pass over the
#: final (canonicalized) placement yields the exact result stack AND the
#: band-pressure stack banked as the next window's delta cache, so
#: ``remember`` never dispatches its own pressure program on the warm
#: path.
_WARM_INIT_JIT = None
_WARM_FINISH_JIT = None


def _press6(p):
    import jax.numpy as jnp

    return jnp.stack(
        (p.usage_over, p.usage_under, p.lead_over, p.lead_under,
         p.lbi_over, p.lbi_under)
    )


def _warm_init_program():
    global _WARM_INIT_JIT
    if _WARM_INIT_JIT is None:
        import functools

        import jax
        import jax.numpy as jnp

        from ccx.common import costmodel
        from ccx.goals.stack import _evaluate
        from ccx.model.aggregates import broker_aggregates
        from ccx.search.state import (
            broker_pressure,
            init_search_state,
            make_topic_group,
            stack_needs_topic,
        )

        @costmodel.instrument("warm-init")
        @functools.partial(
            jax.jit,
            static_argnames=("cfg", "goal_names", "max_pt", "has_banked"),
        )
        def _init(m, banked, key, *, cfg, goal_names, max_pt, has_banked):
            agg = broker_aggregates(m)
            stack = _evaluate(m, agg, cfg, goal_names)
            press = _press6(broker_pressure(m, agg, cfg))
            if has_banked:
                delta = jnp.abs(press - banked)
                tol = PRESSURE_ATOL + PRESSURE_RTOL * jnp.maximum(
                    jnp.abs(banked), jnp.abs(press)
                )
                mask = jnp.any(delta > tol, axis=0)
            else:
                # no comparable cache: every band re-scored (safe default)
                mask = jnp.ones(press.shape[1], bool)
            group = (
                make_topic_group(m, max_pt)
                if stack_needs_topic(goal_names)
                else None
            )
            state0 = init_search_state(
                m, cfg, goal_names, key, group=group, agg=agg
            )
            return state0, stack, press, mask, jnp.sum(mask).astype(jnp.int32)

        _WARM_INIT_JIT = _init
    return _WARM_INIT_JIT


def warm_finish(model, cfg, goal_names: tuple[str, ...]):
    """(exact StackResult, f32[6, B] pressure stack) of a final placement
    as ONE fused program — the result evaluation and the next window's
    delta-cache bank share a single aggregate pass."""
    global _WARM_FINISH_JIT
    if _WARM_FINISH_JIT is None:
        import functools

        import jax

        from ccx.common import costmodel
        from ccx.goals.stack import _evaluate
        from ccx.model.aggregates import broker_aggregates
        from ccx.search.state import broker_pressure

        @costmodel.instrument("warm-finish")
        @functools.partial(
            jax.jit, static_argnames=("cfg", "goal_names")
        )
        def _finish(m, *, cfg, goal_names):
            agg = broker_aggregates(m)
            return (
                _evaluate(m, agg, cfg, goal_names),
                _press6(broker_pressure(m, agg, cfg)),
            )

        _WARM_FINISH_JIT = _finish
    return _WARM_FINISH_JIT(model, cfg=cfg, goal_names=tuple(goal_names))


def _touched_mask(new, old):
    """(bool[B] mask, i32 count) DEVICE arrays: bands whose pressure
    moved beyond the asymmetric tolerance between two pressure stacks.
    Jitted and non-blocking — the common (metrics-only) warm path reads
    the count only when the info block is assembled, after the warm
    engines already ran."""
    global _TOUCHED_JIT

    if _TOUCHED_JIT is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def _cmp(new, old):
            delta = jnp.abs(new - old)
            tol = PRESSURE_ATOL + PRESSURE_RTOL * jnp.maximum(
                jnp.abs(old), jnp.abs(new)
            )
            mask = jnp.any(delta > tol, axis=0)
            return mask, jnp.sum(mask).astype(jnp.int32)

        _TOUCHED_JIT = _cmp
    return _TOUCHED_JIT(new, old)


#: module-level jitted placement-merge program (ONE compile per shape,
#: paid at prewarm like the other warm programs)
_MERGE_JIT = None


def _merge_program():
    global _MERGE_JIT
    if _MERGE_JIT is None:
        import jax
        import jax.numpy as jnp

        from ccx.common import costmodel

        @costmodel.instrument("warm-merge")
        @jax.jit
        def _merge(new_a, new_ls, new_rd, wa, wls, wrd):
            base_has = (wa >= 0).any(axis=1)
            return (
                jnp.where(base_has[:, None], wa, new_a),
                jnp.where(base_has, wls, new_ls),
                jnp.where(base_has[:, None], wrd, new_rd),
            )

        _MERGE_JIT = _merge
    return _MERGE_JIT


def warm_model(m_new, warm: WarmStart):
    """The new snapshot's metric/topology tensors with the previous
    converged placement grafted on — array replacements, never a model
    rebuild. None when the padded shapes disagree (topology changed
    enough that the warm placement is meaningless — callers cold-start).

    Elasticity merge (round 18, the scenario corpus): rows where the
    warm base holds NO replicas but the new snapshot does are partitions
    CREATED since the base was banked (a partition-count change, arxiv
    2205.09415's production event) — they keep the snapshot's
    controller placement instead of arriving empty, so an elastic window
    stays a warm window (the drift scan sees the new partitions' bands
    as touched and the warm engines re-balance them). One tiny fused
    device program; for a pure metrics window the merge is the identity
    on the warm arrays."""
    if tuple(m_new.assignment.shape) != tuple(warm.assignment.shape) or (
        tuple(m_new.leader_slot.shape) != tuple(warm.leader_slot.shape)
    ):
        return None
    a, ls, rd = _merge_program()(
        m_new.assignment, m_new.leader_slot, m_new.replica_disk,
        warm.assignment, warm.leader_slot, warm.replica_disk,
    )
    return m_new.replace(assignment=a, leader_slot=ls, replica_disk=rd)


# ----- drift scan: touched bands -> targeted hot list ------------------------


def touched_brokers(warm: WarmStart, model, cfg=None):
    """bool[B] numpy mask of brokers whose band pressure moved beyond
    tolerance between the banked delta cache and the same placement under
    the NEW metrics — the "touched bands" the warm run re-scores. All-True
    when no cache was banked (every band re-scored: the safe default)."""
    import numpy as np

    new = _pressure_stack(model, cfg)
    if warm.pressure is None or tuple(warm.pressure.shape) != tuple(new.shape):
        return np.ones(new.shape[1], bool), new
    mask, _count = _touched_mask(new, warm.pressure)
    return np.asarray(mask), new


def drift_hot_list(model, touched, goal_names: tuple[str, ...], cfg):
    """The warm run's targeted hot list: structural offenders (the
    device hot list — dead brokers/disks, rack duplicates, capacity)
    UNIONED with partitions holding a replica on a touched broker, padded
    to the shared ``_evac_bucket`` size so the warm chunk program keys on
    the same operand shape as every other engine. Returns
    ``(evac int32[bucket], n_evac, n_structural)`` — ``n_structural`` > 0
    means the warm base is infeasible and the caller must repair."""
    import jax.numpy as jnp
    import numpy as np

    from ccx.search.annealer import (
        _evac_bucket,
        hot_partition_list_device,
    )

    evac_s, n_s = hot_partition_list_device(
        model, goal_names=goal_names, cfg=cfg
    )
    n_structural = int(n_s)
    touched = np.asarray(touched)
    a = np.asarray(model.assignment)
    pvalid = np.asarray(model.partition_valid)
    B = model.B
    on_touched = (
        ((a >= 0) & touched[np.clip(a, 0, B - 1)]).any(axis=1) & pvalid
    )
    drift_idx = np.nonzero(on_touched)[0]
    if n_structural:
        drift_idx = np.union1d(
            drift_idx, np.asarray(evac_s)[:n_structural]
        )
    bucket = _evac_bucket(model.P)
    if len(drift_idx) > bucket:
        # over-full drift set: keep the structural offenders and an even
        # subsample of the rest — targeting is a bias, not a correctness
        # gate (acceptance still vets every move)
        keep = drift_idx[:: (len(drift_idx) + bucket - 1) // bucket]
        drift_idx = keep[:bucket]
    out = np.zeros(bucket, np.int32)
    out[: len(drift_idx)] = drift_idx.astype(np.int32)
    return (
        jnp.asarray(out),
        jnp.asarray(len(drift_idx), jnp.int32),
        n_structural,
    )


# ----- the warm pipeline -----------------------------------------------------


def warm_anneal_options(iopts: IncrementalOptions, base_anneal):
    """The warm run's AnnealOptions: the cold rung's proposal mix with a
    short traced budget, low temperature, boosted hot-list draw and the
    plateau exit armed. Chunk size/chains are the only new program shapes
    (one compile each, shared by every warm call)."""
    return dataclasses.replace(
        base_anneal,
        n_chains=max(iopts.warm_chains, 1),
        n_steps=max(iopts.warm_steps, 1),
        moves_per_step=max(iopts.warm_moves_per_step, 1),
        chunk_steps=max(iopts.warm_chunk_steps, 1),
        t0=iopts.warm_t0,
        t1=min(base_anneal.t1, iopts.warm_t0),
        p_evac=0.5,
        plateau_window=max(iopts.plateau_window, 1),
    )


def reoptimize(
    m,
    warm: WarmStart,
    cfg,
    goal_names: tuple[str, ...],
    iopts: IncrementalOptions,
    base_opts,
    phase=None,
    tally=None,
):
    """The warm pipeline body (called by ``ccx.optimizer.optimize`` under
    its root span; ``phase`` is the optimizer's phase context manager,
    ``tally`` its move-counter/convergence accumulator).

    Two paths share it. The COMMON path (metrics-only drift) runs ONE
    fused init program (``_warm_init``: descent state + exact base stack
    + pressure scan + touched mask off a single aggregate pass) and ONE
    engine: the usage-coupled swap polish — pure lex descent that
    re-scores the band-pressure tables from its carried aggregates every
    iteration, so the drift-touched cells are targeted without any [P]
    re-scan. The result stack is DEFERRED: the caller canonicalizes
    preferred leaders first, then evaluates the final placement once via
    ``warm_finish`` (which also yields the pressure bank). The
    STRUCTURAL path (the base stack's hard tier is non-zero — a broker/
    disk died inside the drift window, or drift overflowed a capacity)
    first repairs and runs a short plateau-terminated warm SA over the
    targeted hot list — slower by construction, correctness first.

    Returns ``(model, stack_before, stack_after, search_result, info,
    base_model, bank_pressure, n_engine_moves)`` — ``stack_after`` is
    None on the common path (the caller runs ``warm_finish`` after
    canonicalization); ``bank_pressure`` is the f32[6, B] delta cache to
    ``remember`` (None when the final placement was not the one
    scanned); ``n_engine_moves`` counts applied swap-polish + leadership
    moves across every engine that ran (``OptimizerResult.
    n_polish_moves``). ``info`` is the ``OptimizerResult.incremental``
    block. Raises ``ColdStartRequired``
    when the warm base cannot be applied (shape mismatch): the caller
    falls back to the cold pipeline."""
    import contextlib

    import jax
    import numpy as np

    from ccx.search.annealer import anneal, allows_inter_broker
    from ccx.search.greedy import SwapPolishOptions, swap_polish

    nullphase = contextlib.nullcontext

    def _phase(name, **attrs):
        return phase(name, **attrs) if phase is not None else nullphase()

    with _phase("warm-model"):
        wm = warm_model(m, warm)
        if wm is None:
            raise ColdStartRequired(
                f"shape mismatch: snapshot {tuple(m.assignment.shape)} vs "
                f"warm base {warm.shape_key()[0]}"
            )
        if iopts.leadership_only:
            # a leadership-only verb (demote) may only inherit a base
            # whose REPLICA placement matches the live snapshot — a base
            # carrying unapplied replica moves would leak them into a
            # diff contractually restricted to leadership transfers.
            # (After the shape gate above, so a topology change reports
            # as the shape mismatch it is, not as unapplied moves.)
            import jax.numpy as jnp

            same = bool(
                jnp.array_equal(m.assignment, warm.assignment)
            ) and bool(
                jnp.array_equal(m.replica_disk, warm.replica_disk)
            )
            if not same:
                raise ColdStartRequired(
                    "leadership-only verb: warm base replica placement "
                    "differs from the live snapshot (unapplied moves) — "
                    "inheriting it would move replicas"
                )

    run_swap = iopts.warm_swap_iters > 0 and allows_inter_broker(goal_names)
    ksw = max(iopts.warm_swap_candidates // 2, 1)
    spo = SwapPolishOptions(
        n_swap_candidates=ksw,
        n_lead_candidates=max(iopts.warm_swap_candidates - ksw, 0),
        max_iters=iopts.warm_swap_iters,
        patience=max(iopts.warm_swap_patience, 1),
        trd_guard=base_opts.swap_polish_guarded,
        chunk_iters=max(iopts.warm_swap_iters, 1),
    )

    with _phase("drift-scan"):
        # the fused init program: ONE aggregate pass yields the descent
        # state, the exact stack of the warm base under the NEW metrics,
        # the band-pressure stack and the touched mask vs the banked
        # delta cache. Its hard-violation count is the structural-path
        # gate (the stack's StructuralFeasibility tier covers dead
        # brokers/disks, rack breaks and capacity overflows — the same
        # offenses the hot list scans for), so the common path pays
        # exactly one sync here and no separate hot-list program.
        from ccx.search.state import max_partitions_per_topic

        has_banked = warm.pressure is not None and tuple(
            warm.pressure.shape
        ) == (6, int(wm.B))
        state0, stack_before, new_pressure, touched_dev, touched_n = (
            _warm_init_program()(
                wm,
                warm.pressure if has_banked else None,
                jax.random.PRNGKey(spo.seed),
                cfg=cfg,
                goal_names=tuple(goal_names),
                max_pt=max_partitions_per_topic(wm),
                has_banked=has_banked,
            )
        )
        structural = float(stack_before.hard_violations) > 0
        evac = n_evac = None
        n_offenders = 0
        if structural:
            # structural damage: the targeted hot list (structural
            # offenders ∪ drift-touched partitions) feeds the warm SA —
            # the rare path pays the extra scan + sync
            touched = np.asarray(touched_dev)
            evac, n_evac, n_offenders = drift_hot_list(
                wm, touched, goal_names, cfg
            )

    def _touched_count():
        if not has_banked:
            # no comparable cache banked: every band was re-scored
            return int(wm.B)
        return int(np.asarray(touched_n))

    sa = None
    if structural:
        # hard damage in the drift window (dead broker/disk, rack break,
        # capacity overflow): repair + a short plateau-terminated warm SA
        # over the targeted hot list re-establish feasibility before the
        # polish — the cold pipeline's contract, at warm budgets. Slower
        # than the metrics-only path by construction.
        from ccx.search.repair import hard_repair

        with _phase("repair", backend=base_opts.repair_backend):
            wm, _n_rep = hard_repair(
                wm, cfg, goal_names, backend=base_opts.repair_backend
            )
        aopts = warm_anneal_options(iopts, base_opts.anneal)
        with _phase(
            "anneal",
            chains=aopts.n_chains,
            steps=aopts.n_steps,
            chunkSteps=aopts.chunk_steps,
            warm=True,
        ):
            sa = anneal(wm, cfg, goal_names, aopts, evac=(evac, n_evac))
        if tally is not None:
            tally(sa, "anneal")
        # the repaired-and-annealed placement becomes the warm base the
        # revert guard protects (never revert INTO infeasibility)
        wm = sa.model
        stack_before = sa.stack_before
        model = sa.model
        stack_after = sa.stack_after
    else:
        model = wm
        stack_after = None

    search = sa
    n_engine_moves = 0
    if run_swap:
        # the primary warm engine (module docstring): coupled swap pairs
        # + leadership transfers, lex-descent only. Candidate shape
        # matches the cold pipeline's swap-polish program split; the
        # chunk size is the warm budget itself (one small chunk program,
        # compiled once, shared by every warm call). The common path
        # hands the fused init's (state0, stack_before) in and DEFERS
        # the result stack (the caller evaluates once, after preferred-
        # leader canonicalization); the structural path re-inits from
        # the repaired placement but defers the same way.
        with _phase("swap-polish", iters=iopts.warm_swap_iters, warm=True):
            sp = swap_polish(
                model, cfg, goal_names, spo,
                init=None if structural else (state0, stack_before),
                defer_stack_after=True,
            )
        if tally is not None:
            tally(sp, "swap-polish")
        model = sp.model
        stack_after = None
        search = search or sp
        n_engine_moves += int(getattr(sp, "n_moves", 0))
    bank_pressure = None
    if not structural and not run_swap:
        # every engine disabled (warm_swap_iters=0 on a soft window):
        # the proposal is the base itself — already evaluated by the
        # fused init, whose pressure stack doubles as the next bank
        stack_after = stack_before
        bank_pressure = new_pressure

    n_lead = 0
    if iopts.warm_leader_iters > 0:
        import dataclasses as _dc

        from ccx.search.greedy import greedy_optimize

        with _phase("leader-pass", iters=iopts.warm_leader_iters):
            lead = greedy_optimize(
                model, cfg, goal_names,
                _dc.replace(
                    base_opts.polish,
                    leadership_only=True,
                    max_iters=iopts.warm_leader_iters,
                ),
            )
            if tally is not None:
                tally(lead, "leader-pass")
            model = lead.model
            n_lead = int(lead.n_moves)
            n_engine_moves += n_lead
            if n_lead:
                # leadership moved off the placement the pending stack /
                # pressure bank were scored on: defer both to the
                # caller's fused warm-finish over the FINAL model — a
                # bank scanned before these moves would misread the next
                # window's leadership bands as fresh drift
                stack_after = None
                bank_pressure = None
            else:
                stack_after = lead.stack_after

    info = {
        "warmStart": True,
        "coldStart": False,
        "session": warm.session,
        "baseGeneration": warm.generation,
        "touchedBrokers": _touched_count(),
        "driftPartitions": None if n_evac is None else int(n_evac),
        "structuralOffenders": int(n_offenders),
        "swapIters": iopts.warm_swap_iters,
        "plateau": sa.plateau if sa is not None else None,
        "leaderMoves": n_lead,
    }
    # the revert guard (never ship a warm result lexicographically behind
    # its own repaired base) lives in the CALLER (_optimize_warm): with
    # the result stack deferred past preferred-leader canonicalization,
    # the guard can only run once the final stack exists.
    return (model, stack_before, stack_after, search, info, wm,
            bank_pressure, n_engine_moves)


def _significantly_lex_worse(after, before) -> bool:
    """True when ``after``'s (hard-violations, cost-vector) key is
    significantly lexicographically worse than ``before``'s, under the
    convergence module's asymmetric tolerances."""
    import numpy as np

    from ccx.common.convergence import lex_improved

    ka = (float(after.hard_violations),) + tuple(
        float(x) for x in np.asarray(after.costs)
    )
    kb = (float(before.hard_violations),) + tuple(
        float(x) for x in np.asarray(before.costs)
    )
    return lex_improved(kb, ka)


class ColdStartRequired(Exception):
    """The warm base cannot be applied to this snapshot (e.g. padded-shape
    mismatch after a topology change) — fall back to the cold pipeline."""
