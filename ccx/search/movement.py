"""Movement planning — a device-scheduled execution plan on every proposal.

A proposal's real-world cost is not its final placement but the bytes it
moves and how long the cluster stays degraded while they move. Today the
executor batches the columnar diff with a naive host greedy under fixed
per-broker caps (``ExecutionTaskPlanner.inter_broker_batch``); this module
turns the same diff into **execution waves** — a throttle-respecting
schedule computed where the diff already lives (on device), surfaced on
``OptimizerResult.plan`` and consumed by the executor (wave = batch).

Two planning products:

* ``movement_cost(before, after)`` — the movement-cost tier for the lex
  objective: (total bytes moved, peak per-broker inbound bytes), computed
  from the same assignment tensors the columnar diff masks. Gated by
  ``optimizer.plan.cost.tier``; when the gate is off this module is never
  imported on the hot path (bit-exact, zero new recompile classes).

* ``plan_movement(diff, ...)`` — the wave planner: orders the diff rows
  into waves under per-broker concurrent-move caps (mirroring
  ``ExecutionConcurrencyManager``'s per-broker cap) and per-wave
  per-broker byte budgets (mirroring ``ReplicationThrottleHelper``'s
  replication throttle), greedily minimizing makespan and peak inflow:
  rows in largest-bytes-first (LPT) order, each placed by the
  lexicographic wave rule in ``_plan_numpy`` — avoid raising the
  schedule-wide peak inflow, then least bottleneck growth, then lowest
  resulting destination inflow, earliest wave on full ties. The
  compiled device program and the numpy reference oracle implement the
  SAME deterministic greedy (bit-identical wave assignments,
  test-pinned); any device surprise degrades to the oracle — a plan must
  never fail a proposal.

Scheduling unit = one diff ROW (partition): ``alter_partition_reassignments``
starts every destination replica of a partition fetching at once, so the
executor cannot start a partition's destinations in different waves.
A row's cost is its per-replica disk footprint (the DISK resource row is
role-independent — ``model/tensor_model.py``); each destination broker
receives that many bytes, each vacated source broker sends them.

Both the planned schedule and the naive executor baseline are priced
under the same round-barrier fluid model: a wave/batch completes before
the next starts, and its duration is the slowest broker's
``max(inbound, outbound) / throttle_rate``. That is the executor's
worst-case poll-loop behavior and makes planned-vs-naive makespans
directly comparable (bench.py --plan banks the A/B).
"""

from __future__ import annotations

import dataclasses
import functools
import os

import numpy as np

#: env override: ``CCX_DEVICE_PLAN=0`` routes every plan through the host
#: numpy oracle; ``=1`` forces the compiled device program regardless of
#: diff size; unset applies the size gate below
ENV_DEVICE_PLAN = "CCX_DEVICE_PLAN"

#: diff-row floor for the device planner by default: below it the numpy
#: oracle finishes in milliseconds and a compile is pure loss (mirrors
#: ``ccx.proposals.DEVICE_DIFF_MIN_P`` rationale — test fixtures touch
#: dozens of tiny shapes; serving diffs bucket to a handful of big ones)
DEVICE_PLAN_MIN_ROWS = 4096

#: floor of the padded-row compile bucket (pow2 bucketing, one compiled
#: program per bucket — a fluctuating warm drift-diff size must never
#: recompile mid-steady-loop)
PLAN_ROWS_FLOOR = 1024


def _pow2_ceil(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


@dataclasses.dataclass(frozen=True)
class PlanOptions:
    """Wave-planner knobs (config ``optimizer.plan.*``).

    ``broker_cap`` mirrors ``num.concurrent.partition.movements.per.
    broker`` (a broker participates in at most this many concurrent
    partition movements per wave, as source or destination).
    ``wave_bytes`` is the per-broker per-wave byte budget in model load
    units (MB) — the replication-throttle image: at throttle rate R and a
    target wave duration T, set ``wave_bytes ≈ R*T``; <=0 = uncapped
    (count caps only). ``throttle_mb_per_sec`` prices the projected wave
    durations; <=0 reports makespan in relative byte units (rate 1)."""

    broker_cap: int = 5
    wave_bytes: float = 0.0
    max_waves: int = 64
    throttle_mb_per_sec: float = 0.0
    #: None = env/size gate; "numpy"/"device" force a path
    backend: str | None = None


@dataclasses.dataclass
class MovementPlan:
    """A scheduled execution plan over one columnar diff.

    ``wave`` is ALIGNED with the diff's row order (``wave[i]`` schedules
    diff row i) — the executor's tasks are built from the same rows, so
    consumption is an O(1) lookup per task. Rows with no inter-broker
    movement (pure leadership / intra-broker disk rows) carry wave 0 and
    zero scheduled bytes."""

    wave: np.ndarray              #: int32[N], aligned with diff rows
    partition: np.ndarray         #: int32[N], the diff's partition column
    moves: np.ndarray             #: int32[N] replicas entering new brokers
    move_bytes: np.ndarray        #: float32[N] bytes per moving replica
    wave_bytes: np.ndarray        #: float32[W] total bytes entering per wave
    wave_inflow_peak: np.ndarray  #: float32[W] max per-broker inbound bytes
    wave_outflow_peak: np.ndarray  #: float32[W] max per-broker outbound bytes
    n_waves: int
    #: rows that fit no feasible wave and were forced into the last one
    #: (max_waves too small for the diff at these caps)
    overflow_rows: int
    backend: str
    opts: PlanOptions

    _wave_of: dict | None = dataclasses.field(
        default=None, repr=False, compare=False
    )

    # ----- derived metrics --------------------------------------------------

    @property
    def n_moves(self) -> int:
        return int(self.moves.sum())

    @property
    def bytes_moved(self) -> float:
        return float(self.wave_bytes.sum())

    @property
    def peak_inflow(self) -> float:
        """Max per-broker inbound bytes of any single wave — the
        concurrent-inflow pressure the schedule ever puts on one broker."""
        return float(self.wave_inflow_peak.max(initial=0.0))

    @property
    def wave_seconds(self) -> np.ndarray:
        """Projected duration per wave under the round-barrier fluid
        model: the slowest broker's max(in, out) bytes over the throttle
        rate (rate <= 0 → relative byte units)."""
        rate = self.opts.throttle_mb_per_sec
        peak = np.maximum(self.wave_inflow_peak, self.wave_outflow_peak)
        return peak / np.float32(rate if rate > 0 else 1.0)

    @property
    def makespan_seconds(self) -> float:
        return float(self.wave_seconds.sum())

    def wave_of(self, partition: int) -> int | None:
        """Wave index for a dense partition index (None = not in plan)."""
        if self._wave_of is None:
            self._wave_of = dict(
                zip(self.partition.tolist(), self.wave.tolist())
            )
        return self._wave_of.get(partition)

    # ----- serialization ----------------------------------------------------

    def summary_json(self) -> dict:
        """The additive ``plan`` result block: scalars + per-wave profile
        (never the per-row arrays — those ride the columnar wire blob)."""
        return {
            "nWaves": int(self.n_waves),
            "nMoves": self.n_moves,
            "bytesMoved": round(self.bytes_moved, 3),
            "peakInflowMb": round(self.peak_inflow, 3),
            "makespanSeconds": round(self.makespan_seconds, 3),
            "overflowRows": int(self.overflow_rows),
            "backend": self.backend,
            "brokerCap": int(self.opts.broker_cap),
            "waveBytesBudgetMb": float(self.opts.wave_bytes),
            "throttleMbPerSec": float(self.opts.throttle_mb_per_sec),
            "waveBytesMb": [round(float(x), 3) for x in self.wave_bytes],
            "waveInflowPeakMb": [
                round(float(x), 3) for x in self.wave_inflow_peak
            ],
            "waveSeconds": [round(float(x), 3) for x in self.wave_seconds],
        }

    def wire_cols(self) -> dict[str, np.ndarray]:
        """The flat typed arrays for the columnar result path (wire round
        20, ``planColumnar``): the row-aligned wave/partition columns plus
        the per-wave profiles, ``pack_arrays``-ready."""
        return {
            "wave": self.wave.astype(np.int32),
            "partition": self.partition.astype(np.int32),
            "moves": self.moves.astype(np.int32),
            "moveBytes": self.move_bytes.astype(np.float32),
            "waveBytes": self.wave_bytes.astype(np.float32),
            "waveInflowPeak": self.wave_inflow_peak.astype(np.float32),
            "waveOutflowPeak": self.wave_outflow_peak.astype(np.float32),
        }


# ----- movement-cost tier ----------------------------------------------------


def _cost_numpy(a0, a1, pvalid, bytes_pp, B: int):
    a0 = np.asarray(a0)
    a1 = np.asarray(a1)
    member = (a1[:, :, None] == a0[:, None, :]).any(axis=2)
    dst = (a1 >= 0) & ~member & np.asarray(pvalid)[:, None]
    b = np.where(dst, np.asarray(bytes_pp, np.float32)[:, None], np.float32(0))
    inflow = np.zeros(B, np.float32)
    np.add.at(inflow, np.clip(a1, 0, B - 1).reshape(-1), b.reshape(-1))
    return float(b.sum(dtype=np.float64)), float(inflow.max(initial=0.0))


_COST_PROGRAM = None


def _cost_program():
    global _COST_PROGRAM
    if _COST_PROGRAM is not None:
        return _COST_PROGRAM
    import jax
    import jax.numpy as jnp

    from ccx.common import costmodel

    @costmodel.instrument("plan-movement-cost")
    @functools.partial(jax.jit, static_argnames=("B",))
    def _cost(a0, a1, pvalid, bytes_pp, *, B):
        member = (a1[:, :, None] == a0[:, None, :]).any(axis=2)
        dst = (a1 >= 0) & ~member & pvalid[:, None]
        b = jnp.where(dst, bytes_pp[:, None], jnp.float32(0))
        inflow = jnp.zeros((B,), jnp.float32).at[
            jnp.clip(a1, 0, B - 1).reshape(-1)
        ].add(b.reshape(-1))
        return b.sum(), inflow.max()

    _COST_PROGRAM = _cost
    return _cost


def movement_cost(before, after, backend: str | None = None):
    """The movement-cost lex tier for a candidate placement: ``(bytes
    moved, peak per-broker inbound bytes)`` of ``before -> after``, from
    the same assignment tensors the columnar diff masks. Device-computed
    at serving scale (same ``DEVICE_DIFF_MIN_P``-style gate as the diff),
    numpy reference below it; any device surprise degrades to numpy."""
    from ccx.common.resources import Resource

    B = int(before.B)
    bytes_pp = before.leader_load[Resource.DISK]
    if backend is None:
        env = os.environ.get(ENV_DEVICE_PLAN)
        if env == "0":
            backend = "numpy"
        elif env == "1":
            backend = "device"
        else:
            from ccx.proposals import DEVICE_DIFF_MIN_P

            backend = (
                "device" if int(before.P) >= DEVICE_DIFF_MIN_P else "numpy"
            )
    if backend == "device":
        try:
            bm, pk = _cost_program()(
                before.assignment, after.assignment,
                before.partition_valid, bytes_pp, B=B,
            )
            return float(bm), float(pk)
        except Exception:  # noqa: BLE001 — degrade to the host reference
            import logging

            logging.getLogger(__name__).exception(
                "device movement_cost failed; falling back to numpy"
            )
    return _cost_numpy(
        np.asarray(before.assignment), np.asarray(after.assignment),
        np.asarray(before.partition_valid), np.asarray(bytes_pp), B,
    )


# ----- wave planner ----------------------------------------------------------


def _prepare(cols: dict, bytes_pp: np.ndarray | None):
    """Host-side planning inputs from the diff columns: per-row source /
    destination broker slots (-1 pad), per-replica bytes, and the
    deterministic processing order (largest-bytes-first, partition-index
    tie-break — the LPT rule both backends replay identically)."""
    old = np.asarray(cols["oldReplicas"], np.int32)
    new = np.asarray(cols["newReplicas"], np.int32)
    part = np.asarray(cols["partition"], np.int32)
    if old.size == 0:
        z = np.zeros((0,), np.int32)
        return z.reshape(0, 1), z.reshape(0, 1), np.zeros(0, np.float32), z
    in_old = (new[:, :, None] == old[:, None, :]).any(axis=2)
    in_new = (old[:, :, None] == new[:, None, :]).any(axis=2)
    dst = np.where((new >= 0) & ~in_old, new, -1).astype(np.int32)
    src = np.where((old >= 0) & ~in_new, old, -1).astype(np.int32)
    if bytes_pp is not None:
        b = np.asarray(bytes_pp, np.float32)[part]
    else:
        b = np.ones(part.shape[0], np.float32)
    # rows with no inter-broker movement cost nothing and pin to wave 0
    b = np.where((dst >= 0).any(axis=1), b, np.float32(0)).astype(np.float32)
    order = np.lexsort((part, -b)).astype(np.int32)
    return src, dst, b, order


def _plan_numpy(src, dst, b, order, W: int, B: int, cap: int, budget: float):
    """The reference greedy (the correctness pin): for each row in LPT
    order, among the waves where every involved broker is below the
    concurrent-move cap and the row's bytes fit the per-broker byte
    budget (a broker with nothing scheduled in a wave always admits one
    row, so an over-budget single row still schedules), pick the wave
    whose round-barrier bottleneck — ``max_b max(in, out)`` — grows the
    LEAST, earliest wave on ties. That is LPT least-loaded packing: big
    rows land first where they raise no wave's duration, which minimizes
    the fluid-model makespan AND spreads concurrent inflow instead of
    piling the largest rows onto one broker's wave-0 cap. No feasible
    wave → the last wave, counted as overflow. float32 accumulation
    throughout; cross-broker reductions happen once on the host
    (``plan_movement``) — bit-identical to the compiled device program."""
    n = order.shape[0]
    cnt = np.zeros((W, B), np.int32)
    inb = np.zeros((W, B), np.float32)
    outb = np.zeros((W, B), np.float32)
    peak = np.zeros(W, np.float32)  # per-wave bottleneck max_b max(in,out)
    p_in = np.float32(0)  # schedule-wide peak per-broker inflow so far
    inf = np.float32(np.inf)
    wave = np.zeros(n, np.int32)
    overflow = 0
    bud = np.float32(budget)
    for i in order.tolist():
        d = dst[i][dst[i] >= 0]
        s = src[i][src[i] >= 0]
        bi = np.float32(b[i])
        ok = (cnt[:, d] < cap).all(axis=1) & (cnt[:, s] < cap).all(axis=1)
        ok &= ((inb[:, d] + bi <= bud) | (inb[:, d] <= 0)).all(axis=1)
        ok &= ((outb[:, s] + bi <= bud) | (outb[:, s] <= 0)).all(axis=1)
        if ok.any():
            cand_in = (
                (inb[:, d] + bi).max(axis=1) if d.size
                else np.zeros(W, np.float32)
            )
            cand_out = (
                (outb[:, s] + bi).max(axis=1) if s.size
                else np.zeros(W, np.float32)
            )
            cand = np.maximum(cand_in, cand_out)
            # lexicographic wave choice, earliest wave on full ties:
            # (1) never raise the schedule-wide peak inflow when some
            #     feasible wave avoids it (a dominant source outflow must
            #     not hide inflow stacking under a "free" makespan move);
            # (2) least growth of that wave's round-barrier bottleneck —
            #     the greedy-makespan term;
            # (3) lowest resulting destination inflow (balance).
            raise_in = np.where(ok, np.maximum(cand_in - p_in, 0), inf)
            t1 = ok & (raise_in == raise_in.min())
            grow = np.where(t1, np.maximum(peak, cand) - peak, inf)
            t2 = t1 & (grow == grow.min())
            w = int(np.argmin(np.where(t2, cand_in, inf)))
        else:
            w = W - 1
            overflow += 1
        cnt[w, d] += 1
        cnt[w, s] += 1
        inb[w, d] += bi
        outb[w, s] += bi
        new_in = inb[w, d].max() if d.size else np.float32(0)
        new_out = outb[w, s].max() if s.size else np.float32(0)
        peak[w] = max(peak[w], new_in, new_out)
        p_in = max(p_in, new_in)
        wave[i] = w
    return wave, inb, outb, overflow


_PLAN_PROGRAM = None


def _plan_program():
    """Lazy jitted wave scheduler: one ``fori_loop`` over the (traced)
    row count — greedy state is [W, B] per-wave broker occupancy, the
    loop body is the same feasibility test as the numpy oracle. Shape
    class = (padded rows, R, W, B); caps/budgets are traced data, so a
    cap or throttle retune never recompiles."""
    global _PLAN_PROGRAM
    if _PLAN_PROGRAM is not None:
        return _PLAN_PROGRAM
    import jax
    import jax.numpy as jnp

    from ccx.common import costmodel

    @costmodel.instrument("plan-waves")
    @functools.partial(jax.jit, static_argnames=("W", "B"))
    def _waves(src, dst, b, order, n, cap, budget, *, W, B):
        inf = jnp.float32(jnp.inf)

        def body(i, state):
            cnt, inb, outb, peak, p_in, wave, overflow = state
            idx = order[i]
            d, s = dst[idx], src[idx]
            dval, sval = d >= 0, s >= 0
            dcl = jnp.clip(d, 0, B - 1)
            scl = jnp.clip(s, 0, B - 1)
            bi = b[idx]
            ok = (
                jnp.where(dval[None, :], cnt[:, dcl] < cap, True).all(axis=1)
                & jnp.where(sval[None, :], cnt[:, scl] < cap, True).all(axis=1)
                & jnp.where(
                    dval[None, :],
                    (inb[:, dcl] + bi <= budget) | (inb[:, dcl] <= 0),
                    True,
                ).all(axis=1)
                & jnp.where(
                    sval[None, :],
                    (outb[:, scl] + bi <= budget) | (outb[:, scl] <= 0),
                    True,
                ).all(axis=1)
            )
            feasible = ok.any()
            cand_in = jnp.where(
                dval[None, :], inb[:, dcl] + bi, 0.0
            ).max(axis=1)
            cand_out = jnp.where(
                sval[None, :], outb[:, scl] + bi, 0.0
            ).max(axis=1)
            cand = jnp.maximum(cand_in, cand_out)
            raise_in = jnp.where(
                ok, jnp.maximum(cand_in - p_in, 0.0), inf
            )
            t1 = ok & (raise_in == raise_in.min())
            grow = jnp.where(t1, jnp.maximum(peak, cand) - peak, inf)
            t2 = t1 & (grow == grow.min())
            best = jnp.argmin(
                jnp.where(t2, cand_in, inf)
            ).astype(jnp.int32)
            w = jnp.where(feasible, best, W - 1).astype(jnp.int32)
            cnt = cnt.at[w, dcl].add(dval.astype(jnp.int32))
            cnt = cnt.at[w, scl].add(sval.astype(jnp.int32))
            inb = inb.at[w, dcl].add(jnp.where(dval, bi, 0.0))
            outb = outb.at[w, scl].add(jnp.where(sval, bi, 0.0))
            new_in = jnp.where(dval, inb[w, dcl], 0.0).max()
            new_out = jnp.where(sval, outb[w, scl], 0.0).max()
            peak = peak.at[w].set(
                jnp.maximum(peak[w], jnp.maximum(new_in, new_out))
            )
            p_in = jnp.maximum(p_in, new_in)
            wave = wave.at[idx].set(w)
            overflow = overflow + jnp.where(feasible, 0, 1)
            return cnt, inb, outb, peak, p_in, wave, overflow

        n_rows = src.shape[0]
        state = (
            jnp.zeros((W, B), jnp.int32),
            jnp.zeros((W, B), jnp.float32),
            jnp.zeros((W, B), jnp.float32),
            jnp.zeros((W,), jnp.float32),
            jnp.float32(0),
            jnp.zeros((n_rows,), jnp.int32),
            jnp.int32(0),
        )
        cnt, inb, outb, peak, p_in, wave, overflow = jax.lax.fori_loop(
            0, n, body, state
        )
        return wave, inb, outb, overflow

    _PLAN_PROGRAM = _waves
    return _waves


def _plan_device(src, dst, b, order, W: int, B: int, cap: int, budget: float):
    n = order.shape[0]
    rows_cap = _pow2_ceil(max(PLAN_ROWS_FLOOR, n))
    pad = rows_cap - n
    if pad:
        src = np.pad(src, [(0, pad), (0, 0)], constant_values=-1)
        dst = np.pad(dst, [(0, pad), (0, 0)], constant_values=-1)
        b = np.pad(b, [(0, pad)])
        order = np.pad(order, [(0, pad)])
    wave, inb, outb, overflow = _plan_program()(
        src, dst, b, order, np.int32(n), np.int32(cap),
        np.float32(budget), W=W, B=B,
    )
    return (
        np.asarray(wave)[:n], np.asarray(inb), np.asarray(outb),
        int(overflow),
    )


def plan_movement(
    diff,
    bytes_per_partition: np.ndarray | None,
    n_brokers: int,
    opts: PlanOptions = PlanOptions(),
) -> MovementPlan:
    """Schedule a columnar diff into execution waves.

    ``diff`` is a ``ccx.proposals.ColumnarDiff`` or its ``cols`` dict;
    ``bytes_per_partition`` the f32[P] per-replica disk footprint (None =
    unit bytes: pure count packing); ``n_brokers`` the broker-axis size
    the per-wave occupancy state is shaped on. Backend selection mirrors
    ``columnar_diff``: env ``CCX_DEVICE_PLAN``, else the device program
    at/above ``DEVICE_PLAN_MIN_ROWS`` rows, numpy oracle below; any
    device surprise degrades to the oracle."""
    cols = diff.cols if hasattr(diff, "cols") else diff
    src, dst, b, order = _prepare(cols, bytes_per_partition)
    part = np.asarray(cols["partition"], np.int32)
    n = part.shape[0]
    W = max(int(opts.max_waves), 1)
    cap = max(int(opts.broker_cap), 1)
    budget = float(opts.wave_bytes) if opts.wave_bytes > 0 else np.inf
    backend = opts.backend
    if backend is None:
        env = os.environ.get(ENV_DEVICE_PLAN)
        if env == "0":
            backend = "numpy"
        elif env == "1":
            backend = "device"
        else:
            backend = "device" if n >= DEVICE_PLAN_MIN_ROWS else "numpy"
    if n == 0:
        z = np.zeros(0, np.float32)
        return MovementPlan(
            wave=np.zeros(0, np.int32), partition=part,
            moves=np.zeros(0, np.int32), move_bytes=z,
            wave_bytes=z, wave_inflow_peak=z, wave_outflow_peak=z,
            n_waves=0, overflow_rows=0, backend="empty", opts=opts,
        )
    if backend == "device":
        try:
            wave, inb, outb, overflow = _plan_device(
                src, dst, b, order, W, int(n_brokers), cap, budget
            )
        except Exception:  # noqa: BLE001 — a plan must never fail a proposal
            import logging

            logging.getLogger(__name__).exception(
                "device wave planner failed; falling back to numpy"
            )
            backend = "numpy (device error)"
            wave, inb, outb, overflow = _plan_numpy(
                src, dst, b, order, W, int(n_brokers), cap, budget
            )
    else:
        wave, inb, outb, overflow = _plan_numpy(
            src, dst, b, order, W, int(n_brokers), cap, budget
        )
    # cross-broker reductions on the host, from the bit-identical [W, B]
    # accumulators — the per-wave profiles can never drift between
    # backends on reduction order
    wb = inb.sum(axis=1, dtype=np.float32)
    wip = inb.max(axis=1, initial=0.0)
    wop = outb.max(axis=1, initial=0.0)
    n_waves = int(wave.max(initial=0)) + 1
    return MovementPlan(
        wave=np.asarray(wave, np.int32),
        partition=part,
        moves=(dst >= 0).sum(axis=1).astype(np.int32),
        move_bytes=np.asarray(b, np.float32),
        wave_bytes=np.asarray(wb, np.float32)[:n_waves],
        wave_inflow_peak=np.asarray(wip, np.float32)[:n_waves],
        wave_outflow_peak=np.asarray(wop, np.float32)[:n_waves],
        n_waves=n_waves,
        overflow_rows=int(overflow),
        backend=backend,
        opts=opts,
    )


# ----- naive executor baseline ----------------------------------------------


def naive_schedule(
    diff,
    bytes_per_partition: np.ndarray | None,
    n_brokers: int,
    cap: int = 5,
    throttle_mb_per_sec: float = 0.0,
    max_cluster_movements: int | None = None,
) -> dict:
    """The legacy executor's batching, priced under the same round-barrier
    fluid model as the planner: repeated ``inter_broker_batch``-style
    rounds (task-id order, skip rows whose src/dst broker is at the
    per-broker cap, optional cluster-wide budget), each round's duration
    = the slowest broker's max(in, out) bytes over the throttle rate.
    This is the A/B baseline ``bench.py --plan`` banks against."""
    cols = diff.cols if hasattr(diff, "cols") else diff
    src, dst, b, _ = _prepare(cols, bytes_per_partition)
    n = src.shape[0]
    rate = np.float32(
        throttle_mb_per_sec if throttle_mb_per_sec > 0 else 1.0
    )
    moving = [i for i in range(n) if (dst[i] >= 0).any()]
    pending = list(moving)  # task-id (diff-row) order, like the tracker
    rounds = 0
    makespan = np.float32(0)
    peak_inflow = np.float32(0)
    round_seconds: list[float] = []
    budget = (
        int(max_cluster_movements) if max_cluster_movements else n + 1
    )
    while pending:
        cnt = np.zeros(n_brokers, np.int32)
        inb = np.zeros(n_brokers, np.float32)
        outb = np.zeros(n_brokers, np.float32)
        batch: list[int] = []
        rest: list[int] = []
        for i in pending:
            d = dst[i][dst[i] >= 0]
            s = src[i][src[i] >= 0]
            if (
                len(batch) < budget
                and (cnt[d] < cap).all()
                and (cnt[s] < cap).all()
            ):
                cnt[d] += 1
                cnt[s] += 1
                inb[d] += np.float32(b[i])
                outb[s] += np.float32(b[i])
                batch.append(i)
            else:
                rest.append(i)
        if not batch:  # cap <= 0 pathology: avoid spinning forever
            break
        rounds += 1
        peak_inflow = max(peak_inflow, np.float32(inb.max(initial=0.0)))
        dur = np.float32(
            max(inb.max(initial=0.0), outb.max(initial=0.0))
        ) / rate
        round_seconds.append(float(dur))
        makespan = np.float32(makespan + dur)
        pending = rest
    return {
        "rounds": rounds,
        "makespanSeconds": float(makespan),
        "peakInflowMb": float(peak_inflow),
        "roundSeconds": [round(s, 3) for s in round_seconds],
        "nMoves": int(sum((dst[i] >= 0).sum() for i in moving)),
    }
