"""ccx — a TPU-native cluster-rebalancing framework.

A from-scratch re-design of the capabilities of jlei-nr/cruise-control
(LinkedIn-style Kafka Cruise Control; see SURVEY.md): a goal-based cluster
rebalancer whose analyzer runs natively on TPU via JAX/XLA — the ClusterModel
is a pytree of broker x partition load tensors, every goal is a pure penalty
kernel, and proposal search is batched simulated annealing under jit/vmap/
pjit — surrounded by the monitor / executor / detector / REST layers the
reference provides on the JVM (SURVEY.md section 2 inventory).

Reference parity citations use the upstream layout, e.g.
``cruise-control/src/main/java/com/linkedin/kafka/cruisecontrol/...`` — see
SURVEY.md's provenance banner (the /root/reference mount was empty; class
names from BASELINE.json + upstream structural knowledge).
"""

__version__ = "0.1.0"
