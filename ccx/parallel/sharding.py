"""Device-mesh sharding of the cluster model and search.

The reference scales by cluster size (brokers x partitions) inside one JVM
heap (SURVEY.md section 5.7 "the reference's long-sequence axis is cluster
size"); its concurrency axes are thread pools (section 2.5). The TPU-native
scale-out story replaces both with a 2-axis ``jax.sharding.Mesh``:

* ``chains`` — data parallelism over independent SA chains (the descendant of
  ``num.proposal.precompute.threads``): each device runs its own chains; the
  only cross-device step is the final lexicographic argmin.
* ``parts`` — sequence-parallel-style sharding of the *partition axis* of the
  model tensors: broker aggregates are segment-sums over partitions, so each
  device reduces its shard and a ``psum`` over ICI produces the global
  aggregates (the XLA-collective equivalent of the reference's single-heap
  O(P) walks).

Everything here composes with jit: ``shard_map`` bodies contain the explicit
collectives; XLA lays the psums on ICI when the mesh spans real chips.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ccx.goals import partition_terms as pt
from ccx.goals.base import GOAL_REGISTRY, GoalConfig
from ccx.goals.stack import DEFAULT_GOAL_ORDER, StackResult
from ccx.model.aggregates import broker_aggregates
from ccx.model.tensor_model import TensorClusterModel

CHAINS_AXIS = "chains"
PARTS_AXIS = "parts"


def make_mesh(
    devices: list | None = None, parts: int | None = None
) -> Mesh:
    """A (chains x parts) mesh over the given (default: all) devices.

    By default the device count is split with a small ``parts`` factor —
    partition-axis sharding only pays off for very large clusters, while
    chain parallelism is embarrassingly parallel — callers with 100k+
    partition models should raise ``parts``.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if parts is None:
        parts = 2 if n % 2 == 0 and n > 1 else 1
    chains = n // parts
    if chains * parts != n:
        raise ValueError(f"{n} devices not divisible into parts={parts}")
    return Mesh(
        np.asarray(devices[: chains * parts]).reshape(chains, parts),
        (CHAINS_AXIS, PARTS_AXIS),
    )


def model_pspecs(m: TensorClusterModel) -> TensorClusterModel:
    """PartitionSpec pytree for a TensorClusterModel: partition-axis arrays
    sharded over ``parts``; broker/disk/topic arrays replicated (they are
    O(B) and every device needs them to score aggregates)."""
    return TensorClusterModel(
        assignment=P(PARTS_AXIS, None),
        leader_slot=P(PARTS_AXIS),
        replica_disk=P(PARTS_AXIS, None),
        partition_valid=P(PARTS_AXIS),
        partition_topic=P(PARTS_AXIS),
        partition_immovable=P(PARTS_AXIS),
        leader_load=P(None, PARTS_AXIS),
        follower_load=P(None, PARTS_AXIS),
        broker_capacity=P(),
        broker_rack=P(),
        broker_valid=P(),
        broker_alive=P(),
        broker_new=P(),
        broker_excl_replicas=P(),
        broker_excl_leadership=P(),
        disk_capacity=P(),
        disk_alive=P(),
        topic_min_leaders=P(),
        num_topics=m.num_topics,
        num_racks=m.num_racks,
    )


def shard_model(m: TensorClusterModel, mesh: Mesh) -> TensorClusterModel:
    """Place the model on the mesh with the partition axis sharded."""
    specs = model_pspecs(m)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), m, specs
    )


def replicate(x, mesh: Mesh):
    """Fully replicate a pytree across the mesh."""
    return jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, P())), x
    )


def sharded_stack_eval(
    m: TensorClusterModel,
    cfg: GoalConfig = GoalConfig(),
    goal_names: tuple[str, ...] = DEFAULT_GOAL_ORDER,
    mesh: Mesh | None = None,
) -> StackResult:
    """evaluate_stack with the partition axis sharded over ``parts``.

    Each device segment-sums its partition shard into partial broker
    aggregates and per-partition goal sums; one ``psum`` over the ``parts``
    axis yields globals; goal kernels then score the (replicated) broker-axis
    state. Numerically identical to ``ccx.goals.stack.evaluate_stack`` up to
    float reduction order.
    """
    if mesh is None:
        mesh = make_mesh()
    specs = model_pspecs(m)
    hard_mask = tuple(GOAL_REGISTRY[n].hard for n in goal_names)
    part_idx = {n: i for i, n in enumerate(pt.PARTITION_GOALS)}
    for name in goal_names:
        if GOAL_REGISTRY[name].placement_dependent and name not in part_idx:
            raise ValueError(
                f"goal {name} reads per-partition placement and has no "
                "partition_terms row function; it cannot be shard-evaluated"
            )

    def body(m_local: TensorClusterModel):
        agg = jax.tree.map(
            lambda x: jax.lax.psum(x, PARTS_AXIS), broker_aggregates(m_local)
        )
        psums = jax.lax.psum(
            pt.partition_sums(
                m_local,
                m_local.assignment,
                m_local.leader_slot,
                m_local.replica_disk,
                m_local.partition_valid,
            ),
            PARTS_AXIS,
        )
        inv_np = 1.0 / jnp.maximum(
            jnp.sum(agg.leader_count).astype(jnp.float32), 1.0
        )
        vio, cost = [], []
        for name in goal_names:
            if name in part_idx:
                v = psums[part_idx[name]]
                c = v * inv_np if name == "PreferredLeaderElectionGoal" else v
            else:
                r = GOAL_REGISTRY[name].fn(m_local, agg, cfg)
                v, c = r.violations, r.cost
            vio.append(v)
            cost.append(c)
        return jnp.stack(vio), jnp.stack(cost)

    fn = jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=(specs,), out_specs=(P(), P()))
    )
    violations, costs = fn(m)
    return StackResult(
        names=tuple(goal_names),
        hard_mask=hard_mask,
        violations=violations,
        costs=costs,
    )
