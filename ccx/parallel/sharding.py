"""Device-mesh sharding of the cluster model and search.

The reference scales by cluster size (brokers x partitions) inside one JVM
heap (SURVEY.md section 5.7 "the reference's long-sequence axis is cluster
size"); its concurrency axes are thread pools (section 2.5). The TPU-native
scale-out story replaces both with a 2-axis ``jax.sharding.Mesh``:

* ``chains`` — data parallelism over independent SA chains (the descendant of
  ``num.proposal.precompute.threads``): each device runs its own chains; the
  only cross-device step is the final lexicographic argmin.
* ``parts`` — sequence-parallel-style sharding of the *partition axis* of the
  model tensors: broker aggregates are segment-sums over partitions, so each
  device reduces its shard and a ``psum`` over ICI produces the global
  aggregates (the XLA-collective equivalent of the reference's single-heap
  O(P) walks).

Everything here composes with jit: ``shard_map`` bodies contain the explicit
collectives; XLA lays the psums on ICI when the mesh spans real chips.
"""

from __future__ import annotations

from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ccx.goals import partition_terms as pt
from ccx.goals.base import GOAL_REGISTRY, GoalConfig
from ccx.goals.stack import DEFAULT_GOAL_ORDER, StackResult
from ccx.model.aggregates import BrokerAggregates, broker_aggregates
from ccx.model.tensor_model import TensorClusterModel

CHAINS_AXIS = "chains"
PARTS_AXIS = "parts"


def make_mesh(
    devices: list | None = None, parts: int | None = None
) -> Mesh:
    """A (chains x parts) mesh over the given (default: all) devices.

    By default the device count is split with a small ``parts`` factor —
    partition-axis sharding only pays off for very large clusters, while
    chain parallelism is embarrassingly parallel — callers with 100k+
    partition models should raise ``parts``.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if parts is None:
        parts = 2 if n % 2 == 0 and n > 1 else 1
    chains = n // parts
    if chains * parts != n:
        raise ValueError(f"{n} devices not divisible into parts={parts}")
    return Mesh(
        np.asarray(devices[: chains * parts]).reshape(chains, parts),
        (CHAINS_AXIS, PARTS_AXIS),
    )


def model_pspecs(m: TensorClusterModel) -> TensorClusterModel:
    """PartitionSpec pytree for a TensorClusterModel: partition-axis arrays
    sharded over ``parts``; broker/disk/topic arrays replicated (they are
    O(B) and every device needs them to score aggregates)."""
    return TensorClusterModel(
        assignment=P(PARTS_AXIS, None),
        leader_slot=P(PARTS_AXIS),
        replica_disk=P(PARTS_AXIS, None),
        partition_valid=P(PARTS_AXIS),
        partition_topic=P(PARTS_AXIS),
        partition_immovable=P(PARTS_AXIS),
        leader_load=P(None, PARTS_AXIS),
        follower_load=P(None, PARTS_AXIS),
        broker_capacity=P(),
        broker_rack=P(),
        broker_host=P(),
        broker_valid=P(),
        broker_alive=P(),
        broker_new=P(),
        broker_excl_replicas=P(),
        broker_excl_leadership=P(),
        disk_capacity=P(),
        disk_alive=P(),
        topic_min_leaders=P(),
        num_topics=m.num_topics,
        num_racks=m.num_racks,
    )


def shard_model(m: TensorClusterModel, mesh: Mesh) -> TensorClusterModel:
    """Place the model on the mesh with the partition axis sharded."""
    specs = model_pspecs(m)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), m, specs
    )


def replicate(x, mesh: Mesh):
    """Fully replicate a pytree across the mesh."""
    return jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, P())), x
    )


def _struct_key(m) -> tuple:
    """Hashable (treedef, leaf shapes/dtypes) key for a model pytree.

    The sharded entry points build their jitted shard_map programs as local
    closures; a fresh closure per call is a fresh jit cache entry, so every
    call RETRACES AND RECOMPILES (measured ~26 s per sharded_anneal call at
    256 brokers / 16k partitions on the 8-device CPU mesh — flat in step
    count, pure compile). The module-level caches below reuse the compiled
    program across calls with identical static config + model structure."""
    return (
        jax.tree.structure(m),
        tuple(
            (tuple(leaf.shape), jnp.result_type(leaf).name)
            for leaf in jax.tree.leaves(m)
        ),
    )


#: Bounded LRU: a long-lived service re-optimizing an evolving cluster mints
#: a new struct key whenever padded shapes change; unbounded dicts would pin
#: every old B5-scale compiled program forever (jax.clear_caches() cannot
#: reach programs held by these wrappers).
_CACHE_MAX = 8


def _cache_get(cache: "OrderedDict", key):
    fn = cache.get(key)
    if fn is not None:
        cache.move_to_end(key)
    return fn


def _cache_put(cache: "OrderedDict", key, fn) -> None:
    cache[key] = fn
    cache.move_to_end(key)
    while len(cache) > _CACHE_MAX:
        cache.popitem(last=False)


#: (mesh, goal_names, cfg, struct) -> jitted sharded stack evaluator
_EVAL_CACHE: "OrderedDict" = OrderedDict()
#: sharded_anneal static config -> jitted run program
_RUN_CACHE: "OrderedDict" = OrderedDict()


def sharded_stack_eval(
    m: TensorClusterModel,
    cfg: GoalConfig = GoalConfig(),
    goal_names: tuple[str, ...] = DEFAULT_GOAL_ORDER,
    mesh: Mesh | None = None,
) -> StackResult:
    """evaluate_stack with the partition axis sharded over ``parts``.

    Each device segment-sums its partition shard into partial broker
    aggregates and per-partition goal sums; one ``psum`` over the ``parts``
    axis yields globals; goal kernels then score the (replicated) broker-axis
    state. Numerically identical to ``ccx.goals.stack.evaluate_stack`` up to
    float reduction order. Accepts every searchable stack, including the
    kafka-assigner mode's decomposed KafkaAssignerEvenRackAwareGoal
    (SURVEY.md C19) — same decomposition as ccx.search.state.
    """
    if mesh is None:
        mesh = make_mesh()
    from ccx.search.state import check_searchable

    hard_mask = tuple(GOAL_REGISTRY[n].hard for n in goal_names)
    check_searchable(goal_names)
    cache_key = (mesh, goal_names, cfg, _struct_key(m))
    cached = _cache_get(_EVAL_CACHE, cache_key)
    if cached is not None:
        violations, costs = cached(m)
        return StackResult(
            names=tuple(goal_names),
            hard_mask=hard_mask,
            violations=violations,
            costs=costs,
        )

    specs = model_pspecs(m)
    part_idx = {n: i for i, n in enumerate(pt.PARTITION_GOALS)}

    def body(m_local: TensorClusterModel):
        agg = jax.tree.map(
            lambda x: jax.lax.psum(x, PARTS_AXIS), broker_aggregates(m_local)
        )
        psums = jax.lax.psum(
            pt.partition_sums(
                m_local,
                m_local.assignment,
                m_local.leader_slot,
                m_local.replica_disk,
                m_local.partition_valid,
            ),
            PARTS_AXIS,
        )
        inv_np = 1.0 / jnp.maximum(
            jnp.sum(agg.leader_count).astype(jnp.float32), 1.0
        )
        vio, cost = [], []
        for name in goal_names:
            if name in part_idx:
                v = psums[part_idx[name]]
                c = v * inv_np if name == "PreferredLeaderElectionGoal" else v
            elif name == "KafkaAssignerEvenRackAwareGoal":
                # rack half from the psummed row sums; leader-evenness half
                # from the (already global) aggregates — the full kernel's
                # math on sharded inputs (ccx.search.state decomposition)
                alive = m_local.broker_valid & m_local.broker_alive
                n_alive = jnp.maximum(jnp.sum(alive).astype(jnp.float32), 1.0)
                avg = jnp.sum(agg.leader_count).astype(jnp.float32) / n_alive
                upper = jnp.ceil(avg)
                over = jnp.where(
                    alive, jnp.maximum(agg.leader_count - upper, 0.0), 0.0
                )
                rack = psums[part_idx["RackAwareGoal"]]
                v = rack + jnp.sum(over > 0).astype(jnp.float32)
                c = rack + jnp.sum(over) / jnp.maximum(avg, 1e-9)
            else:
                r = GOAL_REGISTRY[name].fn(m_local, agg, cfg)
                v, c = r.violations, r.cost
            vio.append(v)
            cost.append(c)
        return jnp.stack(vio), jnp.stack(cost)

    fn = jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=(specs,), out_specs=(P(), P()))
    )
    _cache_put(_EVAL_CACHE, cache_key, fn)
    violations, costs = fn(m)
    return StackResult(
        names=tuple(goal_names),
        hard_mask=hard_mask,
        violations=violations,
        costs=costs,
    )


# ---------------------------------------------------------------------------
# Partition-axis-sharded simulated annealing
# ---------------------------------------------------------------------------

def _mask_view(view, owned):
    """Zero a PartitionView's contribution on non-owner shards so a psum
    reconstructs the owner's values (``owned`` broadcasts over trailing
    axes of stacked views)."""

    def mask(x):
        ow = owned.reshape(owned.shape + (1,) * (x.ndim - owned.ndim))
        if x.dtype == jnp.bool_:
            return x & ow
        return x * ow.astype(x.dtype)

    return jax.tree.map(mask, view)


def _psum_tree(tree, axis):
    def red(x):
        if x.dtype == jnp.bool_:
            return jax.lax.psum(x.astype(jnp.int32), axis) > 0
        return jax.lax.psum(x, axis)

    return jax.tree.map(red, tree)


def sharded_anneal(
    m: TensorClusterModel,
    cfg: GoalConfig = GoalConfig(),
    goal_names: tuple[str, ...] = DEFAULT_GOAL_ORDER,
    opts=None,
    mesh: Mesh | None = None,
):
    """Batched SA with the model's partition axis sharded inside the search
    (SURVEY.md section 5.7, the long-context analogue): model tensors stay
    sharded over ``parts`` for the whole run — they are never replicated —
    while chains ride the ``chains`` axis as data parallelism.

    Per proposal, the shard owning the drawn partition gathers its
    PartitionView locally and one ``psum`` over ICI broadcasts it (O(R)
    scalars — the only per-step collective); every shard then scores and
    accepts identically (replicated RNG), and only the owner writes the
    placement row. Aggregates/accumulators are replicated per chain and
    updated identically everywhere, so no resynchronization is ever needed.

    Semantics match ``ccx.search.anneal`` (same RNG stream, same acceptance
    rule); results can differ only by float reduction order in the initial
    psummed aggregates.
    """
    from ccx.goals.stack import evaluate_stack, soft_weights
    from ccx.search.annealer import (
        CAPACITY_GOALS as CAPACITY_GOALS_,
        RACK_TARGET_GOALS,
        AnnealOptions,
        AnnealResult,
        ProposalParams,
        _anneal_step,
        _anneal_step_batched,
        _swap_ramp_of,
        allows_inter_broker,
        best_chain_index,
        hot_partition_list,
        lead_swap_share,
    )
    from ccx.search.state import (
        PartitionView,
        SearchState,
        TopicGroup,
        make_cost_vector_fn,
        make_move_scorer,
        make_swap_scorer,
        make_topic_group,
        max_partitions_per_topic,
        stack_needs_topic,
        with_placement,
    )
    from ccx.goals import topic_terms as tt_

    if opts is None:
        opts = AnnealOptions()
    if mesh is None:
        mesh = make_mesh()
    n_parts = mesh.shape[PARTS_AXIS]
    n_chain_ranks = mesh.shape[CHAINS_AXIS]
    if m.P % n_parts:
        raise ValueError(f"padded P={m.P} not divisible by parts={n_parts}")
    if opts.n_chains % n_chain_ranks:
        raise ValueError(
            f"n_chains={opts.n_chains} not divisible by chains axis "
            f"{n_chain_ranks}"
        )

    stack_before = evaluate_stack(m, cfg, goal_names)
    p_real = int(np.asarray(m.partition_valid).sum())
    bv = np.asarray(m.broker_valid)
    b_real = int(np.max(np.where(bv, np.arange(m.B), -1))) + 1
    evac_np, n_evac_i = hot_partition_list(m, goal_names, cfg)

    hard_mask = tuple(GOAL_REGISTRY[n].hard for n in goal_names)
    allow_inter = allows_inter_broker(goal_names)
    pp = ProposalParams(
        p_real=p_real,
        b_real=b_real,
        p_leadership=opts.p_leadership,
        p_disk=opts.p_disk,
        p_biased_dest=opts.p_biased_dest,
        p_evac=opts.p_evac,
        target_rack=bool(RACK_TARGET_GOALS & set(goal_names)),
        allow_inter=allow_inter,
        p_swap=opts.p_swap if allow_inter else 0.0,
        target_capacity=bool(CAPACITY_GOALS_ & set(goal_names)),
        cap_thresholds=tuple(cfg.capacity_threshold),
        p_lead_swap=lead_swap_share(opts.p_leadership),
        # swap-knob parity with annealer._build_step: the coupled
        # endpoint draw and the p_swap schedule run under sharding too
        p_couple=opts.swap_coupling if allow_inter else 0.0,
        couple_pool=opts.couple_pool,
    )
    schedule_on = allow_inter and opts.p_swap_end >= 0

    m_sharded = shard_model(m, mesh)
    keys = jax.random.split(jax.random.PRNGKey(opts.seed), opts.n_chains)
    keys = jax.device_put(keys, NamedSharding(mesh, P(CHAINS_AXIS, None)))
    evac = jax.device_put(jnp.asarray(evac_np), NamedSharding(mesh, P()))
    n_evac = jax.device_put(
        jnp.asarray(n_evac_i, jnp.int32), NamedSharding(mesh, P())
    )
    # Static topic-membership structure (GLOBAL partition ids), replicated.
    # The grouped placement mirror it indexes is replicated per chain: every
    # shard sees the psum'd view of each move, so all shards write identical
    # mirror cells — reads then need no collective.
    needs_topic = stack_needs_topic(goal_names)
    group_rep = (
        jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(mesh, P())),
            make_topic_group(m, max_partitions_per_topic(m)),
        )
        if needs_topic
        else None
    )

    # Reuse the compiled program across calls (see _struct_key: a fresh jit
    # closure per call would retrace + recompile every time — ~26 s/call at
    # 256 brokers / 16k partitions). Keyed on every static the closure
    # captures; array shapes are covered by _struct_key + jit's own
    # shape-based retrace.
    cache_key = (
        mesh, goal_names, cfg, pp, b_real,
        opts.n_steps, opts.t0, opts.t1, opts.moves_per_step, opts.batched,
        opts.p_swap_end,
        needs_topic, _struct_key(m),
    )
    cached_run = _cache_get(_RUN_CACHE, cache_key)
    if cached_run is not None:
        states = cached_run(m_sharded, keys, evac, n_evac, group_rep)
        return _finish_sharded_anneal(
            m_sharded, states, cfg, goal_names, opts, stack_before
        )

    mspecs = model_pspecs(m)
    state_specs = SearchState(
        assignment=P(CHAINS_AXIS, PARTS_AXIS, None),
        leader_slot=P(CHAINS_AXIS, PARTS_AXIS),
        replica_disk=P(CHAINS_AXIS, PARTS_AXIS, None),
        agg=BrokerAggregates(
            broker_load=P(CHAINS_AXIS, None, None),
            replica_count=P(CHAINS_AXIS, None),
            leader_count=P(CHAINS_AXIS, None),
            potential_nw_out=P(CHAINS_AXIS, None),
            leader_bytes_in=P(CHAINS_AXIS, None),
            topic_replica_count=P(CHAINS_AXIS, None, None),
            topic_leader_count=P(CHAINS_AXIS, None, None),
            disk_load=P(CHAINS_AXIS, None, None),
        ),
        part_sums=P(CHAINS_AXIS, None),
        topic_totals=P(CHAINS_AXIS, None),
        mtl_sum=P(CHAINS_AXIS),
        trd_sum=P(CHAINS_AXIS),
        cost_vec=P(CHAINS_AXIS, None),
        key=P(CHAINS_AXIS, None),
        n_accepted=P(CHAINS_AXIS),
        hard_mask=hard_mask,
        grouped_assign=(
            P(CHAINS_AXIS, None, None, None) if needs_topic else None
        ),
        grouped_leader=(
            P(CHAINS_AXIS, None, None) if needs_topic else None
        ),
        n_prop_kind=P(CHAINS_AXIS, None),
        n_acc_kind=P(CHAINS_AXIS, None),
    )

    import functools as _ft

    @_ft.partial(jax.jit, static_argnames=())
    def run(m_s, keys_s, evac_s, n_evac_s, group_arg):
        def body(m_local, keys_local, evac_l, n_evac_l, group_l):
            P_local = m_local.assignment.shape[0]
            offset = jax.lax.axis_index(PARTS_AXIS) * P_local

            # ---- init: partial sums + psum -> replicated bookkeeping ------
            agg = _psum_tree(broker_aggregates(m_local), PARTS_AXIS)
            part_sums = jax.lax.psum(
                pt.partition_sums(
                    m_local,
                    m_local.assignment,
                    m_local.leader_slot,
                    m_local.replica_disk,
                    m_local.partition_valid,
                ),
                PARTS_AXIS,
            )
            mtl_sum = jnp.sum(
                tt_.mtl_row(
                    m_local, cfg, m_local.topic_min_leaders, agg.topic_leader_count
                )
            )
            pen, _ = tt_.trd_row_pen(m_local, cfg, agg.topic_replica_count)
            trd_sum = jnp.sum(pen)
            topic_totals = tt_.trd_row_total(m_local, agg.topic_replica_count)
            trd_norm = tt_.trd_normalizer(m_local, topic_totals)
            cost_vec = make_cost_vector_fn(m_local, goal_names, cfg)(
                agg, part_sums, mtl_sum, trd_sum, trd_norm
            )
            # search never carries the [T, B] matrices (ccx.search.state
            # module docstring) — loud dummies, same as init_search_state
            agg = agg.replace(
                topic_replica_count=jnp.zeros((1, 1), jnp.int32),
                topic_leader_count=jnp.zeros((1, 1), jnp.int32),
            )
            # grouped placement mirror, replicated: each member partition is
            # owned by exactly one shard, which contributes row+1 (others 0);
            # the psum minus 1 reconstructs the row (-1 for pad entries)
            ga = gl = None
            if group_l is not None:
                mp = group_l.members
                li = mp - offset
                mine = (mp >= 0) & (li >= 0) & (li < P_local)
                lic = jnp.clip(li, 0, P_local - 1)
                ga = (
                    jax.lax.psum(
                        jnp.where(
                            mine[..., None],
                            m_local.assignment[lic] + 1,
                            0,
                        ),
                        PARTS_AXIS,
                    )
                    - 1
                )
                gl = (
                    jax.lax.psum(
                        jnp.where(mine, m_local.leader_slot[lic] + 1, 0),
                        PARTS_AXIS,
                    )
                    - 1
                )
            state0 = SearchState(
                assignment=m_local.assignment,
                leader_slot=m_local.leader_slot,
                replica_disk=m_local.replica_disk,
                agg=agg,
                part_sums=part_sums,
                topic_totals=topic_totals,
                mtl_sum=mtl_sum,
                trd_sum=trd_sum,
                cost_vec=cost_vec,
                key=keys_local[0],
                n_accepted=jnp.asarray(0, jnp.int32),
                hard_mask=hard_mask,
                grouped_assign=ga,
                grouped_leader=gl,
                n_prop_kind=jnp.zeros(3, jnp.int32),
                n_acc_kind=jnp.zeros(3, jnp.int32),
            )
            states = jax.vmap(lambda k: state0.replace(key=k))(keys_local)

            # ---- sharding hooks ------------------------------------------
            def gather(ss, _m, ps):
                # stacked owner-gather + psum: ps is int32[k] of GLOBAL ids
                li = jnp.clip(ps - offset, 0, P_local - 1)
                owned = (ps >= offset) & (ps < offset + P_local)
                view_local = PartitionView(
                    pvalid=m_local.partition_valid[li] & owned,
                    immovable=m_local.partition_immovable[li] & owned,
                    topic=m_local.partition_topic[li],
                    lead_load=m_local.leader_load[:, li].T,
                    foll_load=m_local.follower_load[:, li].T,
                    assign=ss.assignment[li],
                    leader=ss.leader_slot[li],
                    disk=ss.replica_disk[li],
                )
                return _psum_tree(_mask_view(view_local, owned), PARTS_AXIS)

            def locate(p):
                owned = (p >= offset) & (p < offset + P_local)
                return jnp.clip(p - offset, 0, P_local - 1), owned


            hard_arr = jnp.asarray(hard_mask)
            weights = soft_weights(hard_mask)
            n = max(opts.n_steps, 1)
            decay = (opts.t1 / opts.t0) ** (1.0 / max(n - 1, 1))
            # same small-cluster + p_swap gate as annealer._run_chains
            # (p_swap == 0 stacks keep the sequential inner_single_only
            # fast path — one use per carried buffer)
            batched = (
                opts.batched
                and opts.moves_per_step > 1
                and (pp.p_swap > 0.0 or schedule_on)
                and b_real >= 4 * m_local.R * opts.moves_per_step
            )
            step = _ft.partial(
                _anneal_step_batched if batched else _anneal_step,
                m=m_local,
                pp=pp,
                hard_arr=hard_arr,
                weights=weights,
                moves_per_step=max(opts.moves_per_step, 1),
                scorer=make_move_scorer(m_local, goal_names, cfg),
                swap_scorer=make_swap_scorer(m_local, goal_names, cfg),
                gather=gather,
                locate=locate,
                group=group_l,
                swap_ramp=_swap_ramp_of(opts, n),
                swap_schedule_on=schedule_on,
                cfg=cfg,
                **(
                    {
                        "vector_fn": make_cost_vector_fn(
                            m_local, goal_names, cfg
                        )
                    }
                    if batched
                    else {}
                ),
            )

            def scan_body(ss, t):
                temp = opts.t0 * decay**t
                ss = jax.vmap(step, in_axes=(0, None, None, None, None))(
                    ss, temp, t, evac_l, n_evac_l
                )
                return ss, None

            states, _ = jax.lax.scan(scan_body, states, jnp.arange(n))
            return states

        group_specs = (
            TopicGroup(members=P(), member_slot=P())
            if group_arg is not None
            else None
        )
        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(mspecs, P(CHAINS_AXIS, None), P(), P(), group_specs),
            out_specs=state_specs,
            # the scan carry mixes axis-invariant init values with
            # axis-varying updates; skip the varying-manual-axes check
            check_vma=False,
        )(m_s, keys_s, evac_s, n_evac_s, group_arg)

    _cache_put(_RUN_CACHE, cache_key, run)
    states = run(m_sharded, keys, evac, n_evac, group_rep)
    return _finish_sharded_anneal(
        m_sharded, states, cfg, goal_names, opts, stack_before
    )


def _finish_sharded_anneal(m_sharded, states, cfg, goal_names, opts, stack_before):
    from ccx.search.annealer import AnnealResult, best_chain_index
    from ccx.search.state import with_placement
    from ccx.goals.stack import evaluate_stack

    best = best_chain_index(np.asarray(states.cost_vec))
    pick = jax.tree.map(lambda a: a[best], states)
    result_model = with_placement(m_sharded, pick)
    stack_after = evaluate_stack(result_model, cfg, goal_names)
    return AnnealResult(
        model=result_model,
        stack_before=stack_before,
        stack_after=stack_after,
        n_accepted=int(np.asarray(pick.n_accepted)),
        n_chains=opts.n_chains,
        n_steps=opts.n_steps,
        best_chain=best,
        n_prop_kind=tuple(int(x) for x in np.asarray(pick.n_prop_kind)),
        n_acc_kind=tuple(int(x) for x in np.asarray(pick.n_acc_kind)),
    )
