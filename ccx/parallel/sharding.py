"""Device-mesh sharding of the cluster model and search.

The reference scales by cluster size (brokers x partitions) inside one JVM
heap (SURVEY.md section 5.7 "the reference's long-sequence axis is cluster
size"); its concurrency axes are thread pools (section 2.5). The TPU-native
scale-out story replaces both with a 2-axis ``jax.sharding.Mesh``:

* ``chains`` — data parallelism over independent SA chains (the descendant of
  ``num.proposal.precompute.threads``): each device runs its own chains; the
  only cross-device step is the final lexicographic argmin.
* ``parts`` — sequence-parallel-style sharding of the *partition axis* of the
  model tensors: broker aggregates are segment-sums over partitions, so each
  device reduces its shard and a ``psum`` over ICI produces the global
  aggregates (the XLA-collective equivalent of the reference's single-heap
  O(P) walks).

Everything here composes with jit: ``shard_map`` bodies contain the explicit
collectives; XLA lays the psums on ICI when the mesh spans real chips.
"""

from __future__ import annotations

from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ccx.common import costmodel
from ccx.goals import partition_terms as pt
from ccx.goals.base import GOAL_REGISTRY, GoalConfig
from ccx.goals.stack import DEFAULT_GOAL_ORDER, StackResult
from ccx.model.aggregates import BrokerAggregates, broker_aggregates
from ccx.model.tensor_model import TensorClusterModel

CHAINS_AXIS = "chains"
PARTS_AXIS = "parts"


def _shard_map(body, mesh, in_specs, out_specs, check: bool = True):
    """``shard_map`` across jax versions: newer jax exposes
    ``jax.shard_map`` with a ``check_vma`` knob; 0.4.x ships it under
    ``jax.experimental.shard_map`` with ``check_rep``. Both knobs gate the
    same class of replication/varying-axes validation that the SA scan
    carry trips (axis-invariant init values mixed with axis-varying
    updates), so ``check=False`` maps onto whichever exists."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check,
    )


def make_mesh(
    devices: list | None = None, parts: int | None = None
) -> Mesh:
    """A (chains x parts) mesh over the given (default: all) devices.

    By default the device count is split with a small ``parts`` factor —
    partition-axis sharding only pays off for very large clusters, while
    chain parallelism is embarrassingly parallel — callers with 100k+
    partition models should raise ``parts``.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if parts is None:
        parts = 2 if n % 2 == 0 and n > 1 else 1
    chains = n // parts
    if chains * parts != n:
        raise ValueError(f"{n} devices not divisible into parts={parts}")
    return Mesh(
        np.asarray(devices[: chains * parts]).reshape(chains, parts),
        (CHAINS_AXIS, PARTS_AXIS),
    )


def model_pspecs(m: TensorClusterModel) -> TensorClusterModel:
    """PartitionSpec pytree for a TensorClusterModel: partition-axis arrays
    sharded over ``parts``; broker/disk/topic arrays replicated (they are
    O(B) and every device needs them to score aggregates)."""
    return TensorClusterModel(
        assignment=P(PARTS_AXIS, None),
        leader_slot=P(PARTS_AXIS),
        replica_disk=P(PARTS_AXIS, None),
        partition_valid=P(PARTS_AXIS),
        partition_topic=P(PARTS_AXIS),
        partition_immovable=P(PARTS_AXIS),
        leader_load=P(None, PARTS_AXIS),
        follower_load=P(None, PARTS_AXIS),
        broker_capacity=P(),
        broker_rack=P(),
        broker_host=P(),
        broker_valid=P(),
        broker_alive=P(),
        broker_new=P(),
        broker_excl_replicas=P(),
        broker_excl_leadership=P(),
        disk_capacity=P(),
        disk_alive=P(),
        topic_min_leaders=P(),
        num_topics=m.num_topics,
        num_racks=m.num_racks,
    )


def shard_model(m: TensorClusterModel, mesh: Mesh) -> TensorClusterModel:
    """Place the model on the mesh with the partition axis sharded."""
    specs = model_pspecs(m)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), m, specs
    )


def replicate(x, mesh: Mesh):
    """Fully replicate a pytree across the mesh."""
    return jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, P())), x
    )


def _struct_key(m) -> tuple:
    """Hashable (treedef, leaf shapes/dtypes) key for a model pytree.

    The sharded entry points build their jitted shard_map programs as local
    closures; a fresh closure per call is a fresh jit cache entry, so every
    call RETRACES AND RECOMPILES (measured ~26 s per sharded_anneal call at
    256 brokers / 16k partitions on the 8-device CPU mesh — flat in step
    count, pure compile). The module-level caches below reuse the compiled
    program across calls with identical static config + model structure."""
    return (
        jax.tree.structure(m),
        tuple(
            (tuple(leaf.shape), jnp.result_type(leaf).name)
            for leaf in jax.tree.leaves(m)
        ),
    )


#: Bounded LRU: a long-lived service re-optimizing an evolving cluster mints
#: a new struct key whenever padded shapes change; unbounded dicts would pin
#: every old B5-scale compiled program forever (jax.clear_caches() cannot
#: reach programs held by these wrappers).
_CACHE_MAX = 8


def _cache_get(cache: "OrderedDict", key):
    fn = cache.get(key)
    if fn is not None:
        cache.move_to_end(key)
    return fn


def _cache_put(cache: "OrderedDict", key, fn) -> None:
    cache[key] = fn
    cache.move_to_end(key)
    while len(cache) > _CACHE_MAX:
        cache.popitem(last=False)


#: (mesh, goal_names, cfg, struct) -> jitted sharded stack evaluator
_EVAL_CACHE: "OrderedDict" = OrderedDict()
#: sharded_anneal static config -> jitted program. Tagged keys share one
#: LRU: ("init", ...) chain-init, ("chunk", ...) the traced-budget chunk
#: program (n_steps/t1/ramp retunes hit the SAME entry), ("run", ...) the
#: monolithic one-shot scan.
_RUN_CACHE: "OrderedDict" = OrderedDict()


def program_cache_stats() -> dict:
    """Live sharded-program cache occupancy — the ``shardedPrograms``
    block surfaced on ``AnalyzerState.observability`` and BENCH lines so
    an operator can see how many compiled mesh programs are resident (and
    whether a retune minted a new one, which it never should for
    chunk-driven budget changes)."""
    return {
        "run": len(_RUN_CACHE),
        "eval": len(_EVAL_CACHE),
        "max": _CACHE_MAX,
    }


def sharded_stack_eval(
    m: TensorClusterModel,
    cfg: GoalConfig = GoalConfig(),
    goal_names: tuple[str, ...] = DEFAULT_GOAL_ORDER,
    mesh: Mesh | None = None,
) -> StackResult:
    """evaluate_stack with the partition axis sharded over ``parts``.

    Each device segment-sums its partition shard into partial broker
    aggregates and per-partition goal sums; one ``psum`` over the ``parts``
    axis yields globals; goal kernels then score the (replicated) broker-axis
    state. Numerically identical to ``ccx.goals.stack.evaluate_stack`` up to
    float reduction order. Accepts every searchable stack, including the
    kafka-assigner mode's decomposed KafkaAssignerEvenRackAwareGoal
    (SURVEY.md C19) — same decomposition as ccx.search.state.
    """
    if mesh is None:
        mesh = make_mesh()
    from ccx.search.state import check_searchable

    hard_mask = tuple(GOAL_REGISTRY[n].hard for n in goal_names)
    check_searchable(goal_names)
    cache_key = (mesh, goal_names, cfg, _struct_key(m))
    cached = _cache_get(_EVAL_CACHE, cache_key)
    if cached is not None:
        violations, costs = cached(m)
        return StackResult(
            names=tuple(goal_names),
            hard_mask=hard_mask,
            violations=violations,
            costs=costs,
        )

    specs = model_pspecs(m)
    part_idx = {n: i for i, n in enumerate(pt.PARTITION_GOALS)}

    def body(m_local: TensorClusterModel):
        agg = jax.tree.map(
            lambda x: jax.lax.psum(x, PARTS_AXIS), broker_aggregates(m_local)
        )
        psums = jax.lax.psum(
            pt.partition_sums(
                m_local,
                m_local.assignment,
                m_local.leader_slot,
                m_local.replica_disk,
                m_local.partition_valid,
            ),
            PARTS_AXIS,
        )
        inv_np = 1.0 / jnp.maximum(
            jnp.sum(agg.leader_count).astype(jnp.float32), 1.0
        )
        vio, cost = [], []
        for name in goal_names:
            if name in part_idx:
                v = psums[part_idx[name]]
                c = v * inv_np if name == "PreferredLeaderElectionGoal" else v
            elif name == "KafkaAssignerEvenRackAwareGoal":
                # rack half from the psummed row sums; leader-evenness half
                # from the (already global) aggregates — the full kernel's
                # math on sharded inputs (ccx.search.state decomposition)
                alive = m_local.broker_valid & m_local.broker_alive
                n_alive = jnp.maximum(jnp.sum(alive).astype(jnp.float32), 1.0)
                avg = jnp.sum(agg.leader_count).astype(jnp.float32) / n_alive
                upper = jnp.ceil(avg)
                over = jnp.where(
                    alive, jnp.maximum(agg.leader_count - upper, 0.0), 0.0
                )
                rack = psums[part_idx["RackAwareGoal"]]
                v = rack + jnp.sum(over > 0).astype(jnp.float32)
                c = rack + jnp.sum(over) / jnp.maximum(avg, 1e-9)
            else:
                r = GOAL_REGISTRY[name].fn(m_local, agg, cfg)
                v, c = r.violations, r.cost
            vio.append(v)
            cost.append(c)
        return jnp.stack(vio), jnp.stack(cost)

    fn = costmodel.instrument("sharded-stack-eval")(
        jax.jit(
            _shard_map(body, mesh, in_specs=(specs,), out_specs=(P(), P()))
        )
    )
    _cache_put(_EVAL_CACHE, cache_key, fn)
    violations, costs = fn(m)
    return StackResult(
        names=tuple(goal_names),
        hard_mask=hard_mask,
        violations=violations,
        costs=costs,
    )


# ---------------------------------------------------------------------------
# Partition-axis-sharded simulated annealing
# ---------------------------------------------------------------------------

def _mask_view(view, owned):
    """Zero a PartitionView's contribution on non-owner shards so a psum
    reconstructs the owner's values (``owned`` broadcasts over trailing
    axes of stacked views)."""

    def mask(x):
        ow = owned.reshape(owned.shape + (1,) * (x.ndim - owned.ndim))
        if x.dtype == jnp.bool_:
            return x & ow
        return x * ow.astype(x.dtype)

    return jax.tree.map(mask, view)


def _psum_tree(tree, axis):
    def red(x):
        if x.dtype == jnp.bool_:
            return jax.lax.psum(x.astype(jnp.int32), axis) > 0
        return jax.lax.psum(x, axis)

    return jax.tree.map(red, tree)


def sharded_anneal(
    m: TensorClusterModel,
    cfg: GoalConfig = GoalConfig(),
    goal_names: tuple[str, ...] = DEFAULT_GOAL_ORDER,
    opts=None,
    mesh: Mesh | None = None,
    evac=None,
):
    """Batched SA with the model's partition axis sharded inside the search
    (SURVEY.md section 5.7, the long-context analogue): model tensors stay
    sharded over ``parts`` for the whole run — they are never replicated —
    while chains ride the ``chains`` axis as data parallelism.

    Per proposal, the shard owning the drawn partition gathers its
    PartitionView locally and one ``psum`` over ICI broadcasts it (O(R)
    scalars — the only per-step collective; batched steps amortize it to
    ONE stacked gather+psum per step); every shard then scores and accepts
    identically (replicated RNG), and only the owner writes the placement
    row. Aggregates/accumulators are replicated per chain and updated
    identically everywhere, so no resynchronization is ever needed.

    With ``opts.chunk_steps > 0`` the run is CHUNK-DRIVEN (the production
    path — ``anneal(mesh=...)`` and ``optimize()`` land here): one
    compiled shard_map chunk program per static shape, with the step
    budget, cooling schedule and swap ramp entering as traced data —
    retunes never recompile — driven by ``annealer.drive_chunks``, so a
    mesh run emits the same per-chunk flight-recorder heartbeats, obeys
    the stall watchdog and banks ``costmodel`` capture exactly like the
    single-chip chunk engine. SA chunks return no early-exit scalar: zero
    host syncs, the chunks queue on the device streams and the chunk
    boundary costs only the heartbeat. ``chunk_steps == 0`` keeps the
    one-shot monolithic scan (compile keyed on the step count — the
    parity reference).

    ``opts.n_chains`` is rounded up to the next multiple of the mesh's
    chain ranks when it does not divide (logged, never an abort);
    ``evac`` optionally supplies a precomputed hot-partition list
    ``(indices, count)`` like ``anneal``.

    Semantics match ``ccx.search.anneal`` (same RNG stream, same acceptance
    rule); results can differ only by float reduction order in the initial
    psummed aggregates.
    """
    import dataclasses as _dc

    from ccx.common.tracing import TRACER
    from ccx.goals.stack import evaluate_stack, soft_weights
    from ccx.search.annealer import (
        CAPACITY_GOALS as CAPACITY_GOALS_,
        RACK_TARGET_GOALS,
        AnnealOptions,
        AnnealResult,
        ProposalParams,
        _anneal_step,
        _anneal_step_batched,
        _swap_ramp_of,
        allows_inter_broker,
        best_chain_index,
        drive_chunks,
        hot_partition_list,
        lead_swap_share,
        round_up_chains,
    )
    from ccx.search.state import (
        PartitionView,
        SearchState,
        TopicGroup,
        make_cost_vector_fn,
        make_move_scorer,
        make_swap_scorer,
        make_topic_group,
        max_partitions_per_topic,
        stack_needs_topic,
        with_placement,
    )
    from ccx.goals import topic_terms as tt_

    if opts is None:
        opts = AnnealOptions()
    if mesh is None:
        mesh = make_mesh()
    n_parts = mesh.shape[PARTS_AXIS]
    n_chain_ranks = mesh.shape[CHAINS_AXIS]
    if m.P % n_parts:
        raise ValueError(f"padded P={m.P} not divisible by parts={n_parts}")
    if opts.n_temps > 1:
        # the partition-axis engine builds its own chunk program (one
        # owner-gather + psum per step) and does not carry the exchange
        # sweep yet — run flat rather than abort; chains-mesh data
        # parallelism (parts == 1) goes through annealer._run_chunk and
        # gets the full ladder.
        import logging

        logging.getLogger(__name__).warning(
            "sharded_anneal: replica-exchange ladder (n_temps=%d) is not "
            "supported by the partition-axis-sharded engine; running flat",
            opts.n_temps,
        )
        opts = _dc.replace(opts, n_temps=1)
    n_chains = round_up_chains(opts.n_chains, n_chain_ranks, "sharded_anneal")
    if n_chains != opts.n_chains:
        opts = _dc.replace(opts, n_chains=n_chains)

    stack_before = evaluate_stack(m, cfg, goal_names)
    p_real = int(np.asarray(m.partition_valid).sum())
    bv = np.asarray(m.broker_valid)
    b_real = int(np.max(np.where(bv, np.arange(m.B), -1))) + 1
    evac_np, n_evac_i = (
        evac if evac is not None else hot_partition_list(m, goal_names, cfg)
    )

    hard_mask = tuple(GOAL_REGISTRY[n].hard for n in goal_names)
    allow_inter = allows_inter_broker(goal_names)
    pp = ProposalParams(
        p_real=p_real,
        b_real=b_real,
        p_leadership=opts.p_leadership,
        p_disk=opts.p_disk,
        p_biased_dest=opts.p_biased_dest,
        p_evac=opts.p_evac,
        target_rack=bool(RACK_TARGET_GOALS & set(goal_names)),
        allow_inter=allow_inter,
        p_swap=opts.p_swap if allow_inter else 0.0,
        target_capacity=bool(CAPACITY_GOALS_ & set(goal_names)),
        cap_thresholds=tuple(cfg.capacity_threshold),
        p_lead_swap=lead_swap_share(opts.p_leadership),
        # swap-knob parity with annealer._build_step: the coupled
        # endpoint draw and the p_swap schedule run under sharding too
        p_couple=opts.swap_coupling if allow_inter else 0.0,
        couple_pool=opts.couple_pool,
    )
    schedule_on = allow_inter and opts.p_swap_end >= 0

    m_sharded = shard_model(m, mesh)
    keys = jax.random.split(jax.random.PRNGKey(opts.seed), opts.n_chains)
    keys = jax.device_put(keys, NamedSharding(mesh, P(CHAINS_AXIS, None)))
    evac = jax.device_put(jnp.asarray(evac_np), NamedSharding(mesh, P()))
    n_evac = jax.device_put(
        jnp.asarray(n_evac_i, jnp.int32), NamedSharding(mesh, P())
    )
    # Static topic-membership structure (GLOBAL partition ids), replicated.
    # The grouped placement mirror it indexes is replicated per chain: every
    # shard sees the psum'd view of each move, so all shards write identical
    # mirror cells — reads then need no collective.
    needs_topic = stack_needs_topic(goal_names)
    group_rep = (
        jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(mesh, P())),
            make_topic_group(m, max_partitions_per_topic(m)),
        )
        if needs_topic
        else None
    )

    mspecs = model_pspecs(m)
    state_specs = SearchState(
        assignment=P(CHAINS_AXIS, PARTS_AXIS, None),
        leader_slot=P(CHAINS_AXIS, PARTS_AXIS),
        replica_disk=P(CHAINS_AXIS, PARTS_AXIS, None),
        agg=BrokerAggregates(
            broker_load=P(CHAINS_AXIS, None, None),
            replica_count=P(CHAINS_AXIS, None),
            leader_count=P(CHAINS_AXIS, None),
            potential_nw_out=P(CHAINS_AXIS, None),
            leader_bytes_in=P(CHAINS_AXIS, None),
            topic_replica_count=P(CHAINS_AXIS, None, None),
            topic_leader_count=P(CHAINS_AXIS, None, None),
            disk_load=P(CHAINS_AXIS, None, None),
        ),
        part_sums=P(CHAINS_AXIS, None),
        topic_totals=P(CHAINS_AXIS, None),
        mtl_sum=P(CHAINS_AXIS),
        trd_sum=P(CHAINS_AXIS),
        cost_vec=P(CHAINS_AXIS, None),
        key=P(CHAINS_AXIS, None),
        n_accepted=P(CHAINS_AXIS),
        hard_mask=hard_mask,
        grouped_assign=(
            P(CHAINS_AXIS, None, None, None) if needs_topic else None
        ),
        grouped_leader=(
            P(CHAINS_AXIS, None, None) if needs_topic else None
        ),
        n_prop_kind=P(CHAINS_AXIS, None),
        n_acc_kind=P(CHAINS_AXIS, None),
    )

    group_specs = (
        TopicGroup(members=P(), member_slot=P()) if needs_topic else None
    )

    import functools as _ft

    # ---- shard-local building blocks ------------------------------------
    # Shared by the monolithic scan and the chunked program bodies. These
    # are per-call closures; the compiled programs built from them are
    # cached at module level keyed on EVERY static they capture (see the
    # cache keys below), so a later call with an identical key safely
    # reuses the first call's closures.

    def _init_states(m_local, keys_local, group_l):
        """Init section: partial sums + psum -> replicated bookkeeping,
        grouped-placement mirror reconstruction, vmapped chain states."""
        P_local = m_local.assignment.shape[0]
        offset = jax.lax.axis_index(PARTS_AXIS) * P_local
        agg = _psum_tree(broker_aggregates(m_local), PARTS_AXIS)
        part_sums = jax.lax.psum(
            pt.partition_sums(
                m_local,
                m_local.assignment,
                m_local.leader_slot,
                m_local.replica_disk,
                m_local.partition_valid,
            ),
            PARTS_AXIS,
        )
        mtl_sum = jnp.sum(
            tt_.mtl_row(
                m_local, cfg, m_local.topic_min_leaders, agg.topic_leader_count
            )
        )
        pen, _ = tt_.trd_row_pen(m_local, cfg, agg.topic_replica_count)
        trd_sum = jnp.sum(pen)
        topic_totals = tt_.trd_row_total(m_local, agg.topic_replica_count)
        trd_norm = tt_.trd_normalizer(m_local, topic_totals)
        cost_vec = make_cost_vector_fn(m_local, goal_names, cfg)(
            agg, part_sums, mtl_sum, trd_sum, trd_norm
        )
        # search never carries the [T, B] matrices (ccx.search.state
        # module docstring) — loud dummies, same as init_search_state
        agg = agg.replace(
            topic_replica_count=jnp.zeros((1, 1), jnp.int32),
            topic_leader_count=jnp.zeros((1, 1), jnp.int32),
        )
        # grouped placement mirror, replicated: each member partition is
        # owned by exactly one shard, which contributes row+1 (others 0);
        # the psum minus 1 reconstructs the row (-1 for pad entries)
        ga = gl = None
        if group_l is not None:
            mp = group_l.members
            li = mp - offset
            mine = (mp >= 0) & (li >= 0) & (li < P_local)
            lic = jnp.clip(li, 0, P_local - 1)
            ga = (
                jax.lax.psum(
                    jnp.where(
                        mine[..., None],
                        m_local.assignment[lic] + 1,
                        0,
                    ),
                    PARTS_AXIS,
                )
                - 1
            )
            gl = (
                jax.lax.psum(
                    jnp.where(mine, m_local.leader_slot[lic] + 1, 0),
                    PARTS_AXIS,
                )
                - 1
            )
        state0 = SearchState(
            assignment=m_local.assignment,
            leader_slot=m_local.leader_slot,
            replica_disk=m_local.replica_disk,
            agg=agg,
            part_sums=part_sums,
            topic_totals=topic_totals,
            mtl_sum=mtl_sum,
            trd_sum=trd_sum,
            cost_vec=cost_vec,
            key=keys_local[0],
            n_accepted=jnp.asarray(0, jnp.int32),
            hard_mask=hard_mask,
            grouped_assign=ga,
            grouped_leader=gl,
            n_prop_kind=jnp.zeros(3, jnp.int32),
            n_acc_kind=jnp.zeros(3, jnp.int32),
        )
        return jax.vmap(lambda k: state0.replace(key=k))(keys_local)

    def _make_step(m_local, group_l, swap_ramp):
        """The shard-local step partial: owner-gather/locate sharding hooks
        around the SAME _anneal_step bodies the single-chip engine runs.
        ``swap_ramp`` may be a python float (monolith — folded statically)
        or a traced scalar (chunk program — schedule retunes reuse it)."""
        P_local = m_local.assignment.shape[0]
        offset = jax.lax.axis_index(PARTS_AXIS) * P_local

        def gather(ss, _m, ps):
            # stacked owner-gather + psum: ps is int32[k] of GLOBAL ids
            li = jnp.clip(ps - offset, 0, P_local - 1)
            owned = (ps >= offset) & (ps < offset + P_local)
            view_local = PartitionView(
                pvalid=m_local.partition_valid[li] & owned,
                immovable=m_local.partition_immovable[li] & owned,
                topic=m_local.partition_topic[li],
                lead_load=m_local.leader_load[:, li].T,
                foll_load=m_local.follower_load[:, li].T,
                assign=ss.assignment[li],
                leader=ss.leader_slot[li],
                disk=ss.replica_disk[li],
            )
            return _psum_tree(_mask_view(view_local, owned), PARTS_AXIS)

        def locate(p):
            owned = (p >= offset) & (p < offset + P_local)
            return jnp.clip(p - offset, 0, P_local - 1), owned

        # same small-cluster + p_swap gate as annealer._run_chains
        # (p_swap == 0 stacks keep the sequential inner_single_only
        # fast path — one use per carried buffer)
        batched = (
            opts.batched
            and opts.moves_per_step > 1
            and (pp.p_swap > 0.0 or schedule_on)
            and b_real >= 4 * m_local.R * opts.moves_per_step
        )
        return _ft.partial(
            _anneal_step_batched if batched else _anneal_step,
            m=m_local,
            pp=pp,
            hard_arr=jnp.asarray(hard_mask),
            weights=soft_weights(hard_mask),
            moves_per_step=max(opts.moves_per_step, 1),
            scorer=make_move_scorer(m_local, goal_names, cfg),
            swap_scorer=make_swap_scorer(m_local, goal_names, cfg),
            gather=gather,
            locate=locate,
            group=group_l,
            swap_ramp=swap_ramp,
            swap_schedule_on=schedule_on,
            cfg=cfg,
            **(
                {"vector_fn": make_cost_vector_fn(m_local, goal_names, cfg)}
                if batched
                else {}
            ),
        )

    n = max(opts.n_steps, 1)
    decay = (opts.t1 / opts.t0) ** (1.0 / max(n - 1, 1))

    # shape-keyed engine span (the greedy descent idiom): drive_chunks
    # heartbeats attach the live chunk index here, so a flight recording
    # of a wedged mesh run names the sharded program and how deep it got
    with TRACER.span(
        "sharded-anneal",
        chains=opts.n_chains, steps=opts.n_steps,
        chunkSteps=opts.chunk_steps,
        meshChains=n_chain_ranks, meshParts=n_parts,
    ):
        if opts.chunk_steps > 0:
            # ---- chunk-driven path (the production mesh path) ------------
            # One compiled shard_map chunk program per static shape; the
            # step budget (n_total), cooling schedule (t_offset, decay) and
            # swap ramp enter as TRACED scalars — n_steps/t1/p_swap_end
            # retunes never recompile (t >= n_total steps are inert, the
            # single-chip _run_chunk contract). Driven by drive_chunks: one
            # heartbeat per chunk, no device sync (SA returns done=None).
            chunk = int(opts.chunk_steps)
            init_key = (
                "init", mesh, goal_names, cfg, needs_topic, _struct_key(m),
            )
            init_fn = _cache_get(_RUN_CACHE, init_key)
            if init_fn is None:

                def _init_run(m_s, keys_s, group_arg):
                    # init mixes axis-invariant model stats with
                    # axis-varying keys; skip the varying-axes check
                    return _shard_map(
                        _init_states,
                        mesh,
                        in_specs=(mspecs, P(CHAINS_AXIS, None), group_specs),
                        out_specs=state_specs,
                        check=False,
                    )(m_s, keys_s, group_arg)

                init_fn = costmodel.instrument("sharded-chain-init")(
                    jax.jit(_init_run)
                )
                _cache_put(_RUN_CACHE, init_key, init_fn)

            # convergence taps (ccx.search.telemetry): the tap update runs
            # OUTSIDE the shard_map body, in the same jitted program — a
            # tiny auto-sharded reduction over the [chains, G] cost
            # vectors, no extra host sync, replicated output. Tap
            # presence is program shape, so it joins the cache key.
            from ccx.search import telemetry as _telemetry

            taps_on = _telemetry.enabled()
            chunk_key = (
                "chunk", mesh, goal_names, cfg, pp, b_real,
                opts.t0, opts.moves_per_step, opts.batched, schedule_on,
                needs_topic, chunk, taps_on, _struct_key(m),
            )
            chunk_fn = _cache_get(_RUN_CACHE, chunk_key)
            if chunk_fn is None:

                def _chunk_run(states, m_s, evac_s, n_evac_s, group_arg,
                               t_offset, decay_t, ramp_t, n_total,
                               tap=None):
                    def body(ss, m_local, evac_l, n_evac_l, group_l,
                             t_off, dec, ramp, n_tot):
                        step = _make_step(m_local, group_l, ramp)

                        def scan_body(s, t):
                            def active(si):
                                temp = opts.t0 * dec**t
                                return jax.vmap(
                                    step, in_axes=(0, None, None, None, None)
                                )(si, temp, t, evac_l, n_evac_l)

                            return (
                                jax.lax.cond(
                                    t < n_tot, active, lambda si: si, s
                                ),
                                None,
                            )

                        ss, _ = jax.lax.scan(
                            scan_body, ss, t_off + jnp.arange(chunk)
                        )
                        return ss

                    # the scan carry mixes axis-invariant init values
                    # with axis-varying updates; skip the check
                    states = _shard_map(
                        body,
                        mesh,
                        in_specs=(
                            state_specs, mspecs, P(), P(), group_specs,
                            P(), P(), P(), P(),
                        ),
                        out_specs=state_specs,
                        check=False,
                    )(states, m_s, evac_s, n_evac_s, group_arg,
                      t_offset, decay_t, ramp_t, n_total)
                    if tap is not None:
                        t_last = jnp.maximum(
                            jnp.minimum(t_offset + chunk, n_total) - 1, 0
                        )
                        tap = _telemetry.record(
                            tap,
                            _telemetry.lex_best_row(states.cost_vec),
                            jnp.sum(states.n_prop_kind, axis=0),
                            jnp.sum(states.n_acc_kind, axis=0),
                            opts.t0 * decay_t**t_last,
                        )
                    return states, tap

                chunk_fn = costmodel.instrument(
                    "sharded-sa-chunk", iters=lambda k, c=chunk: c
                )(jax.jit(_chunk_run, donate_argnums=(0,)))
                _cache_put(_RUN_CACHE, chunk_key, chunk_fn)

            rep = NamedSharding(mesh, P())
            decay_j = jax.device_put(jnp.float32(decay), rep)
            ramp_j = jax.device_put(
                jnp.float32(_swap_ramp_of(opts, n)), rep
            )
            n_j = jax.device_put(jnp.asarray(n, jnp.int32), rep)
            states = init_fn(m_sharded, keys, group_rep)
            tap = (
                jax.device_put(
                    _telemetry.make_tap(len(goal_names)), rep
                )
                if taps_on
                else None
            )

            def run_one(carry, off):
                ss, tp = carry
                off_j = jax.device_put(jnp.asarray(off, jnp.int32), rep)
                return chunk_fn(
                    ss, m_sharded, evac, n_evac, group_rep,
                    off_j, decay_j, ramp_j, n_j, tp,
                ), None

            probe = None
            if tap is not None:
                # tier-0 heartbeat energy, non-blocking (drive_chunks
                # reads it via is_ready — the mesh path has no sync)
                def probe(carry):
                    return jnp.min(carry[0].cost_vec[:, 0])

            states, tap = drive_chunks(
                run_one, (states, tap), total=n, chunk=chunk, probe=probe
            )
            convergence = _telemetry.decode(
                tap, goal_names, chunk_size=chunk, budget=n
            )
        else:
            # ---- monolithic one-shot scan (parity reference) -------------
            # Reuse the compiled program across calls (see _struct_key: a
            # fresh jit closure per call would retrace + recompile every
            # time — ~26 s/call at 256 brokers / 16k partitions). Keyed on
            # every static the closure captures; shapes are covered by
            # _struct_key + jit's own shape-based retrace.
            cache_key = (
                "run", mesh, goal_names, cfg, pp, b_real,
                opts.n_steps, opts.t0, opts.t1, opts.moves_per_step,
                opts.batched, opts.p_swap_end,
                needs_topic, _struct_key(m),
            )
            run = _cache_get(_RUN_CACHE, cache_key)
            if run is None:

                def _run(m_s, keys_s, evac_s, n_evac_s, group_arg):
                    def body(m_local, keys_local, evac_l, n_evac_l, group_l):
                        states = _init_states(m_local, keys_local, group_l)
                        step = _make_step(
                            m_local, group_l, _swap_ramp_of(opts, n)
                        )

                        def scan_body(ss, t):
                            temp = opts.t0 * decay**t
                            ss = jax.vmap(
                                step, in_axes=(0, None, None, None, None)
                            )(ss, temp, t, evac_l, n_evac_l)
                            return ss, None

                        states, _ = jax.lax.scan(
                            scan_body, states, jnp.arange(n)
                        )
                        return states

                    # the scan carry mixes axis-invariant init values
                    # with axis-varying updates; skip the check
                    return _shard_map(
                        body,
                        mesh,
                        in_specs=(
                            mspecs, P(CHAINS_AXIS, None), P(), P(),
                            group_specs,
                        ),
                        out_specs=state_specs,
                        check=False,
                    )(m_s, keys_s, evac_s, n_evac_s, group_arg)

                run = costmodel.instrument(
                    "sharded-sa-monolith", iters=lambda k, it=n: it
                )(jax.jit(_run))
                _cache_put(_RUN_CACHE, cache_key, run)
            states = run(m_sharded, keys, evac, n_evac, group_rep)
            convergence = None
    return _finish_sharded_anneal(
        m_sharded, states, cfg, goal_names, opts, stack_before,
        convergence=convergence,
    )


def _finish_sharded_anneal(m_sharded, states, cfg, goal_names, opts,
                           stack_before, convergence=None):
    from ccx.search.annealer import AnnealResult, best_chain_index
    from ccx.search.state import with_placement
    from ccx.goals.stack import evaluate_stack

    best = best_chain_index(np.asarray(states.cost_vec))
    pick = jax.tree.map(lambda a: a[best], states)
    result_model = with_placement(m_sharded, pick)
    stack_after = evaluate_stack(result_model, cfg, goal_names)
    return AnnealResult(
        model=result_model,
        stack_before=stack_before,
        stack_after=stack_after,
        n_accepted=int(np.asarray(pick.n_accepted)),
        n_chains=opts.n_chains,
        n_steps=opts.n_steps,
        best_chain=best,
        n_prop_kind=tuple(int(x) for x in np.asarray(pick.n_prop_kind)),
        n_acc_kind=tuple(int(x) for x in np.asarray(pick.n_acc_kind)),
        convergence=convergence,
    )
