from ccx.parallel.sharding import (
    make_mesh,
    model_pspecs,
    replicate,
    shard_model,
    sharded_anneal,
    sharded_stack_eval,
)

__all__ = [
    "make_mesh",
    "model_pspecs",
    "replicate",
    "shard_model",
    "sharded_anneal",
    "sharded_stack_eval",
]
