"""Hard-goal infeasibility proofs — OptimizationFailureException parity.

The reference raises ``OptimizationFailureException`` when a hard goal is
violated and *unfixable* (SURVEY.md C16: "violation => Optimization-
FailureException if unfixable"). The tensor rebuild separates the two
concerns: search reduces violations; this module supplies *conservative
lower-bound proofs* that no placement could satisfy a hard goal, so the
verifier (ccx.verify) and service can distinguish "the input is impossible"
from "the search under-converged". A goal reported here is provably
infeasible; absence of a report does NOT prove feasibility.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ccx.common.resources import Resource
from ccx.goals.base import GoalConfig
from ccx.model.tensor_model import TensorClusterModel

_CAPACITY_GOAL_RESOURCE = {
    "CpuCapacityGoal": Resource.CPU,
    "NetworkInboundCapacityGoal": Resource.NW_IN,
    "NetworkOutboundCapacityGoal": Resource.NW_OUT,
    "DiskCapacityGoal": Resource.DISK,
}


@dataclasses.dataclass
class FeasibilityReport:
    """goal name -> human-readable proof of infeasibility."""

    infeasible: dict[str, str]

    def __contains__(self, goal: str) -> bool:
        return goal in self.infeasible

    def to_json(self) -> dict:
        return dict(self.infeasible)


def feasibility_report(
    m: TensorClusterModel, cfg: GoalConfig = GoalConfig()
) -> FeasibilityReport:
    out: dict[str, str] = {}
    pvalid = np.asarray(m.partition_valid)
    alive = np.asarray(m.broker_alive & m.broker_valid)
    n_alive = int(alive.sum())
    a = np.asarray(m.assignment)
    rf = ((a >= 0) & pvalid[:, None]).sum(axis=1)
    lead = np.asarray(m.leader_load)[:, : m.P]
    foll = np.asarray(m.follower_load)[:, : m.P]

    if n_alive == 0:
        return FeasibilityReport({"StructuralFeasibility": "no alive brokers"})

    # --- capacity goals ----------------------------------------------------
    cap = np.asarray(m.broker_capacity)
    for goal, res in _CAPACITY_GOAL_RESOURCE.items():
        th = cfg.capacity_threshold[int(res)]
        allowed = np.where(alive, cap[res] * th, 0.0)
        max_allowed = float(allowed.max(initial=0.0))
        # (a) some partition's leader alone exceeds every broker's allowance
        # (every partition must lead somewhere; follower load <= leader load
        # for all resources, so the leader bound is the tight one).
        worst = float(np.where(pvalid, lead[res], 0.0).max(initial=0.0))
        if worst > max_allowed * (1 + 1e-6):
            out[goal] = (
                f"partition leader load {worst:.3f} exceeds max broker "
                f"allowance {max_allowed:.3f} ({res.name})"
            )
            continue
        # (b) total minimal load exceeds total allowance
        total = float(
            np.sum(np.where(pvalid, lead[res] + foll[res] * np.maximum(rf - 1, 0), 0.0))
        )
        if total > float(allowed.sum()) * (1 + 1e-6):
            out[goal] = (
                f"total load {total:.3f} exceeds cluster allowance "
                f"{float(allowed.sum()):.3f} ({res.name})"
            )

    # --- replica count capacity -------------------------------------------
    total_replicas = int(rf.sum())
    if total_replicas > cfg.max_replicas_per_broker * n_alive:
        out["ReplicaCapacityGoal"] = (
            f"{total_replicas} replicas > {cfg.max_replicas_per_broker:.0f} "
            f"per broker x {n_alive} alive brokers"
        )

    # --- rack awareness ----------------------------------------------------
    racks = np.asarray(m.broker_rack)
    n_alive_racks = len(set(racks[alive].tolist()))
    max_rf = int(rf.max(initial=0))
    if max_rf > n_alive_racks:
        out["RackAwareGoal"] = (
            f"replication factor {max_rf} > {n_alive_racks} racks with alive brokers"
        )
    # RackAwareDistribution allows ceil(rf/#racks) per rack — always
    # satisfiable when enough alive brokers exist per rack; conservative:
    # only flag when some partition's rf exceeds the total alive brokers.
    if max_rf > n_alive:
        out["RackAwareDistributionGoal"] = (
            f"replication factor {max_rf} > {n_alive} alive brokers"
        )
        out.setdefault(
            "StructuralFeasibility",
            f"replication factor {max_rf} > {n_alive} alive brokers",
        )

    # --- min topic leaders -------------------------------------------------
    tml = np.asarray(m.topic_min_leaders)
    if tml.any():
        # only brokers that may hold leadership need min leaders
        eligible = alive & ~np.asarray(m.broker_excl_leadership)
        n_eligible = int(eligible.sum())
        topics = np.asarray(m.partition_topic)
        for t in np.nonzero(tml)[0]:
            n_parts = int(np.sum(pvalid & (topics == t)))
            need = cfg.min_topic_leaders_per_broker * n_eligible
            if 0 < n_parts < need:
                out["MinTopicLeadersPerBrokerGoal"] = (
                    f"topic {t}: {n_parts} partitions < "
                    f"{cfg.min_topic_leaders_per_broker} leaders x "
                    f"{n_eligible} eligible brokers"
                )
                break

    # --- JBOD disk capacity ------------------------------------------------
    disk_alive = np.asarray(m.disk_alive) & alive[:, None]
    if disk_alive.any():
        dcap = np.asarray(m.disk_capacity)
        allowance = np.where(disk_alive, dcap * cfg.intra_disk_capacity_threshold, 0.0)
        worst_disk_load = float(np.where(pvalid, lead[Resource.DISK], 0.0).max(initial=0.0))
        if worst_disk_load > float(allowance.max(initial=0.0)) * (1 + 1e-6):
            out["IntraBrokerDiskCapacityGoal"] = (
                f"partition disk load {worst_disk_load:.3f} exceeds max disk "
                f"allowance {float(allowance.max(initial=0.0)):.3f}"
            )

    return FeasibilityReport(out)
