"""CruiseControl — the service façade: one method per operation verb.

Parity: ``KafkaCruiseControl.java`` + ``KafkaCruiseControlApp`` lifecycle
(SURVEY.md C22, call stacks 3.1/3.2/3.3): construction wires LoadMonitor,
the analyzer (TPU optimizer), Executor and AnomalyDetectorManager; startUp
order is monitor → detector → (REST server started by the caller). Each verb
builds a model from the monitor, runs the goal stack on device, and either
returns the dry-run result or hands proposals to the executor.

The analyzer side honors ``goal.optimizer.backend`` (north star
``=tpu``, BASELINE.json:5): 'tpu' = batched SA + polish on device,
'greedy' = host-side greedy oracle only.
"""

from __future__ import annotations

import threading
import time as _time

import logging

from ccx.common.exceptions import (
    OptimizationFailureException,
    UserRequestException,
)
from ccx.common import profiling
from ccx.common.metrics import REGISTRY
from ccx.common.tracing import TRACER

#: the reference's separate operations log (SURVEY.md §5.1: log4j
#: `operationLogger` recording every request/decision)
oplog = logging.getLogger("ccx.operationLogger")
from ccx.detector.manager import AnomalyDetectorManager
from ccx.detector.provisioner import BasicProvisioner
from ccx.executor.admin import SimulatedAdminClient
from ccx.executor.executor import Executor
from ccx.goals.base import GOAL_REGISTRY, GoalConfig
from ccx.goals.stack import INTRA_BROKER_GOAL_ORDER
from ccx.monitor.aggregator import ModelCompletenessRequirements
from ccx.monitor.load_monitor import LoadMonitor, ModelBuildOptions
from ccx.monitor.metricdef import BROKER_METRIC_DEF
from ccx.optimizer import OptimizeOptions, OptimizerResult, optimize
from ccx.search.annealer import AnnealOptions
from ccx.search.greedy import GreedyOptions, greedy_optimize
from ccx.proposals import columnar_diff


class CruiseControl:
    """The L4 façade (ref C22)."""

    def __init__(self, config, admin=None, clock=None, executor_waiter=None) -> None:
        self.config = config
        self.clock = clock or (lambda: int(_time.time() * 1000))
        self.admin = admin or config.configured_instance("admin.client.class")
        self.load_monitor = LoadMonitor(config, self.admin, clock=self.clock)
        self.executor = Executor(
            config, self.admin, clock=self.clock, waiter=executor_waiter,
            broker_metrics_fn=self._broker_health_metrics,
        )
        self.anomaly_detector = AnomalyDetectorManager(
            config, self.load_monitor, facade=self, clock=self.clock
        )
        self.provisioner = config.configured_instance("provisioner.class")
        if self.provisioner is None:
            self.provisioner = BasicProvisioner(config)
        self.goal_config = GoalConfig.from_config(config)
        self._proposal_cache: OptimizerResult | None = None
        self._proposal_cache_ms = -1
        self._proposal_lock = threading.Lock()
        # fleet serving (ccx.search.scheduler): per-CLUSTER proposal
        # mutual exclusion replaces the old coarse convoy — two proposals
        # for the same cluster still serialize (duplicate work, and the
        # executor must never see two racing plans for one cluster), but
        # concurrent Propose calls for different clusters interleave
        # chunks on the device instead of queueing behind one lock
        self._cluster_locks: dict[str, threading.Lock] = {}
        self._cluster_locks_guard = threading.Lock()
        from ccx.search import scheduler as _fleet

        _fleet.configure(
            max_concurrent=config["optimizer.fleet.max.concurrent"],
            dispatch_width=config["optimizer.fleet.dispatch.width"],
        )
        self._precompute_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._start_ms = self.clock()
        # observability wiring (ccx.common.tracing): arm the flight
        # recorder / stall watchdog / device-honest span timing from the
        # observability.* keys (env CCX_FLIGHT_RECORDER et al. still apply
        # when the keys are unset); live compile counters become /metrics
        # gauges so a wedged run is observable from outside
        from ccx.common import compilestats

        # tri-state precedence: a key ABSENT from the operator's properties
        # passes None (the env arming — CCX_FLIGHT_RECORDER et al. —
        # survives facade construction); a key explicitly set wins over
        # env, including explicit falsy values (watchdog.seconds=0 /
        # trace.sync=false are documented off-switches)
        def _explicit(key):
            return (
                config[key]
                if key in getattr(config, "originals", {})
                else None
            )

        TRACER.configure(
            sync=_explicit("observability.trace.sync"),
            watchdog_seconds=_explicit("observability.watchdog.seconds"),
            path=config["observability.flight.recorder.path"] or None,
        )
        compilestats.export_gauges(REGISTRY)
        # device cost observatory (ccx.common.costmodel): same tri-state
        # precedence as the tracer knobs — an absent capture key leaves
        # the env (CCX_COST_CAPTURE) in charge; roofline-ceiling overrides
        # default to the built-in device-spec table at 0
        from ccx.common import costmodel

        cap = _explicit("observability.cost.capture")
        if cap is not None:
            costmodel.set_capture(bool(cap))
        costmodel.set_device_override(
            config["observability.cost.peak.tflops"],
            config["observability.cost.hbm.gbps"],
        )
        # fleet snapshot-registry budget (0 = auto from device capacity
        # minus the captured watermark) — consumed by any in-process
        # sidecar registry; the standalone sidecar takes the env/flag twin
        costmodel.set_fleet_hbm_budget(
            config["optimizer.fleet.snapshot.hbm.mb"]
        )
        costmodel.export_gauges(REGISTRY)
        # unified device-memory ledger (ccx.common.devmem): one budget
        # pricing snapshot models + warm placement bases + the compiled
        # working set together, priority-aware eviction. 0 = fall through
        # to the fleet snapshot derivation above.
        from ccx.common import devmem as _devmem

        _devmem.configure(budget_mb=config["optimizer.devmem.budget.mb"])
        # convergence telemetry taps (ccx.search.telemetry): same
        # tri-state precedence — an absent key leaves the env
        # (CCX_CONVERGENCE) in charge of the default-on taps; the
        # ring-buffer depth is program shape, set once at construction
        from ccx.search import telemetry

        telemetry.configure(
            enabled=_explicit("observability.convergence"),
            max_chunks=config["observability.convergence.max.chunks"],
        )
        # incremental re-optimization (ccx.search.incremental, ISSUE 10):
        # size the process-wide warm-placement store; per-cluster
        # generations are facade-local monotonic counters
        from ccx.search import incremental as _incremental

        _incremental.configure(
            max_sessions=config["optimizer.incremental.max.sessions"]
        )
        self._incremental_gen: dict[str, int] = {}
        # fault injection (ccx.common.faults, ISSUE 12): armed ONLY by an
        # explicit spec — config here, CCX_FAULTS for bench/standalone
        # entry points; an empty spec leaves the registry disarmed (the
        # zero-overhead default)
        from ccx.common import faults as _faults

        fault_spec = config["observability.faults.spec"]
        if fault_spec:
            _faults.FAULTS.arm(
                fault_spec, seed=config["observability.faults.seed"]
            )

    # ----- lifecycle (ref startUp order: monitor -> detector -> servlet) ----

    def start_up(self, run_background_threads: bool = True) -> None:
        self.load_monitor.start_up(run_sampling_loop=run_background_threads)
        if run_background_threads:
            self.anomaly_detector.start_detection()
            if self.config["num.proposal.precompute.threads"] > 0:
                self._precompute_thread = threading.Thread(
                    target=self._precompute_loop,
                    name="ProposalCandidateComputer", daemon=True,
                )
                self._precompute_thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        self.anomaly_detector.shutdown()
        self.load_monitor.shutdown()

    # ----- goal plumbing ----------------------------------------------------

    def _resolve_goals(self, goals=None, self_healing: bool = False) -> tuple[str, ...]:
        """Request goal list -> registry stack with the structural term first
        (ref: goalsByPriority resolution in GoalOptimizer)."""
        if goals:
            unknown = [g for g in goals if g not in GOAL_REGISTRY]
            if unknown:
                raise UserRequestException(f"Unknown goals: {unknown}")
            names = tuple(goals)
        elif self_healing:
            names = tuple(self.config["self.healing.goals"]) or tuple(
                self.config["hard.goals"]
            )
        else:
            names = tuple(self.config["default.goals"]) or tuple(
                self.config["goals"]
            )
        names = tuple(g for g in names if g in GOAL_REGISTRY)
        return ("StructuralFeasibility",) + tuple(
            g for g in names if g != "StructuralFeasibility"
        )

    def _optimize_options(self, leadership_only: bool = False,
                          disk_only: bool = False) -> OptimizeOptions:
        anneal = AnnealOptions(
            n_chains=self.config["optimizer.num.chains"],
            n_steps=self.config["optimizer.num.steps"],
            moves_per_step=self.config["optimizer.moves.per.step"],
            seed=self.config["optimizer.seed"],
            chunk_steps=self.config["optimizer.chunk.steps"],
            p_swap=self.config["optimizer.swap.p.swap"],
            p_swap_end=self.config["optimizer.swap.p.swap.end"],
            swap_coupling=self.config["optimizer.swap.coupling"],
            n_temps=self.config["optimizer.exchange.n.temps"],
            exchange_interval=self.config["optimizer.exchange.interval"],
            bf16_scoring=self.config["optimizer.bf16.scoring"],
        )
        polish = GreedyOptions(
            n_candidates=self.config["optimizer.polish.candidates"],
            max_iters=self.config["optimizer.polish.max.iters"],
            batch_moves=self.config["optimizer.polish.batch.moves"],
            chunk_iters=self.config["optimizer.polish.chunk.iters"],
        )
        import dataclasses as _dc

        if leadership_only:
            # The annealer's swap branch mixes replica swaps in by draw, so
            # a leadership-only search (demote) disables swaps there; the
            # polish runs in leadership_only mode, where every proposal —
            # including swaps, which become count-preserving leadership
            # rotations — is guaranteed to keep replicas in place.
            anneal = _dc.replace(
                anneal, p_leadership=1.0, p_biased_dest=0.0, p_swap=0.0
            )
            polish = _dc.replace(polish, leadership_only=True)
        if disk_only:
            anneal = _dc.replace(
                anneal, p_disk=1.0, p_leadership=0.0, p_biased_dest=0.0,
                p_swap=0.0,
            )
            polish = _dc.replace(
                polish, p_disk=1.0, p_leadership=0.0, swap_fraction=0.0
            )
        return OptimizeOptions(
            anneal=anneal, polish=polish,
            check_evacuation=not disk_only,
            # the targeted TRD stage only applies to full placement stacks —
            # leadership-/disk-only paths never move topic replica counts
            topic_rebalance_rounds=(
                0 if (leadership_only or disk_only)
                else self.config["optimizer.topic.rebalance.rounds"]
            ),
            topic_rebalance_max_sweeps=self.config[
                "optimizer.topic.rebalance.max.sweeps"
            ],
            topic_rebalance_move_leaders=self.config[
                "optimizer.topic.rebalance.move.leaders"
            ],
            topic_rebalance_guarded=self.config[
                "optimizer.topic.rebalance.guarded"
            ],
            topic_rebalance_polish_iters=(
                None
                if self.config["optimizer.topic.rebalance.polish.iters"] < 0
                else self.config["optimizer.topic.rebalance.polish.iters"]
            ),
            leader_pass_max_iters=(
                None
                if self.config["optimizer.leader.pass.max.iters"] < 0
                else self.config["optimizer.leader.pass.max.iters"]
            ),
            # the portfolio candidate roughly doubles polish-phase cost;
            # never pay it on the leadership-/disk-only fast paths
            run_cold_greedy=(
                self.config["optimizer.portfolio.cold.greedy"]
                and not (leadership_only or disk_only)
            ),
            repair_backend=self.config["optimizer.repair.backend"],
            overlap_repair=self.config["optimizer.repair.overlap"],
            # mesh-sharded SA (REST-overridable like every optimizer.*
            # key): the facade runs sharded without bespoke entry points
            mesh_enabled=self.config["optimizer.mesh.enabled"],
            mesh_devices=self.config["optimizer.mesh.devices"],
            mesh_parts=self.config["optimizer.mesh.parts"],
            # swap-polish moves replicas between brokers: never on the
            # leadership-only (demote) or intra-broker (disk) fast paths
            swap_polish_iters=(
                0 if (leadership_only or disk_only)
                else self.config["optimizer.swap.polish.iters"]
            ),
            swap_polish_post_iters=(
                0 if (leadership_only or disk_only)
                else self.config["optimizer.swap.polish.post.iters"]
            ),
            swap_polish_candidates=self.config[
                "optimizer.swap.polish.candidates"
            ],
            swap_polish_guarded=self.config["optimizer.swap.polish.guarded"],
            swap_polish_chunk_iters=self.config[
                "optimizer.swap.polish.chunk.iters"
            ],
            # incremental re-optimization (ISSUE 10 / round 18): the
            # full warm pipeline serves the placement verbs; a
            # leadership-only verb (demote) warm-starts too, but with
            # the swap engine ZEROED — its stack is not intra-only, so
            # an armed swap polish would move replicas and break the
            # leadership-only contract — and the leadership pass as the
            # warm engine instead. Disk-only keeps from-scratch
            # semantics (intra-broker moves have no warm engine).
            incremental=self._incremental_options(
                disabled=disk_only, leadership_only=leadership_only
            ),
            # movement planning (ISSUE 17): wave-schedule inter-broker
            # movement; meaningless on the leadership-/disk-only fast
            # paths (no inter-broker moves to schedule)
            plan_enabled=(
                self.config["optimizer.plan.enabled"]
                and not (leadership_only or disk_only)
            ),
            plan_cost_tier=self.config["optimizer.plan.cost.tier"],
            plan_max_waves=self.config["optimizer.plan.max.waves"],
            plan_broker_cap=self.config["optimizer.plan.broker.cap"],
            plan_wave_bytes_mb=self.config["optimizer.plan.wave.bytes.mb"],
            # wave pricing prefers the executor's MEASURED per-wave MB/s
            # (ISSUE 20 satellite): once a movement wave has completed,
            # re-plans price the remaining waves with the observed rate
            # instead of the static config — the closed feedback loop
            plan_throttle_mb_per_sec=self._plan_throttle_mbps(),
        )

    def _plan_throttle_mbps(self) -> float:
        static = self.config["optimizer.plan.throttle.mbps"]
        if not self.config["optimizer.plan.throttle.measured"]:
            return static
        try:
            measured = self.executor.measured_wave_mb_per_sec()
        except Exception:  # noqa: BLE001 — pricing must never fail a verb
            measured = 0.0
        return measured if measured > 0.0 else static

    def _incremental_options(self, disabled: bool = False,
                             leadership_only: bool = False):
        from ccx.search.incremental import IncrementalOptions

        return IncrementalOptions(
            enabled=(
                not disabled
                and self.config["optimizer.incremental.enabled"]
            ),
            warm_swap_iters=(
                0 if leadership_only
                else self.config["optimizer.incremental.warm.swap.iters"]
            ),
            warm_swap_patience=self.config[
                "optimizer.incremental.warm.swap.patience"
            ],
            warm_swap_candidates=self.config[
                "optimizer.incremental.warm.swap.candidates"
            ],
            warm_steps=self.config["optimizer.incremental.warm.steps"],
            warm_chunk_steps=self.config[
                "optimizer.incremental.warm.chunk.steps"
            ],
            warm_chains=self.config["optimizer.incremental.warm.chains"],
            warm_moves_per_step=self.config["optimizer.incremental.warm.moves"],
            plateau_window=self.config["optimizer.incremental.plateau.window"],
            warm_t0=self.config["optimizer.incremental.warm.t0"],
            # the leadership-only warm engine: a demote's drift is pure
            # leadership, so the greedy leader pass (never a replica
            # move by construction) does the work the zeroed swap
            # engine would otherwise
            warm_leader_iters=(
                max(
                    self.config["optimizer.incremental.warm.leader.iters"],
                    8,
                )
                if leadership_only
                else self.config["optimizer.incremental.warm.leader.iters"]
            ),
            max_sessions=self.config["optimizer.incremental.max.sessions"],
            leadership_only=leadership_only,
        )

    def _cluster_lock(self, cluster_id: str | None = None) -> threading.Lock:
        """The per-cluster proposal mutex (fleet serving): proposals for
        ONE cluster serialize; different clusters never convoy."""
        cid = cluster_id or self.config["optimizer.fleet.cluster.id"]
        with self._cluster_locks_guard:
            return self._cluster_locks.setdefault(cid, threading.Lock())

    def _run_optimizer(self, model, goal_names, opts: OptimizeOptions,
                       progress=None, verb: str = "proposal",
                       urgent: bool = False,
                       cluster_id: str | None = None) -> OptimizerResult:
        backend = self.config["goal.optimizer.backend"]
        if progress:
            progress.step(f"Optimizing ({backend} backend, {len(goal_names)} goals)")
        cid = cluster_id or self.config["optimizer.fleet.cluster.id"]
        priority = (
            self.config["optimizer.fleet.priority.urgent"] if urgent else 0
        )
        from ccx.search.scheduler import FLEET

        # per-cluster mutual exclusion + fleet job registration: the verb
        # runs as one job on the multi-job chunk scheduler, and all its
        # spans/heartbeats carry job=<cluster-id>. Preemption semantics:
        # an urgent self-healing verb preempts OTHER clusters' in-flight
        # jobs at their next chunk boundary (and jumps the cross-cluster
        # run queue); verbs for the SAME cluster serialize on the cluster
        # lock BY DESIGN — the executor must never see two racing plans
        # for one cluster, so intra-cluster urgency means "next in line",
        # not mid-run cancellation.
        # verb span: the facade layer of the span pipeline (verb →
        # optimizer phases → chunk heartbeats → sidecar RPCs) — per-verb
        # Prometheus histogram + the flight-recorder breadcrumb naming
        # which operation a dead process was serving
        with self._cluster_lock(cid), \
                FLEET.job(cid, priority), \
                REGISTRY.timer("proposal-computation").time(), \
                TRACER.span(verb, kind="verb", backend=backend,
                            goals=len(goal_names)), \
                profiling.trace(self.config["optimizer.profile.dir"]):
            # incremental re-optimization (ISSUE 10): resolve this
            # cluster's last converged placement as the warm base (the
            # steady-state loop); a verified result banks the NEXT base.
            # Cold-start fallback is optimize()'s own (shape mismatch →
            # normal pipeline with the reason on the result).
            from ccx.search import incremental as _inc

            warm = None
            if getattr(opts, "incremental", None) is not None \
                    and opts.incremental.armed and backend != "greedy":
                warm = _inc.STORE.get(cid, priority=priority)
            res = self._run_optimizer_timed(
                model, goal_names, opts, progress, backend, warm_start=warm
            )
            if (
                getattr(opts, "incremental", None) is not None
                and opts.incremental.armed
                and backend != "greedy"
                and warm is None
                and res.incremental is None
            ):
                # documented cold start (the sidecar Propose contract,
                # now mirrored by every verb): warm was armed but no
                # base fit — say so on the result instead of silently
                # looking like a from-scratch run
                import dataclasses as _dc

                res = _dc.replace(
                    res,
                    incremental={
                        "warmStart": False, "coldStart": True,
                        "reason": (
                            f"no warm placement banked for cluster {cid!r}"
                        ),
                    },
                )
            if (
                getattr(opts, "incremental", None) is not None
                and opts.incremental.armed
                and backend != "greedy"
                and res.verification.ok
            ):
                gen = self._incremental_gen.get(cid, 0) + 1
                self._incremental_gen[cid] = gen
                # the verb's fleet priority prices the banked base on the
                # unified device-memory ledger (urgent self-healing bases
                # are protected from dryrun packing)
                _inc.remember(cid, gen, res.model, self.goal_config,
                              pressure=res.warm_pressure, priority=priority)
            return res

    def _run_optimizer_timed(self, model, goal_names, opts, progress,
                             backend, warm_start=None) -> OptimizerResult:
        if backend == "greedy":
            import time as _t

            t0 = _t.monotonic()
            g = greedy_optimize(model, self.goal_config, goal_names, opts.polish)
            from ccx.goals.stack import evaluate_stack
            from ccx.search.repair import finalize_preferred_leaders
            from ccx.verify import verify_optimization

            out_model, stack_after, _ = finalize_preferred_leaders(
                g.model, self.goal_config, goal_names, g.stack_after
            )
            dcols = columnar_diff(model, out_model)
            stack_before = evaluate_stack(model, self.goal_config, goal_names)
            verification = verify_optimization(
                model, out_model, self.goal_config, goal_names,
                proposals=dcols,
                require_hard_zero=opts.require_hard_zero,
                check_evacuation=opts.check_evacuation,
                stack_before=stack_before,
                stack_after=stack_after,
            )
            return OptimizerResult(
                diff=dcols,
                stack_before=stack_before,
                stack_after=stack_after,
                verification=verification,
                model=out_model,
                wall_seconds=_t.monotonic() - t0,
                n_sa_accepted=0,
                n_polish_moves=g.n_moves,
            )
        return optimize(model, self.goal_config, goal_names, opts,
                        warm_start=warm_start)

    def _model(self, options: ModelBuildOptions | None = None,
               requirements: ModelCompletenessRequirements | None = None,
               progress=None):
        if progress:
            progress.step("Acquiring cluster model")
        req = requirements or ModelCompletenessRequirements(1, 0.5)
        with self.load_monitor.acquire_for_model_generation():
            return self.load_monitor.cluster_model(req, options)

    def _finish(self, res: OptimizerResult, metadata, dryrun: bool,
                reason: str, uuid: str | None, progress=None,
                replication_throttle=None) -> dict:
        oplog.info(
            "operation uuid=%s dryrun=%s reason=%r proposals=%d verified=%s "
            "wall=%.3fs",
            uuid, dryrun, reason, len(res.proposals), res.verification.ok,
            res.wall_seconds,
        )
        REGISTRY.counter("operations" if dryrun else "executions").inc()
        out = res.to_json()
        out["dryRun"] = dryrun
        out["reason"] = reason
        out["provisionStatus"] = self.provisioner.rightsize(res.model).to_json()
        if not dryrun and res.proposals:
            # Never hand unverified proposals to the executor (ref: the
            # GoalOptimizer raises OptimizationFailureException instead of
            # executing). This is the only gate between the self-healing
            # auto-fix path (dryrun=False, no human in the loop) and the
            # cluster, so a broken optimization must fail loudly here.
            if not res.verification.ok:
                oplog.error(
                    "refusing to execute unverified proposals uuid=%s: %s",
                    uuid, "; ".join(res.verification.failures),
                )
                raise OptimizationFailureException(
                    "optimization result failed verification: "
                    + "; ".join(res.verification.failures)
                )
            if res.verification.infeasible:
                oplog.error(
                    "refusing to execute infeasible optimization uuid=%s: %s",
                    uuid, res.verification.infeasible,
                )
                raise OptimizationFailureException(
                    "hard goals unsatisfiable for this cluster: "
                    + "; ".join(
                        f"{g}: {why}"
                        for g, why in res.verification.infeasible.items()
                    )
                )
            if progress:
                progress.step(f"Executing {len(res.proposals)} proposals")
            self.executor.execute_proposals(
                res.proposals, metadata, uuid=uuid,
                replication_throttle=replication_throttle, background=True,
                plan=res.plan,
            )
            out["executionStarted"] = True
        return out

    # ----- verbs (one per REST operation, ref C22) --------------------------

    #: ref kafkaassigner-mode goal stack (SURVEY.md C19): the compatibility
    #: mode mimicking the older kafka-assigner tool
    KAFKA_ASSIGNER_GOALS = (
        "KafkaAssignerEvenRackAwareGoal",
        "KafkaAssignerDiskUsageDistributionGoal",
    )

    def rebalance(self, goals=None, dryrun: bool = True, reason: str = "",
                  self_healing: bool = False, excluded_topics: str = "",
                  uuid: str | None = None, progress=None,
                  rebalance_disk: bool = False,
                  destination_brokers=(),
                  kafka_assigner: bool = False,
                  data_from: str = "VALID_WINDOWS",
                  replication_throttle=None) -> dict:
        if rebalance_disk:
            return self.rebalance_disk(
                dryrun=dryrun, reason=reason, uuid=uuid, progress=progress
            )
        if kafka_assigner and not goals:
            goals = self.KAFKA_ASSIGNER_GOALS
        model, metadata, gen = self._model(
            ModelBuildOptions(excluded_topics_pattern=excluded_topics),
            requirements=_requirements_for(data_from),
            progress=progress,
        )
        model = _restrict_destinations(model, metadata, destination_brokers)
        res = self._run_optimizer(
            model, self._resolve_goals(goals, self_healing),
            self._optimize_options(), progress, verb="rebalance",
            urgent=self_healing,
        )
        return self._finish(res, metadata, dryrun, reason, uuid, progress,
                            replication_throttle)

    def add_brokers(self, broker_ids, goals=None, dryrun: bool = True,
                    reason: str = "", self_healing: bool = False,
                    uuid: str | None = None, progress=None,
                    replication_throttle=None) -> dict:
        """Move load onto the added brokers (ref addBrokers: existing brokers
        may not receive replicas during the operation)."""
        model, metadata, gen = self._model(
            ModelBuildOptions(brokers_to_add=tuple(broker_ids)),
            progress=progress,
        )
        import numpy as np

        new_mask = np.asarray(model.broker_new)
        excl = np.asarray(model.broker_valid) & ~new_mask
        model = model.replace(
            broker_excl_replicas=model.broker_excl_replicas | excl
        )
        res = self._run_optimizer(
            model, self._resolve_goals(goals, self_healing),
            self._optimize_options(), progress, verb="add-brokers",
            urgent=self_healing,
        )
        return self._finish(res, metadata, dryrun, reason, uuid, progress,
                            replication_throttle)

    def remove_brokers(self, broker_ids, goals=None, dryrun: bool = True,
                       reason: str = "", self_healing: bool = False,
                       uuid: str | None = None, progress=None,
                       destination_brokers=(),
                       replication_throttle=None) -> dict:
        """Evacuate the given brokers (ref removeBrokers; also the
        broker-failure self-healing fix, call stack 3.5)."""
        model, metadata, gen = self._model(
            ModelBuildOptions(brokers_to_remove=tuple(broker_ids)),
            progress=progress,
        )
        model = _restrict_destinations(model, metadata, destination_brokers)
        res = self._run_optimizer(
            model, self._resolve_goals(goals, self_healing),
            self._optimize_options(), progress, verb="remove-brokers",
            urgent=self_healing,
        )
        return self._finish(res, metadata, dryrun, reason, uuid, progress,
                            replication_throttle)

    def demote_brokers(self, broker_ids, dryrun: bool = True, reason: str = "",
                       self_healing: bool = False, uuid: str | None = None,
                       progress=None) -> dict:
        """Shed leadership from the given brokers (ref demoteBrokers →
        PreferredLeaderElectionGoal, leadership moves only)."""
        model, metadata, gen = self._model(
            ModelBuildOptions(brokers_to_demote=tuple(broker_ids)),
            progress=progress,
        )
        # urgent=self_healing (round 18 fix): a detector-triggered demote
        # (slow-broker self-healing) must preempt queued dryruns like the
        # other anomaly verbs — it previously dropped the flag and ran at
        # normal priority
        res = self._run_optimizer(
            model,
            ("StructuralFeasibility", "PreferredLeaderElectionGoal"),
            self._optimize_options(leadership_only=True),
            progress, verb="demote-brokers",
            urgent=self_healing,
        )
        return self._finish(res, metadata, dryrun, reason, uuid, progress)

    def fix_offline_replicas(self, goals=None, dryrun: bool = True,
                             reason: str = "", self_healing: bool = False,
                             uuid: str | None = None, progress=None) -> dict:
        """Move replicas off dead brokers/disks (ref fixOfflineReplicas;
        the disk-failure self-healing fix)."""
        model, metadata, gen = self._model(progress=progress)
        # the flagship urgent verb: replicas are offline NOW — it jumps
        # every queued dryrun at the next chunk boundary
        res = self._run_optimizer(
            model, self._resolve_goals(goals, self_healing=True),
            self._optimize_options(), progress, verb="fix-offline-replicas",
            urgent=True,
        )
        return self._finish(res, metadata, dryrun, reason, uuid, progress)

    def rebalance_disk(self, dryrun: bool = True, reason: str = "",
                       uuid: str | None = None, progress=None) -> dict:
        """Intra-broker JBOD rebalance (ref rebalance?rebalance_disk, C18)."""
        model, metadata, gen = self._model(
            ModelBuildOptions(populate_disks=True), progress=progress
        )
        res = self._run_optimizer(
            model, INTRA_BROKER_GOAL_ORDER,
            self._optimize_options(disk_only=True), progress,
            verb="rebalance-disk",
        )
        return self._finish(res, metadata, dryrun, reason, uuid, progress)

    def update_topic_configuration(self, topic_rf: dict[str, int],
                                   dryrun: bool = True, reason: str = "",
                                   self_healing: bool = False,
                                   uuid: str | None = None,
                                   progress=None) -> dict:
        """Change topic replication factors (ref TOPIC_CONFIGURATION
        endpoint): grow RF rack-aware onto least-loaded brokers, shrink by
        dropping the most-loaded non-leader replica; placement is then
        verified/executed through the normal proposal path."""
        if progress:
            progress.step("Computing replication-factor changes")
        metadata = self.admin.describe_cluster()
        from ccx.proposals import ExecutionProposal

        alive = metadata.alive_broker_ids()
        rack_of = {b.broker_id: b.rack for b in metadata.brokers}
        load = {b.broker_id: 0 for b in metadata.brokers}
        for p in metadata.partitions:
            for b in p.replicas:
                load[b] = load.get(b, 0) + 1
        proposals = []
        pidx = metadata.partition_index()
        for topic, target in topic_rf.items():
            for part in metadata.partitions_of(topic):
                current = list(part.replicas)
                new = list(current)
                while len(new) < target:
                    used_racks = {rack_of[b] for b in new}
                    candidates = sorted(
                        (b for b in alive if b not in new),
                        key=lambda b: (rack_of[b] in used_racks, load[b]),
                    )
                    if not candidates:
                        break
                    new.append(candidates[0])
                    load[candidates[0]] += 1
                while len(new) > target and len(new) > 1:
                    drop = max(
                        (b for b in new if b != part.leader),
                        key=lambda b: load[b],
                        default=None,
                    )
                    if drop is None:
                        break
                    new.remove(drop)
                    load[drop] -= 1
                if new != current:
                    proposals.append(
                        ExecutionProposal(
                            partition=pidx[part.tp], topic=0,
                            old_replicas=tuple(current),
                            new_replicas=tuple(new),
                            old_leader=part.leader, new_leader=part.leader,
                        )
                    )
        out = {
            "proposals": [p.to_json() for p in proposals],
            "numReplicaMovements": len(proposals),
            "dryRun": dryrun,
            "reason": reason,
        }
        if not dryrun and proposals:
            # proposals here already use real broker ids: execute with a
            # metadata whose broker order maps identity
            if progress:
                progress.step(f"Executing {len(proposals)} RF changes")
            self.executor.execute_proposals(
                proposals, _identity_metadata(metadata), uuid=uuid,
                background=True,
            )
            out["executionStarted"] = True
        return out

    def rightsize(self, progress=None) -> dict:
        """Ref RIGHTSIZE endpoint → Provisioner SPI (C21)."""
        model, metadata, gen = self._model(progress=progress)
        return self.provisioner.rightsize(model).to_json()

    def observability(self, include_threads: bool = False) -> dict:
        """The flight-deck endpoint (GET /observability): tracer + flight-
        recorder + watchdog state, live span stacks with chunk progress,
        live compile counters, the unified device-memory ledger, and —
        with ``threads=true`` — an all-thread stack dump. Works DURING a
        wedged proposal: the optimizer holds no lock this path needs, and
        a stuck device call releases the GIL."""
        out = TRACER.observability_json(threads=include_threads)
        out["deviceMemory"] = self._devmem_state()
        out["executor"] = self.executor.observability_json()
        # the closed-loop control plane (ISSUE 20): live SLO burn rates +
        # the healing-event timeline (detected -> fired -> recovered arcs
        # with cause attribution) — USER-gated like the rest of this view
        try:
            out["healing"] = self.anomaly_detector.stream.observability_json()
        except Exception:  # noqa: BLE001 — the view must stay readable
            pass
        return out

    # ----- cached proposals (ref GoalOptimizer precompute, C14) -------------

    def proposals(self, progress=None, ignore_cache: bool = False) -> dict:
        with self._proposal_lock:
            fresh = (
                self._proposal_cache is not None
                and self.clock() - self._proposal_cache_ms
                < self.config["proposal.expiration.ms"]
            )
            if fresh and not ignore_cache:
                out = self._proposal_cache.to_json()
                out["fromCache"] = True
                return out
        model, metadata, gen = self._model(progress=progress)
        res = self._run_optimizer(
            model, self._resolve_goals(), self._optimize_options(), progress,
            verb="proposals",
        )
        with self._proposal_lock:
            self._proposal_cache = res
            self._proposal_cache_ms = self.clock()
        out = res.to_json()
        out["fromCache"] = False
        return out

    def _precompute_loop(self) -> None:
        interval = max(self.config["proposal.expiration.ms"] / 2, 1000) / 1000.0
        while not self._stop.wait(interval):
            try:
                self.proposals(ignore_cache=True)
            except Exception:
                import logging

                logging.getLogger(__name__).exception("proposal precompute failed")

    # ----- read endpoints ---------------------------------------------------

    def state(self, substates: tuple[str, ...] = ()) -> dict:
        want = set(s.lower() for s in substates) or {
            "monitor", "executor", "analyzer", "anomaly_detector"
        }
        out: dict = {"version": 1}
        if "monitor" in want:
            out["MonitorState"] = self.load_monitor.state()
        if "executor" in want:
            out["ExecutorState"] = self.executor.state_json()
        if "analyzer" in want:
            from ccx.sidecar.wire import WIRE_VERSION

            with self._proposal_lock:
                out["AnalyzerState"] = {
                    "isProposalReady": self._proposal_cache is not None,
                    "readyGoals": list(self._resolve_goals()),
                    "backend": self.config["goal.optimizer.backend"],
                    # the sidecar envelope version this build speaks — lets
                    # an operator (or the JVM bridge) confirm wire compat
                    # from the REST state endpoint before routing proposals
                    "sidecarWireVersion": WIRE_VERSION,
                    # swap-engine state: which move-class escalation this
                    # analyzer runs (diagnosable from REST, like the wire
                    # version) — per-request overridable via the same keys
                    "swapEngine": {
                        "coupling": self.config["optimizer.swap.coupling"],
                        "pSwap": self.config["optimizer.swap.p.swap"],
                        "pSwapEnd": self.config["optimizer.swap.p.swap.end"],
                        "polishIters": self.config[
                            "optimizer.swap.polish.iters"
                        ],
                        "polishPostIters": self.config[
                            "optimizer.swap.polish.post.iters"
                        ],
                        "polishCandidates": self.config[
                            "optimizer.swap.polish.candidates"
                        ],
                        "polishGuarded": self.config[
                            "optimizer.swap.polish.guarded"
                        ],
                    },
                    # chunked-descent engine state (r8): the chunk sizes
                    # are the only shape-bearing polish budgets — an
                    # operator can confirm from REST that a budget retune
                    # cannot trigger a recompile (chunkIters unchanged);
                    # 0 flags that engine deliberately monolithic
                    "polishEngine": {
                        "chunkIters": self.config[
                            "optimizer.polish.chunk.iters"
                        ],
                        "swapPolishChunkIters": self.config[
                            "optimizer.swap.polish.chunk.iters"
                        ],
                    },
                    # incremental re-optimization state (ISSUE 10):
                    # armed + warm knobs + live store occupancy, so an
                    # operator confirms from REST whether steady-state
                    # proposals warm-start and how many sessions are
                    # device-resident
                    "incremental": self._incremental_state(),
                    # fleet serving state (ccx.search.scheduler): the
                    # multi-job chunk scheduler's live run queue + window
                    # stats — an operator confirms from REST that
                    # concurrent proposals interleave (meanDepth > 1)
                    # instead of convoying, and which cluster ids are
                    # active at what priority
                    "fleet": self._fleet_state(),
                    # flight-recorder / watchdog / span state (ccx.common.
                    # tracing), VIEWER-safe summary: STATE is viewer-
                    # readable, so this must not leak what security.py
                    # gates at USER on /observability (recorder file path,
                    # live span/thread stacks)
                    "observability": {
                        **TRACER.observability_summary(),
                        # mesh-sharded optimizer state: the configured
                        # mesh shape and the live sharded-program cache
                        # occupancy — an operator confirms from REST that
                        # a mesh run is armed and that budget retunes are
                        # not minting new compiled programs
                        "mesh": self._mesh_state(),
                        # convergence-telemetry state (ISSUE 9): taps
                        # armed + ring depth; the per-job energy summary
                        # rides observability_summary() above (VIEWER-
                        # safe — the full timeline is USER-gated on
                        # /observability)
                        "convergenceTaps": self._convergence_state(),
                        # unified device-memory ledger (ccx.common.
                        # devmem): resident bytes per class (snapshots /
                        # warm bases / programs), eviction counts by
                        # reason and priority, and the budget — sizes and
                        # counters only, VIEWER-safe
                        "deviceMemory": self._devmem_state(),
                        # windowed SLO engine + stream detector (ISSUE
                        # 20): objectives, burn rates, episode counts and
                        # time-to-heal percentiles — numbers and family
                        # names only; the full healing timeline (causes,
                        # verbs, per-episode arcs) is USER-gated on
                        # /observability
                        "slo": self._slo_state(),
                    },
                }
        if "anomaly_detector" in want:
            out["AnomalyDetectorState"] = self.anomaly_detector.state()
        return out

    def kafka_cluster_state(self) -> dict:
        """Ref KAFKA_CLUSTER_STATE endpoint."""
        md = self.admin.describe_cluster()
        return {
            "KafkaBrokerState": {
                "ReplicaCountByBrokerId": _count_by_broker(md, leaders=False),
                "LeaderCountByBrokerId": _count_by_broker(md, leaders=True),
                "OnlineLogDirsByBrokerId": {
                    str(b): [d for d, ok in dirs.items() if ok]
                    for b, dirs in self.admin.describe_log_dirs().items()
                },
                "IsController": {},
                "HostByBrokerId": {
                    str(b.broker_id): b.host_key() for b in md.brokers
                },
                "Summary": {
                    "Brokers": len(md.brokers),
                    "Hosts": len(md.hosts()),
                    "AliveBrokers": len(md.alive_broker_ids()),
                    "Topics": len(md.topics()),
                    "Partitions": len(md.partitions),
                    "Replicas": md.replica_count(),
                    "UnderReplicatedPartitions": len(md.under_replicated()),
                },
            }
        }

    def load(self) -> dict:
        """Ref LOAD endpoint: per-broker resource utilization + the
        ClusterModelStats block (SURVEY.md C4)."""
        model, metadata, gen = self._model()
        from ccx.model.aggregates import broker_aggregates
        from ccx.model.stats import cluster_model_stats
        import numpy as np

        agg = broker_aggregates(model)
        loads = np.asarray(agg.broker_load)          # [RES, B]
        caps = np.asarray(model.broker_capacity)
        out = []
        for i, b in enumerate(metadata.brokers):
            out.append(
                {
                    "Broker": b.broker_id,
                    "Rack": b.rack,
                    "Host": b.host_key(),
                    "BrokerState": "ALIVE" if b.alive else "DEAD",
                    "Replicas": int(np.asarray(agg.replica_count)[i]),
                    "Leaders": int(np.asarray(agg.leader_count)[i]),
                    "CpuPct": float(loads[0, i]),
                    "NwInRate": float(loads[1, i]),
                    "NwOutRate": float(loads[2, i]),
                    "DiskMB": float(loads[3, i]),
                    "DiskPct": float(
                        100.0 * loads[3, i] / max(caps[3, i], 1e-9)
                    ),
                }
            )
        return {
            "brokers": out,
            "modelGeneration": str(gen),
            **cluster_model_stats(model, agg).to_json(),
        }

    def partition_load(self, max_entries: int = 100, resource: str = "CPU",
                       topic: str = "") -> dict:
        """Ref PARTITION_LOAD endpoint: partitions sorted by the requested
        resource's utilization, optionally filtered by topic regex."""
        import re as _re

        from ccx.common.resources import Resource

        try:
            res = Resource[resource.upper()]
        except KeyError:
            raise UserRequestException(
                f"Unknown resource {resource!r}; one of "
                f"{[r.name for r in Resource]}"
            ) from None
        model, metadata, gen = self._model()
        import numpy as np

        lead = np.asarray(model.leader_load)  # [RES, P]
        valid = np.asarray(model.partition_valid).copy()
        if topic:
            rx = _re.compile(topic)
            for i, info in enumerate(metadata.partitions):
                if not rx.fullmatch(info.tp.topic):
                    valid[i] = False
        # Filter to valid partitions first, then sort + slice — slicing
        # before the validity filter would return fewer than max_entries
        # when zero-load valid partitions tie with masked-out ones.
        valid_idx = np.nonzero(valid)[0]
        order = valid_idx[np.argsort(-lead[res][valid_idx])][:max_entries]
        records = []
        for p in order:
            info = metadata.partitions[int(p)]
            records.append(
                {
                    "topic": info.tp.topic,
                    "partition": info.tp.partition,
                    "leader": info.leader,
                    "followers": [b for b in info.replicas if b != info.leader],
                    "cpu": float(lead[0, p]),
                    "networkInbound": float(lead[1, p]),
                    "networkOutbound": float(lead[2, p]),
                    "disk": float(lead[3, p]),
                }
            )
        return {"records": records}

    # ----- admin verbs ------------------------------------------------------

    def pause_sampling(self, reason: str = "") -> dict:
        self.load_monitor.pause_sampling(reason or "paused by user")
        return {"message": "Sampling paused"}

    def resume_sampling(self, reason: str = "") -> dict:
        self.load_monitor.resume_sampling()
        return {"message": "Sampling resumed"}

    def stop_proposal_execution(self) -> dict:
        self.executor.stop_execution()
        return {"message": "Execution stop requested"}

    def bootstrap(self, start_ms: int | None, end_ms: int | None,
                  clear_metrics: bool = True) -> dict:
        """Ref BOOTSTRAP endpoint (SURVEY.md C9): replay a historical metric
        range into the aggregators to warm windows without waiting."""
        if start_ms is None or end_ms is None:
            raise UserRequestException(
                "bootstrap requires start and end timestamps (ms)"
            )
        if end_ms <= start_ms:
            raise UserRequestException("bootstrap end must be after start")
        return self.load_monitor.bootstrap(start_ms, end_ms, clear_metrics)

    def train(self, start_ms: int | None, end_ms: int | None) -> dict:
        """Ref TRAIN endpoint (SURVEY.md C6): fit the linear-regression CPU
        estimation model from broker samples over a historical range."""
        if start_ms is None or end_ms is None:
            raise UserRequestException(
                "train requires start and end timestamps (ms)"
            )
        if end_ms <= start_ms:
            raise UserRequestException("train end must be after start")
        return self.load_monitor.train(start_ms, end_ms)

    # ----- internals --------------------------------------------------------

    def _fleet_state(self) -> dict:
        """AnalyzerState.fleet: scheduler config + live run-queue stats
        (never raises — STATE must stay readable under any backend)."""
        try:
            from ccx.search.scheduler import FLEET

            return {
                "clusterId": self.config["optimizer.fleet.cluster.id"],
                "urgentPriority": self.config[
                    "optimizer.fleet.priority.urgent"
                ],
                "scheduler": FLEET.stats(),
            }
        except Exception:  # noqa: BLE001 — state must stay readable
            return {"clusterId": self.config["optimizer.fleet.cluster.id"]}

    def _mesh_state(self) -> dict:
        """AnalyzerState.observability.mesh: configured mesh shape + live
        sharded-program cache stats (never raises — a broken backend must
        not take the STATE endpoint down with it)."""
        from ccx.parallel.sharding import program_cache_stats

        out: dict = {
            "enabled": bool(self.config["optimizer.mesh.enabled"]),
            "parts": self.config["optimizer.mesh.parts"],
            "shardedPrograms": program_cache_stats(),
        }
        if out["enabled"]:
            # mirror optimizer._make_run_mesh exactly (clamp to visible
            # devices, <2-device fallback, non-dividing parts -> 1), so
            # REST reports the mesh optimize() will actually build — not
            # a config fiction
            try:
                import jax

                n = len(jax.devices())
                if self.config["optimizer.mesh.devices"] > 0:
                    n = min(n, self.config["optimizer.mesh.devices"])
                if n < 2:
                    out["meshShape"] = None  # runs single-device
                else:
                    parts = max(self.config["optimizer.mesh.parts"], 1)
                    if n % parts:
                        parts = 1
                    out["meshShape"] = {"chains": n // parts, "parts": parts}
            except Exception:  # noqa: BLE001 — state must stay readable
                out["meshShape"] = None
        return out

    def _incremental_state(self) -> dict:
        """AnalyzerState.incremental: the warm-start drift loop's config
        + live placement-store stats (ccx.search.incremental)."""
        from ccx.search import incremental as _inc

        iopts = self._incremental_options()
        return {
            "enabled": bool(iopts.enabled),
            "armed": bool(iopts.armed),
            "warmSwapIters": iopts.warm_swap_iters,
            "warmSteps": iopts.warm_steps,
            "warmChunkSteps": iopts.warm_chunk_steps,
            "warmChains": iopts.warm_chains,
            "plateauWindow": iopts.plateau_window,
            "warmT0": iopts.warm_t0,
            "store": _inc.STORE.stats(),
        }

    def _devmem_state(self) -> dict:
        """AnalyzerState.observability.deviceMemory / the /observability
        ledger block (never raises — state must stay readable)."""
        try:
            from ccx.common.devmem import DEVMEM

            return DEVMEM.stats()
        except Exception:  # noqa: BLE001 — state must stay readable
            return {}

    def _slo_state(self) -> dict:
        """AnalyzerState.observability.slo: the stream detector's
        VIEWER-safe SLO summary (never raises — state must stay
        readable)."""
        try:
            return self.anomaly_detector.stream.state()
        except Exception:  # noqa: BLE001 — state must stay readable
            return {}

    def _convergence_state(self) -> dict:
        """AnalyzerState.observability.convergenceTaps: taps armed + ring
        depth (never raises — state must stay readable)."""
        try:
            from ccx.search import telemetry

            return {
                "enabled": telemetry.enabled(),
                "maxChunks": telemetry.max_chunks(),
            }
        except Exception:  # noqa: BLE001 — state must stay readable
            return {"enabled": None}

    def _broker_health_metrics(self) -> dict[int, dict[str, float]]:
        """Latest broker-window metrics for the concurrency adjuster (C26)."""
        md = self.admin.describe_cluster()
        agg = self.load_monitor.broker_aggregator.aggregate(len(md.brokers))
        if agg.num_windows == 0:
            return {}
        urp_id = BROKER_METRIC_DEF.metric_info("UNDER_REPLICATED_PARTITIONS").id
        out = {}
        for i, b in enumerate(md.brokers):
            out[b.broker_id] = {
                "UNDER_REPLICATED_PARTITIONS": float(agg.values[i, -1, urp_id])
            }
        return out


def _requirements_for(data_from: str):
    """Ref ``data_from`` parameter: VALID_WINDOWS (default — enough complete
    windows) vs VALID_PARTITIONS (one window, nearly all partitions).
    Invalid values are rejected like the reference's enum parse."""
    from ccx.monitor.aggregator import ModelCompletenessRequirements

    v = data_from.upper()
    if v == "VALID_PARTITIONS":
        return ModelCompletenessRequirements(1, 0.95)
    if v == "VALID_WINDOWS":
        return ModelCompletenessRequirements(1, 0.5)
    raise UserRequestException(
        f"Invalid data_from {data_from!r}; one of VALID_WINDOWS, "
        "VALID_PARTITIONS"
    )


def _restrict_destinations(model, metadata, destination_broker_ids):
    """Ref destination_broker_ids parameter: only the listed brokers may
    receive replicas during this operation."""
    if not destination_broker_ids:
        return model
    import numpy as np

    bidx = metadata.broker_index()
    allowed = np.zeros(model.B, bool)
    for b in destination_broker_ids:
        if b in bidx:
            allowed[bidx[b]] = True
    excl = np.asarray(model.broker_valid) & ~allowed
    return model.replace(
        broker_excl_replicas=model.broker_excl_replicas | excl
    )


def _count_by_broker(md, leaders: bool) -> dict[str, int]:
    counts: dict[str, int] = {str(b.broker_id): 0 for b in md.brokers}
    for p in md.partitions:
        if leaders:
            if p.leader >= 0:
                counts[str(p.leader)] = counts.get(str(p.leader), 0) + 1
        else:
            for b in p.replicas:
                counts[str(b)] = counts.get(str(b), 0) + 1
    return counts


def _identity_metadata(md):
    """Metadata whose dense broker index == broker id is unnecessary for
    proposals already carrying real ids; tasks_from_proposals resolves via
    metadata.brokers order, so build a shim mapping dense idx -> same id."""
    import dataclasses as _dc

    from ccx.common.metadata import BrokerInfo, ClusterMetadata

    max_id = max((b.broker_id for b in md.brokers), default=0)
    brokers = []
    real = {b.broker_id: b for b in md.brokers}
    for i in range(max_id + 1):
        brokers.append(real.get(i, BrokerInfo(i, "", alive=False)))
    return ClusterMetadata(md.generation, tuple(brokers), md.partitions)
