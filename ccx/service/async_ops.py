"""Async orchestration — operation futures, progress, user tasks.

Parity: ``async/{AsyncKafkaCruiseControl,OperationFuture}.java``,
``async/progress/OperationProgress.java`` and ``servlet/UserTaskManager.java``
(SURVEY.md C31/C32): every expensive request runs on a session executor as an
``OperationFuture`` with step-by-step progress; the ``UserTaskManager`` maps
task UUIDs to futures, replays completed responses, and retains a bounded
history surfaced by the ``user_tasks`` endpoint.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import threading
import time as _time
import uuid as _uuid


class OperationProgress:
    """Ref OperationProgress: ordered steps with timings, readable while the
    operation runs (surfaced via `state?substates=...` and `user_tasks`)."""

    def __init__(self) -> None:
        self._steps: list[dict] = []
        self._lock = threading.Lock()

    def step(self, description: str) -> None:
        with self._lock:
            now = _time.time()
            if self._steps:
                self._steps[-1]["timeToFinishSec"] = round(
                    now - self._steps[-1]["_start"], 6
                )
            self._steps.append({"step": description, "_start": now})

    def done(self) -> None:
        with self._lock:
            if self._steps and "timeToFinishSec" not in self._steps[-1]:
                self._steps[-1]["timeToFinishSec"] = round(
                    _time.time() - self._steps[-1]["_start"], 6
                )

    def to_json(self) -> list[dict]:
        with self._lock:
            return [
                {k: v for k, v in s.items() if not k.startswith("_")}
                for s in self._steps
            ]


class TaskState:
    ACTIVE = "Active"
    IN_EXECUTION = "InExecution"
    COMPLETED = "Completed"
    COMPLETED_WITH_ERROR = "CompletedWithError"
    KILLED = "Killed"


@dataclasses.dataclass
class UserTaskInfo:
    task_id: str
    endpoint: str
    request_url: str
    start_ms: int
    future: concurrent.futures.Future
    progress: OperationProgress
    client_id: str = ""

    @property
    def state(self) -> str:
        if self.future.cancelled():
            return TaskState.KILLED
        if not self.future.done():
            return TaskState.ACTIVE
        return (
            TaskState.COMPLETED_WITH_ERROR
            if self.future.exception() is not None
            else TaskState.COMPLETED
        )

    def to_json(self) -> dict:
        out = {
            "UserTaskId": self.task_id,
            "RequestURL": self.request_url,
            "Endpoint": self.endpoint,
            "ClientIdentity": self.client_id,
            "StartMs": self.start_ms,
            "Status": self.state,
            "Progress": self.progress.to_json(),
        }
        if self.future.done() and self.future.exception() is not None:
            out["ErrorMessage"] = str(self.future.exception())
        return out


class UserTaskManager:
    """Ref UserTaskManager (C32): bounded async session executor + completed
    task retention for response replay."""

    def __init__(self, max_active_tasks: int = 25,
                 completed_retention_ms: int = 86_400_000,
                 max_cached_completed: int = 100, clock=None) -> None:
        self.max_active_tasks = max_active_tasks
        self.completed_retention_ms = completed_retention_ms
        self.max_cached_completed = max_cached_completed
        self.clock = clock or (lambda: int(_time.time() * 1000))
        # +2 headroom over the admission cap: urgent (self-healing)
        # submissions bypass the cap and must get a worker immediately
        # instead of queueing in the pool behind the very dryruns they
        # outrank (the thread-pool twin of the fleet scheduler's
        # priority bypass)
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_active_tasks + 2, thread_name_prefix="user-task"
        )
        self._tasks: dict[str, UserTaskInfo] = {}
        self._lock = threading.Lock()

    @classmethod
    def from_config(cls, config, clock=None) -> "UserTaskManager":
        return cls(
            config["max.active.user.tasks"],
            config["completed.user.task.retention.time.ms"],
            config["max.cached.completed.user.tasks"],
            clock=clock,
        )

    def submit(self, endpoint: str, fn, request_url: str = "",
               client_id: str = "", urgent: bool = False) -> UserTaskInfo:
        """Run ``fn(progress)`` async; raises if at the active-task cap.
        ``urgent`` (self-healing verbs — fix_offline_replicas) bypasses
        the cap: an offline-replica fix must never be 503'd because
        dryruns saturated the task table (the executor keeps headroom so
        it also starts immediately)."""
        with self._lock:
            self._expire()
            active = sum(
                1 for t in self._tasks.values() if t.state == TaskState.ACTIVE
            )
            if active >= self.max_active_tasks and not urgent:
                raise RuntimeError(
                    f"There are already {active} active user tasks "
                    f"(max.active.user.tasks={self.max_active_tasks})"
                )
            progress = OperationProgress()
            task_id = str(_uuid.uuid4())

            def run():
                try:
                    return fn(progress)
                finally:
                    progress.done()

            info = UserTaskInfo(
                task_id=task_id,
                endpoint=endpoint,
                request_url=request_url or f"/{endpoint.lower()}",
                start_ms=self.clock(),
                future=self._executor.submit(run),
                progress=progress,
                client_id=client_id,
            )
            self._tasks[task_id] = info
            return info

    def get(self, task_id: str) -> UserTaskInfo | None:
        with self._lock:
            return self._tasks.get(task_id)

    def tasks(self, states: tuple[str, ...] = ()) -> list[UserTaskInfo]:
        with self._lock:
            self._expire()
            ts = sorted(self._tasks.values(), key=lambda t: -t.start_ms)
            if states:
                ts = [t for t in ts if t.state in states]
            return ts

    def _expire(self) -> None:
        now = self.clock()
        completed = [
            t for t in self._tasks.values()
            if t.state != TaskState.ACTIVE
        ]
        completed.sort(key=lambda t: t.start_ms)
        drop = set()
        for t in completed:
            if now - t.start_ms > self.completed_retention_ms:
                drop.add(t.task_id)
        overflow = len(completed) - len(drop) - self.max_cached_completed
        for t in completed:
            if overflow <= 0:
                break
            if t.task_id not in drop:
                drop.add(t.task_id)
                overflow -= 1
        for tid in drop:
            del self._tasks[tid]

    def shutdown(self) -> None:
        self._executor.shutdown(wait=False, cancel_futures=True)
