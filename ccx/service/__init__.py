"""Service layer: façade, async orchestration, REST API (ref C22, C31-C34)."""
