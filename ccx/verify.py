"""OptimizationVerifier equivalent — post-condition checks on optimizations.

Parity: the reference's analyzer tests never assert move-for-move golden
outputs; ``analyzer/OptimizationVerifier.java`` asserts *post-conditions*
after a goal run (hard goals satisfied, stats improved, proposals
self-consistent, dead brokers evacuated — SURVEY.md section 4). This module
is that verifier for the tensor model, used by the test suite, the optimizer
service (sanity gate before returning proposals), and the benchmarks.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ccx.feasibility import feasibility_report
from ccx.goals.base import GOAL_REGISTRY, GoalConfig
from ccx.goals.stack import DEFAULT_GOAL_ORDER, StackResult, evaluate_stack
from ccx.model.tensor_model import TensorClusterModel
from ccx.proposals import ColumnarDiff, ExecutionProposal


@dataclasses.dataclass
class Verification:
    ok: bool
    failures: list[str]
    #: hard goals with remaining violations *proven unfixable* for this input
    #: (OptimizationFailureException parity, ccx.feasibility) — reported, not
    #: counted as verification failures.
    infeasible: dict[str, str] = dataclasses.field(default_factory=dict)

    def __bool__(self) -> bool:
        return self.ok


def verify_model_consistency(m: TensorClusterModel) -> list[str]:
    """Structural invariants any placement must satisfy (ClusterModel
    invariants, SURVEY.md C1)."""
    failures: list[str] = []
    a = np.asarray(m.assignment)
    pvalid = np.asarray(m.partition_valid)
    bvalid = np.asarray(m.broker_valid)
    leader = np.asarray(m.leader_slot)

    if np.any(a[pvalid] >= m.B):
        failures.append("replica assigned to out-of-range broker index")
    placed = a[pvalid]
    placed_valid = placed >= 0
    refs = placed[placed_valid]
    if refs.size and not bvalid[refs].all():
        failures.append("replica assigned to an invalid (padding) broker")

    # distinct brokers within each replica set (vectorized: key invalid slots
    # to unique negatives, sort rows, look for equal neighbours)
    keyed = np.where(a >= 0, a, -1 - np.arange(m.R)[None, :])
    srt = np.sort(keyed, axis=1)
    dup_rows = pvalid & np.any((srt[:, 1:] == srt[:, :-1]) & (srt[:, 1:] >= 0), axis=1)
    if dup_rows.any():
        p = int(np.nonzero(dup_rows)[0][0])
        failures.append(f"partition {p}: duplicate broker in replica set")

    # leader slot points at a live replica slot
    lp = leader[pvalid]
    rows = a[pvalid]
    lead_b = rows[np.arange(rows.shape[0]), np.clip(lp, 0, m.R - 1)]
    if np.any((lp < 0) | (lp >= m.R)) or np.any(lead_b < 0):
        failures.append("leader slot does not hold a replica")
    return failures


#: soft goals whose violations are counted per (topic x broker) cell rather
#: than per broker (kernels.topic_replica_distribution via tt.trd_row_pen)
_TOPIC_CELL_GOALS = frozenset({"TopicReplicaDistributionGoal"})
#: soft goals counted per (broker x disk) cell (intra-broker JBOD)
_DISK_CELL_GOALS = frozenset({"IntraBrokerDiskUsageDistributionGoal"})


def soft_goal_slack(
    name: str,
    m: TensorClusterModel,
    cfg: GoalConfig,
    violations_before: float,
    hard_feasible_start: bool,
) -> float:
    """Allowed violation-count increase for one soft goal.

    Lexicographic optimization legitimately trades LOWER tiers for higher
    ones, and a balance band is a knife-edge: a broker at 0.999x the band
    limit flips to a violation when an unrelated move shifts the cluster
    average. The slack therefore scales with the number of scoring units the
    goal counts over — brokers for the per-broker distribution goals,
    (topic x broker) cells for topic distribution, (broker x disk) cells for
    intra-broker JBOD — at 2% of units (min 2): enough for band-edge churn,
    far below real debris (the 28-violation PLE regression this bound was
    built against is 28% of an 8-broker cluster's natural units).

    Three exceptions:
    * PreferredLeaderElectionGoal gets ZERO slack — the pipeline's final
      canonicalization pass (repair.canonicalize_preferred_leaders) makes
      every fixable violation vanish exactly, so any regression is a bug.
    * PotentialNwOutGoal is a fixed-cap hinge over a placement-INVARIANT
      total (every replica of a partition contributes its would-be-leader
      outbound no matter where it sits), so when the per-broker average
      potential exceeds a broker's cap, that broker is over cap in ANY
      placement as balanced as the higher tiers demand — the input only
      scores lower by being imbalanced. Brokers whose cap sits below the
      alive-average potential are counted as unavoidable and excused.
    * From a hard-INFEASIBLE start (dead brokers to evacuate, capacity
      overflow to shed), structural repair must land displaced load on
      scored brokers — every receiver can cross a band edge even when the
      input scored zero. Allow an extra 3% of units (min 2, the absolute
      component a goal at 0 needs) plus 10% of the input count.
    """
    if name == "PreferredLeaderElectionGoal":
        return 0.0
    alive_mask = np.asarray(m.broker_alive) & np.asarray(m.broker_valid)
    alive = float(np.sum(alive_mask))
    if name in _TOPIC_CELL_GOALS:
        units = alive * max(float(m.num_topics), 1.0)
    elif name in _DISK_CELL_GOALS:
        units = float(np.sum(np.asarray(m.disk_alive)))
    else:
        units = alive
    slack = max(2.0, 0.02 * units)
    if name == "PotentialNwOutGoal":
        from ccx.common.resources import Resource

        pvalid = np.asarray(m.partition_valid)
        rf = ((np.asarray(m.assignment) >= 0) & pvalid[:, None]).sum(axis=1)
        out_rate = np.asarray(m.leader_load[int(Resource.NW_OUT)])
        total = float(np.sum(out_rate * rf * pvalid))
        avg = total / max(alive, 1.0)
        # effective cap matches kernels.potential_nw_out
        cap_eff = np.asarray(m.broker_capacity[int(Resource.NW_OUT)]) * float(
            cfg.capacity_threshold[int(Resource.NW_OUT)]
        )
        unavoidable = float(np.sum(alive_mask & (cap_eff < avg)))
        slack += max(0.0, unavoidable - violations_before)
    if not hard_feasible_start:
        slack += max(2.0, 0.03 * units) + 0.10 * violations_before
    return slack


def verify_optimization(
    before: TensorClusterModel,
    after: TensorClusterModel,
    cfg: GoalConfig = GoalConfig(),
    goal_names: tuple[str, ...] = DEFAULT_GOAL_ORDER,
    proposals: "list[ExecutionProposal] | ColumnarDiff | None" = None,
    require_hard_zero: bool = True,
    check_evacuation: bool = True,
    check_per_goal: bool = True,
    stack_before: "StackResult | None" = None,
    stack_after: "StackResult | None" = None,
) -> Verification:
    """The reference verifier's post-conditions, tensor-model edition:

    1. structural consistency of the optimized placement;
    2. replication factor preserved per partition;
    3. excluded (immovable) partitions untouched;
    4. dead brokers fully evacuated (self-healing, SURVEY.md section 5.3);
    5. hard goals satisfied (or at least not worsened);
    6. soft stats not worsened (tiered scalar);
    7. proposals consistent with the before/after placements.
    """
    failures = verify_model_consistency(after)

    a0 = np.asarray(before.assignment)
    a1 = np.asarray(after.assignment)
    pvalid = np.asarray(before.partition_valid)

    rf0 = (a0 >= 0).sum(axis=1)
    rf1 = (a1 >= 0).sum(axis=1)
    if np.any(rf0[pvalid] != rf1[pvalid]):
        failures.append("replication factor changed by optimization")

    immovable = np.asarray(before.partition_immovable) & pvalid
    if np.any(a0[immovable] != a1[immovable]):
        failures.append("excluded/immovable partition was moved")
    l0 = np.asarray(before.leader_slot)
    l1 = np.asarray(after.leader_slot)
    if np.any(l0[immovable] != l1[immovable]):
        failures.append("excluded/immovable partition's leadership was moved")

    if check_evacuation:
        # disk-only stacks (rebalance_disk) cannot evacuate brokers; callers
        # disable this check there
        dead = ~(np.asarray(after.broker_alive) & np.asarray(after.broker_valid))
        placed = a1[pvalid]
        on_dead = placed[(placed >= 0)]
        if on_dead.size and dead[on_dead].any():
            failures.append("dead broker not evacuated")

    s0 = stack_before if stack_before is not None else evaluate_stack(before, cfg, goal_names)
    s1 = stack_after if stack_after is not None else evaluate_stack(after, cfg, goal_names)
    hard_names = [n for n in goal_names if GOAL_REGISTRY[n].hard]
    v1 = s1.by_name()
    v0 = s0.by_name()
    infeasible: dict[str, str] = {}
    feas = feasibility_report(before, cfg)
    for n in hard_names:
        if require_hard_zero:
            if v1[n][0] > 0:
                if n in feas:
                    # unfixable for this input — OptimizationFailure, not a
                    # search failure (SURVEY.md C16)
                    infeasible[n] = feas.infeasible[n]
                else:
                    failures.append(f"hard goal {n}: {v1[n][0]:.0f} violations remain")
        elif v1[n][0] > v0[n][0]:
            failures.append(f"hard goal {n}: violations increased")

    soft0 = float(s0.soft_scalar)
    soft1 = float(s1.soft_scalar)
    # Soft goals are optimized *subject to* hard feasibility: when the input
    # already violates hard goals (e.g. dead brokers to evacuate), repairing
    # them may legitimately raise soft cost — load invisible on dead brokers
    # lands on scored alive ones. Only enforce no-soft-regression from a
    # hard-feasible start.
    if float(s0.hard_violations) == 0 and soft1 > soft0 * (1.0 + 1e-4) + 1e-6:
        failures.append(f"soft cost worsened: {soft0:.4f} -> {soft1:.4f}")

    # Per-goal violation-count non-regression (ref: OptimizationVerifier
    # asserts per-goal stats, SURVEY.md section 4). The aggregate soft
    # scalar is blind to a low tier regressing while a high tier improves —
    # round-2's bench carried verified=true while PreferredLeaderElection
    # went 0->364 — so every soft goal's count is checked individually,
    # with slack derived from the goal's natural unit count
    # (``soft_goal_slack``).
    # ``check_per_goal=False`` is for verifying PARTIAL pipelines (e.g. the
    # annealer alone, whose low-tier debris the final leadership pass
    # cleans); the full optimize() result is always held to the strict bar.
    hard_feasible_start = float(s0.hard_violations) == 0
    for n in s1.names if check_per_goal else ():
        if GOAL_REGISTRY[n].hard:
            continue
        vb_, va_ = v0[n][0], v1[n][0]
        if va_ > vb_ + soft_goal_slack(n, after, cfg, vb_, hard_feasible_start):
            failures.append(
                f"soft goal {n}: violations regressed {vb_:.0f} -> {va_:.0f}"
            )

    if proposals is not None:
        failures.extend(_verify_proposals(before, after, proposals))

    return Verification(ok=not failures, failures=failures, infeasible=infeasible)


def _verify_proposals(
    before: TensorClusterModel,
    after: TensorClusterModel,
    proposals: "list[ExecutionProposal] | ColumnarDiff",
) -> list[str]:
    failures = []
    a0 = np.asarray(before.assignment)
    a1 = np.asarray(after.assignment)
    l0 = np.asarray(before.leader_slot)
    l1 = np.asarray(after.leader_slot)
    d0 = np.asarray(before.replica_disk)
    d1 = np.asarray(after.replica_disk)

    # Vectorized replica-list comparison: replica slots are left-packed
    # (absent slots trail as -1), so a proposal's padded replica list must
    # equal the assignment row verbatim. A ColumnarDiff hands the padded
    # slot arrays over directly — the verifier never materializes rows.
    # For the columnar form the verbatim compare re-verifies the DEVICE
    # gather against the host arrays but is vacuous about slot packing
    # (the columns are gathers of the very rows compared against), so the
    # left-packed invariant the row path enforced via tuple repacking is
    # checked explicitly: a valid broker after a -1 hole is malformed.
    R = a0.shape[1]
    if isinstance(proposals, ColumnarDiff):
        idx = proposals.cols["partition"].astype(np.int64)
        oldr = proposals.cols["oldReplicas"]
        newr = proposals.cols["newReplicas"]
        for label, rows in (("old", oldr), ("new", newr)):
            holes = (rows[:, :-1] < 0) & (rows[:, 1:] >= 0)
            if holes.any():
                p = int(idx[np.nonzero(holes.any(axis=1))[0][0]])
                failures.append(
                    f"proposal {p}: {label} replica slots not left-packed"
                )
    else:
        n = len(proposals)
        idx = np.empty(n, np.int64)
        oldr = np.full((n, R), -1, np.int32)
        newr = np.full((n, R), -1, np.int32)
        for i, pr in enumerate(proposals):
            idx[i] = pr.partition
            oldr[i, : len(pr.old_replicas)] = pr.old_replicas
            newr[i, : len(pr.new_replicas)] = pr.new_replicas
    bad_old = np.any(a0[idx] != oldr, axis=1)
    bad_new = np.any(a1[idx] != newr, axis=1)
    if bad_old.any():
        failures.append(
            f"proposal {int(idx[np.nonzero(bad_old)[0][0]])}: old replicas mismatch"
        )
    if bad_new.any():
        failures.append(
            f"proposal {int(idx[np.nonzero(bad_new)[0][0]])}: new replicas mismatch"
        )

    # every changed partition must be covered by a proposal
    pvalid = np.asarray(before.partition_valid)
    changed = pvalid & (
        np.any(a0 != a1, axis=1) | (l0 != l1) | np.any(d0 != d1, axis=1)
    )
    covered = np.zeros(changed.shape[0], bool)
    covered[idx] = True
    missing = changed & ~covered
    if missing.any():
        failures.append(
            f"changed partition {int(np.nonzero(missing)[0][0])} missing from proposals"
        )
    return failures
