"""Metric definitions — canonical metric ids + aggregation functions.

Parity: ``cruise-control-core/.../metricdef/{MetricDef,MetricInfo,
AggregationFunction}.java`` and ``monitor/metricdefinition/KafkaMetricDef.java``
(SURVEY.md C12, M1). A ``MetricDef`` is an ordered registry: each metric has a
dense integer id (tensor column), an aggregation function applied when many
raw samples land in one window, and a group (used by CPU estimation and the
anomaly finders).

Two scopes exist, as in the reference: the **partition** def (the per-replica
loads the ClusterModel is built from — one per ``Resource``) and the
**broker** def (health metrics consumed by SlowBrokerFinder and the
concurrency adjuster).
"""

from __future__ import annotations

import dataclasses
import enum

from ccx.common.resources import Resource


class AggregationFunction(enum.Enum):
    AVG = "avg"
    MAX = "max"
    LATEST = "latest"


@dataclasses.dataclass(frozen=True)
class MetricInfo:
    name: str
    id: int
    aggregation: AggregationFunction
    group: str = ""


class MetricDef:
    """Ordered metric registry with dense ids (ref MetricDef.define())."""

    def __init__(self) -> None:
        self._by_name: dict[str, MetricInfo] = {}
        self._by_id: list[MetricInfo] = []

    def define(self, name: str, aggregation: AggregationFunction,
               group: str = "") -> "MetricDef":
        if name in self._by_name:
            raise ValueError(f"metric {name} defined twice")
        info = MetricInfo(name, len(self._by_id), aggregation, group)
        self._by_name[name] = info
        self._by_id.append(info)
        return self

    def metric_info(self, name: str) -> MetricInfo:
        return self._by_name[name]

    def info_for_id(self, metric_id: int) -> MetricInfo:
        return self._by_id[metric_id]

    @property
    def num_metrics(self) -> int:
        return len(self._by_id)

    def all_metrics(self) -> tuple[MetricInfo, ...]:
        return tuple(self._by_id)

    def ids_in_group(self, group: str) -> tuple[int, ...]:
        return tuple(m.id for m in self._by_id if m.group == group)


def partition_metric_def() -> MetricDef:
    """The four resource loads of a partition (ref KafkaMetricDef common
    metric defs; the ClusterModel's ``Load`` columns, SURVEY.md C3).

    Column order matches ``ccx.common.resources.Resource`` so aggregated
    arrays feed ``build_model`` without reindexing.
    """
    d = MetricDef()
    d.define("CPU_USAGE", AggregationFunction.AVG, group="CPU")
    d.define("NETWORK_IN_RATE", AggregationFunction.AVG, group="NETWORK")
    d.define("NETWORK_OUT_RATE", AggregationFunction.AVG, group="NETWORK")
    d.define("DISK_USAGE", AggregationFunction.LATEST, group="DISK")
    assert [m.id for m in d.all_metrics()] == [
        Resource.CPU, Resource.NW_IN, Resource.NW_OUT, Resource.DISK
    ]
    return d


def broker_metric_def() -> MetricDef:
    """Broker health metrics (ref KafkaMetricDef broker defs / RawMetricType
    broker subset, SURVEY.md C12/C37) — the inputs to SlowBrokerFinder and
    ExecutionConcurrencyManager."""
    d = MetricDef()
    d.define("ALL_TOPIC_BYTES_IN", AggregationFunction.AVG, group="NETWORK")
    d.define("ALL_TOPIC_BYTES_OUT", AggregationFunction.AVG, group="NETWORK")
    d.define("ALL_TOPIC_REPLICATION_BYTES_IN", AggregationFunction.AVG, group="NETWORK")
    d.define("ALL_TOPIC_REPLICATION_BYTES_OUT", AggregationFunction.AVG, group="NETWORK")
    d.define("ALL_TOPIC_MESSAGES_IN_PER_SEC", AggregationFunction.AVG, group="NETWORK")
    d.define("ALL_TOPIC_PRODUCE_REQUEST_RATE", AggregationFunction.AVG, group="REQUEST")
    d.define("ALL_TOPIC_FETCH_REQUEST_RATE", AggregationFunction.AVG, group="REQUEST")
    d.define("BROKER_CPU_UTIL", AggregationFunction.AVG, group="CPU")
    d.define("BROKER_DISK_UTIL", AggregationFunction.LATEST, group="DISK")
    d.define("BROKER_PRODUCE_LOCAL_TIME_MS_MEAN", AggregationFunction.AVG, group="LATENCY")
    d.define("BROKER_PRODUCE_LOCAL_TIME_MS_MAX", AggregationFunction.MAX, group="LATENCY")
    d.define("BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MEAN", AggregationFunction.AVG, group="LATENCY")
    d.define("BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MEAN", AggregationFunction.AVG, group="LATENCY")
    d.define("BROKER_LOG_FLUSH_TIME_MS_MEAN", AggregationFunction.AVG, group="LATENCY")
    d.define("BROKER_LOG_FLUSH_TIME_MS_MAX", AggregationFunction.MAX, group="LATENCY")
    d.define("BROKER_LOG_FLUSH_RATE", AggregationFunction.AVG, group="REQUEST")
    d.define("BROKER_REQUEST_QUEUE_SIZE", AggregationFunction.MAX, group="QUEUE")
    d.define("BROKER_RESPONSE_QUEUE_SIZE", AggregationFunction.MAX, group="QUEUE")
    d.define("UNDER_REPLICATED_PARTITIONS", AggregationFunction.LATEST, group="HEALTH")
    d.define("OFFLINE_LOG_DIRS", AggregationFunction.LATEST, group="HEALTH")
    return d


PARTITION_METRIC_DEF = partition_metric_def()
BROKER_METRIC_DEF = broker_metric_def()
