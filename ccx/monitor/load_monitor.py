"""LoadMonitor — builds tensor ClusterModels from metadata + samples.

Parity: ``monitor/LoadMonitor.java`` + ``monitor/task/LoadMonitorTaskRunner``
+ ``monitor/sampling/MetricFetcherManager`` (SURVEY.md C7/C9): a scheduled
sampling loop shards partitions across fetcher threads, feeds windowed
aggregators and the sample store; ``cluster_model(requirements)`` snapshots
metadata + aggregates into the model the analyzer optimizes, stamped with a
``ModelGeneration``; sampling can be paused/resumed; on startup the sample
store is replayed for a warm model (§5.4 checkpoint/resume).

TPU-native departure: the "model" produced is the frozen
``TensorClusterModel`` pytree (device-ready), not a mutable object tree —
aggregation windows are averaged into per-partition leader/follower load
vectors on the host (numpy) and shipped once per generation.
"""

from __future__ import annotations

import dataclasses
import enum
import re
import threading
import time as _time

import numpy as np

from ccx.common.exceptions import NotEnoughValidWindowsException
from ccx.common.metadata import ClusterMetadata
from ccx.common.resources import NUM_RESOURCES
from ccx.model.tensor_model import TensorClusterModel, build_model
from ccx.monitor.aggregator import (
    AggregationResult,
    MetricSampleAggregator,
    ModelCompletenessRequirements,
)
from ccx.monitor.capacity import capacity_matrix, disk_capacity_matrix
from ccx.monitor.metricdef import BROKER_METRIC_DEF, PARTITION_METRIC_DEF
from ccx.monitor.model_utils import (
    CpuEstimationParams,
    LinearRegressionModelParameters,
    split_roles,
)
from ccx.monitor.sampling.holders import samples_to_arrays
from ccx.monitor.sampling.sampler import Samples


class LoadMonitorState(enum.Enum):
    """Ref C9 LoadMonitorTaskRunner state machine (incl. the legacy
    BOOTSTRAPPING/TRAINING modes driven by the bootstrap/train endpoints)."""

    NOT_STARTED = "NOT_STARTED"
    LOADING = "LOADING"
    RUNNING = "RUNNING"
    SAMPLING = "SAMPLING"
    PAUSED = "PAUSED"
    BOOTSTRAPPING = "BOOTSTRAPPING"
    TRAINING = "TRAINING"


@dataclasses.dataclass(frozen=True)
class ModelGeneration:
    """Ref monitor/ModelGeneration: (metadata generation, sample generation)
    — pins a snapshot so analyzer results are traceable to inputs."""

    metadata_generation: int
    sample_generation: int

    def __str__(self) -> str:
        return f"[{self.metadata_generation},{self.sample_generation}]"


@dataclasses.dataclass
class ModelBuildOptions:
    """Per-request model shaping (ref OptimizationOptions inputs, C20)."""

    excluded_topics_pattern: str = ""
    brokers_to_add: tuple[int, ...] = ()
    brokers_to_remove: tuple[int, ...] = ()
    brokers_to_demote: tuple[int, ...] = ()
    populate_disks: bool = False


class MetricFetcherManager:
    """Shards the partition space across fetcher threads (ref C9)."""

    def __init__(self, sampler, num_fetchers: int = 1) -> None:
        self.sampler = sampler
        self.num_fetchers = max(int(num_fetchers), 1)

    def fetch(self, metadata: ClusterMetadata, start_ms: int, end_ms: int) -> Samples:
        n = len(metadata.partitions)
        shards = [list(range(i, n, self.num_fetchers))
                  for i in range(self.num_fetchers)]
        results: list[Samples | None] = [None] * len(shards)
        errors: list[BaseException] = []

        def run(i: int) -> None:
            try:
                results[i] = self.sampler.get_samples(
                    metadata, shards[i], start_ms, end_ms
                )
            except BaseException as e:  # propagate to the caller's thread
                errors.append(e)

        if self.num_fetchers == 1:
            run(0)
        else:
            threads = [threading.Thread(target=run, args=(i,), daemon=True)
                       for i in range(len(shards))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        if errors:
            # A failed fetch round must fail loudly so sample_once does not
            # advance the sampled horizon past an un-fetched interval.
            raise errors[0]
        merged = Samples([], [])
        seen_broker: set[tuple[int, int]] = set()
        for r in results:
            if r is not None:
                merged.partition_samples.extend(r.partition_samples)
                # Broker samples are not sharded by the fetcher split — a
                # sampler may emit them on every shard; dedupe by
                # (broker, timestamp) so counts are not inflated N-fetchers x.
                for s in r.broker_samples:
                    key = (s.broker_id, s.time_ms)
                    if key not in seen_broker:
                        seen_broker.add(key)
                        merged.broker_samples.append(s)
        return merged


class LoadMonitor:
    """The L2 entry point (ref C7). ``admin`` supplies metadata snapshots
    (``ccx.executor.admin.AdminApi``); ``clock`` returns epoch ms (injectable
    for tests, like the reference's Time mock)."""

    def __init__(self, config, admin, clock=None) -> None:
        self.config = config
        self.admin = admin
        self.clock = clock or (lambda: int(_time.time() * 1000))
        self.partition_aggregator = MetricSampleAggregator(
            PARTITION_METRIC_DEF,
            num_windows=config["num.partition.metrics.windows"],
            window_ms=config["partition.metrics.window.ms"],
            min_samples_per_window=config["min.samples.per.partition.metrics.window"],
            max_allowed_extrapolations=config["max.allowed.extrapolations.per.partition"],
        )
        self.broker_aggregator = MetricSampleAggregator(
            BROKER_METRIC_DEF,
            num_windows=config["num.broker.metrics.windows"],
            window_ms=config["broker.metrics.window.ms"],
            min_samples_per_window=config["min.samples.per.broker.metrics.window"],
            max_allowed_extrapolations=config["max.allowed.extrapolations.per.broker"],
        )
        self.sampler = config.configured_instance("metric.sampler.class")
        self.sample_store = config.configured_instance("sample.store.class")
        self.capacity_resolver = config.configured_instance(
            "broker.capacity.config.resolver.class"
        )
        self.cpu_params = CpuEstimationParams.from_config(config)
        self.fetcher_manager = MetricFetcherManager(
            self.sampler, config["num.metric.fetchers"]
        )
        self._state = LoadMonitorState.NOT_STARTED
        self._pause_reason: str | None = None
        self._lock = threading.RLock()
        self._model_semaphore = threading.Semaphore(1)
        self._last_sample_ms: int | None = None
        self._runner: threading.Thread | None = None
        self._stop = threading.Event()
        self._num_samples = 0
        #: legacy linear-regression CPU-model training (ref C6; train verb)
        self.lr_params = LinearRegressionModelParameters()
        self._trained = False

    # ----- lifecycle (ref LoadMonitor.startUp / shutdown) -------------------

    def start_up(self, run_sampling_loop: bool = True) -> None:
        with self._lock:
            self._state = LoadMonitorState.LOADING
        if not self._warm_start_native():
            warm = self.sample_store.load_samples()
            self._ingest(warm)
        with self._lock:
            self._state = LoadMonitorState.RUNNING
        if run_sampling_loop:
            self._stop.clear()
            self._runner = threading.Thread(
                target=self._sampling_loop, name="LoadMonitorTaskRunner",
                daemon=True,
            )
            self._runner.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._runner is not None:
            self._runner.join(timeout=5)
        self.sampler.close()
        self.sample_store.close()

    def _warm_start_native(self) -> bool:
        """Columnar warm start: decode the partition log natively straight
        into the aggregator (the object path costs ~3us/record; at millions
        of persisted samples boot time matters). Broker samples still replay
        through the object path (small volume). Returns False to fall back."""
        from ccx import native

        raw = getattr(self.sample_store, "raw_partition_log", None)
        if raw is None or not native.available():
            return False
        buf = raw()
        M = self.partition_aggregator.metric_def.num_metrics
        # capacity: a record is >= 34 bytes on the wire
        decoded = native.decode_partition_samples(buf, len(buf) // 34 + 1, M)
        if decoded is None:
            return False
        ids, times, metrics = decoded
        if len(ids):
            self.partition_aggregator.add_samples(ids, times, metrics)
        self._ingest(Samples([], self.sample_store.load_broker_samples()))
        self._num_samples += len(ids)
        return True

    # ----- sampling ---------------------------------------------------------

    def _sampling_loop(self) -> None:
        interval = self.config["metric.sampling.interval.ms"]
        while not self._stop.wait(interval / 1000.0):
            # skip while paused AND while a bootstrap/train replay owns the
            # aggregators — concurrent ingestion would double-count windows
            if self._state is not LoadMonitorState.RUNNING:
                continue
            try:
                self.sample_once()
            except Exception:  # sampling must never kill the loop (ref C9)
                import logging

                logging.getLogger(__name__).exception("sampling round failed")

    def sample_once(self, end_ms: int | None = None) -> int:
        """One fetch round over [last_sample, end); returns samples ingested."""
        with self._lock:
            if self._state not in (
                LoadMonitorState.RUNNING, LoadMonitorState.LOADING
            ):
                return 0
            prev_state = self._state
            self._state = LoadMonitorState.SAMPLING
        try:
            end_ms = end_ms if end_ms is not None else self.clock()
            start_ms = (
                self._last_sample_ms
                if self._last_sample_ms is not None
                else end_ms - self.config["metric.sampling.interval.ms"]
            )
            metadata = self.admin.describe_cluster()
            samples = self.fetcher_manager.fetch(metadata, start_ms, end_ms)
            self._ingest(samples, metadata, now_ms=end_ms)
            self.sample_store.store_samples(samples)
            # Retention: drop persisted samples older than each scope's
            # monitored span so warm start replays only what the aggregators
            # can hold.
            p_horizon = (
                self.config["num.partition.metrics.windows"] + 1
            ) * self.config["partition.metrics.window.ms"]
            b_horizon = (
                self.config["num.broker.metrics.windows"] + 1
            ) * self.config["broker.metrics.window.ms"]
            self.sample_store.evict_before(
                end_ms - p_horizon, end_ms - b_horizon
            )
            self._last_sample_ms = end_ms
            return len(samples.partition_samples) + len(samples.broker_samples)
        finally:
            with self._lock:
                if self._state is LoadMonitorState.SAMPLING:
                    self._state = prev_state

    def _ingest(self, samples: Samples, metadata: ClusterMetadata | None = None,
                now_ms: int | None = None) -> None:
        if samples.partition_samples:
            ids, times, metrics = samples_to_arrays(samples.partition_samples)
            self.partition_aggregator.add_samples(ids, times, metrics, now_ms=now_ms)
        if samples.broker_samples:
            # Broker ids are operator-chosen and possibly sparse/large; map to
            # the dense broker axis via the metadata snapshot (same contract
            # as the partition axis).
            if metadata is None:
                metadata = self.admin.describe_cluster()
            bidx = metadata.broker_index()
            kept = [s for s in samples.broker_samples if s.broker_id in bidx]
            if kept:
                ids = np.array([bidx[s.broker_id] for s in kept], np.int64)
                times = np.array([s.time_ms for s in kept], np.int64)
                metrics = np.array([s.metrics for s in kept])
                self.broker_aggregator.add_samples(ids, times, metrics, now_ms=now_ms)
        self._num_samples += len(samples.partition_samples) + len(samples.broker_samples)

    def bootstrap(self, start_ms: int, end_ms: int,
                  clear_metrics: bool = True) -> dict:
        """Ref BOOTSTRAP endpoint / BOOTSTRAPPING state (SURVEY.md C9):
        fetch a historical range window-by-window to (re)fill the
        aggregators without waiting real time."""
        window_ms = int(self.config["partition.metrics.window.ms"])
        with self._lock:
            if self._state is not LoadMonitorState.RUNNING:
                raise RuntimeError(
                    f"cannot bootstrap while monitor is {self._state.value}"
                )
            self._state = LoadMonitorState.BOOTSTRAPPING
        try:
            if clear_metrics:
                self.partition_aggregator.clear()
                self.broker_aggregator.clear()
                self._num_samples = 0
            metadata = self.admin.describe_cluster()
            n = 0
            t = int(start_ms)
            while t < end_ms:
                hi = min(t + window_ms, int(end_ms))
                samples = self.fetcher_manager.fetch(metadata, t, hi)
                self._ingest(samples, metadata, now_ms=hi)
                self.sample_store.store_samples(samples)
                n += len(samples.partition_samples) + len(samples.broker_samples)
                t = hi
            with self._lock:
                self._last_sample_ms = max(self._last_sample_ms or 0, int(end_ms))
            r = self.partition_aggregator.aggregate()
            return {
                "numSamples": n,
                "numValidWindows": int(r.num_windows),
                "validPartitionsRatio": r.valid_entity_ratio,
            }
        finally:
            with self._lock:
                # guarded restore (same pattern as sample_once): a concurrent
                # pause must not be clobbered back to RUNNING
                if self._state is LoadMonitorState.BOOTSTRAPPING:
                    self._state = LoadMonitorState.RUNNING

    def train(self, start_ms: int, end_ms: int) -> dict:
        """Ref TRAIN endpoint / TRAINING state (SURVEY.md C6/C9): fit the
        linear-regression CPU model from broker samples over a historical
        range; once enough observations accumulate, the fitted coefficients
        replace the static ``*.weight.for.cpu.util`` config estimates."""
        with self._lock:
            if self._state is not LoadMonitorState.RUNNING:
                raise RuntimeError(
                    f"cannot train while monitor is {self._state.value}"
                )
            self._state = LoadMonitorState.TRAINING
        try:
            metadata = self.admin.describe_cluster()
            samples = self.fetcher_manager.fetch(
                metadata, int(start_ms), int(end_ms)
            )
            cpu_id = BROKER_METRIC_DEF.metric_info("BROKER_CPU_UTIL").id
            in_id = BROKER_METRIC_DEF.metric_info("ALL_TOPIC_BYTES_IN").id
            out_id = BROKER_METRIC_DEF.metric_info("ALL_TOPIC_BYTES_OUT").id
            if samples.broker_samples:
                rows = np.array([s.metrics for s in samples.broker_samples])
                self.lr_params.add_broker_samples(
                    rows[:, None, :], cpu_id, in_id, out_id
                )
            out = {
                "numTrainingSamples": self.lr_params.num_observations,
                "trained": False,
            }
            if self.lr_params.trainable:
                self.cpu_params = self.lr_params.to_params()
                self._trained = True
                out["trained"] = True
                out["coefficients"] = {
                    "leaderNetworkInboundWeightForCpuUtil":
                        self.cpu_params.leader_nw_in_weight,
                    "leaderNetworkOutboundWeightForCpuUtil":
                        self.cpu_params.leader_nw_out_weight,
                    "followerNetworkInboundWeightForCpuUtil":
                        self.cpu_params.follower_nw_in_weight,
                }
            return out
        finally:
            with self._lock:
                if self._state is LoadMonitorState.TRAINING:
                    self._state = LoadMonitorState.RUNNING

    def pause_sampling(self, reason: str = "user request") -> None:
        with self._lock:
            self._state = LoadMonitorState.PAUSED
            self._pause_reason = reason

    def resume_sampling(self) -> None:
        with self._lock:
            self._state = LoadMonitorState.RUNNING
            self._pause_reason = None

    # ----- model generation -------------------------------------------------

    def model_generation(self, metadata: ClusterMetadata | None = None) -> ModelGeneration:
        md = metadata or self.admin.describe_cluster()
        return ModelGeneration(md.generation, self.partition_aggregator.generation)

    def acquire_for_model_generation(self):
        """Ref LoadMonitor's model-generation semaphore: serialize expensive
        model builds; context-manager style."""
        class _Guard:
            def __init__(self, sem):
                self._sem = sem

            def __enter__(self):
                self._sem.acquire()
                return self

            def __exit__(self, *exc):
                self._sem.release()
                return False

        return _Guard(self._model_semaphore)

    def partition_completeness(self):
        metadata = self.admin.describe_cluster()
        r = self.partition_aggregator.aggregate(len(metadata.partitions))
        return r, metadata

    def cluster_model(
        self,
        requirements: ModelCompletenessRequirements | None = None,
        options: ModelBuildOptions | None = None,
    ) -> tuple[TensorClusterModel, ClusterMetadata, ModelGeneration]:
        """Ref LoadMonitor.clusterModel(now, requirements, progress) — the
        L2 half of call stack 3.2. Raises NotEnoughValidWindowsException when
        completeness is below ``requirements``."""
        req = requirements or ModelCompletenessRequirements()
        options = options or ModelBuildOptions()
        agg, metadata = self.partition_completeness()
        if not agg.meets(req):
            raise NotEnoughValidWindowsException(
                f"monitor completeness {agg.valid_entity_ratio:.2%} over "
                f"{agg.num_windows} windows does not meet {req}"
            )
        model = build_tensor_model(
            metadata, agg, self.capacity_resolver, self.cpu_params, options
        )
        return model, metadata, self.model_generation(metadata)

    # ----- state ------------------------------------------------------------

    def state(self) -> dict:
        r = self.partition_aggregator.aggregate()
        return {
            "state": self._state.value,
            "reasonOfLatestPauseOrResume": self._pause_reason,
            "numValidWindows": int(r.num_windows),
            "validPartitionsRatio": r.valid_entity_ratio,
            "numTotalSamples": self._num_samples,
            "modelGeneration": str(self.model_generation()),
            "trained": self._trained,
            "numTrainingSamples": self.lr_params.num_observations,
        }


def build_tensor_model(
    metadata: ClusterMetadata,
    agg: AggregationResult,
    capacity_resolver,
    cpu_params: CpuEstimationParams,
    options: ModelBuildOptions | None = None,
) -> TensorClusterModel:
    """Metadata + windowed loads -> TensorClusterModel (the populate-model
    half of call stack 3.2: createReplica/setReplicaLoad per replica in the
    reference becomes a handful of vectorized gathers here)."""
    options = options or ModelBuildOptions()
    P = len(metadata.partitions)
    R = max((len(p.replicas) for p in metadata.partitions), default=1)
    bidx = metadata.broker_index()
    tidx = metadata.topic_index()
    # effective rack keys: rack || host || broker id — a rack-less broker
    # falls back to HOST distinctness (upstream ClusterModel.createBroker
    # semantics, ref model/{Rack,Host}.java), not to one shared "" rack
    racks = {r: i for i, r in enumerate(metadata.rack_keys())}
    hosts = {h: i for i, h in enumerate(metadata.hosts())}

    assignment = np.full((P, R), -1, np.int32)
    replica_disk = np.full((P, R), -1, np.int32)
    leader_slot = np.zeros(P, np.int32)
    partition_topic = np.zeros(P, np.int32)
    for i, p in enumerate(metadata.partitions):
        for s, b in enumerate(p.replicas):
            assignment[i, s] = bidx[b]
            if p.replica_dirs:
                replica_disk[i, s] = p.replica_dirs[s]
            else:
                replica_disk[i, s] = 0
        if p.leader >= 0 and p.leader in bidx:
            try:
                leader_slot[i] = p.replicas.index(p.leader)
            except ValueError:
                leader_slot[i] = 0
        partition_topic[i] = tidx[p.tp.topic]

    # windowed loads -> per-partition vector (average over valid windows;
    # entities with no valid data contribute zeros, matching the reference's
    # completeness gate having already passed)
    valid_w = agg.extrapolations < 3  # not NO_VALID
    with np.errstate(invalid="ignore", divide="ignore"):
        wsum = (agg.values * valid_w[..., None]).sum(axis=1)
        wcnt = np.maximum(valid_w.sum(axis=1), 1)[..., None]
        loads = wsum / wcnt  # [P, M]
    leader_load, follower_load = split_roles(cpu_params, loads)

    broker_ids = metadata.broker_ids()
    broker_capacity = capacity_matrix(capacity_resolver, broker_ids)
    broker_rack = np.array(
        [racks[b.rack_key()] for b in metadata.brokers], np.int32
    )
    broker_host = np.array(
        [hosts[b.host_key()] for b in metadata.brokers], np.int32
    )
    broker_alive = np.array(
        [b.alive and b.broker_id not in options.brokers_to_remove
         for b in metadata.brokers], bool
    )
    broker_new = np.array(
        [b.broker_id in options.brokers_to_add for b in metadata.brokers], bool
    )
    demoted = np.array(
        [b.broker_id in options.brokers_to_demote for b in metadata.brokers], bool
    )

    excluded = np.zeros(P, bool)
    if options.excluded_topics_pattern:
        rx = re.compile(options.excluded_topics_pattern)
        topic_names = metadata.topics()
        excluded_topics = {tidx[t] for t in topic_names if rx.fullmatch(t)}
        excluded = np.array(
            [partition_topic[i] in excluded_topics for i in range(P)], bool
        )

    disk_capacity = disk_capacity_matrix(capacity_resolver, broker_ids)
    disk_alive = np.ones_like(disk_capacity, bool)
    for i, b in enumerate(metadata.brokers):
        for d in b.offline_disks:
            if d < disk_alive.shape[1]:
                disk_alive[i, d] = False

    return build_model(
        assignment=assignment,
        leader_load=leader_load,
        follower_load=follower_load,
        broker_capacity=broker_capacity,
        broker_rack=broker_rack,
        broker_host=broker_host,
        partition_topic=partition_topic,
        leader_slot=leader_slot,
        replica_disk=replica_disk,
        broker_alive=broker_alive,
        broker_new=broker_new,
        broker_excl_leadership=demoted,
        partition_immovable=excluded,
        disk_capacity=disk_capacity,
        disk_alive=disk_alive,
        num_racks=len(racks),
    )
