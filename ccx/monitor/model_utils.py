"""Leader/follower load decomposition — CPU estimation coefficients.

Parity: ``model/{ModelUtils,ModelParameters,LinearRegressionModelParameters}
.java`` (SURVEY.md C6): the reference estimates a replica's CPU from its
network activity with fixed coefficients (configurable; a legacy linear-
regression training path can fit them), and derives the **follower** role's
load profile from the leader's (follower CPU ~ replication traffic only,
follower NW_OUT = 0, follower NW_IN = leader bytes-in).

These functions produce the ``leader_load`` / ``follower_load`` pair the
TensorClusterModel stores per partition (ccx.model.tensor_model), which is
how leadership transfer re-weights broker loads with no re-aggregation.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ccx.common.resources import Resource


@dataclasses.dataclass(frozen=True)
class CpuEstimationParams:
    """Ref MonitorConfig `*.weight.for.cpu.util` keys (SURVEY.md C6)."""

    leader_nw_in_weight: float = 0.6
    leader_nw_out_weight: float = 0.1
    follower_nw_in_weight: float = 0.3

    @classmethod
    def from_config(cls, config) -> "CpuEstimationParams":
        return cls(
            config["leader.network.inbound.weight.for.cpu.util"],
            config["leader.network.outbound.weight.for.cpu.util"],
            config["follower.network.inbound.weight.for.cpu.util"],
        )


def estimate_leader_cpu(params: CpuEstimationParams, broker_cpu: np.ndarray,
                        nw_in: np.ndarray, nw_out: np.ndarray,
                        broker_nw_in: np.ndarray, broker_nw_out: np.ndarray) -> np.ndarray:
    """Apportion measured broker CPU to a leader replica by its share of
    weighted network activity (ref ModelUtils.estimateLeaderCpuUtil)."""
    denom = (params.leader_nw_in_weight * broker_nw_in
             + params.leader_nw_out_weight * broker_nw_out)
    numer = (params.leader_nw_in_weight * nw_in
             + params.leader_nw_out_weight * nw_out)
    with np.errstate(invalid="ignore", divide="ignore"):
        share = np.where(denom > 0, numer / np.maximum(denom, 1e-12), 0.0)
    return broker_cpu * share


def follower_cpu_from_leader(params: CpuEstimationParams,
                             leader_cpu: np.ndarray,
                             leader_nw_in: np.ndarray,
                             leader_nw_out: np.ndarray) -> np.ndarray:
    """Ref ModelUtils.getFollowerCpuUtilFromLeaderLoad: follower CPU is the
    replication-fetch share of the leader's network-attributed CPU."""
    denom = (params.leader_nw_in_weight * leader_nw_in
             + params.leader_nw_out_weight * leader_nw_out)
    with np.errstate(invalid="ignore", divide="ignore"):
        ratio = np.where(
            denom > 0,
            params.follower_nw_in_weight * leader_nw_in / np.maximum(denom, 1e-12),
            0.0,
        )
    return leader_cpu * ratio


def split_roles(params: CpuEstimationParams,
                leader_metrics: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(leader_load, follower_load) float64[RES, P] from leader-side windowed
    metrics float64[P, M] (M = partition metric def = Resource order).

    Role semantics (ref Load/ModelUtils, tensor_model docstring):
    follower NW_OUT = 0 (no consumer traffic), follower NW_IN = leader NW_IN
    (replication), DISK role-independent, follower CPU derived.
    """
    lm = np.asarray(leader_metrics, np.float64)
    leader = lm.T.copy()  # [RES, P]
    follower = leader.copy()
    follower[Resource.NW_OUT] = 0.0
    follower[Resource.CPU] = follower_cpu_from_leader(
        params, leader[Resource.CPU], leader[Resource.NW_IN],
        leader[Resource.NW_OUT],
    )
    return leader, follower
