"""Leader/follower load decomposition — CPU estimation coefficients.

Parity: ``model/{ModelUtils,ModelParameters,LinearRegressionModelParameters}
.java`` (SURVEY.md C6): the reference estimates a replica's CPU from its
network activity with fixed coefficients (configurable; a legacy linear-
regression training path can fit them), and derives the **follower** role's
load profile from the leader's (follower CPU ~ replication traffic only,
follower NW_OUT = 0, follower NW_IN = leader bytes-in).

These functions produce the ``leader_load`` / ``follower_load`` pair the
TensorClusterModel stores per partition (ccx.model.tensor_model), which is
how leadership transfer re-weights broker loads with no re-aggregation.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ccx.common.resources import Resource


@dataclasses.dataclass(frozen=True)
class CpuEstimationParams:
    """Ref MonitorConfig `*.weight.for.cpu.util` keys (SURVEY.md C6)."""

    leader_nw_in_weight: float = 0.6
    leader_nw_out_weight: float = 0.1
    follower_nw_in_weight: float = 0.3

    @classmethod
    def from_config(cls, config) -> "CpuEstimationParams":
        return cls(
            config["leader.network.inbound.weight.for.cpu.util"],
            config["leader.network.outbound.weight.for.cpu.util"],
            config["follower.network.inbound.weight.for.cpu.util"],
        )


def estimate_leader_cpu(params: CpuEstimationParams, broker_cpu: np.ndarray,
                        nw_in: np.ndarray, nw_out: np.ndarray,
                        broker_nw_in: np.ndarray, broker_nw_out: np.ndarray) -> np.ndarray:
    """Apportion measured broker CPU to a leader replica by its share of
    weighted network activity (ref ModelUtils.estimateLeaderCpuUtil)."""
    denom = (params.leader_nw_in_weight * broker_nw_in
             + params.leader_nw_out_weight * broker_nw_out)
    numer = (params.leader_nw_in_weight * nw_in
             + params.leader_nw_out_weight * nw_out)
    with np.errstate(invalid="ignore", divide="ignore"):
        share = np.where(denom > 0, numer / np.maximum(denom, 1e-12), 0.0)
    return broker_cpu * share


def follower_cpu_from_leader(params: CpuEstimationParams,
                             leader_cpu: np.ndarray,
                             leader_nw_in: np.ndarray,
                             leader_nw_out: np.ndarray) -> np.ndarray:
    """Ref ModelUtils.getFollowerCpuUtilFromLeaderLoad: follower CPU is the
    replication-fetch share of the leader's network-attributed CPU."""
    denom = (params.leader_nw_in_weight * leader_nw_in
             + params.leader_nw_out_weight * leader_nw_out)
    with np.errstate(invalid="ignore", divide="ignore"):
        ratio = np.where(
            denom > 0,
            params.follower_nw_in_weight * leader_nw_in / np.maximum(denom, 1e-12),
            0.0,
        )
    return leader_cpu * ratio


class LinearRegressionModelParameters:
    """Parity: ``model/LinearRegressionModelParameters.java`` (SURVEY.md C6)
    — the legacy ``train`` path fitting the CPU coefficients from observed
    (broker CPU, NW_IN, NW_OUT) triples instead of using the static config
    weights. Least-squares over accumulated samples; ``to_params`` emits a
    ``CpuEstimationParams`` once enough observations arrived.
    """

    MIN_SAMPLES = 16

    def __init__(self) -> None:
        self._rows: list[tuple[float, float, float]] = []

    def add_observation(self, broker_cpu: float, nw_in: float, nw_out: float) -> None:
        self._rows.append((broker_cpu, nw_in, nw_out))

    def add_broker_samples(self, agg_values: np.ndarray, cpu_id: int,
                           in_id: int, out_id: int) -> None:
        """Ingest from a broker AggregationResult values array [B, W, M]."""
        v = agg_values.reshape(-1, agg_values.shape[-1])
        for row in v:
            if row[in_id] > 0 or row[out_id] > 0:
                self.add_observation(row[cpu_id], row[in_id], row[out_id])

    @property
    def num_observations(self) -> int:
        return len(self._rows)

    @property
    def trainable(self) -> bool:
        return len(self._rows) >= self.MIN_SAMPLES

    def fit(self) -> tuple[float, float]:
        """(nw_in_weight, nw_out_weight) such that cpu ~ a*in + b*out."""
        if not self.trainable:
            raise ValueError(
                f"need >= {self.MIN_SAMPLES} observations, have {len(self._rows)}"
            )
        rows = np.asarray(self._rows)
        coeffs, *_ = np.linalg.lstsq(rows[:, 1:], rows[:, 0], rcond=None)
        return float(max(coeffs[0], 0.0)), float(max(coeffs[1], 0.0))

    def to_params(self, follower_ratio: float = 0.5) -> CpuEstimationParams:
        a, b = self.fit()
        return CpuEstimationParams(
            leader_nw_in_weight=a,
            leader_nw_out_weight=b,
            follower_nw_in_weight=follower_ratio * a,
        )


def split_roles(params: CpuEstimationParams,
                leader_metrics: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(leader_load, follower_load) float64[RES, P] from leader-side windowed
    metrics float64[P, M] (M = partition metric def = Resource order).

    Role semantics (ref Load/ModelUtils, tensor_model docstring):
    follower NW_OUT = 0 (no consumer traffic), follower NW_IN = leader NW_IN
    (replication), DISK role-independent, follower CPU derived.
    """
    lm = np.asarray(leader_metrics, np.float64)
    leader = lm.T.copy()  # [RES, P]
    follower = leader.copy()
    follower[Resource.NW_OUT] = 0.0
    follower[Resource.CPU] = follower_cpu_from_leader(
        params, leader[Resource.CPU], leader[Resource.NW_IN],
        leader[Resource.NW_OUT],
    )
    return leader, follower
