"""Metric sample records + versioned binary serde.

Parity: ``monitor/sampling/holder/{PartitionMetricSample,BrokerMetricSample}
.java`` (SURVEY.md C13) — serializable sample records carried from the
samplers to the aggregators and persisted by the SampleStore — and the
serde role of ``cruise-control-metrics-reporter``'s ``MetricSerde`` for these
holder types. The binary layout is a little-endian versioned header + the
metric vector, so stores stay readable across schema evolution.
"""

from __future__ import annotations

import dataclasses
import struct

import numpy as np

from ccx.monitor.metricdef import BROKER_METRIC_DEF, PARTITION_METRIC_DEF, MetricDef

_MAGIC_PARTITION = b"CXP"
_MAGIC_BROKER = b"CXB"
_VERSION = 1


@dataclasses.dataclass(frozen=True)
class PartitionMetricSample:
    """One sampling interval's loads for one partition (leader-side).

    ``metrics`` is indexed by ``PARTITION_METRIC_DEF`` ids, i.e. the
    ``Resource`` axis order (CPU, NW_IN, NW_OUT, DISK).
    """

    broker_id: int
    partition: int          # dense partition index (topic-partition resolved
                            # by the metadata snapshot, ref ModelGeneration)
    time_ms: int
    metrics: tuple[float, ...]

    def metric(self, metric_id: int) -> float:
        return self.metrics[metric_id]

    def serialize(self) -> bytes:
        head = struct.pack(
            "<3sBqqqH", _MAGIC_PARTITION, _VERSION, self.broker_id,
            self.partition, self.time_ms, len(self.metrics)
        )
        return head + struct.pack(f"<{len(self.metrics)}d", *self.metrics)

    @classmethod
    def deserialize(cls, buf: bytes) -> "PartitionMetricSample":
        magic, version, broker, part, t, n = struct.unpack_from("<3sBqqqH", buf)
        if magic != _MAGIC_PARTITION:
            raise ValueError(f"bad partition-sample magic {magic!r}")
        if version > _VERSION:
            raise ValueError(f"unsupported partition-sample version {version}")
        vals = struct.unpack_from(f"<{n}d", buf, struct.calcsize("<3sBqqqH"))
        return cls(broker, part, t, tuple(vals))


@dataclasses.dataclass(frozen=True)
class BrokerMetricSample:
    """One sampling interval's health metrics for one broker (ref C13)."""

    broker_id: int
    time_ms: int
    metrics: tuple[float, ...]   # indexed by BROKER_METRIC_DEF ids

    def metric(self, metric_id: int) -> float:
        return self.metrics[metric_id]

    def serialize(self) -> bytes:
        head = struct.pack(
            "<3sBqqH", _MAGIC_BROKER, _VERSION, self.broker_id, self.time_ms,
            len(self.metrics)
        )
        return head + struct.pack(f"<{len(self.metrics)}d", *self.metrics)

    @classmethod
    def deserialize(cls, buf: bytes) -> "BrokerMetricSample":
        magic, version, broker, t, n = struct.unpack_from("<3sBqqH", buf)
        if magic != _MAGIC_BROKER:
            raise ValueError(f"bad broker-sample magic {magic!r}")
        if version > _VERSION:
            raise ValueError(f"unsupported broker-sample version {version}")
        vals = struct.unpack_from(f"<{n}d", buf, struct.calcsize("<3sBqqH"))
        return cls(broker, t, tuple(vals))


def metric_vector(values: dict[str, float], metric_def: MetricDef) -> tuple[float, ...]:
    """Build a dense metric tuple from a name->value dict (missing = 0)."""
    out = [0.0] * metric_def.num_metrics
    for name, v in values.items():
        out[metric_def.metric_info(name).id] = float(v)
    return tuple(out)


def partition_sample(broker_id: int, partition: int, time_ms: int,
                     **named: float) -> PartitionMetricSample:
    return PartitionMetricSample(
        broker_id, partition, time_ms, metric_vector(named, PARTITION_METRIC_DEF)
    )


def broker_sample(broker_id: int, time_ms: int, **named: float) -> BrokerMetricSample:
    return BrokerMetricSample(
        broker_id, time_ms, metric_vector(named, BROKER_METRIC_DEF)
    )


def serialize_batch(samples) -> bytes:
    """Length-prefixed concatenation (SampleStore on-disk record format)."""
    out = bytearray()
    for s in samples:
        b = s.serialize()
        out += struct.pack("<I", len(b)) + b
    return bytes(out)


def deserialize_batch(buf: bytes) -> list:
    out = []
    off = 0
    while off < len(buf):
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        rec = buf[off:off + n]
        off += n
        if rec[:3] == _MAGIC_PARTITION:
            out.append(PartitionMetricSample.deserialize(rec))
        elif rec[:3] == _MAGIC_BROKER:
            out.append(BrokerMetricSample.deserialize(rec))
        else:
            raise ValueError(f"bad sample magic {rec[:3]!r}")
    return out


def samples_to_arrays(samples: list[PartitionMetricSample]) -> tuple[np.ndarray, ...]:
    """Columnar view (entity_ids, time_ms, metrics[n, M]) for batch ingest."""
    ids = np.fromiter((s.partition for s in samples), np.int64, len(samples))
    times = np.fromiter((s.time_ms for s in samples), np.int64, len(samples))
    metrics = np.asarray([s.metrics for s in samples], np.float64)
    return ids, times, metrics
