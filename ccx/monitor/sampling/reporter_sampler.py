"""ReporterMetricSampler — the default sampler, fed by the metrics reporter.

Parity: ``monitor/sampling/CruiseControlMetricsReporterSampler.java``
(SURVEY.md C10, call stack 3.4): consumes the raw-metric channel the
in-broker reporters produce to, groups records by partition/broker and time,
derives ``PartitionMetricSample``s — estimating per-partition leader CPU
from the broker's CPU by weighted network share, exactly the
``ModelUtils``/``ModelParameters`` role (C6) — and ``BrokerMetricSample``s
from the broker-scope rows.
"""

from __future__ import annotations

import collections

from ccx.common.metadata import ClusterMetadata
from ccx.monitor.metricdef import BROKER_METRIC_DEF
from ccx.monitor.model_utils import CpuEstimationParams
from ccx.monitor.sampling.holders import (
    BrokerMetricSample,
    PartitionMetricSample,
    metric_vector,
)
from ccx.monitor.sampling.sampler import MetricSampler, Samples
from ccx.reporter.metrics import RawMetricType
from ccx.reporter.transport import DEFAULT_CHANNEL, InMemoryTransport


class ReporterMetricSampler(MetricSampler):
    """Default ``metric.sampler.class`` (ref C10)."""

    def __init__(self, transport=None, config=None) -> None:
        self.transport = transport
        self.cpu_params = CpuEstimationParams()
        self.channel = DEFAULT_CHANNEL
        if config is not None:
            self.configure(config)

    def configure(self, config) -> None:
        self.channel = config["cruise.control.metrics.topic"]
        self.cpu_params = CpuEstimationParams.from_config(config)
        if self.transport is None:
            self.transport = InMemoryTransport.channel(self.channel)

    def get_samples(self, metadata: ClusterMetadata,
                    assigned_partitions: list[int],
                    start_ms: int, end_ms: int) -> Samples:
        if self.transport is None:
            self.transport = InMemoryTransport.channel(self.channel)
        records = self.transport.consume(start_ms, end_ms)
        # Retention: records older than one full sampling interval before
        # this round's start will never be consumed again (fetcher shards of
        # the current round all read >= start_ms) — evict so the channel
        # does not grow for the life of the process.
        self.transport.evict_before(start_ms - max(end_ms - start_ms, 1))
        pidx = metadata.partition_index()
        leader_of = {
            (p.tp.topic, p.tp.partition): p.leader for p in metadata.partitions
        }
        assigned = set(assigned_partitions)

        # ---- broker-scope rows: (broker, time) -> {metric name: value} ----
        broker_rows: dict[tuple[int, int], dict[str, float]] = (
            collections.defaultdict(dict)
        )
        # ---- partition-scope rows: (tp, time) -> {type: (broker, value)} --
        part_rows: dict[tuple, dict[RawMetricType, tuple[int, float]]] = (
            collections.defaultdict(dict)
        )
        for m in records:
            if m.scope == "BROKER":
                broker_rows[(m.broker_id, m.time_ms)][m.metric_type.name] = m.value
            elif m.scope == "PARTITION":
                key = ((m.topic, m.partition), m.time_ms)
                prev = part_rows[key].get(m.metric_type)
                # leader-reported rows win over follower-reported sizes
                if (
                    prev is None
                    or m.metric_type is not RawMetricType.PARTITION_SIZE
                    or prev[0] != leader_of.get((m.topic, m.partition), -1)
                ):
                    part_rows[key][m.metric_type] = (m.broker_id, m.value)

        psamples: list[PartitionMetricSample] = []
        for ((topic, partition), t), row in part_rows.items():
            from ccx.common.metadata import TopicPartition

            dense = pidx.get(TopicPartition(topic, partition))
            if dense is None or dense not in assigned:
                continue
            nw_in = row.get(RawMetricType.PARTITION_BYTES_IN, (0, 0.0))[1]
            nw_out = row.get(RawMetricType.PARTITION_BYTES_OUT, (0, 0.0))[1]
            size = row.get(RawMetricType.PARTITION_SIZE, (0, 0.0))[1]
            leader = leader_of.get((topic, partition), -1)
            if leader < 0:
                continue
            brow = broker_rows.get((leader, t), {})
            broker_cpu = brow.get("BROKER_CPU_UTIL", 0.0) * 100.0
            broker_in = brow.get("ALL_TOPIC_BYTES_IN", 0.0)
            broker_out = brow.get("ALL_TOPIC_BYTES_OUT", 0.0)
            from ccx.monitor.model_utils import estimate_leader_cpu
            import numpy as np

            cpu = float(
                estimate_leader_cpu(
                    self.cpu_params, np.array(broker_cpu), np.array(nw_in),
                    np.array(nw_out), np.array(broker_in), np.array(broker_out),
                )
            )
            psamples.append(
                PartitionMetricSample(
                    leader, dense, t, (cpu, nw_in, nw_out, size)
                )
            )

        bsamples: list[BrokerMetricSample] = []
        known_names = {m.name for m in BROKER_METRIC_DEF.all_metrics()}
        for (broker, t), row in broker_rows.items():
            named = {k: v for k, v in row.items() if k in known_names}
            if named:
                bsamples.append(
                    BrokerMetricSample(
                        broker, t, metric_vector(named, BROKER_METRIC_DEF)
                    )
                )
        return Samples(psamples, bsamples)
