"""SampleStore SPI — sample persistence and warm start.

Parity: ``monitor/sampling/KafkaSampleStore.java`` / ``NoopSampleStore``
(SURVEY.md C11, §5.4): every sample batch is persisted, and on startup
``load_samples`` replays them into the aggregators so the monitor's windows
survive a restart — this is the framework's checkpoint/resume mechanism (the
service itself stays stateless). The default store is file-backed
(segmented append-only logs, the two-topics analogue), with retention by
window span.
"""

from __future__ import annotations

import os
import threading

from ccx.monitor.sampling.holders import (
    BrokerMetricSample,
    PartitionMetricSample,
    deserialize_batch,
    serialize_batch,
)
from ccx.monitor.sampling.sampler import Samples


class SampleStore:
    """SPI (ref C11)."""

    def configure(self, config) -> None:
        pass

    def store_samples(self, samples: Samples) -> None:
        raise NotImplementedError

    def load_samples(self) -> Samples:
        """Replay persisted samples (called once at LoadMonitor startup)."""
        raise NotImplementedError

    def evict_before(self, partition_before_ms: int,
                     broker_before_ms: int | None = None) -> None:
        """Drop expired samples; broker scope may retain a different span."""

    def close(self) -> None:
        pass


class NoopSampleStore(SampleStore):
    def __init__(self, config=None) -> None:
        pass

    def store_samples(self, samples: Samples) -> None:
        pass

    def load_samples(self) -> Samples:
        return Samples([], [])


class FileSampleStore(SampleStore):
    """Append-only segmented files, one per sample scope.

    ``partition-samples.log`` / ``broker-samples.log`` under ``dir``, records
    length-prefixed (holders.serialize_batch framing). ``evict_before``
    rewrites segments dropping expired records — cheap at the monitor's
    sample volumes, and keeps the store a plain directory an operator can
    delete to cold-start (ref: topic retention on the sample-store topics).
    """

    PARTITION_LOG = "partition-samples.log"
    BROKER_LOG = "broker-samples.log"

    def __init__(self, dir: str | None = None, config=None) -> None:
        if dir is None and config is not None:
            dir = config["sample.store.dir"]
        self.dir = dir or "/tmp/ccx-samples"
        self._lock = threading.Lock()
        os.makedirs(self.dir, exist_ok=True)

    def configure(self, config) -> None:
        self.dir = config["sample.store.dir"]
        os.makedirs(self.dir, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.dir, name)

    def store_samples(self, samples: Samples) -> None:
        with self._lock:
            if samples.partition_samples:
                with open(self._path(self.PARTITION_LOG), "ab") as f:
                    f.write(serialize_batch(samples.partition_samples))
            if samples.broker_samples:
                with open(self._path(self.BROKER_LOG), "ab") as f:
                    f.write(serialize_batch(samples.broker_samples))

    def _read(self, name: str) -> list:
        path = self._path(name)
        if not os.path.exists(path):
            return []
        with open(path, "rb") as f:
            return deserialize_batch(f.read())

    def load_samples(self) -> Samples:
        with self._lock:
            return Samples(
                [s for s in self._read(self.PARTITION_LOG)
                 if isinstance(s, PartitionMetricSample)],
                [s for s in self._read(self.BROKER_LOG)
                 if isinstance(s, BrokerMetricSample)],
            )

    def load_broker_samples(self) -> list[BrokerMetricSample]:
        with self._lock:
            return [s for s in self._read(self.BROKER_LOG)
                    if isinstance(s, BrokerMetricSample)]

    def raw_partition_log(self) -> bytes:
        """Raw log bytes for the native columnar decoder (warm-start fast
        path; see ccx.native.decode_partition_samples)."""
        with self._lock:
            path = self._path(self.PARTITION_LOG)
            if not os.path.exists(path):
                return b""
            with open(path, "rb") as f:
                return f.read()

    def evict_before(self, partition_before_ms: int,
                     broker_before_ms: int | None = None) -> None:
        if broker_before_ms is None:
            broker_before_ms = partition_before_ms
        with self._lock:
            for name, cutoff in (
                (self.PARTITION_LOG, partition_before_ms),
                (self.BROKER_LOG, broker_before_ms),
            ):
                path = self._path(name)
                if not os.path.exists(path):
                    continue
                recs = [s for s in self._read(name) if s.time_ms >= cutoff]
                # Atomic rewrite: a crash mid-eviction must not destroy the
                # warm-start checkpoint (write-temp + rename).
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(serialize_batch(recs))
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
