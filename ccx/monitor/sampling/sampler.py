"""MetricSampler SPI — pluggable raw-metric sources.

Parity: ``monitor/sampling/MetricSampler.java`` (SURVEY.md C10). A sampler
turns an external metric source into ``PartitionMetricSample`` /
``BrokerMetricSample`` batches for its assigned partitions over a time range.
The default implementation consumes the metrics-reporter transport
(``ccx.reporter``, the ``__CruiseControlMetrics`` analogue); a synthetic
sampler serves tests and benchmarks the way the reference's unit fixtures do.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ccx.common.metadata import ClusterMetadata
from ccx.monitor.sampling.holders import BrokerMetricSample, PartitionMetricSample


@dataclasses.dataclass
class Samples:
    partition_samples: list[PartitionMetricSample]
    broker_samples: list[BrokerMetricSample]


class MetricSampler:
    """SPI (ref C10). ``assigned_partitions`` are dense partition indices of
    the given metadata generation; implementations must only return samples
    for those (fetcher threads shard the partition space)."""

    def configure(self, config) -> None:  # optional
        pass

    def get_samples(self, metadata: ClusterMetadata,
                    assigned_partitions: list[int],
                    start_ms: int, end_ms: int) -> Samples:
        raise NotImplementedError

    def close(self) -> None:
        pass


class SyntheticMetricSampler(MetricSampler):
    """Deterministic load generator (test/bench double for C10).

    Each partition gets a stable pseudo-random base load from its index; a
    sinusoidal time component exercises windowing. Broker health metrics are
    derived from hosted leader load so SlowBrokerFinder fixtures can perturb
    individual brokers via ``broker_latency_overrides``.
    """

    def __init__(self, seed: int = 7, interval_ms: int = 1000, config=None) -> None:
        self.seed = seed
        self.interval_ms = interval_ms
        self.broker_latency_overrides: dict[int, float] = {}

    def configure(self, config) -> None:
        self.interval_ms = min(self.interval_ms, config["metric.sampling.interval.ms"])

    def _base_loads(self, n: int) -> np.ndarray:
        rng = np.random.default_rng(self.seed)
        base = rng.random((n, 4))
        base[:, 0] = 1.0 + 4.0 * base[:, 0]      # CPU %
        base[:, 1] = 50.0 + 400.0 * base[:, 1]   # NW_IN KB/s
        base[:, 2] = 80.0 + 600.0 * base[:, 2]   # NW_OUT KB/s
        base[:, 3] = 100.0 + 900.0 * base[:, 3]  # DISK MB
        return base

    def get_samples(self, metadata: ClusterMetadata,
                    assigned_partitions: list[int],
                    start_ms: int, end_ms: int) -> Samples:
        base = self._base_loads(len(metadata.partitions))
        psamples: list[PartitionMetricSample] = []
        times = np.arange(start_ms, end_ms, self.interval_ms)
        for p in assigned_partitions:
            info = metadata.partitions[p]
            if info.leader < 0:
                continue
            for t in times:
                wobble = 1.0 + 0.1 * np.sin(2 * np.pi * (t % 3_600_000) / 3_600_000)
                m = base[p] * wobble
                psamples.append(
                    PartitionMetricSample(info.leader, p, int(t), tuple(m))
                )
        # broker samples: aggregate leader load onto brokers
        bsamples: list[BrokerMetricSample] = []
        bidx = metadata.broker_index()
        leader_in = np.zeros(len(metadata.brokers))
        leader_out = np.zeros(len(metadata.brokers))
        cpu = np.zeros(len(metadata.brokers))
        for p, info in enumerate(metadata.partitions):
            if info.leader >= 0 and info.leader in bidx:
                leader_in[bidx[info.leader]] += base[p, 1]
                leader_out[bidx[info.leader]] += base[p, 2]
                cpu[bidx[info.leader]] += base[p, 0]
        from ccx.monitor.metricdef import BROKER_METRIC_DEF
        from ccx.monitor.sampling.holders import metric_vector

        for b in metadata.brokers:
            if not b.alive:
                continue
            i = bidx[b.broker_id]
            flush = self.broker_latency_overrides.get(b.broker_id, 5.0)
            for t in times:
                vec = metric_vector(
                    {
                        "ALL_TOPIC_BYTES_IN": leader_in[i],
                        "ALL_TOPIC_BYTES_OUT": leader_out[i],
                        "BROKER_CPU_UTIL": min(cpu[i] / 100.0, 1.0),
                        "BROKER_LOG_FLUSH_TIME_MS_MEAN": flush,
                        "BROKER_LOG_FLUSH_TIME_MS_MAX": 2.0 * flush,
                        "UNDER_REPLICATED_PARTITIONS": 0.0,
                        "OFFLINE_LOG_DIRS": float(len(b.offline_disks)),
                    },
                    BROKER_METRIC_DEF,
                )
                bsamples.append(BrokerMetricSample(b.broker_id, int(t), vec))
        return Samples(psamples, bsamples)
