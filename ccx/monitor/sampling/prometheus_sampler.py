"""PrometheusMetricSampler — scrape a Prometheus server for raw metrics.

Parity: ``monitor/sampling/prometheus/PrometheusMetricSampler.java``
(SURVEY.md C10): an alternative ``metric.sampler.class`` for clusters whose
brokers expose metrics through Prometheus instead of the metrics-reporter
topic. Queries the ``query_range`` HTTP API for a configurable mapping of
PromQL expressions to partition/broker metrics; stdlib urllib only.

Config keys (prefix ``prometheus.server.``): the endpoint URL plus optional
query overrides; default queries follow kafka_exporter/jmx-exporter naming.
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request

import numpy as np

from ccx.common.metadata import ClusterMetadata, TopicPartition
from ccx.monitor.metricdef import BROKER_METRIC_DEF
from ccx.monitor.model_utils import CpuEstimationParams, estimate_leader_cpu
from ccx.monitor.sampling.holders import (
    BrokerMetricSample,
    PartitionMetricSample,
    metric_vector,
)
from ccx.monitor.sampling.sampler import MetricSampler, Samples

#: PromQL per partition metric (labels: topic, partition, instance->broker)
DEFAULT_PARTITION_QUERIES = {
    "NETWORK_IN_RATE": "rate(kafka_server_brokertopicmetrics_bytesin_total[1m])/1024",
    "NETWORK_OUT_RATE": "rate(kafka_server_brokertopicmetrics_bytesout_total[1m])/1024",
    "DISK_USAGE": "kafka_log_log_size/1048576",
}
DEFAULT_BROKER_QUERIES = {
    "ALL_TOPIC_BYTES_IN": "sum by (instance) (rate(kafka_server_brokertopicmetrics_bytesin_total[1m]))/1024",
    "ALL_TOPIC_BYTES_OUT": "sum by (instance) (rate(kafka_server_brokertopicmetrics_bytesout_total[1m]))/1024",
    "BROKER_CPU_UTIL": "1 - avg by (instance) (rate(node_cpu_seconds_total{mode='idle'}[1m]))",
    "BROKER_LOG_FLUSH_TIME_MS_MEAN": "kafka_log_logflushstats_logflushtime_ms{quantile='0.50'}",
}


class PrometheusMetricSampler(MetricSampler):
    def __init__(self, endpoint: str = "http://127.0.0.1:9090",
                 broker_label: str = "instance", config=None) -> None:
        self.endpoint = endpoint.rstrip("/")
        self.broker_label = broker_label
        self.partition_queries = dict(DEFAULT_PARTITION_QUERIES)
        self.broker_queries = dict(DEFAULT_BROKER_QUERIES)
        self.cpu_params = CpuEstimationParams()
        self.step_s = 60

    def configure(self, config) -> None:
        ep = config.get("prometheus.server.endpoint")
        if ep:
            self.endpoint = str(ep).rstrip("/")
        self.cpu_params = CpuEstimationParams.from_config(config)

    # ----- HTTP -------------------------------------------------------------

    def _query_range(self, query: str, start_ms: int, end_ms: int) -> list[dict]:
        params = urllib.parse.urlencode({
            "query": query,
            "start": start_ms / 1000.0,
            "end": max(end_ms - 1, start_ms) / 1000.0,
            "step": self.step_s,
        })
        url = f"{self.endpoint}/api/v1/query_range?{params}"
        with urllib.request.urlopen(url, timeout=30) as resp:
            doc = json.load(resp)
        if doc.get("status") != "success":
            raise RuntimeError(f"prometheus query failed: {doc}")
        return doc["data"]["result"]

    def _broker_id(self, labels: dict) -> int | None:
        raw = labels.get(self.broker_label, "")
        digits = "".join(c for c in raw.split(":")[0] if c.isdigit())
        try:
            return int(labels.get("broker_id", digits))
        except ValueError:
            return None

    # ----- sampling ---------------------------------------------------------

    def get_samples(self, metadata: ClusterMetadata,
                    assigned_partitions: list[int],
                    start_ms: int, end_ms: int) -> Samples:
        pidx = metadata.partition_index()
        assigned = set(assigned_partitions)
        leader_of = {p.tp: p.leader for p in metadata.partitions}

        # (dense partition, t) -> {metric name: value}
        part_rows: dict[tuple[int, int], dict[str, float]] = {}
        for name, q in self.partition_queries.items():
            for series in self._query_range(q, start_ms, end_ms):
                labels = series.get("metric", {})
                tp = TopicPartition(
                    labels.get("topic", ""), int(labels.get("partition", -1))
                )
                dense = pidx.get(tp)
                if dense is None or dense not in assigned:
                    continue
                for ts, value in series.get("values", ()):
                    t = int(float(ts) * 1000)
                    part_rows.setdefault((dense, t), {})[name] = float(value)

        broker_rows: dict[tuple[int, int], dict[str, float]] = {}
        for name, q in self.broker_queries.items():
            for series in self._query_range(q, start_ms, end_ms):
                broker = self._broker_id(series.get("metric", {}))
                if broker is None:
                    continue
                for ts, value in series.get("values", ()):
                    t = int(float(ts) * 1000)
                    broker_rows.setdefault((broker, t), {})[name] = float(value)

        psamples = []
        for (dense, t), row in part_rows.items():
            leader = leader_of.get(metadata.partitions[dense].tp, -1)
            if leader < 0:
                continue
            brow = broker_rows.get((leader, t), {})
            cpu = float(estimate_leader_cpu(
                self.cpu_params,
                np.array(brow.get("BROKER_CPU_UTIL", 0.0) * 100.0),
                np.array(row.get("NETWORK_IN_RATE", 0.0)),
                np.array(row.get("NETWORK_OUT_RATE", 0.0)),
                np.array(brow.get("ALL_TOPIC_BYTES_IN", 0.0)),
                np.array(brow.get("ALL_TOPIC_BYTES_OUT", 0.0)),
            ))
            psamples.append(PartitionMetricSample(
                leader, dense, t,
                (cpu, row.get("NETWORK_IN_RATE", 0.0),
                 row.get("NETWORK_OUT_RATE", 0.0),
                 row.get("DISK_USAGE", 0.0)),
            ))

        known = {m.name for m in BROKER_METRIC_DEF.all_metrics()}
        bsamples = []
        # BROKER_CPU_UTIL passes through as the 0-1 ratio the queries yield
        # (same convention as reporter_sampler).
        for (broker, t), row in broker_rows.items():
            named = {k: v for k, v in row.items() if k in known}
            if named:
                bsamples.append(BrokerMetricSample(
                    broker, t, metric_vector(named, BROKER_METRIC_DEF)
                ))
        return Samples(psamples, bsamples)
