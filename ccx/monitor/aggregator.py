"""Windowed metric-sample aggregation with extrapolation + completeness.

Parity: ``cruise-control-core``'s ``MetricSampleAggregator`` family
(SURVEY.md M1/C8): raw samples land in fixed-span time windows per entity
(partition or broker); aggregation rolls each window up with the metric's
``AggregationFunction``; windows with too few samples are *extrapolated*
(``FORCED_INSUFFICIENT`` = use what's there, ``AVG_ADJACENT`` = average the
neighbor windows) up to a per-entity budget; a ``MetricSampleCompleteness``
summary gates model generation via ``ModelCompletenessRequirements``.

Design departure from the JVM: instead of per-entity hash maps of per-window
sample lists, the store is **columnar numpy** — ``sum/count/max/latest``
arrays of shape [E, W, M] with a rolling window base — so ingest is
``np.add.at`` scatter, aggregation is one vectorized pass, and the output
feeds the tensor ClusterModel build (and the TPU) without per-object walks.
This is the host-side half of the "hot loop #2" (O(P·W)) in SURVEY.md call
stack 3.2.
"""

from __future__ import annotations

import dataclasses
import enum
import threading

import numpy as np

from ccx.monitor.metricdef import AggregationFunction, MetricDef


class Extrapolation(enum.IntEnum):
    """Per entity-window provenance (ref core's Extrapolation enum)."""

    NONE = 0                 # enough samples
    FORCED_INSUFFICIENT = 1  # some samples, below the minimum
    AVG_ADJACENT = 2         # zero samples, neighbors averaged
    NO_VALID = 3             # zero samples, no usable neighbors


@dataclasses.dataclass(frozen=True)
class ModelCompletenessRequirements:
    """Parity: monitor ``ModelCompletenessRequirements`` (SURVEY.md C8)."""

    min_required_num_windows: int = 1
    min_valid_entity_ratio: float = 0.95   # min.monitored.partition.percentage
    include_all_entities: bool = False

    def merged(self, other: "ModelCompletenessRequirements") -> "ModelCompletenessRequirements":
        """The stricter union of two requirements (ref: requirements of all
        goals in a request are combined)."""
        return ModelCompletenessRequirements(
            max(self.min_required_num_windows, other.min_required_num_windows),
            max(self.min_valid_entity_ratio, other.min_valid_entity_ratio),
            self.include_all_entities or other.include_all_entities,
        )


@dataclasses.dataclass
class AggregationResult:
    """Parity: ``MetricSampleAggregationResult`` + ``ValuesAndExtrapolations``.

    ``values``: float64[E, W, M] — newest window last; ``extrapolations``:
    int8[E, W]; ``entity_valid``: bool[E] (within the extrapolation budget and
    no NO_VALID window); ``window_starts_ms``: int64[W].
    """

    values: np.ndarray
    extrapolations: np.ndarray
    entity_valid: np.ndarray
    window_starts_ms: np.ndarray
    valid_entity_ratio: float
    generation: int

    @property
    def num_windows(self) -> int:
        return self.values.shape[1]

    def meets(self, req: ModelCompletenessRequirements) -> bool:
        if self.num_windows < req.min_required_num_windows:
            return False
        if self.valid_entity_ratio < req.min_valid_entity_ratio:
            return False
        if req.include_all_entities and not bool(self.entity_valid.all()):
            return False
        return True


class MetricSampleAggregator:
    """Rolling columnar window store for one entity class.

    Subclassed/instantiated per scope like the reference's
    ``KafkaPartitionMetricSampleAggregator`` / ``KafkaBrokerMetricSampleAggregator``
    (SURVEY.md C8): ``num_entities`` is resizable upward (new partitions /
    brokers appear); entity ids are dense indices supplied by the caller's
    metadata snapshot.
    """

    def __init__(
        self,
        metric_def: MetricDef,
        num_windows: int,
        window_ms: int,
        min_samples_per_window: int = 1,
        max_allowed_extrapolations: int = 5,
        num_entities: int = 0,
    ) -> None:
        self.metric_def = metric_def
        self.num_windows = int(num_windows)
        self.window_ms = int(window_ms)
        self.min_samples_per_window = int(min_samples_per_window)
        self.max_allowed_extrapolations = int(max_allowed_extrapolations)
        # W+1 slots: the newest ("current") window is still filling and is
        # excluded from aggregation, as in the reference.
        self._slots = self.num_windows + 1
        self._base_window = None  # absolute index of slot 0
        self._first_window = None  # absolute index of the earliest real sample
        self._generation = 0      # bumps on every window roll (ModelGeneration)
        self._lock = threading.RLock()
        E, W, M = num_entities, self._slots, metric_def.num_metrics
        self._sum = np.zeros((E, W, M))
        self._max = np.full((E, W, M), -np.inf)
        self._latest = np.zeros((E, W, M))
        self._latest_t = np.full((E, W), -1, np.int64)
        self._count = np.zeros((E, W), np.int64)
        # per-metric aggregation selector
        agg = [m.aggregation for m in metric_def.all_metrics()]
        self._is_avg = np.array([a is AggregationFunction.AVG for a in agg])
        self._is_max = np.array([a is AggregationFunction.MAX for a in agg])
        self._is_latest = np.array([a is AggregationFunction.LATEST for a in agg])

    # ----- sizing ----------------------------------------------------------

    @property
    def num_entities(self) -> int:
        return self._sum.shape[0]

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def clear(self) -> None:
        """Drop all windows and samples (ref bootstrap `clearmetrics`);
        entity capacity is kept, the generation bumps."""
        with self._lock:
            self._sum[:] = 0.0
            self._max[:] = -np.inf
            self._latest[:] = 0.0
            self._latest_t[:] = -1
            self._count[:] = 0
            self._base_window = None
            self._first_window = None
            self._generation += 1

    def ensure_entities(self, n: int) -> None:
        with self._lock:
            E = self.num_entities
            if n <= E:
                return
            grow = n - E
            W, M = self._slots, self.metric_def.num_metrics
            self._sum = np.concatenate([self._sum, np.zeros((grow, W, M))])
            self._max = np.concatenate([self._max, np.full((grow, W, M), -np.inf)])
            self._latest = np.concatenate([self._latest, np.zeros((grow, W, M))])
            self._latest_t = np.concatenate(
                [self._latest_t, np.full((grow, W), -1, np.int64)]
            )
            self._count = np.concatenate([self._count, np.zeros((grow, W), np.int64)])
            self._generation += 1

    # ----- ingest ----------------------------------------------------------

    def _roll_to(self, newest_window: int) -> None:
        """Advance the rolling buffer so ``newest_window`` fits in-slot."""
        if self._base_window is None:
            self._base_window = newest_window - self._slots + 1
        shift = newest_window - (self._base_window + self._slots - 1)
        if shift <= 0:
            return
        self._generation += 1
        if shift >= self._slots:
            self._sum[:] = 0.0
            self._max[:] = -np.inf
            self._latest[:] = 0.0
            self._latest_t[:] = -1
            self._count[:] = 0
        else:
            self._sum = np.roll(self._sum, -shift, axis=1)
            self._max = np.roll(self._max, -shift, axis=1)
            self._latest = np.roll(self._latest, -shift, axis=1)
            self._latest_t = np.roll(self._latest_t, -shift, axis=1)
            self._count = np.roll(self._count, -shift, axis=1)
            self._sum[:, -shift:] = 0.0
            self._max[:, -shift:] = -np.inf
            self._latest[:, -shift:] = 0.0
            self._latest_t[:, -shift:] = -1
            self._count[:, -shift:] = 0
        self._base_window += shift

    def add_samples(self, entity_ids: np.ndarray, times_ms: np.ndarray,
                    metrics: np.ndarray, now_ms: int | None = None) -> int:
        """Batch ingest; returns the number of accepted samples.

        Samples outside the monitored period are dropped (ref: the
        aggregator rejects out-of-period samples): older than the retained
        window range, or — when ``now_ms`` is given — timestamped beyond
        one window into the future (clock skew / buggy sampler), which would
        otherwise wipe history by force-rolling the buffer forward.
        """
        with self._lock:
            entity_ids = np.asarray(entity_ids, np.int64)
            times_ms = np.asarray(times_ms, np.int64)
            metrics = np.asarray(metrics, np.float64)
            if now_ms is not None:
                fresh = times_ms <= now_ms + self.window_ms
                entity_ids, times_ms, metrics = (
                    entity_ids[fresh], times_ms[fresh], metrics[fresh]
                )
            if entity_ids.size == 0:
                return 0
            self.ensure_entities(int(entity_ids.max()) + 1)
            windows = times_ms // self.window_ms
            if self._first_window is None:
                self._first_window = int(windows.min())
            else:
                self._first_window = min(self._first_window, int(windows.min()))
            self._roll_to(int(windows.max()))
            slot = windows - self._base_window
            ok = slot >= 0
            if not ok.any():
                return 0
            e, s, t, m = entity_ids[ok], slot[ok], times_ms[ok], metrics[ok]
            # Rows sorted ascending by time: required for last-write-wins
            # LATEST semantics in both the native and numpy paths.
            order = np.argsort(t, kind="stable")
            e, s, t, m = e[order], s[order], t[order], m[order]
            from ccx import native

            if not native.scatter(
                self._sum, self._max, self._latest, self._latest_t,
                self._count, e, s, t, m,
            ):
                np.add.at(self._sum, (e, s), m)
                np.maximum.at(self._max, (e, s), m)
                np.add.at(self._count, (e, s), 1)
                newer = t >= self._latest_t[e, s]
                # later duplicates in the same batch overwrite — sorted order
                # makes fancy-assignment's last-occurrence the newest sample
                self._latest[e[newer], s[newer]] = m[newer]
                self._latest_t[e[newer], s[newer]] = t[newer]
            return int(ok.sum())

    def add_sample(self, entity_id: int, time_ms: int, metrics) -> bool:
        return self.add_samples(
            np.array([entity_id]), np.array([time_ms]),
            np.array([metrics], np.float64)
        ) == 1

    # ----- aggregation -----------------------------------------------------

    def aggregate(self, num_entities: int | None = None) -> AggregationResult:
        """Roll up the W completed windows (newest-but-one backwards).

        ``num_entities`` lets the caller size the result to the metadata
        snapshot (entities never sampled count as invalid, which is exactly
        how completeness sees unmonitored partitions).
        """
        with self._lock:
            E = self.num_entities if num_entities is None else int(num_entities)
            W, M = self.num_windows, self.metric_def.num_metrics
            if self._base_window is None:
                values = np.zeros((E, 0, M))
                extrap = np.zeros((E, 0), np.int8)
                starts = np.zeros(0, np.int64)
                return AggregationResult(
                    values, extrap, np.zeros(E, bool), starts, 0.0,
                    self._generation,
                )
            # Read path: never grow the store (that would bump the generation
            # on a pure read) — entities beyond the stored range are reported
            # as never-sampled via zero-padded virtual rows.
            Es = min(E, self.num_entities)
            sum_, max_, latest = self._sum[:Es, :W], self._max[:Es, :W], self._latest[:Es, :W]
            count = self._count[:Es, :W]
            if E > Es:
                pad = (0, E - Es)
                sum_ = np.pad(sum_, (pad, (0, 0), (0, 0)))
                max_ = np.pad(max_, (pad, (0, 0), (0, 0)),
                              constant_values=-np.inf)
                latest = np.pad(latest, (pad, (0, 0), (0, 0)))
                count = np.pad(count, (pad, (0, 0)))

            with np.errstate(invalid="ignore", divide="ignore"):
                avg = np.where(count[..., None] > 0, sum_ / np.maximum(count[..., None], 1), 0.0)
            vals = np.where(
                self._is_avg, avg,
                np.where(self._is_max, np.where(np.isfinite(max_), max_, 0.0), latest),
            )

            has_any = count > 0
            enough = count >= self.min_samples_per_window
            # AVG_ADJACENT for empty windows with a sampled window on each side
            left = np.zeros_like(has_any)
            right = np.zeros_like(has_any)
            left[:, 1:] = has_any[:, :-1]
            right[:, :-1] = has_any[:, 1:]
            adjacent_ok = (~has_any) & left & right
            vleft = np.zeros_like(vals)
            vright = np.zeros_like(vals)
            vleft[:, 1:] = vals[:, :-1]
            vright[:, :-1] = vals[:, 1:]
            vals = np.where(adjacent_ok[..., None], 0.5 * (vleft + vright), vals)

            extrap = np.full((E, W), Extrapolation.NONE, np.int8)
            extrap[has_any & ~enough] = Extrapolation.FORCED_INSUFFICIENT
            extrap[adjacent_ok] = Extrapolation.AVG_ADJACENT
            extrap[~has_any & ~adjacent_ok] = Extrapolation.NO_VALID

            # Windows predating the first real sample ("pre-genesis") do not
            # exist yet: report only windows since genesis so early models
            # with few-but-complete windows are possible (numValidWindows
            # reflects actual data, as in the reference).
            k = 0
            if self._first_window is not None:
                k = min(max(self._first_window - self._base_window, 0), W)
            vals = vals[:, k:]
            extrap = extrap[:, k:]

            n_extrapolated = (extrap > Extrapolation.NONE).sum(axis=1)
            entity_valid = (
                (extrap != Extrapolation.NO_VALID).all(axis=1)
                & (n_extrapolated <= self.max_allowed_extrapolations)
                & (extrap.shape[1] > 0)
            )
            ratio = float(entity_valid.mean()) if E else 0.0
            starts = (self._base_window + np.arange(k, W)) * self.window_ms
            return AggregationResult(
                vals, extrap, entity_valid, starts, ratio, self._generation
            )

    def completeness(self, num_entities: int | None = None,
                     req: ModelCompletenessRequirements | None = None):
        """(valid_entity_ratio, num_windows, meets) summary (ref
        ``MetricSampleCompleteness``)."""
        r = self.aggregate(num_entities)
        ok = r.meets(req) if req is not None else True
        return r.valid_entity_ratio, r.num_windows, ok
